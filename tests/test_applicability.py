"""Dedicated applicability-checker tests — the mirror of the reference's
checks/ApplicabilityTest.scala (recognize applicable checks, detect
non-existing columns, invalid expressions) plus the typed random-data
generator's contracts (reference: analyzers/applicability/Applicability.scala)."""

from __future__ import annotations

import numpy as np

from deequ_tpu import Check, CheckLevel
from deequ_tpu.analyzers import Completeness, Compliance, Mean, Size
from deequ_tpu.applicability.applicability import (
    Applicability,
    SchemaField,
    generate_random_data,
)
from deequ_tpu.data.table import ColumnType
from deequ_tpu.verification.suite import VerificationSuite

SCHEMA = [
    SchemaField("item", ColumnType.STRING, nullable=False),
    SchemaField("att1", ColumnType.STRING),
    SchemaField("count", ColumnType.LONG),
    SchemaField("price", ColumnType.DOUBLE),
    SchemaField("flag", ColumnType.BOOLEAN),
    SchemaField("dec", ColumnType.DECIMAL, precision=10, scale=2),
    SchemaField("ts", ColumnType.TIMESTAMP),
]


class TestRandomDataGenerator:
    """reference: Applicability.scala:46-155."""

    def test_all_types_generate(self):
        t = generate_random_data(SCHEMA, 1000, seed=1)
        assert t.num_rows == 1000
        assert [name for name, _ in t.schema] == [f.name for f in SCHEMA]
        types = dict(t.schema)
        assert types["count"] == ColumnType.LONG
        assert types["price"] == ColumnType.DOUBLE
        assert types["flag"] == ColumnType.BOOLEAN
        assert types["ts"] == ColumnType.TIMESTAMP

    def test_nullable_fields_get_about_one_percent_nulls(self):
        t = generate_random_data(SCHEMA, 20_000, seed=2)
        null_fraction = t.column("att1").null_count / 20_000
        assert 0.002 < null_fraction < 0.03
        # non-nullable fields get none
        assert t.column("item").null_count == 0

    def test_decimal_respects_precision_and_scale(self):
        t = generate_random_data(
            [SchemaField("d", ColumnType.DECIMAL, nullable=False, precision=6, scale=2)],
            500,
            seed=3,
        )
        vals = t.column("d").values
        assert np.all(vals < 10**6)
        assert np.all(vals >= 0)

    def test_string_lengths_bounded(self):
        t = generate_random_data(
            [SchemaField("s", ColumnType.STRING, nullable=False)], 500, seed=4
        )
        lengths = [len(v) for v in t.column("s").values]
        assert min(lengths) >= 1 and max(lengths) <= 20

    def test_decimal_precision_equals_scale(self):
        # regression: precision == scale means zero whole digits; the
        # generator used to call rng.integers(0.1, 1.0) and crash
        t = generate_random_data(
            [SchemaField("d", ColumnType.DECIMAL, nullable=False, precision=2, scale=2)],
            500,
            seed=5,
        )
        vals = t.column("d").values
        assert np.all(vals >= 0)
        assert np.all(vals < 1)


class TestCheckApplicability:
    """reference: ApplicabilityTest.scala:49-178."""

    def test_recognizes_applicable_check(self):
        check = (
            Check(CheckLevel.ERROR, "applicable")
            .is_complete("item")
            .has_completeness("att1", lambda v: v > 0.5)
            .has_mean("price", lambda v: True)
            .has_size(lambda n: n > 0)
        )
        result = Applicability().is_applicable(check, SCHEMA)
        assert result.is_applicable
        assert not result.failures
        assert all(result.constraint_applicabilities.values())
        assert len(result.constraint_applicabilities) == 4

    def test_detects_non_existing_column(self):
        check = Check(CheckLevel.ERROR, "bad").is_complete("notThere")
        result = Applicability().is_applicable(check, SCHEMA)
        assert not result.is_applicable
        assert result.failures
        assert any("notThere" in name for name, _ in result.failures)

    def test_detects_wrong_type(self):
        check = Check(CheckLevel.ERROR, "bad").has_mean("att1", lambda v: True)
        result = Applicability().is_applicable(check, SCHEMA)
        assert not result.is_applicable

    def test_detects_invalid_expression(self):
        check = Check(CheckLevel.ERROR, "bad").satisfies(
            "count > > 3", "broken expression"
        )
        result = Applicability().is_applicable(check, SCHEMA)
        assert not result.is_applicable

    def test_partial_applicability_maps_per_constraint(self):
        check = (
            Check(CheckLevel.ERROR, "mixed")
            .is_complete("item")
            .is_complete("missing")
        )
        result = Applicability().is_applicable(check, SCHEMA)
        assert not result.is_applicable
        applicable = list(result.constraint_applicabilities.values())
        assert applicable.count(True) == 1
        assert applicable.count(False) == 1


class TestAnalyzersApplicability:
    def test_applicable_analyzers(self):
        result = Applicability().are_applicable(
            [Size(), Completeness("att1"), Mean("price")], SCHEMA
        )
        assert result.is_applicable
        assert not result.failures

    def test_failures_carry_instance_and_exception(self):
        result = Applicability().are_applicable(
            [Mean("att1"), Compliance("c", "price > > 1")], SCHEMA
        )
        assert not result.is_applicable
        assert len(result.failures) == 2
        for _instance, exception in result.failures:
            assert isinstance(exception, BaseException)


class TestStaticFirst:
    """The applicability checker answers statically whenever it can —
    zero random data generated, zero scans (ISSUE 2, Layer 3)."""

    def test_static_checks_never_generate_data(self, monkeypatch):
        import deequ_tpu.applicability.applicability as mod

        def boom(*args, **kwargs):
            raise AssertionError("static-first path generated random data")

        monkeypatch.setattr(mod, "generate_random_data", boom)
        check = (
            Check(CheckLevel.ERROR, "static")
            .is_complete("item")
            .has_mean("price", lambda v: True)
            .satisfies("count > 0", "positive")
            .is_complete("missing")  # static failure, still no scan
        )
        result = Applicability().is_applicable(check, SCHEMA)
        assert not result.is_applicable
        applicable = list(result.constraint_applicabilities.values())
        assert applicable.count(True) == 3
        assert applicable.count(False) == 1

    def test_static_analyzers_never_generate_data(self, monkeypatch):
        import deequ_tpu.applicability.applicability as mod

        def boom(*args, **kwargs):
            raise AssertionError("static-first path generated random data")

        monkeypatch.setattr(mod, "generate_random_data", boom)
        result = Applicability().are_applicable(
            [Size(), Completeness("att1"), Mean("price"),
             Compliance("c", "price > > 1")],
            SCHEMA,
        )
        assert not result.is_applicable
        assert len(result.failures) == 1

    def test_udf_analyzer_falls_back_to_dynamic(self):
        # a binning UDF can fail in ways no static pass sees — the
        # dry-run on generated data must still run for it
        from deequ_tpu.analyzers import Histogram

        def bad_binning(value):
            raise RuntimeError("udf exploded")

        result = Applicability().are_applicable(
            [Histogram("att1", binning_udf=bad_binning)], SCHEMA
        )
        assert not result.is_applicable
        assert len(result.failures) == 1

    def test_invalid_pattern_caught_statically(self, monkeypatch):
        import deequ_tpu.applicability.applicability as mod
        from deequ_tpu.analyzers import PatternMatch

        monkeypatch.setattr(
            mod,
            "generate_random_data",
            lambda *a, **k: (_ for _ in ()).throw(AssertionError("scanned")),
        )
        result = Applicability().are_applicable(
            [PatternMatch("att1", "(unclosed")], SCHEMA
        )
        assert not result.is_applicable
        assert len(result.failures) == 1


class TestSuiteIntegration:
    """reference: VerificationSuite.isCheckApplicableToData
    (VerificationSuite.scala:238-261)."""

    def test_is_check_applicable_to_data(self):
        # takes a schema, like the reference's StructType overload
        ok = VerificationSuite.is_check_applicable_to_data(
            Check(CheckLevel.ERROR, "c").is_complete("att1"), SCHEMA
        )
        assert ok.is_applicable
        bad = VerificationSuite.is_check_applicable_to_data(
            Check(CheckLevel.ERROR, "c").is_complete("zzz"), SCHEMA
        )
        assert not bad.is_applicable

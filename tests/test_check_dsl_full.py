"""Full-DSL check tests mirroring the reference's CheckTest.scala scenario
by scenario (reference: src/test/scala/com/amazon/deequ/checks/CheckTest.scala)
on the same fixture data (reference: utils/FixtureSupport.scala:86-188)."""

from __future__ import annotations

import numpy as np
import pytest

from deequ_tpu import Check, CheckLevel, CheckStatus, Table, VerificationSuite
from deequ_tpu.constraints.constraint import ConstraintStatus
from deequ_tpu.runners.analysis_runner import AnalysisRunner


def run_checks(table: Table, *checks: Check):
    analyzers = []
    for check in checks:
        analyzers.extend(check.required_analyzers())
    return AnalysisRunner.do_analysis_run(table, analyzers)


def assert_evaluates_to(check: Check, context, status: CheckStatus):
    assert check.evaluate(context).status == status, [
        (r.constraint, r.message)
        for r in check.evaluate(context).constraint_results
    ]


def df_complete_and_incomplete_columns() -> Table:
    """reference: FixtureSupport.scala:86-97."""
    return Table.from_numpy(
        {
            "item": np.array(["1", "2", "3", "4", "5", "6"], dtype=object),
            "att1": np.array(["a", "b", "a", "a", "b", "a"], dtype=object),
            "att2": np.array(["f", "d", None, "f", None, "f"], dtype=object),
        }
    )


def df_with_unique_columns() -> Table:
    """reference: FixtureSupport.scala:162-175."""
    return Table.from_numpy(
        {
            "unique": np.array(["1", "2", "3", "4", "5", "6"], dtype=object),
            "nonUnique": np.array(["0", "0", "0", "5", "6", "7"], dtype=object),
            "nonUniqueWithNulls": np.array(
                ["3", "3", "3", None, None, None], dtype=object
            ),
            "uniqueWithNulls": np.array(
                ["1", "2", None, "3", "4", "5"], dtype=object
            ),
            "onlyUniqueWithOtherNonUnique": np.array(
                ["5", "6", "7", "0", "0", "0"], dtype=object
            ),
            "halfUniqueCombinedWithNonUnique": np.array(
                ["0", "0", "0", "4", "5", "6"], dtype=object
            ),
        }
    )


def df_with_distinct_values() -> Table:
    """reference: FixtureSupport.scala:177-188."""
    return Table.from_numpy(
        {
            "att1": np.array(["a", "a", None, "b", "b", "c"], dtype=object),
            "att2": np.array([None, None, "x", "x", "x", "y"], dtype=object),
        }
    )


def df_with_numeric_values() -> Table:
    """reference: FixtureSupport.scala:137-148 — att2 always > att1 for
    the last three rows only."""
    return Table.from_numpy(
        {
            "item": np.array(["1", "2", "3", "4", "5", "6"], dtype=object),
            "att1": np.array([1, 2, 3, 4, 5, 6], dtype=np.int64),
            "att2": np.array([0, 0, 0, 5, 6, 7], dtype=np.int64),
        }
    )


class TestCheckStatuses:
    """reference: CheckTest.scala:42-62."""

    def test_completeness(self):
        check1 = (
            Check(CheckLevel.ERROR, "group-1")
            .is_complete("att1")
            .has_completeness("att1", lambda v: v == 1.0)
        )
        check2 = Check(CheckLevel.ERROR, "group-2-E").has_completeness(
            "att2", lambda v: v > 0.8
        )
        check3 = Check(CheckLevel.WARNING, "group-2-W").has_completeness(
            "att2", lambda v: v > 0.8
        )
        context = run_checks(df_complete_and_incomplete_columns(), check1, check2, check3)
        assert_evaluates_to(check1, context, CheckStatus.SUCCESS)
        assert_evaluates_to(check2, context, CheckStatus.ERROR)
        assert_evaluates_to(check3, context, CheckStatus.WARNING)

    def test_uniqueness(self):
        """reference: CheckTest.scala:64-81."""
        check = (
            Check(CheckLevel.ERROR, "group-1")
            .is_unique("unique")
            .is_unique("uniqueWithNulls")
            .is_unique("nonUnique")
            .is_unique("nonUniqueWithNulls")
        )
        context = run_checks(df_with_unique_columns(), check)
        result = check.evaluate(context)
        assert result.status == CheckStatus.ERROR
        statuses = [r.status for r in result.constraint_results]
        assert statuses == [
            ConstraintStatus.SUCCESS,
            ConstraintStatus.FAILURE,
            ConstraintStatus.FAILURE,
            ConstraintStatus.FAILURE,
        ]

    def test_distinctness(self):
        """reference: CheckTest.scala:83-98."""
        check = (
            Check(CheckLevel.ERROR, "distinctness-check")
            .has_distinctness(["att1"], lambda v: v == 0.5)
            .has_distinctness(["att1", "att2"], lambda v: v == 1.0 / 3)
            .has_distinctness(["att2"], lambda v: v == 1.0)
        )
        context = run_checks(df_with_distinct_values(), check)
        result = check.evaluate(context)
        assert result.status == CheckStatus.ERROR
        statuses = [r.status for r in result.constraint_results]
        assert statuses == [
            ConstraintStatus.SUCCESS,
            ConstraintStatus.SUCCESS,
            ConstraintStatus.FAILURE,
        ]

    def test_has_uniqueness_overloads(self):
        """reference: CheckTest.scala:100-126."""
        check = (
            Check(CheckLevel.ERROR, "group-1-u")
            .has_uniqueness(["nonUnique"], lambda fraction: fraction == 0.5)
            .has_uniqueness(["nonUnique"], lambda fraction: fraction < 0.6)
            .has_uniqueness(
                ["halfUniqueCombinedWithNonUnique", "nonUnique"],
                lambda fraction: fraction == 0.5,
            )
            .has_uniqueness(
                ["onlyUniqueWithOtherNonUnique", "nonUnique"], Check.IsOne
            )
            .has_uniqueness(["unique"], Check.IsOne)
            .has_uniqueness(["uniqueWithNulls"], Check.IsOne)
        )
        context = run_checks(df_with_unique_columns(), check)
        result = check.evaluate(context)
        assert result.status == CheckStatus.ERROR
        statuses = [r.status for r in result.constraint_results]
        assert statuses == [
            ConstraintStatus.SUCCESS,
            ConstraintStatus.SUCCESS,
            ConstraintStatus.SUCCESS,
            ConstraintStatus.SUCCESS,
            ConstraintStatus.SUCCESS,
            ConstraintStatus.FAILURE,  # nulls are duplicated
        ]

    def test_conditional_column_constraints(self):
        """reference: CheckTest.scala:174-192."""
        check_to_succeed = (
            Check(CheckLevel.ERROR, "group-1")
            .satisfies("att1 < att2", "rule1")
            .where("att1 > 3")
        )
        check_to_fail = (
            Check(CheckLevel.ERROR, "group-1")
            .satisfies("att2 > 0", "rule2")
            .where("att1 > 0")
        )
        check_partial = (
            Check(CheckLevel.ERROR, "group-1")
            .satisfies("att2 > 0", "rule3", lambda v: v == 0.5)
            .where("att1 > 0")
        )
        context = run_checks(
            df_with_numeric_values(), check_to_succeed, check_to_fail, check_partial
        )
        assert_evaluates_to(check_to_succeed, context, CheckStatus.SUCCESS)
        assert_evaluates_to(check_to_fail, context, CheckStatus.ERROR)
        assert_evaluates_to(check_partial, context, CheckStatus.SUCCESS)

    def test_convenience_constraints(self):
        """reference: CheckTest.scala:194-239."""
        less_than = (
            Check(CheckLevel.ERROR, "a").is_less_than("att1", "att2").where("item > 3")
        )
        incorrect_less_than = Check(CheckLevel.ERROR, "a").is_less_than("att1", "att2")
        non_negative = Check(CheckLevel.ERROR, "a").is_non_negative("item")
        positive = Check(CheckLevel.ERROR, "a").is_positive("item")
        context = run_checks(
            df_with_numeric_values(),
            less_than, incorrect_less_than, non_negative, positive,
        )
        assert_evaluates_to(less_than, context, CheckStatus.SUCCESS)
        assert_evaluates_to(incorrect_less_than, context, CheckStatus.ERROR)
        assert_evaluates_to(non_negative, context, CheckStatus.SUCCESS)
        assert_evaluates_to(positive, context, CheckStatus.SUCCESS)

    def test_is_contained_in_values(self):
        """reference: CheckTest.scala:236-254."""
        range_check = Check(CheckLevel.ERROR, "a").is_contained_in(
            "att1", ["a", "b", "c"]
        )
        incorrect = Check(CheckLevel.ERROR, "a").is_contained_in("att1", ["a", "b"])
        custom = Check(CheckLevel.ERROR, "a").is_contained_in(
            "att1", ["a"], lambda v: v == 0.5
        )
        context = run_checks(df_with_distinct_values(), range_check, incorrect, custom)
        assert_evaluates_to(range_check, context, CheckStatus.SUCCESS)
        assert_evaluates_to(incorrect, context, CheckStatus.ERROR)
        # 2 of 6 values are 'a', 1 is NULL (counts as pass), 3 fail -> 0.5
        assert_evaluates_to(custom, context, CheckStatus.SUCCESS)

    @pytest.mark.parametrize(
        "lower,upper,inc_lower,inc_upper,expected",
        [
            (0, 7, True, True, CheckStatus.SUCCESS),   # nr1
            (1, 7, True, True, CheckStatus.ERROR),     # nr2
            (0, 6, True, True, CheckStatus.ERROR),     # nr3
            (0, 7, False, False, CheckStatus.ERROR),   # nr4
            (-1, 8, False, False, CheckStatus.SUCCESS),  # nr5
            (0, 7, True, False, CheckStatus.ERROR),    # nr6
            (0, 8, True, False, CheckStatus.SUCCESS),  # nr7
            (0, 7, False, True, CheckStatus.ERROR),    # nr8
            (-1, 7, False, True, CheckStatus.SUCCESS),  # nr9
        ],
    )
    def test_is_contained_in_bounds(self, lower, upper, inc_lower, inc_upper, expected):
        """reference: CheckTest.scala:256-273 — all 9 bound combinations."""
        check = Check(CheckLevel.ERROR, "nr").is_contained_in(
            "att2",
            lower_bound=lower,
            upper_bound=upper,
            include_lower_bound=inc_lower,
            include_upper_bound=inc_upper,
        )
        context = run_checks(df_with_numeric_values(), check)
        assert_evaluates_to(check, context, expected)


class TestEmbeddedPatterns:
    """containsX finds patterns EMBEDDED in text, not anchored
    (reference: CheckTest.scala:439-476)."""

    def _single_column(self, value: str) -> Table:
        return Table.from_numpy({"some": np.array([value], dtype=object)})

    def test_credit_card_embedded(self):
        table = self._single_column("My credit card number is: 4111-1111-1111-1111.")
        check = Check(CheckLevel.ERROR, "d").contains_credit_card_number(
            "some", lambda v: v == 1.0
        )
        assert_evaluates_to(check, run_checks(table, check), CheckStatus.SUCCESS)

    def test_email_embedded(self):
        table = self._single_column("Please contact me at someone@somewhere.org, thank you.")
        check = Check(CheckLevel.ERROR, "d").contains_email("some", lambda v: v == 1.0)
        assert_evaluates_to(check, run_checks(table, check), CheckStatus.SUCCESS)

    def test_url_embedded(self):
        table = self._single_column(
            "Hey, please have a look at https://www.example.com/foo/?bar=baz&inga=42&quux!"
        )
        check = Check(CheckLevel.ERROR, "d").contains_url("some", lambda v: v == 1.0)
        assert_evaluates_to(check, run_checks(table, check), CheckStatus.SUCCESS)

    def test_ssn_embedded(self):
        table = self._single_column("My SSN is 111-05-1130, thanks.")
        check = Check(CheckLevel.ERROR, "d").contains_social_security_number(
            "some", lambda v: v == 1.0
        )
        assert_evaluates_to(check, run_checks(table, check), CheckStatus.SUCCESS)

    def test_mixed_data_fails_default_assertion(self):
        """reference: CheckTest.scala:362-370, 381-389 — default assertion
        is IsOne; mixed data fails it."""
        table = Table.from_numpy(
            {
                "some": np.array(
                    ["someone@somewhere.org", "someone@else"], dtype=object
                )
            }
        )
        check = Check(CheckLevel.ERROR, "d").contains_email("some")
        assert_evaluates_to(check, run_checks(table, check), CheckStatus.ERROR)


class TestExoticColumnNames:
    """Backtick-quoted SQL generation must survive special characters
    (reference: CheckTest.scala:491-558)."""

    COLUMN = "att.1 with space"

    def test_is_contained_in_values_variant(self):
        table = Table.from_numpy(
            {self.COLUMN: np.array(["a", "b", "a"], dtype=object)}
        )
        check = Check(CheckLevel.ERROR, "c").is_contained_in(self.COLUMN, ["a", "b"])
        result = VerificationSuite().on_data(table).add_check(check).run()
        assert result.status == CheckStatus.SUCCESS

    def test_is_contained_in_bounds_variant(self):
        table = Table.from_numpy({self.COLUMN: np.array([1.0, 2.0, 3.0])})
        check = Check(CheckLevel.ERROR, "c").is_contained_in(
            self.COLUMN, lower_bound=0.0, upper_bound=4.0
        )
        result = VerificationSuite().on_data(table).add_check(check).run()
        assert result.status == CheckStatus.SUCCESS


class TestAnomalyHistoryFiltering:
    """reference: CheckTest.scala:647-714 — only history inside the
    configured window / tags feeds the detector."""

    def _repo_with_history(self):
        from deequ_tpu.analyzers import Size
        from deequ_tpu.core.maybe import Success
        from deequ_tpu.core.metrics import DoubleMetric, Entity
        from deequ_tpu.repository.base import ResultKey
        from deequ_tpu.repository.memory import InMemoryMetricsRepository
        from deequ_tpu.runners.context import AnalyzerContext

        repo = InMemoryMetricsRepository()
        for ts, value, tags in [
            (1000, 11.0, {"env": "prod"}),
            (2000, 12.0, {"env": "prod"}),
            (3000, 50.0, {"env": "test"}),  # outlier under a different tag
        ]:
            repo.save(
                ResultKey(ts, tags),
                AnalyzerContext(
                    {
                        Size(): DoubleMetric(
                            Entity.DATASET, "Size", "*", Success(value)
                        )
                    }
                ),
            )
        return repo

    def test_tag_filter_excludes_other_environments(self):
        from deequ_tpu.analyzers import Size
        from deequ_tpu.anomaly.strategies import SimpleThresholdStrategy

        repo = self._repo_with_history()
        table = Table.from_numpy({"x": np.arange(13.0)})  # size 13
        # with the prod tag filter, history is [11, 12] and 13 is fine;
        # without it, the test outlier (50) would not change simple
        # threshold semantics, so use a rate bound instead
        check = Check(CheckLevel.WARNING, "anomaly").is_newest_point_non_anomalous(
            repo,
            SimpleThresholdStrategy(lower_bound=0.0, upper_bound=20.0),
            Size(),
            {"env": "prod"},
            None,
            None,
        )
        context = run_checks(table, check)
        assert check.evaluate(context).status == CheckStatus.SUCCESS

    def test_before_after_window(self):
        from deequ_tpu.analyzers import Size
        from deequ_tpu.anomaly.strategies import RateOfChangeStrategy

        repo = self._repo_with_history()
        table = Table.from_numpy({"x": np.arange(13.0)})  # size 13
        # window [0, 2500]: history [11, 12] -> 13 is a +1 step: fine
        ok = Check(CheckLevel.WARNING, "anomaly").is_newest_point_non_anomalous(
            repo,
            RateOfChangeStrategy(max_rate_increase=2.0),
            Size(),
            None,
            0,
            2500,
        )
        context = run_checks(table, ok)
        assert ok.evaluate(context).status == CheckStatus.SUCCESS
        # full window: the tagged outlier 50 enters history -> 50 -> 13
        # is a huge negative step; with a decrease bound it is anomalous
        bad = Check(CheckLevel.WARNING, "anomaly").is_newest_point_non_anomalous(
            repo,
            RateOfChangeStrategy(max_rate_decrease=-5.0, max_rate_increase=40.0),
            Size(),
            None,
            None,
            None,
        )
        context = run_checks(table, bad)
        assert bad.evaluate(context).status == CheckStatus.WARNING

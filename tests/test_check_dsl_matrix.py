"""Systematic DSL matrix: every Check method × pass/warn/fail × where
variants — the depth of the reference's CheckTest.scala (808 LoC;
reference: src/test/scala/com/amazon/deequ/checks/CheckTest.scala), on
the FixtureSupport tables. Complements tests/test_check_dsl_full.py's
scenario tests with per-method coverage."""

from __future__ import annotations

import numpy as np
import pytest

from deequ_tpu import Check, CheckLevel, CheckStatus, Table, VerificationSuite
from deequ_tpu.constraints.constrainable_data_types import ConstrainableDataTypes
from deequ_tpu.constraints.constraint import ConstraintStatus
from tests.fixtures import (
    get_df_full,
    get_df_missing,
    get_df_with_distinct_values,
    get_df_with_numeric_values,
    get_df_with_unique_columns,
)


def status_of(table: Table, check: Check) -> CheckStatus:
    return VerificationSuite.on_data(table).add_check(check).run().status


def constraint_statuses(table: Table, check: Check):
    result = VerificationSuite.on_data(table).add_check(check).run()
    return [
        cr.status for cr in next(iter(result.check_results.values())).constraint_results
    ]


def error_check() -> Check:
    return Check(CheckLevel.ERROR, "error level")


def warning_check() -> Check:
    return Check(CheckLevel.WARNING, "warning level")


class TestSize:
    """reference: CheckTest.scala:128-154."""

    def test_exact_equality_passes(self):
        assert status_of(get_df_full(), error_check().has_size(lambda n: n == 4)) \
            == CheckStatus.SUCCESS

    def test_bounds(self):
        t = get_df_full()
        assert status_of(t, error_check().has_size(lambda n: n < 5)) == CheckStatus.SUCCESS
        assert status_of(t, error_check().has_size(lambda n: n > 3)) == CheckStatus.SUCCESS
        assert status_of(t, error_check().has_size(lambda n: n > 4)) == CheckStatus.ERROR

    def test_failing_at_warning_level_yields_warning(self):
        assert status_of(get_df_full(), warning_check().has_size(lambda n: n == 0)) \
            == CheckStatus.WARNING

    def test_with_where_filter(self):
        check = error_check().has_size(lambda n: n == 3).where("att1 = 'a'")
        assert status_of(get_df_full(), check) == CheckStatus.SUCCESS


class TestCompletenessFamily:
    """reference: CheckTest.scala:42-62."""

    def test_is_complete_passes_on_full_column(self):
        assert status_of(get_df_missing(), error_check().is_complete("item")) \
            == CheckStatus.SUCCESS

    def test_is_complete_fails_on_missing(self):
        assert status_of(get_df_missing(), error_check().is_complete("att1")) \
            == CheckStatus.ERROR

    def test_has_completeness_exact_fractions(self):
        t = get_df_missing()  # att1: 6/12, att2: 9/12
        assert status_of(t, error_check().has_completeness("att1", lambda v: v == 0.5)) \
            == CheckStatus.SUCCESS
        assert status_of(t, error_check().has_completeness("att2", lambda v: v == 0.75)) \
            == CheckStatus.SUCCESS
        assert status_of(t, error_check().has_completeness("att2", lambda v: v > 0.8)) \
            == CheckStatus.ERROR

    def test_where_filter_changes_fraction(self):
        # rows where att2 = 'd': items 2,6,7,12 -> att1 = b,None,None,None
        check = (
            error_check()
            .has_completeness("att1", lambda v: v == 0.25)
            .where("att2 = 'd'")
        )
        assert status_of(get_df_missing(), check) == CheckStatus.SUCCESS

    def test_missing_column_is_error(self):
        assert status_of(get_df_missing(), error_check().is_complete("nope")) \
            == CheckStatus.ERROR


class TestUniquenessFamily:
    """reference: CheckTest.scala:64-126."""

    def test_is_unique(self):
        t = get_df_with_unique_columns()
        assert status_of(t, error_check().is_unique("unique")) == CheckStatus.SUCCESS
        assert status_of(t, error_check().is_unique("nonUnique")) == CheckStatus.ERROR
        # nulls stay in the DENOMINATOR (numRows), so a unique-but-gappy
        # column is NOT unique (reference: CheckTest.scala:64-82 asserts
        # Failure for uniqueWithNulls)
        assert status_of(t, error_check().is_unique("uniqueWithNulls")) \
            == CheckStatus.ERROR
        assert status_of(t, error_check().is_unique("nonUniqueWithNulls")) \
            == CheckStatus.ERROR

    def test_is_primary_key(self):
        t = get_df_with_unique_columns()
        assert status_of(t, error_check().is_primary_key("unique")) == CheckStatus.SUCCESS
        # a primary key must also be complete: uniqueWithNulls fails
        assert status_of(t, error_check().is_primary_key("uniqueWithNulls")) \
            == CheckStatus.ERROR
        assert status_of(
            t, error_check().is_primary_key("halfUniqueCombinedWithNonUnique", "onlyUniqueWithOtherNonUnique")
        ) == CheckStatus.SUCCESS

    def test_has_uniqueness_fractions(self):
        t = get_df_with_unique_columns()
        # halfUniqueCombinedWithNonUnique: values 0,0,0,4,5,6 -> 3 of 6 unique
        assert status_of(
            t,
            error_check().has_uniqueness(
                ["halfUniqueCombinedWithNonUnique"], lambda v: v == 0.5
            ),
        ) == CheckStatus.SUCCESS
        # multi-column uniqueness over the combination
        assert status_of(
            t,
            error_check().has_uniqueness(
                ["halfUniqueCombinedWithNonUnique", "nonUnique"], lambda v: v == 0.5
            ),
        ) == CheckStatus.SUCCESS

    def test_has_unique_value_ratio(self):
        t = get_df_with_unique_columns()
        # nonUnique: groups {0:3, 5:1, 6:1, 7:1} -> 3 unique of 4 groups
        assert status_of(
            t,
            error_check().has_unique_value_ratio(["nonUnique"], lambda v: v == 0.75),
        ) == CheckStatus.SUCCESS
        assert status_of(
            t,
            error_check().has_unique_value_ratio(["nonUnique"], lambda v: v > 0.75),
        ) == CheckStatus.ERROR

    def test_has_distinctness(self):
        t = get_df_with_distinct_values()
        # att1: groups a,b,c of 6 rows -> 0.5
        assert status_of(
            t, error_check().has_distinctness(["att1"], lambda v: v == 0.5)
        ) == CheckStatus.SUCCESS
        # att2: groups x,y of 6 rows -> 1/3
        assert status_of(
            t, error_check().has_distinctness(["att2"], lambda v: abs(v - 1 / 3) < 1e-12)
        ) == CheckStatus.SUCCESS

    def test_has_number_of_distinct_values(self):
        # histogram semantics: NullValue is a bin (att1: a,b,c + NullValue)
        t = get_df_with_distinct_values()
        assert status_of(
            t, error_check().has_number_of_distinct_values("att1", lambda v: v == 4)
        ) == CheckStatus.SUCCESS
        assert status_of(
            t, error_check().has_number_of_distinct_values("att2", lambda v: v == 3)
        ) == CheckStatus.SUCCESS
        assert status_of(
            t, error_check().has_number_of_distinct_values("att2", lambda v: v == 2)
        ) == CheckStatus.ERROR


class TestHistogramAndEntropy:
    """reference: CheckTest.scala:275-320."""

    def test_has_histogram_values_ratios(self):
        t = get_df_missing()
        # att1 non-null: a x4, b x2; NullValue x6 of 12 rows
        check = error_check().has_histogram_values(
            "att1",
            lambda d: d.values["a"].ratio == 4 / 12
            and d.values["b"].ratio == 2 / 12
            and d.values["NullValue"].ratio == 6 / 12,
        )
        assert status_of(t, check) == CheckStatus.SUCCESS

    def test_has_histogram_values_absolutes(self):
        check = error_check().has_histogram_values(
            "att1",
            lambda d: d.values["a"].absolute == 4 and d.values["b"].absolute == 2,
        )
        assert status_of(get_df_missing(), check) == CheckStatus.SUCCESS

    def test_has_entropy_exact(self):
        t = get_df_full()  # att1: a x3, b x1 over 4 rows
        expected = -(3 / 4 * np.log(3 / 4) + 1 / 4 * np.log(1 / 4))
        assert status_of(
            t, error_check().has_entropy("att1", lambda v: abs(v - expected) < 1e-12)
        ) == CheckStatus.SUCCESS
        assert status_of(
            t, error_check().has_entropy("att1", lambda v: v == 0)
        ) == CheckStatus.ERROR


class TestBasicStats:
    """reference: CheckTest.scala:321-351 'yield correct results for
    basic stats' — exact values through the check surface."""

    def test_all_stats_exact(self):
        t = get_df_with_numeric_values()
        att1 = np.array([1, 2, 3, 4, 5, 6], dtype=np.float64)
        check = (
            error_check()
            .has_min("att1", lambda v: v == 1.0)
            .has_max("att1", lambda v: v == 6.0)
            .has_mean("att1", lambda v: v == 3.5)
            .has_sum("att1", lambda v: v == 21.0)
            .has_standard_deviation(
                "att1", lambda v: abs(v - float(np.std(att1))) < 1e-12
            )
            .has_approx_count_distinct("att1", lambda v: v == 6.0)
        )
        assert status_of(t, check) == CheckStatus.SUCCESS

    def test_approx_quantile(self):
        t = get_df_with_numeric_values()
        assert status_of(
            t,
            error_check().has_approx_quantile("att1", 0.5, lambda v: 3.0 <= v <= 4.0),
        ) == CheckStatus.SUCCESS

    def test_correlation_of_column_with_itself_is_one(self):
        t = get_df_with_numeric_values()
        assert status_of(
            t,
            error_check().has_correlation("att1", "att1", lambda v: v == 1.0),
        ) == CheckStatus.SUCCESS

    def test_stats_with_where_filter(self):
        t = get_df_with_numeric_values()
        check = (
            error_check()
            .has_mean("att1", lambda v: v == 5.0)
            .where("att2 > 0")  # rows 4,5,6
        )
        assert status_of(t, check) == CheckStatus.SUCCESS

    def test_mutual_information(self):
        t = get_df_with_numeric_values()
        # att1 determines att2 -> MI = H(att2)
        check = error_check().has_mutual_information(
            "att1", "att2", lambda v: v > 0.0
        )
        assert status_of(t, check) == CheckStatus.SUCCESS

    def test_stat_on_non_numeric_column_errors(self):
        assert status_of(
            get_df_full(), error_check().has_mean("att1", lambda v: True)
        ) == CheckStatus.ERROR


class TestColumnComparisons:
    """reference: CheckTest.scala:156-192 (conditional column constraints)."""

    def test_is_less_than(self):
        t = get_df_with_numeric_values()
        assert status_of(t, error_check().is_less_than("att1", "att2").where("item > '3'")) \
            == CheckStatus.SUCCESS
        assert status_of(t, error_check().is_less_than("att1", "att2")) \
            == CheckStatus.ERROR

    def test_is_less_than_or_equal_to(self):
        t = get_df_with_numeric_values()
        assert status_of(
            t, error_check().is_less_than_or_equal_to("att1", "att2").where("item > '3'")
        ) == CheckStatus.SUCCESS

    def test_is_greater_than(self):
        t = get_df_with_numeric_values()
        assert status_of(t, error_check().is_greater_than("att2", "att1").where("item > '3'")) \
            == CheckStatus.SUCCESS
        assert status_of(t, error_check().is_greater_than("att1", "att2")) \
            == CheckStatus.ERROR

    def test_is_greater_than_or_equal_to(self):
        t = get_df_with_numeric_values()
        assert status_of(
            t,
            error_check().is_greater_than_or_equal_to("att2", "att1").where("item > '3'"),
        ) == CheckStatus.SUCCESS


class TestSignChecks:
    """reference: CheckTest.scala:478-489 + the NULL-coalescing predicate
    (Check.scala:676)."""

    def test_is_non_negative_passes_with_nulls(self):
        # COALESCE(col, 0) >= 0: nulls count as satisfied
        t = Table.from_pydict({"v": [1.0, 0.0, None, 5.5]})
        assert status_of(t, error_check().is_non_negative("v")) == CheckStatus.SUCCESS

    def test_is_non_negative_fails_on_negative(self):
        t = Table.from_pydict({"v": [1.0, -0.5, 2.0]})
        assert status_of(t, error_check().is_non_negative("v")) == CheckStatus.ERROR

    def test_is_positive(self):
        assert status_of(
            Table.from_pydict({"v": [1, 2, 3]}), error_check().is_positive("v")
        ) == CheckStatus.SUCCESS
        # zero is not positive
        assert status_of(
            Table.from_pydict({"v": [0, 1, 2]}), error_check().is_positive("v")
        ) == CheckStatus.ERROR

    def test_numeric_string_column_is_coerced(self):
        # reference runs these on string columns holding numbers
        t = Table.from_pydict({"v": ["-1", "-2", "-3"]})
        assert status_of(t, error_check().is_non_negative("v")) == CheckStatus.ERROR


class TestSatisfies:
    """reference: CheckTest.scala:194+ (compliance)."""

    def test_full_compliance(self):
        t = get_df_with_numeric_values()
        assert status_of(
            t, error_check().satisfies("att1 > 0", "positive")
        ) == CheckStatus.SUCCESS

    def test_fractional_compliance_with_assertion(self):
        t = get_df_with_numeric_values()
        assert status_of(
            t,
            error_check().satisfies(
                "att1 > 3", "bigger than 3", lambda v: v == 0.5
            ),
        ) == CheckStatus.SUCCESS

    def test_compliance_where_filter(self):
        t = get_df_with_numeric_values()
        check = error_check().satisfies(
            "att2 > 0", "att2 positive on filtered", lambda v: v == 1.0
        ).where("att1 > 3")
        assert status_of(t, check) == CheckStatus.SUCCESS

    def test_invalid_expression_is_error(self):
        assert status_of(
            get_df_with_numeric_values(),
            error_check().satisfies("SELECT GARBAGE ( (", "bad"),
        ) == CheckStatus.ERROR


class TestDataTypeCheck:
    """reference: CheckTest.scala:430-438."""

    def test_integral_column(self):
        t = Table.from_pydict({"v": ["1", "2", "3"]})
        assert status_of(
            t,
            error_check().has_data_type(
                "v", ConstrainableDataTypes.INTEGRAL, lambda v: v == 1.0
            ),
        ) == CheckStatus.SUCCESS

    def test_fractional_ratio(self):
        t = Table.from_pydict({"v": ["1.0", "2.0", "3"]})
        # 2 of 3 fractional
        assert status_of(
            t,
            error_check().has_data_type(
                "v", ConstrainableDataTypes.FRACTIONAL, lambda v: abs(v - 2 / 3) < 1e-12
            ),
        ) == CheckStatus.SUCCESS

    def test_numeric_union_type(self):
        t = Table.from_pydict({"v": ["1.0", "2", "x"]})
        assert status_of(
            t,
            error_check().has_data_type(
                "v", ConstrainableDataTypes.NUMERIC, lambda v: abs(v - 2 / 3) < 1e-12
            ),
        ) == CheckStatus.SUCCESS

    def test_boolean_type(self):
        t = Table.from_pydict({"v": ["true", "false", "true"]})
        assert status_of(
            t,
            error_check().has_data_type(
                "v", ConstrainableDataTypes.BOOLEAN, lambda v: v == 1.0
            ),
        ) == CheckStatus.SUCCESS


class TestStatusPrecedence:
    """Overall status = max severity over checks
    (reference: VerificationSuite.scala:272-278)."""

    def test_warning_and_error_mix(self):
        t = get_df_missing()
        result = (
            VerificationSuite.on_data(t)
            .add_check(warning_check().is_complete("att1"))  # fails -> WARNING
            .add_check(error_check().is_complete("item"))  # passes
            .run()
        )
        assert result.status == CheckStatus.WARNING
        result = (
            VerificationSuite.on_data(t)
            .add_check(warning_check().is_complete("att1"))  # fails -> WARNING
            .add_check(error_check().is_complete("att2"))  # fails -> ERROR
            .run()
        )
        assert result.status == CheckStatus.ERROR

    def test_success_when_all_pass(self):
        result = (
            VerificationSuite.on_data(get_df_full())
            .add_check(error_check().is_complete("att1"))
            .add_check(warning_check().has_size(lambda n: n == 4))
            .run()
        )
        assert result.status == CheckStatus.SUCCESS

    def test_constraint_order_preserved(self):
        check = (
            error_check()
            .is_complete("item")
            .has_size(lambda n: n == 4)
            .is_unique("item")
        )
        statuses = constraint_statuses(get_df_full(), check)
        assert len(statuses) == 3
        assert all(s == ConstraintStatus.SUCCESS for s in statuses)


class TestExoticColumnNames:
    """reference: CheckTest.scala:491-558 — special characters must
    survive the expression layer via backtick quoting."""

    @pytest.fixture
    def table(self):
        return Table.from_pydict(
            {"item.one with spaces": ["a", "b", "c"], "thing#2": [1.0, 2.0, 3.0]}
        )

    def test_completeness(self, table):
        assert status_of(
            table, error_check().is_complete("item.one with spaces")
        ) == CheckStatus.SUCCESS

    def test_contained_in_values(self, table):
        assert status_of(
            table,
            error_check().is_contained_in("item.one with spaces", ("a", "b", "c")),
        ) == CheckStatus.SUCCESS

    def test_contained_in_bounds(self, table):
        assert status_of(
            table,
            error_check().is_contained_in("thing#2", lower_bound=0.5, upper_bound=3.5),
        ) == CheckStatus.SUCCESS


class TestHints:
    """Hints ride through to constraint messages
    (reference: constraints carry `hint`)."""

    def test_hint_in_failed_constraint_message(self):
        result = (
            VerificationSuite.on_data(get_df_missing())
            .add_check(
                error_check().has_completeness(
                    "att1", lambda v: v > 0.9, hint="att1 must be well-populated"
                )
            )
            .run()
        )
        rows = result.check_results_as_rows()
        assert any(
            "att1 must be well-populated" in (row["constraint_message"] or "")
            for row in rows
        )

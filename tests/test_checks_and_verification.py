"""Check DSL + VerificationSuite end-to-end (mirrors reference
checks/CheckTest.scala, VerificationSuiteTest.scala and the README
BasicExample contract from BASELINE.md)."""

import json

import pytest

from deequ_tpu import (
    Check,
    CheckLevel,
    CheckStatus,
    ConstrainableDataTypes,
    Table,
    VerificationSuite,
)
from deequ_tpu.analyzers import Size
from deequ_tpu.constraints.constraint import ConstraintStatus
from deequ_tpu.ops import runtime

from fixtures import (
    get_basic_example_table,
    get_df_full,
    get_df_missing,
    get_df_with_numeric_values,
    get_df_with_unique_columns,
)


class TestBasicExample:
    """The README contract: Completeness(name)=0.8 fails, containsURL=0.4
    fails, everything else passes (reference: examples/BasicExample.scala +
    README.md:113-119)."""

    def run_example(self):
        data = get_basic_example_table()
        return (
            VerificationSuite.on_data(data)
            .add_check(
                Check(CheckLevel.ERROR, "integrity checks")
                .has_size(lambda s: s == 5)
                .is_complete("id")
                .is_unique("id")
                .is_complete("name")
                .is_contained_in("priority", ["high", "low"])
                .is_non_negative("numViews")
            )
            .add_check(
                Check(CheckLevel.WARNING, "distribution checks")
                .contains_url("description", lambda v: v >= 0.5)
                .has_approx_quantile("numViews", 0.5, lambda v: v <= 10)
            )
            .run()
        )

    def test_overall_status(self):
        result = self.run_example()
        assert result.status == CheckStatus.ERROR

    def test_failing_constraints_and_messages(self):
        result = self.run_example()
        failures = [
            r
            for check_result in result.check_results.values()
            for r in check_result.constraint_results
            if r.status != ConstraintStatus.SUCCESS
        ]
        by_name = {repr(r.constraint): r for r in failures}
        assert len(failures) == 2
        assert (
            by_name["CompletenessConstraint(Completeness(name,None))"].message
            == "Value: 0.8 does not meet the constraint requirement!"
        )
        assert (
            by_name["containsURL(description)"].message
            == "Value: 0.4 does not meet the constraint requirement!"
        )

    def test_check_levels(self):
        result = self.run_example()
        statuses = {
            check.description: res.status for check, res in result.check_results.items()
        }
        assert statuses["integrity checks"] == CheckStatus.ERROR
        assert statuses["distribution checks"] == CheckStatus.WARNING

    def test_single_fused_scan_plus_grouping(self):
        data = get_basic_example_table()
        with runtime.monitored() as stats:
            self.run_example.__wrapped__(self) if hasattr(self.run_example, "__wrapped__") else self.run_example()
        # 1 fused scan (size/completeness×2/compliance×2/pattern/quantile)
        # + 2 jobs for the uniqueness grouping set
        assert stats.device_passes + stats.group_passes == 3


class TestCheckDSL:
    def test_has_size_where(self):
        df = get_df_with_numeric_values()
        check = Check(CheckLevel.ERROR, "size").has_size(lambda s: s == 3).where("att1 > 3")
        result = VerificationSuite().run(df, [check])
        assert result.status == CheckStatus.SUCCESS

    def test_completeness_family(self):
        df = get_df_missing()
        check = (
            Check(CheckLevel.ERROR, "completeness")
            .has_completeness("att1", lambda v: v == 0.5)
            .has_completeness("att2", lambda v: v == 0.75)
        )
        result = VerificationSuite().run(df, [check])
        assert result.status == CheckStatus.SUCCESS

    def test_uniqueness_and_primary_key(self):
        df = get_df_with_unique_columns()
        good = (
            Check(CheckLevel.ERROR, "unique")
            .is_unique("unique")
            .is_primary_key("unique", "nonUnique")
            .has_uniqueness("nonUnique", lambda v: v == 0.5)
            .has_distinctness(["nonUnique"], lambda v: v == pytest.approx(4 / 6))
            .has_unique_value_ratio(["nonUnique"], lambda v: v == 0.75)
        )
        result = VerificationSuite().run(df, [good])
        assert result.status == CheckStatus.SUCCESS

    def test_min_max_mean_sum_std(self):
        df = get_df_with_numeric_values()
        check = (
            Check(CheckLevel.ERROR, "numbers")
            .has_min("att1", lambda v: v == 1.0)
            .has_max("att1", lambda v: v == 6.0)
            .has_mean("att1", lambda v: v == 3.5)
            .has_sum("att1", lambda v: v == 21.0)
            .has_standard_deviation("att1", lambda v: abs(v - 1.707825) < 1e-5)
            .has_approx_count_distinct("att1", lambda v: v == 6.0)
            .has_correlation("att1", "att2", lambda v: v > 0.9)
        )
        result = VerificationSuite().run(df, [check])
        for r in list(result.check_results.values())[0].constraint_results:
            assert r.status == ConstraintStatus.SUCCESS, (repr(r.constraint), r.message)

    def test_comparison_dsl(self):
        df = get_df_with_numeric_values()
        check = (
            Check(CheckLevel.ERROR, "cmp")
            .is_less_than_or_equal_to("att1", "att2")
            .where("att1 > 3")
            .is_non_negative("att1")
            .is_positive("att1")
        )
        result = VerificationSuite().run(df, [check])
        assert result.status == CheckStatus.SUCCESS

    def test_is_contained_in_range(self):
        df = get_df_with_numeric_values()
        check = Check(CheckLevel.ERROR, "range").is_contained_in(
            "att1", lower_bound=1.0, upper_bound=6.0
        )
        result = VerificationSuite().run(df, [check])
        assert result.status == CheckStatus.SUCCESS

    def test_entropy_and_mi(self):
        df = get_df_full()
        import numpy as np

        expected = -(3 / 4) * np.log(3 / 4) - (1 / 4) * np.log(1 / 4)
        check = (
            Check(CheckLevel.ERROR, "info")
            .has_entropy("att1", lambda v: v == pytest.approx(expected))
            # joint (a,c):3,(b,d):1 -> MI = 3/4·ln(4/3) + 1/4·ln(4)
            .has_mutual_information(
                "att1", "att2",
                lambda v: v == pytest.approx(0.75 * np.log(4 / 3) + 0.25 * np.log(4.0)),
            )
        )
        result = VerificationSuite().run(df, [check])
        for r in list(result.check_results.values())[0].constraint_results:
            assert r.status == ConstraintStatus.SUCCESS, (repr(r.constraint), r.message)

    def test_has_data_type(self):
        df = Table.from_pydict({"s": ["1", "2", "3.0"]})
        check = Check(CheckLevel.ERROR, "dt").has_data_type(
            "s", ConstrainableDataTypes.NUMERIC
        )
        result = VerificationSuite().run(df, [check])
        assert result.status == CheckStatus.SUCCESS
        check2 = Check(CheckLevel.ERROR, "dt2").has_data_type(
            "s", ConstrainableDataTypes.INTEGRAL, lambda v: v == pytest.approx(2 / 3)
        )
        result2 = VerificationSuite().run(df, [check2])
        assert result2.status == CheckStatus.SUCCESS

    def test_histogram_dsl(self):
        df = get_df_missing()
        check = (
            Check(CheckLevel.ERROR, "hist")
            .has_number_of_distinct_values("att1", lambda n: n == 3)
            .has_histogram_values("att1", lambda d: d["a"].absolute == 4)
        )
        result = VerificationSuite().run(df, [check])
        assert result.status == CheckStatus.SUCCESS

    def test_pattern_dsl(self):
        df = Table.from_pydict(
            {
                "email": ["someone@somewhere.org", "nope"],
                "ssn": ["123-45-6789", "123-45-6789"],
            }
        )
        check = (
            Check(CheckLevel.ERROR, "patterns")
            .contains_email("email", lambda v: v == 0.5)
            .contains_social_security_number("ssn")
        )
        result = VerificationSuite().run(df, [check])
        assert result.status == CheckStatus.SUCCESS

    def test_warning_level_check(self):
        df = get_df_missing()
        check = Check(CheckLevel.WARNING, "warn").is_complete("att1")
        result = VerificationSuite().run(df, [check])
        assert result.status == CheckStatus.WARNING

    def test_missing_analysis_message(self):
        from deequ_tpu.runners.context import AnalyzerContext

        check = Check(CheckLevel.ERROR, "x").is_complete("att1")
        result = check.evaluate(AnalyzerContext.empty())
        assert result.constraint_results[0].message == (
            "Missing Analysis, can't run the constraint!"
        )

    def test_failure_metric_propagates_message(self):
        df = get_df_full()
        check = Check(CheckLevel.ERROR, "x").has_mean("att1", lambda v: True)
        result = VerificationSuite().run(df, [check])
        cr = list(result.check_results.values())[0].constraint_results[0]
        assert cr.status == ConstraintStatus.FAILURE
        assert "Expected type of column att1" in cr.message


class TestVerificationResult:
    def test_exports(self):
        df = get_df_with_numeric_values()
        result = VerificationSuite().run(
            df,
            [Check(CheckLevel.ERROR, "group-1").has_size(lambda s: s == 6).has_mean("att1", lambda v: v == 3.5)],
        )
        metrics = result.success_metrics_as_rows()
        assert {
            "entity": "Dataset",
            "instance": "*",
            "name": "Size",
            "value": 6.0,
        } in metrics
        checks = json.loads(result.check_results_as_json())
        assert len(checks) == 2
        assert all(r["check"] == "group-1" for r in checks)
        assert all(r["constraint_status"] == "Success" for r in checks)

    def test_required_analyzers_deduped_across_checks(self):
        df = get_df_with_numeric_values()
        with runtime.monitored() as stats:
            VerificationSuite().run(
                df,
                [
                    Check(CheckLevel.ERROR, "a").is_complete("att1"),
                    Check(CheckLevel.WARNING, "b").has_completeness("att1", lambda v: v > 0.5),
                ],
            )
        assert stats.device_passes == 1

"""Per-rule trigger boundaries + generated code strings + evaluated
candidates — the depth of the reference's ConstraintRulesTest.scala
(728 LoC) and ConstraintSuggestionResultTest.scala (498 LoC). Rules are
unit-tested against hand-built profiles (the reference's style), and
each candidate constraint is re-evaluated against data that should
satisfy / violate it."""

from __future__ import annotations

import math

import pytest

from deequ_tpu.analyzers.scan import DataTypeInstances
from deequ_tpu.core.metrics import Distribution, DistributionValue
from deequ_tpu.data.table import Table
from deequ_tpu.profiles.column_profile import (
    NumericColumnProfile,
    StandardColumnProfile,
)
from deequ_tpu.suggestions.rules import (
    DEFAULT_RULES,
    CategoricalRangeRule,
    CompleteIfCompleteRule,
    FractionalCategoricalRangeRule,
    NonNegativeNumbersRule,
    RetainCompletenessRule,
    RetainTypeRule,
    Rules,
    UniqueIfApproximatelyUniqueRule,
)
from deequ_tpu.constraints.constraint import ConstraintStatus
from deequ_tpu.runners.analysis_runner import AnalysisRunner


def string_profile(column="col", completeness=1.0, distinct=10,
                   data_type=DataTypeInstances.STRING, inferred=False,
                   histogram=None):
    return StandardColumnProfile(
        column, completeness, distinct, data_type, inferred, {}, histogram
    )


def numeric_profile(column="col", completeness=1.0, distinct=10,
                    minimum=None, data_type=DataTypeInstances.INTEGRAL):
    return NumericColumnProfile(
        column, completeness, distinct, data_type, True, {}, None,
        mean=1.0, maximum=10.0, minimum=minimum, sum=10.0, std_dev=1.0,
    )


def evaluate_candidate(suggestion, table: Table) -> ConstraintStatus:
    """Run the suggested constraint against real data (the reference
    round-trips candidates through VerificationSuite the same way)."""
    constraint = suggestion.constraint
    inner = getattr(constraint, "inner", constraint)  # unwrap NamedConstraint
    ctx = AnalysisRunner.do_analysis_run(table, [inner.analyzer])
    return constraint.evaluate(ctx.metric_map).status


class TestCompleteIfCompleteRule:
    """reference: rules/CompleteIfCompleteRule.scala:25 — fires iff
    completeness == 1.0."""

    def test_trigger_boundaries(self):
        rule = CompleteIfCompleteRule()
        assert rule.should_be_applied(string_profile(completeness=1.0), 100)
        assert not rule.should_be_applied(string_profile(completeness=0.99), 100)
        assert not rule.should_be_applied(string_profile(completeness=0.0), 100)

    def test_code_string(self):
        s = CompleteIfCompleteRule().candidate(string_profile(column="abc"), 100)
        assert s.code_for_constraint == '.is_complete("abc")'
        assert s.column_name == "abc"
        assert s.current_value == "Completeness: 1.0"

    def test_candidate_evaluates(self):
        s = CompleteIfCompleteRule().candidate(string_profile(column="v"), 3)
        assert evaluate_candidate(s, Table.from_pydict({"v": ["a", "b", "c"]})) \
            == ConstraintStatus.SUCCESS
        assert evaluate_candidate(s, Table.from_pydict({"v": ["a", None, "c"]})) \
            == ConstraintStatus.FAILURE


class TestRetainCompletenessRule:
    """reference: rules/RetainCompletenessRule.scala:28-43 — fires for
    0.2 < completeness < 1.0; suggests the binomial-CI lower bound
    (z=1.96, floored to 2 decimals)."""

    def test_trigger_boundaries(self):
        rule = RetainCompletenessRule()
        assert not rule.should_be_applied(string_profile(completeness=0.2), 100)
        assert rule.should_be_applied(string_profile(completeness=0.21), 100)
        assert rule.should_be_applied(string_profile(completeness=0.99), 100)
        assert not rule.should_be_applied(string_profile(completeness=1.0), 100)
        assert not rule.should_be_applied(string_profile(completeness=0.1), 100)

    def test_ci_lower_bound_in_code(self):
        p, n = 0.5, 100
        target = math.floor((p - 1.96 * math.sqrt(p * (1 - p) / n)) * 100) / 100
        s = RetainCompletenessRule().candidate(
            string_profile(column="c", completeness=p), n
        )
        assert f"v >= {target}" in s.code_for_constraint
        assert f"above {target}!" in s.code_for_constraint

    def test_candidate_evaluates_against_bound(self):
        # p=0.5, n=4 -> target = floor(0.5 - 1.96*0.25) = 0.01
        s = RetainCompletenessRule().candidate(
            string_profile(column="v", completeness=0.5), 4
        )
        assert evaluate_candidate(
            s, Table.from_pydict({"v": ["a", None, "b", None]})
        ) == ConstraintStatus.SUCCESS


class TestRetainTypeRule:
    """reference: rules/RetainTypeRule.scala:27 — fires only for INFERRED
    Integral/Fractional/Boolean."""

    def test_trigger_matrix(self):
        rule = RetainTypeRule()
        for dt, expected in [
            (DataTypeInstances.INTEGRAL, True),
            (DataTypeInstances.FRACTIONAL, True),
            (DataTypeInstances.BOOLEAN, True),
            (DataTypeInstances.STRING, False),
            (DataTypeInstances.UNKNOWN, False),
        ]:
            profile = string_profile(data_type=dt, inferred=True)
            assert rule.should_be_applied(profile, 10) == expected, dt
        # not inferred (schema-known) -> never fires
        profile = string_profile(data_type=DataTypeInstances.INTEGRAL, inferred=False)
        assert not rule.should_be_applied(profile, 10)

    def test_code_string(self):
        s = RetainTypeRule().candidate(
            string_profile(column="n", data_type=DataTypeInstances.FRACTIONAL,
                           inferred=True),
            10,
        )
        assert s.code_for_constraint == \
            '.has_data_type("n", ConstrainableDataTypes.FRACTIONAL)'

    def test_candidate_evaluates(self):
        s = RetainTypeRule().candidate(
            string_profile(column="v", data_type=DataTypeInstances.INTEGRAL,
                           inferred=True),
            3,
        )
        assert evaluate_candidate(s, Table.from_pydict({"v": ["1", "2", "3"]})) \
            == ConstraintStatus.SUCCESS
        assert evaluate_candidate(s, Table.from_pydict({"v": ["1", "x", "3"]})) \
            == ConstraintStatus.FAILURE


def histogram_of(pairs, total):
    return Distribution(
        {k: DistributionValue(c, c / total) for k, c in pairs}, len(pairs)
    )


class TestCategoricalRangeRule:
    """reference: rules/CategoricalRangeRule.scala:27-60 — fires when the
    ratio of singleton bins is <= 0.1; values ordered by popularity."""

    def test_trigger_boundary(self):
        rule = CategoricalRangeRule()
        # 10 bins, 1 singleton -> ratio 0.1 -> fires
        hist = histogram_of([(f"v{i}", 5) for i in range(9)] + [("solo", 1)], 46)
        assert rule.should_be_applied(string_profile(histogram=hist), 46)
        # 2 singletons of 10 -> 0.2 -> no
        hist = histogram_of(
            [(f"v{i}", 5) for i in range(8)] + [("s1", 1), ("s2", 1)], 42
        )
        assert not rule.should_be_applied(string_profile(histogram=hist), 42)

    def test_requires_string_type_and_histogram(self):
        rule = CategoricalRangeRule()
        hist = histogram_of([("a", 5), ("b", 5)], 10)
        assert not rule.should_be_applied(
            string_profile(data_type=DataTypeInstances.INTEGRAL, histogram=hist), 10
        )
        assert not rule.should_be_applied(string_profile(histogram=None), 10)

    def test_values_ordered_by_popularity_in_code(self):
        hist = histogram_of([("rare", 2), ("common", 10), ("mid", 5)], 17)
        s = CategoricalRangeRule().candidate(
            string_profile(column="cat", histogram=hist), 17
        )
        assert '.is_contained_in("cat", ["common", "mid", "rare"])' \
            == s.code_for_constraint

    def test_quote_escaping(self):
        hist = histogram_of([("it's", 5), ("ok", 5)], 10)
        s = CategoricalRangeRule().candidate(
            string_profile(column="c", histogram=hist), 10
        )
        # SQL-side: doubled single quote (reference Check.scala:836-841)
        inner = getattr(s.constraint, "inner", s.constraint)
        assert "it''s" in inner.analyzer.predicate
        assert evaluate_candidate(
            s, Table.from_pydict({"c": ["it's", "ok", "ok"]})
        ) == ConstraintStatus.SUCCESS

    def test_null_bin_excluded_from_values(self):
        hist = histogram_of([("a", 6), ("NullValue", 3), ("b", 6)], 15)
        s = CategoricalRangeRule().candidate(
            string_profile(column="c", histogram=hist), 15
        )
        assert "NullValue" not in s.code_for_constraint


class TestFractionalCategoricalRangeRule:
    """reference: rules/FractionalCategoricalRangeRule.scala:29 — top
    categories covering >= 0.9, CI-adjusted assertion."""

    def test_fires_on_long_tail(self):
        # 2 big categories cover 90%, tail of 10 singletons
        pairs = [("a", 500), ("b", 400)] + [(f"t{i}", 10) for i in range(10)]
        hist = histogram_of(pairs, 1000)
        rule = FractionalCategoricalRangeRule()
        assert rule.should_be_applied(string_profile(histogram=hist), 1000)

    def test_not_fired_when_all_unique(self):
        pairs = [(f"u{i}", 1) for i in range(10)]
        hist = histogram_of(pairs, 10)
        assert not FractionalCategoricalRangeRule().should_be_applied(
            string_profile(histogram=hist), 10
        )

    def test_code_contains_ci_bound_and_categories(self):
        pairs = [("a", 500), ("b", 400)] + [(f"t{i}", 10) for i in range(10)]
        hist = histogram_of(pairs, 1000)
        s = FractionalCategoricalRangeRule().candidate(
            string_profile(column="c", histogram=hist), 1000
        )
        assert '.is_contained_in("c", ["a", "b"]' in s.code_for_constraint
        assert "lambda v: v >=" in s.code_for_constraint
        # evaluated against matching data: 95% in {a,b} passes the bound
        t = Table.from_pydict({"c": ["a"] * 10 + ["b"] * 9 + ["z"]})
        assert evaluate_candidate(s, t) == ConstraintStatus.SUCCESS


class TestNonNegativeNumbersRule:
    """reference: rules/NonNegativeNumbersRule.scala:25-44."""

    def test_trigger_boundaries(self):
        rule = NonNegativeNumbersRule()
        assert rule.should_be_applied(numeric_profile(minimum=0.0), 10)
        assert rule.should_be_applied(numeric_profile(minimum=4.5), 10)
        assert not rule.should_be_applied(numeric_profile(minimum=-0.01), 10)
        assert not rule.should_be_applied(numeric_profile(minimum=None), 10)
        # non-numeric profile never fires
        assert not rule.should_be_applied(string_profile(), 10)

    def test_code_and_current_value(self):
        s = NonNegativeNumbersRule().candidate(numeric_profile(column="n", minimum=0.0), 10)
        assert s.code_for_constraint == '.is_non_negative("n")'
        assert s.current_value == "Minimum: 0.0"

    def test_candidate_evaluates(self):
        s = NonNegativeNumbersRule().candidate(numeric_profile(column="v", minimum=0.0), 3)
        assert evaluate_candidate(s, Table.from_pydict({"v": [0, 1, 2]})) \
            == ConstraintStatus.SUCCESS
        assert evaluate_candidate(s, Table.from_pydict({"v": [0, -1, 2]})) \
            == ConstraintStatus.FAILURE


class TestUniqueIfApproximatelyUniqueRule:
    """reference: rules/UniqueIfApproximatelyUniqueRule.scala:28-41 —
    NOT in DEFAULT; fires for complete columns whose approx distinct
    count is within 8% of the row count."""

    def test_trigger_boundaries(self):
        rule = UniqueIfApproximatelyUniqueRule()
        assert rule.should_be_applied(string_profile(distinct=100), 100)
        assert rule.should_be_applied(string_profile(distinct=92), 100)
        assert not rule.should_be_applied(string_profile(distinct=91), 100)
        # 108/100: |1-1.08| is one double ulp ABOVE 0.08 — doesn't fire,
        # the same IEEE behavior the reference's Scala doubles have
        assert rule.should_be_applied(string_profile(distinct=107), 100)
        assert not rule.should_be_applied(string_profile(distinct=108), 100)
        assert not rule.should_be_applied(string_profile(distinct=109), 100)
        # incomplete column never fires
        assert not rule.should_be_applied(
            string_profile(completeness=0.99, distinct=100), 100
        )
        assert not rule.should_be_applied(string_profile(distinct=0), 0)

    def test_code_string(self):
        s = UniqueIfApproximatelyUniqueRule().candidate(
            string_profile(column="id", distinct=100), 100
        )
        assert s.code_for_constraint == '.is_unique("id")'

    def test_candidate_evaluates(self):
        s = UniqueIfApproximatelyUniqueRule().candidate(
            string_profile(column="v", distinct=3), 3
        )
        assert evaluate_candidate(s, Table.from_pydict({"v": ["a", "b", "c"]})) \
            == ConstraintStatus.SUCCESS
        assert evaluate_candidate(s, Table.from_pydict({"v": ["a", "a", "c"]})) \
            == ConstraintStatus.FAILURE


class TestRuleSets:
    def test_default_has_six_rules(self):
        """reference: ConstraintSuggestionRunner.scala:29-35."""
        rules = DEFAULT_RULES()
        assert len(rules) == 6
        names = {type(r).__name__ for r in rules}
        assert names == {
            "CompleteIfCompleteRule",
            "RetainCompletenessRule",
            "RetainTypeRule",
            "CategoricalRangeRule",
            "FractionalCategoricalRangeRule",
            "NonNegativeNumbersRule",
        }
        assert "UniqueIfApproximatelyUniqueRule" not in names

    def test_rules_default_constant(self):
        assert len(Rules.DEFAULT) == 6

    def test_every_rule_has_description(self):
        for rule in list(DEFAULT_RULES()) + [UniqueIfApproximatelyUniqueRule()]:
            assert rule.rule_description


class TestSuggestionsEndToEnd:
    """reference: ConstraintSuggestionsIntegrationTest.scala — the rules
    fire on real profiled data and the code strings are executable DSL."""

    @pytest.fixture
    def table(self):
        import numpy as np

        rng = np.random.default_rng(0)
        n = 500
        return Table.from_pydict(
            {
                "id": [f"id{i}" for i in range(n)],
                "status": [["active", "inactive"][i % 2] for i in range(n)],
                "count": [int(v) for v in rng.integers(0, 50, n)],
                "maybe": [("x" if i % 3 else None) for i in range(n)],
            }
        )

    def test_fired_rules(self, table):
        from deequ_tpu.suggestions.runner import ConstraintSuggestionRunner

        result = (
            ConstraintSuggestionRunner.on_data(table)
            .add_constraint_rules(DEFAULT_RULES)
            .run()
        )
        by_col = result.constraint_suggestions
        assert any(
            s.code_for_constraint == '.is_complete("id")' for s in by_col["id"]
        )
        assert any(
            ".is_contained_in" in s.code_for_constraint for s in by_col["status"]
        )
        assert any(
            s.code_for_constraint == '.is_non_negative("count")'
            for s in by_col["count"]
        )
        assert any(
            ".has_completeness" in s.code_for_constraint for s in by_col["maybe"]
        )

    def test_generated_code_is_executable_dsl(self, table):
        """Every generated snippet must parse and run against the Check
        builder (the reference emits compilable Scala; we emit runnable
        Python)."""
        from deequ_tpu import Check, CheckLevel, VerificationSuite
        from deequ_tpu.constraints.constrainable_data_types import (
            ConstrainableDataTypes,
        )
        from deequ_tpu.suggestions.runner import ConstraintSuggestionRunner

        result = (
            ConstraintSuggestionRunner.on_data(table)
            .add_constraint_rules(DEFAULT_RULES)
            .run()
        )
        check = Check(CheckLevel.WARNING, "generated")
        for suggestion in result.all_suggestions():
            check = eval(  # noqa: S307 - our own generated snippets
                "check" + suggestion.code_for_constraint,
                {"check": check, "ConstrainableDataTypes": ConstrainableDataTypes},
            )
        outcome = VerificationSuite.on_data(table).add_check(check).run()
        statuses = [
            cr.status
            for cr in next(iter(outcome.check_results.values())).constraint_results
        ]
        assert statuses and all(
            s == ConstraintStatus.SUCCESS for s in statuses
        ), statuses

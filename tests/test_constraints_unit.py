"""Constraint-layer unit tests with stub analyzers — the mirror of the
reference's AnalysisBasedConstraintTest.scala (242 LoC, mocked pickers
and assertions) and ConstraintsTest.scala (164 LoC): evaluation over
precomputed metric maps, every failure mode mapped to its message."""

from __future__ import annotations

import pytest

from deequ_tpu.analyzers import Completeness
from deequ_tpu.constraints import constraint as C
from deequ_tpu.constraints.constraint import (
    AnalysisBasedConstraint,
    ConstraintDecorator,
    ConstraintStatus,
    NamedConstraint,
)
from deequ_tpu.core.maybe import Failure, Success
from deequ_tpu.core.metrics import DoubleMetric, Entity
from tests.fixtures import get_df_missing


def metric_of(value: float) -> DoubleMetric:
    return DoubleMetric(Entity.COLUMN, "Completeness", "att1", Success(value))


def failed_metric(exc: BaseException) -> DoubleMetric:
    return DoubleMetric(Entity.COLUMN, "Completeness", "att1", Failure(exc))


ANALYZER = Completeness("att1")


class TestAnalysisBasedConstraintEvaluation:
    """reference: AnalysisBasedConstraint.scala:54-97."""

    def test_success_when_assertion_holds(self):
        constraint = AnalysisBasedConstraint(ANALYZER, lambda v: v == 0.5)
        result = constraint.evaluate({ANALYZER: metric_of(0.5)})
        assert result.status == ConstraintStatus.SUCCESS
        assert result.metric is not None

    def test_failure_when_assertion_does_not_hold(self):
        constraint = AnalysisBasedConstraint(ANALYZER, lambda v: v > 0.9)
        result = constraint.evaluate({ANALYZER: metric_of(0.5)})
        assert result.status == ConstraintStatus.FAILURE
        assert "0.5" in result.message
        assert "does not meet the constraint requirement" in result.message

    def test_missing_analysis_message(self):
        """reference: AnalysisBasedConstraint.scala:115 MissingAnalysis."""
        constraint = AnalysisBasedConstraint(ANALYZER, lambda v: True)
        result = constraint.evaluate({})
        assert result.status == ConstraintStatus.FAILURE
        assert "Missing Analysis" in result.message

    def test_failed_metric_propagates_its_message(self):
        constraint = AnalysisBasedConstraint(ANALYZER, lambda v: True)
        result = constraint.evaluate(
            {ANALYZER: failed_metric(ValueError("kaboom in the scan"))}
        )
        assert result.status == ConstraintStatus.FAILURE
        assert "kaboom in the scan" in result.message

    def test_assertion_exception_becomes_failure(self):
        """reference: AnalysisBasedConstraint.scala:117 AssertionException."""

        def exploding(v):
            raise RuntimeError("assertion blew up")

        constraint = AnalysisBasedConstraint(ANALYZER, exploding)
        result = constraint.evaluate({ANALYZER: metric_of(0.5)})
        assert result.status == ConstraintStatus.FAILURE
        assert "assertion blew up" in result.message

    def test_value_picker_transforms_value(self):
        constraint = AnalysisBasedConstraint(
            ANALYZER, lambda v: v == 6, value_picker=lambda v: v * 12
        )
        assert constraint.evaluate({ANALYZER: metric_of(0.5)}).status \
            == ConstraintStatus.SUCCESS

    def test_value_picker_exception_becomes_failure(self):
        """reference: AnalysisBasedConstraint.scala:116 ProblematicMetricPicker."""

        def bad_picker(v):
            raise RuntimeError("picker exploded")

        constraint = AnalysisBasedConstraint(
            ANALYZER, lambda v: True, value_picker=bad_picker
        )
        result = constraint.evaluate({ANALYZER: metric_of(0.5)})
        assert result.status == ConstraintStatus.FAILURE
        assert "Can't retrieve the value to assert on" in result.message

    def test_hint_appended_to_failure_message(self):
        constraint = AnalysisBasedConstraint(
            ANALYZER, lambda v: v > 0.9, hint="att1 must be nearly full"
        )
        result = constraint.evaluate({ANALYZER: metric_of(0.5)})
        assert "att1 must be nearly full" in result.message


class TestNamedConstraint:
    """reference: Constraint.scala:66."""

    def test_repr_uses_name(self):
        inner = AnalysisBasedConstraint(ANALYZER, lambda v: True)
        named = NamedConstraint(inner, "CompletenessConstraint(custom)")
        assert repr(named) == "CompletenessConstraint(custom)"

    def test_decorator_unwraps_to_innermost(self):
        inner = AnalysisBasedConstraint(ANALYZER, lambda v: True)
        named = NamedConstraint(inner, "outer")
        assert named.inner is inner

    def test_evaluation_passes_through(self):
        inner = AnalysisBasedConstraint(ANALYZER, lambda v: v == 0.5)
        named = NamedConstraint(inner, "outer")
        assert named.evaluate({ANALYZER: metric_of(0.5)}).status \
            == ConstraintStatus.SUCCESS


class TestFactoryReprs:
    """Factory-built constraints carry the reference's display names
    (reference: Constraint.scala:83-613)."""

    @pytest.mark.parametrize(
        "constraint, expected_prefix",
        [
            (C.size_constraint(lambda n: n > 0), "SizeConstraint(Size"),
            (
                C.completeness_constraint("att1", lambda v: True),
                "CompletenessConstraint(Completeness",
            ),
            (
                C.uniqueness_constraint(["att1"], lambda v: True),
                "UniquenessConstraint(Uniqueness",
            ),
            (
                C.distinctness_constraint(["att1"], lambda v: True),
                "DistinctnessConstraint(Distinctness",
            ),
            (
                C.compliance_constraint("name", "att1 > 0", lambda v: True),
                "ComplianceConstraint(Compliance",
            ),
            (
                C.entropy_constraint("att1", lambda v: True),
                "EntropyConstraint(Entropy",
            ),
            (C.mean_constraint("att1", lambda v: True), "MeanConstraint(Mean"),
            (C.min_constraint("att1", lambda v: True), "MinimumConstraint(Minimum"),
            (C.max_constraint("att1", lambda v: True), "MaximumConstraint(Maximum"),
            (C.sum_constraint("att1", lambda v: True), "SumConstraint(Sum"),
            (
                C.standard_deviation_constraint("att1", lambda v: True),
                "StandardDeviationConstraint(StandardDeviation",
            ),
            (
                C.approx_count_distinct_constraint("att1", lambda v: True),
                "ApproxCountDistinctConstraint(ApproxCountDistinct",
            ),
            (
                C.correlation_constraint("a", "b", lambda v: True),
                "CorrelationConstraint(Correlation",
            ),
            (
                C.pattern_match_constraint("att1", r"\d+", lambda v: True),
                "PatternMatchConstraint",
            ),
        ],
    )
    def test_repr(self, constraint, expected_prefix):
        assert repr(constraint).startswith(expected_prefix)


class TestSizeConstraintEndToEnd:
    def test_size_value_formats_as_integer(self):
        """The failure message prints whole-number metric values the way
        the reference does ('Value: 4', not 'Value: 4.0')."""
        from deequ_tpu.runners.analysis_runner import AnalysisRunner

        table = get_df_missing()
        constraint = C.size_constraint(lambda n: n > 100)
        inner = constraint.inner if isinstance(constraint, ConstraintDecorator) else constraint
        ctx = AnalysisRunner.do_analysis_run(table, [inner.analyzer])
        result = constraint.evaluate(ctx.metric_map)
        assert result.status == ConstraintStatus.FAILURE
        assert "Value: 12" in result.message

"""Parity tests for the counts-based family fast paths (round 5):

1. `ops/counts_family` — a low-range int64 column's fused moments,
   decimated quantile sample and HLL registers derived from ONE windowed
   count pass must match the select kernel (`masked_moments_select`)
   output for output: sample/registers/min/max/count EXACTLY, sum
   exactly for in-range integers, m2 within float tolerance.
2. DataType-from-dictionary-counts — classifying the dictionary and
   weighing by _LowCardCounts' per-entry counts must equal the per-row
   classification bincount exactly (integer counts).
3. _OptimisticNumericStats-from-counts — the numeric bundle for an
   inferred-numeric string column derived from (parsed dictionary,
   counts) must match the per-row cast + select path.
4. End-to-end: ColumnProfiler output with the fast paths enabled equals
   the output with DEEQU_TPU_NO_COUNTS_FASTPATH=1 (the pre-existing
   per-row kernels) on a mixed table.

Reference behavior being preserved: profiles/ColumnProfiler.scala
:103-187 pass outputs; catalyst/StatefulDataType.scala classification
counts; catalyst/StatefulApproxQuantile.scala per-partition updates.
"""

from __future__ import annotations

import numpy as np
import pytest

from deequ_tpu.ops import counts_family, native


needs_native = pytest.mark.skipif(
    not native.available(), reason="native kernels unavailable"
)


def _select_reference(vals, valid, where, cap, with_hll):
    x = vals.astype(np.float64)
    return native.masked_moments_select(
        x,
        valid,
        where,
        cap,
        hll_mode=2 if with_hll else 0,
        hashvals=vals if with_hll else None,
    )


@needs_native
class TestCountsFamilyParity:
    @pytest.mark.parametrize(
        "case",
        ["dense", "nulls", "where", "offset_base", "negative", "tiny",
         "constant", "two_values"],
    )
    def test_matches_select_kernel(self, case):
        seeds = {
            "dense": 1, "nulls": 2, "where": 3, "offset_base": 4,
            "negative": 5, "tiny": 6, "constant": 7, "two_values": 8,
        }
        rng = np.random.default_rng(seeds[case])
        n = 200_000
        valid = where = None
        if case == "dense":
            vals = rng.integers(1, 100, n)
        elif case == "nulls":
            vals = rng.integers(-50, 5000, n)
            valid = rng.random(n) > 0.15
        elif case == "where":
            vals = rng.integers(0, 30, n)
            valid = rng.random(n) > 0.05
            where = rng.random(n) > 0.5
        elif case == "offset_base":
            vals = rng.integers(10**14, 10**14 + 20_000, n)
        elif case == "negative":
            vals = rng.integers(-30_000, -29_000, n)
        elif case == "tiny":
            vals = np.array([3, 1, 4, 1, 5])
        elif case == "constant":
            vals = np.full(n, 77)
        else:  # two_values
            vals = np.where(rng.random(n) > 0.7, 10, 20)
        vals = vals.astype(np.int64)
        cap = 460

        res = counts_family.counts_for_column(vals, valid, where)
        assert res is not None, case
        counts, lo, n_valid, n_where = res
        mom_c, sample_c, n_c, lvl_c, regs_c = counts_family.family_from_counts(
            counts, lo, cap, n_where, want_regs=True
        )
        mom_r, sample_r, n_r, lvl_r, regs_r = _select_reference(
            vals, valid, where, cap, with_hll=True
        )
        assert (n_c, lvl_c) == (n_r, lvl_r)
        assert np.array_equal(sample_c, sample_r)
        assert np.array_equal(regs_c, regs_r)
        # count / min / max / n_where exact
        assert mom_c[0] == mom_r[0]
        assert mom_c[2] == mom_r[2] and mom_c[3] == mom_r[3]
        assert mom_c[5] == mom_r[5]
        # the counts-path sum is exact integer arithmetic; the kernel's
        # long-double stream matches it bit-for-bit while the true total
        # fits the accumulator, and to 1e-15 relative beyond that
        # (offset_base: totals ~2e19 overflow even the 64-bit mantissa)
        if abs(mom_r[1]) < float(1 << 53):
            assert mom_c[1] == mom_r[1]
        else:
            assert mom_c[1] == pytest.approx(mom_r[1], rel=1e-15)
        assert mom_c[4] == pytest.approx(mom_r[4], rel=1e-9, abs=1e-9)

    def test_fallbacks(self):
        rng = np.random.default_rng(0)
        # wide range: probe refuses before any pass
        wide = rng.integers(0, 10**12, 10_000).astype(np.int64)
        assert counts_family.counts_for_column(wide, None, None) is None
        # non-int64 columns are not eligible
        assert (
            counts_family.counts_for_column(
                rng.random(1000), None, None
            )
            is None
        )
        # narrow probe but an unprobed outlier: the kernel aborts
        trick = np.full(100_001, 5, dtype=np.int64)
        trick[70_000] = 10**9  # outside head/middle/tail probes
        assert counts_family.counts_for_column(trick, None, None) is None
        # all-null column: no probe information
        vals = rng.integers(0, 5, 1000).astype(np.int64)
        assert (
            counts_family.counts_for_column(
                vals, np.zeros(1000, dtype=bool), None
            )
            is None
        )

    @pytest.mark.parametrize(
        "case",
        ["discount", "tax_nulls", "neg_zero", "extreme_floats",
         "sparse_int", "where_float"],
    )
    def test_hash_counts_match_select_kernel(self, case):
        """The open-addressing hash counter extends the fast path to
        low-cardinality FLOATS and sparse wide-range integers: outputs
        must match the select kernel exactly (samples via the f64_key
        total order — -0.0 before +0.0 — registers via the bit-pattern
        identity)."""
        rng = np.random.default_rng(
            {"discount": 31, "tax_nulls": 32, "neg_zero": 33,
             "extreme_floats": 34, "sparse_int": 35, "where_float": 36}[case]
        )
        n = 150_000
        valid = where = None
        if case == "discount":
            vals = rng.integers(0, 11, n) / 100.0
        elif case == "tax_nulls":
            vals = rng.integers(0, 9, n) / 100.0
            valid = rng.random(n) > 0.15
        elif case == "neg_zero":
            vals = np.where(rng.random(n) > 0.5, 0.0, -0.0)
        elif case == "extreme_floats":
            vals = rng.choice(
                [1.5, -2.25, 1e300, -1e-300, 0.125, np.finfo(float).tiny], n
            )
        elif case == "sparse_int":
            vals = (rng.integers(0, 4000, n) * 982451653).astype(np.int64)
        else:  # where_float
            vals = rng.integers(0, 4, n) / 4.0
            valid = rng.random(n) > 0.05
            where = rng.random(n) > 0.5
        is_int = np.issubdtype(vals.dtype, np.integer)
        vals = vals.astype(np.int64 if is_int else np.float64)
        cap = 460
        hres = counts_family.hash_counts_for_column(vals, valid, where)
        assert hres is not None, case
        keys, counts, _n_valid, n_where = hres
        mom_c, sample_c, n_c, lvl_c, regs_c = (
            counts_family.family_from_hash_counts(
                keys, counts, "i64" if is_int else "f64", cap, n_where,
                want_regs=True,
            )
        )
        if is_int:
            ref = _select_reference(vals, valid, where, cap, with_hll=True)
        else:
            ref = native.masked_moments_select(
                vals, valid, where, cap, hll_mode=1
            )
        mom_r, sample_r, n_r, lvl_r, regs_r = ref
        assert (n_c, lvl_c) == (n_r, lvl_r), case
        assert np.array_equal(sample_c, sample_r), case
        assert np.array_equal(regs_c, regs_r), case
        assert mom_c[0] == mom_r[0], case
        assert mom_c[2] == mom_r[2] and mom_c[3] == mom_r[3], case
        assert mom_c[5] == mom_r[5], case
        assert mom_c[1] == pytest.approx(mom_r[1], rel=1e-12, abs=1e-12)
        assert mom_c[4] == pytest.approx(mom_r[4], rel=1e-9, abs=1e-9)

    def test_hash_counts_high_cardinality_aborts(self):
        rng = np.random.default_rng(40)
        big = rng.lognormal(3, 1, 200_000)
        assert counts_family.hash_counts_for_column(big, None, None) is None
        # object/str columns are not eligible at all
        assert (
            counts_family.hash_counts_for_column(
                np.array(["a"], dtype=object), None, None
            )
            is None
        )

    def test_hash_counts_skew_guard_bails_on_late_tail(self):
        """A column whose distinct count exceeds the cap only in a late
        tail (the Zipf/skew worst case) must abort after the bounded
        probe prefix, not after scanning nearly everything."""
        rng = np.random.default_rng(41)
        n = 1_500_000
        head = rng.integers(0, 64_000, int(n * 0.95)).astype(np.float64)
        tail = rng.integers(64_000, 72_000, n - len(head)).astype(
            np.float64
        )
        vals = np.concatenate([head, tail])
        import time

        t0 = time.process_time()
        assert counts_family.hash_counts_for_column(vals, None, None) is None
        # bounded prefix (~8ms typical): the bound must stay below a
        # full ~12ns/row scan of 1.5M rows (~18ms typical, ~90ms on this
        # box's worst observed 5x-slow phases) while tolerating those
        # same slow phases on the guard path
        assert time.process_time() - t0 < 0.08

    def test_int64_extreme_sentinels_stay_successful(self):
        """Columns of Long.MIN/MAX-adjacent sentinels: the speculative
        window must clamp inside int64 (no ctypes wrap, no OverflowError)
        and the metrics must succeed either via the counts path or the
        select fallback (regression: review round 5)."""
        from deequ_tpu.analyzers import ApproxQuantiles, Mean
        from deequ_tpu.data.table import Table
        from deequ_tpu.runners import AnalysisRunner

        for value in (-(1 << 63) + 5, (1 << 63) - 3):
            t = Table.from_numpy(
                {"x": np.full(5000, value, dtype=np.int64)}
            )
            res = (
                AnalysisRunner.on_data(t)
                .add_analyzers([Mean("x"), ApproxQuantiles("x", (0.5,))])
                .run()
            )
            for _a, metric in res.metric_map.items():
                assert metric.value.is_success, (value, metric.value)

    def test_empty_after_masks(self):
        # probed values exist but `where` excludes everything: counts
        # all zero, family must report the empty-state shape
        vals = np.arange(100, dtype=np.int64)
        where = np.zeros(100, dtype=bool)
        res = counts_family.counts_for_column(vals, None, where)
        assert res is not None
        counts, lo, n_valid, n_where = res
        assert n_valid == 0 and n_where == 0
        mom, sample, m, level, regs = counts_family.family_from_counts(
            counts, lo, 460, n_where, want_regs=True
        )
        assert m == 0 and len(sample) == 0
        assert mom[0] == 0.0 and mom[2] == np.inf and mom[3] == -np.inf
        assert not regs.any()


class TestDictionaryContentMemo:
    def test_cross_batch_hits_and_content_safety(self, tmp_path):
        """Streamed batches with EQUAL dictionaries share one derived
        classify/parse/hash; different dictionary content never hits the
        memo; streamed profile equals the in-memory profile either way."""
        import pyarrow as pa
        import pyarrow.parquet as pq

        from deequ_tpu.data import table as table_mod
        from deequ_tpu.data.table import Table, parsed_dictionary

        # same dictionary in both row groups
        values = ["10", "20", "30", "40"] * 500
        at = pa.table({"s": pa.array(values).dictionary_encode()})
        path = str(tmp_path / "memo.parquet")
        pq.write_table(at, path, row_group_size=1000)

        calls = {"n": 0}
        original = table_mod.cached_dictionary_encode

        def counting(col, key, compute):
            def compute_counted(c):
                calls["n"] += 1
                return compute(c)

            return original(col, key, compute_counted)

        src = Table.scan_parquet(path, batch_rows=1000)
        batches = list(src.batches(1000))
        assert len(batches) >= 2
        cols = [b.column("s") for b in batches]
        assert cols[0]._dict_content_key is not None
        assert cols[0]._dict_content_key == cols[1]._dict_content_key
        import unittest.mock as mock

        with mock.patch.object(
            table_mod, "cached_dictionary_encode", counting
        ):
            a = parsed_dictionary(cols[0])
            b = parsed_dictionary(cols[1])
        assert calls["n"] == 1, "second batch must hit the cross-batch memo"
        assert np.array_equal(a[0], b[0]) and np.array_equal(a[1], b[1])

        # different content -> different key (no false sharing)
        at2 = pa.table(
            {"s": pa.array(["99", "88", "77", "66"] * 250).dictionary_encode()}
        )
        path2 = str(tmp_path / "memo2.parquet")
        pq.write_table(at2, path2)
        col2 = next(iter(Table.scan_parquet(path2).batches(10_000))).column(
            "s"
        )
        assert col2._dict_content_key != cols[0]._dict_content_key
        v2, ok2 = parsed_dictionary(col2)
        assert sorted(v2.tolist()) == [66.0, 77.0, 88.0, 99.0]
        assert ok2.all()


class TestToArrowDegenerate:
    def test_all_null_string_column_round_trips(self, tmp_path):
        """An all-null string column must not infer arrow's null type:
        dictionary-encoding a null-typed array produces a
        DictionaryArray parquet cannot write (regression: round-5 soak
        fuzz)."""
        from deequ_tpu.data.table import ColumnType, Table

        t = Table.from_pydict(
            {"s": [None, None, None], "x": [1.0, None, 3.0]},
            types={"s": ColumnType.STRING, "x": ColumnType.DOUBLE},
        )
        path = str(tmp_path / "allnull.parquet")
        t.to_parquet(path, dictionary_encode_strings=True)
        back = Table.from_parquet(path)
        assert back.column("s").null_count == 3
        assert back.column("s").ctype == ColumnType.STRING
        assert back.column("x").null_count == 1


class TestDataTypeFromCounts:
    def _datatype_agg(self, table, monkeypatch=None, disable=False):
        from deequ_tpu.runners import AnalysisRunner
        from deequ_tpu.analyzers import DataType

        res = AnalysisRunner.on_data(table).add_analyzers([DataType("s")]).run()
        (metric,) = res.metric_map.values()
        return metric.value.get()

    def test_matches_per_row_path(self, monkeypatch):
        from deequ_tpu.data.table import Table
        from deequ_tpu.profiles.column_profiler import ColumnProfiler

        rng = np.random.default_rng(7)
        pool = np.array(
            ["12", "-3", "4.5", "true", "false", "zebra", "", "+8", " 9",
             "7.", ".5", "NaN"],
            dtype=object,
        )
        values = pool[rng.integers(0, len(pool), 20_000)]
        values[rng.random(20_000) < 0.1] = None
        table = Table.from_pydict({"s": values})

        fast = ColumnProfiler.profile(table).profiles["s"]
        monkeypatch.setenv("DEEQU_TPU_NO_COUNTS_FASTPATH", "1")
        slow = ColumnProfiler.profile(
            Table.from_pydict({"s": values})
        ).profiles["s"]
        assert fast.type_counts == slow.type_counts
        assert fast.data_type == slow.data_type
        assert fast.completeness == slow.completeness


class TestProfilerEndToEndParity:
    def test_mixed_table_profiles_equal(self, monkeypatch):
        from deequ_tpu.data.table import Table
        from deequ_tpu.profiles.column_profiler import ColumnProfiler

        rng = np.random.default_rng(11)
        n = 50_000
        qty = rng.integers(1, 100, n).astype(np.int64)
        price = rng.lognormal(1.0, 0.5, n)
        price[rng.random(n) < 0.05] = np.nan
        code = np.array(
            [str(v) for v in rng.integers(0, 500, n)], dtype=object
        )
        cat = np.array(["a", "b", "c", "d"], dtype=object)[
            rng.integers(0, 4, n)
        ]
        flag = rng.random(n) < 0.5

        def build():
            return Table.from_numpy(
                {
                    "qty": qty.copy(),
                    "price": price.copy(),
                    "code": code.copy(),
                    "cat": cat.copy(),
                    "flag": flag.copy(),
                }
            )

        # KLL batch seeds are content-derived (sketch._batch_seed), so
        # two identical runs compare bit-for-bit with no seed pinning
        fast = ColumnProfiler.profile(build()).profiles
        monkeypatch.setenv("DEEQU_TPU_NO_COUNTS_FASTPATH", "1")
        slow = ColumnProfiler.profile(build()).profiles
        assert fast.keys() == slow.keys()
        for name in fast:
            f, s = fast[name], slow[name]
            assert f.completeness == s.completeness, name
            assert f.approximate_num_distinct_values == (
                s.approximate_num_distinct_values
            ), name
            assert f.data_type == s.data_type, name
            assert f.type_counts == s.type_counts, name
            if getattr(f, "mean", None) is not None:
                assert f.mean == pytest.approx(s.mean, rel=1e-12), name
                assert f.minimum == s.minimum and f.maximum == s.maximum, name
                assert f.sum == pytest.approx(s.sum, rel=1e-12), name
                assert f.std_dev == pytest.approx(s.std_dev, rel=1e-9), name
                fq = list(f.approx_percentiles or [])
                sq = list(s.approx_percentiles or [])
                assert len(fq) == len(sq) and len(fq) > 0, name
                for i, (fv, sv) in enumerate(zip(fq, sq)):
                    assert fv == pytest.approx(sv, rel=1e-9, abs=1e-12), (
                        name,
                        i,
                    )
            hf = getattr(f, "histogram", None)
            hs = getattr(s, "histogram", None)
            assert (hf is None) == (hs is None), name
            if hf is not None:
                assert hf.values == hs.values, name

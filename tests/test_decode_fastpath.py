"""Decode fast path (ISSUE 8): buffer-level native decode + parallel
row-group decode workers.

Three layers are pinned here:
  - bit-identity of `Table.from_arrow(..., fastpath_columns=...)`
    against the host chain on every Arrow edge case the kernels must
    honor — sliced arrays with nonzero offsets, multi-chunk columns,
    all-null groups, validity-bitmap tail bits, NaN folds, integer
    widening, bool bitmaps, dictionary codes (including dictionaries
    crossing row groups);
  - the planner: decode_column_types tokens, classify_decode_columns
    eligibility/reasons, the decode-unit replay of the serial
    coalescer, and the runtime/prediction zero-drift pin;
  - observability: decode counters, the telemetry derivations, and
    the sentinel's watch list.

The end-to-end fastpath/workers differential fuzz lives in
tests/test_suite_differential_fuzz.py.
"""

from __future__ import annotations

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from deequ_tpu.data.source import ParquetSource
from deequ_tpu.data.table import Table
from deequ_tpu.ops import native

pytestmark = pytest.mark.skipif(
    not native.available(), reason="no C compiler for the native kernels"
)


def _materialize(col):
    return np.asarray(col.values)


def assert_tables_bit_identical(fast: Table, slow: Table, context=""):
    assert fast.column_names == slow.column_names
    for name in fast.column_names:
        cf, cs = fast.column(name), slow.column(name)
        assert cf.ctype == cs.ctype, (context, name)
        vf, vs = _materialize(cf), _materialize(cs)
        assert vf.dtype == vs.dtype, (context, name, vf.dtype, vs.dtype)
        assert np.array_equal(vf, vs), (context, name)
        assert np.array_equal(np.asarray(cf.valid), np.asarray(cs.valid)), (
            context,
            name,
        )
        if "dict_encode" in cs._cache:
            codes_f, uniq_f = cf._cache["dict_encode"]
            codes_s, uniq_s = cs._cache["dict_encode"]
            assert codes_f.dtype == codes_s.dtype
            assert np.array_equal(codes_f, codes_s), (context, name)
            assert list(uniq_f) == list(uniq_s), (context, name)
            assert cf._dict_content_key == cs._dict_content_key


def both_paths(arrow_table, columns):
    fast = Table.from_arrow(arrow_table, fastpath_columns=set(columns))
    slow = Table.from_arrow(arrow_table)
    return fast, slow


class TestFromArrowBitIdentity:
    def test_sliced_float_with_nulls_and_nan(self):
        arr = pa.array(
            [1.5, None, float("nan"), 4.0, 5.5, None, 7.0], type=pa.float64()
        )
        t = pa.table({"x": arr.slice(1, 5)})
        fast, slow = both_paths(t, ["x"])
        assert_tables_bit_identical(fast, slow, "sliced f64")
        # null AND NaN slots both fold to invalid + 0.0
        assert _materialize(fast.column("x"))[0] == 0.0
        assert not fast.column("x").valid[0]

    def test_float32_widens_to_float64(self):
        arr = pa.array([1.25, None, float("nan"), 9.0], type=pa.float32())
        t = pa.table({"g": arr})
        fast, slow = both_paths(t, ["g"])
        assert_tables_bit_identical(fast, slow, "f32")
        assert _materialize(fast.column("g")).dtype == np.float64

    @pytest.mark.parametrize(
        "dtype",
        [pa.int8(), pa.int16(), pa.int32(), pa.int64(),
         pa.uint8(), pa.uint16(), pa.uint32(), pa.uint64()],
    )
    def test_integer_widths_widen_with_nulls(self, dtype):
        vals = [1, None, 3, None, 5, 100]
        t = pa.table({"i": pa.array(vals, type=dtype)})
        fast, slow = both_paths(t, ["i"])
        assert_tables_bit_identical(fast, slow, str(dtype))

    def test_uint64_wraps_like_numpy_astype(self):
        big = (1 << 63) + 7  # > INT64_MAX: must wrap, not raise
        t = pa.table({"u": pa.array([big, 1, None], type=pa.uint64())})
        fast, slow = both_paths(t, ["u"])
        assert_tables_bit_identical(fast, slow, "uint64 wrap")

    def test_bool_bitmap_with_nonzero_offset(self):
        arr = pa.array([True, None, False, True, None, True, False, True, True])
        t = pa.table({"b": arr.slice(3, 5)})
        fast, slow = both_paths(t, ["b"])
        assert_tables_bit_identical(fast, slow, "sliced bool")

    def test_validity_bitmap_tail_bits(self):
        # n not a multiple of 8: bits past the last row exist in the
        # bitmap byte but must never be read
        for n in (1, 3, 7, 9, 15, 17):
            vals = [None if i % 3 == 0 else float(i) for i in range(n)]
            t = pa.table({"x": pa.array(vals, type=pa.float64())})
            fast, slow = both_paths(t, ["x"])
            assert_tables_bit_identical(fast, slow, f"tail n={n}")

    def test_all_null_column(self):
        t = pa.table({"u": pa.array([None] * 11, type=pa.int32())})
        fast, slow = both_paths(t, ["u"])
        assert_tables_bit_identical(fast, slow, "all-null")
        assert not fast.column("u").valid.any()

    def test_multi_chunk_primitive(self):
        chunked = pa.chunked_array(
            [
                pa.array([1.0, None], type=pa.float64()),
                pa.array([float("nan"), 4.0, 5.0], type=pa.float64()),
                pa.array([], type=pa.float64()),
                pa.array([None, 7.0], type=pa.float64()),
            ]
        )
        t = pa.table({"x": chunked})
        fast, slow = both_paths(t, ["x"])
        assert_tables_bit_identical(fast, slow, "multi-chunk")

    def test_dictionary_column_single_chunk(self):
        arr = pa.array(["a", "b", None, "a", "c", None]).dictionary_encode()
        t = pa.table({"s": arr})
        fast, slow = both_paths(t, ["s"])
        assert_tables_bit_identical(fast, slow, "dict")
        codes, _ = fast.column("s")._cache["dict_encode"]
        assert codes.dtype == np.int32
        assert codes[2] == -1  # null sentinel

    def test_multi_chunk_dictionary_falls_back_identically(self):
        # dictionary unification is the fallback's job; the fast path
        # must route multi-chunk dict columns back without divergence
        chunked = pa.chunked_array(
            [
                pa.array(["a", "b", "a"]).dictionary_encode(),
                pa.array(["c", "b", None]).dictionary_encode(),
            ]
        )
        t = pa.table({"s": chunked})
        fast, slow = both_paths(t, ["s"])
        assert_tables_bit_identical(fast, slow, "multi-chunk dict")

    def test_fastpath_off_by_default_for_unlisted_columns(self):
        t = pa.table({"x": pa.array([1.0, 2.0]), "y": pa.array([3.0, 4.0])})
        fast, slow = both_paths(t, ["x"])  # y not approved
        assert_tables_bit_identical(fast, slow, "partial set")


class TestSourceDecode:
    def _write(self, tmp_path, n=3000, row_group_size=256):
        rng = np.random.default_rng(5)
        t = pa.table(
            {
                "x": pa.array(np.where(rng.random(n) < 0.1, np.nan, rng.random(n))),
                "i": pa.array(rng.integers(0, 50, n), type=pa.int16()),
                "s": pa.array(rng.choice(["a", "b", "c", None], n).tolist()),
                "b": pa.array((rng.random(n) < 0.5).tolist()),
            }
        )
        path = str(tmp_path / "d.parquet")
        pq.write_table(t, path, row_group_size=row_group_size)
        return path

    def test_decode_column_types_tokens(self, tmp_path):
        path = self._write(tmp_path)
        tokens = ParquetSource(path).decode_column_types()
        assert tokens == {
            "x": "double",
            "i": "int16",
            # strings arrive dictionary-encoded via read_dictionary
            "s": "dictionary<string,int32>",
            "b": "bool",
        }

    def test_dictionary_crossing_row_groups(self, tmp_path, monkeypatch):
        # each row group carries its own dictionary; codes must stay
        # per-batch consistent on both routes, at any worker count
        path = self._write(tmp_path, n=2000, row_group_size=100)

        def strings(env_workers, fastpath):
            monkeypatch.setenv("DEEQU_TPU_DECODE_WORKERS", env_workers)
            src = ParquetSource(path, batch_rows=512)
            if fastpath:
                src = src.with_decode_fastpath(["s", "x", "i", "b"])
            out = []
            for batch in src.batches(512):
                col = batch.column("s")
                vals = _materialize(col)
                valid = np.asarray(col.valid)
                out.extend(
                    v if ok else None for v, ok in zip(vals.tolist(), valid)
                )
            return out

        base = strings("1", False)
        assert strings("1", True) == base
        assert strings("3", True) == base
        assert strings("3", False) == base

    def test_decode_units_replay_serial_coalescing(self, tmp_path):
        # mixed tiny/large groups: write two files and concat-read one
        # with groups of very different sizes via multiple writes
        rng = np.random.default_rng(9)
        parts = [17, 13, 900, 11, 7, 600, 23]  # tiny runs around big groups
        tables = [
            pa.table({"v": pa.array(rng.random(k))}) for k in parts
        ]
        path = str(tmp_path / "mixed.parquet")
        with pq.ParquetWriter(path, tables[0].schema) as w:
            for t in tables:
                w.write_table(t, row_group_size=max(parts))
        src = ParquetSource(path, batch_rows=512)
        units = src._plan_decode_units(512)
        # units must cover every group exactly once, in order
        flat = [g for unit in units for g in unit]
        assert flat == list(range(len(parts)))
        # the serial iterator and the parallel one agree batch-for-batch
        serial = [b.num_rows for b in src._iter_tables_serial(512)]
        parallel = [b.num_rows for b in src._iter_tables_parallel(512, 3)]
        assert serial == parallel

    def test_workers_env_knob(self, monkeypatch):
        from deequ_tpu.ops import runtime

        monkeypatch.setenv("DEEQU_TPU_DECODE_WORKERS", "3")
        assert runtime.decode_workers() == 3
        monkeypatch.setenv("DEEQU_TPU_DECODE_WORKERS", "not-a-number")
        assert runtime.decode_workers() >= 1  # falls to the default
        monkeypatch.delenv("DEEQU_TPU_DECODE_WORKERS")
        import os

        assert runtime.decode_workers() == min(os.cpu_count() or 1, 4)

    def test_fastpath_env_knob(self, monkeypatch):
        from deequ_tpu.ops import runtime

        monkeypatch.delenv("DEEQU_TPU_DECODE_FASTPATH", raising=False)
        assert runtime.decode_fastpath_enabled()
        monkeypatch.setenv("DEEQU_TPU_DECODE_FASTPATH", "0")
        assert not runtime.decode_fastpath_enabled()


class TestPlannerAndDrift:
    def test_classifier_eligibility_and_reasons(self):
        from deequ_tpu.analyzers.base import InputSpec
        from deequ_tpu.ops.fused import classify_decode_columns

        col_types = {
            "f": "double",
            "i": "int32",
            "b": "bool",
            "d": "dictionary<string,int32>",
            "p": "string",
            "ts": "timestamp[us]",
            "dec": "decimal128(10, 2)",
        }
        specs = {
            "num:f": InputSpec(key="num:f", build=None, columns=("f",)),
            "valid:d": InputSpec(key="valid:d", build=None, columns=("d",)),
        }
        fast, fallbacks = classify_decode_columns(col_types, specs)
        assert set(fast) == {"f", "i", "b", "d"}
        reasons = dict(fallbacks)
        assert "host objects" in reasons["p"]
        assert "timestamp" in reasons["ts"]
        assert "decimal" in reasons["dec"]

    def test_classifier_conservative_on_unknown_prefix(self):
        from deequ_tpu.analyzers.base import InputSpec
        from deequ_tpu.ops.fused import classify_decode_columns

        specs = {
            "rawstr:d": InputSpec(key="rawstr:d", build=None, columns=("d",)),
        }
        fast, fallbacks = classify_decode_columns(
            {"d": "dictionary<string,int32>"}, specs
        )
        assert fast == []
        assert fallbacks and "rawstr" in fallbacks[0][1]

    def test_prediction_pins_to_trace_with_zero_drift(self, tmp_path, monkeypatch):
        from deequ_tpu.analyzers import Completeness, Mean
        from deequ_tpu.lint.cost import cost_drift
        from deequ_tpu.lint.explain import explain_plan
        from deequ_tpu.observe.runtrace import traced_run
        from deequ_tpu.runners import AnalysisRunner

        monkeypatch.setenv("DEEQU_TPU_PLACEMENT", "host")
        n = 4000
        t = pa.table(
            {
                "i": pa.array(np.arange(n), type=pa.int64()),
                "ts": pa.array([np.datetime64("2024-01-01", "us")] * n),
            }
        )
        path = str(tmp_path / "p.parquet")
        pq.write_table(t, path, row_group_size=1024)
        analyzers = [Mean("i"), Completeness("ts")]
        res = explain_plan(ParquetSource(path, batch_rows=2048), analyzers)
        scan = res.cost.scan_pass
        assert scan.decode_cols_total == 2
        assert scan.decode_cols_fast == 1
        assert dict(scan.decode_fallbacks).keys() == {"ts"}
        assert scan.saved_decode_bytes and scan.saved_decode_bytes > 0
        assert any(d.code == "DQ312" for d in res.diagnostics)

        with traced_run("t", enable=True) as handle:
            AnalysisRunner().on_data(
                ParquetSource(path, batch_rows=2048)
            ).add_analyzers(analyzers).run()
        drift = cost_drift(res.cost, handle.trace)
        assert drift["drift.decode_cols_fast"] == 0.0
        assert handle.trace.counters["decode_cols_fast"] == 1
        assert handle.trace.counters["decode_cols_total"] == 2

    def test_knob_off_disables_plan_and_prediction(self, tmp_path, monkeypatch):
        from deequ_tpu.analyzers import Mean
        from deequ_tpu.lint.explain import explain_plan
        from deequ_tpu.observe.runtrace import traced_run
        from deequ_tpu.runners import AnalysisRunner

        monkeypatch.setenv("DEEQU_TPU_DECODE_FASTPATH", "0")
        t = pa.table({"i": pa.array(np.arange(100), type=pa.int64())})
        path = str(tmp_path / "off.parquet")
        pq.write_table(t, path)
        analyzers = [Mean("i")]
        res = explain_plan(ParquetSource(path), analyzers)
        assert res.cost.scan_pass.decode_cols_total is None
        with traced_run("t", enable=True) as handle:
            AnalysisRunner().on_data(ParquetSource(path)).add_analyzers(
                analyzers
            ).run()
        assert "decode_cols_total" not in handle.trace.counters


class TestObservability:
    def test_telemetry_derivations_and_sentinel_watch(self, tmp_path, monkeypatch):
        from deequ_tpu.analyzers import Completeness, Mean
        from deequ_tpu.observe.runtrace import traced_run
        from deequ_tpu.observe.telemetry import engine_metric_record
        from deequ_tpu.runners import AnalysisRunner

        monkeypatch.setenv("DEEQU_TPU_PLACEMENT", "host")
        t = pa.table(
            {
                "i": pa.array(np.arange(500), type=pa.int64()),
                "ts": pa.array([np.datetime64("2024-01-01", "us")] * 500),
            }
        )
        path = str(tmp_path / "m.parquet")
        pq.write_table(t, path)
        with traced_run("t", enable=True) as handle:
            AnalysisRunner().on_data(ParquetSource(path)).add_analyzers(
                [Mean("i"), Completeness("ts")]
            ).run()
        rec = engine_metric_record(handle.trace)
        assert rec["engine.decode_fastpath_ratio"] == 0.5
        assert rec["engine.decode_workers"] == 1.0

        import importlib.util
        import os

        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        spec = importlib.util.spec_from_file_location(
            "sentinel", os.path.join(repo, "tools", "sentinel.py")
        )
        sentinel = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(sentinel)
        watched = dict(sentinel.WATCHED_SERIES)
        assert watched.get("engine.decode_fastpath_ratio") == "down"
        assert watched.get("engine.decode_workers") == "down"

    def test_decode_fastpath_span_attrs(self, tmp_path, monkeypatch):
        from deequ_tpu import observe
        from deequ_tpu.analyzers import Mean
        from deequ_tpu.runners import AnalysisRunner

        monkeypatch.setenv("DEEQU_TPU_PLACEMENT", "host")
        t = pa.table({"i": pa.array(np.arange(300), type=pa.int64())})
        path = str(tmp_path / "sp.parquet")
        pq.write_table(t, path)
        with observe.tracing() as tracer:
            AnalysisRunner().on_data(ParquetSource(path)).add_analyzers(
                [Mean("i")]
            ).run()

        def spans(root):
            stack = [root]
            while stack:
                sp = stack.pop()
                yield sp
                stack.extend(sp.children)

        plan_spans = [
            sp
            for root in tracer.roots
            for sp in spans(root)
            if sp.name == "decode_fastpath"
        ]
        assert plan_spans
        attrs = plan_spans[0].attrs
        assert attrs["cols_total"] == 1
        assert attrs["cols_fast"] == 1
        assert attrs["cols_fallback"] == 0
        assert attrs["workers"] >= 1

    def test_distributed_scan_uses_fastpath(self, tmp_path, monkeypatch):
        """DistributedScanPass plans decode routing like FusedScanPass:
        the mesh shards packed wire arrays, so the fast path must engage
        (and stay bit-identical) on the multi-device route too."""
        from deequ_tpu import observe
        from deequ_tpu.analyzers import Completeness, Mean
        from deequ_tpu.parallel import DistributedScanPass, data_mesh

        t = pa.table(
            {
                "x": pa.array(
                    [float(i) / 3 if i % 5 else None for i in range(4096)]
                ),
                "b": pa.array([bool(i % 2) for i in range(4096)]),
            }
        )
        path = str(tmp_path / "d.parquet")
        pq.write_table(t, path)
        analyzers = [Mean("x"), Completeness("b")]

        def run():
            with observe.tracing() as tracer:
                res = DistributedScanPass(analyzers, mesh=data_mesh()).run(
                    ParquetSource(path)
                )
            snap = [
                (
                    repr(r.analyzer),
                    r.analyzer.compute_metric_from(r.state_or_raise()).value.get(),
                )
                for r in res
            ]
            return snap, tracer

        on, tracer = run()
        monkeypatch.setenv("DEEQU_TPU_DECODE_FASTPATH", "0")
        off, _ = run()
        assert on == off

        def spans(root):
            stack = [root]
            while stack:
                sp = stack.pop()
                yield sp
                stack.extend(sp.children)

        plan_spans = [
            sp
            for root in tracer.roots
            for sp in spans(root)
            if sp.name == "decode_fastpath"
        ]
        assert plan_spans
        assert plan_spans[0].attrs["cols_fast"] == 2

"""Randomized differential testing: the same randomized analysis must
produce identical metrics through every execution path — single-device
fused, 8-device mesh, and each placement mode. Catches divergence the
hand-written parity tests' fixed shapes can miss (odd null densities,
degenerate columns, empty filters, constant values)."""

from __future__ import annotations

import numpy as np
import pytest

from deequ_tpu.analyzers import (
    ApproxCountDistinct,
    Completeness,
    Compliance,
    CountDistinct,
    DataType,
    Distinctness,
    Entropy,
    Histogram,
    Maximum,
    Mean,
    Minimum,
    PatternMatch,
    Size,
    StandardDeviation,
    Sum,
    Uniqueness,
)
from deequ_tpu.analyzers.sketch import ApproxQuantile
from deequ_tpu.data.table import ColumnType, Table
from deequ_tpu.parallel.distributed import data_mesh
from deequ_tpu.runners.analysis_runner import AnalysisRunner

N_TRIALS = 12


def random_table(rng: np.random.Generator) -> Table:
    n = int(rng.integers(1, 5000))
    null_density = float(rng.choice([0.0, 0.02, 0.5, 0.97]))
    x = rng.normal(rng.uniform(-100, 100), rng.uniform(0.0, 50.0), n)
    x[rng.random(n) < null_density] = np.nan
    cardinality = int(rng.choice([1, 2, 37, 4000]))
    pool = np.array(
        ["", "x", "-3", "7.5", "true", "word word", "ünïcodé", "it's"][
            : max(1, min(8, cardinality))
        ]
        + [f"v{i}" for i in range(max(0, cardinality - 8))],
        dtype=object,
    )
    s = pool[rng.integers(0, len(pool), n)]
    s[rng.random(n) < null_density] = None
    g = rng.integers(0, max(1, cardinality), n)
    # low-cardinality float: the hash-count family fast path's shape
    r = rng.integers(0, 9, n) / 8.0
    r[rng.random(n) < null_density] = np.nan
    return Table.from_pydict(
        {"x": list(x), "s": list(s), "g": [int(v) for v in g], "r": list(r)},
        types={
            "x": ColumnType.DOUBLE,
            "s": ColumnType.STRING,
            "g": ColumnType.LONG,
            "r": ColumnType.DOUBLE,
        },
    )


def random_analyzers(rng: np.random.Generator):
    pool = [
        Size(),
        Size(where="g > 1"),
        Completeness("x"),
        Completeness("s", where="g >= 0"),
        Compliance("pos", "x > 0"),
        Compliance("never", "x > 1e12"),
        PatternMatch("s", r"^v\d+$"),
        Mean("x"),
        Minimum("x"),
        Maximum("x"),
        Sum("x"),
        StandardDeviation("x"),
        DataType("s"),
        ApproxCountDistinct("g"),
        ApproxCountDistinct("s"),
        ApproxQuantile("x", 0.5),
        Mean("r"),
        StandardDeviation("r"),
        ApproxQuantile("r", 0.25),
        ApproxCountDistinct("r"),
        Uniqueness(("g",)),
        Distinctness(("s",)),
        CountDistinct(("g", "s")),
        Entropy("g"),
        Histogram("g", max_detail_bins=10),
    ]
    k = int(rng.integers(3, len(pool) + 1))
    idx = rng.choice(len(pool), size=k, replace=False)
    return [pool[i] for i in idx]


def quantile_abs_tol(key: str) -> float:
    """Scale-appropriate absolute tolerance for loose quantile
    comparisons: x spans [-100, 100] (abs=2.0 is ~1% of range); r is a
    [0, 1]-bounded low-card float where abs=2.0 would be vacuous."""
    return 0.05 if "(r," in key else 2.0


def metric_snapshot(ctx, analyzers):
    out = {}
    for analyzer in analyzers:
        v = ctx.metric_map[analyzer].value
        if v.is_failure:
            out[repr(analyzer)] = ("FAIL", type(v.exception).__name__)
        else:
            value = v.get()
            if hasattr(value, "values"):  # Distribution
                value = tuple(sorted((k, dv.absolute) for k, dv in value.values.items()))
            out[repr(analyzer)] = ("OK", value)
    return out


@pytest.mark.parametrize("seed", range(N_TRIALS))
def test_engines_agree_on_random_input(seed):
    rng = np.random.default_rng(1000 + seed)
    table = random_table(rng)
    analyzers = random_analyzers(rng)

    single = metric_snapshot(
        AnalysisRunner.do_analysis_run(table, analyzers, engine="single"), analyzers
    )
    mesh = metric_snapshot(
        AnalysisRunner.do_analysis_run(
            table, analyzers, engine="distributed", mesh=data_mesh()
        ),
        analyzers,
    )

    assert single.keys() == mesh.keys()
    for key in single:
        s_status, s_val = single[key]
        m_status, m_val = mesh[key]
        assert s_status == m_status, (key, single[key], mesh[key])
        if s_status == "FAIL":
            # same failure CLASS on both engines
            assert s_val == m_val, key
        elif key.startswith("ApproxQuantile"):
            # sketch randomization differs across shard splits: both
            # values are within rank error of the truth, so they agree
            # loosely, not bit-for-bit
            assert m_val == pytest.approx(
                s_val, rel=0.25, abs=quantile_abs_tol(key)
            ), (
                key,
                single[key],
                mesh[key],
            )
        elif isinstance(s_val, float):
            assert m_val == pytest.approx(s_val, rel=1e-9, abs=1e-12), (
                key,
                single[key],
                mesh[key],
            )
        else:
            assert s_val == m_val, key


@pytest.mark.parametrize("seed", range(0, N_TRIALS, 3))
def test_placements_agree_on_random_input(seed, monkeypatch):
    rng = np.random.default_rng(2000 + seed)
    table = random_table(rng)
    analyzers = random_analyzers(rng)

    snaps = {}
    for placement in ("host", "host-discrete", "device"):
        monkeypatch.setenv("DEEQU_TPU_PLACEMENT", placement)
        snaps[placement] = metric_snapshot(
            AnalysisRunner.do_analysis_run(table, analyzers, engine="single"),
            analyzers,
        )
    base = snaps["host"]
    for placement in ("host-discrete", "device"):
        other = snaps[placement]
        for key in base:
            b_status, b_val = base[key]
            o_status, o_val = other[key]
            assert b_status == o_status, (placement, key, base[key], other[key])
            if b_status != "OK":
                assert b_val == o_val, (placement, key)
            elif key.startswith("ApproxQuantile"):
                # host and device sketch paths decimate with different
                # per-batch structure: equal within rank error, not bits
                # (abs=2.0 keeps the bound meaningful near-zero medians,
                # same as the engine test above)
                assert o_val == pytest.approx(
                    b_val, rel=0.25, abs=quantile_abs_tol(key)
                ), (
                    placement,
                    key,
                )
            elif isinstance(b_val, float):
                assert o_val == pytest.approx(b_val, rel=1e-9, abs=1e-12), (
                    placement,
                    key,
                )
            else:
                assert b_val == o_val, (placement, key)

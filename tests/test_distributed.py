"""Distributed-pass tests on the virtual 8-device CPU mesh: sharded
results must equal single-device results (the mesh analogue of the
reference's StateAggregationIntegrationTest)."""

import jax
import numpy as np
import pytest

from deequ_tpu.analyzers import (
    ApproxCountDistinct,
    ApproxQuantile,
    Completeness,
    Correlation,
    DataType,
    Maximum,
    Mean,
    Minimum,
    Size,
    StandardDeviation,
    Sum,
)
from deequ_tpu.data.table import Table
from deequ_tpu.ops.fused import FusedScanPass
from deequ_tpu.parallel import DistributedScanPass, data_mesh, run_distributed_analysis


def make_table(n=10_000, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(3.0, 2.0, n)
    y = 0.5 * x + rng.normal(0, 1, n)
    x[::11] = np.nan
    return Table.from_numpy({"x": x, "y": y})


ANALYZERS = [
    Size(),
    Completeness("x"),
    Mean("x"),
    Minimum("x"),
    Maximum("x"),
    Sum("x"),
    StandardDeviation("x"),
    Correlation("x", "y"),
    ApproxCountDistinct("x"),
    ApproxQuantile("x", 0.5),
]


# On a box where the conftest platform override could not win (e.g. jax's
# backend was initialized on a real accelerator before conftest ran), the
# mesh tests still run — DistributedScanPass adapts to however many devices
# exist — but the 8-way sharding property itself is only asserted when the
# virtual CPU mesh is actually available.
requires_virtual_mesh = pytest.mark.skipif(
    len(jax.devices()) != 8,
    reason="needs the 8-device virtual CPU mesh; running on real hardware",
)


class TestDistributedParity:
    @requires_virtual_mesh
    def test_eight_devices(self):
        assert len(jax.devices()) == 8, "conftest must provide 8 virtual devices"

    def test_sharded_equals_single_device(self):
        table = make_table()
        single = FusedScanPass(ANALYZERS).run(table)
        sharded = DistributedScanPass(ANALYZERS, mesh=data_mesh()).run(table)
        for s, d in zip(single, sharded):
            ms = s.analyzer.compute_metric_from(s.state_or_raise())
            md = d.analyzer.compute_metric_from(d.state_or_raise())
            assert ms.value.is_success and md.value.is_success, repr(s.analyzer)
            if isinstance(ms.value.get(), float):
                if repr(s.analyzer).startswith("ApproxQuantile"):
                    # KLL is randomized; equal within sketch error
                    assert md.value.get() == pytest.approx(ms.value.get(), abs=0.1)
                else:
                    assert md.value.get() == pytest.approx(
                        ms.value.get(), rel=1e-9
                    ), repr(s.analyzer)

    def test_sharded_multibatch(self):
        table = make_table(4096)
        sharded = DistributedScanPass(
            [Size(), Mean("x"), Maximum("x")],
            mesh=data_mesh(),
            batch_size_per_device=64,  # forces many global batches
        ).run(table)
        single = FusedScanPass([Size(), Mean("x"), Maximum("x")]).run(table)
        for s, d in zip(single, sharded):
            assert d.state_or_raise() is not None
            assert d.analyzer.compute_metric_from(d.state_or_raise()).value.get() == (
                pytest.approx(
                    s.analyzer.compute_metric_from(s.state_or_raise()).value.get(),
                    rel=1e-9,
                )
            )

    def test_uneven_rows(self):
        # rows not divisible by device count exercises padding
        table = make_table(1001)
        context = run_distributed_analysis(table, [Size(), Completeness("x")])
        assert context.metric_map[Size()].value.get() == 1001.0

    def test_hll_registers_identical(self):
        table = make_table(5000)
        single = FusedScanPass([ApproxCountDistinct("x")]).run(table)[0]
        sharded = DistributedScanPass([ApproxCountDistinct("x")], mesh=data_mesh()).run(
            table
        )[0]
        assert np.array_equal(
            single.state_or_raise().registers, sharded.state_or_raise().registers
        )

    def test_datatype_on_mesh(self):
        t = Table.from_pydict({"s": (["1", "2.5", "true", "abc", None] * 100)})
        context = run_distributed_analysis(t, [DataType("s")])
        dist = context.metric_map[DataType("s")].value.get()
        assert dist["Integral"].absolute == 100
        assert dist["Fractional"].absolute == 100
        assert dist["Boolean"].absolute == 100
        assert dist["String"].absolute == 100
        assert dist["Unknown"].absolute == 100

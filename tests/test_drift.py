"""State-vs-state drift statistics (analyzers/drift.py) and the drift
Check family (checks/drift.py): every measure is pinned against a
direct numpy two-sample recomputation over the raw samples, the
hand-rolled chi-square survival function is validated against known
scipy values and closed forms, StateBags round-trip through the DQST
envelope (KLL rng tail included), and `DriftCheck.evaluate` covers the
pass/fail/missing-state/signature-mismatch (DQ324) paths.
"""

from __future__ import annotations

import math

import numpy as np
import pytest

from deequ_tpu.analyzers import (
    ApproxCountDistinct,
    ApproxQuantile,
    Completeness,
    Mean,
    Size,
    StandardDeviation,
)
from deequ_tpu.analyzers import states as S
from deequ_tpu.analyzers.drift import (
    StateBag,
    cardinality_drift,
    completeness_drift,
    frequency_chi_square,
    mean_drift,
    quantile_drift,
    regularized_gamma_q,
    stddev_drift,
)
from deequ_tpu.analyzers.frequency import FrequenciesAndNumRows
from deequ_tpu.checks import CheckLevel, CheckStatus, DriftCheck
from deequ_tpu.constraints.constraint import ConstraintStatus
from deequ_tpu.data.table import ColumnType, Table
from deequ_tpu.ops.fused import FusedScanPass
from deequ_tpu.ops.sketches.kll import KLLSketch
from deequ_tpu.repository.states import decode_states, encode_states


def _table(rng: np.random.Generator, n: int, *, mean=50.0, scale=10.0,
           nulls=0.05, card=200) -> Table:
    x = rng.normal(mean, scale, n)
    x[rng.random(n) < nulls] = np.nan
    g = rng.integers(0, card, n)
    return Table.from_pydict(
        {"x": list(x), "g": [int(v) for v in g]},
        types={"x": ColumnType.DOUBLE, "g": ColumnType.LONG},
    )


def _fold(analyzers, table):
    results = FusedScanPass(list(analyzers)).run(table)
    for r in results:
        assert r.error is None, r.error
    return [(r.analyzer, r.state) for r in results]


def _sketch(values) -> KLLSketch:
    sk = KLLSketch(k=2048)
    sk.update_batch(np.asarray(values, dtype=np.float64))
    return sk


def _np_two_sample_ks(a, b) -> float:
    """Direct numpy two-sample KS distance over the raw samples."""
    a = np.sort(np.asarray(a, dtype=np.float64))
    b = np.sort(np.asarray(b, dtype=np.float64))
    union = np.unique(np.concatenate([a, b]))
    fa = np.searchsorted(a, union, side="right") / len(a)
    fb = np.searchsorted(b, union, side="right") / len(b)
    return float(np.max(np.abs(fa - fb)))


# ---------------------------------------------------------------------------
# quantile (KS) drift
# ---------------------------------------------------------------------------


class TestQuantileDrift:
    def test_matches_numpy_ks_when_sketches_are_exact(self):
        """Small samples sit below the KLL compaction threshold, so the
        sketches hold every item and the state-vs-state KS must equal
        the direct numpy two-sample KS exactly."""
        rng = np.random.default_rng(3)
        a = rng.normal(0.0, 1.0, 400)
        b = rng.normal(0.6, 1.3, 500)
        got = quantile_drift(_sketch(a), _sketch(b))
        assert got == pytest.approx(_np_two_sample_ks(a, b), abs=1e-12)

    def test_identical_samples_have_zero_drift(self):
        rng = np.random.default_rng(4)
        a = rng.normal(10.0, 2.0, 300)
        assert quantile_drift(_sketch(a), _sketch(a.copy())) == 0.0

    def test_disjoint_supports_approach_one(self):
        a = _sketch(np.arange(0.0, 100.0))
        b = _sketch(np.arange(1000.0, 1100.0))
        assert quantile_drift(a, b) == pytest.approx(1.0)

    def test_large_samples_stay_near_numpy_within_sketch_error(self):
        rng = np.random.default_rng(5)
        a = rng.normal(0.0, 1.0, 60_000)
        b = rng.normal(0.25, 1.0, 60_000)
        got = quantile_drift(_sketch(a), _sketch(b))
        ref = _np_two_sample_ks(a, b)
        assert got == pytest.approx(ref, abs=0.02)  # 2x the k=2048 error

    def test_empty_sides(self):
        empty = KLLSketch(k=256)
        assert quantile_drift(empty, KLLSketch(k=256)) == 0.0
        assert quantile_drift(empty, _sketch([1.0, 2.0])) == 1.0

    def test_reads_the_digest_of_a_quantile_state(self):
        rng = np.random.default_rng(6)
        a = rng.normal(5.0, 1.0, 200)
        [(_, state)] = _fold(
            [ApproxQuantile("x", 0.5)],
            Table.from_pydict({"x": list(a)}, types={"x": ColumnType.DOUBLE}),
        )
        assert quantile_drift(state, _sketch(a)) == pytest.approx(0.0, abs=1e-12)


# ---------------------------------------------------------------------------
# cardinality drift
# ---------------------------------------------------------------------------


class TestCardinalityDrift:
    def _hll(self, rng, card, n=4000):
        [(_, state)] = _fold(
            [ApproxCountDistinct("g")],
            Table.from_pydict(
                {"g": [int(v) for v in rng.integers(0, card, n)]},
                types={"g": ColumnType.LONG},
            ),
        )
        return state

    def test_equal_sides_zero(self):
        rng = np.random.default_rng(7)
        a = self._hll(rng, 300)
        assert cardinality_drift(a, a) == 0.0

    def test_doubling_is_about_one_and_symmetric(self):
        rng = np.random.default_rng(8)
        a = self._hll(rng, 250)
        b = self._hll(rng, 500)
        d = cardinality_drift(a, b)
        assert d == pytest.approx(1.0, abs=0.15)  # HLL error band
        assert cardinality_drift(b, a) == d

    def test_matches_the_estimates_ratio_exactly(self):
        rng = np.random.default_rng(9)
        a, b = self._hll(rng, 100), self._hll(rng, 130)
        r = float(a.metric_value()) / float(b.metric_value())
        assert cardinality_drift(a, b) == pytest.approx(max(r, 1 / r) - 1.0)


# ---------------------------------------------------------------------------
# the chi-square machinery
# ---------------------------------------------------------------------------


class TestRegularizedGammaQ:
    def test_closed_form_dof2_family(self):
        # Q(1, x) = e^-x exactly
        for x in (0.01, 0.5, 1.0, 3.0, 10.0, 40.0):
            assert regularized_gamma_q(1.0, x) == pytest.approx(
                math.exp(-x), rel=1e-12
            )

    def test_closed_form_dof1_family(self):
        # Q(1/2, x) = erfc(sqrt(x)) — the chi-square(1) survival function
        for x in (0.05, 0.5, 2.0, 8.0):
            assert regularized_gamma_q(0.5, x) == pytest.approx(
                math.erfc(math.sqrt(x)), rel=1e-10
            )

    def test_integer_a_poisson_tail(self):
        # Q(k, x) = e^-x * sum_{j<k} x^j / j! for integer k
        for k in (2, 3, 6):
            for x in (0.5, 2.5, 9.0):
                ref = math.exp(-x) * sum(
                    x**j / math.factorial(j) for j in range(k)
                )
                assert regularized_gamma_q(float(k), x) == pytest.approx(
                    ref, rel=1e-10
                )

    def test_known_scipy_critical_values(self):
        # chi2.sf at the textbook 5% critical values, scipy-validated
        for stat, dof in (
            (3.841458820694124, 1),
            (5.991464547107979, 2),
            (11.070497693516351, 5),
        ):
            assert regularized_gamma_q(dof / 2.0, stat / 2.0) == pytest.approx(
                0.05, rel=1e-9
            )

    def test_domain_errors(self):
        with pytest.raises(ValueError):
            regularized_gamma_q(0.0, 1.0)
        with pytest.raises(ValueError):
            regularized_gamma_q(1.0, -0.5)
        assert regularized_gamma_q(2.0, 0.0) == 1.0


def _freq(counts: dict) -> FrequenciesAndNumRows:
    keys = list(counts)
    return FrequenciesAndNumRows(
        ["s"],
        [np.array(keys, dtype=object)],
        np.array([counts[k] for k in keys], dtype=np.int64),
        int(sum(counts.values())),
    )


class TestFrequencyChiSquare:
    def test_statistic_matches_numpy_recomputation(self):
        a = {"a": 10, "b": 20, "c": 30}
        b = {"a": 30, "b": 20, "c": 10, "d": 5}
        res = frequency_chi_square(_freq(a), _freq(b))
        # direct numpy homogeneity recomputation over the union
        union = sorted(set(a) | set(b))
        ca = np.array([a.get(k, 0) for k in union], dtype=np.float64)
        cb = np.array([b.get(k, 0) for k in union], dtype=np.float64)
        ta, tb = ca.sum(), cb.sum()
        ea = (ca + cb) * ta / (ta + tb)
        eb = (ca + cb) * tb / (ta + tb)
        ref = float((((ca - ea) ** 2) / ea + ((cb - eb) ** 2) / eb).sum())
        assert res.statistic == pytest.approx(ref, rel=1e-12)
        assert res.dof == len(union) - 1
        assert res.p_value == pytest.approx(
            regularized_gamma_q(res.dof / 2.0, res.statistic / 2.0)
        )

    def test_identical_distributions_do_not_reject(self):
        a = {"a": 500, "b": 300, "c": 200}
        res = frequency_chi_square(_freq(a), _freq(dict(a)))
        assert res.statistic == 0.0
        assert res.p_value == 1.0

    def test_shifted_distribution_rejects(self):
        a = {"a": 500, "b": 300, "c": 200}
        b = {"a": 200, "b": 300, "c": 500}
        assert frequency_chi_square(_freq(a), _freq(b)).p_value < 1e-6

    def test_degenerate_sides(self):
        res = frequency_chi_square(_freq({}), _freq({"a": 3}))
        assert (res.statistic, res.dof, res.p_value) == (0.0, 0, 1.0)
        res = frequency_chi_square(_freq({"a": 3}), _freq({"a": 5}))
        assert res.dof == 0 and res.p_value == 1.0


# ---------------------------------------------------------------------------
# scalar deltas, pinned against numpy recomputation
# ---------------------------------------------------------------------------


class TestScalarDrift:
    def test_completeness_mean_stddev_match_numpy(self):
        rng = np.random.default_rng(11)
        xa = rng.normal(40.0, 5.0, 800)
        xa[rng.random(800) < 0.10] = np.nan
        xb = rng.normal(44.0, 7.0, 600)
        xb[rng.random(600) < 0.02] = np.nan
        analyzers = [Completeness("x"), Mean("x"), StandardDeviation("x")]
        ta = Table.from_pydict({"x": list(xa)}, types={"x": ColumnType.DOUBLE})
        tb = Table.from_pydict({"x": list(xb)}, types={"x": ColumnType.DOUBLE})
        (_, ca), (_, ma), (_, sa) = _fold(analyzers, ta)
        (_, cb), (_, mb), (_, sb) = _fold(analyzers, tb)

        ra = np.count_nonzero(~np.isnan(xa)) / len(xa)
        rb = np.count_nonzero(~np.isnan(xb)) / len(xb)
        assert completeness_drift(ca, cb) == pytest.approx(abs(ra - rb), abs=1e-12)

        mean_a, mean_b = np.nanmean(xa), np.nanmean(xb)
        assert mean_drift(ma, mb) == pytest.approx(
            abs(mean_a - mean_b) / max(abs(mean_a), abs(mean_b)), rel=1e-9
        )

        std_a = np.nanstd(xa)  # population stddev, the engine's definition
        std_b = np.nanstd(xb)
        assert stddev_drift(sa, sb) == pytest.approx(
            abs(std_a - std_b) / max(std_a, std_b), rel=1e-6
        )

    def test_nan_handling(self):
        both = S.MeanState(float("nan"), 0)
        ok = S.MeanState(10.0, 2)
        assert mean_drift(both, S.MeanState(float("nan"), 0)) == 0.0
        assert mean_drift(both, ok) == float("inf")
        assert completeness_drift(
            S.NumMatchesAndCount(0, 0), S.NumMatchesAndCount(1, 2)
        ) == float("inf")

    def test_near_zero_means_do_not_explode(self):
        a = S.MeanState(1e-15, 1)
        b = S.MeanState(-1e-15, 1)
        assert mean_drift(a, b) == 0.0


# ---------------------------------------------------------------------------
# StateBag + envelope round trip (KLL rng tail included)
# ---------------------------------------------------------------------------


ANALYZERS = [
    Size(),
    Completeness("x"),
    Mean("x"),
    StandardDeviation("x"),
    ApproxCountDistinct("g"),
    ApproxQuantile("x", 0.5),
]


def _bag(rng: np.random.Generator, n=900, **kw) -> StateBag:
    pairs = _fold(ANALYZERS, _table(rng, n, **kw))
    return StateBag.from_pairs(pairs, signature="sig-A", label="test")


class TestStateBag:
    def test_round_trips_through_the_envelope(self):
        rng = np.random.default_rng(13)
        bag = _bag(rng)
        blob = encode_states([(a, bag.get(a)) for a in ANALYZERS])
        restored = StateBag.from_pairs(
            list(zip(ANALYZERS, decode_states(blob, ANALYZERS))),
            signature=bag.signature,
        )
        for a in ANALYZERS:
            assert a in restored
        # every drift measure sees the serde'd side as identical
        assert quantile_drift(
            bag.get(ApproxQuantile("x", 0.5)),
            restored.get(ApproxQuantile("x", 0.5)),
        ) == 0.0
        assert mean_drift(bag.get(Mean("x")), restored.get(Mean("x"))) == 0.0
        assert cardinality_drift(
            bag.get(ApproxCountDistinct("g")),
            restored.get(ApproxCountDistinct("g")),
        ) == 0.0

    def test_kll_rng_tail_survives_serde(self):
        """A deserialized KLL partial must merge bit-identically to the
        live sketch it was saved from — the envelope carries the PCG64
        generator position, not just (k, n, levels)."""
        rng = np.random.default_rng(14)
        analyzer = ApproxQuantile("x", 0.5)
        big = rng.normal(0.0, 1.0, 30_000)  # above compaction threshold
        [(_, live)] = _fold(
            [analyzer],
            Table.from_pydict({"x": list(big)}, types={"x": ColumnType.DOUBLE}),
        )
        [restored] = decode_states(
            encode_states([(analyzer, live)]), [analyzer]
        )
        [(_, other)] = _fold(
            [analyzer],
            Table.from_pydict(
                {"x": list(rng.normal(0.0, 1.0, 30_000))},
                types={"x": ColumnType.DOUBLE},
            ),
        )
        merged_live = live.merge(other)
        merged_restored = restored.merge(other)
        ka, na, la = merged_live.digest.to_arrays()
        kb, nb, lb = merged_restored.digest.to_arrays()
        assert (ka, na) == (kb, nb)
        assert all(np.array_equal(x, y) for x, y in zip(la, lb))

    def test_missing_analyzer(self):
        rng = np.random.default_rng(15)
        bag = _bag(rng)
        assert bag.get(Mean("zzz")) is None
        assert Mean("zzz") not in bag


# ---------------------------------------------------------------------------
# DriftCheck evaluate
# ---------------------------------------------------------------------------


class TestDriftCheck:
    CHECK = (
        DriftCheck(CheckLevel.ERROR, "weekly")
        .has_no_quantile_drift("x", max_quantile_shift=0.1)
        .has_no_cardinality_drift("g", max_ratio_drift=0.25)
        .has_no_completeness_drift("x", max_delta=0.05)
        .has_no_mean_drift("x", max_relative_delta=0.05)
        .has_no_stddev_drift("x", max_relative_delta=0.25)
    )

    def test_required_analyzers(self):
        reprs = {repr(a) for a in self.CHECK.required_analyzers()}
        assert repr(ApproxQuantile("x", 0.5)) in reprs
        assert repr(ApproxCountDistinct("g")) in reprs
        assert repr(Mean("x")) in reprs

    def test_stable_data_passes(self):
        rng = np.random.default_rng(16)
        result = self.CHECK.evaluate(
            current=_bag(rng), baseline=_bag(rng)
        )
        assert result.status == CheckStatus.SUCCESS
        assert all(
            r.status == ConstraintStatus.SUCCESS
            for r in result.constraint_results
        )
        assert result.diagnostics == []

    def test_skewed_data_fails_with_values(self):
        rng = np.random.default_rng(17)
        baseline = _bag(rng)
        current = _bag(rng, mean=80.0, scale=25.0, nulls=0.3, card=600)
        result = self.CHECK.evaluate(current=current, baseline=baseline)
        assert result.status == CheckStatus.ERROR
        failed = [
            r
            for r in result.constraint_results
            if r.status == ConstraintStatus.FAILURE
        ]
        assert len(failed) == len(result.constraint_results)
        assert all(r.value is not None for r in failed)

    def test_warning_level_degrades_status_not_constraints(self):
        rng = np.random.default_rng(18)
        check = DriftCheck(CheckLevel.WARNING, "w").has_no_mean_drift(
            "x", max_relative_delta=1e-9
        )
        result = check.evaluate(
            current=_bag(rng), baseline=_bag(rng)
        )
        assert result.status == CheckStatus.WARNING

    def test_missing_baseline_state_fails_with_dq324(self):
        rng = np.random.default_rng(19)
        current = _bag(rng)
        thin = StateBag.from_pairs(
            [(Mean("x"), current.get(Mean("x")))], signature="sig-A"
        )
        check = (
            DriftCheck(CheckLevel.ERROR, "w")
            .has_no_mean_drift("x")
            .has_no_completeness_drift("x")
        )
        result = check.evaluate(current=current, baseline=thin)
        by_desc = {
            r.constraint.description.split(" <=")[0]: r.status
            for r in result.constraint_results
        }
        assert by_desc["mean drift of 'x'"] == ConstraintStatus.SUCCESS
        assert by_desc["completeness drift of 'x'"] == ConstraintStatus.FAILURE
        assert any(d.code == "DQ324" for d in result.diagnostics)

    def test_signature_mismatch_fails_everything_with_dq324(self):
        rng = np.random.default_rng(20)
        a = _bag(rng)
        b = _bag(rng)
        b.signature = "sig-OTHER"
        result = self.CHECK.evaluate(current=a, baseline=b)
        assert result.status == CheckStatus.ERROR
        assert all(
            r.status == ConstraintStatus.FAILURE
            for r in result.constraint_results
        )
        assert any(d.code == "DQ324" for d in result.diagnostics)

    def test_unknown_signatures_are_not_a_mismatch(self):
        rng = np.random.default_rng(21)
        a = _bag(rng)
        b = _bag(rng)
        a.signature = None
        result = self.CHECK.evaluate(current=a, baseline=b)
        assert result.status == CheckStatus.SUCCESS

    def test_has_no_drift_bundle(self):
        rng = np.random.default_rng(22)
        check = DriftCheck(CheckLevel.ERROR, "bundle").has_no_drift(
            "x",
            max_quantile_shift=0.1,
            max_cardinality_drift=0.5,
            max_completeness_delta=0.05,
            max_mean_delta=0.05,
        )
        # cardinality rides column 'x' here: give both bags an x-HLL
        analyzers = list(ANALYZERS) + [ApproxCountDistinct("x")]

        def bag(**kw):
            pairs = _fold(analyzers, _table(rng, 900, **kw))
            return StateBag.from_pairs(pairs, signature="s")

        assert (
            check.evaluate(current=bag(), baseline=bag()).status
            == CheckStatus.SUCCESS
        )
        skew = check.evaluate(
            current=bag(mean=95.0, scale=30.0, nulls=0.4),
            baseline=bag(),
        )
        assert skew.status == CheckStatus.ERROR

    def test_min_mode_frequency_constraint(self):
        """p-value constraints pass when the value is ABOVE threshold
        (mode='min'), the inverse of every drift-magnitude bound."""
        check = DriftCheck(CheckLevel.ERROR, "freq").has_no_frequency_drift(
            "s", min_p_value=0.01
        )
        [constraint] = check.constraints
        assert constraint.mode == "min"
        stable = _freq({"a": 500, "b": 300})
        shifted = _freq({"a": 100, "b": 700})
        from deequ_tpu.analyzers import CountDistinct

        analyzer = CountDistinct(["s"])
        good = check.evaluate(
            current=StateBag.from_pairs([(analyzer, stable)]),
            baseline=StateBag.from_pairs([(analyzer, _freq({"a": 495, "b": 305}))]),
        )
        assert good.status == CheckStatus.SUCCESS
        bad = check.evaluate(
            current=StateBag.from_pairs([(analyzer, shifted)]),
            baseline=StateBag.from_pairs([(analyzer, stable)]),
        )
        assert bad.status == CheckStatus.ERROR
        [r] = bad.constraint_results
        assert r.value is not None and r.value < 0.01

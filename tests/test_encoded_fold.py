"""Encoded-fold directed tests (ISSUE 20): run-length and
dictionary-aware fold kernels.

Four layers:

* chunk-level — `decode_chunk_runs` on crafted chunks (long runs,
  bit-packed alternation, all-null pages) must expand via `expand_runs`
  to exactly what the row-width `decode_chunk` produces, bit for bit;
* fail-closed — a dictionary past the code cap, corrupt run streams,
  and the `decode.runs` chaos directive must fall the chunk back to the
  row-width path with identical results, never wrong values;
* planner — `classify_encfold_columns` names the disqualifying
  property per column (DQ325), the EXPLAIN plan line renders the
  runs/dict split, and the plan signature is keyed on the fold mode so
  encoded-fold states never mix with row-fold cache entries;
* suite-level — end-to-end scans with the fold on vs the
  `DEEQU_TPU_ENCODED_FOLD=0` kill switch must be bit-identical while
  the `engine.encfold.*` counters prove the fold actually engaged.
"""

from __future__ import annotations

import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from deequ_tpu import observe
from deequ_tpu.data import native_reader as nr
from deequ_tpu.data.source import ParquetSource
from deequ_tpu.ops import native, runtime
from deequ_tpu.testing import faults

requires_native = pytest.mark.skipif(
    not native.available(), reason="native library unavailable"
)

pytestmark = pytest.mark.usefixtures("_host_placement")


@pytest.fixture
def _host_placement(monkeypatch):
    monkeypatch.setenv("DEEQU_TPU_PLACEMENT", "host")


def _write(table, path, version="1.0", row_group_size=None, **kw):
    pq.write_table(
        table,
        path,
        compression="NONE",
        version=version,
        row_group_size=row_group_size or table.num_rows,
        **kw,
    )


def _chunk(tmp_path, column_arrays, name, version="1.0", **kw):
    """Raw bytes + meta of every (group, column) chunk of a file."""
    path = tmp_path / f"{name}.parquet"
    _write(pa.table(column_arrays), path, version=version, **kw)
    src = ParquetSource(str(path))
    metas = src._reader_chunk_meta(frozenset(column_arrays))
    fd = os.open(str(path), os.O_RDONLY)
    try:
        return {
            key: (nr.fetch_chunk(fd, meta), meta)
            for key, meta in metas.items()
        }
    finally:
        os.close(fd)


def _assert_expansion_bit_identical(raw, meta):
    """decode_chunk_runs -> expand_runs must equal decode_chunk exactly."""
    rc = nr.decode_chunk_runs(raw, meta)
    assert rc is not None, meta.column
    row = nr.decode_chunk(raw, meta)
    assert row is not None, meta.column
    exp = nr.expand_runs(rc)
    assert exp is not None, meta.column
    assert rc.null_count == row.null_count
    assert exp.null_count == row.null_count
    assert exp.num_values == row.num_values
    if row.validity is None:
        assert exp.validity is None or np.array_equal(
            np.unpackbits(exp.validity), np.unpackbits(exp.validity)
        )
    else:
        nbits = row.num_values
        assert np.array_equal(
            np.unpackbits(exp.validity, bitorder="little")[:nbits],
            np.unpackbits(row.validity, bitorder="little")[:nbits],
        )
    # raw value bits (uint views: NaN payloads and signed zeros count)
    a = exp.values.view(np.uint64 if exp.values.itemsize == 8 else np.uint32)
    b = row.values.view(np.uint64 if row.values.itemsize == 8 else np.uint32)
    assert np.array_equal(a, b), meta.column
    return rc


@requires_native
@pytest.mark.parametrize("version", ["1.0", "2.6"])
def test_runs_decode_long_runs_bit_identical(tmp_path, version):
    """Sorted low-cardinality data: few long runs. The run count must
    collapse far below the row count, and expansion must be exact."""
    n = 6000
    sorted_vals = np.sort(np.repeat(np.arange(12, dtype=np.int64), n // 12))
    rng = np.random.default_rng(5)
    chunks = _chunk(
        tmp_path,
        {
            "long": pa.array(sorted_vals),
            "nullish": pa.array(
                sorted_vals.astype(np.float64) * 0.5,
                mask=rng.random(n) < 0.15,
            ),
        },
        f"longruns_{version}",
        version=version,
    )
    for (g, name), (raw, meta) in chunks.items():
        rc = _assert_expansion_bit_identical(raw, meta)
        if name == "long":
            assert len(rc.run_len) < n // 50, "runs did not coalesce"
        assert int(np.sum(rc.run_len)) == rc.num_values - rc.null_count


@requires_native
def test_runs_decode_bitpacked_groups_bit_identical(tmp_path):
    """High-frequency alternation: the RLE/bit-packed hybrid emits
    bit-packed groups, the worst case for coalescing — expansion must
    still be exact and the def-level fold must match the page loop."""
    n = 4097  # ends mid bit-packed group
    rng = np.random.default_rng(11)
    vals = rng.integers(0, 64, size=n).astype(np.int64)
    chunks = _chunk(
        tmp_path,
        {"alt": pa.array(vals, mask=rng.random(n) < 0.5)},
        "bitpacked",
        data_page_size=2048,
    )
    for (g, name), (raw, meta) in chunks.items():
        rc = _assert_expansion_bit_identical(raw, meta)
        folded = native.encfold_def_nulls(
            rc.def_len, rc.def_val, rc.num_values
        )
        assert folded == rc.null_count


@requires_native
def test_runs_decode_all_null_def_runs(tmp_path):
    """All-null pages inside a dictionary-coded chunk: the leading
    pages carry only definition levels (long zero runs), and the null
    count comes from the def runs alone with no materialized validity
    mask. A chunk that is entirely null (pyarrow writes an empty
    dictionary) fails closed in BOTH decoders — the pyarrow fallback
    owns it, exactly like the row-width reader always has."""
    n = 5000
    vals = np.full(n, None, dtype=object)
    vals[-400:] = [float(i % 6) for i in range(400)]
    chunks = _chunk(
        tmp_path,
        {"mostly": pa.array(list(vals), type=pa.float64())},
        "allnullpages",
        data_page_size=1024,
    )
    ((g, name), (raw, meta)) = next(iter(chunks.items()))
    rc = _assert_expansion_bit_identical(raw, meta)
    assert rc.null_count == n - 400
    assert int(np.sum(rc.run_len)) == 400
    assert native.encfold_def_nulls(rc.def_len, rc.def_val, n) == n - 400
    # long all-null def runs actually coalesced (not one run per page)
    assert int(rc.def_len.max()) > 1024

    chunks = _chunk(
        tmp_path,
        {"gone": pa.array([None] * 1500, type=pa.float64())},
        "allnull",
    )
    ((g, name), (raw, meta)) = next(iter(chunks.items()))
    assert nr.decode_chunk_runs(raw, meta) is None
    assert nr.decode_chunk(raw, meta) is None  # pre-existing row behavior


@requires_native
def test_dict_code_overflow_fails_closed(tmp_path):
    """A dictionary wider than ENCFOLD_DICT_CAP distinct values: the
    footer still shows a pure-dictionary chunk (the planner approves),
    but the runs decoder must refuse at decode time — fail closed to
    the row-width path, never a truncated dictionary."""
    n = native.ENCFOLD_DICT_CAP + 1000
    vals = np.arange(n, dtype=np.int64)  # every value distinct
    chunks = _chunk(
        tmp_path,
        {"wide": pa.array(vals)},
        "overflow",
        use_dictionary=True,
        dictionary_pagesize_limit=1 << 21,
    )
    ((g, name), (raw, meta)) = next(iter(chunks.items()))
    if nr.decode_chunk(raw, meta) is None:
        pytest.skip("writer did not produce a decodable chunk")
    assert nr.decode_chunk_runs(raw, meta) is None


@requires_native
def test_corrupt_run_streams_fail_closed():
    """The fold kernels reject corrupt run structure: non-positive run
    lengths, out-of-range codes, and def-run row counts that disagree
    with the slice are -1 (None), never a wrong fold."""
    run_len = np.array([3, 5, 2], dtype=np.int64)
    run_code = np.array([0, 1, 0], dtype=np.uint32)
    counts = native.encfold_code_counts(run_len, run_code, 2)
    assert counts is not None and counts.tolist() == [5, 5]
    bad_len = run_len.copy()
    bad_len[1] = 0
    assert native.encfold_code_counts(bad_len, run_code, 2) is None
    bad_code = run_code.copy()
    bad_code[2] = 9
    assert native.encfold_code_counts(run_len, bad_code, 2) is None
    def_len = np.array([7, 3], dtype=np.int64)
    def_val = np.array([1, 0], dtype=np.uint8)
    assert native.encfold_def_nulls(def_len, def_val, 10) == 3
    assert native.encfold_def_nulls(def_len, def_val, 11) is None
    assert native.encfold_def_nulls(
        def_len, np.array([1, 2], dtype=np.uint8), 10
    ) is None


def _low_card_table(n=12000, seed=3):
    rng = np.random.default_rng(seed)
    return pa.table(
        {
            "code": pa.array(
                rng.integers(0, 40, n).astype(np.int64),
                mask=rng.random(n) < 0.07,
            ),
            "price": pa.array(
                rng.choice(np.round(rng.normal(0, 5, 25), 3), n),
                mask=rng.random(n) < 0.05,
            ),
        }
    )


def _run_suite(path, batch_rows=8192):
    from deequ_tpu.analyzers import (
        ApproxCountDistinct,
        ApproxQuantile,
        Completeness,
        Maximum,
        Mean,
        Minimum,
        Sum,
    )
    from deequ_tpu.runners import AnalysisRunner

    res = (
        AnalysisRunner()
        .on_data(ParquetSource(path, batch_rows=batch_rows))
        .add_analyzers(
            [
                Mean("code"),
                Sum("code"),
                Minimum("code"),
                Maximum("code"),
                Completeness("code"),
                ApproxQuantile("price", 0.5),
                ApproxCountDistinct("price"),
                Mean("price"),
            ]
        )
        .run()
    )
    return {
        repr(a): repr(m.value.get() if not m.value.is_failure else None)
        for a, m in res.metric_map.items()
    }


@requires_native
def test_suite_bit_identical_and_counters(tmp_path, monkeypatch):
    """End to end: encoded fold on vs the kill switch must be
    bit-identical, and under a tracer the fold must actually engage
    (planner approval, run-folded chunks, run/value/code counters,
    runs_native span attrs)."""
    path = str(tmp_path / "enc.parquet")
    _write(_low_card_table(), path, row_group_size=4096)

    monkeypatch.setenv("DEEQU_TPU_ENCODED_FOLD", "0")
    baseline = _run_suite(path)
    with observe.tracing() as off_tracer:
        assert _run_suite(path) == baseline
    assert "encfold_chunks" not in off_tracer.counters
    assert "encfold_cols" not in off_tracer.counters

    monkeypatch.setenv("DEEQU_TPU_ENCODED_FOLD", "1")
    with observe.tracing() as tracer:
        assert _run_suite(path) == baseline
    c = tracer.counters
    assert c.get("encfold_cols", 0) == 2
    assert c.get("encfold_chunks", 0) > 0
    assert c.get("encfold_runs", 0) > 0
    assert c.get("encfold_values", 0) > 0
    assert c.get("encfold_codes_folded", 0) > 0

    def _spans(root):
        yield root
        for ch in root.children:
            yield from _spans(ch)

    decodes = [
        sp
        for root in tracer.roots
        for sp in _spans(root)
        if sp.name == "page_decode"
    ]
    assert decodes
    assert sum(sp.attrs.get("runs_native", 0) for sp in decodes) == c.get(
        "encfold_runs"
    )


@requires_native
def test_chaos_decode_runs_fault_falls_back_bit_identical(
    tmp_path, monkeypatch
):
    """The decode.runs chaos directive: a corrupt run stream must fail
    closed to the row-width path — results stay bit-identical and the
    fallback is counted, never silently wrong values."""
    path = str(tmp_path / "chaos.parquet")
    _write(_low_card_table(), path, row_group_size=4096)
    monkeypatch.setenv("DEEQU_TPU_ENCODED_FOLD", "0")
    baseline = _run_suite(path)
    monkeypatch.setenv("DEEQU_TPU_ENCODED_FOLD", "1")
    with faults.install("seed=3,decode.runs:1.0"):
        with observe.tracing() as tracer:
            assert _run_suite(path) == baseline
    assert tracer.counters.get("encfold_chunks_fallback", 0) > 0
    assert tracer.counters.get("encfold_chunks", 0) == 0


@requires_native
def test_all_null_column_suite_completeness(tmp_path, monkeypatch):
    """An entirely-null run-folded column: Completeness and the family
    sketches must agree with the row path (n_rows from def runs)."""
    n = 5000
    rng = np.random.default_rng(9)
    t = pa.table(
        {
            "gone": pa.array([None] * n, type=pa.int64()),
            "code": pa.array(rng.integers(0, 9, n).astype(np.int64)),
        }
    )
    path = str(tmp_path / "nul.parquet")
    _write(t, path, row_group_size=2048)

    from deequ_tpu.analyzers import ApproxCountDistinct, Completeness, Mean
    from deequ_tpu.runners import AnalysisRunner

    def run():
        res = (
            AnalysisRunner()
            .on_data(ParquetSource(path, batch_rows=4096))
            .add_analyzers(
                [
                    Completeness("gone"),
                    ApproxCountDistinct("gone"),
                    Completeness("code"),
                    Mean("code"),
                ]
            )
            .run()
        )
        return {
            repr(a): repr(m.value.get() if not m.value.is_failure else None)
            for a, m in res.metric_map.items()
        }

    monkeypatch.setenv("DEEQU_TPU_ENCODED_FOLD", "0")
    baseline = run()
    monkeypatch.setenv("DEEQU_TPU_ENCODED_FOLD", "1")
    with observe.tracing() as tracer:
        assert run() == baseline
    assert tracer.counters.get("encfold_cols", 0) >= 1
    comp = [v for k, v in baseline.items() if "Completeness(gone" in k]
    assert comp and float(comp[0].strip("'")) == 0.0


@requires_native
def test_classifier_names_the_disqualifying_property(tmp_path):
    """DQ325 per-column fall-off reasons carry their class prefix:
    analyzer (StdDev without a sketch, where filters, row-width
    consumers), codec (dict-size fallback at write), and the approved
    columns render on the encoded-fold plan line with the runs/dict
    split."""
    from deequ_tpu.analyzers import (
        ApproxCountDistinct,
        Correlation,
        Mean,
        StandardDeviation,
    )
    from deequ_tpu.lint.explain import explain_plan, render_explain

    n = 9000
    rng = np.random.default_rng(2)
    t = pa.table(
        {
            "ok_m": pa.array(rng.integers(0, 20, n).astype(np.int64)),
            "ok_d": pa.array(
                rng.choice(np.round(rng.normal(0, 2, 16), 2), n)
            ),
            "sd": pa.array(rng.integers(0, 6, n).astype(np.int64)),
            "uniq": pa.array(rng.integers(0, 30, n).astype(np.int64)),
            "uniq2": pa.array(rng.integers(0, 30, n).astype(np.int64)),
            "wh": pa.array(rng.integers(0, 7, n).astype(np.int64)),
            "plainish": pa.array(rng.normal(size=n)),
            "plaincodec": pa.array(rng.integers(0, 50, n).astype(np.int64)),
        }
    )
    path = str(tmp_path / "cls.parquet")
    # plaincodec is written WITHOUT dictionary pages: a codec: falloff
    # even though its consumer (a sketch family) is memo-servable
    _write(
        t,
        path,
        row_group_size=n,
        use_dictionary=[c for c in t.column_names if c != "plaincodec"],
    )
    analyzers = [
        Mean("ok_m"),
        ApproxCountDistinct("ok_d"),
        StandardDeviation("sd"),
        Correlation("uniq", "uniq2"),
        Mean("wh", where="wh > 2"),
        Mean("plainish"),
        ApproxCountDistinct("plaincodec"),
    ]
    res = explain_plan(ParquetSource(path, batch_rows=4096), analyzers)
    reasons = {
        d.source: d.message
        for d in res.diagnostics
        if d.code == "DQ325"
    }
    assert "sd" in reasons and "StandardDeviation" in reasons["sd"]
    assert "uniq" in reasons and "Correlation" in reasons["uniq"]
    assert "uniq2" in reasons
    assert "wh" in reasons and "where" in reasons["wh"]
    # moments-only f64 without a sketch: nothing the memos can serve —
    # the benefit gate names it before any codec check runs
    assert "plainish" in reasons and "dict-size:" in reasons["plainish"]
    assert "plaincodec" in reasons and "codec:" in reasons["plaincodec"]
    scan = res.cost.scan_pass
    assert scan.encfold_cols == 2
    assert scan.encfold_moment_cols == 1
    rendered = render_explain(res.cost)
    assert "encoded-fold:" in rendered
    assert "runs=1" in rendered


@requires_native
def test_plan_signature_keyed_on_fold_mode(tmp_path, monkeypatch):
    """Encoded-fold states must never mix with row-fold cache entries:
    the plan signature changes with the fold mode."""
    from deequ_tpu.analyzers import Mean
    from deequ_tpu.repository.states import plan_signature_for

    monkeypatch.setenv("DEEQU_TPU_ENCODED_FOLD", "1")
    assert "encfold" in runtime.fold_signature_variant()
    on = plan_signature_for([Mean("code")])
    monkeypatch.setenv("DEEQU_TPU_ENCODED_FOLD", "0")
    assert "encfold" not in runtime.fold_signature_variant()
    off = plan_signature_for([Mean("code")])
    assert on != off


@requires_native
def test_kill_switch_disables_planning(tmp_path, monkeypatch):
    """DEEQU_TPU_ENCODED_FOLD=0: the planner never approves a column
    and the source never decodes runs."""
    from deequ_tpu.analyzers import Mean
    from deequ_tpu.lint.explain import explain_plan

    path = str(tmp_path / "off.parquet")
    _write(_low_card_table(4000), path)
    monkeypatch.setenv("DEEQU_TPU_ENCODED_FOLD", "0")
    assert not runtime.encoded_fold_enabled()
    res = explain_plan(ParquetSource(path), [Mean("code")])
    assert res.cost.scan_pass.encfold_cols is None

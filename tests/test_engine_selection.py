"""Distribution as THE engine: engine selection in every runner, and
end-to-end mesh-vs-single parity for ALL analyzer families through
VerificationSuite (the analogue of the reference default path,
AnalysisRunner.scala:279-326, where partition parallelism is not opt-in).
"""

import jax
import numpy as np
import pytest

from deequ_tpu.analyzers import (
    ApproxCountDistinct,
    ApproxQuantile,
    ApproxQuantiles,
    Completeness,
    Compliance,
    Correlation,
    CountDistinct,
    DataType,
    Distinctness,
    Entropy,
    Histogram,
    Maximum,
    Mean,
    Minimum,
    MutualInformation,
    PatternMatch,
    Size,
    StandardDeviation,
    Sum,
    Uniqueness,
    UniqueValueRatio,
)
from deequ_tpu.data.table import Table
from deequ_tpu.profiles.column_profiler import ColumnProfiler
from deequ_tpu.runners.analysis_runner import AnalysisRunner
from deequ_tpu.runners.engine import AUTO_MIN_ROWS, resolve_engine
from deequ_tpu.verification import VerificationSuite

requires_virtual_mesh = pytest.mark.skipif(
    len(jax.devices()) != 8,
    reason="needs the 8-device virtual CPU mesh; running on real hardware",
)


def make_table(n=20_011, seed=3):  # prime-ish: exercises shard padding
    rng = np.random.default_rng(seed)
    x = rng.normal(10.0, 3.0, n)
    x[rng.random(n) < 0.04] = np.nan
    y = 0.3 * x + rng.normal(0, 1, n)
    cats = np.array(["alpha", "beta", "gamma", "delta", None], dtype=object)
    return Table.from_numpy(
        {
            "x": x,
            "y": y,
            "qty": rng.integers(0, 30, n),
            "cat": cats[rng.integers(0, 5, n)],
            "code": np.array(
                [str(v) for v in rng.integers(0, 800, n)], dtype=object
            ),
        }
    )


# every analyzer family in SURVEY §2.5 (21 analyzers)
ALL_ANALYZERS = [
    Size(),
    Completeness("x"),
    Compliance("x big", "x >= 10"),
    PatternMatch("cat", r"^(alp|bet)"),
    Mean("x"),
    Minimum("x"),
    Maximum("x"),
    Sum("x"),
    StandardDeviation("x"),
    Correlation("x", "y"),
    DataType("code"),
    ApproxCountDistinct("code"),
    ApproxQuantile("x", 0.5),
    ApproxQuantiles("x", (0.25, 0.5, 0.75)),
    Uniqueness(["cat"]),
    Distinctness(["cat"]),
    UniqueValueRatio(["cat"]),
    CountDistinct(["cat", "qty"]),
    Entropy("cat"),
    MutualInformation("cat", "qty"),
    Histogram("cat"),
]


def _compare(map_d, map_s):
    for analyzer in ALL_ANALYZERS:
        md, ms = map_d[analyzer], map_s[analyzer]
        assert md.value.is_success, (analyzer, md.value)
        assert ms.value.is_success, (analyzer, ms.value)
        vd, vs = md.value.get(), ms.value.get()
        if isinstance(vd, float):
            if repr(analyzer).startswith("ApproxQuantile("):
                assert vd == pytest.approx(vs, abs=0.2), analyzer
            else:
                assert vd == pytest.approx(vs, rel=1e-9), analyzer
        elif isinstance(vd, dict):  # KeyedDoubleMetric
            for k in vd:
                assert vd[k] == pytest.approx(vs[k], abs=0.2), (analyzer, k)
        else:
            assert vd == vs, analyzer


class TestEngineParity:
    @requires_virtual_mesh
    def test_all_21_analyzers_mesh_equals_single(self):
        table = make_table()
        ctx_d = (
            AnalysisRunner.on_data(table)
            .add_analyzers(ALL_ANALYZERS)
            .with_engine("distributed")
            .run()
        )
        ctx_s = (
            AnalysisRunner.on_data(table)
            .add_analyzers(ALL_ANALYZERS)
            .with_engine("single")
            .run()
        )
        _compare(ctx_d.metric_map, ctx_s.metric_map)

    @requires_virtual_mesh
    def test_verification_suite_distributed(self):
        table = make_table()
        result = (
            VerificationSuite.on_data(table)
            .add_required_analyzers(ALL_ANALYZERS)
            .with_engine("distributed")
            .run()
        )
        single = (
            VerificationSuite.on_data(table)
            .add_required_analyzers(ALL_ANALYZERS)
            .with_engine("single")
            .run()
        )
        _compare(result.metrics, single.metrics)

    @requires_virtual_mesh
    def test_profiler_distributed(self):
        table = make_table()
        pd_ = ColumnProfiler.profile(table, engine="distributed")
        ps = ColumnProfiler.profile(table, engine="single")
        assert pd_.num_records == ps.num_records
        for name in ("x", "qty", "cat", "code"):
            d, s = pd_.profiles[name], ps.profiles[name]
            assert d.data_type == s.data_type
            assert d.completeness == pytest.approx(s.completeness, rel=1e-9)
            assert d.approximate_num_distinct_values == (
                s.approximate_num_distinct_values
            )
            if getattr(d, "mean", None) is not None:
                assert d.mean == pytest.approx(s.mean, rel=1e-9)

    def test_auto_threshold(self):
        # tiny tables stay single-device under "auto"
        assert resolve_engine("auto", num_rows=100) is None
        if len(jax.devices()) > 1:
            assert resolve_engine("auto", num_rows=AUTO_MIN_ROWS) is not None
            assert resolve_engine("distributed", num_rows=1) is not None
        assert resolve_engine("single", num_rows=10**9) is None
        with pytest.raises(ValueError):
            resolve_engine("warp")

    @requires_virtual_mesh
    def test_streaming_source_distributed(self, tmp_path):
        import pyarrow as pa
        import pyarrow.parquet as pq

        rng = np.random.default_rng(1)
        n = 12_000
        path = str(tmp_path / "d.parquet")
        pq.write_table(
            pa.table({"v": rng.normal(0, 1, n), "g": rng.integers(0, 7, n)}),
            path,
            row_group_size=2048,
        )
        source = Table.scan_parquet(path, batch_rows=2048)
        analyzers = [Size(), Mean("v"), Uniqueness(["g"]), Entropy("g")]
        ctx_d = (
            AnalysisRunner.on_data(source)
            .add_analyzers(analyzers)
            .with_engine("distributed")
            .run()
        )
        ctx_s = (
            AnalysisRunner.on_data(Table.from_parquet(path))
            .add_analyzers(analyzers)
            .with_engine("single")
            .run()
        )
        for a in analyzers:
            assert ctx_d.metric_map[a].value.get() == pytest.approx(
                ctx_s.metric_map[a].value.get(), rel=1e-9
            ), a

"""Engine telemetry end-to-end (ISSUE 6 tentpole + satellites).

Covers the fleet-telemetry loop: a traced suite run flattens into an
`engine.*` metric record (rows/s, per-phase seconds, wire bytes, peak
RSS from /proc, predicted-vs-observed drift), persists as a time series
through the ordinary `MetricsRepository`, renders as OpenMetrics
exposition text, and feeds the regression sentinel — which must flag
exactly a synthetically injected 30% throughput drop and exit nonzero.

Also here: the `_sanitize_tag_column` collision regression test and
loader filter coverage (`after`/`before`/`with_tag_values`) over
interleaved engine + data-quality result keys, including a filesystem
round trip.
"""

from __future__ import annotations

import importlib.util
import io
import json
import os
import re

from deequ_tpu.analyzers import Mean, Minimum, Size, StandardDeviation
from deequ_tpu.core.maybe import Success
from deequ_tpu.core.metrics import DoubleMetric, Entity
from deequ_tpu.observe import telemetry
from deequ_tpu.repository import (
    FileSystemMetricsRepository,
    InMemoryMetricsRepository,
    ResultKey,
)
from deequ_tpu.repository import engine as engine_repo
from deequ_tpu.repository.base import AnalysisResult, _sanitize_tag_column
from deequ_tpu.repository.serde import (
    deserialize_analyzer,
    serialize_analyzer,
)
from deequ_tpu.runners import AnalysisRunner
from deequ_tpu.runners.context import AnalyzerContext

from fixtures import get_df_with_numeric_values

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _traced_context():
    return (
        AnalysisRunner.on_data(get_df_with_numeric_values())
        .with_tracing(True)
        .add_analyzers([Size(), Mean("att1"), StandardDeviation("att2"), Minimum("att1")])
        .run()
    )


def _data_context(value=5.0):
    analyzer = Size()
    metric = DoubleMetric(Entity.DATASET, "Size", "*", Success(float(value)))
    return AnalyzerContext({analyzer: metric})


# ---------------------------------------------------------------------------
# /proc resources (satellite: no psutil)
# ---------------------------------------------------------------------------


class TestProcResources:
    def test_reports_peak_rss_and_major_faults(self):
        res = telemetry.proc_resources()
        assert res["peak_rss_mb"] > 0.0
        assert res["major_faults"] >= 0.0

    def test_traced_run_stamps_resources_on_root_span(self):
        ctx = _traced_context()
        attrs = ctx.run_trace.root.attrs
        assert attrs["peak_rss_mb"] > 0.0
        assert attrs["major_faults"] >= 0


# ---------------------------------------------------------------------------
# flat engine metric record from a traced run
# ---------------------------------------------------------------------------


class TestEngineMetricRecord:
    def test_record_shape_from_traced_run(self):
        ctx = _traced_context()
        rec = telemetry.engine_metric_record(ctx.run_trace, ctx.plan_cost)

        assert all(k.startswith("engine.") for k in rec)
        assert all(isinstance(v, float) for v in rec.values())
        assert rec["engine.wall_s"] > 0.0
        assert rec["engine.cpu_s"] >= 0.0
        assert rec["engine.rows"] == 6.0
        assert rec["engine.batches"] >= 1.0
        assert rec["engine.rows_per_s"] > 0.0
        assert rec["engine.peak_rss_mb"] > 0.0
        assert rec["engine.major_faults"] >= 0.0
        # the four dispatch-report phases are always present
        for phase in ("plan", "dispatch", "transfer", "merge"):
            assert f"engine.phase.{phase}_s" in rec

    def test_drift_is_zero_when_plan_matches_trace(self):
        # PR4's differential pins dispatch_signature equality between
        # PlanCost and the trace, so every drift field must be 0.
        ctx = _traced_context()
        rec = telemetry.engine_metric_record(ctx.run_trace, ctx.plan_cost)
        drift = {k: v for k, v in rec.items() if k.startswith("engine.drift.")}
        assert drift, "no drift fields computed despite a PlanCost"
        assert all(v == 0.0 for v in drift.values()), drift

    def test_wire_bytes_summed_from_dispatch_spans(self, monkeypatch):
        monkeypatch.setenv("DEEQU_TPU_PLACEMENT", "device")
        ctx = _traced_context()
        rec = telemetry.engine_metric_record(ctx.run_trace)
        assert rec.get("engine.wire_bytes", 0.0) > 0.0

    def test_extra_keys_are_prefixed(self):
        ctx = _traced_context()
        rec = telemetry.engine_metric_record(
            ctx.run_trace, extra={"round": 3.0, "engine.custom": 1.5}
        )
        assert rec["engine.round"] == 3.0
        assert rec["engine.custom"] == 1.5


# ---------------------------------------------------------------------------
# repository persistence: EngineMetric pseudo-analyzer + record_run
# ---------------------------------------------------------------------------


class TestEnginePersistence:
    def test_engine_metric_serde_round_trip(self):
        analyzer = engine_repo.EngineMetric("engine.rows_per_s", "engine")
        back = deserialize_analyzer(serialize_analyzer(analyzer))
        assert back == analyzer
        assert back.metric == "engine.rows_per_s"
        assert back.instance == "engine"

    def test_record_run_round_trip_in_memory(self):
        ctx = _traced_context()
        repo = InMemoryMetricsRepository()
        key = engine_repo.record_run(
            repo, ctx.run_trace, ctx.plan_cost,
            suite="nightly", dataset="numeric", data_set_date=1111,
        )
        assert key.data_set_date == 1111
        assert key.tags["telemetry"] == "engine"
        assert key.tags["suite"] == "nightly"
        assert key.tags["dataset"] == "numeric"
        assert "host" in key.tags and "placement" in key.tags

        series = engine_repo.engine_series(repo, "engine.rows_per_s")
        assert [p.time for p in series] == [1111]
        assert series[0].metric_value > 0.0
        names = engine_repo.engine_metric_names(repo)
        assert "engine.wall_s" in names and "engine.rows" in names

    def test_engine_series_survives_fs_round_trip(self, tmp_path):
        ctx = _traced_context()
        path = str(tmp_path / "engine.json")
        repo = FileSystemMetricsRepository(path)
        for date in (300, 100, 200):
            engine_repo.record_run(
                repo, ctx.run_trace, ctx.plan_cost,
                suite="s", dataset="d", data_set_date=date,
            )
        # fresh instance: forces deserialization from disk
        reloaded = FileSystemMetricsRepository(path)
        series = engine_repo.engine_series(reloaded, "engine.wall_s")
        assert [p.time for p in series] == [100, 200, 300]
        assert all(p.metric_value > 0.0 for p in series)

    def test_persist_skips_non_numeric_values(self):
        repo = InMemoryMetricsRepository()
        key = engine_repo.engine_result_key(1, suite="s", dataset="d")
        engine_repo.persist_engine_record(
            repo, {"engine.ok": 2.0, "engine.bad": "nan-string-not-a-number"}, key
        )
        names = engine_repo.engine_metric_names(repo)
        assert names == ["engine.ok"]


# ---------------------------------------------------------------------------
# satellite: _sanitize_tag_column collision fix
# ---------------------------------------------------------------------------


class TestSanitizeTagColumn:
    def test_collision_suffixes_are_distinct(self):
        # old code returned "a_b_2" for BOTH the second and third
        # colliding tag, silently overwriting a column
        row = {"a_b": 1}
        second = _sanitize_tag_column("a.b", row)
        assert second == "a_b_2"
        row[second] = 2
        third = _sanitize_tag_column("a@b", row)
        assert third == "a_b_3"

    def test_no_collision_passes_through(self):
        assert _sanitize_tag_column("region", {"value": 1}) == "region"
        assert _sanitize_tag_column("data set", {}) == "data_set"

    def test_three_colliding_tags_yield_three_columns(self):
        key = ResultKey(7, {"a b": "x", "a.b": "y", "a@b": "z"})
        rows = AnalysisResult(key, _data_context()).get_success_metrics_as_rows()
        assert len(rows) == 1
        row = rows[0]
        assert row["a_b"] == "x"
        assert row["a_b_2"] == "y"
        assert row["a_b_3"] == "z"
        assert row["dataset_date"] == 7


# ---------------------------------------------------------------------------
# satellite: loader filters over interleaved engine + data result keys
# ---------------------------------------------------------------------------


def _interleaved_repo(repo):
    """Data results at 100/300, engine records at 200/400."""
    for date in (100, 300):
        repo.save(ResultKey(date, {"kind": "data", "region": "eu"}), _data_context(date))
    for date in (200, 400):
        key = engine_repo.engine_result_key(date, suite="nightly", dataset="numeric")
        engine_repo.persist_engine_record(
            repo, {"engine.rows_per_s": float(date)}, key
        )
    return repo


class TestInterleavedLoaderFilters:
    def _check(self, repo):
        def dates(loader):
            return sorted(r.result_key.data_set_date for r in loader.get())

        assert dates(repo.load()) == [100, 200, 300, 400]
        assert dates(repo.load().after(150)) == [200, 300, 400]
        assert dates(repo.load().before(250)) == [100, 200]
        assert dates(repo.load().after(150).before(350)) == [200, 300]
        assert dates(repo.load().with_tag_values({"telemetry": "engine"})) == [200, 400]
        assert dates(repo.load().with_tag_values({"kind": "data"})) == [100, 300]
        assert dates(
            repo.load().after(250).with_tag_values({"telemetry": "engine"})
        ) == [400]
        # engine pseudo-analyzers coexist with data analyzers per-result
        engine_rows = repo.load().with_tag_values({"telemetry": "engine"}).get()
        for result in engine_rows:
            assert all(
                isinstance(a, engine_repo.EngineMetric)
                for a in result.analyzer_context.metric_map
            )

    def test_in_memory(self):
        self._check(_interleaved_repo(InMemoryMetricsRepository()))

    def test_fs_round_trip(self, tmp_path):
        path = str(tmp_path / "mixed.json")
        _interleaved_repo(FileSystemMetricsRepository(path))
        self._check(FileSystemMetricsRepository(path))


# ---------------------------------------------------------------------------
# OpenMetrics exposition (satellite: grammar-validated in tier 1)
# ---------------------------------------------------------------------------

_TYPE_RE = re.compile(r"^# TYPE ([a-zA-Z_:][a-zA-Z0-9_:]*) gauge$")
_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)"  # family name
    r"(\{[a-zA-Z_][a-zA-Z0-9_]*=\"(?:[^\"\\\n]|\\\\|\\\"|\\n)*\""
    r"(,[a-zA-Z_][a-zA-Z0-9_]*=\"(?:[^\"\\\n]|\\\\|\\\"|\\n)*\")*\})?"
    r" (-?\d+(\.\d+)?([eE][+-]?\d+)?)$"
)


def _validate_openmetrics(text):
    """Minimal exposition-grammar validator: returns {family: [samples]}.

    Enforces: newline-terminated, `# EOF` last line, every sample
    preceded by its family's TYPE line, no duplicate (family, labelset).
    """
    assert text.endswith("\n")
    lines = text.splitlines()
    assert lines[-1] == "# EOF"
    typed = set()
    seen = set()
    families = {}
    for line in lines[:-1]:
        m = _TYPE_RE.match(line)
        if m:
            assert m.group(1) not in typed, f"duplicate TYPE for {m.group(1)}"
            typed.add(m.group(1))
            continue
        m = _SAMPLE_RE.match(line)
        assert m, f"line fails exposition grammar: {line!r}"
        family, labels = m.group(1), m.group(2) or ""
        assert family in typed, f"sample before TYPE line: {line!r}"
        assert (family, labels) not in seen, f"duplicate label set: {line!r}"
        seen.add((family, labels))
        families.setdefault(family, []).append(line)
    return families


class TestOpenMetrics:
    def test_engine_and_data_results_validate(self):
        repo = _interleaved_repo(InMemoryMetricsRepository())
        text = telemetry.openmetrics_text(repo.load().get())
        families = _validate_openmetrics(text)
        assert "deequ_tpu_engine_rows_per_s" in families
        assert "deequ_tpu_metric" in families
        # data family labelled by metric/instance/entity
        assert any(
            'metric="Size"' in line for line in families["deequ_tpu_metric"]
        )

    def test_latest_point_per_tag_set_wins(self):
        repo = InMemoryMetricsRepository()
        tags = {"telemetry": "engine", "suite": "s"}
        for date, value in ((1, 10.0), (2, 99.0)):
            engine_repo.persist_engine_record(
                repo, {"engine.rows_per_s": value}, ResultKey(date, dict(tags))
            )
        text = telemetry.openmetrics_text(repo.load().get())
        _validate_openmetrics(text)
        assert "99.0" in text
        assert "10.0" not in text

    def test_label_values_are_escaped(self):
        repo = InMemoryMetricsRepository()
        nasty = 'we"ird\\path\nline'
        engine_repo.persist_engine_record(
            repo,
            {"engine.rows_per_s": 5.0},
            ResultKey(1, {"telemetry": "engine", "source": nasty}),
        )
        text = telemetry.openmetrics_text(repo.load().get())
        _validate_openmetrics(text)
        assert 'source="we\\"ird\\\\path\\nline"' in text

    def test_failed_and_non_finite_metrics_are_skipped(self):
        repo = InMemoryMetricsRepository()
        engine_repo.persist_engine_record(
            repo,
            {"engine.ok": 1.0, "engine.inf": float("inf"), "engine.nan": float("nan")},
            ResultKey(1, {"telemetry": "engine"}),
        )
        text = telemetry.openmetrics_text(repo.load().get())
        families = _validate_openmetrics(text)
        assert "deequ_tpu_engine_ok" in families
        assert "deequ_tpu_engine_inf" not in families
        assert "deequ_tpu_engine_nan" not in families


# ---------------------------------------------------------------------------
# regression sentinel (tentpole: injected 30% drop flags exactly once)
# ---------------------------------------------------------------------------


def _sentinel_module():
    spec = importlib.util.spec_from_file_location(
        "repo_sentinel", os.path.join(REPO, "tools", "sentinel.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


#: stable ~100 rows/s with small deterministic jitter, then a 30% drop
FLAT_HISTORY = [100.0, 101.0, 99.0, 100.5, 100.0, 99.5, 101.0, 100.0, 100.2]
DROP_VALUE = 70.0
DROP_TIME = 10


def _series_repo(path, inject_drop):
    repo = FileSystemMetricsRepository(path)
    values = list(FLAT_HISTORY) + ([DROP_VALUE] if inject_drop else [])
    for t, value in enumerate(values, start=1):
        key = engine_repo.engine_result_key(t, suite="bench", dataset="stream")
        engine_repo.persist_engine_record(
            repo, {"engine.rows_per_s": value, "engine.wall_s": 1.0}, key
        )
    return path


class TestSentinel:
    def test_detects_exactly_the_injected_drop(self, tmp_path):
        sentinel = _sentinel_module()
        path = _series_repo(str(tmp_path / "engine.json"), inject_drop=True)
        points = engine_repo.engine_series(
            FileSystemMetricsRepository(path), "engine.rows_per_s"
        )
        findings = sentinel.detect_regressions(points, direction="down", max_drop=0.2)
        assert [f["time"] for f in findings] == [DROP_TIME]
        assert findings[0]["value"] == DROP_VALUE
        assert "RateOfChange" in findings[0]["strategies"]

    def test_clean_history_passes(self, tmp_path):
        sentinel = _sentinel_module()
        path = _series_repo(str(tmp_path / "engine.json"), inject_drop=False)
        points = engine_repo.engine_series(
            FileSystemMetricsRepository(path), "engine.rows_per_s"
        )
        assert sentinel.detect_regressions(points, direction="down") == []

    def test_run_sentinel_exits_nonzero_on_regression(self, tmp_path):
        sentinel = _sentinel_module()
        path = _series_repo(str(tmp_path / "engine.json"), inject_drop=True)
        out = io.StringIO()
        rc = sentinel.run_sentinel(
            path, str(tmp_path / "no-bench-*.json"), out=out
        )
        text = out.getvalue()
        assert rc == 1
        assert "REGRESSION" in text
        assert "verdict: REGRESSION" in text
        assert f"t={DROP_TIME}" in text

    def test_run_sentinel_ok_on_clean_history(self, tmp_path):
        sentinel = _sentinel_module()
        path = _series_repo(str(tmp_path / "engine.json"), inject_drop=False)
        out = io.StringIO()
        rc = sentinel.run_sentinel(path, str(tmp_path / "no-bench-*.json"), out=out)
        assert rc == 0
        assert "verdict: ok" in out.getvalue()

    def test_main_cli_on_injected_drop(self, tmp_path, capsys):
        sentinel = _sentinel_module()
        path = _series_repo(str(tmp_path / "engine.json"), inject_drop=True)
        rc = sentinel.main(
            ["--repo", path, "--bench", str(tmp_path / "none-*.json")]
        )
        assert rc == 1
        assert "REGRESSION" in capsys.readouterr().out

    def test_not_enough_history_is_ok(self, tmp_path):
        sentinel = _sentinel_module()
        out = io.StringIO()
        rc = sentinel.run_sentinel(
            str(tmp_path / "absent.json"), str(tmp_path / "none-*.json"), out=out
        )
        assert rc == 0
        assert "not enough engine history" in out.getvalue()

    def test_constant_series_is_not_flagged(self, tmp_path):
        # zero-variance series are routine engine telemetry (identical
        # peak RSS every run); a one-sided OnlineNormal must not flag
        # them (regression: inf * 0 = nan used to poison the bounds)
        sentinel = _sentinel_module()
        path = str(tmp_path / "engine.json")
        repo = FileSystemMetricsRepository(path)
        for t in range(1, 11):
            engine_repo.persist_engine_record(
                repo,
                {
                    "engine.rows_per_s": 100.0,
                    "engine.wall_s": 1.0,
                    "engine.peak_rss_mb": 250.0,
                    "engine.phase.dispatch_s": 0.25,
                },
                engine_repo.engine_result_key(t, suite="s", dataset="d"),
            )
        out = io.StringIO()
        rc = sentinel.run_sentinel(path, str(tmp_path / "none-*.json"), out=out)
        assert rc == 0, out.getvalue()
        assert "verdict: ok" in out.getvalue()

    def test_bench_series_skips_unparsed_rounds_and_sorts(self, tmp_path):
        sentinel = _sentinel_module()
        rounds = [
            ("BENCH_r03.json", {"n": 3, "parsed": {"value": 120.0}}),
            ("BENCH_r01.json", {"n": 1, "parsed": None}),
            ("BENCH_r02.json", {"n": 2, "parsed": {"value": 100.0}}),
        ]
        for name, payload in rounds:
            (tmp_path / name).write_text(json.dumps(payload))
        points = sentinel._bench_series(str(tmp_path / "BENCH_r0*.json"))
        assert [(p.time, p.metric_value) for p in points] == [(2, 100.0), (3, 120.0)]

    def test_phase_share_regression_flags(self, tmp_path):
        # a phase eating a growing share of wall time is an "up" regression
        sentinel = _sentinel_module()
        path = str(tmp_path / "engine.json")
        repo = FileSystemMetricsRepository(path)
        shares = [0.10, 0.11, 0.10, 0.09, 0.10, 0.11, 0.10, 0.10, 0.10, 0.40]
        for t, share in enumerate(shares, start=1):
            key = engine_repo.engine_result_key(t, suite="s", dataset="d")
            engine_repo.persist_engine_record(
                repo,
                {
                    "engine.rows_per_s": 100.0,
                    "engine.wall_s": 2.0,
                    "engine.phase.dispatch_s": 2.0 * share,
                },
                key,
            )
        out = io.StringIO()
        rc = sentinel.run_sentinel(path, str(tmp_path / "none-*.json"), out=out)
        text = out.getvalue()
        assert rc == 1
        assert "engine.phase_share.dispatch" in text
        assert "t=10" in text


# ---------------------------------------------------------------------------
# end-to-end: traced run -> repository -> sentinel
# ---------------------------------------------------------------------------


class TestEndToEnd:
    def test_traced_suite_run_feeds_the_sentinel(self, tmp_path):
        sentinel = _sentinel_module()
        path = str(tmp_path / "engine.json")
        repo = FileSystemMetricsRepository(path)
        ctx = _traced_context()
        # 9 healthy synthetic points anchored on the real run's record,
        # then the real record scaled to a 30% throughput collapse
        rec = telemetry.engine_metric_record(ctx.run_trace, ctx.plan_cost)
        base = rec["engine.rows_per_s"]
        for t, jitter in enumerate(FLAT_HISTORY, start=1):
            engine_repo.persist_engine_record(
                repo,
                {"engine.rows_per_s": base * (jitter / 100.0), "engine.wall_s": rec["engine.wall_s"]},
                engine_repo.engine_result_key(t, suite="e2e", dataset="numeric"),
            )
        dropped = dict(rec)
        dropped["engine.rows_per_s"] = base * 0.70
        engine_repo.persist_engine_record(
            repo, dropped,
            engine_repo.engine_result_key(DROP_TIME, suite="e2e", dataset="numeric"),
        )
        out = io.StringIO()
        rc = sentinel.run_sentinel(path, str(tmp_path / "none-*.json"), out=out)
        text = out.getvalue()
        assert rc == 1
        assert "engine.rows_per_s" in text
        assert f"REGRESSION at t={DROP_TIME}" in text

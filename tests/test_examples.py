"""Smoke-runs every runnable example (reference: examples/ExamplesTest.scala
— the reference smoke-runs its examples the same way)."""

from __future__ import annotations

import runpy
from pathlib import Path

import pytest

EXAMPLES_DIR = Path(__file__).resolve().parent.parent / "examples"

EXAMPLES = sorted(p.name for p in EXAMPLES_DIR.glob("*_example.py"))


def test_examples_inventory_matches_reference():
    # the reference ships 7 runnable examples + utils/entities; we port all
    # of them and add three TPU-native extras (mesh, streaming parquet,
    # high-cardinality spill)
    assert {
        "basic_example.py",
        "metrics_repository_example.py",
        "data_profiling_example.py",
        "anomaly_detection_example.py",
        "constraint_suggestion_example.py",
        "incremental_metrics_example.py",
        "update_metrics_on_partitioned_data_example.py",
        "distributed_mesh_example.py",
        "streaming_parquet_example.py",
        "high_cardinality_spill_example.py",
    } <= set(EXAMPLES)


@pytest.mark.parametrize("example", EXAMPLES)
def test_example_runs(example, capsys, monkeypatch):
    monkeypatch.syspath_prepend(str(EXAMPLES_DIR))
    # examples are scripts: run them as __main__
    runpy.run_path(str(EXAMPLES_DIR / example), run_name="__main__")
    out = capsys.readouterr().out
    assert out.strip(), f"{example} printed nothing"


def test_basic_example_reproduces_readme_output(capsys, monkeypatch):
    """The README's expected outcome (reference: README.md:113-119):
    name completeness 0.8 and description URL ratio 0.4 fail."""
    monkeypatch.syspath_prepend(str(EXAMPLES_DIR))
    runpy.run_path(str(EXAMPLES_DIR / "basic_example.py"), run_name="__main__")
    out = capsys.readouterr().out
    assert "We found errors in the data" in out
    assert "Value: 0.8 does not meet the constraint requirement!" in out
    assert "Value: 0.4 does not meet the constraint requirement!" in out

"""Static cost analyzer + EXPLAIN tests (ISSUE 4 tentpole).

Covers the golden report shape, each DQ300-DQ304 diagnostic with a
firing AND a non-firing plan, strict-mode aggregation of DQ3xx warnings
next to DQ1xx/DQ2xx errors, and the zero-scan guarantee: the analyzer
must never pack a batch, run a fused pass, or launch a kernel.
"""

from __future__ import annotations

import numpy as np
import pytest

from deequ_tpu.analyzers import (
    ApproxCountDistinct,
    ApproxQuantile,
    Completeness,
    Histogram,
    Maximum,
    Mean,
    Minimum,
    StandardDeviation,
    Uniqueness,
)
from deequ_tpu.data.table import ColumnType, Table
from deequ_tpu.lint import (
    FieldInfo,
    PlanValidationError,
    SchemaInfo,
    analyze_plan,
    explain,
    explain_plan,
    validate_plan,
)
from deequ_tpu.lint.explain import (
    DQ302_CAP_LIMIT,
    DQ304_MAX_BATCHES,
    DQ304_MIN_BATCH,
)

SCHEMA = SchemaInfo(
    [
        FieldInfo("item", ColumnType.STRING, nullable=False),
        FieldInfo("qty", ColumnType.LONG, nullable=False),
        FieldInfo("price", ColumnType.DOUBLE, nullable=True),
        FieldInfo("cost", ColumnType.DOUBLE, nullable=True),
    ]
)


def codes(diags):
    return [d.code for d in diags]


def explain_diags(analyzers, schema=SCHEMA, **kwargs):
    return explain_plan(schema, analyzers=analyzers, **kwargs).diagnostics


# -- golden report ------------------------------------------------------------


class TestExplainReport:
    def test_golden_report_structure(self):
        report = explain(
            [
                Mean("price"),
                Minimum("price"),
                Completeness("qty"),
                ApproxCountDistinct("item"),
            ],
            SCHEMA,
            num_rows=1_000_000,
            placement="device",
        )
        # header
        assert "== Plan explain (static — no data scanned) ==" in report
        assert "analyzers: 4" in report
        assert "placement: device" in report
        assert "rows: 1000000" in report
        # the fused scan pass with its members and batch count
        assert "fused scan" in report and "[scan]" in report
        assert "batches: 1" in report
        # prediction lines are machine-checked elsewhere; here only shape
        assert "predicted counters: device_passes=" in report
        assert "predicted spans: " in report
        assert "-- no performance diagnostics --" in report

    def test_report_renders_diagnostics_tail(self):
        result = explain_plan(
            SCHEMA,
            analyzers=[ApproxQuantile("price", 0.5, relative_error=1e-6)],
        )
        text = result.render()
        assert "diagnostic(s) --" in text
        assert "DQ302" in text

    def test_explain_accepts_table_and_infers_rows(self):
        table = Table.from_pydict(
            {"price": np.arange(100, dtype=np.float64)}
        )
        result = explain_plan(table, analyzers=[Mean("price")])
        assert result.cost.num_rows == 100
        assert result.cost.scan_pass is not None

    def test_precondition_failures_reported_without_scanning(self):
        result = explain_plan(SCHEMA, analyzers=[Mean("item")])
        assert result.cost.precondition_failures
        assert "precondition failures" in result.render()


# -- DQ300: redundant extra pass ----------------------------------------------


class TestDQ300:
    def test_fires_when_aux_pass_rereads_scan_columns(self):
        diags = explain_diags([Mean("price"), Histogram("price")])
        assert "DQ300" in codes(diags)

    def test_silent_when_aux_pass_reads_other_columns(self):
        diags = explain_diags([Mean("price"), Histogram("item")])
        assert "DQ300" not in codes(diags)


# -- DQ301: equivalent-but-differently-normalized wheres ----------------------


class TestDQ301:
    def test_fires_on_provably_equivalent_spellings(self):
        diags = explain_diags(
            [
                Mean("price", where="qty > 1"),
                Minimum("price", where="not (qty <= 1)"),
            ]
        )
        assert "DQ301" in codes(diags)

    def test_silent_on_genuinely_different_predicates(self):
        diags = explain_diags(
            [
                Mean("price", where="qty > 1"),
                Minimum("price", where="qty > 2"),
            ]
        )
        assert "DQ301" not in codes(diags)

    def test_silent_on_identical_normalization(self):
        # same normalize key is DQ206's territory, not DQ301's
        diags = explain_diags(
            [
                Mean("price", where="qty > 1"),
                Minimum("price", where="qty  >  1"),
            ]
        )
        assert "DQ301" not in codes(diags)


# -- DQ302: sketch/grouping blowup --------------------------------------------


class TestDQ302:
    def test_fires_on_extreme_quantile_cap(self):
        analyzer = ApproxQuantile("price", 0.5, relative_error=1e-6)
        assert analyzer._sample_size() >= DQ302_CAP_LIMIT
        diags = explain_diags([analyzer])
        assert "DQ302" in codes(diags)

    def test_silent_on_default_quantile_cap(self):
        diags = explain_diags([ApproxQuantile("price", 0.5)])
        assert "DQ302" not in codes(diags)

    def test_fires_on_estimated_group_blowup(self):
        schema = SchemaInfo(
            [
                FieldInfo("a", ColumnType.STRING, approx_distinct=3000),
                FieldInfo("b", ColumnType.STRING, approx_distinct=3000),
            ]
        )
        diags = explain_diags([Uniqueness(["a", "b"])], schema=schema)
        assert "DQ302" in codes(diags)
        cost = explain_plan(schema, analyzers=[Uniqueness(["a", "b"])]).cost
        grouping = [p for p in cost.passes if p.kind == "grouping"]
        assert grouping and grouping[0].spill_risk
        assert grouping[0].estimated_groups == 3000 * 3000

    def test_silent_on_small_estimated_groups(self):
        schema = SchemaInfo(
            [
                FieldInfo("a", ColumnType.STRING, approx_distinct=10),
                FieldInfo("b", ColumnType.STRING, approx_distinct=10),
            ]
        )
        diags = explain_diags([Uniqueness(["a", "b"])], schema=schema)
        assert "DQ302" not in codes(diags)

    def test_silent_without_cardinality_hints(self):
        diags = explain_diags([Uniqueness(["item", "qty"])])
        assert "DQ302" not in codes(diags)


# -- DQ303: family-group cache tile over budget -------------------------------


class TestDQ303:
    @staticmethod
    def _wide_schema(n):
        return SchemaInfo(
            [FieldInfo(f"c{i}", ColumnType.DOUBLE) for i in range(n)]
        )

    def test_fires_when_one_family_group_batches_too_many_columns(self):
        n = 30
        diags = explain_diags(
            [ApproxQuantile(f"c{i}", 0.5) for i in range(n)],
            schema=self._wide_schema(n),
            placement="host-all",
        )
        assert "DQ303" in codes(diags)

    def test_silent_on_modest_family_groups(self):
        n = 4
        diags = explain_diags(
            [ApproxQuantile(f"c{i}", 0.5) for i in range(n)],
            schema=self._wide_schema(n),
            placement="host-all",
        )
        assert "DQ303" not in codes(diags)


# -- DQ304: tiny explicit batch size ------------------------------------------


class TestDQ304:
    def test_fires_on_tiny_batches_with_device_members(self):
        diags = explain_diags(
            [Mean("price"), Maximum("price")],
            num_rows=100_000,
            batch_size=4096,
            placement="device",
        )
        assert "DQ304" in codes(diags)
        cost = analyze_plan(
            [Mean("price")],
            SCHEMA,
            num_rows=100_000,
            batch_size=4096,
            placement="device",
        )
        assert cost.scan_pass.n_batches > DQ304_MAX_BATCHES
        assert cost.batch_size < DQ304_MIN_BATCH

    def test_silent_on_default_batch_size(self):
        diags = explain_diags(
            [Mean("price")], num_rows=100_000, placement="device"
        )
        assert "DQ304" not in codes(diags)

    def test_silent_without_device_members(self):
        # host-only members never dispatch: batch size is irrelevant
        diags = explain_diags(
            [ApproxQuantile("price", 0.5)],
            num_rows=100_000,
            batch_size=4096,
            placement="host-all",
        )
        assert "DQ304" not in codes(diags)


# -- strict-mode aggregation --------------------------------------------------


class TestStrictAggregation:
    def test_dq3xx_warnings_ride_in_plan_validation_error(self):
        with pytest.raises(PlanValidationError) as excinfo:
            validate_plan(
                SCHEMA,
                required_analyzers=[
                    Mean("item"),  # DQ102: numeric analyzer on STRING
                    ApproxQuantile("price", 0.5, relative_error=1e-6),
                ],
                mode="strict",
            )
        seen = codes(excinfo.value.diagnostics)
        assert "DQ102" in seen
        assert "DQ302" in seen

    def test_lenient_report_attaches_plan_cost(self):
        report = validate_plan(
            SCHEMA,
            required_analyzers=[Mean("price")],
            mode="lenient",
            num_rows=50_000,
        )
        assert report.plan_cost is not None
        assert report.plan_cost.num_rows == 50_000
        assert report.plan_cost.scan_pass is not None


# -- the zero-scan guarantee --------------------------------------------------


class TestZeroScan:
    def test_explain_never_packs_dispatches_or_scans(self, monkeypatch):
        """EXPLAIN is static: trap every execution entry point and prove
        none is reached even when a real data table is explained."""
        import deequ_tpu.ops.fused as fused
        import deequ_tpu.runners.grouping_runner as grouping_runner

        def trap(name):
            def _boom(*args, **kwargs):
                raise AssertionError(f"explain executed {name}")

            return _boom

        monkeypatch.setattr(
            fused, "pack_batch_inputs", trap("pack_batch_inputs")
        )
        monkeypatch.setattr(
            fused.FusedScanPass, "run", trap("FusedScanPass.run")
        )
        monkeypatch.setattr(
            fused.FusedScanPass, "_run_pass", trap("FusedScanPass._run_pass")
        )
        monkeypatch.setattr(
            grouping_runner,
            "run_grouping_analyzers",
            trap("run_grouping_analyzers"),
        )

        table = Table.from_pydict(
            {
                "price": np.arange(10_000, dtype=np.float64),
                "qty": np.arange(10_000, dtype=np.int64),
            }
        )
        result = explain_plan(
            table,
            analyzers=[
                Mean("price"),
                StandardDeviation("price"),
                ApproxQuantile("price", 0.5),
                Uniqueness(["qty"]),
                Histogram("qty"),
            ],
        )
        assert result.cost.scan_pass is not None
        assert result.cost.num_rows == 10_000
        assert result.render()

    def test_validate_plan_is_static_too(self, monkeypatch):
        import deequ_tpu.ops.fused as fused

        def boom(*args, **kwargs):
            raise AssertionError("validate_plan packed a batch")

        monkeypatch.setattr(fused, "pack_batch_inputs", boom)
        report = validate_plan(
            SCHEMA,
            required_analyzers=[Mean("price"), Uniqueness(["item"])],
            mode="lenient",
            num_rows=123_456,
        )
        assert report.plan_cost is not None

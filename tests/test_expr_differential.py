"""Differential fuzz of the expression engine against SQL three-valued
logic (emulated with pandas + explicit null handling): random
comparison/AND/OR predicates over columns with ~20% nulls must produce
exactly the WHERE-mask SQL would (NULL comparisons drop rows; each
operand's null-ness is tracked through the conjunction)."""

from __future__ import annotations

import numpy as np
import pandas as pd
import pytest

from deequ_tpu.data.expr import Predicate
from deequ_tpu.data.table import Table

OPS = [">", ">=", "<", "<=", "=", "!="]


@pytest.mark.parametrize("seed", range(40))
def test_random_predicates_match_sql_semantics(seed):
    rng = np.random.default_rng(seed)
    n = int(rng.integers(1, 200))
    a = rng.integers(-5, 5, n).astype(float)
    a[rng.random(n) < 0.2] = np.nan
    b = rng.integers(-5, 5, n).astype(float)
    s = np.array(["x", "y", "zz", None], dtype=object)[rng.integers(0, 4, n)]
    table = Table.from_pydict({"a": list(a), "b": list(b), "s": list(s)})
    df = pd.DataFrame({"a": a, "b": b, "s": s})

    op = rng.choice(OPS)
    lit = int(rng.integers(-5, 5))
    conj = rng.choice(["AND", "OR"])
    op2 = rng.choice([">", "<"])
    predicate = f"a {op} {lit} {conj} b {op2} 0"

    py_op = "==" if op == "=" else op
    p = pd.eval(f"df.a {py_op} {lit}")
    q = pd.eval(f"df.b {op2} 0")
    p_null, q_null = df.a.isna(), df.b.isna()
    if conj == "AND":
        expected = (p & ~p_null) & (q & ~q_null)
    else:
        expected = (p & ~p_null) | (q & ~q_null)

    got = Predicate(predicate).eval_mask(table)
    np.testing.assert_array_equal(np.asarray(expected), got, err_msg=predicate)


@pytest.mark.parametrize("seed", range(0, 40, 5))
def test_in_list_and_is_null(seed):
    rng = np.random.default_rng(100 + seed)
    n = int(rng.integers(1, 150))
    a = rng.integers(-5, 5, n).astype(float)
    a[rng.random(n) < 0.3] = np.nan
    s = np.array(["x", "y", "zz", None], dtype=object)[rng.integers(0, 4, n)]
    table = Table.from_pydict({"a": list(a), "s": list(s)})
    df = pd.DataFrame({"a": a, "s": s})

    got = Predicate("s IN ('x','zz') OR a IS NULL").eval_mask(table)
    expected = np.asarray(df.s.isin(["x", "zz"]) | df.a.isna())
    np.testing.assert_array_equal(expected, got)

    got2 = Predicate("s IS NOT NULL AND a >= 0").eval_mask(table)
    expected2 = np.asarray(df.s.notna() & (df.a >= 0).fillna(False))
    np.testing.assert_array_equal(expected2, got2)

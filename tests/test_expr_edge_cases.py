"""SQL predicate-engine edge cases: Kleene NULL logic, LIKE escapes,
IN with NULLs, CASE, arithmetic null propagation — the spec is Spark SQL
semantics (reference: the reference feeds all predicates through Spark,
e.g. Compliance analyzers/Compliance.scala:37 and the NULL-coalescing
isNonNegative predicate checks/Check.scala:676)."""

from __future__ import annotations

import numpy as np
import pytest

from deequ_tpu.data.expr import Predicate, eval_predicate
from deequ_tpu.data.table import Table


def tbl(**cols) -> Table:
    return Table.from_numpy(
        {
            k: (np.array(v, dtype=object) if any(x is None or isinstance(x, str) for x in v) else np.array(v))
            for k, v in cols.items()
        }
    )


def mask(expr: str, table: Table):
    return eval_predicate(expr, table).tolist()


class TestKleeneLogic:
    """Three-valued logic: NULL propagates through comparisons; AND/OR
    short-circuit per Kleene; the final row mask treats NULL as False."""

    def test_true_or_null_is_true(self):
        t = tbl(a=[1.0, 1.0], b=[None, 2.0])
        # a = 1 is TRUE for both rows; b > 1 is NULL for row 0
        assert mask("a = 1 OR b > 1", t) == [True, True]

    def test_false_or_null_is_null(self):
        t = tbl(a=[0.0, 0.0], b=[None, 2.0])
        assert mask("a = 1 OR b > 1", t) == [False, True]

    def test_false_and_null_is_false_negated(self):
        t = tbl(a=[0.0], b=[None])
        # FALSE AND NULL = FALSE, so NOT(...) = TRUE
        assert mask("NOT (a = 1 AND b > 1)", t) == [True]

    def test_true_and_null_is_null(self):
        t = tbl(a=[1.0], b=[None])
        assert mask("a = 1 AND b > 1", t) == [False]  # NULL -> excluded

    def test_not_null_is_null(self):
        t = tbl(b=[None, 0.0])
        assert mask("NOT (b > 1)", t) == [False, True]

    def test_null_comparisons_propagate(self):
        t = tbl(a=[None, 1.0])
        for expr in ("a = 1", "a != 1", "a < 1", "a >= 1"):
            assert mask(expr, t)[0] is np.False_ or mask(expr, t)[0] is False

    def test_is_null_and_is_not_null(self):
        t = tbl(a=[None, 1.0])
        assert mask("a IS NULL", t) == [True, False]
        assert mask("a IS NOT NULL", t) == [False, True]

    def test_null_equality_is_not_true_for_two_nulls(self):
        t = tbl(a=[None], b=[None])
        assert mask("a = b", t) == [False]


class TestInAndBetween:
    def test_in_list_with_null_value(self):
        t = tbl(s=["a", None, "c"])
        assert mask("s IN ('a', 'b')", t) == [True, False, False]

    def test_not_in_with_null_is_null(self):
        t = tbl(s=["a", None, "c"])
        # NULL NOT IN (...) is NULL -> excluded
        assert mask("s NOT IN ('a', 'b')", t) == [False, False, True]

    def test_between_inclusive(self):
        t = tbl(x=[0.0, 1.0, 5.0, 7.0, 8.0, None])
        assert mask("x BETWEEN 1 AND 7", t) == [False, True, True, True, False, False]

    def test_not_between(self):
        t = tbl(x=[0.0, 5.0, None])
        assert mask("x NOT BETWEEN 1 AND 7", t) == [True, False, False]


class TestLike:
    def test_percent_wildcard(self):
        t = tbl(s=["hello", "help", "shell", None])
        assert mask("s LIKE 'hel%'", t) == [True, True, False, False]
        assert mask("s LIKE '%ell%'", t) == [True, False, True, False]

    def test_underscore_wildcard(self):
        t = tbl(s=["cat", "cut", "coat"])
        assert mask("s LIKE 'c_t'", t) == [True, True, False]

    def test_regex_metacharacters_are_literal_in_like(self):
        # '.' and '*' and '(' must NOT act as regex in LIKE patterns
        t = tbl(s=["a.b", "axb", "a*b", "a(b"])
        assert mask("s LIKE 'a.b'", t) == [True, False, False, False]
        assert mask("s LIKE 'a*b'", t) == [False, False, True, False]
        assert mask("s LIKE 'a(b'", t) == [False, False, False, True]

    def test_rlike_is_regex(self):
        t = tbl(s=["a.b", "axb"])
        assert mask("s RLIKE 'a.b'", t) == [True, True]

    def test_not_like(self):
        t = tbl(s=["hello", "world", None])
        assert mask("s NOT LIKE 'hel%'", t) == [False, True, False]


class TestCaseAndFunctions:
    def test_case_when(self):
        t = tbl(x=[1.0, 5.0, None])
        assert mask("CASE WHEN x > 2 THEN TRUE ELSE FALSE END", t) == [
            False, True, False,
        ]

    def test_coalesce_null_fill(self):
        t = tbl(x=[None, -1.0, 3.0])
        # the isNonNegative predicate shape (reference: Check.scala:676)
        assert mask("COALESCE(x, 0.0) >= 0", t) == [True, False, True]

    def test_arithmetic_null_propagation(self):
        t = tbl(a=[1.0, None], b=[2.0, 2.0])
        assert mask("a + b > 2", t) == [True, False]
        assert mask("a * b = 2", t) == [True, False]

    def test_division_and_comparison(self):
        t = tbl(a=[4.0, 9.0], b=[2.0, 3.0])
        assert mask("a / b = 2", t) == [True, False]


class TestStringAndQuoting:
    def test_escaped_single_quote_literal(self):
        t = tbl(s=["it's", "its"])
        assert mask("s = 'it''s'", t) == [True, False]

    def test_backtick_column_with_spaces_and_dots(self):
        t = Table.from_numpy(
            {"att.1 with space": np.array(["a", "b"], dtype=object)}
        )
        assert mask("`att.1 with space` = 'a'", t) == [True, False]

    def test_string_comparison_lexicographic(self):
        t = tbl(s=["apple", "banana"])
        assert mask("s < 'b'", t) == [True, False]


class TestErrors:
    def test_unknown_column_raises(self):
        t = tbl(a=[1.0])
        with pytest.raises(Exception):
            eval_predicate("nope > 1", t)

    def test_parse_error_raises(self):
        t = tbl(a=[1.0])
        with pytest.raises(Exception):
            Predicate("a >>> 1").eval_mask(t)

"""The float32 wire format (what a real TPU runs with x64 off), exercised
on CPU by forcing runtime.compute_dtype to float32: multi-batch scans must
keep counts EXACT (bitpacked masks, packed-output casts, 2^24 guard) and
float statistics within f32 tolerance of the f64 engine."""

from __future__ import annotations

import numpy as np
import pytest

from deequ_tpu.analyzers import (
    ApproxCountDistinct,
    Completeness,
    Compliance,
    DataType,
    Maximum,
    Mean,
    Minimum,
    PatternMatch,
    Size,
    StandardDeviation,
    Sum,
)
from deequ_tpu.analyzers.sketch import ApproxQuantile
from deequ_tpu.data.table import Table
from deequ_tpu.ops import runtime
from deequ_tpu.ops.fused import FusedScanPass


@pytest.fixture
def f32_engine(monkeypatch):
    import jax.numpy as jnp

    monkeypatch.setattr(runtime, "compute_dtype", lambda: jnp.float32)
    # exercise the DEVICE wire format, not the host fold
    monkeypatch.setenv("DEEQU_TPU_PLACEMENT", "device")


def make_table(n=10_000):
    rng = np.random.default_rng(3)
    x = rng.normal(100.0, 10.0, n)
    x[::17] = np.nan
    return Table.from_numpy(
        {
            "x": x,
            "q": rng.integers(-3, 1000, n),
            "s": np.array(
                [["9", "word", "1.5", None][i % 4] for i in range(n)], dtype=object
            ),
        }
    )


ANALYZERS = [
    Size(),
    Size(where="q > 500"),
    Completeness("x"),
    Completeness("s"),
    Compliance("pos", "q >= 0"),
    PatternMatch("s", r"^\d+$"),
    DataType("s"),
    ApproxCountDistinct("q"),
    Mean("x"),
    Minimum("x"),
    Maximum("x"),
    Sum("x"),
    StandardDeviation("x"),
    ApproxQuantile("x", 0.5),
]


def metrics_with(batch_size, table):
    out = {}
    for r in FusedScanPass(ANALYZERS, batch_size=batch_size).run(table):
        state = r.state_or_raise()
        metric = r.analyzer.compute_metric_from(state)
        out[repr(r.analyzer)] = metric.value.get()
    return out


def test_f32_multibatch_counts_exact_and_floats_bounded(f32_engine):
    table = make_table()
    f32_multi = metrics_with(512, table)  # 20 batches through the wire

    # recompute ground truth in f64 (fresh pass w/o the monkeypatched dtype
    # is not possible inside the fixture, so compute expected values directly)
    x = table.column("x")
    xs = x.values[x.valid]
    n = table.num_rows
    q = table.column("q").values

    # counting analyzers: EXACT across batches
    assert f32_multi["Size(None)"] == n
    assert f32_multi["Size(Some(q > 500))"] == int((q > 500).sum())
    assert f32_multi["Completeness(x,None)"] == pytest.approx(
        x.valid.sum() / n, abs=0
    )
    assert f32_multi["Compliance(pos,q >= 0,None)"] == pytest.approx(
        (q >= 0).sum() / n, abs=0
    )
    # 1 in 4 rows is a digit string; 1 in 4 is NULL
    assert f32_multi[f"PatternMatch(s,^\\d+$,None)"] == pytest.approx(0.25, abs=1e-12)

    # float statistics: within f32 relative tolerance
    assert f32_multi["Minimum(x,None)"] == pytest.approx(xs.min(), rel=1e-6)
    assert f32_multi["Maximum(x,None)"] == pytest.approx(xs.max(), rel=1e-6)
    assert f32_multi["Mean(x,None)"] == pytest.approx(xs.mean(), rel=1e-4)
    assert f32_multi["Sum(x,None)"] == pytest.approx(xs.sum(), rel=1e-4)
    assert f32_multi["StandardDeviation(x,None)"] == pytest.approx(
        xs.std(), rel=1e-3
    )
    assert f32_multi["ApproxQuantile(x,0.5,0.01)"] == pytest.approx(
        float(np.quantile(xs, 0.5)), rel=0.01
    )
    # HLL over int values: within the declared rsd
    exact_distinct = len(np.unique(q))
    assert f32_multi["ApproxCountDistinct(q,None)"] == pytest.approx(
        exact_distinct, rel=0.15
    )


def test_f32_batch_size_guard(f32_engine):
    table = make_table(100)
    results = FusedScanPass([Size()], batch_size=(1 << 24) + 8).run(table)
    with pytest.raises(ValueError, match="2\\^24"):
        results[0].state_or_raise()


def test_f32_multibatch_equals_singlebatch(f32_engine):
    """Same engine, different batch boundaries: counts identical, floats
    within fold roundoff."""
    table = make_table()
    multi = metrics_with(512, table)
    single = metrics_with(1 << 16, table)
    for key in multi:
        if key.startswith(("Size", "Completeness", "Compliance", "PatternMatch")):
            assert multi[key] == single[key], key
        elif key.startswith("ApproxQuantile"):
            assert multi[key] == pytest.approx(single[key], rel=0.02), key
        else:
            assert multi[key] == pytest.approx(single[key], rel=1e-4), key


class TestIllConditionedF32:
    """VERDICT r3 #6: the 1e-6 parity contract under a float32 wire must
    survive ill-conditioned data. The engine pre-centers each numeric
    column (scan-constant shift, undone via unshift_agg/unshift_batch)
    BEFORE the f32 cast; without it the variance signal is destroyed by
    wire quantization and no kernel can recover it."""

    def _table(self, n=40_000, mean=1.0e7, sd=1.0e-1):
        rng = np.random.default_rng(42)
        x = mean + rng.normal(0.0, sd, n)
        # y correlated with x through the SMALL signal only
        y = 2.0e7 + 3.0 * (x - mean) + rng.normal(0.0, sd / 10, n)
        run = np.full(n, mean)  # long near-constant run
        run[n // 2 :] = mean + 1.0e-1
        return Table.from_numpy({"x": x, "y": y, "run": run})

    def test_naive_f32_cast_destroys_the_signal(self):
        """The premise: casting x (mean 1e7, sd 0.1) straight to f32
        quantizes at 1 ulp = 1.0 — stddev inflates by ~the quantization
        noise. This is what a shift-less engine would compute at best."""
        t = self._table()
        x = t.column("x").values
        naive = np.asarray(x, dtype=np.float32).astype(np.float64)
        naive_sd = naive.std()
        # every value rounds to the same float32: the signal is GONE
        assert naive_sd == 0.0

    def test_stddev_and_mean_survive_f32_wire(self, f32_engine):
        from deequ_tpu.analyzers import StandardDeviation

        t = self._table()
        x = t.column("x").values
        res = FusedScanPass(
            [Mean("x"), StandardDeviation("x"), Minimum("x"), Maximum("x"), Sum("x")]
        ).run(t)
        got = {type(r.analyzer).__name__: r for r in res}
        exact_sd = float(np.std(np.asarray(x, dtype=np.float64)))
        sd = got["StandardDeviation"].state_or_raise().metric_value()
        assert sd == pytest.approx(exact_sd, rel=1e-3), (sd, exact_sd)
        mean = got["Mean"].state_or_raise().metric_value()
        assert mean == pytest.approx(float(np.mean(x)), rel=1e-9)
        assert got["Minimum"].state_or_raise().metric_value() == pytest.approx(
            float(np.min(x)), abs=1e-5
        )
        assert got["Maximum"].state_or_raise().metric_value() == pytest.approx(
            float(np.max(x)), abs=1e-5
        )
        assert got["Sum"].state_or_raise().metric_value() == pytest.approx(
            float(np.sum(np.asarray(x, dtype=np.float64))), rel=1e-7
        )

    def test_correlation_survives_f32_wire(self, f32_engine):
        from deequ_tpu.analyzers import Correlation

        t = self._table()
        x = np.asarray(t.column("x").values, dtype=np.float64)
        y = np.asarray(t.column("y").values, dtype=np.float64)
        exact_r = float(np.corrcoef(x, y)[0, 1])
        assert exact_r > 0.9  # the correlation lives in the small signal
        res = FusedScanPass([Correlation("x", "y")]).run(t)
        r = res[0].state_or_raise().metric_value()
        assert r == pytest.approx(exact_r, abs=2e-3), (r, exact_r)

    def test_near_constant_run_stddev(self, f32_engine):
        from deequ_tpu.analyzers import StandardDeviation

        t = self._table()
        res = FusedScanPass([StandardDeviation("run")]).run(t)
        sd = res[0].state_or_raise().metric_value()
        assert sd == pytest.approx(0.05, rel=1e-3)  # half at +0.1 -> sd 0.05

    def test_quantile_sample_unshifted(self, f32_engine):
        t = self._table()
        res = FusedScanPass([ApproxQuantile("x", 0.5)]).run(t)
        q = res[0].analyzer.compute_metric_from(res[0].state_or_raise())
        median = q.value.get()
        x = np.sort(np.asarray(t.column("x").values, dtype=np.float64))
        rank = (x <= median).mean()
        assert abs(rank - 0.5) <= 0.03, (median, rank)
        assert abs(median - 1.0e7) < 1.0  # absolute scale restored

    def test_leading_null_does_not_disable_centering(self, f32_engine):
        """The shift is picked from the first VALID row: a null in row 0
        (whose 0.0 fill is 'finite') must not silently disable the
        pre-centering (reviewer finding, round 4)."""
        from deequ_tpu.analyzers import StandardDeviation

        rng = np.random.default_rng(42)
        x = 1.0e7 + rng.normal(0.0, 0.1, 40_000)
        x[0] = np.nan
        t = Table.from_numpy({"x": x})
        res = FusedScanPass([StandardDeviation("x")]).run(t)
        sd = res[0].state_or_raise().metric_value()
        assert sd == pytest.approx(float(np.nanstd(x)), rel=1e-3)

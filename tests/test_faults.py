"""Chaos harness + run control (ISSUE 13): fault spec parsing, schedule
determinism, retry/backoff, RunController cancel/deadline semantics,
StallWatchdog dump-then-cancel, suite-level cancel-then-resume through
the state repository, and the DQ318/EXPLAIN resilience surface.
"""

from __future__ import annotations

import io
import struct
import time

import numpy as np
import pytest

from deequ_tpu.analyzers import Completeness, Mean, Size, StandardDeviation
from deequ_tpu.core.controller import (
    DQ_CANCELLED,
    DQ_DEADLINE,
    DQ_STALLED,
    RunCancelled,
    RunController,
    StallWatchdog,
    backoff_s,
    retry_call,
)
from deequ_tpu.data.table import ColumnType, Table
from deequ_tpu.repository.states import InMemoryStateRepository
from deequ_tpu.runners.analysis_runner import AnalysisRunner
from deequ_tpu.testing import faults
from deequ_tpu.testing.faults import (
    FaultPlan,
    FaultSpecError,
    InjectedFaultError,
    parse_spec,
)


def _bits(x: float) -> bytes:
    return struct.pack(">d", float(x))


def _random_table(rng: np.random.Generator, n: int = 400) -> Table:
    x = rng.normal(0.0, 10.0, n)
    x[rng.random(n) < 0.1] = np.nan
    return Table.from_pydict(
        {"x": list(x), "g": [int(v) for v in rng.integers(0, 20, n)]},
        types={"x": ColumnType.DOUBLE, "g": ColumnType.LONG},
    )


# ---------------------------------------------------------------------------
# spec parsing
# ---------------------------------------------------------------------------


class TestSpecParsing:
    def test_full_grammar(self):
        plan = parse_spec("seed=7,stall=0.5,read.pread:0.25:3,decode.chunk:1.0")
        assert plan.seed == 7
        assert plan.stall_s == 0.5
        assert plan.specs["read.pread"] == (0.25, 3)
        assert plan.specs["decode.chunk"] == (1.0, None)

    def test_empty_tokens_and_whitespace(self):
        plan = parse_spec(" seed=1 , , read.short:0.5:2 ")
        assert plan.seed == 1
        assert plan.specs == {"read.short": (0.5, 2)}

    @pytest.mark.parametrize(
        "spec",
        [
            "read.pread",            # no rate
            "read.pread:x",          # non-numeric rate
            "read.pread:0.5:1:9",    # too many fields
            "read.pread:1.5",        # rate out of [0,1]
            "no.such.point:0.5",     # unregistered point
        ],
    )
    def test_bad_specs_raise(self, spec):
        with pytest.raises(FaultSpecError):
            parse_spec(spec)

    def test_every_registered_point_parses(self):
        for point in sorted(faults.FAULT_POINTS):
            plan = parse_spec(f"{point}:1.0:1")
            assert point in plan.specs


# ---------------------------------------------------------------------------
# schedule determinism + budgets
# ---------------------------------------------------------------------------


class TestSchedule:
    def _schedule(self, plan: FaultPlan, point: str, n: int):
        out = []
        for _ in range(n):
            try:
                out.append(plan.decide(point))
            except InjectedFaultError:
                out.append("RAISE")
        return out

    def test_same_seed_same_schedule(self):
        a = parse_spec("seed=11,read.short:0.3")
        b = parse_spec("seed=11,read.short:0.3")
        assert self._schedule(a, "read.short", 200) == self._schedule(
            b, "read.short", 200
        )

    def test_different_seed_different_schedule(self):
        a = parse_spec("seed=11,read.short:0.3")
        b = parse_spec("seed=12,read.short:0.3")
        assert self._schedule(a, "read.short", 200) != self._schedule(
            b, "read.short", 200
        )

    def test_budget_caps_injections(self):
        plan = parse_spec("seed=3,read.pread:1.0:4")
        sched = self._schedule(plan, "read.pread", 50)
        assert sched.count("RAISE") == 4
        assert plan.injected["read.pread"] == 4
        # the first 4 occurrences fire (rate 1.0), later ones pass
        assert sched[:4] == ["RAISE"] * 4

    def test_unarmed_point_passes_through(self):
        plan = parse_spec("seed=3,read.pread:1.0")
        assert plan.decide("state.save") is None

    def test_raise_kind_carries_point_and_occurrence(self):
        plan = parse_spec("seed=0,decode.worker:1.0:1")
        with pytest.raises(InjectedFaultError) as exc_info:
            plan.decide("decode.worker")
        assert exc_info.value.point == "decode.worker"
        assert exc_info.value.occurrence == 0
        assert isinstance(exc_info.value, OSError)

    def test_data_directives(self):
        for point, directive in [
            ("read.short", "short"),
            ("read.corrupt", "corrupt"),
            ("decode.chunk", "fail"),
        ]:
            plan = parse_spec(f"{point}:1.0:1")
            assert plan.decide(point) == directive

    def test_install_arms_and_restores(self):
        assert faults.active_plan() is None
        with faults.install("seed=5,read.short:1.0:1") as plan:
            assert faults.active_plan() is plan
            assert faults.fault_point("read.short") == "short"
            assert faults.fault_point("read.short") is None  # budget spent
        assert faults.active_plan() is None
        assert faults.fault_point("read.short") is None

    def test_install_from_env(self, monkeypatch):
        monkeypatch.setenv(faults.ENV_KNOB, "seed=2,state.save:1.0:1")
        plan = faults.install_from_env()
        try:
            assert plan is not None
            assert faults.active_plan() is plan
        finally:
            monkeypatch.setenv(faults.ENV_KNOB, "")
            assert faults.install_from_env() is None
            # env-armed plans have no context manager: disarm by hand
            faults._PLAN = None
        assert faults.active_plan() is None


# ---------------------------------------------------------------------------
# retry + backoff
# ---------------------------------------------------------------------------


class TestRetry:
    def test_backoff_deterministic_and_bounded(self):
        for attempt in range(5):
            a = backoff_s(0.01, attempt, key="unit-3")
            b = backoff_s(0.01, attempt, key="unit-3")
            assert a == b
            lo = 0.01 * (2.0 ** attempt) * 0.5
            hi = 0.01 * (2.0 ** attempt) * 1.5
            assert lo <= a < hi

    def test_backoff_key_decorrelates(self):
        assert backoff_s(0.01, 2, key="a") != backoff_s(0.01, 2, key="b")

    def test_recovers_after_transient_failures(self):
        calls = {"n": 0}

        def flaky():
            calls["n"] += 1
            if calls["n"] <= 2:
                raise OSError("transient")
            return b"data"

        result, retries, recovered = retry_call(
            flaky, attempts=3, base_s=0.0001, key="t"
        )
        assert result == b"data"
        assert retries == 2
        assert recovered is True

    def test_none_result_counts_as_transient(self):
        calls = {"n": 0}

        def short_read():
            calls["n"] += 1
            return None if calls["n"] == 1 else b"full"

        result, retries, recovered = retry_call(
            short_read, attempts=3, base_s=0.0001
        )
        assert result == b"full"
        assert (retries, recovered) == (1, True)

    def test_exhaustion_degrades_never_raises(self):
        def always_fails():
            raise OSError("persistent")

        result, retries, recovered = retry_call(
            always_fails, attempts=2, base_s=0.0001
        )
        assert result is None
        assert retries == 2
        assert recovered is False

    def test_non_retryable_propagates(self):
        def typo():
            raise KeyError("not io")

        with pytest.raises(KeyError):
            retry_call(typo, attempts=3, base_s=0.0001)

    def test_first_try_success_is_zero_retries(self):
        result, retries, recovered = retry_call(
            lambda: 42, attempts=3, base_s=0.0001
        )
        assert (result, retries, recovered) == (42, 0, False)


# ---------------------------------------------------------------------------
# RunController + RunCancelled
# ---------------------------------------------------------------------------


class TestController:
    def test_cancel_raises_dq401_with_progress(self):
        ctl = RunController()
        ctl.check(where="warm")  # no-op before cancel
        ctl.cancel()
        with pytest.raises(RunCancelled) as exc_info:
            ctl.check(where="fold batch", progress={"batches": 7, "rows": 900})
        err = exc_info.value
        assert err.code == DQ_CANCELLED
        assert err.where == "fold batch"
        assert err.progress == {"batches": 7, "rows": 900}
        assert "[DQ401]" in str(err)
        assert "batches=7" in str(err)

    def test_first_cancel_wins_reason(self):
        ctl = RunController()
        ctl.cancel("stalled")
        ctl.cancel("cancelled")
        with pytest.raises(RunCancelled) as exc_info:
            ctl.check()
        assert exc_info.value.code == DQ_STALLED

    def test_deadline_trips_dq402(self):
        ctl = RunController(deadline_s=0.0)
        time.sleep(0.002)
        with pytest.raises(RunCancelled) as exc_info:
            ctl.check(where="partition p1")
        assert exc_info.value.code == DQ_DEADLINE
        assert ctl.cancelled

    def test_remaining_s(self):
        assert RunController().remaining_s() is None
        ctl = RunController(deadline_s=60.0)
        r = ctl.remaining_s()
        assert r is not None and 0 < r <= 60.0

    def test_beat_counts(self):
        ctl = RunController()
        for _ in range(3):
            ctl.beat()
        assert ctl.beats == 3


class TestWatchdog:
    def test_dump_then_cancel_on_silence(self):
        ctl = RunController()
        out = io.StringIO()
        wd = StallWatchdog(ctl, 0.03, out=out).start()
        try:
            deadline = time.monotonic() + 5.0
            while not ctl.cancelled and time.monotonic() < deadline:
                time.sleep(0.01)
        finally:
            wd.stop()
        assert ctl.cancelled
        with pytest.raises(RunCancelled) as exc_info:
            ctl.check()
        assert exc_info.value.code == DQ_STALLED
        assert wd.dumps >= 2  # one diagnostic dump BEFORE the cancel
        assert "no batch progress" in out.getvalue()

    def test_beats_keep_watchdog_quiet(self):
        ctl = RunController()
        out = io.StringIO()
        wd = StallWatchdog(ctl, 0.05, out=out).start()
        try:
            for _ in range(8):
                ctl.beat()
                time.sleep(0.02)
        finally:
            wd.stop()
        assert not ctl.cancelled

    def test_snapshot_fn_feeds_dump(self):
        ctl = RunController()
        out = io.StringIO()
        wd = StallWatchdog(
            ctl, 0.03, out=out, snapshot_fn=lambda: {"stage": "decode", "q": 4}
        ).start()
        try:
            deadline = time.monotonic() + 5.0
            while not ctl.cancelled and time.monotonic() < deadline:
                time.sleep(0.01)
        finally:
            wd.stop()
        assert "decode" in out.getvalue()


# ---------------------------------------------------------------------------
# suite-level: cancel mid-run, resume from committed partitions
# ---------------------------------------------------------------------------


class _CancelAfterFirstCommit(InMemoryStateRepository):
    """Trips the controller the moment the first partition state
    commits — the sharpest possible mid-run cancel."""

    def __init__(self, controller: RunController) -> None:
        super().__init__()
        self._controller = controller

    def _put(self, dataset, signature, fingerprint, blob):
        super()._put(dataset, signature, fingerprint, blob)
        self._controller.cancel()


class TestCancelThenResume:
    def test_rerun_scans_only_remaining_partitions(self, tmp_path, monkeypatch):
        monkeypatch.delenv("DEEQU_TPU_STATE_CACHE", raising=False)
        rng = np.random.default_rng(99)
        data_dir = tmp_path / "data"
        data_dir.mkdir()
        for i in range(3):
            _random_table(rng, 300 + 17 * i).to_parquet(
                str(data_dir / f"p{i}.parquet"), row_group_size=128
            )
        analyzers = [Size(), Mean("x"), StandardDeviation("x"), Completeness("x")]

        clean = AnalysisRunner.do_analysis_run(
            Table.scan_parquet_dataset(str(data_dir)), analyzers
        )

        ctl = RunController()
        repo = _CancelAfterFirstCommit(ctl)
        with pytest.raises(RunCancelled) as exc_info:
            AnalysisRunner.do_analysis_run(
                Table.scan_parquet_dataset(str(data_dir)), analyzers,
                state_repository=repo, dataset_name="resume",
                controller=ctl,
            )
        err = exc_info.value
        assert err.code == DQ_CANCELLED
        assert err.progress.get("partitions_done") == 1
        assert err.progress.get("partitions_total") == 3

        # the rerun loads the committed partition and scans ONLY the rest
        resumed = AnalysisRunner.do_analysis_run(
            Table.scan_parquet_dataset(str(data_dir)), analyzers,
            state_repository=repo, dataset_name="resume", tracing=True,
        )
        counters = resumed.run_trace.counters
        assert counters["partitions_cached"] == 1
        assert counters["partitions_scanned"] == 2
        for a in analyzers:
            assert _bits(clean.metric_map[a].value.get()) == _bits(
                resumed.metric_map[a].value.get()
            ), repr(a)

    def test_cancelled_run_leaks_no_engine_threads(self, tmp_path):
        import threading

        rng = np.random.default_rng(5)
        data_dir = tmp_path / "data"
        data_dir.mkdir()
        _random_table(rng, 2000).to_parquet(
            str(data_dir / "p0.parquet"), row_group_size=128
        )
        ctl = RunController()
        ctl.cancel()
        with pytest.raises(RunCancelled):
            AnalysisRunner.do_analysis_run(
                Table.scan_parquet_dataset(str(data_dir)),
                [Size(), Mean("x")],
                controller=ctl,
            )
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            alive = [
                t.name
                for t in threading.enumerate()
                if t.name.startswith("deequ-") and t.name != "deequ-watchdog"
            ]
            if not alive:
                break
            time.sleep(0.01)
        assert not alive, f"engine threads leaked past cancel: {alive}"


# ---------------------------------------------------------------------------
# EXPLAIN + DQ318: the resilience surface
# ---------------------------------------------------------------------------


class TestExplainResilience:
    def test_deadline_without_partitions_warns_dq318(self):
        from deequ_tpu.verification.suite import VerificationSuite

        rng = np.random.default_rng(1)
        explained = (
            VerificationSuite.on_data(_random_table(rng, 100))
            .add_required_analyzer(Mean("x"))
            .with_deadline(30.0)
            .explain()
        )
        rendered = str(explained)
        assert "resilience: retries=" in rendered
        assert "deadline=30s" in rendered
        assert any(
            d.code == "DQ318" for d in explained.diagnostics
        ), [d.code for d in explained.diagnostics]

    def test_no_deadline_no_dq318_no_resilience_deadline(self):
        from deequ_tpu.verification.suite import VerificationSuite

        rng = np.random.default_rng(1)
        explained = (
            VerificationSuite.on_data(_random_table(rng, 100))
            .add_required_analyzer(Mean("x"))
            .explain()
        )
        assert not any(d.code == "DQ318" for d in explained.diagnostics)
        assert "deadline=" not in str(explained)

"""Failure forensics (ISSUE 12 tentpole): row-level violation capture,
metric provenance, and the persistent audit trail.

Contracts pinned here:

* every FAILURE-status row-level-capable constraint yields >= 1 sampled
  violating row, and every sample's (partition, row group, row index,
  value) coordinates verify against an independent numpy mirror of the
  written data;
* the reservoir is deterministic (content-derived seed, the
  `sketch._batch_seed` trick): reruns sample identical rows;
* the report round-trips through the FileSystem metrics repository as a
  versioned binary envelope — corrupt, truncated, or version-bumped
  entries warn DQ317 and degrade to no-forensics, never a wrong answer —
  including under concurrent writers;
* EXPLAIN predicts forensics capability statically (DQ316 fall-offs);
* forensics is off by default and the off path returns None.
"""

from __future__ import annotations

import base64
import struct
import threading

import numpy as np
import pytest

from deequ_tpu.checks.check import Check, CheckLevel, CheckStatus
from deequ_tpu.data.table import Table
from deequ_tpu.observe.forensics import ForensicsReport
from deequ_tpu.repository.audit import (
    AUDIT_FORMAT_VERSION,
    AUDIT_MAGIC,
    AuditDecodeError,
    AuditRecord,
    audit_entry_for,
    decode_audit,
    encode_audit,
    load_audit_trail,
)
from deequ_tpu.repository.base import ResultKey
from deequ_tpu.repository.fs import FileSystemMetricsRepository
from deequ_tpu.verification.suite import VerificationSuite

ROW_GROUP = 100


def _partition_arrays(part: int, n: int = 400):
    """Deterministic per-partition columns with known violations."""
    rng = np.random.default_rng(1000 + part)
    ids = (np.arange(n) + part * n).astype(np.int64)
    val = rng.uniform(10.0, 90.0, n)
    name = np.array([f"n{i}" for i in range(n)], dtype=object)
    code = np.array(["ABC"] * n, dtype=object)
    if part != 1:
        # completeness violations
        name[[3, 155, 311]] = None
        # min violations (negative) + max violations (> 1000)
        val[[7, 250]] = [-5.0 - part, -1.0]
        val[[380]] = 5000.0 + part
        # pattern violations (lowercase) and a null (null is NOT a
        # pattern violation — the mask requires a present value)
        code[[42, 199]] = ["xyz", "nope"]
        code[[60]] = None
    return {"id": ids, "val": val, "name": name, "code": code}


def _write_dataset(tmp_path, parts=3):
    data_dir = tmp_path / "dataset"
    data_dir.mkdir(exist_ok=True)
    arrays = {}
    for p in range(parts):
        cols = _partition_arrays(p)
        arrays[f"part-{p}.parquet"] = cols
        Table.from_pydict(dict(cols)).to_parquet(
            str(data_dir / f"part-{p}.parquet"), row_group_size=ROW_GROUP
        )
    return str(data_dir), arrays


def _checks():
    return (
        Check(CheckLevel.ERROR, "forensics e2e")
        .is_complete("name")
        .has_min("val", lambda v: v >= 0.0)
        .has_max("val", lambda v: v <= 1000.0)
        .satisfies("val < 100", "val bounded", lambda r: r >= 1.0)
        .has_pattern("code", r"^[A-Z]{3}$")
    )


def _run(data_dir, **kwargs):
    data = Table.scan_parquet_dataset(data_dir)
    builder = VerificationSuite.on_data(data).add_check(_checks())
    builder = builder.with_forensics()
    for key, value in kwargs.items():
        builder = getattr(builder, key)(*value)
    return builder.run()


def _mirror_violations(arrays, kind):
    """Independent numpy mirror: {(partition, row_group, row_in_group)}
    -> expected offending value(s), per forensics family."""
    out = {}
    for part_name, cols in arrays.items():
        val, name, code = cols["val"], cols["name"], cols["code"]
        if kind == "completeness":
            rows = [i for i, v in enumerate(name) if v is None]
            values = {i: {"name": None} for i in rows}
        elif kind == "minimum":
            rows = [i for i in range(len(val)) if not (val[i] >= 0.0)]
            values = {i: {"val": float(val[i])} for i in rows}
        elif kind == "maximum":
            rows = [i for i in range(len(val)) if not (val[i] <= 1000.0)]
            values = {i: {"val": float(val[i])} for i in rows}
        elif kind == "compliance":
            rows = [i for i in range(len(val)) if not (val[i] < 100.0)]
            values = {i: {"val": float(val[i])} for i in rows}
        elif kind == "pattern":
            rows = [
                i
                for i, c in enumerate(code)
                if c is not None and not (len(c) == 3 and c.isupper())
            ]
            values = {i: {"code": str(code[i])} for i in rows}
        else:  # pragma: no cover - test bug
            raise AssertionError(kind)
        for i in rows:
            out[(part_name, i // ROW_GROUP, i % ROW_GROUP)] = values[i]
    return out


def test_failure_samples_verify_against_numpy_mirror(tmp_path):
    data_dir, arrays = _write_dataset(tmp_path)
    result = _run(data_dir)
    assert result.status == CheckStatus.ERROR
    report = result.forensics()
    assert report is not None

    by_kind = {c.kind: c for c in report.constraints}
    # every family in the plan was classified capable
    assert set(by_kind) == {
        "completeness", "minimum", "maximum", "compliance", "pattern",
    }
    assert report.falloffs == []

    for kind, entry in by_kind.items():
        mirror = _mirror_violations(arrays, kind)
        assert entry.status == ("SUCCESS" if not mirror else "FAILURE")
        if not mirror:
            assert entry.samples == []
            continue
        # acceptance: every FAILURE capable constraint sampled >= 1 row
        assert entry.samples, f"{kind}: no sampled violating rows"
        assert entry.capture_errors == 0
        for sample in entry.samples:
            coord = (sample.partition, sample.row_group, sample.row_index)
            assert coord in mirror, f"{kind}: {coord} is not a violation"
            assert sample.values == mirror[coord], f"{kind}: wrong values"
            assert sample.fingerprint  # partition fingerprint attached
        # the ratio families count exact violations over the scan
        if kind in ("completeness", "compliance", "pattern"):
            assert entry.violations_seen == len(mirror)


def test_reservoir_is_deterministic_and_bounded(tmp_path):
    data_dir, _ = _write_dataset(tmp_path)

    def coords(result):
        return {
            c.kind: [
                (s.partition, s.row_group, s.row_index, repr(s.values))
                for s in c.samples
            ]
            for c in result.forensics().constraints
        }

    first = coords(_run(data_dir))
    second = coords(_run(data_dir))
    assert first == second

    # a tighter cap stays deterministic and bounded
    data = Table.scan_parquet_dataset(data_dir)
    tight = (
        VerificationSuite.on_data(data)
        .add_check(_checks())
        .with_forensics(True, 2)
        .run()
    )
    for entry in tight.forensics().constraints:
        assert len(entry.samples) <= 2


def test_forensics_off_by_default(tmp_path):
    data_dir, _ = _write_dataset(tmp_path, parts=1)
    data = Table.scan_parquet_dataset(data_dir)
    result = VerificationSuite.on_data(data).add_check(_checks()).run()
    assert result.forensics() is None


def test_env_knob_enables_forensics(tmp_path, monkeypatch):
    data_dir, _ = _write_dataset(tmp_path, parts=1)
    monkeypatch.setenv("DEEQU_TPU_FORENSICS", "1")
    data = Table.scan_parquet_dataset(data_dir)
    result = VerificationSuite.on_data(data).add_check(_checks()).run()
    assert result.forensics() is not None
    # explicit False wins over the env knob
    data = Table.scan_parquet_dataset(data_dir)
    result = (
        VerificationSuite.on_data(data)
        .add_check(_checks())
        .with_forensics(False)
        .run()
    )
    assert result.forensics() is None


def test_provenance_names_cached_vs_scanned_partitions(tmp_path):
    from deequ_tpu.repository.states import FileSystemStateRepository

    data_dir, _ = _write_dataset(tmp_path)
    repo = FileSystemStateRepository(str(tmp_path / "states"))

    def run():
        data = Table.scan_parquet_dataset(data_dir)
        return (
            VerificationSuite.on_data(data)
            .add_check(_checks())
            .with_forensics()
            .with_state_repository(repo, "forensics")
            .run()
        )

    cold = run().forensics()
    assert [p["mode"] for p in cold.provenance["partitions"]] == ["scan"] * 3
    assert cold.provenance["planSignature"]
    assert cold.provenance["rowGroupsScanned"] > 0

    warm = run().forensics()
    assert [p["mode"] for p in warm.provenance["partitions"]] == ["cache"] * 3
    assert warm.provenance["planSignature"] == cold.provenance["planSignature"]
    # cached partitions contribute provenance, not samples
    for entry in warm.constraints:
        assert entry.samples == []
    # same fingerprints either way, in the same partition order
    assert [p["fingerprint"] for p in warm.provenance["partitions"]] == [
        p["fingerprint"] for p in cold.provenance["partitions"]
    ]


def test_render_names_rows_partitions_and_plan(tmp_path):
    data_dir, _ = _write_dataset(tmp_path)
    report = _run(data_dir).forensics()
    text = report.render()
    assert "failure forensics" in text
    assert "part-0.parquet" in text
    assert "[FAILURE]" in text
    assert "partitions: 3 scanned, 0 merged from state cache (3 total)" in text
    # report rides render_report as the forensics section
    from deequ_tpu import observe

    with observe.tracing() as tracer:
        with observe.span("x", cat="plan"):
            pass
    full = observe.render_report(tracer, forensics=report)
    assert "failure forensics" in full


# -- audit-trail envelope ----------------------------------------------------


def _report():
    return ForensicsReport(
        constraints=[],
        falloffs=[{"constraint": "c", "reason": "r"}],
        provenance={"planSignature": "abc", "partitions": []},
    )


def test_envelope_round_trip():
    payload = _report().to_dict()
    assert decode_audit(encode_audit(payload)) == payload


def test_envelope_rejects_bit_flips():
    blob = bytearray(encode_audit(_report().to_dict()))
    for pos in (0, 5, len(blob) // 2, len(blob) - 1):
        flipped = bytearray(blob)
        flipped[pos] ^= 0x40
        with pytest.raises(AuditDecodeError):
            decode_audit(bytes(flipped))


def test_envelope_rejects_truncation():
    blob = encode_audit(_report().to_dict())
    for keep in (0, 3, 11, len(blob) // 2, len(blob) - 1):
        with pytest.raises(AuditDecodeError):
            decode_audit(blob[:keep])


def test_envelope_rejects_version_bump_with_valid_digest():
    import hashlib

    blob = encode_audit(_report().to_dict())
    body = bytearray(blob[:-32])
    struct.pack_into(">I", body, len(AUDIT_MAGIC), AUDIT_FORMAT_VERSION + 1)
    bumped = bytes(body) + hashlib.sha256(bytes(body)).digest()
    with pytest.raises(AuditDecodeError, match="format version"):
        decode_audit(bumped)


def test_audit_round_trips_through_fs_repository(tmp_path):
    data_dir, _ = _write_dataset(tmp_path)
    repo = FileSystemMetricsRepository(str(tmp_path / "metrics"))
    key = ResultKey(20260805, {"suite": "forensics"})
    result = _run(
        data_dir, use_repository=(repo,), save_or_append_result=(key,)
    )
    report = result.forensics()
    loaded = load_audit_trail(repo, key)
    assert loaded is not None
    assert loaded.to_dict() == report.to_dict()
    # the ordinary metrics for the run were saved alongside the trail
    context = repo.load_by_key(key)
    assert any(
        getattr(a, "name", None) != "ForensicsAudit"
        for a in context.metric_map
    )


def _save_corrupted(repo, key, mutate):
    """Persist a run context whose audit payload is `mutate`d."""
    report = _report()
    record, _ = audit_entry_for(report)
    blob = bytearray(base64.b64decode(record.payload))
    payload = mutate(blob)
    bad = AuditRecord(base64.b64encode(bytes(payload)).decode("ascii"))
    from deequ_tpu.runners.context import AnalyzerContext

    repo.save(key, AnalyzerContext({bad: bad.to_metric()}))


def test_unusable_audit_entries_warn_dq317_and_degrade(tmp_path):
    repo = FileSystemMetricsRepository(str(tmp_path / "metrics"))
    cases = {
        "flip": lambda b: bytes(b[:40]) + bytes([b[40] ^ 0x01]) + bytes(b[41:]),
        "truncate": lambda b: bytes(b[: len(b) // 2]),
        "empty": lambda b: b"",
    }
    for i, (label, mutate) in enumerate(cases.items()):
        key = ResultKey(i, {"case": label})
        _save_corrupted(repo, key, mutate)
        with pytest.warns(RuntimeWarning, match="DQ317"):
            assert load_audit_trail(repo, key) is None


def test_missing_trail_is_none_without_warning(tmp_path):
    repo = FileSystemMetricsRepository(str(tmp_path / "metrics"))
    assert load_audit_trail(repo, ResultKey(1, {})) is None


def test_audit_trail_under_concurrent_writers(tmp_path):
    """Writer threads racing on one FileSystemMetricsRepository file,
    with concurrent readers. The repository's whole-history
    read-modify-write can LOSE a racing entry (last atomic publish
    wins) but must never TEAR one: every trail that is present loads
    back intact under its own key — the envelope digest guarantees a
    decoded trail is exactly what its writer persisted — and readers
    never see a torn file or a wrong-key payload."""
    from deequ_tpu.runners.context import AnalyzerContext

    repo = FileSystemMetricsRepository(str(tmp_path / "metrics"))
    n = 16
    barrier = threading.Barrier(n + 1)
    errors = []
    stop = threading.Event()

    def write(i):
        report = ForensicsReport(
            constraints=[],
            falloffs=[],
            provenance={"planSignature": f"sig-{i}", "partitions": []},
        )
        record, metric = audit_entry_for(report)
        barrier.wait()
        try:
            repo.save(
                ResultKey(i, {"w": str(i)}),
                AnalyzerContext({record: metric}),
            )
        except Exception as e:  # noqa: BLE001 - collected for the assert
            errors.append(e)

    def read():
        barrier.wait()
        while not stop.is_set():
            for i in range(n):
                try:
                    loaded = load_audit_trail(repo, ResultKey(i, {"w": str(i)}))
                except Exception as e:  # noqa: BLE001
                    errors.append(e)
                    return
                if loaded is not None:
                    sig = loaded.provenance.get("planSignature")
                    if sig != f"sig-{i}":
                        errors.append(AssertionError(f"key {i} read {sig}"))
                        return

    threads = [threading.Thread(target=write, args=(i,)) for i in range(n)]
    reader = threading.Thread(target=read)
    for t in threads:
        t.start()
    reader.start()
    for t in threads:
        t.join()
    stop.set()
    reader.join()
    assert errors == []
    survived = 0
    for i in range(n):
        loaded = load_audit_trail(repo, ResultKey(i, {"w": str(i)}))
        if loaded is not None:
            assert loaded.provenance["planSignature"] == f"sig-{i}"
            survived += 1
    # the last publish always lands whole
    assert survived >= 1


# -- EXPLAIN prediction ------------------------------------------------------


def test_explain_predicts_capability_and_dq316_falloffs(tmp_path):
    data_dir, _ = _write_dataset(tmp_path, parts=1)
    data = Table.scan_parquet_dataset(data_dir)
    check = (
        Check(CheckLevel.ERROR, "predict")
        .is_complete("name")
        .is_unique("id")  # uniqueness is grouped: no per-row identity
    )
    explained = VerificationSuite.on_data(data).add_check(check).explain()
    assert any(code == "DQ316" for code in _diag_codes(explained))
    assert len(explained.forensics_capable) == 1
    assert "Completeness" in explained.forensics_capable[0][0]
    assert len(explained.forensics_falloffs) == 1
    text = str(explained)
    assert "failure forensics" in text
    assert "DQ316" in text


def _diag_codes(explained):
    return [d.code for d in explained.diagnostics]

"""Bounded-memory high-cardinality grouping: the hash-partitioned disk
spill behind the frequency family (the engine-level MEMORY_AND_DISK
escape hatch, reference: runners/AnalysisRunner.scala:75,479-483).

Every test forces a tiny in-memory group cap so the spill machinery is
exercised at test scale, and asserts metric equality against the plain
in-memory path — the spill must be an execution detail, never a
semantics change."""

from __future__ import annotations

import os

import numpy as np
import pytest

from deequ_tpu.analyzers import (
    CountDistinct,
    Distinctness,
    Entropy,
    Histogram,
    MutualInformation,
    Uniqueness,
    UniqueValueRatio,
)
from deequ_tpu.analyzers.freq_spill import GroupCountAccumulator, SpilledFrequencies
from deequ_tpu.analyzers.frequency import FrequenciesAndNumRows, compute_frequencies
from deequ_tpu.data.source import ParquetSource
from deequ_tpu.data.table import Table
from deequ_tpu.runners.analysis_runner import AnalysisRunner

N_ROWS = 120_000


@pytest.fixture(autouse=True)
def tiny_group_cap(monkeypatch):
    # spill after 10k in-RAM groups: the ~unique id column (120k groups)
    # must go to disk
    monkeypatch.setenv("DEEQU_TPU_MAX_GROUPS_IN_MEMORY", "10000")


@pytest.fixture(scope="module")
def high_card_parquet(tmp_path_factory):
    import pyarrow as pa
    import pyarrow.parquet as pq

    rng = np.random.default_rng(11)
    ids = np.array([f"id_{i:08d}" for i in range(N_ROWS)], dtype=object)
    rng.shuffle(ids)
    ids[::1000] = "dup_key"  # a few repeats so uniqueness < 1
    cat = np.array(["x", "y", "z"], dtype=object)[rng.integers(0, 3, N_ROWS)]
    path = tmp_path_factory.mktemp("spill") / "high_card.parquet"
    pq.write_table(
        pa.table({"id": pa.array(list(ids)), "cat": pa.array(list(cat))}),
        str(path),
        row_group_size=20_000,
    )
    return str(path)


GROUPING = [
    Uniqueness(("id",)),
    Distinctness(("id",)),
    UniqueValueRatio(("id",)),
    CountDistinct(("id",)),
    Entropy("id"),
]


def test_streaming_high_card_spills_and_matches_in_memory(high_card_parquet):
    source = ParquetSource(high_card_parquet, batch_rows=1 << 14)
    ctx_stream = AnalysisRunner.do_analysis_run(source, GROUPING, engine="single")
    ctx_mem = AnalysisRunner.do_analysis_run(
        Table.from_parquet(high_card_parquet), GROUPING, engine="single"
    )
    for analyzer in GROUPING:
        got = ctx_stream.metric_map[analyzer].value.get()
        want = ctx_mem.metric_map[analyzer].value.get()
        assert got == pytest.approx(want, rel=1e-12), analyzer


def test_streaming_high_card_mesh_engine(high_card_parquet):
    source = ParquetSource(high_card_parquet, batch_rows=1 << 14)
    from deequ_tpu.parallel.distributed import data_mesh

    ctx = AnalysisRunner.do_analysis_run(
        source, GROUPING, engine="distributed", mesh=data_mesh()
    )
    ctx_mem = AnalysisRunner.do_analysis_run(
        Table.from_parquet(high_card_parquet), GROUPING, engine="single"
    )
    for analyzer in GROUPING:
        assert ctx.metric_map[analyzer].value.get() == pytest.approx(
            ctx_mem.metric_map[analyzer].value.get(), rel=1e-12
        ), analyzer


def test_spilled_state_is_actually_used(high_card_parquet):
    source = ParquetSource(high_card_parquet, batch_rows=1 << 14)
    state = compute_frequencies(source, ["id"])
    assert isinstance(state, SpilledFrequencies)
    assert state.num_rows == N_ROWS
    # exact group count survives partition compaction: dup_key overwrote
    # every 1000th id (120 ids gone, 1 new key)
    assert state.num_groups == N_ROWS - N_ROWS // 1000 + 1


def test_spill_accumulator_peak_memory_stays_bounded(high_card_parquet):
    """The fold's resident group count never exceeds cap + one batch:
    proxy assertion via the accumulator internals (the RSS-level
    evidence lives in the 100M bench artifact)."""
    acc = GroupCountAccumulator(["id"], max_groups_in_memory=10_000)
    source = ParquetSource(high_card_parquet, batch_rows=1 << 14)
    max_resident = 0
    for batch in source.batches(1 << 14):
        partial = compute_frequencies(batch, ["id"])
        acc.add(partial)
        if acc._buffer is not None:
            max_resident = max(max_resident, acc._buffer.num_groups)
    state = acc.finalize()
    assert isinstance(state, SpilledFrequencies)
    # once spilled, nothing accumulates in RAM; before, bounded by
    # cap + one batch of new groups
    assert max_resident <= 10_000 + (1 << 14)


def test_histogram_over_spilled_state(high_card_parquet):
    source = ParquetSource(high_card_parquet, batch_rows=1 << 14)
    analyzer = Histogram("id", max_detail_bins=5)
    ctx = AnalysisRunner.do_analysis_run(source, [analyzer], engine="single")
    dist = ctx.metric_map[analyzer].value.get()
    # top bin must be the repeated key, with its exact count
    assert dist.values["dup_key"].absolute == N_ROWS // 1000
    assert dist.number_of_bins == N_ROWS - N_ROWS // 1000 + 1
    assert len(dist.values) == 5


def test_histogram_streaming_state_actually_spills(high_card_parquet):
    source = ParquetSource(high_card_parquet, batch_rows=1 << 14)
    state = Histogram("id").compute_state_from(source)
    assert isinstance(state, SpilledFrequencies)
    assert state.num_rows == N_ROWS


def test_spill_writer_cleans_up_on_abandonment():
    """A fold that dies after spilling must not leak the spill dir."""
    import gc
    import os

    from deequ_tpu.analyzers.freq_spill import _SpillWriter

    writer = _SpillWriter(["c"])
    writer.append(
        FrequenciesAndNumRows(
            ["c"],
            [np.array(["a", "b"], dtype=object)],
            np.array([1, 2], dtype=np.int64),
            2,
        )
    )
    directory = writer.directory
    assert os.path.isdir(directory)
    del writer
    gc.collect()
    assert not os.path.exists(directory)


def test_mutual_information_over_spilled_state(high_card_parquet):
    mi = MutualInformation("id", "cat")
    source = ParquetSource(high_card_parquet, batch_rows=1 << 14)
    ctx_stream = AnalysisRunner.do_analysis_run(source, [mi], engine="single")
    ctx_mem = AnalysisRunner.do_analysis_run(
        Table.from_parquet(high_card_parquet), [mi], engine="single"
    )
    assert ctx_stream.metric_map[mi].value.get() == pytest.approx(
        ctx_mem.metric_map[mi].value.get(), rel=1e-9
    )


def test_histogram_top_n_tie_break_is_deterministic():
    """(count desc, key asc): with max_detail_bins below the number of
    tied groups, the selected detail set must be identical in-memory and
    streamed/spilled (the reference's rdd.top leaves this partition-
    dependent; we define it)."""
    from deequ_tpu.analyzers.frequency import top_n_order

    keys = np.array(["b", "d", "a", "c", "e"], dtype=object)
    counts = np.array([2, 1, 2, 2, 1], dtype=np.int64)
    order = top_n_order(keys, counts, 4)
    assert list(keys[order]) == ["a", "b", "c", "d"]  # 2s by key, then 1s

    # cross-path: all-tied counts, cap smaller than the group count
    import pyarrow as pa
    import pyarrow.parquet as pq
    import tempfile

    n = 60_000  # all-unique -> every count ties at 1
    ids = np.array([f"k{i:06d}" for i in range(n)], dtype=object)
    with tempfile.TemporaryDirectory() as tmp:
        path = f"{tmp}/ties.parquet"
        pq.write_table(
            pa.table({"id": pa.array(list(ids))}), path, row_group_size=10_000
        )
        analyzer = Histogram("id", max_detail_bins=7)
        mem = AnalysisRunner.do_analysis_run(
            Table.from_parquet(path), [analyzer], engine="single"
        ).metric_map[analyzer].value.get()
        stream = AnalysisRunner.do_analysis_run(
            ParquetSource(path, batch_rows=1 << 13), [analyzer], engine="single"
        ).metric_map[analyzer].value.get()
    assert list(mem.values) == list(stream.values) == [
        f"k{i:06d}" for i in range(7)
    ]


def test_multi_column_spill_matches_in_memory(high_card_parquet):
    """Spill routing hashes ALL key columns; a (near-unique, low-card)
    pair must produce the same metrics as the in-memory path."""
    grouping = [
        Uniqueness(("id", "cat")),
        CountDistinct(("id", "cat")),
        UniqueValueRatio(("cat", "id")),  # declared order differs from sorted
    ]
    source = ParquetSource(high_card_parquet, batch_rows=1 << 14)
    ctx_stream = AnalysisRunner.do_analysis_run(source, grouping, engine="single")
    ctx_mem = AnalysisRunner.do_analysis_run(
        Table.from_parquet(high_card_parquet), grouping, engine="single"
    )
    # the joint key is ~unique: the state must actually have spilled
    state = compute_frequencies(
        ParquetSource(high_card_parquet, batch_rows=1 << 14), ["cat", "id"]
    )
    assert isinstance(state, SpilledFrequencies)
    for analyzer in grouping:
        assert ctx_stream.metric_map[analyzer].value.get() == pytest.approx(
            ctx_mem.metric_map[analyzer].value.get(), rel=1e-12
        ), analyzer


def test_spilled_merge_with_in_memory_partial():
    rng = np.random.default_rng(5)
    keys_a = np.array([f"k{i}" for i in range(30_000)], dtype=object)
    keys_b = np.array([f"k{i}" for i in range(15_000, 45_000)], dtype=object)

    acc = GroupCountAccumulator(["c"], max_groups_in_memory=5_000)
    acc.add(
        FrequenciesAndNumRows(
            ["c"], [keys_a], np.ones(len(keys_a), dtype=np.int64), len(keys_a)
        )
    )
    acc.add(
        FrequenciesAndNumRows(
            ["c"], [keys_b], np.ones(len(keys_b), dtype=np.int64), len(keys_b)
        )
    )
    spilled = acc.finalize()
    assert isinstance(spilled, SpilledFrequencies)
    assert spilled.num_groups == 45_000
    assert spilled.num_rows == 60_000

    extra = FrequenciesAndNumRows(
        ["c"],
        [np.array(["k0", "new"], dtype=object)],
        np.array([7, 3], dtype=np.int64),
        10,
    )
    merged = spilled.merge(extra)
    assert merged.num_groups == 45_001
    assert merged.num_rows == 60_010
    # merge must not mutate its operands (num_rows is the metric
    # denominator downstream)
    assert extra.num_rows == 10
    assert spilled.num_rows == 60_000
    # commutes through the in-memory side too
    merged2 = extra.merge(spilled)
    assert merged2.num_groups == 45_001
    assert merged2.num_rows == 60_010
    assert extra.num_rows == 10

    # the overlapping key's count actually summed (k0: 1 from the first
    # partial + 7 from the merged extra; keys_b starts at k15000)
    total = 0
    for part in merged.partitions():
        for key, count in zip(part.key_columns[0], part.counts):
            if key == "k0":
                total += int(count)
    assert total == 1 + 7


def test_spilled_state_serializes_for_multihost_envelope(high_card_parquet):
    """The DCN state envelope must handle spilled frequencies: serialize
    streams partitions, deserialize re-spills on the receiving host."""
    from deequ_tpu.analyzers.state_provider import (
        deserialize_state,
        serialize_state,
    )

    source = ParquetSource(high_card_parquet, batch_rows=1 << 14)
    state = compute_frequencies(source, ["id"])
    assert isinstance(state, SpilledFrequencies)
    analyzer = Uniqueness(("id",))
    blob = serialize_state(analyzer, state)
    restored = deserialize_state(analyzer, blob)
    assert restored.num_rows == state.num_rows
    assert restored.num_groups == state.num_groups
    # metric computed from the round-tripped state matches
    a = analyzer.compute_metric_from(state).value.get()
    b = analyzer.compute_metric_from(restored).value.get()
    assert a == pytest.approx(b, rel=0, abs=0)


def test_spilled_state_persists_via_state_provider(tmp_path, high_card_parquet):
    from deequ_tpu.analyzers.state_provider import FileSystemStateProvider

    source = ParquetSource(high_card_parquet, batch_rows=1 << 14)
    state = compute_frequencies(source, ["id"])
    assert isinstance(state, SpilledFrequencies)
    provider = FileSystemStateProvider(str(tmp_path))
    analyzer = Uniqueness(("id",))
    provider.persist(analyzer, state)
    loaded = provider.load(analyzer)
    assert loaded.num_rows == state.num_rows
    assert loaded.num_groups == state.num_groups

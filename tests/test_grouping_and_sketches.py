"""Grouping-analyzer + sketch tests (mirrors reference AnalyzerTests
uniqueness/entropy/MI sections, NullHandlingTests frequency cases, and the
approximate analyzer error-bound tests)."""

import numpy as np
import pytest

from deequ_tpu.analyzers import (
    ApproxCountDistinct,
    ApproxQuantile,
    ApproxQuantiles,
    CountDistinct,
    Distinctness,
    Entropy,
    Histogram,
    MutualInformation,
    UniqueValueRatio,
    Uniqueness,
    compute_frequencies,
)
from deequ_tpu.core.exceptions import (
    EmptyStateException,
    IllegalAnalyzerParameterException,
    NumberOfSpecifiedColumnsException,
)
from deequ_tpu.data.table import Table
from deequ_tpu.ops import runtime
from deequ_tpu.runners import AnalysisRunner

from fixtures import (
    get_df_full,
    get_df_missing,
    get_df_with_conditionally_informative_columns,
    get_df_with_conditionally_uninformative_columns,
    get_df_with_distinct_values,
    get_df_with_unique_columns,
    get_full_nulls,
)


def value_of(metric):
    assert metric.value.is_success, f"expected success, got {metric.value}"
    return metric.value.get()


def failure_of(metric):
    assert metric.value.is_failure, f"expected failure, got {metric.value}"
    return metric.value.exception


class TestUniquenessFamily:
    def test_uniqueness(self):
        df = get_df_with_unique_columns()
        assert value_of(Uniqueness("unique").calculate(df)) == 1.0
        assert value_of(Uniqueness("uniqueWithNulls").calculate(df)) == pytest.approx(5 / 6)
        assert value_of(Uniqueness("nonUnique").calculate(df)) == pytest.approx(3 / 6)

    def test_uniqueness_multi_column(self):
        df = get_df_full()
        # (a,c) x3? fixture: att1=[a,a,a,b], att2=[c,c,c,d] -> groups (a,c):3,(b,d):1
        assert value_of(Uniqueness(["att1", "att2"]).calculate(df)) == pytest.approx(1 / 4)

    def test_distinctness(self):
        df = get_df_with_distinct_values()
        assert value_of(Distinctness(["att1"]).calculate(df)) == pytest.approx(3 / 6)
        assert value_of(Distinctness(["att2"]).calculate(df)) == pytest.approx(2 / 6)

    def test_unique_value_ratio(self):
        df = get_df_with_unique_columns()
        # nonUnique groups: {0:3, 5:1, 6:1, 7:1} -> 3 unique / 4 distinct
        assert value_of(UniqueValueRatio(["nonUnique"]).calculate(df)) == pytest.approx(3 / 4)

    def test_count_distinct(self):
        df = get_df_with_unique_columns()
        assert value_of(CountDistinct("uniqueWithNulls").calculate(df)) == 5.0

    def test_fully_null_column(self):
        df = get_full_nulls()
        assert value_of(CountDistinct("att1").calculate(df)) == 0.0
        err = failure_of(Uniqueness("att1").calculate(df))
        assert isinstance(err, EmptyStateException)
        err = failure_of(Entropy("att1").calculate(df))
        assert isinstance(err, EmptyStateException)


class TestEntropyAndMI:
    def test_entropy(self):
        df = get_df_full()
        # att1: a:3, b:1 over 4 rows
        expected = -(3 / 4) * np.log(3 / 4) - (1 / 4) * np.log(1 / 4)
        assert value_of(Entropy("att1").calculate(df)) == pytest.approx(expected)

    def test_mutual_information_uninformative(self):
        df = get_df_with_conditionally_uninformative_columns()
        assert value_of(MutualInformation("att1", "att2").calculate(df)) == pytest.approx(0.0)

    def test_mutual_information_informative(self):
        df = get_df_with_conditionally_informative_columns()
        # deterministic 1:1 mapping: MI == entropy of att1 (ln 3)
        assert value_of(MutualInformation("att1", "att2").calculate(df)) == pytest.approx(
            np.log(3)
        )

    def test_entropy_equals_mi_with_self(self):
        df = get_df_full()
        mi = value_of(MutualInformation("att1", "att1").calculate(df))
        entropy = value_of(Entropy("att1").calculate(df))
        assert mi == pytest.approx(entropy)

    def test_mi_requires_two_columns(self):
        df = get_df_full()
        err = failure_of(MutualInformation(["att1", "att2", "item"]).calculate(df))
        assert isinstance(err, NumberOfSpecifiedColumnsException)


class TestFrequencyState:
    def test_state_merge_equals_whole(self):
        df = get_df_missing()
        left, right = df.slice(0, 6), df.slice(6, 12)
        whole = compute_frequencies(df, ["att1"])
        merged = compute_frequencies(left, ["att1"]).merge(
            compute_frequencies(right, ["att1"])
        )
        assert merged == whole

    def test_null_rows_excluded_but_counted(self):
        df = get_full_nulls()
        state = compute_frequencies(df, ["att1"])
        assert state.num_rows == 3
        assert state.num_groups == 0


class TestHistogram:
    def test_histogram_with_nulls(self):
        df = get_df_missing()
        dist = value_of(Histogram("att1").calculate(df))
        assert dist.number_of_bins == 3  # a, b, NullValue
        assert dist["a"].absolute == 4
        assert dist["b"].absolute == 2
        assert dist["NullValue"].absolute == 6
        assert dist["a"].ratio == pytest.approx(4 / 12)

    def test_histogram_numeric_column(self):
        df = Table.from_pydict({"x": [1, 1, 2, None]})
        dist = value_of(Histogram("x").calculate(df))
        assert dist["1"].absolute == 2
        assert dist["NullValue"].absolute == 1

    def test_max_bins_cap(self):
        df = get_df_full()
        err = failure_of(Histogram("att1", max_detail_bins=1001).calculate(df))
        assert isinstance(err, IllegalAnalyzerParameterException)

    def test_detail_bins_limited_but_bincount_full(self):
        df = Table.from_pydict({"x": list("abcdef")})
        dist = value_of(Histogram("x", max_detail_bins=3).calculate(df))
        assert dist.number_of_bins == 6
        assert len(dist.values) == 3


class TestApproxCountDistinct:
    def test_small_exact(self):
        df = get_df_with_unique_columns()
        assert value_of(ApproxCountDistinct("uniqueWithNulls").calculate(df)) == 5.0

    def test_with_filter(self):
        df = get_df_with_unique_columns()
        m = ApproxCountDistinct("uniqueWithNulls", where="unique < 4").calculate(df)
        assert value_of(m) == 2.0

    def test_fully_null_is_zero(self):
        df = get_full_nulls()
        assert value_of(ApproxCountDistinct("att1").calculate(df)) == 0.0

    def test_error_bound_large(self):
        rng = np.random.default_rng(3)
        n = 50_000
        values = rng.integers(0, 20_000, n)
        df = Table.from_numpy({"x": values})
        exact = len(np.unique(values))
        est = value_of(ApproxCountDistinct("x").calculate(df))
        assert abs(est - exact) / exact < 0.12  # ~2.4 sigma at rsd 0.05

    def test_state_merge(self):
        df = Table.from_pydict({"x": [str(i) for i in range(100)]})
        left, right = df.slice(0, 50), df.slice(50, 100)
        sa = ApproxCountDistinct("x").compute_state_from(left)
        sb = ApproxCountDistinct("x").compute_state_from(right)
        merged = sa.merge(sb)
        direct = ApproxCountDistinct("x").compute_state_from(df)
        assert np.array_equal(merged.registers, direct.registers)


class TestApproxQuantile:
    def test_median_small(self):
        df = Table.from_pydict({"x": [0, 0, 5, 10, 12]})
        assert value_of(ApproxQuantile("x", 0.5).calculate(df)) == 5.0

    def test_quantiles_within_bounds(self):
        df = Table.from_numpy({"x": np.arange(-1000, 1000).astype(np.float64)})
        assert -20 < value_of(ApproxQuantile("x", 0.5).calculate(df)) < 20
        assert -520 < value_of(ApproxQuantile("x", 0.25).calculate(df)) < -480
        assert 480 < value_of(ApproxQuantile("x", 0.75).calculate(df)) < 520

    def test_param_checks(self):
        df = Table.from_pydict({"x": [1, 2, 3]})
        err = failure_of(ApproxQuantile("x", 0.5, relative_error=1.1).calculate(df))
        assert isinstance(err, IllegalAnalyzerParameterException)
        assert str(err) == (
            "Relative error parameter must be in the closed interval [0, 1]. "
            "Currently, the value is: 1.1!"
        )
        err = failure_of(ApproxQuantile("x", -0.2).calculate(df))
        assert "Quantile parameter" in str(err)

    def test_fully_null(self):
        df = Table.from_numpy({"x": np.array([np.nan, np.nan])})
        err = failure_of(ApproxQuantile("x", 0.5).calculate(df))
        assert isinstance(err, EmptyStateException)

    def test_approx_quantiles_keyed(self):
        df = Table.from_numpy({"x": np.arange(100).astype(np.float64)})
        metric = ApproxQuantiles("x", [0.25, 0.5, 0.75]).calculate(df)
        values = metric.value.get()
        assert set(values.keys()) == {"0.25", "0.5", "0.75"}
        assert values["0.5"] == pytest.approx(49.5, abs=2)
        flat = metric.flatten()
        assert {m.name for m in flat} == {
            "ApproxQuantiles-0.25",
            "ApproxQuantiles-0.5",
            "ApproxQuantiles-0.75",
        }

    def test_merge_parity(self):
        rng = np.random.default_rng(5)
        values = rng.normal(size=10_000)
        df = Table.from_numpy({"x": values})
        a = ApproxQuantile("x", 0.5)
        s1 = a.compute_state_from(df.slice(0, 5000))
        s2 = a.compute_state_from(df.slice(5000, 10000))
        merged_median = s1.merge(s2).digest.quantile(0.5)
        exact = float(np.quantile(values, 0.5))
        assert abs(merged_median - exact) < 0.05


class TestGroupingJobCounts:
    def test_shared_frequency_pass(self):
        df = get_df_with_unique_columns()
        analyzers = [
            Uniqueness("nonUnique"),
            UniqueValueRatio(["nonUnique"]),
            Distinctness(["nonUnique"]),
            Entropy("nonUnique"),
        ]
        # separate: 2 jobs each = 8
        with runtime.monitored() as separate:
            results = [a.calculate(df) for a in analyzers]
        assert separate.jobs == 8

        # fused: 1 group-by + 1 shared aggregation = 2 jobs
        with runtime.monitored() as fused:
            context = AnalysisRunner.on_data(df).add_analyzers(analyzers).run()
        assert fused.jobs == 2

        for analyzer, sep in zip(analyzers, results):
            assert context.metric(analyzer).value.get() == sep.value.get()

    def test_mixed_scan_and_grouping(self):
        from deequ_tpu.analyzers import Completeness, Size

        df = get_df_with_unique_columns()
        with runtime.monitored() as stats:
            context = (
                AnalysisRunner.on_data(df)
                .add_analyzers(
                    [
                        Size(),
                        Completeness("unique"),
                        Uniqueness("nonUnique"),
                        Distinctness(["nonUnique"]),
                        Uniqueness(["nonUnique", "unique"]),
                    ]
                )
                .run()
            )
        # 1 scan + (2 jobs × 2 grouping sets) = 5
        assert stats.jobs == 5
        assert all(m.value.is_success for m in context.all_metrics())

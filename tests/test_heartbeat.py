"""Live scan heartbeat (ISSUE 6 tentpole).

A streamed scan with `DEEQU_TPU_HEARTBEAT_S` set must emit periodic
progress snapshots — completed/predicted batches, instantaneous rows/s,
the pipeline bottleneck, a converging ETA — plus one final `done`
snapshot, via registered callbacks and/or a JSONL sink. The disabled
path must never construct a `ScanProgress`, never spawn the timer
thread, stay within the repo's <2% overhead budget (bounded
analytically, like test_observe_overhead.py), and produce bit-identical
metrics (differential test).
"""

from __future__ import annotations

import json
import threading
import time

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from deequ_tpu.analyzers import Completeness, Mean, Size, StandardDeviation
from deequ_tpu.data.table import Table
from deequ_tpu.observe import heartbeat
from deequ_tpu.runners.analysis_runner import AnalysisRunner

N_ROWS = 100_000
BATCH_ROWS = 10_000
N_BATCHES = N_ROWS // BATCH_ROWS

ANALYZERS = [Size(), Completeness("x"), Mean("x"), StandardDeviation("x")]


@pytest.fixture(scope="module")
def parquet_path(tmp_path_factory):
    rng = np.random.default_rng(11)
    x = rng.normal(3.0, 1.5, N_ROWS)
    x[rng.random(N_ROWS) < 0.02] = np.nan
    table = pa.table({"x": x, "qty": rng.integers(0, 99, N_ROWS)})
    path = str(tmp_path_factory.mktemp("hb") / "data.parquet")
    pq.write_table(table, path, row_group_size=BATCH_ROWS)
    return path


def _scan(path):
    source = Table.scan_parquet(path, batch_rows=BATCH_ROWS)
    return AnalysisRunner.on_data(source).add_analyzers(ANALYZERS).run()


class TestHeartbeatOnStreamedScan:
    def test_emits_converging_snapshots(self, parquet_path, monkeypatch):
        monkeypatch.setenv("DEEQU_TPU_HEARTBEAT_S", "0.02")
        # stall decode 10ms/row-group so the scan outlives a few beats
        monkeypatch.setenv("DEEQU_TPU_SOURCE_STALL_MS", "10")
        monkeypatch.setenv("DEEQU_TPU_PIPELINE", "1")
        snaps = []
        cb = snaps.append
        heartbeat.register_callback(cb)
        try:
            _scan(parquet_path)
        finally:
            heartbeat.unregister_callback(cb)

        assert len(snaps) >= 2, "expected periodic + final snapshots"
        assert any(not s["done"] for s in snaps), "no periodic snapshot fired"
        final = snaps[-1]
        assert final["done"] is True
        assert final["name"] == "fused_scan"
        assert final["rows"] == N_ROWS
        assert final["batches"] == N_BATCHES
        assert final["predicted_batches"] == N_BATCHES
        assert final["total_rows"] == N_ROWS
        assert final["progress"] == 1.0
        assert final["eta_s"] == 0
        assert final["avg_rows_per_s"] > 0

        # ETA converges: once estimable it must end at (or below) where
        # it started, terminating in the final 0
        etas = [s["eta_s"] for s in snaps if "eta_s" in s]
        assert etas, "no snapshot carried an ETA"
        assert etas[-1] <= etas[0] + 1e-9
        assert etas[-1] == 0

        # pipelined scan attributes stage busy-time: the bottleneck is
        # one of the stream stages (decode stalled -> likely decode);
        # "read" is the native reader's fetch-slot bucket (ISSUE 11)
        assert final.get("bottleneck") in {"read", "decode", "prep", "fold"}
        assert set(final.get("occupancy", {})) <= {
            "read",
            "decode",
            "prep",
            "fold",
        }

    def test_jsonl_sink_from_env(self, parquet_path, tmp_path, monkeypatch):
        out = str(tmp_path / "beats.jsonl")
        monkeypatch.setenv("DEEQU_TPU_HEARTBEAT_S", "0.02")
        monkeypatch.setenv("DEEQU_TPU_HEARTBEAT_OUT", out)
        monkeypatch.setenv("DEEQU_TPU_SOURCE_STALL_MS", "10")
        _scan(parquet_path)
        with open(out, encoding="utf-8") as fh:
            lines = [json.loads(line) for line in fh if line.strip()]
        assert len(lines) >= 1
        assert lines[-1]["done"] is True
        assert lines[-1]["rows"] == N_ROWS
        for snap in lines:
            assert {"ts", "name", "rows", "batches", "wall_s"} <= set(snap)


class TestHeartbeatDisabledPath:
    def test_no_scanprogress_and_no_thread_when_off(self, parquet_path, monkeypatch):
        monkeypatch.delenv("DEEQU_TPU_HEARTBEAT_S", raising=False)
        constructed = []

        class _Boom(heartbeat.ScanProgress):
            def __init__(self, *a, **k):
                constructed.append(1)
                super().__init__(*a, **k)

        monkeypatch.setattr(heartbeat, "ScanProgress", _Boom)
        _scan(parquet_path)
        assert constructed == []
        assert not any(
            t.name == heartbeat.THREAD_NAME for t in threading.enumerate()
        )

    def test_disabled_metrics_bit_identical(self, parquet_path, tmp_path, monkeypatch):
        monkeypatch.delenv("DEEQU_TPU_HEARTBEAT_S", raising=False)
        baseline = _scan(parquet_path).success_metrics_as_rows()

        monkeypatch.setenv("DEEQU_TPU_HEARTBEAT_S", "0.01")
        monkeypatch.setenv("DEEQU_TPU_HEARTBEAT_OUT", str(tmp_path / "hb.jsonl"))
        with_hb = _scan(parquet_path).success_metrics_as_rows()

        assert baseline == with_hb  # exact equality, not approx

    def test_noop_overhead_under_two_percent(self, parquet_path, monkeypatch):
        """Analytic overhead bound, mirroring test_observe_overhead.py:
        probes_per_run x measured no-op probe cost < 2% of scan wall."""
        monkeypatch.delenv("DEEQU_TPU_HEARTBEAT_S", raising=False)
        monkeypatch.delenv("DEEQU_TPU_SOURCE_STALL_MS", raising=False)
        _scan(parquet_path)  # warm up compiles

        wall = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            _scan(parquet_path)
            wall = min(wall, time.perf_counter() - t0)

        noop = heartbeat.NOOP_PROGRESS
        calls = 100_000
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            for _ in range(calls):
                with noop.timed("stage"):
                    pass
                noop.advance(1)
            best = min(best, time.perf_counter() - t0)
        probe_cost = best / calls

        # per batch: decode + stage timers (pipeline stage thread), fold
        # timer + advance (consumer). x2 margin for start()/finish().
        probes_per_run = 8 * N_BATCHES
        overhead = probes_per_run * probe_cost
        assert overhead < 0.02 * wall, (
            f"no-op heartbeat overhead {overhead * 1e6:.1f}us exceeds 2% "
            f"of scan wall {wall * 1e3:.1f}ms"
        )


class TestHeartbeatUnit:
    def test_env_interval_parsing(self, monkeypatch):
        cases = [
            ("", 0.0), ("0", 0.0), ("off", 0.0), ("no", 0.0),
            ("false", 0.0), ("junk", 0.0), ("-3", 0.0), ("0.5", 0.5),
            (" 2 ", 2.0),
        ]
        for raw, expected in cases:
            monkeypatch.setenv(heartbeat.ENV_KNOB, raw)
            assert heartbeat.env_interval_s() == expected, raw
        monkeypatch.delenv(heartbeat.ENV_KNOB)
        assert heartbeat.env_interval_s() == 0.0

    def test_start_returns_falsy_noop_when_disabled(self, monkeypatch):
        monkeypatch.delenv(heartbeat.ENV_KNOB, raising=False)
        progress = heartbeat.start()
        assert progress is heartbeat.NOOP_PROGRESS
        assert not progress
        # every hook is inert and snapshot-free
        progress.advance(10)
        with progress.timed("x"):
            pass
        assert progress.snapshot() is None
        progress.finish()

    def test_periodic_jsonl_snapshots_with_eta(self, tmp_path):
        out = str(tmp_path / "unit.jsonl")
        progress = heartbeat.start(
            0.01, total_rows=1000, predicted_batches=4, out_path=out
        )
        assert isinstance(progress, heartbeat.ScanProgress)
        try:
            for _ in range(4):
                progress.advance(250)
                time.sleep(0.02)
        finally:
            progress.finish()
        with open(out, encoding="utf-8") as fh:
            snaps = [json.loads(line) for line in fh if line.strip()]
        assert len(snaps) >= 2
        assert snaps[-1]["done"] is True
        assert snaps[-1]["progress"] == 1.0
        assert snaps[-1]["eta_s"] == 0
        assert all(s["predicted_batches"] == 4 for s in snaps)
        # monotone non-decreasing row counts across emissions
        rows = [s["rows"] for s in snaps]
        assert rows == sorted(rows)

    def test_scan_heartbeat_contextmanager_and_registry(self):
        seen = []
        cb = seen.append
        heartbeat.register_callback(cb)
        heartbeat.register_callback(cb)  # idempotent
        try:
            with heartbeat.scan_heartbeat(5.0, total_rows=10, name="unit") as p:
                p.advance(10)
        finally:
            heartbeat.unregister_callback(cb)
        assert len(seen) == 1  # one final emit, delivered once
        assert seen[0]["done"] is True and seen[0]["name"] == "unit"

        with heartbeat.scan_heartbeat(5.0, total_rows=10) as p:
            p.advance(10)
        assert len(seen) == 1  # unregistered: no further deliveries

    def test_scan_heartbeat_disabled_yields_noop(self, monkeypatch):
        monkeypatch.delenv(heartbeat.ENV_KNOB, raising=False)
        with heartbeat.scan_heartbeat() as progress:
            assert progress is heartbeat.NOOP_PROGRESS

    def test_bottleneck_tracks_busiest_stage(self):
        progress = heartbeat.ScanProgress(1000.0, name="unit")
        with progress.timed("fold"):
            time.sleep(0.01)
        with progress.timed("decode"):
            time.sleep(0.03)
        snap = progress.snapshot()
        assert snap["bottleneck"] == "decode"
        assert snap["occupancy"]["decode"] >= snap["occupancy"]["fold"]
        progress.finish()

    def test_callback_exceptions_do_not_break_emission(self, tmp_path):
        out = str(tmp_path / "safe.jsonl")

        def bad(_snap):
            raise RuntimeError("consumer bug")

        progress = heartbeat.ScanProgress(1000.0, callback=bad, out_path=out)
        progress.advance(5)
        progress.finish()  # must not raise
        with open(out, encoding="utf-8") as fh:
            assert json.loads(fh.readline())["rows"] == 5


class TestReadaheadAttribution:
    """ISSUE 12 satellite: read-ahead hits/misses fold into the
    heartbeat snapshot, and a miss-starved window renames the
    bottleneck to "read" (the blocked future waits otherwise hide
    inside the consumer stage's timer)."""

    def test_misses_promote_read_bottleneck(self):
        progress = heartbeat.ScanProgress(1000.0, name="unit")
        with progress.timed("fold"):
            time.sleep(0.01)
        for hit in (True, False, False):
            progress.note_readahead(hit)
        snap = progress.snapshot()
        assert snap["readahead"] == {"hits": 1, "misses": 2}
        assert snap["bottleneck"] == "read"
        progress.finish()

    def test_hits_keep_stage_bottleneck(self):
        progress = heartbeat.ScanProgress(1000.0, name="unit")
        with progress.timed("decode"):
            time.sleep(0.01)
        for hit in (True, True, False):
            progress.note_readahead(hit)
        snap = progress.snapshot()
        assert snap["readahead"] == {"hits": 2, "misses": 1}
        assert snap["bottleneck"] == "decode"
        progress.finish()

    def test_no_readahead_no_snapshot_key(self):
        progress = heartbeat.ScanProgress(1000.0, name="unit")
        assert "readahead" not in progress.snapshot()
        progress.finish()

    def test_noop_progress_accepts_note_readahead(self):
        heartbeat.NOOP_PROGRESS.note_readahead(True)  # must not raise

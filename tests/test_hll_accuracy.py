"""HLL++ accuracy sweep: estimates must stay inside the declared
rsd=0.05 envelope across the cardinality range, including the mid-range
regime the bias tables exist for
(reference: catalyst/HLLConstants.scala:25, StatefulHyperloglogPlus.scala:210-297).
"""

import numpy as np
import pytest

from deequ_tpu.ops.sketches import hll
from deequ_tpu.ops.sketches.hll_bias import BIAS_P9, RAW_ESTIMATE_P9, THRESHOLD_P9


def estimate_for_cardinality(n: int, seed: int) -> float:
    rng = np.random.default_rng(seed)
    # distinct 64-bit values; hash through the engine's numeric path
    values = rng.permutation(np.arange(1, n + 1, dtype=np.int64)) + (
        np.int64(seed) << 32
    )
    registers = np.zeros(hll.M, dtype=np.int32)
    hashes = hll.xxhash64_u64(values)
    idx, rank = hll.registers_from_hashes(hashes)
    hll.update_registers(registers, idx, rank)
    return hll.estimate(registers)


class TestAccuracySweep:
    @pytest.mark.parametrize(
        "cardinality",
        [100, 300, 700, 1_500, 3_000, 6_000, 12_000, 25_000,
         50_000, 100_000, 300_000, 1_000_000],
    )
    def test_relative_error_within_rsd(self, cardinality):
        errors = []
        for seed in (1, 2, 3):
            est = estimate_for_cardinality(cardinality, seed)
            errors.append(abs(est - cardinality) / cardinality)
        # rsd = 0.05; mean of 3 runs within 2 sigma
        assert np.mean(errors) <= 0.10, (cardinality, errors)

    def test_small_cardinalities_near_exact(self):
        # linear counting regime: exact until register collisions appear
        # (n=50 over 512 registers already expects ~2 collisions — the
        # reference's estimator has the identical behavior)
        for n in (1, 2, 5, 10):
            est = estimate_for_cardinality(n, 9)
            assert est == n, (n, est)
        for n in (50, 200, 500):
            est = estimate_for_cardinality(n, 9)
            assert abs(est - n) <= max(2, 0.1 * n), (n, est)

    def test_tables_well_formed(self):
        assert len(RAW_ESTIMATE_P9) == len(BIAS_P9) == 201
        assert np.all(np.diff(RAW_ESTIMATE_P9) > 0)  # sorted for searchsorted
        assert THRESHOLD_P9 == 400.0

    def test_bias_interpolation_window(self):
        # below the first table point: uses the first K entries
        b = hll.estimate_bias(float(RAW_ESTIMATE_P9[0]) - 100)
        assert b == pytest.approx(float(np.mean(BIAS_P9[:6])))
        # above the last point the reference's clamping yields a 5-entry
        # window: nearest=201 -> low=196, high=min(202, 201)=201
        b = hll.estimate_bias(float(RAW_ESTIMATE_P9[-1]) + 100)
        assert b == pytest.approx(float(np.mean(BIAS_P9[196:201])))

    def test_mid_range_improved_by_bias_correction(self):
        """In the 2.5m..5m regime (m=512: ~1280..2560) the raw estimate
        is known to overestimate; the corrected estimator must not."""
        errs = []
        for n in (1_400, 1_800, 2_200, 2_600, 3_200):
            for seed in (11, 12, 13, 14):
                est = estimate_for_cardinality(n, seed)
                errs.append((est - n) / n)
        # mean signed error near zero: no systematic overestimate
        assert abs(float(np.mean(errs))) <= 0.05, errs

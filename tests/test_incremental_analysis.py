"""Incremental/partitioned state algebra, analyzer by analyzer — the
mirror of the reference's IncrementalAnalysisTest (incremental ==
from-scratch), IncrementalAnalyzerTest (270 LoC),
StateAggregationTests/StateAggregationIntegrationTest (245 LoC:
partitioned state merge == whole table through the runner AND the suite)
and PartitionedTableIntegrationTest (169 LoC)."""

from __future__ import annotations

import numpy as np
import pytest

from deequ_tpu import Check, CheckLevel, CheckStatus, Table, VerificationSuite
from deequ_tpu.analyzers import (
    ApproxCountDistinct,
    Completeness,
    Compliance,
    Correlation,
    CountDistinct,
    DataType,
    Distinctness,
    Entropy,
    Histogram,
    Maximum,
    Mean,
    Minimum,
    MutualInformation,
    PatternMatch,
    Size,
    StandardDeviation,
    Sum,
    Uniqueness,
    UniqueValueRatio,
)
from deequ_tpu.analyzers.sketch import ApproxQuantile, ApproxQuantiles
from deequ_tpu.analyzers.state_provider import InMemoryStateProvider
from deequ_tpu.runners.analysis_runner import AnalysisRunner


def make_partition(seed: int, n: int = 4000) -> dict:
    rng = np.random.default_rng(seed)
    x = rng.normal(5.0, 3.0, n)
    x[:: max(7, seed + 7)] = np.nan
    return {
        "x": x,
        "y": rng.normal(size=n),
        "g": rng.integers(0, 25, n),
        "s": np.array(
            [["42", "word", "3.14", None, "true"][i % 5] for i in range(n)],
            dtype=object,
        ),
    }


PARTS = [make_partition(seed) for seed in (0, 1, 2)]
WHOLE = Table.from_numpy(
    {k: np.concatenate([p[k] for p in PARTS]) for k in ("x", "y", "g", "s")}
)

ALL_ANALYZERS = [
    Size(),
    Size(where="x > 5"),
    Completeness("x"),
    Completeness("s", where="g < 10"),
    Compliance("pos", "x > 0"),
    PatternMatch("s", r"^\d+$"),
    Mean("x"),
    Minimum("x"),
    Maximum("x"),
    Sum("x"),
    StandardDeviation("x"),
    Correlation("x", "y"),
    DataType("s"),
    ApproxCountDistinct("g"),
    ApproxQuantile("x", 0.25),
    ApproxQuantiles("x", (0.1, 0.5, 0.9)),
    Uniqueness(("g",)),
    Distinctness(("g",)),
    UniqueValueRatio(("g",)),
    CountDistinct(("g",)),
    Entropy("g"),
    Histogram("g"),
    MutualInformation("g", "s"),
]


@pytest.fixture(scope="module")
def partition_states():
    providers = []
    for part in PARTS:
        provider = InMemoryStateProvider()
        AnalysisRunner.do_analysis_run(
            Table.from_numpy(part), ALL_ANALYZERS, save_states_with=provider
        )
        providers.append(provider)
    return providers


@pytest.fixture(scope="module")
def whole_table_context():
    return AnalysisRunner.do_analysis_run(WHOLE, ALL_ANALYZERS)


@pytest.fixture(scope="module")
def aggregated_context(partition_states):
    return AnalysisRunner.run_on_aggregated_states(
        WHOLE, ALL_ANALYZERS, partition_states
    )


@pytest.mark.parametrize("analyzer", ALL_ANALYZERS, ids=repr)
def test_partition_merge_equals_whole_table(
    analyzer, aggregated_context, whole_table_context
):
    """State semigroup: fold(partition states) == whole-table run, for
    EVERY analyzer (reference: StateAggregationIntegrationTest)."""
    merged = aggregated_context.metric_map[analyzer].value
    whole = whole_table_context.metric_map[analyzer].value
    assert merged.is_success == whole.is_success, analyzer
    got, want = merged.get(), whole.get()
    if isinstance(analyzer, (ApproxQuantile, ApproxQuantiles)):
        # sketches merged in a different order agree within RANK error —
        # the sketch's actual contract (value-space tolerances break down
        # in distribution tails where the density is low)
        xs = np.sort(WHOLE.column("x").values[WHOLE.column("x").valid])

        def rank_of(v: float) -> float:
            return float(np.searchsorted(xs, v, side="right")) / len(xs)

        def assert_rank_close(g: float, w: float, q: float) -> None:
            # each sketch answers within ~eps of q; allow both errors
            budget = 3 * 0.01
            assert abs(rank_of(g) - q) <= budget, (q, g, rank_of(g))
            assert abs(rank_of(w) - q) <= budget, (q, w, rank_of(w))

        if isinstance(got, dict):
            for key in want:
                assert_rank_close(got[key], want[key], float(key))
        else:
            assert_rank_close(got, want, analyzer.quantile)
    elif hasattr(want, "values"):  # Distribution
        assert {k: v.absolute for k, v in got.values.items()} == {
            k: v.absolute for k, v in want.values.items()
        }
    else:
        assert got == pytest.approx(want, rel=1e-9), analyzer


def test_incremental_update_recomputes_only_new_partition(partition_states):
    """Add a partition: only its state is computed; the merge then covers
    all four (reference: UpdateMetricsOnPartitionedDataExample.scala:63-86)."""
    new_part = make_partition(9)
    new_provider = InMemoryStateProvider()
    AnalysisRunner.do_analysis_run(
        Table.from_numpy(new_part), [Size(), Mean("x")], save_states_with=new_provider
    )
    ctx = AnalysisRunner.run_on_aggregated_states(
        WHOLE, [Size(), Mean("x")], list(partition_states) + [new_provider]
    )
    assert ctx.metric_map[Size()].value.get() == float(
        WHOLE.num_rows + len(new_part["x"])
    )

    all_x = np.concatenate([p["x"] for p in PARTS] + [new_part["x"]])
    expected_mean = float(np.nanmean(all_x))
    assert ctx.metric_map[Mean("x")].value.get() == pytest.approx(
        expected_mean, rel=1e-12
    )


def test_aggregated_states_through_verification_suite(partition_states):
    """reference: VerificationSuite.runOnAggregatedStates
    (VerificationSuite.scala:208-229)."""
    result = VerificationSuite.run_on_aggregated_states(
        WHOLE,
        [
            Check(CheckLevel.ERROR, "aggregated")
            .has_size(lambda n: n == WHOLE.num_rows)
            .has_completeness("x", lambda v: 0.7 < v < 1.0)
            .has_uniqueness(("g",), lambda v: v < 0.1)
        ],
        partition_states,
    )
    assert result.status == CheckStatus.SUCCESS


def test_aggregation_persists_merged_state(partition_states):
    target = InMemoryStateProvider()
    AnalysisRunner.run_on_aggregated_states(
        WHOLE, [Sum("x")], partition_states, save_states_with=target
    )
    merged_state = target.load(Sum("x"))
    assert merged_state is not None
    expected = float(np.nansum(np.concatenate([p["x"] for p in PARTS])))
    assert merged_state.metric_value() == pytest.approx(expected, rel=1e-12)


def test_no_data_scan_during_aggregation(partition_states):
    """Aggregating states must not launch scans over the data
    (reference: 'metrics purely from merged states')."""
    from deequ_tpu.ops import runtime

    with runtime.monitored() as stats:
        AnalysisRunner.run_on_aggregated_states(
            WHOLE, [Size(), Mean("x"), StandardDeviation("x")], partition_states
        )
    assert stats.device_passes == 0
    assert stats.device_launches == 0


def test_empty_loaders_give_empty_state_failures():
    empty = InMemoryStateProvider()
    ctx = AnalysisRunner.run_on_aggregated_states(WHOLE, [Mean("x")], [empty])
    assert ctx.metric_map[Mean("x")].value.is_failure


def test_two_dataset_merge_mean_exact():
    """The reference's IncrementalAnalysisTest headline: metrics from
    merged states equal metrics over the union, exactly."""
    a = Table.from_pydict({"v": [1.0, 2.0, 3.0]})
    b = Table.from_pydict({"v": [10.0, 20.0]})
    pa_, pb = InMemoryStateProvider(), InMemoryStateProvider()
    AnalysisRunner.do_analysis_run(a, [Mean("v"), Maximum("v")], save_states_with=pa_)
    AnalysisRunner.do_analysis_run(b, [Mean("v"), Maximum("v")], save_states_with=pb)
    from deequ_tpu.data.table import ColumnType

    union_schema = Table.from_pydict({"v": []}, types={"v": ColumnType.DOUBLE})
    ctx = AnalysisRunner.run_on_aggregated_states(
        union_schema, [Mean("v"), Maximum("v")], [pa_, pb]
    )
    assert ctx.metric_map[Mean("v")].value.get() == pytest.approx(36.0 / 5)
    assert ctx.metric_map[Maximum("v")].value.get() == 20.0

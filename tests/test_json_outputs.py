"""JSON-file output options on the verification and suggestion builders
(reference: VerificationRunBuilder.scala:213-256 —
saveCheckResultsJsonToPath / saveSuccessMetricsJsonToPath /
overwritePreviousFiles — and ConstraintSuggestionRunBuilder.scala:229-289's
three save paths)."""

from __future__ import annotations

import json

import numpy as np
import pytest

from deequ_tpu.checks import Check, CheckLevel
from deequ_tpu.data.table import Table
from deequ_tpu.suggestions.rules import DEFAULT_RULES
from deequ_tpu.suggestions.runner import ConstraintSuggestionRunner
from deequ_tpu.verification import VerificationSuite


def make_table(n: int = 200) -> Table:
    rng = np.random.default_rng(0)
    x = rng.normal(10.0, 1.0, n)
    cat = np.array(["a", "b", "c"], dtype=object)[rng.integers(0, 3, n)]
    return Table.from_numpy({"x": x, "cat": cat})


class TestVerificationJsonOutputs:
    def _run(self, tmp_path, overwrite=False, **paths):
        builder = VerificationSuite.on_data(make_table()).add_check(
            Check(CheckLevel.ERROR, "basic").is_complete("x").has_size(lambda n: n == 200)
        )
        if "checks" in paths:
            builder = builder.save_check_results_json_to_path(str(paths["checks"]))
        if "metrics" in paths:
            builder = builder.save_success_metrics_json_to_path(str(paths["metrics"]))
        builder = builder.overwrite_output_files(overwrite)
        return builder.run()

    def test_check_results_json_written(self, tmp_path):
        out = tmp_path / "checks.json"
        result = self._run(tmp_path, checks=out)
        payload = json.loads(out.read_text())
        # same rows as the in-memory exporter
        assert payload == json.loads(result.check_results_as_json())
        assert any(row["constraint_status"] == "Success" for row in payload)

    def test_success_metrics_json_written(self, tmp_path):
        out = tmp_path / "metrics.json"
        result = self._run(tmp_path, metrics=out)
        payload = json.loads(out.read_text())
        assert payload == json.loads(result.success_metrics_as_json())
        names = {row["name"] for row in payload}
        assert {"Completeness", "Size"} <= names

    def test_overwrite_guard(self, tmp_path):
        out = tmp_path / "checks.json"
        out.write_text("old")
        with pytest.raises(FileExistsError):
            self._run(tmp_path, checks=out)
        assert out.read_text() == "old"  # guarded write left it untouched
        self._run(tmp_path, checks=out, overwrite=True)
        assert out.read_text() != "old"


class TestSuggestionJsonOutputs:
    def test_three_save_paths(self, tmp_path):
        profiles_out = tmp_path / "profiles.json"
        suggestions_out = tmp_path / "suggestions.json"
        evaluation_out = tmp_path / "evaluation.json"
        result = (
            ConstraintSuggestionRunner.on_data(make_table())
            .add_constraint_rules(DEFAULT_RULES)
            .use_train_test_split_with_test_set_ratio(0.3, seed=7)
            .save_column_profiles_json_to_path(str(profiles_out))
            .save_constraint_suggestions_json_to_path(str(suggestions_out))
            .save_evaluation_results_json_to_path(str(evaluation_out))
            .run()
        )
        profiles = json.loads(profiles_out.read_text())
        assert {p["column"] for p in profiles["columns"]} == {"x", "cat"}

        suggestions = json.loads(suggestions_out.read_text())
        assert suggestions == json.loads(result.suggestions_as_json())
        assert suggestions["constraint_suggestions"], "rules should fire"

        evaluation = json.loads(evaluation_out.read_text())
        entries = evaluation["constraint_suggestions"]
        assert len(entries) == len(result.all_suggestions())
        statuses = {e["constraint_result_on_test_set"] for e in entries}
        assert statuses <= {"Success", "Failure", "Unknown"}
        assert "Success" in statuses  # complete column evaluates cleanly

    def test_evaluation_without_split_is_unknown(self, tmp_path):
        evaluation_out = tmp_path / "evaluation.json"
        (
            ConstraintSuggestionRunner.on_data(make_table())
            .add_constraint_rules(DEFAULT_RULES)
            .save_evaluation_results_json_to_path(str(evaluation_out))
            .run()
        )
        entries = json.loads(evaluation_out.read_text())["constraint_suggestions"]
        assert entries and all(
            e["constraint_result_on_test_set"] == "Unknown" for e in entries
        )

    def test_suggestion_overwrite_guard(self, tmp_path):
        out = tmp_path / "suggestions.json"
        out.write_text("old")
        builder = (
            ConstraintSuggestionRunner.on_data(make_table())
            .add_constraint_rules(DEFAULT_RULES)
            .save_constraint_suggestions_json_to_path(str(out))
        )
        with pytest.raises(FileExistsError):
            builder.run()
        builder.overwrite_output_files(True).run()
        assert out.read_text() != "old"

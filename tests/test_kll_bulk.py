"""KLL bulk-insertion accuracy: the one-sort stride-decimation path must
keep rank error inside the relative_error=0.01 contract
(reference: analyzers/ApproxQuantile.scala:49)."""

import numpy as np
import pytest

from deequ_tpu.ops.sketches.kll import KLLSketch, k_for_error


class TestBulkInsert:
    @pytest.mark.parametrize("dist", ["uniform", "lognormal", "sorted"])
    def test_rank_error_within_contract(self, dist):
        rng = np.random.default_rng(5)
        n = 1_000_000
        if dist == "uniform":
            values = rng.random(n)
        elif dist == "lognormal":
            values = rng.lognormal(0, 2, n)
        else:
            values = np.arange(n, dtype=np.float64)
        sketch = KLLSketch(k=k_for_error(0.01), seed=11)
        # several large batches: exercises bulk insert + level merging
        for chunk in np.array_split(values, 7):
            sketch.update_batch(chunk)
        exact_sorted = np.sort(values)
        for q in (0.01, 0.1, 0.25, 0.5, 0.75, 0.9, 0.99):
            estimate = sketch.quantile(q)
            # rank of the estimate must be within eps of q
            rank = np.searchsorted(exact_sorted, estimate, side="right") / n
            assert abs(rank - q) <= 0.01, (dist, q, rank)

    def test_bulk_then_merge_parity(self):
        rng = np.random.default_rng(6)
        a, b = rng.normal(0, 1, 500_000), rng.normal(3, 1, 500_000)
        sa = KLLSketch(k=512, seed=1).update_batch(a)
        sb = KLLSketch(k=512, seed=2).update_batch(b)
        merged = sa.merge(sb)
        exact = np.sort(np.concatenate([a, b]))
        for q in (0.1, 0.5, 0.9):
            rank = np.searchsorted(exact, merged.quantile(q), side="right") / len(exact)
            assert abs(rank - q) <= 0.01, (q, rank)

    def test_device_assisted_rank_error_within_contract(self):
        """The fused-pass quantile path (device sort + stride decimation,
        host KLL level-inserts) must satisfy the same rank-error contract
        across many batches."""
        import pytest

        from deequ_tpu.analyzers import ApproxQuantiles
        from deequ_tpu.data.table import Table
        from deequ_tpu.ops.fused import FusedScanPass

        rng = np.random.default_rng(17)
        values = rng.lognormal(0.0, 1.5, 600_000)
        t = Table.from_numpy({"v": values})
        analyzer = ApproxQuantiles("v", (0.01, 0.1, 0.5, 0.9, 0.99))
        result = FusedScanPass([analyzer], batch_size=1 << 16).run(t)[0]  # 10 batches
        metric = analyzer.compute_metric_from(result.state_or_raise())
        exact_sorted = np.sort(values)
        for q, estimate in metric.value.get().items():
            rank = np.searchsorted(exact_sorted, estimate, side="right") / len(values)
            assert abs(rank - float(q)) <= 0.01, (q, rank)

    def test_device_assisted_with_where_filter(self):
        from deequ_tpu.analyzers import ApproxQuantile
        from deequ_tpu.data.table import Table
        from deequ_tpu.ops.fused import FusedScanPass

        t = Table.from_numpy(
            {"v": np.arange(10_000, dtype=np.float64),
             "g": np.arange(10_000) % 2}
        )
        analyzer = ApproxQuantile("v", 0.5, where="g = 0")
        result = FusedScanPass([analyzer]).run(t)[0]
        metric = analyzer.compute_metric_from(result.state_or_raise())
        # evens only: median ~ 5000 +- sketch error
        assert abs(metric.value.get() - 5000) <= 150

    def test_small_batches_unaffected(self):
        # below the bulk threshold the buffered path still runs
        sketch = KLLSketch(k=64, seed=3)
        values = np.arange(1000, dtype=np.float64)
        for chunk in np.array_split(values, 50):
            sketch.update_batch(chunk)
        assert sketch.n == 1000
        assert abs(sketch.quantile(0.5) - 500) <= 40  # eps ~ 2.3/64

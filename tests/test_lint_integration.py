"""Fail-fast integration tests (ISSUE 2, Layer 3): strict mode raises
ONE aggregated PlanValidationError before any kernel dispatch; lenient
attaches warnings to the result/context; off skips the pass; the mode
resolves from builder > parameter > DEEQU_TPU_VALIDATE env > lenient."""

from __future__ import annotations

import pytest

from deequ_tpu import Check, CheckLevel
from deequ_tpu.analyzers import Completeness, Mean
from deequ_tpu.data.table import Table
from deequ_tpu.lint import PlanValidationError
from deequ_tpu.lint.planlint import resolve_validation_mode
from deequ_tpu.runners.analysis_runner import AnalysisRunner
from deequ_tpu.verification.suite import VerificationSuite


def small_table() -> Table:
    return Table.from_pydict(
        {
            "price": [1.0, 2.0, 3.0, None],
            "item": ["a", "b", "c", "d"],
        }
    )


BAD_CHECK = Check(CheckLevel.ERROR, "bad").is_complete("prce")
GOOD_CHECK = Check(CheckLevel.ERROR, "good").is_complete("item")


def _no_scan(monkeypatch):
    """Make ANY kernel dispatch explode — proves fail-fast ordering."""
    from deequ_tpu.ops.fused import FusedScanPass

    def boom(self, *args, **kwargs):
        raise AssertionError("kernel dispatched before plan validation")

    monkeypatch.setattr(FusedScanPass, "run", boom)


class TestStrictMode:
    def test_strict_raises_before_any_kernel_dispatch(self, monkeypatch):
        _no_scan(monkeypatch)
        with pytest.raises(PlanValidationError) as excinfo:
            VerificationSuite.do_verification_run(
                small_table(), [BAD_CHECK], validation="strict"
            )
        assert any(d.code == "DQ101" for d in excinfo.value.diagnostics)

    def test_strict_aggregates_all_errors_in_one_raise(self):
        check = (
            Check(CheckLevel.ERROR, "bad")
            .is_complete("prce")
            .has_mean("item", lambda v: True)  # wrong type
            .satisfies("price < 1 AND price > 2", "impossible")
        )
        with pytest.raises(PlanValidationError) as excinfo:
            VerificationSuite.do_verification_run(
                small_table(), [check], validation="strict"
            )
        found = {d.code for d in excinfo.value.diagnostics}
        assert {"DQ101", "DQ102", "DQ204"} <= found
        assert "Plan validation failed" in str(excinfo.value)

    def test_strict_passes_clean_plan(self):
        result = VerificationSuite.do_verification_run(
            small_table(), [GOOD_CHECK], validation="strict"
        )
        assert result.validation_warnings == []

    def test_strict_runner_raises_before_dispatch(self, monkeypatch):
        _no_scan(monkeypatch)
        with pytest.raises(PlanValidationError):
            AnalysisRunner.do_analysis_run(
                small_table(), [Mean("nope")], validation="strict"
            )

    def test_warnings_do_not_fail_strict(self):
        # duplicate analyzers are warning-severity: strict still runs
        result = VerificationSuite.do_verification_run(
            small_table(),
            [GOOD_CHECK],
            required_analyzers=[Mean("price"), Mean("price")],
            validation="strict",
        )
        assert any(d.code == "DQ202" for d in result.validation_warnings)


class TestLenientMode:
    def test_lenient_runs_and_attaches_diagnostics(self):
        result = VerificationSuite.do_verification_run(
            small_table(), [BAD_CHECK]  # lenient is the default
        )
        assert any(d.code == "DQ101" for d in result.validation_warnings)
        # the run itself proceeded: the bad constraint failed at runtime
        assert result.status.name != "SUCCESS"

    def test_lenient_runner_attaches_to_context(self):
        context = AnalysisRunner.do_analysis_run(
            small_table(), [Mean("nope")], validation="lenient"
        )
        assert any(d.code == "DQ101" for d in context.validation_warnings)

    def test_clean_plan_attaches_nothing(self):
        context = AnalysisRunner.do_analysis_run(
            small_table(), [Mean("price")], validation="lenient"
        )
        assert context.validation_warnings == []
        assert context.metric_map[Mean("price")].value.get() == 2.0


class TestOffMode:
    def test_off_skips_validation(self):
        result = VerificationSuite.do_verification_run(
            small_table(), [BAD_CHECK], validation="off"
        )
        assert result.validation_warnings == []


class TestModeResolution:
    def test_explicit_mode_wins(self, monkeypatch):
        monkeypatch.setenv("DEEQU_TPU_VALIDATE", "off")
        assert resolve_validation_mode("strict") == "strict"

    def test_env_knob(self, monkeypatch):
        monkeypatch.setenv("DEEQU_TPU_VALIDATE", "strict")
        assert resolve_validation_mode(None) == "strict"
        with pytest.raises(PlanValidationError):
            VerificationSuite.do_verification_run(small_table(), [BAD_CHECK])

    def test_default_is_lenient(self, monkeypatch):
        monkeypatch.delenv("DEEQU_TPU_VALIDATE", raising=False)
        assert resolve_validation_mode(None) == "lenient"

    def test_unknown_mode_degrades_to_lenient(self):
        assert resolve_validation_mode("bogus") == "lenient"
        assert resolve_validation_mode(" STRICT ") == "strict"


class TestBuilders:
    def test_verification_builder_strict(self):
        with pytest.raises(PlanValidationError):
            (
                VerificationSuite()
                .on_data(small_table())
                .add_check(BAD_CHECK)
                .with_plan_validation("strict")
                .run()
            )

    def test_analysis_builder_strict(self):
        with pytest.raises(PlanValidationError):
            (
                AnalysisRunner.on_data(small_table())
                .add_analyzer(Mean("nope"))
                .with_plan_validation("strict")
                .run()
            )

    def test_analysis_builder_lenient_default(self):
        context = (
            AnalysisRunner.on_data(small_table())
            .add_analyzer(Completeness("prce"))
            .run()
        )
        assert any(d.code == "DQ101" for d in context.validation_warnings)
        assert any(
            d.suggestion == "price" for d in context.validation_warnings
        )


class TestSchemaInference:
    def test_nullability_inferred_from_table_validity(self):
        # price has a NULL -> nullable; item has none -> non-nullable,
        # so `item IS NULL` is statically unsatisfiable on THIS table
        table = small_table()
        context = AnalysisRunner.do_analysis_run(
            table,
            [Mean("price", where="item IS NULL")],
            validation="lenient",
        )
        assert any(d.code == "DQ204" for d in context.validation_warnings)

    def test_suite_passes_off_to_inner_runner(self, monkeypatch):
        # the suite validates the full plan once; the inner analysis run
        # must not re-lint (it would double every diagnostic)
        calls = []
        import deequ_tpu.runners.analysis_runner as runner_mod

        original = runner_mod.AnalysisRunner._validate_plan

        def counting(data, analyzers, validation, state_cache=None):
            calls.append(validation)
            return original(data, analyzers, validation, state_cache)

        monkeypatch.setattr(
            runner_mod.AnalysisRunner, "_validate_plan", staticmethod(counting)
        )
        VerificationSuite.do_verification_run(small_table(), [GOOD_CHECK])
        assert calls == ["off"]

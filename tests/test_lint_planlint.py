"""Plan-level lint tests: DQ110 and DQ202-DQ206, plus the constant-fold
and satisfiability engines they're built on (ISSUE 2, Layer 2)."""

from __future__ import annotations

from deequ_tpu import Check, CheckLevel
from deequ_tpu.analyzers import (
    ApproxQuantile,
    Completeness,
    Compliance,
    Mean,
    PatternMatch,
)
from deequ_tpu.data.expr import normalize_expression, parse
from deequ_tpu.data.table import ColumnType
from deequ_tpu.lint import (
    FieldInfo,
    SchemaInfo,
    Severity,
    fold_to_constant,
    lint_analyzer,
    lint_plan,
    satisfiability,
)

SCHEMA = SchemaInfo(
    [
        FieldInfo("item", ColumnType.STRING, nullable=False),
        FieldInfo("att1", ColumnType.STRING, nullable=True),
        FieldInfo("count", ColumnType.LONG, nullable=True),
        FieldInfo("price", ColumnType.DOUBLE, nullable=True),
        FieldInfo("flag", ColumnType.BOOLEAN, nullable=False),
    ]
)


def codes(diags):
    return [d.code for d in diags]


class TestConstantFold:
    def test_folds_literal_truths(self):
        assert fold_to_constant(parse("1 < 2"))[1] is True
        assert fold_to_constant(parse("1 > 2"))[1] is False
        assert fold_to_constant(parse("NULL IS NULL"))[1] is True

    def test_division_by_zero_folds_to_null(self):
        ok, value = fold_to_constant(parse("1 / 0 > 3"))
        assert ok and value is None

    def test_kleene_shortcut(self):
        # FALSE AND <anything> folds even when the rest references columns
        ok, value = fold_to_constant(parse("1 > 2 AND price > 0"))
        assert ok and value is False

    def test_column_references_do_not_fold(self):
        assert fold_to_constant(parse("price > 0")) is None


class TestSatisfiability:
    def test_contradictory_interval(self):
        assert satisfiability(parse("price < 1 AND price > 2"), SCHEMA) == "unsat"

    def test_satisfiable_interval(self):
        assert satisfiability(parse("price > 1 AND price < 2"), SCHEMA) == "sat"

    def test_point_interval_strictness(self):
        assert satisfiability(parse("price >= 1 AND price <= 1"), SCHEMA) == "sat"
        assert satisfiability(parse("price > 1 AND price <= 1"), SCHEMA) == "unsat"

    def test_equality_outside_bounds(self):
        assert (
            satisfiability(parse("price = 5 AND price < 3"), SCHEMA) == "unsat"
        )

    def test_null_on_non_nullable_column(self):
        assert satisfiability(parse("flag IS NULL"), SCHEMA) == "unsat"

    def test_plain_is_null_on_nullable_column_is_sat(self):
        assert satisfiability(parse("price IS NULL"), SCHEMA) == "sat"

    def test_null_only_escape(self):
        # the isContainedIn shape with an impossible non-NULL range
        verdict = satisfiability(
            parse("price IS NULL OR (price > 5 AND price < 3)"), SCHEMA
        )
        assert verdict == "null-only"

    def test_string_domains(self):
        assert (
            satisfiability(parse("item = 'a' AND item = 'b'"), SCHEMA) == "unsat"
        )
        assert satisfiability(parse("item = 'a'"), SCHEMA) == "sat"

    def test_opaque_stays_unknown(self):
        assert (
            satisfiability(parse("LENGTH(item) > 3 AND price < 0"), SCHEMA)
            == "unknown"
        )


class TestLintAnalyzer:
    def test_missing_column_dq101(self):
        diags = lint_analyzer(Mean("prce"), SCHEMA)
        assert "DQ101" in codes(diags)
        d = next(d for d in diags if d.code == "DQ101")
        assert d.suggestion == "price"
        assert d.subject == repr(Mean("prce"))

    def test_wrong_type_dq102_via_preconditions(self):
        diags = lint_analyzer(Mean("att1"), SCHEMA)
        assert "DQ102" in codes(diags)
        d = next(d for d in diags if d.code == "DQ102")
        assert d.severity == Severity.ERROR

    def test_bad_parameter_dq110(self):
        diags = lint_analyzer(ApproxQuantile("price", 1.5), SCHEMA)
        assert "DQ110" in codes(diags)

    def test_invalid_pattern_dq103(self):
        diags = lint_analyzer(PatternMatch("att1", "(unclosed"), SCHEMA)
        assert "DQ103" in codes(diags)

    def test_clean_analyzer(self):
        assert lint_analyzer(Mean("price"), SCHEMA) == []
        assert lint_analyzer(Mean("price", where="count > 0"), SCHEMA) == []


class TestLintPlan:
    def test_duplicate_analyzer_dq202(self):
        report = lint_plan(
            SCHEMA, required_analyzers=[Mean("price"), Mean("price")]
        )
        assert "DQ202" in codes(report.diagnostics)
        assert report.errors == []  # duplicates are a warning

    def test_contradictory_constraints_dq203(self):
        check = (
            Check(CheckLevel.ERROR, "c")
            .is_complete("att1")
            .satisfies("att1 IS NULL", "att1 must be null")
        )
        report = lint_plan(SCHEMA, checks=[check])
        assert "DQ203" in codes(report.diagnostics)

    def test_contradictory_compliance_pair_dq203(self):
        check = (
            Check(CheckLevel.ERROR, "c")
            .satisfies("price > 10", "big")
            .satisfies("price < 5", "small")
        )
        report = lint_plan(SCHEMA, checks=[check])
        assert "DQ203" in codes(report.diagnostics)

    def test_compatible_constraints_no_dq203(self):
        check = (
            Check(CheckLevel.ERROR, "c")
            .is_complete("att1")
            .satisfies("price >= 0", "non-negative")
        )
        report = lint_plan(SCHEMA, checks=[check])
        assert "DQ203" not in codes(report.diagnostics)

    def test_unsatisfiable_predicate_dq204(self):
        report = lint_plan(
            SCHEMA,
            required_analyzers=[Compliance("c", "price < 1 AND price > 2")],
        )
        assert "DQ204" in codes(report.diagnostics)
        assert report.errors

    def test_unsatisfiable_where_dq204(self):
        report = lint_plan(
            SCHEMA, required_analyzers=[Mean("price", where="flag IS NULL")]
        )
        assert "DQ204" in codes(report.diagnostics)

    def test_constant_true_predicate_dq205(self):
        report = lint_plan(
            SCHEMA, required_analyzers=[Compliance("c", "1 < 2")]
        )
        assert "DQ205" in codes(report.diagnostics)
        assert report.errors == []  # constant TRUE is a warning

    def test_constant_false_predicate_dq204(self):
        report = lint_plan(
            SCHEMA, required_analyzers=[Compliance("c", "1 > 2")]
        )
        assert "DQ204" in codes(report.diagnostics)

    def test_fusion_breaking_where_dq206(self):
        report = lint_plan(
            SCHEMA,
            required_analyzers=[
                Mean("price", where="count > 1"),
                Completeness("att1", where="count>1"),
            ],
        )
        assert "DQ206" in codes(report.diagnostics)
        d = next(d for d in report.diagnostics if d.code == "DQ206")
        assert "count > 1" in d.message and "count>1" in d.message

    def test_identical_wheres_no_dq206(self):
        report = lint_plan(
            SCHEMA,
            required_analyzers=[
                Mean("price", where="count > 1"),
                Completeness("att1", where="count > 1"),
            ],
        )
        assert "DQ206" not in codes(report.diagnostics)

    def test_clean_plan_is_empty(self):
        check = (
            Check(CheckLevel.ERROR, "clean")
            .is_complete("item")
            .has_mean("price", lambda v: v > 0)
            .satisfies("count >= 0", "non-negative count")
        )
        report = lint_plan(
            SCHEMA, checks=[check], required_analyzers=[Completeness("att1")]
        )
        assert report.diagnostics == []


class TestNormalizeExpression:
    def test_formatting_invariance(self):
        assert normalize_expression("a==1 AND  `b` <> 2.0") == (
            normalize_expression("`a` = 1.0 AND b != 2")
        )

    def test_distinct_predicates_stay_distinct(self):
        assert normalize_expression("a > 1") != normalize_expression("a >= 1")

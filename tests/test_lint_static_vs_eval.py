"""Differential suite: the static typechecker's verdicts must agree with
real evaluation (ISSUE 2, satellite). For every predicate in the
test_expr_differential.py corpus (and an expression zoo on top):

* the statically inferred kind equals the evaluator's Series kind;
* static nullable=False implies the evaluated null mask is all-False
  (the conservative direction: static may over-report nullability,
  never under-report).
"""

from __future__ import annotations

import numpy as np
import pytest

from deequ_tpu.data.expr import _eval, parse
from deequ_tpu.data.table import Table
from deequ_tpu.lint import SchemaInfo, analyze_ast

OPS = [">", ">=", "<", "<=", "=", "!="]


def _check(expression: str, table: Table) -> None:
    schema = SchemaInfo.from_table(table)
    ast = parse(expression)
    typed, _diags = analyze_ast(ast, schema, source=expression)
    _values, null, kind = _eval(ast, table, table.num_rows)
    assert typed.kind == kind, (
        f"{expression!r}: static kind {typed.kind} != eval kind {kind}"
    )
    if not typed.nullable:
        assert not null.any(), (
            f"{expression!r}: static says non-nullable but eval produced "
            f"{int(null.sum())} NULL row(s)"
        )


def _corpus_table(rng: np.random.Generator, n: int) -> Table:
    a = rng.integers(-5, 5, n).astype(float)
    a[rng.random(n) < 0.2] = np.nan
    b = rng.integers(-5, 5, n).astype(float)
    s = np.array(["x", "y", "zz", None], dtype=object)[rng.integers(0, 4, n)]
    return Table.from_pydict({"a": list(a), "b": list(b), "s": list(s)})


@pytest.mark.parametrize("seed", range(40))
def test_random_predicates_static_matches_eval(seed):
    rng = np.random.default_rng(seed)
    n = int(rng.integers(1, 200))
    table = _corpus_table(rng, n)

    op = rng.choice(OPS)
    lit = int(rng.integers(-5, 5))
    conj = rng.choice(["AND", "OR"])
    op2 = rng.choice([">", "<"])
    _check(f"a {op} {lit} {conj} b {op2} 0", table)


@pytest.mark.parametrize("seed", range(0, 40, 5))
def test_in_list_and_is_null_static_matches_eval(seed):
    rng = np.random.default_rng(100 + seed)
    n = int(rng.integers(1, 150))
    table = _corpus_table(rng, n)
    _check("s IN ('x','zz') OR a IS NULL", table)
    _check("s IS NOT NULL AND a >= 0", table)


EXPRESSION_ZOO = [
    # arithmetic
    "a + b",
    "b * 2",
    "b - 1",
    "b / 2",
    "b / 0",
    "b % 3",
    "-b",
    # comparisons and logic
    "b > 0",
    "b > 0 AND b < 10",
    "b > 0 OR a > 0",
    "NOT (b > 0)",
    "a BETWEEN -2 AND 2",
    "b BETWEEN -2 AND 2",
    # null handling
    "a IS NULL",
    "a IS NOT NULL",
    "s IS NULL",
    "COALESCE(a, 0)",
    "COALESCE(a, b)",
    "COALESCE(s, 'none')",
    # strings
    "s",
    "s LIKE 'z%'",
    "s RLIKE '^z+$'",
    "LENGTH(s)",
    "LOWER(s)",
    "UPPER(s) = 'X'",
    "TRIM(s)",
    "s IN ('x', 'y')",
    "b IN (1, 2, 3)",
    # functions
    "ABS(b)",
    "ABS(a)",
    "ISNULL(a)",
    "ISNOTNULL(a)",
    # case
    "CASE WHEN b > 0 THEN 1 ELSE 0 END",
    "CASE WHEN b > 0 THEN 1 END",
    "CASE WHEN b > 0 THEN 'pos' ELSE 'neg' END",
    # literals
    "1 + 2",
    "TRUE",
    "NULL",
    "'abc'",
]


@pytest.mark.parametrize("expression", EXPRESSION_ZOO)
def test_expression_zoo_static_matches_eval(expression):
    rng = np.random.default_rng(7)
    table = _corpus_table(rng, 64)
    _check(expression, table)


@pytest.mark.parametrize("expression", EXPRESSION_ZOO)
def test_expression_zoo_on_null_free_table(expression):
    # no-null columns: static sees nullable=False fields, which makes the
    # "static non-nullable => eval has no NULLs" direction bite hardest
    rng = np.random.default_rng(11)
    n = 64
    table = Table.from_pydict(
        {
            "a": list(rng.integers(-5, 5, n).astype(float)),
            "b": list(rng.integers(-5, 5, n).astype(float)),
            "s": list(np.array(["x", "y", "zz"], dtype=object)[
                rng.integers(0, 3, n)
            ]),
        }
    )
    _check(expression, table)

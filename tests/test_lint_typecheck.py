"""Typed expression analysis tests: every expression-level diagnostic
code (DQ100-DQ105) plus kind/nullability inference and source spans
(ISSUE 2, Layer 1)."""

from __future__ import annotations

from deequ_tpu.data.table import ColumnType
from deequ_tpu.lint import (
    FieldInfo,
    SchemaInfo,
    Severity,
    analyze_expression,
)

SCHEMA = SchemaInfo(
    [
        FieldInfo("item", ColumnType.STRING, nullable=False),
        FieldInfo("att1", ColumnType.STRING, nullable=True),
        FieldInfo("count", ColumnType.LONG, nullable=True),
        FieldInfo("price", ColumnType.DOUBLE, nullable=True),
        FieldInfo("flag", ColumnType.BOOLEAN, nullable=False),
        FieldInfo("ts", ColumnType.TIMESTAMP, nullable=False),
    ]
)


def codes(diags):
    return [d.code for d in diags]


class TestKinds:
    def test_comparison_is_bool(self):
        typed, diags = analyze_expression("price > 1", SCHEMA)
        assert typed.kind == "bool"
        assert diags == []

    def test_numeric_column_kinds(self):
        for expr in ("count + 1", "price * 2", "ts"):
            typed, diags = analyze_expression(expr, SCHEMA)
            assert typed.kind == "num", expr
            assert diags == []

    def test_string_column_kind(self):
        typed, _ = analyze_expression("item", SCHEMA)
        assert typed.kind == "str"

    def test_bool_column_kind(self):
        typed, _ = analyze_expression("flag", SCHEMA)
        assert typed.kind == "bool"

    def test_non_nullable_comparison_not_nullable(self):
        typed, _ = analyze_expression("flag = TRUE", SCHEMA)
        assert typed.nullable is False

    def test_nullable_column_propagates(self):
        typed, _ = analyze_expression("price > 1", SCHEMA)
        assert typed.nullable is True

    def test_is_null_never_nullable(self):
        typed, _ = analyze_expression("price IS NULL", SCHEMA)
        assert typed.kind == "bool" and typed.nullable is False

    def test_division_is_nullable_unless_literal_nonzero(self):
        typed, _ = analyze_expression("1 / 2", SCHEMA)
        assert typed.nullable is False
        typed, _ = analyze_expression("1 / 0", SCHEMA)
        assert typed.nullable is True
        typed, _ = analyze_expression("1 % (price + 1)", SCHEMA)
        assert typed.nullable is True


class TestDQ100Parse:
    def test_unparseable_expression(self):
        typed, diags = analyze_expression("count > > 3", SCHEMA)
        assert typed is None
        assert codes(diags) == ["DQ100"]
        assert diags[0].severity == Severity.ERROR


class TestDQ101UnresolvedColumn:
    def test_unknown_column_is_error(self):
        typed, diags = analyze_expression("prce > 1", SCHEMA)
        assert codes(diags) == ["DQ101"]
        assert diags[0].severity == Severity.ERROR
        assert typed is not None  # recovery: analysis continues

    def test_did_you_mean_suggestion(self):
        _, diags = analyze_expression("prce > 1", SCHEMA)
        assert diags[0].suggestion == "price"

    def test_span_points_at_the_column(self):
        source = "1 + prce > 1"
        _, diags = analyze_expression(source, SCHEMA)
        a, b = diags[0].span
        assert source[a:b] == "prce"

    def test_rendered_with_caret(self):
        _, diags = analyze_expression("prce > 1", SCHEMA)
        rendered = diags[0].render()
        assert "prce > 1" in rendered
        assert "^^^^" in rendered
        assert "did you mean 'price'" in rendered


class TestDQ102TypeMismatch:
    def test_bool_vs_num_comparison_warns(self):
        _, diags = analyze_expression("flag > 1", SCHEMA)
        assert "DQ102" in codes(diags)
        assert all(d.severity == Severity.WARNING for d in diags)

    def test_bool_vs_str_comparison_warns(self):
        _, diags = analyze_expression("flag = 'true'", SCHEMA)
        assert "DQ102" in codes(diags)

    def test_string_column_in_numeric_context_warns(self):
        _, diags = analyze_expression("att1 + 1", SCHEMA)
        assert "DQ102" in codes(diags)

    def test_like_on_numeric_warns(self):
        _, diags = analyze_expression("price LIKE '1%'", SCHEMA)
        assert "DQ102" in codes(diags)

    def test_clean_expression_has_no_diags(self):
        _, diags = analyze_expression(
            "item LIKE 'a%' AND price BETWEEN 0 AND 10", SCHEMA
        )
        assert diags == []


class TestDQ103InvalidLiteral:
    def test_non_numeric_string_vs_numeric_column(self):
        _, diags = analyze_expression("price > 'abc'", SCHEMA)
        assert "DQ103" in codes(diags)
        d = next(d for d in diags if d.code == "DQ103")
        assert d.severity == Severity.ERROR
        assert "always yields NULL" in d.message

    def test_numeric_string_literal_is_fine(self):
        _, diags = analyze_expression("price > '1.5'", SCHEMA)
        assert diags == []

    def test_invalid_rlike_regex(self):
        _, diags = analyze_expression("item RLIKE '(unclosed'", SCHEMA)
        assert "DQ103" in codes(diags)


class TestDQ104UnknownFunction:
    def test_unknown_function(self):
        _, diags = analyze_expression("FOO(price) > 1", SCHEMA)
        assert "DQ104" in codes(diags)
        assert diags[0].severity == Severity.ERROR

    def test_known_functions_clean(self):
        for expr in (
            "ABS(price) > 1",
            "LENGTH(item) > 3",
            "COALESCE(price, 0) >= 0",
            "LOWER(item) = 'x'",
        ):
            _, diags = analyze_expression(expr, SCHEMA)
            assert diags == [], expr


class TestDQ105Arity:
    def test_missing_argument(self):
        _, diags = analyze_expression("ABS() > 1", SCHEMA)
        assert "DQ105" in codes(diags)
        assert diags[0].severity == Severity.ERROR


class TestFuncAndCaseInference:
    def test_coalesce_with_non_nullable_fallback(self):
        typed, _ = analyze_expression("COALESCE(price, 0)", SCHEMA)
        assert typed.kind == "num" and typed.nullable is False

    def test_coalesce_all_nullable(self):
        typed, _ = analyze_expression("COALESCE(price, count)", SCHEMA)
        assert typed.nullable is True

    def test_case_without_else_is_nullable(self):
        typed, _ = analyze_expression(
            "CASE WHEN flag THEN 1 END", SCHEMA
        )
        assert typed.kind == "num" and typed.nullable is True

    def test_case_with_else_of_literals_not_nullable(self):
        typed, _ = analyze_expression(
            "CASE WHEN flag THEN 1 ELSE 2 END", SCHEMA
        )
        assert typed.nullable is False

    def test_length_of_non_nullable_string(self):
        typed, _ = analyze_expression("LENGTH(item)", SCHEMA)
        assert typed.kind == "num" and typed.nullable is False

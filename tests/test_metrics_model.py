"""Metrics model + AnalyzerContext unit tests — the mirror of the
reference's MetricsTests.scala and AnalyzerContextTest.scala (132 LoC):
flatten() contracts for every composite metric, Distribution argmax,
context merge semantics and exporters."""

from __future__ import annotations

from deequ_tpu.analyzers import Completeness, Size
from deequ_tpu.core.maybe import Failure, Success
from deequ_tpu.core.metrics import (
    Distribution,
    DistributionValue,
    DoubleMetric,
    Entity,
    HistogramMetric,
    KeyedDoubleMetric,
)
from deequ_tpu.runners.context import AnalyzerContext


class TestDoubleMetric:
    def test_flatten_is_identity(self):
        m = DoubleMetric(Entity.COLUMN, "Completeness", "att1", Success(0.5))
        assert list(m.flatten()) == [m]

    def test_failure_flattens_to_itself(self):
        m = DoubleMetric(
            Entity.COLUMN, "Completeness", "att1", Failure(ValueError("x"))
        )
        assert list(m.flatten()) == [m]


class TestKeyedDoubleMetric:
    """reference: Metric.scala:45-68 — flatten emits `name-$key`."""

    def test_flatten_emits_per_key_metrics(self):
        m = KeyedDoubleMetric(
            Entity.COLUMN,
            "ApproxQuantiles",
            "x",
            Success({"0.25": 1.0, "0.5": 2.0, "0.75": 3.0}),
        )
        flat = list(m.flatten())
        assert {f.name for f in flat} == {
            "ApproxQuantiles-0.25",
            "ApproxQuantiles-0.5",
            "ApproxQuantiles-0.75",
        }
        assert {f.value.get() for f in flat} == {1.0, 2.0, 3.0}
        assert all(f.entity == Entity.COLUMN and f.instance == "x" for f in flat)

    def test_failed_keyed_metric_flattens_to_single_failure(self):
        m = KeyedDoubleMetric(
            Entity.COLUMN, "ApproxQuantiles", "x", Failure(ValueError("bad"))
        )
        flat = list(m.flatten())
        assert len(flat) == 1
        assert flat[0].value.is_failure


class TestDistribution:
    def test_argmax(self):
        d = Distribution(
            {
                "a": DistributionValue(5, 0.5),
                "b": DistributionValue(3, 0.3),
                "c": DistributionValue(2, 0.2),
            },
            3,
        )
        assert d.argmax() == "a"

    def test_getitem(self):
        d = Distribution({"a": DistributionValue(5, 1.0)}, 1)
        assert d["a"].absolute == 5


class TestHistogramMetric:
    """reference: HistogramMetric.scala:37-60 — flatten emits bins +
    abs/ratio per value."""

    def test_flatten_names(self):
        d = Distribution(
            {"a": DistributionValue(3, 0.75), "b": DistributionValue(1, 0.25)}, 2
        )
        m = HistogramMetric(Entity.COLUMN, "Histogram", "att1", Success(d))
        flat = list(m.flatten())
        names = {f.name for f in flat}
        assert names == {
            "Histogram.bins",
            "Histogram.abs.a",
            "Histogram.ratio.a",
            "Histogram.abs.b",
            "Histogram.ratio.b",
        }
        by_name = {f.name: f.value.get() for f in flat}
        assert by_name["Histogram.bins"] == 2.0
        assert by_name["Histogram.abs.a"] == 3.0
        assert by_name["Histogram.ratio.a"] == 0.75


class TestEntitySerialization:
    def test_multicolumn_typo_is_load_bearing(self):
        """reference: Metric.scala:19 — 'Mutlicolumn' (sic) is the
        serialized token; byte compatibility keeps it."""
        assert Entity.MULTICOLUMN.value == "Mutlicolumn"


class TestAnalyzerContext:
    """reference: AnalyzerContextTest.scala."""

    def _ctx(self, value: float) -> AnalyzerContext:
        return AnalyzerContext(
            {
                Size(): DoubleMetric(Entity.DATASET, "Size", "*", Success(value)),
            }
        )

    def test_merge_right_side_wins(self):
        merged = self._ctx(1.0) + self._ctx(2.0)
        assert merged.metric(Size()).value.get() == 2.0

    def test_merge_unions_disjoint_analyzers(self):
        left = self._ctx(1.0)
        right = AnalyzerContext(
            {
                Completeness("a"): DoubleMetric(
                    Entity.COLUMN, "Completeness", "a", Success(0.5)
                )
            }
        )
        merged = left + right
        assert len(merged.all_metrics()) == 2

    def test_empty(self):
        assert AnalyzerContext.empty().all_metrics() == []

    def test_equality_by_metric_map(self):
        assert self._ctx(1.0) == self._ctx(1.0)
        assert self._ctx(1.0) != self._ctx(2.0)

    def test_missing_metric_is_none(self):
        assert self._ctx(1.0).metric(Completeness("zzz")) is None

    def test_success_metrics_rows_skip_failures(self):
        ctx = AnalyzerContext(
            {
                Size(): DoubleMetric(Entity.DATASET, "Size", "*", Success(4.0)),
                Completeness("a"): DoubleMetric(
                    Entity.COLUMN, "Completeness", "a", Failure(ValueError("x"))
                ),
            }
        )
        rows = ctx.success_metrics_as_rows()
        assert len(rows) == 1
        assert rows[0]["name"] == "Size"

    def test_composite_metrics_flatten_in_rows(self):
        quantiles = KeyedDoubleMetric(
            Entity.COLUMN, "ApproxQuantiles", "x", Success({"0.5": 2.0})
        )
        from deequ_tpu.analyzers.sketch import ApproxQuantiles

        ctx = AnalyzerContext({ApproxQuantiles("x", (0.5,)): quantiles})
        rows = ctx.success_metrics_as_rows()
        assert rows[0]["name"] == "ApproxQuantiles-0.5"
        assert rows[0]["value"] == 2.0

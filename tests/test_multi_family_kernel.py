"""Parity tests for the multi-column batched family kernel:

1. `native.masked_moments_select_multi` — K columns folded in one
   row-blocked traversal must be BIT-IDENTICAL (moments, decimated
   samples, HLL registers, meta) to K solo `masked_moments_select`
   calls, across where masks, null masks, constant/compact/all-null
   columns and both HLL modes.
2. The fused.py grouping layer — same-(where, cap) families dispatch
   ONE batched call; `DEEQU_TPU_NO_MULTI_FAMILY=1` forces the
   per-column kernel and end-to-end metrics must not move at all.
3. Streaming — a multi-batch parquet scan under the toggle equals the
   batched path, and the counts-shortcut miss is probed once per
   (column, where) per stream, not once per batch.
"""

from __future__ import annotations

import numpy as np
import pytest

from deequ_tpu.ops import native

needs_native = pytest.mark.skipif(
    not native.available(), reason="native kernels unavailable"
)


def _solo(x, valid, where, cap, hll_mode=0, hashvals=None):
    return native.masked_moments_select(
        x, valid, where, cap, hll_mode=hll_mode, hashvals=hashvals
    )


def _assert_bit_identical(multi_out, solo_out, tag):
    mom_m, sample_m, n_m, lvl_m, regs_m = multi_out
    mom_s, sample_s, n_s, lvl_s, regs_s = solo_out
    assert (n_m, lvl_m) == (n_s, lvl_s), tag
    assert np.array_equal(mom_m, mom_s, equal_nan=True), (tag, mom_m, mom_s)
    assert np.array_equal(sample_m, sample_s), tag
    assert (regs_m is None) == (regs_s is None), tag
    if regs_m is not None:
        assert np.array_equal(regs_m, regs_s), tag


@needs_native
class TestMultiKernelBitExact:
    def _check_group(self, columns, where, cap, tag):
        outs = native.masked_moments_select_multi(columns, where, cap)
        assert outs is not None, tag
        assert len(outs) == len(columns), tag
        for i, (x, valid, hll_mode, hashvals) in enumerate(columns):
            solo = _solo(x, valid, where, cap, hll_mode, hashvals)
            _assert_bit_identical(outs[i], solo, (tag, i))

    @pytest.mark.parametrize("with_where", [False, True])
    def test_mixed_columns(self, with_where):
        rng = np.random.default_rng(3 if with_where else 2)
        n = 120_000
        columns = []
        for i in range(7):
            kind = i % 4
            if kind == 0:
                x = rng.random(n) * (i + 1)
            elif kind == 1:
                x = rng.lognormal(2.0, 1.0, n)
            elif kind == 2:
                x = rng.integers(0, 10**9, n).astype(np.float64)
            else:
                # compact key prefix: every key shares one top bucket
                x = 100.0 + rng.random(n) * 1e-9
            valid = None
            if i % 3 == 1:
                valid = rng.random(n) > 0.1
            hll_mode = i % 3  # off / f64-bits / canonical-int64
            hashvals = (
                rng.integers(-(2**62), 2**62, n) if hll_mode == 2 else None
            )
            columns.append((x, valid, hll_mode, hashvals))
        where = (rng.random(n) > 0.4) if with_where else None
        self._check_group(columns, where, 460, f"mixed:{with_where}")

    def test_degenerate_columns(self):
        rng = np.random.default_rng(5)
        n = 50_000
        one_valid = np.zeros(n, dtype=bool)
        one_valid[123] = True
        one_val = np.zeros(n)
        one_val[123] = -42.5
        columns = [
            (np.full(n, 3.25), None, 1, None),  # constant
            (np.full(n, np.nan), np.zeros(n, dtype=bool), 0, None),  # all-null
            (one_val, one_valid, 0, None),  # single survivor
            (rng.lognormal(0, 2, n), None, 0, None),  # regular companion
        ]
        self._check_group(columns, None, 64, "degenerate")
        self._check_group(
            columns, np.zeros(n, dtype=bool), 64, "degenerate-where-none"
        )

    @pytest.mark.parametrize("n", [0, 1, 5, 47, 2048, 2049])
    def test_tiny_inputs(self, n):
        rng = np.random.default_rng(n + 50)
        columns = [
            (rng.random(n) * 3, None, 1, None),
            (
                rng.lognormal(0.0, 2.0, n),
                rng.random(n) > 0.5 if n else np.zeros(0, dtype=bool),
                0,
                None,
            ),
        ]
        self._check_group(columns, None, 32, f"n={n}")

    @pytest.mark.parametrize("cap", [16, 64, 1024, 4096])
    def test_cap_sweep(self, cap):
        rng = np.random.default_rng(cap)
        n = 200_000
        columns = [
            (rng.random(n) * 7, None, 0, None),
            (rng.lognormal(2.0, 1.0, n), None, 0, None),
            (rng.integers(0, 10**9, n).astype(np.float64), None, 0, None),
        ]
        self._check_group(columns, None, cap, f"cap={cap}")

    def test_length_mismatch_returns_none(self):
        rng = np.random.default_rng(9)
        columns = [
            (rng.random(100), None, 0, None),
            (rng.random(99), None, 0, None),
        ]
        assert native.masked_moments_select_multi(columns, None, 32) is None


def _family_table(n=200_000, seed=13):
    """High-cardinality float columns — enough rows that the distinct
    count exceeds the hash counter's 65536 bound, so the counts shortcut
    MISSES and the select-family kernel runs."""
    from deequ_tpu.data.table import Table

    rng = np.random.default_rng(seed)
    return Table.from_numpy(
        {
            "a": rng.lognormal(1.0, 0.7, n),
            "b": rng.random(n) * 1000.0,
            "c": rng.standard_normal(n) * 50.0,
            "flag": rng.random(n) < 0.5,
        }
    )


def _run_family_analysis(table):
    from deequ_tpu.analyzers import (
        ApproxCountDistinct,
        ApproxQuantile,
        ApproxQuantiles,
        Mean,
        StandardDeviation,
    )
    from deequ_tpu.runners import AnalysisRunner

    analyzers = []
    for col in ("a", "b", "c"):
        analyzers += [
            ApproxQuantiles(col, (0.25, 0.5, 0.75)),
            Mean(col),
            StandardDeviation(col),
            ApproxCountDistinct(col),
        ]
    analyzers.append(ApproxQuantile("a", 0.5, where="flag"))
    analyzers.append(Mean("b", where="flag"))
    res = AnalysisRunner.on_data(table).add_analyzers(analyzers).run()
    out = {}
    for analyzer, metric in res.metric_map.items():
        assert metric.value.is_success, (analyzer, metric.value)
        out[repr(analyzer)] = metric.value.get()
    return out


@pytest.fixture
def host_placed(monkeypatch):
    """Force host placement: the family kernels only run for HOST-folded
    sketch members (device-placed sketches never reach them)."""
    monkeypatch.setenv("DEEQU_TPU_PLACEMENT", "host")


@needs_native
class TestGroupedDispatchParity:
    def test_end_to_end_equal_under_toggle(self, monkeypatch, host_placed):
        batched = _run_family_analysis(_family_table())
        monkeypatch.setenv("DEEQU_TPU_NO_MULTI_FAMILY", "1")
        solo = _run_family_analysis(_family_table())
        assert batched.keys() == solo.keys()
        for key in batched:
            bv, sv = batched[key], solo[key]
            if isinstance(bv, dict):
                assert bv.keys() == sv.keys(), key
                for q in bv:
                    assert bv[q] == sv[q], (key, q)  # bit-identical
            else:
                assert bv == sv, key  # bit-identical

    def test_multi_kernel_engages_and_toggle_disables(self, monkeypatch, host_placed):
        calls = {"multi": 0, "solo": 0}
        real_multi = native.masked_moments_select_multi
        real_solo = native.masked_moments_select

        def count_multi(columns, where, cap):
            calls["multi"] += 1
            return real_multi(columns, where, cap)

        def count_solo(*a, **k):
            calls["solo"] += 1
            return real_solo(*a, **k)

        monkeypatch.setattr(
            native, "masked_moments_select_multi", count_multi
        )
        monkeypatch.setattr(native, "masked_moments_select", count_solo)
        _run_family_analysis(_family_table())
        # a/b/c share (no-where, cap): one batched call; the where-group
        # has a single sketch member and stays on the solo kernel
        assert calls["multi"] >= 1
        assert calls["solo"] <= 2

        calls.update(multi=0, solo=0)
        monkeypatch.setenv("DEEQU_TPU_NO_MULTI_FAMILY", "1")
        _run_family_analysis(_family_table())
        assert calls["multi"] == 0
        assert calls["solo"] >= 3

    def test_streaming_batches_equal_under_toggle(
        self, tmp_path, monkeypatch, host_placed
    ):
        path = str(tmp_path / "stream.parquet")
        # >65536 distinct values PER BATCH: every batch runs the select
        # family kernels, not the counts shortcut
        _family_table(n=300_000, seed=21).to_parquet(
            path, row_group_size=100_000
        )
        from deequ_tpu.data.table import Table

        def stream():
            return Table.scan_parquet(path, batch_rows=100_000)

        batched = _run_family_analysis(stream())
        monkeypatch.setenv("DEEQU_TPU_NO_MULTI_FAMILY", "1")
        solo = _run_family_analysis(stream())
        assert batched.keys() == solo.keys()
        for key in batched:
            assert batched[key] == solo[key], key


class TestCountsMissMemo:
    def test_probe_runs_once_per_stream(self, tmp_path, monkeypatch, host_placed):
        """High-cardinality columns miss the counts shortcut on the
        first batch; later batches of the same scan must skip the
        ~262k-row probe entirely (the memo lives for the scan, so a
        SECOND scan probes again). A probe that SUCCEEDS is not counted
        against the memo — success means the probe IS the family
        computation (the a:flag family here stays under the hash
        counter's distinct bound per batch, so it legitimately runs
        every batch)."""
        from deequ_tpu.data.table import Table
        from deequ_tpu.ops import counts_family

        path = str(tmp_path / "memo.parquet")
        _family_table(n=300_000, seed=22).to_parquet(
            path, row_group_size=100_000
        )
        probes = {"miss": 0}
        real = counts_family.hash_counts_for_column

        def counting(*a, **k):
            res = real(*a, **k)
            if res is None:
                probes["miss"] += 1
            return res

        monkeypatch.setattr(
            counts_family, "hash_counts_for_column", counting
        )
        _run_family_analysis(Table.scan_parquet(path, batch_rows=100_000))
        # 4 live sketch (column, where) families, 3 batches: without the
        # memo each high-cardinality family would MISS once per BATCH
        assert 0 < probes["miss"] <= 4
        first_scan = probes["miss"]
        # the memo is scoped to one scan: a fresh scan probes again
        _run_family_analysis(Table.scan_parquet(path, batch_rows=100_000))
        assert probes["miss"] == 2 * first_scan, probes["miss"]

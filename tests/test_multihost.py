"""Multi-host (DCN) state merge: byte-level serde round-trips for every
state type, and the cross-host fold (with an injected gather) equals a
whole-table run — the multi-host analogue of the reference's
StateAggregationIntegrationTest (partitioned states == single pass)."""

from __future__ import annotations

import numpy as np
import pytest

from deequ_tpu.analyzers import (
    ApproxCountDistinct,
    Completeness,
    Compliance,
    Correlation,
    CountDistinct,
    DataType,
    Distinctness,
    Entropy,
    Maximum,
    Mean,
    Minimum,
    PatternMatch,
    Size,
    StandardDeviation,
    Sum,
    Uniqueness,
)
from deequ_tpu.analyzers.sketch import ApproxQuantile
from deequ_tpu.analyzers.state_provider import (
    InMemoryStateProvider,
    deserialize_state,
    serialize_state,
)
from deequ_tpu.data.table import Table
from deequ_tpu.parallel import multihost
from deequ_tpu.runners.analysis_runner import AnalysisRunner

ALL_ANALYZERS = [
    Size(),
    Completeness("x"),
    Compliance("pos", "x > 0"),
    PatternMatch("s", r"^\d+$"),
    Mean("x"),
    Minimum("x"),
    Maximum("x"),
    Sum("x"),
    StandardDeviation("x"),
    Correlation("x", "y"),
    DataType("s"),
    ApproxCountDistinct("g"),
    ApproxQuantile("x", 0.5),
    Uniqueness(("g",)),
    Distinctness(("g",)),
    CountDistinct(("g",)),
    Entropy("g"),
]


def make_arrays(seed: int, n: int = 3000) -> dict:
    rng = np.random.default_rng(seed)
    x = rng.normal(1.0, 2.0, n)
    x[::13] = np.nan
    return {
        "x": x,
        "y": rng.normal(size=n),
        "g": rng.integers(0, 40, n),
        "s": np.array(
            [["12", "abc", "3.5", None][i % 4] for i in range(n)], dtype=object
        ),
    }


def make_table(seed: int, n: int = 3000) -> Table:
    return Table.from_numpy(make_arrays(seed, n))


def envelope(analyzers, blobs) -> bytes:
    import struct

    return multihost.analyzer_list_digest(analyzers) + b"".join(
        struct.pack(">i", len(b)) + b for b in blobs
    )


def test_serialize_state_round_trips_every_analyzer():
    table = make_table(0)
    provider = InMemoryStateProvider()
    AnalysisRunner.do_analysis_run(table, ALL_ANALYZERS, save_states_with=provider)
    for analyzer in ALL_ANALYZERS:
        state = provider.load(analyzer)
        assert state is not None, analyzer
        blob = serialize_state(analyzer, state)
        assert isinstance(blob, bytes) and blob
        restored = deserialize_state(analyzer, blob)
        # round-trip must preserve the metric exactly
        a = analyzer.compute_metric_from(state).value.get()
        b = analyzer.compute_metric_from(restored).value.get()
        assert a == pytest.approx(b, rel=0, abs=0), analyzer


def test_allgather_bytes_single_process_identity():
    assert multihost.allgather_bytes(b"abc") == [b"abc"]
    assert multihost.allgather_bytes(b"") == [b""]


def test_multihost_merge_equals_whole_table():
    """Simulate a 3-host run: each 'host' analyzes its own partition; the
    injected gather hands every host all three serialized states. The
    folded metrics must equal a single whole-table run."""
    raw = [make_arrays(seed) for seed in (1, 2, 3)]
    partitions = [Table.from_numpy(arrays) for arrays in raw]
    whole = Table.from_numpy(
        {
            name: np.concatenate([arrays[name] for arrays in raw])
            for name in ("x", "y", "g", "s")
        }
    )

    # per-"host" local states
    local_providers = []
    for part in partitions:
        provider = InMemoryStateProvider()
        AnalysisRunner.do_analysis_run(part, ALL_ANALYZERS, save_states_with=provider)
        local_providers.append(provider)

    def fake_gather_for(host_idx):
        def gather(payload: bytes):
            # every host contributes its serialized state for the SAME
            # analyzer being merged (one-analyzer envelope per call here)
            analyzer = gather.current_analyzer
            envelopes = []
            for provider in local_providers:
                state = provider.load(analyzer)
                blob = (
                    b"\x00"
                    if state is None
                    else b"\x01" + serialize_state(analyzer, state)
                )
                envelopes.append(envelope([analyzer], [blob]))
            assert envelopes[host_idx] == payload
            return envelopes

        return gather

    single = AnalysisRunner.do_analysis_run(whole, ALL_ANALYZERS)

    for host_idx in (0, 1, 2):
        gather = fake_gather_for(host_idx)
        merged = InMemoryStateProvider()
        for analyzer in ALL_ANALYZERS:
            gather.current_analyzer = analyzer
            provider = local_providers[host_idx]
            partial, errors = multihost.merge_states_across_hosts(
                [analyzer], provider, gather=gather
            )
            assert not errors
            state = partial.load(analyzer)
            if state is not None:
                merged.persist(analyzer, state)

        for analyzer in ALL_ANALYZERS:
            expected = single.metric_map[analyzer].value.get()
            got = analyzer.compute_metric_from(merged.load(analyzer)).value.get()
            if isinstance(analyzer, ApproxQuantile):
                # sketches merged in a different order stay within the
                # declared rank error, not bit-identical
                assert got == pytest.approx(expected, rel=0.05), analyzer
            else:
                assert got == pytest.approx(expected, rel=1e-9), analyzer


def test_run_multihost_analysis_single_process():
    table = make_table(9)
    ctx = multihost.run_multihost_analysis(table, ALL_ANALYZERS)
    single = AnalysisRunner.do_analysis_run(table, ALL_ANALYZERS)
    for analyzer in ALL_ANALYZERS:
        rel = 0.05 if isinstance(analyzer, ApproxQuantile) else 1e-9
        assert ctx.metric_map[analyzer].value.get() == pytest.approx(
            single.metric_map[analyzer].value.get(), rel=rel
        ), analyzer


def test_global_data_mesh_spans_all_devices():
    import jax

    mesh = multihost.global_data_mesh()
    assert mesh.devices.size == len(jax.devices())


def test_host_failure_fails_global_metric():
    """A failure on one host must fail the global metric on every host —
    not silently shrink it to the healthy hosts' data."""
    table = make_table(4)

    def gather_with_remote_failure(payload: bytes):
        # host 1 reports a failure for BOTH analyzers in the envelope
        blob = b"\x02" + b"boom on host 1"
        failing = envelope([Size(), Mean("x")], [blob, blob])
        return [payload, failing]

    ctx = multihost.run_multihost_analysis(
        table, [Size(), Mean("x")], gather=gather_with_remote_failure
    )
    for analyzer in (Size(), Mean("x")):
        metric = ctx.metric_map[analyzer]
        assert metric.value.is_failure, analyzer
        assert "boom on host 1" in str(metric.value.exception)


def test_local_failure_propagates_but_empty_partition_does_not():
    table = make_table(5)
    # missing column -> local failure for Mean('nope'); Size still fine
    ctx = multihost.run_multihost_analysis(table, [Size(), Mean("nope")])
    assert ctx.metric_map[Size()].value.is_success
    assert ctx.metric_map[Mean("nope")].value.is_failure
    # an all-NULL partition is an EMPTY contribution, not a failure
    import numpy as np

    from deequ_tpu.data.table import Table as T

    all_null = T.from_numpy({"x": np.full(10, np.nan)})

    def gather_with_data_elsewhere(payload: bytes):
        other = InMemoryStateProvider()
        AnalysisRunner.do_analysis_run(
            make_table(6), [Mean("x")], save_states_with=other
        )
        blob = b"\x01" + serialize_state(Mean("x"), other.load(Mean("x")))
        return [payload, envelope([Mean("x")], [blob])]

    ctx2 = multihost.run_multihost_analysis(
        all_null, [Mean("x")], gather=gather_with_data_elsewhere
    )
    assert ctx2.metric_map[Mean("x")].value.is_success


def test_envelope_digest_mismatch_raises():
    """Hosts running differently ordered/composed analyzer lists must get
    a hard error, not silently swapped same-size states."""
    table = make_table(7, n=100)
    provider = InMemoryStateProvider()
    AnalysisRunner.do_analysis_run(
        table, [Size(), Sum("x")], save_states_with=provider
    )

    def gather_wrong_order(payload: bytes):
        # the "other host" deduped to a different order: digest differs
        blob = b"\x01" + serialize_state(Size(), provider.load(Size()))
        blob2 = b"\x01" + serialize_state(Sum("x"), provider.load(Sum("x")))
        return [payload, envelope([Sum("x"), Size()], [blob2, blob])]

    with pytest.raises(ValueError, match="analyzer-list mismatch"):
        multihost.merge_states_across_hosts(
            [Size(), Sum("x")], provider, gather=gather_wrong_order
        )


def test_duplicate_analyzers_merge_once():
    """Repeated analyzers (e.g. two checks requiring Size()) must not
    double-count the global metric."""
    table = make_table(8, n=100)
    ctx = multihost.run_multihost_analysis(table, [Size(), Size(), Mean("x")])
    assert ctx.metric_map[Size()].value.get() == 100.0

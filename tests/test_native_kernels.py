"""Native C kernels (ops/native): bit-exact parity with the vectorized
numpy implementations, graceful fallback, and in-place register update."""

from __future__ import annotations

import numpy as np
import pytest

from deequ_tpu.ops import native
from deequ_tpu.ops.sketches import hll


@pytest.fixture(scope="module")
def rng():
    return np.random.default_rng(99)


def _reference_pack(canon: np.ndarray, valid: np.ndarray) -> np.ndarray:
    idx, rank = hll.registers_from_hashes(hll.xxhash64_u64(canon[valid]))
    packed = np.zeros(len(canon), dtype=np.int32)
    packed[valid] = (idx << 6) | rank
    return packed


@pytest.mark.skipif(not native.available(), reason="no C compiler")
class TestNativeParity:
    @pytest.mark.parametrize(
        "values",
        [
            lambda r: r.normal(size=50_000),
            lambda r: r.integers(-(2**60), 2**60, 50_000),
            lambda r: r.integers(0, 2, 50_000).astype(bool),
            lambda r: np.array(
                [0.0, -0.0, np.inf, -np.inf, 5e-324, 2.0**31, np.pi]
            ),
        ],
    )
    def test_pack_matches_numpy(self, values, rng):
        vals = values(rng)
        valid = rng.random(len(vals)) > 0.15
        canon = hll.canonical_int64(np.asarray(vals))
        assert np.array_equal(
            native.xxhash64_pack(canon, valid), _reference_pack(canon, valid)
        )

    def test_update_registers_matches_scatter(self, rng):
        packed = _reference_pack(
            hll.canonical_int64(rng.normal(size=20_000)),
            np.ones(20_000, dtype=bool),
        )
        where = rng.random(20_000) > 0.3

        native_regs = np.zeros(hll.M, dtype=np.int32)
        assert native.hll_update_registers(packed, where, native_regs)

        ref = np.zeros(hll.M, dtype=np.int32)
        np.maximum.at(ref, packed >> 6, np.where(where, packed & 0x3F, 0))
        assert np.array_equal(native_regs, ref)

    def test_pack_codes_uses_identical_codes_either_path(self, rng, monkeypatch):
        vals = rng.normal(size=10_000)
        valid = rng.random(10_000) > 0.1
        with_native = hll.pack_codes(vals, valid)
        monkeypatch.setattr(native, "xxhash64_pack", lambda *_: None)
        without_native = hll.pack_codes(vals, valid)
        assert np.array_equal(with_native, without_native)


def test_fallback_when_disabled(monkeypatch, rng):
    monkeypatch.setattr(native, "xxhash64_pack", lambda *a: None)
    monkeypatch.setattr(native, "hll_update_registers", lambda *a: False)
    vals = rng.normal(size=1000)
    valid = np.ones(1000, dtype=bool)
    packed = hll.pack_codes(vals, valid)
    assert packed.dtype == np.int32 and (packed != 0).any()

"""Native parquet column-chunk reader: unit + differential tests.

Three layers (ISSUE 11):

* chunk-level differential — every eligible column chunk decoded by
  parquet_read.c (through `native_reader.decode_chunk`) must match the
  pyarrow read of the same row group bit for bit: null counts, validity
  bits, and raw value bits (floats compared via uint views so NaN
  payloads and signed zeros count);
* robustness — truncated chunks, corrupt Thrift varints, an oversized
  uncompressed_page_size, and random byte corruption must yield a clean
  None (pyarrow fallback), never a crash or an exception;
* assembly — `assemble_column` walks multi-group segment lists through
  the same decode.c kernels the Arrow fast path feeds; its output must
  agree with the pure-numpy mirror on every slice, including slices
  crossing row-group boundaries.
"""

from __future__ import annotations

import dataclasses
import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from deequ_tpu.data import native_reader as nr
from deequ_tpu.data.source import ParquetSource
from deequ_tpu.ops import native, runtime

requires_native = pytest.mark.skipif(
    not native.available(), reason="native library unavailable"
)


def _codec_names():
    mask = native.reader_codecs()
    return [
        name
        for name, bit in native.READER_CODEC_MASK.items()
        if mask & bit
    ]


def _mixed_table(n=4000, seed=7):
    rng = np.random.default_rng(seed)
    d = rng.normal(size=n)
    d[rng.random(n) < 0.1] = np.nan
    return pa.table(
        {
            "d": pa.array(d, mask=rng.random(n) < 0.2),
            "f": pa.array(rng.normal(size=n).astype(np.float32)),
            "i64": pa.array(
                rng.integers(-(10**12), 10**12, size=n),
                mask=rng.random(n) < 0.3,
            ),
            "i32": pa.array(rng.integers(-(2**31), 2**31, size=n).astype(np.int32)),
            "u8": pa.array(rng.integers(0, 256, size=n).astype(np.uint8)),
            "b": pa.array(rng.random(n) < 0.5, mask=rng.random(n) < 0.1),
            # low-cardinality double: stays dictionary-encoded on disk
            "dictish": pa.array((rng.integers(0, 8, size=n) * 1.5).astype(np.float64)),
        }
    )


def _write(table, path, codec, version="2.6", **kw):
    pq.write_table(
        table,
        path,
        compression=codec if codec != "UNCOMPRESSED" else "NONE",
        version=version,
        data_page_size=4096,
        row_group_size=max(1, table.num_rows // 2),
        **kw,
    )


def _metas(path, columns):
    """The source's own per-(group, column) native decode recipes."""
    src = ParquetSource(str(path))
    return src._reader_chunk_meta(frozenset(columns)), src


def _decode_all(path, metas):
    fd = os.open(str(path), os.O_RDONLY)
    try:
        out = {}
        for key, meta in metas.items():
            raw = nr.fetch_chunk(fd, meta)
            assert raw is not None, key
            out[key] = nr.decode_chunk(raw, meta)
        return out
    finally:
        os.close(fd)


@requires_native
@pytest.mark.parametrize("codec", _codec_names() or ["UNCOMPRESSED"])
@pytest.mark.parametrize("version", ["1.0", "2.6"])
def test_decode_chunk_bit_identical_to_pyarrow(tmp_path, codec, version):
    if codec not in _codec_names():
        pytest.skip(f"{codec} not loadable here")
    table = _mixed_table()
    path = tmp_path / f"mix_{codec}_{version}.parquet"
    _write(table, path, codec, version=version)
    cols = list(table.column_names)
    metas, _ = _metas(path, cols)
    assert metas, "no chunk proved eligible — recipe builder regressed"
    # every column of this table is reader-eligible; both row groups too
    pf = pq.ParquetFile(str(path))
    assert len(metas) == pf.metadata.num_row_groups * len(cols)

    decoded = _decode_all(path, metas)
    for (g, name), seg in decoded.items():
        assert seg is not None, (g, name)
        ref = pf.read_row_group(g, columns=[name]).column(0).combine_chunks()
        assert seg.null_count == ref.null_count, (g, name)
        nv = seg.num_values
        ref_valid = ~np.asarray(ref.is_null())
        if seg.validity is not None:
            got_valid = np.unpackbits(seg.validity, bitorder="little")[:nv].astype(bool)
        else:
            got_valid = np.ones(nv, dtype=bool)
        assert np.array_equal(got_valid, ref_valid), (g, name)
        fill = False if seg.token == "bool" else 0
        ref_np = np.asarray(ref.fill_null(fill).to_numpy(zero_copy_only=False))
        if seg.token == "bool":
            got = np.unpackbits(seg.values, bitorder="little")[:nv].astype(bool)
            # null slots decode to 0 bits; compare where valid
            assert np.array_equal(got[got_valid], ref_np[got_valid]), (g, name)
        elif seg.token in ("double", "float"):
            uint = np.uint64 if seg.token == "double" else np.uint32
            a = seg.values[got_valid].view(uint)
            b = ref_np.astype(seg.values.dtype)[got_valid].view(uint)
            assert np.array_equal(a, b), (g, name)
        else:
            a = seg.values[got_valid]
            b = ref_np[got_valid].astype(seg.values.dtype)
            assert np.array_equal(a, b), (g, name)
        assert seg.pages >= 1
        assert seg.uncompressed_bytes > 0


def _one_chunk(tmp_path, name="plain", use_dictionary=True):
    """One eligible UNCOMPRESSED chunk's (raw bytes, meta)."""
    rng = np.random.default_rng(13)
    n = 2000
    table = pa.table(
        {"x": pa.array(rng.normal(size=n), mask=rng.random(n) < 0.2)}
    )
    path = tmp_path / f"{name}.parquet"
    pq.write_table(
        table,
        path,
        compression="NONE",
        data_page_size=4096,
        row_group_size=n,
        use_dictionary=use_dictionary,
    )
    metas, _ = _metas(path, ["x"])
    assert len(metas) == 1
    meta = metas[0, "x"]
    fd = os.open(str(path), os.O_RDONLY)
    try:
        raw = nr.fetch_chunk(fd, meta)
    finally:
        os.close(fd)
    assert raw is not None
    assert nr.decode_chunk(raw, meta) is not None, "healthy chunk must decode"
    return raw, meta


@requires_native
def test_decode_chunk_truncated_page_returns_none(tmp_path):
    raw, meta = _one_chunk(tmp_path)
    for cut in (0, 1, 3, len(raw) // 4, len(raw) // 2, len(raw) - 1):
        assert nr.decode_chunk(raw[:cut].copy(), meta) is None, cut


@requires_native
def test_decode_chunk_corrupt_thrift_varint_returns_none(tmp_path):
    raw, meta = _one_chunk(tmp_path)
    # a compact-Thrift varint with no terminating byte: ten 0xFF
    # continuation bytes where the page header starts
    bad = raw.copy()
    bad[: min(10, len(bad))] = 0xFF
    assert nr.decode_chunk(bad, meta) is None


@requires_native
def test_decode_chunk_oversized_uncompressed_size_returns_none(tmp_path):
    # PLAIN data page first (no dict page): the chunk begins with the
    # compact-Thrift PageHeader — field 1 (type, header byte 0x15) then
    # its varint, field 2 (uncompressed_page_size, 0x15) then its
    # varint. Splice a 5-byte ~2^34 varint in place of that size.
    raw, meta = _one_chunk(tmp_path, name="nodict", use_dictionary=False)
    assert raw[0] == 0x15
    i = 1
    while raw[i] & 0x80:
        i += 1
    i += 1  # past the type varint
    assert raw[i] == 0x15
    j = i + 1
    while raw[j] & 0x80:
        j += 1
    j += 1  # past the original uncompressed_page_size varint
    huge = np.frombuffer(b"\xff\xff\xff\xff\x7f", dtype=np.uint8)
    bad = np.concatenate([raw[: i + 1], huge, raw[j:]])
    assert nr.decode_chunk(bad, meta) is None


@requires_native
def test_decode_chunk_random_corruption_never_raises(tmp_path):
    raw, meta = _one_chunk(tmp_path)
    rng = np.random.default_rng(29)
    for trial in range(150):
        bad = raw.copy()
        if trial % 3 == 0:
            bad = bad[: int(rng.integers(0, len(bad)))].copy()
        else:
            for _ in range(int(rng.integers(1, 8))):
                bad[int(rng.integers(0, len(bad)))] = int(rng.integers(0, 256))
        if len(bad) == 0:
            bad = np.zeros(0, dtype=np.uint8)
        # must return a DecodedChunk or None — never raise, never crash
        out = nr.decode_chunk(bad, meta)
        assert out is None or isinstance(out, nr.DecodedChunk)


# ---- directed structural corruption ----
#
# Byte-wise fuzzing of a valid chunk cannot plausibly synthesize the
# multi-byte varints (bit-packed group counts ~2^58, dictionary counts
# ~2^61) that reach the int64-overflow guards in hybrid_u32 and the
# dictionary-page size check, so these chunks are crafted by hand with a
# minimal compact-Thrift emitter.


def _uvarint(v):
    out = bytearray()
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _zz(v):
    assert v >= 0
    return _uvarint(v << 1)


def _page_header(ptype, size, struct_fid, fields):
    """Compact-Thrift PageHeader: type/sizes then one nested struct whose
    int fields are all emitted as zigzag-varint i32 (ftype 5)."""
    out = bytearray()
    prev = 0
    for fid, val in ((1, ptype), (2, size), (3, size)):
        out.append(((fid - prev) << 4) | 0x05)
        out += _zz(val)
        prev = fid
    out.append(((struct_fid - prev) << 4) | 0x0C)
    sprev = 0
    for fid, val in fields:
        out.append(((fid - sprev) << 4) | 0x05)
        out += _zz(val)
        sprev = fid
    out.append(0)  # struct STOP
    out.append(0)  # PageHeader STOP
    return bytes(out)


def _dict_page(num_values, body):
    # PAGE_DICT, DictionaryPageHeader at fid 7: (num_values, PLAIN)
    return _page_header(2, len(body), 7, [(1, num_values), (2, 0)]) + body


def _dict_data_page(num_values, body):
    # PAGE_DATA, DataPageHeader at fid 5: (num_values, RLE_DICT, RLE defs)
    return _page_header(0, len(body), 5, [(1, num_values), (2, 8), (3, 3)]) + body


def _rle_defs(n):
    run = _uvarint(n << 1) + b"\x01"  # one RLE run of n ones (no nulls)
    return len(run).to_bytes(4, "little") + run


def _read_crafted(chunk_bytes, n):
    vals = np.zeros(n, dtype=np.float64)
    valid = np.zeros((n + 7) // 8, dtype=np.uint8)
    chunk = np.frombuffer(chunk_bytes, dtype=np.uint8)
    return native.read_chunk(chunk, 5, 0, 8, 1, n, vals, valid), vals, valid


@requires_native
def test_decode_chunk_crafted_control_decodes():
    # sanity for the emitter itself: a healthy hand-built chunk must
    # decode, so the corruption tests below cannot pass vacuously on an
    # unrelated parse error
    n = 8
    dict_body = np.arange(4, dtype=np.float64).tobytes()
    idx = bytes([2, 0x03, 0xE4, 0xE4])  # bw=2, 1 group: 0,1,2,3,0,1,2,3
    chunk = _dict_page(4, dict_body) + _dict_data_page(n, _rle_defs(n) + idx)
    res, vals, valid = _read_crafted(chunk, n)
    assert res is not None and res[0] == 0
    assert np.array_equal(vals, np.tile(np.arange(4.0), 2))
    assert valid[0] == 0xFF


@requires_native
def test_decode_chunk_huge_bitpacked_group_count_fails_closed(tmp_path):
    # a bit-packed hybrid header declaring ~2^58 groups at bit width 32:
    # groups*8 and groups*bw overflow int64, and an overflowed negative
    # byte count would bypass the truncation check and send unpack8 far
    # past the input buffer; the decoder must reject before multiplying
    n = 64
    dict_body = np.arange(4, dtype=np.float64).tobytes()
    for groups in (1 << 58, 1 << 60, (1 << 63) - 1):
        idx = bytes([32]) + _uvarint((groups << 1) | 1) + b"\x00" * 8
        chunk = _dict_page(4, dict_body) + _dict_data_page(
            n, _rle_defs(n) + idx
        )
        res, _, _ = _read_crafted(chunk, n)
        assert res is None, hex(groups)


@requires_native
def test_decode_chunk_huge_dict_count_fails_closed(tmp_path):
    # dict_num_values ~2^61 with an 8-byte page body: the old multiply
    # dict_num_values*src_size wrapped past int64 (to 0, 8, or negative)
    # and slipped under uncompressed_size, leaving dict_count huge so
    # every index passed validation and gathered from an empty buffer;
    # the size check must reject via division instead
    n = 8
    data_body = _rle_defs(n) + bytes([1, 0x03, 0xFF])  # bw=1, indices all 1
    for count in (1 << 61, (1 << 61) + 1, (1 << 60) + 1):
        chunk = _dict_page(count, b"\x00" * 8) + _dict_data_page(n, data_body)
        res, _, _ = _read_crafted(chunk, n)
        assert res is None, hex(count)


@requires_native
def test_fetch_chunk_short_read_returns_none(tmp_path):
    raw, meta = _one_chunk(tmp_path)
    path = tmp_path / "plain.parquet"
    size = os.path.getsize(path)
    beyond = dataclasses.replace(meta, offset=max(0, size - 8), nbytes=4096)
    fd = os.open(str(path), os.O_RDONLY)
    try:
        assert nr.fetch_chunk(fd, beyond) is None
        assert nr.fetch_chunk(fd, meta) is not None
    finally:
        os.close(fd)


def test_segment_overlaps_walk():
    def seg(nv):
        return nr.DecodedChunk(
            token="double",
            values=np.zeros(nv),
            validity=None,
            null_count=0,
            num_values=nv,
            pages=1,
            uncompressed_bytes=nv * 8,
        )
    segs = [seg(100), seg(50), seg(100)]
    assert nr._segment_overlaps(segs, 0, 100) == [(segs[0], 0, 100)]
    assert nr._segment_overlaps(segs, 90, 160) == [
        (segs[0], 90, 100),
        (segs[1], 0, 50),
        (segs[2], 0, 10),
    ]
    assert nr._segment_overlaps(segs, 150, 250) == [(segs[2], 0, 100)]
    assert nr._segment_overlaps(segs, 250, 260) == []


@requires_native
@pytest.mark.parametrize("column", ["d", "i64", "u8", "b"])
def test_assemble_column_matches_numpy_mirror(tmp_path, column):
    table = _mixed_table(n=3000, seed=17)
    path = tmp_path / "assemble.parquet"
    _write(table, path, "UNCOMPRESSED")
    metas, _ = _metas(path, [column])
    decoded = _decode_all(path, metas)
    segments = [decoded[key] for key in sorted(decoded)]
    assert all(s is not None for s in segments)
    token = segments[0].token
    total = sum(s.num_values for s in segments)
    # slices inside one group, crossing the group boundary, and full
    half = total // 2
    for start, stop in [(0, 500), (half - 250, half + 250), (0, total)]:
        got = nr.assemble_column(column, token, segments, start, stop, {})
        ref = nr._assemble_column_numpy_fallback(
            column, token, segments, start, stop
        )
        assert got is not None
        gv, rv = np.asarray(got.values), np.asarray(ref.values)
        if gv.dtype.kind == "f":
            assert np.array_equal(gv.view(np.uint64), rv.view(np.uint64))
        else:
            assert np.array_equal(gv, rv)
        assert np.array_equal(np.asarray(got.valid), np.asarray(ref.valid))


@requires_native
def test_classifier_names_the_disqualifying_property(tmp_path):
    """classify_reader_columns' falloff reasons are per-column and name
    the property that disqualified the chunk (DQ315's message body)."""
    from deequ_tpu.ops.fused import classify_reader_columns

    n = 1000
    table = pa.table(
        {
            "ok": pa.array(np.arange(n, dtype=np.float64)),
            "s": pa.array(["x"] * n),
        }
    )
    path = tmp_path / "cls.parquet"
    _write(table, path, "UNCOMPRESSED")
    src = ParquetSource(str(path))
    groups = src.row_group_stats()
    col_types = {"ok": "double", "s": "string"}
    mask = native.reader_codecs()
    cols, falloffs, n_groups = classify_reader_columns(col_types, groups, mask)
    assert cols == ["ok"]
    assert n_groups == len(groups)
    reasons = dict(falloffs)
    assert "no native page decoder" in reasons["s"]

    # codec library mask of 0 disqualifies everything, with the reason
    cols0, falloffs0, _ = classify_reader_columns(col_types, groups, 0)
    assert cols0 == []
    assert all("codec" in r or "decoder" in r for _, r in falloffs0)


def test_kill_switch_disables_reader(monkeypatch):
    monkeypatch.setenv("DEEQU_TPU_NATIVE_READER", "0")
    assert not runtime.native_reader_enabled()
    monkeypatch.setenv("DEEQU_TPU_NATIVE_READER", "1")
    assert runtime.native_reader_enabled()

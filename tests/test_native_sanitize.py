"""ASan/UBSan/TSan smoke tests for the native kernels.

Builds the C kernel with DEEQU_TPU_SANITIZE=address,undefined (ISSUE 2
satellite) or DEEQU_TPU_SANITIZE=thread (ISSUE 4 satellite) in a
subprocess (the sanitizer runtime must be LD_PRELOADed before python
starts, so an in-process test cannot work) and drives the batched
multi-family kernel through it. The TSan variant hammers the kernels
from concurrent threads — the exact shape the family worker pool and
parallel scan threads produce, since the kernels release the GIL. Any
heap overflow / UB / data race the instrumented build detects aborts
the subprocess, failing the test; environments without a
sanitizer-capable toolchain skip.
"""

from __future__ import annotations

import os
import subprocess
import sys
import tempfile

import pytest


def _sanitizer_runtime(library: str = "libasan.so"):
    """Path to a sanitizer runtime via the toolchain, or None."""
    for compiler in ("cc", "gcc"):
        try:
            out = subprocess.run(
                [compiler, f"-print-file-name={library}"],
                capture_output=True,
                text=True,
                timeout=30,
            )
        except (OSError, subprocess.SubprocessError):
            continue
        path = out.stdout.strip()
        if out.returncode == 0 and os.path.isabs(path) and os.path.exists(path):
            return path
    return None


_DRIVER = r"""
import ctypes, sys
import numpy as np
import deequ_tpu.ops.native as native

path = native._build_library()
if path is None:
    print("BUILD_UNAVAILABLE")
    sys.exit(0)
lib = native._load()
if lib is None:
    print("LOAD_UNAVAILABLE")
    sys.exit(0)
assert native.available()

rng = np.random.default_rng(7)
n = 4096
cols = []
for i in range(3):
    x = rng.random(n)
    valid = rng.random(n) > 0.05
    cols.append((x, valid, 1, None))
where = rng.random(n) > 0.3

multi = native.masked_moments_select_multi(cols, where, cap=256)
assert multi is not None and len(multi) == 3
for (x, valid, _, _), (mom, samples, n_valid, level, regs) in zip(cols, multi):
    mask = valid & where
    ref = x[mask]
    assert int(mom[0]) == ref.size == n_valid
    assert abs(mom[1] - ref.sum()) < 1e-6
    assert mom[2] == ref.min() and mom[3] == ref.max()
    solo = native.masked_moments_select(x, valid, where, cap=256, hll_mode=1)
    assert solo is not None
    assert np.array_equal(solo[0], mom)
    assert np.array_equal(solo[1], samples)
    assert np.array_equal(solo[4], regs)

# the scalar kernels too, while instrumented
vals = rng.integers(0, 1000, n)
packed = native.xxhash64_pack(vals, np.ones(n, dtype=bool))
assert packed is not None and packed.shape == (n,)
counts = native.bincount(vals.astype(np.int64), 1000)
assert counts is not None and counts.sum() == n

# decode kernels on SLICED arrays: slice offsets put the validity scan
# at a non-byte-aligned bit position and the row count ends mid-byte,
# the exact shapes where an off-by-one reads past the bitmap
import pyarrow as pa

f = pa.array([float(i) if i % 7 else None for i in range(1001)]).slice(3, 900)
out_v = np.empty(len(f), dtype=np.float64)
out_m = np.empty(len(f), dtype=np.bool_)
bufs = f.buffers()
rc = native.decode_primitive(
    "double", bufs[1].address + f.offset * 8, bufs[0].address,
    f.offset, len(f), out_v, out_m,
)
assert rc == sum(v is None for v in f.to_pylist())
assert [v if m else None for v, m in zip(out_v, out_m)] == f.to_pylist()

b = pa.array([bool(i % 3) if i % 5 else None for i in range(997)]).slice(6, 901)
out_b = np.empty(len(b), dtype=np.bool_)
out_bm = np.empty(len(b), dtype=np.bool_)
bb = b.buffers()
rc = native.decode_bool_bitmap(
    bb[1].address, b.offset, bb[0].address, b.offset, len(b), out_b, out_bm
)
assert rc == sum(v is None for v in b.to_pylist())
assert [bool(v) if m else None for v, m in zip(out_b, out_bm)] == b.to_pylist()

d = pa.array(
    ["abc", None, "de", "abc", "f"] * 201
).dictionary_encode().slice(2, 1000)
idx = d.indices
out_c = np.empty(len(idx), dtype=np.int32)
out_cm = np.empty(len(idx), dtype=np.bool_)
ib = idx.buffers()
rc = native.decode_dict_codes(
    ib[1].address + idx.offset * 4, ib[0].address, idx.offset,
    len(idx), out_c, out_cm,
)
assert rc == d.null_count
assert all(c == -1 for c, m in zip(out_c, out_cm) if not m)

# decode-to-wire kernels on the same sliced odd-offset shapes: the
# bitpacked output lands MID-BYTE (odd out_bit_offset) and the row
# count ends off a byte boundary — exactly where an off-by-one reads
# past the validity bitmap or writes past the mask tail
wb = np.zeros(128, dtype=np.uint8)
wv = np.zeros(len(f), dtype=np.float64)
rcw = native.wire_primitive(
    "double", bufs[1].address + f.offset * 8, bufs[0].address,
    f.offset, len(f), 0.0, wv, wb, 5,
)
assert rcw == sum(v is None for v in f.to_pylist())
wm = np.unpackbits(wb, count=5 + len(f))[5:].astype(bool)
assert [v if m else None for v, m in zip(wv, wm)] == f.to_pylist()

wv32 = np.zeros(len(f), dtype=np.float32)
wb32 = np.zeros(128, dtype=np.uint8)
rcs = native.wire_primitive(
    "double", bufs[1].address + f.offset * 8, bufs[0].address,
    f.offset, len(f), 500.25, wv32, wb32, 3,
)
assert rcs == rcw

ia = pa.array(
    [i % 120 if i % 4 else None for i in range(1003)], type=pa.int64()
).slice(7, 900)
iab = ia.buffers()
wvi = np.zeros(len(ia), dtype=np.int8)
wbi = np.zeros(128, dtype=np.uint8)
rci = native.wire_primitive(
    "int64", iab[1].address + ia.offset * 8, iab[0].address,
    ia.offset, len(ia), 0.0, wvi, wbi, 1,
)
assert rci == sum(v is None for v in ia.to_pylist())
im = np.unpackbits(wbi, count=1 + len(ia))[1:].astype(bool)
assert [int(v) if m else None for v, m in zip(wvi, im)] == ia.to_pylist()

wbv = np.zeros(128, dtype=np.uint8)
rcv = native.wire_valid_bits(iab[0].address, ia.offset, len(ia), wbv, 9)
assert rcv == rci

# native parquet page decode (parquet_read.c) while instrumented: a
# real column chunk (Thrift headers, dict + data pages) decoded into
# arrow-layout buffers, then truncated and bit-flipped variants which
# must fail cleanly without reading or writing out of bounds
import os as _os
import tempfile as _tempfile

import pyarrow.parquet as _pq

_n = 3000
_tbl = pa.table({"x": pa.array([float(i) if i % 7 else None for i in range(_n)])})
_tmp = _tempfile.mkstemp(suffix=".parquet")[1]
_pq.write_table(
    _tbl, _tmp, compression="NONE", data_page_size=1024, row_group_size=_n
)
_md = _pq.ParquetFile(_tmp).metadata
_ch = _md.row_group(0).column(0)
_start = _ch.data_page_offset
if _ch.has_dictionary_page and _ch.dictionary_page_offset is not None:
    _start = min(_start, _ch.dictionary_page_offset)
with open(_tmp, "rb") as _f:
    _f.seek(_start)
    _chunk = np.frombuffer(_f.read(_ch.total_compressed_size), dtype=np.uint8)
_os.unlink(_tmp)
_vals = np.zeros(_n, dtype=np.float64)
_valid = np.zeros((_n + 7) // 8, dtype=np.uint8)
res = native.read_chunk(_chunk, 5, 0, 8, 1, _n, _vals, _valid)
assert res is not None and res[0] == _tbl.column("x").null_count
_rngc = np.random.default_rng(23)
for _t in range(60):
    _bad = _chunk.copy()
    if _t % 2:
        _bad = _bad[: int(_rngc.integers(0, len(_bad)))].copy()
    else:
        for _ in range(4):
            _bad[int(_rngc.integers(0, len(_bad)))] = int(_rngc.integers(0, 256))
    _vals[:] = 0
    _valid[:] = 0
    native.read_chunk(_bad, 5, 0, 8, 1, _n, _vals, _valid)

# runs-mode decode (pq_decode_chunk_runs) while instrumented: the same
# chunk as coalesced (run_length, dict_code) + definition-level runs,
# folded by the encfold kernels, then corrupt-run streams and
# truncated/bit-flipped chunk variants which must fail closed without
# reading or writing out of bounds
_rr = native.read_chunk_runs(_chunk, 5, 0, 1, _n)
assert _rr is not None
_draw, _rl, _rcodes, _dl, _dv, _rnulls, _rpg, _rub, _dc = _rr
assert _rnulls == _tbl.column("x").null_count
_cnts = native.encfold_code_counts(_rl, _rcodes, _dc)
assert _cnts is not None and int(_cnts.sum()) == _n - _rnulls
assert native.encfold_def_nulls(_dl, _dv, _n) == _rnulls
_bad_rl = _rl.copy(); _bad_rl[0] = -3
assert native.encfold_code_counts(_bad_rl, _rcodes, _dc) is None
_bad_rc = _rcodes.copy(); _bad_rc[0] = _dc + 7
assert native.encfold_code_counts(_rl, _bad_rc, _dc) is None
assert native.encfold_def_nulls(_dl, _dv, _n + 1) is None
for _t in range(60):
    _bad = _chunk.copy()
    if _t % 2:
        _bad = _bad[: int(_rngc.integers(0, len(_bad)))].copy()
    else:
        for _ in range(4):
            _bad[int(_rngc.integers(0, len(_bad)))] = int(_rngc.integers(0, 256))
    native.read_chunk_runs(_bad, 5, 0, 1, _n)

# directed structural corruption while instrumented: extreme multi-byte
# varints that byte-wise fuzzing cannot synthesize. A bit-packed group
# count ~2^58 at bit width 32 and a dictionary count ~2^61 each used to
# overflow int64 size math and read out of bounds; both must now fail
# closed with no sanitizer report.
def _uv(v):
    out = bytearray()
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)

def _hdr(ptype, size, sfid, fields):
    out = bytearray()
    prev = 0
    for fid, val in ((1, ptype), (2, size), (3, size)):
        out.append(((fid - prev) << 4) | 0x05)
        out += _uv(val << 1)
        prev = fid
    out.append(((sfid - prev) << 4) | 0x0C)
    sprev = 0
    for fid, val in fields:
        out.append(((fid - sprev) << 4) | 0x05)
        out += _uv(val << 1)
        sprev = fid
    return bytes(out) + b"\x00\x00"

_drun = _uv(8 << 1) + b"\x01"
_defs8 = len(_drun).to_bytes(4, "little") + _drun
_dictb = np.arange(4, dtype=np.float64).tobytes()
_idx_huge = bytes([32]) + _uv(((1 << 58) << 1) | 1) + b"\x00" * 8
_body_a = _defs8 + _idx_huge
_body_b = _defs8 + bytes([1, 0x03, 0xFF])
for _evil in [
    _hdr(2, len(_dictb), 7, [(1, 4), (2, 0)]) + _dictb
    + _hdr(0, len(_body_a), 5, [(1, 8), (2, 8), (3, 3)]) + _body_a,
    _hdr(2, 8, 7, [(1, 1 << 61), (2, 0)]) + b"\x00" * 8
    + _hdr(0, len(_body_b), 5, [(1, 8), (2, 8), (3, 3)]) + _body_b,
]:
    _ev = np.frombuffer(_evil, dtype=np.uint8)
    _vals8 = np.zeros(8, dtype=np.float64)
    _valid8 = np.zeros(1, dtype=np.uint8)
    assert native.read_chunk(_ev, 5, 0, 8, 1, 8, _vals8, _valid8) is None
    assert native.read_chunk_runs(_ev, 5, 0, 1, 8) is None
print("SANITIZED_OK")
"""


def test_sanitized_build_runs_clean():
    runtime = _sanitizer_runtime()
    if runtime is None:
        pytest.skip("no sanitizer-capable toolchain")

    with tempfile.TemporaryDirectory() as cache:
        env = dict(os.environ)
        env.update(
            {
                "DEEQU_TPU_SANITIZE": "address,undefined",
                "DEEQU_TPU_CACHE_DIR": cache,
                "LD_PRELOAD": runtime,
                # python itself leaks by sanitizer standards; we only
                # care about the kernel's memory errors, not exit leaks
                "ASAN_OPTIONS": "detect_leaks=0,abort_on_error=1",
                "JAX_PLATFORMS": "cpu",
            }
        )
        env.pop("DEEQU_TPU_NO_NATIVE", None)
        proc = subprocess.run(
            [sys.executable, "-c", _DRIVER],
            capture_output=True,
            text=True,
            timeout=300,
            env=env,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        )
        if "BUILD_UNAVAILABLE" in proc.stdout or "LOAD_UNAVAILABLE" in proc.stdout:
            pytest.skip("sanitized native build unavailable in this environment")
        assert proc.returncode == 0, (
            f"sanitized run failed (rc={proc.returncode})\n"
            f"stdout:\n{proc.stdout}\nstderr:\n{proc.stderr}"
        )
        assert "SANITIZED_OK" in proc.stdout


def test_sanitize_flags_parse():
    from deequ_tpu.ops.native import _sanitize_flags

    old = os.environ.pop("DEEQU_TPU_SANITIZE", None)
    try:
        assert _sanitize_flags() == []
        os.environ["DEEQU_TPU_SANITIZE"] = "address,undefined"
        flags = _sanitize_flags()
        assert "-fsanitize=address,undefined" in flags
        assert "-g" in flags
        os.environ["DEEQU_TPU_SANITIZE"] = "  "
        assert _sanitize_flags() == []
    finally:
        if old is not None:
            os.environ["DEEQU_TPU_SANITIZE"] = old
        else:
            os.environ.pop("DEEQU_TPU_SANITIZE", None)


_TSAN_DRIVER = r"""
import sys
from concurrent.futures import ThreadPoolExecutor

import numpy as np

import deequ_tpu.ops.native as native

path = native._build_library()
if path is None:
    print("BUILD_UNAVAILABLE")
    sys.exit(0)
lib = native._load()
if lib is None:
    print("LOAD_UNAVAILABLE")
    sys.exit(0)
assert native.available()

rng = np.random.default_rng(11)
n = 8192
N_THREADS = 4
ROUNDS = 8

# per-thread inputs: the kernels must be race-free even when every
# thread traverses its OWN arrays concurrently (thread-local arenas),
# and when two threads share the SAME read-only input (the family pool
# dispatches same-batch groups concurrently)
shared_x = rng.random(n)
shared_valid = rng.random(n) > 0.05
shared_where = rng.random(n) > 0.3

# one shared sliced arrow chunk decoded by every thread — the decode
# worker pool's shape (threads share the arrow buffers, write disjoint
# outputs)
import pyarrow as pa
shared_arrow = pa.array(
    [float(i) if i % 9 else None for i in range(n + 11)]
).slice(5, n)
_ab = shared_arrow.buffers()

# decode-to-wire concurrency shape: every thread reads the SAME arrow
# buffers and packs its own disjoint byte-aligned slice of one shared
# prezeroed bitmask (in the engine each batch's wire buffers have a
# single writer; the sharing under test is the read side + disjoint
# output bytes)
N_SEG = n // N_THREADS  # byte-aligned: n and N_THREADS are powers of 2
shared_wire_bits = np.zeros(n // 8, dtype=np.uint8)

# one shared raw parquet chunk every thread page-decodes concurrently —
# the native reader's decode-worker shape (threads share the chunk
# bytes read-only, each writes its own output buffers)
import os as _os
import tempfile as _tempfile

import pyarrow.parquet as _pq

_cn = 2000
_ctbl = pa.table(
    {"x": pa.array([float(i) if i % 7 else None for i in range(_cn)])}
)
_ctmp = _tempfile.mkstemp(suffix=".parquet")[1]
_pq.write_table(
    _ctbl, _ctmp, compression="NONE", data_page_size=1024, row_group_size=_cn
)
_cch = _pq.ParquetFile(_ctmp).metadata.row_group(0).column(0)
_cstart = _cch.data_page_offset
if _cch.has_dictionary_page and _cch.dictionary_page_offset is not None:
    _cstart = min(_cstart, _cch.dictionary_page_offset)
with open(_ctmp, "rb") as _cf:
    _cf.seek(_cstart)
    shared_chunk = np.frombuffer(
        _cf.read(_cch.total_compressed_size), dtype=np.uint8
    )
_os.unlink(_ctmp)
shared_chunk_nulls = _ctbl.column("x").null_count

def work(seed):
    r = np.random.default_rng(seed)
    x = r.random(n)
    valid = r.random(n) > 0.05
    where = r.random(n) > 0.3
    for _ in range(ROUNDS):
        own = native.masked_moments_select(x, valid, where, cap=256, hll_mode=1)
        assert own is not None
        cols = [(x, valid, 1, None), (shared_x, shared_valid, 1, None)]
        multi = native.masked_moments_select_multi(cols, where, cap=256)
        assert multi is None or len(multi) == 2
        sh = native.masked_moments_select(
            shared_x, shared_valid, shared_where, cap=128
        )
        assert sh is not None
        vals = r.integers(0, 500, n)
        packed = native.xxhash64_pack(vals, np.ones(n, dtype=bool))
        assert packed is not None
        counts = native.bincount(vals.astype(np.int64), 500)
        assert counts is not None and counts.sum() == n
        dv = np.empty(len(shared_arrow), dtype=np.float64)
        dm = np.empty(len(shared_arrow), dtype=np.bool_)
        rc = native.decode_primitive(
            "double", _ab[1].address + shared_arrow.offset * 8,
            _ab[0].address, shared_arrow.offset, len(shared_arrow), dv, dm,
        )
        assert rc == shared_arrow.null_count
        off = seed * N_SEG
        wv = np.zeros(N_SEG, dtype=np.float64)
        rcw = native.wire_primitive(
            "double", _ab[1].address + (shared_arrow.offset + off) * 8,
            _ab[0].address, shared_arrow.offset + off, N_SEG, 0.0, wv,
            shared_wire_bits, off,
        )
        assert rcw is not None and rcw >= 0
        cv = np.zeros(_cn, dtype=np.float64)
        cb = np.zeros((_cn + 7) // 8, dtype=np.uint8)
        cres = native.read_chunk(shared_chunk, 5, 0, 8, 1, _cn, cv, cb)
        assert cres is not None and cres[0] == shared_chunk_nulls
    # deterministic reference: same shared inputs -> same moments
    mom = native.masked_moments_select(
        shared_x, shared_valid, shared_where, cap=128
    )[0]
    return tuple(mom[:4])

with ThreadPoolExecutor(max_workers=N_THREADS) as pool:
    results = list(pool.map(work, range(N_THREADS)))
assert len(set(results)) == 1, "concurrent runs diverged: " + repr(results)
expected_mask = np.array(shared_arrow.is_valid())
assert np.array_equal(
    np.unpackbits(shared_wire_bits, count=n).astype(bool), expected_mask
), "shared wire bitmask diverged from the validity reference"
print("TSAN_OK")
"""


def test_tsan_build_runs_clean_multithreaded():
    """DEEQU_TPU_SANITIZE=thread: the kernels driven concurrently from
    multiple threads under ThreadSanitizer — the native layer's
    concurrency contract (GIL-released kernels, thread-local arenas,
    read-only shared inputs) checked by the instrument, not by luck."""
    runtime = _sanitizer_runtime("libtsan.so")
    if runtime is None:
        pytest.skip("no TSan-capable toolchain")

    with tempfile.TemporaryDirectory() as cache:
        env = dict(os.environ)
        env.update(
            {
                "DEEQU_TPU_SANITIZE": "thread",
                "DEEQU_TPU_CACHE_DIR": cache,
                "LD_PRELOAD": runtime,
                # only the kernel's races matter; halt hard when one is
                # found so the assertion below cannot miss it
                "TSAN_OPTIONS": "halt_on_error=1,exitcode=66",
                "JAX_PLATFORMS": "cpu",
            }
        )
        env.pop("DEEQU_TPU_NO_NATIVE", None)
        proc = subprocess.run(
            [sys.executable, "-c", _TSAN_DRIVER],
            capture_output=True,
            text=True,
            timeout=300,
            env=env,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        )
        if "BUILD_UNAVAILABLE" in proc.stdout or "LOAD_UNAVAILABLE" in proc.stdout:
            pytest.skip("TSan native build unavailable in this environment")
        assert proc.returncode == 0, (
            f"TSan run failed (rc={proc.returncode})\n"
            f"stdout:\n{proc.stdout}\nstderr:\n{proc.stderr}"
        )
        assert "TSAN_OK" in proc.stdout
        assert "WARNING: ThreadSanitizer" not in proc.stderr

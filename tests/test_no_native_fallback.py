"""The numpy fallbacks must produce the same results as the C kernels.

Every native entry point returns None when the library is unavailable
and callers fall back to numpy (`ops/native/__init__.py` docstring
promises identical results) — but nothing exercised that configuration
end-to-end. These tests simulate an image without a C compiler by
pinning the loader to "unavailable" and compare whole-profile and
analyzer outputs against the native run.
"""

from __future__ import annotations

import numpy as np
import pytest

from deequ_tpu.ops import native


@pytest.fixture
def no_native(monkeypatch):
    """Simulate `cc` missing: the loader reports unavailable for the
    rest of the test (module globals restored by monkeypatch)."""
    monkeypatch.setattr(native, "_TRIED", True)
    monkeypatch.setattr(native, "_LIB", None)
    assert not native.available()


needs_native = pytest.mark.skipif(
    not native.available(), reason="native kernels unavailable anyway"
)


@needs_native
def test_profile_identical_without_native(no_native, monkeypatch):
    # order matters: the FALLBACK profile runs first under the fixture's
    # no-native pins, then the pins are overwritten (not restored) so
    # the reference profile runs with the real C kernels
    from deequ_tpu.data.table import Table
    from deequ_tpu.profiles.column_profiler import ColumnProfiler

    rng = np.random.default_rng(21)
    n = 40_000
    price = rng.lognormal(1.0, 0.5, n)
    price[rng.random(n) < 0.05] = np.nan
    qty = rng.integers(1, 60, n).astype(np.int64)
    code = np.array([str(v) for v in rng.integers(0, 400, n)], dtype=object)
    cat = np.array(["a", "b", "c"], dtype=object)[rng.integers(0, 3, n)]

    def build():
        return Table.from_numpy(
            {
                "qty": qty.copy(),
                "price": price.copy(),
                "code": code.copy(),
                "cat": cat.copy(),
            }
        )

    fallback = ColumnProfiler.profile(build()).profiles

    # undo the fixture's pins for the reference run
    monkeypatch.setattr(native, "_TRIED", False)
    monkeypatch.setattr(native, "_LIB", None)
    assert native.available()
    with_native = ColumnProfiler.profile(build()).profiles

    assert fallback.keys() == with_native.keys()
    for name in fallback:
        f, w = fallback[name], with_native[name]
        assert f.completeness == w.completeness, name
        assert f.data_type == w.data_type, name
        assert f.type_counts == w.type_counts, name
        assert f.approximate_num_distinct_values == (
            w.approximate_num_distinct_values
        ), name
        if getattr(f, "mean", None) is not None:
            assert f.mean == pytest.approx(w.mean, rel=1e-12), name
            assert f.minimum == w.minimum and f.maximum == w.maximum, name
            assert f.std_dev == pytest.approx(w.std_dev, rel=1e-9), name
            for fv, wv in zip(
                f.approx_percentiles or [], w.approx_percentiles or []
            ):
                assert fv == pytest.approx(wv, rel=1e-9, abs=1e-12), name
        hf, hw = f.histogram, w.histogram
        assert (hf is None) == (hw is None), name
        if hf is not None:
            assert hf.values == hw.values, name


@needs_native
def test_kernel_wrappers_return_none_without_native(no_native):
    ones = np.ones(128, dtype=bool)
    assert native.xxhash64_pack(np.arange(128, dtype=np.int64), ones) is None
    assert native.masked_moments(np.ones(128), ones, None) is None
    assert native.bincount(np.zeros(128, dtype=np.int64), 4) is None
    assert (
        native.bincount_window(
            np.zeros(128, dtype=np.int64), None, None, 0, 16
        )
        is None
    )
    assert (
        native.masked_moments_select(np.ones(128), ones, None, 16) is None
    )
    from deequ_tpu.ops import counts_family

    # the counts fast path degrades to None (select fallback), never raises
    assert (
        counts_family.counts_for_column(
            np.arange(128, dtype=np.int64), None, None
        )
        is None
    )

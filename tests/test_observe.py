"""Observability subsystem (deequ_tpu.observe) tests — ISSUE 3.

Covers the trace primitives (no-op fast path, span nesting, thread
isolation + worker attachment), Chrome-trace export schema (B/E nesting
discipline, required fields, multihost merge), the golden run report,
counter parity with ExecutionStats (bit-for-bit), the family-kernel
span-per-(where, cap, dtype) invariant, and the differential guarantee
that tracing never changes metric values.
"""

from __future__ import annotations

import json
import threading

import numpy as np
import pytest

from deequ_tpu import observe
from deequ_tpu.data.table import Table
from deequ_tpu.observe.spans import _NOOP, Span
from deequ_tpu.ops import native, runtime

needs_native = pytest.mark.skipif(
    not native.available(), reason="native kernels unavailable"
)


def _small_table(n=4096, seed=0):
    rng = np.random.default_rng(seed)
    return Table.from_numpy(
        {
            "x": rng.standard_normal(n),
            "y": rng.random(n) * 100.0,
            "flag": rng.random(n) < 0.5,
        }
    )


def _scan_analyzers():
    from deequ_tpu.analyzers import Maximum, Mean, Minimum, StandardDeviation

    return [Mean("x"), StandardDeviation("x"), Minimum("y"), Maximum("y")]


def _run_analysis(table, tracing=None):
    from deequ_tpu.runners import AnalysisRunner

    builder = AnalysisRunner.on_data(table).add_analyzers(_scan_analyzers())
    if tracing is not None:
        builder = builder.with_tracing(tracing)
    return builder.run()


# -- no-op fast path ---------------------------------------------------------


class TestNoopFastPath:
    def test_span_returns_falsy_singleton_when_untraced(self):
        sp = observe.span("anything", cat="dispatch", rows=7)
        assert sp is _NOOP
        assert not sp
        with sp as inner:
            assert inner is _NOOP
        # inert attribute surface
        assert sp.set(rows=1) is _NOOP
        assert sp.add("rows", 1) is _NOOP

    def test_annotate_and_counters_safe_when_untraced(self):
        observe.annotate(rows=1)  # must not raise
        assert observe.current_tracer() is None
        assert observe.current_span() is None

    def test_traced_run_disabled_yields_falsy_handle(self):
        with observe.traced_run("run", enable=False) as handle:
            assert not handle
            assert observe.span("x") is _NOOP
        assert handle.trace is None


# -- span tree ---------------------------------------------------------------


class TestSpanTree:
    def test_nesting_and_attrs(self):
        with observe.tracing() as tracer:
            with observe.span("outer", cat="scan") as outer:
                with observe.span("inner", cat="dispatch", rows=3) as inner:
                    observe.annotate(extra=1)
        assert tracer.roots == [outer]
        assert outer.children == [inner]
        assert inner.attrs == {"rows": 3, "extra": 1}
        assert inner.t0 >= outer.t0
        assert inner.t1 <= outer.t1 or inner.duration_s <= outer.duration_s

    def test_error_annotated_on_exception(self):
        with observe.tracing() as tracer:
            with pytest.raises(ValueError):
                with observe.span("boom"):
                    raise ValueError("x")
        assert tracer.roots[0].attrs["error"] == "ValueError"

    def test_tracer_count_lands_on_current_span(self):
        with observe.tracing() as tracer:
            with observe.span("s") as sp:
                tracer.count("device_passes", label="p1")
                tracer.count("device_passes")
        assert tracer.counters == {"device_passes": 2}
        assert tracer.labels == ["p1"]
        assert sp.attrs["device_passes"] == 2

    def test_attached_adopts_dispatcher_context(self):
        results = {}

        def worker(tracer, parent):
            with observe.attached(tracer, parent):
                with observe.span("worker_span", cat="dispatch") as sp:
                    results["span"] = sp

        with observe.tracing() as tracer:
            with observe.span("dispatcher") as parent:
                t = threading.Thread(
                    target=worker,
                    args=(observe.current_tracer(), observe.current_span()),
                )
                t.start()
                t.join()
        assert results["span"] in parent.children
        # worker thread gets its own tid for the exporter
        assert results["span"].tid != parent.tid

    def test_attached_none_is_noop(self):
        with observe.attached(None, None):
            assert observe.span("x") is _NOOP


# -- thread isolation (satellite: two monitored scans on two threads) --------


class TestThreadLocalIsolation:
    def test_two_monitored_scans_on_separate_threads(self):
        table = _small_table()
        _run_analysis(table)  # warm up compilation outside the threads

        barrier = threading.Barrier(2)
        out = {}

        def scan(tag, reps):
            with runtime.monitored() as stats:
                barrier.wait(timeout=30)
                for _ in range(reps):
                    _run_analysis(_small_table(seed=hash(tag) % 100))
            out[tag] = stats

        t_a = threading.Thread(target=scan, args=("a", 2))
        t_b = threading.Thread(target=scan, args=("b", 1))
        t_a.start(), t_b.start()
        t_a.join(), t_b.join()

        # each thread's stats count ONLY its own passes — no cross-talk
        # through the thread-local sink stack
        assert out["a"].device_passes == 2
        assert out["b"].device_passes == 1
        assert len(out["a"].pass_labels) == 2
        assert len(out["b"].pass_labels) == 1

    def test_tracing_is_thread_local(self):
        seen = {}

        def other():
            seen["tracer"] = observe.current_tracer()
            seen["span"] = observe.span("x")

        with observe.tracing():
            with observe.span("main"):
                t = threading.Thread(target=other)
                t.start()
                t.join()
        assert seen["tracer"] is None
        assert seen["span"] is _NOOP


# -- Chrome-trace export schema ----------------------------------------------


def _check_event_schema(doc):
    events = doc["traceEvents"]
    assert doc["displayTimeUnit"] == "ms"
    assert "process_index" in doc["metadata"]
    stacks = {}
    saw_meta = False
    for event in events:
        assert event["ph"] in ("B", "E", "M")
        if event["ph"] == "M":
            saw_meta = True
            assert event["name"] == "process_name"
            continue
        for field in ("ts", "pid", "tid", "name"):
            assert field in event, (field, event)
        assert isinstance(event["ts"], float) and event["ts"] >= 0.0
        stack = stacks.setdefault((event["pid"], event["tid"]), [])
        if event["ph"] == "B":
            assert "args" in event and "cpu_ms" in event["args"]
            stack.append((event["name"], event["ts"]))
        else:
            name, begin_ts = stack.pop()  # E must close the innermost B
            assert name == event["name"]
            assert event["ts"] >= begin_ts
    assert saw_meta
    assert all(not stack for stack in stacks.values()), "unclosed B events"


class TestChromeTraceExport:
    def test_traced_verification_run_schema(self):
        from deequ_tpu.checks.check import Check, CheckLevel
        from deequ_tpu.verification.suite import VerificationSuite

        check = (
            Check(CheckLevel.ERROR, "basics")
            .is_complete("x")
            .has_min("y", lambda v: v >= 0.0)
        )
        result = (
            VerificationSuite.on_data(_small_table())
            .add_check(check)
            .with_tracing(True)
            .run()
        )
        trace = result.run_trace
        assert trace is not None
        doc = trace.to_chrome_trace()
        _check_event_schema(doc)
        json.loads(json.dumps(doc))  # valid JSON end to end
        names = {e["name"] for e in doc["traceEvents"] if e["ph"] == "B"}
        assert {"verification_suite", "analysis_run", "constraint_eval"} <= names
        assert {"plan_validate", "plan_fuse", "fused_scan"} <= names

    def test_write_and_reload(self, tmp_path):
        path = str(tmp_path / "trace.json")
        ctx = _run_analysis(_small_table(), tracing=path)
        assert ctx.run_trace.path == path
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
        _check_event_schema(doc)

    def test_merge_chrome_traces_repids_collisions(self, tmp_path):
        root_a, root_b = Span("run_a"), Span("run_b")
        for root in (root_a, root_b):
            root.t0, root.t1 = 0.0, 0.001
        path_a = observe.write_chrome_trace(str(tmp_path / "a.json"), [root_a])
        path_b = observe.write_chrome_trace(str(tmp_path / "b.json"), [root_b])
        out = str(tmp_path / "merged.json")
        merged = observe.merge_chrome_traces([path_a, path_b], out)
        pids = {e["pid"] for e in merged["traceEvents"]}
        assert len(pids) == 2  # same recorded index, re-pidded apart
        with open(out, encoding="utf-8") as f:
            assert len(json.load(f)["metadata"]["merged_from"]) == 2

    def test_env_knob(self, tmp_path, monkeypatch):
        out = str(tmp_path / "env_trace.json")
        monkeypatch.setenv(observe.ENV_KNOB, "1")
        monkeypatch.setenv(observe.ENV_OUT, out)
        ctx = _run_analysis(_small_table())  # tracing=None → env decides
        assert ctx.run_trace is not None
        with open(out, encoding="utf-8") as f:
            _check_event_schema(json.load(f))

    @pytest.mark.parametrize("value", ["", "0", "false", "off", "no"])
    def test_env_knob_falsey(self, value, monkeypatch):
        monkeypatch.setenv(observe.ENV_KNOB, value)
        assert not observe.env_enabled()
        ctx = _run_analysis(_small_table())
        assert ctx.run_trace is None


# -- golden run report --------------------------------------------------------


def _mk_span(name, cat, t0, t1, cpu=None, **attrs):
    s = Span(name, cat, attrs)
    s.t0, s.t1 = t0, t1
    s.cpu0, s.cpu1 = 0.0, (cpu if cpu is not None else 0.0)
    return s


def _golden_forest():
    root = _mk_span("analysis_run", "run", 0.0, 0.1, cpu=0.08, analyzers=3)
    plan = _mk_span("plan_fuse", "plan", 0.0, 0.01)
    scan = _mk_span("fused_scan", "scan", 0.01, 0.09)
    scan.children += [
        _mk_span("dispatch", "dispatch", 0.01, 0.03, rows=500),
        _mk_span("dispatch", "dispatch", 0.03, 0.05, rows=500),
        _mk_span("transfer", "transfer", 0.05, 0.07, bytes=1024),
        _mk_span("merge", "merge", 0.07, 0.08),
    ]
    root.children += [plan, scan]
    return root


GOLDEN_REPORT = (
    "deequ_tpu run report — analysis_run\n"
    "wall 100.0 ms | cpu 80.0 ms | device_passes 1\n"
    "analysis_run                                    100.0 ms  analyzers=3\n"
    "├─ plan_fuse                                     10.0 ms  [plan]\n"
    "└─ fused_scan                                    80.0 ms  [scan]\n"
    "   ├─ dispatch ×2                                40.0 ms  [dispatch]\n"
    "   ├─ transfer                                   20.0 ms  [transfer]  bytes=1024\n"
    "   └─ merge                                      10.0 ms  [merge]\n"
    "phases (self-time): dispatch 0.040s | transfer 0.020s | run 0.010s"
    " | plan 0.010s | merge 0.010s | scan 0.010s"
)


class TestRunReport:
    def test_golden_rendering(self):
        out = observe.render_report(
            [_golden_forest()], counters={"device_passes": 1}
        )
        assert out == GOLDEN_REPORT

    def test_phase_seconds_buckets_are_disjoint_self_time(self):
        phases = observe.phase_seconds([_golden_forest()])
        for phase in observe.PHASES:
            assert phase in phases
        assert phases["dispatch"] == pytest.approx(0.04)
        assert phases["transfer"] == pytest.approx(0.02)
        # disjoint self-times sum to the root's wall time
        assert sum(phases.values()) == pytest.approx(0.1)

    def test_empty_forest(self):
        assert "no spans" in observe.render_report([])

    def test_live_run_report_renders(self):
        ctx = _run_analysis(_small_table(), tracing=True)
        text = ctx.run_trace.report()
        assert text.startswith("deequ_tpu run report — analysis_run")
        assert "device_passes 1" in text
        assert "phases (self-time):" in text


# -- counter parity with ExecutionStats (bit-for-bit) -------------------------


class TestCounterParity:
    def test_trace_counters_match_execution_stats(self):
        with runtime.monitored() as stats:
            ctx = _run_analysis(_small_table(), tracing=True)
        trace = ctx.run_trace
        assert trace.counters.get("device_passes", 0) == stats.device_passes
        assert trace.counters.get("device_launches", 0) == stats.device_launches
        assert trace.counters.get("group_passes", 0) == stats.group_passes
        # ...and the run root span carries the same deltas as attributes
        for key, value in trace.counters.items():
            assert trace.root.attrs[key] == value

    def test_grouping_counts_match(self):
        from deequ_tpu.analyzers import Uniqueness
        from deequ_tpu.runners import AnalysisRunner

        table = Table.from_pydict(
            {"att1": ["a", "b", "a", "c", "b", "a"]}
        )
        with runtime.monitored() as stats:
            ctx = (
                AnalysisRunner.on_data(table)
                .add_analyzer(Uniqueness(["att1"]))
                .with_tracing(True)
                .run()
            )
        assert stats.group_passes == 1
        assert ctx.run_trace.counters.get("group_passes", 0) == 1
        names = {s.name for s in ctx.run_trace.spans()}
        assert {"grouping", "group_pass", "freq_agg"} <= names


# -- one family_kernel dispatch per (where, cap, dtype) group -----------------


@needs_native
class TestFamilyKernelSpans:
    def test_one_span_per_family_group(self, monkeypatch):
        monkeypatch.setenv("DEEQU_TPU_PLACEMENT", "host")
        from deequ_tpu.analyzers import (
            ApproxCountDistinct,
            ApproxQuantile,
            ApproxQuantiles,
            Mean,
            StandardDeviation,
        )
        from deequ_tpu.runners import AnalysisRunner

        rng = np.random.default_rng(7)
        n = 200_000  # family kernels only engage on high-cardinality cols
        table = Table.from_numpy(
            {
                "a": rng.lognormal(1.0, 0.7, n),
                "b": rng.random(n) * 1000.0,
                "c": rng.standard_normal(n) * 50.0,
                "flag": rng.random(n) < 0.5,
            }
        )
        analyzers = []
        for col in ("a", "b", "c"):
            analyzers += [
                ApproxQuantiles(col, (0.25, 0.5, 0.75)),
                Mean(col),
                StandardDeviation(col),
                ApproxCountDistinct(col),
            ]
        analyzers.append(ApproxQuantile("a", 0.5, where="flag"))
        with runtime.monitored() as stats:
            ctx = (
                AnalysisRunner.on_data(table)
                .add_analyzers(analyzers)
                .with_tracing(True)
                .run()
            )
        fams = [
            s for s in ctx.run_trace.spans() if s.name == "family_kernel"
        ]
        keys = [
            (s.attrs["where"], s.attrs["cap"], s.attrs["dtype"])
            for s in fams
        ]
        # exactly ONE kernel dispatch span per (where, cap, dtype) family
        assert len(keys) == len(set(keys))
        wheres = {k[0] for k in keys}
        assert wheres == {"where:<all>", "where:flag"}
        batched = {s.attrs["where"]: s.attrs for s in fams}
        assert batched["where:<all>"]["columns"] == 3
        assert batched["where:<all>"]["batched"] is True
        assert batched["where:flag"]["columns"] == 1
        # the whole multi-family run is still ONE fused scan pass
        assert stats.device_passes == 1
        assert ctx.run_trace.counters["device_passes"] == 1


# -- differential: tracing never changes metric values ------------------------


class TestTracingIsInert:
    def test_metrics_bit_identical_with_and_without_tracing(self):
        from deequ_tpu.analyzers import (
            Completeness,
            Maximum,
            Mean,
            Minimum,
            StandardDeviation,
            Uniqueness,
        )
        from deequ_tpu.runners import AnalysisRunner

        def run(tracing):
            table = Table.from_pydict(
                {
                    "x": [float(i) * 1.7 for i in range(1000)],
                    "g": [str(i % 7) for i in range(1000)],
                }
            )
            builder = AnalysisRunner.on_data(table).add_analyzers(
                [
                    Mean("x"),
                    StandardDeviation("x"),
                    Minimum("x"),
                    Maximum("x"),
                    Completeness("x"),
                    Uniqueness(["g"]),
                ]
            )
            if tracing is not None:
                builder = builder.with_tracing(tracing)
            ctx = builder.run()
            return {
                repr(a): m.value.get()
                for a, m in ctx.metric_map.items()
                if m.value.is_success
            }

        plain = run(None)
        traced = run(True)
        off = run(False)
        assert plain.keys() == traced.keys() == off.keys()
        for key in plain:
            assert plain[key] == traced[key] == off[key], key  # bit-identical


# -- read-ahead fold into pipeline occupancy (ISSUE 12 satellite) -------------


class TestReadaheadOccupancy:
    """The native reader's read-ahead window (`page_read` spans +
    `readahead_hit` attrs on `page_decode`) folds into
    `pipeline_occupancy` as a synthetic "read" row, promoted to the
    bottleneck slot when prefetch misses dominate."""

    def _forest(self, hits, misses):
        root = _mk_span("analysis_run", "run", 0.0, 1.0)
        decode = _mk_span("pipe_stage", "pipeline", 0.0, 1.0, stage="decode")
        decode.children.append(_mk_span("pipe_item", "pipeline", 0.0, 0.4))
        fold = _mk_span("pipe_stage", "pipeline", 0.0, 1.0, stage="fold")
        fold.children.append(_mk_span("pipe_item", "pipeline", 0.0, 0.9))
        root.children += [decode, fold]
        root.children += [
            _mk_span("page_read", "io", 0.0, 0.3),
            _mk_span("page_read", "io", 0.3, 0.5),
        ]
        for i in range(hits):
            root.children.append(
                _mk_span("page_decode", "io", 0.5, 0.6, readahead_hit=True)
            )
        for i in range(misses):
            root.children.append(
                _mk_span("page_decode", "io", 0.6, 0.7, readahead_hit=False)
            )
        return root

    def test_miss_dominated_promotes_read_to_bottleneck(self):
        rows = observe.pipeline_occupancy([self._forest(hits=1, misses=3)])
        assert rows[0]["stage"] == "read"
        assert rows[0]["readahead_hits"] == 1
        assert rows[0]["readahead_misses"] == 3
        assert rows[0]["items"] == 2  # two page_read fetches
        # fetch wall is the widest stage's wall; busy is the fetch time
        assert rows[0]["wall_s"] == pytest.approx(1.0)
        assert rows[0]["busy_s"] == pytest.approx(0.5)
        assert rows[0]["occupancy"] == pytest.approx(0.5)

    def test_hit_dominated_read_row_trails(self):
        rows = observe.pipeline_occupancy([self._forest(hits=3, misses=1)])
        assert rows[0]["stage"] == "fold"  # busiest pipe stage leads
        assert rows[-1]["stage"] == "read"
        assert rows[-1]["readahead_hits"] == 3

    def test_no_pipe_stages_means_no_occupancy_rows(self):
        """Serial native-reader runs record page_read spans but no pipe
        stages; the occupancy table stays empty (its golden contract)."""
        root = _mk_span("analysis_run", "run", 0.0, 1.0)
        root.children.append(_mk_span("page_read", "io", 0.0, 0.3))
        assert observe.pipeline_occupancy([root]) == []

    def test_render_report_carries_readahead_suffix(self):
        text = observe.render_report([self._forest(hits=1, misses=3)])
        assert "readahead 1h/3m" in text
        assert "read" in text.split("bottleneck")[0]  # promoted row

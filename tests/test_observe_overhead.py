"""Overhead guard for the observability subsystem (ISSUE 3 satellite).

The disabled-tracing path must cost <2% wall overhead vs a
no-instrumentation baseline. A raw A/B wall-clock comparison of two
full engine runs is hopelessly noisy on shared-vCPU CI boxes, so the
guard bounds the overhead analytically and deterministically:

    instrumented_cost ≈ probes_per_run × cost_per_disabled_probe

`probes_per_run` is the exact number of spans a traced run of the same
workload records (an overcount-safe proxy is taken ×4 to cover
`annotate`/`current_*` probes that don't open spans), and
`cost_per_disabled_probe` is measured on the no-op fast path (a single
thread-local getattr returning the falsy singleton). The product must
stay under 2% of the measured disabled-run wall time.

A differential companion (test_observe.py::TestTracingIsInert) pins the
other half of the contract: tracing never changes metric values.
"""

from __future__ import annotations

import time

import numpy as np

from deequ_tpu import observe
from deequ_tpu.data.table import Table


def _medium_table(n=400_000, seed=3):
    rng = np.random.default_rng(seed)
    return Table.from_numpy(
        {
            "x": rng.standard_normal(n),
            "y": rng.lognormal(1.0, 0.5, n),
            "z": rng.integers(0, 1_000_000, n).astype(np.float64),
            "flag": rng.random(n) < 0.5,
        }
    )


def _run(table):
    from deequ_tpu.analyzers import (
        Completeness,
        Maximum,
        Mean,
        Minimum,
        StandardDeviation,
    )
    from deequ_tpu.runners import AnalysisRunner

    analyzers = []
    for col in ("x", "y", "z"):
        analyzers += [Mean(col), StandardDeviation(col), Minimum(col), Maximum(col)]
    analyzers.append(Completeness("x"))
    return AnalysisRunner.on_data(table).add_analyzers(analyzers).run()


def _noop_probe_cost(calls=200_000):
    """Seconds per disabled `span()` call, best of 3 batches."""
    span = observe.span
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(calls):
            span("probe", cat="dispatch", rows=1)
        best = min(best, time.perf_counter() - t0)
    return best / calls


def test_disabled_tracing_overhead_under_two_percent():
    table = _medium_table()
    _run(table)  # warm up: compile every (analyzer-set, shape) program

    # disabled-run wall time, best-of-3 (tracing off: no tracer installed)
    assert observe.current_tracer() is None
    wall = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        _run(table)
        wall = min(wall, time.perf_counter() - t0)

    # exact probe count for this workload, from one traced run
    traced = _run_traced(table)
    n_spans = sum(1 for _ in traced.run_trace.spans())
    probes = n_spans * 4  # headroom for annotate()/current_*() probes

    per_call = _noop_probe_cost()
    overhead = probes * per_call
    assert overhead < 0.02 * wall, (
        f"disabled-path overhead bound {overhead * 1e6:.1f}µs "
        f"({probes} probes × {per_call * 1e9:.0f}ns) exceeds 2% of "
        f"{wall * 1e3:.1f}ms run wall time"
    )


def _run_traced(table):
    from deequ_tpu.analyzers import (
        Completeness,
        Maximum,
        Mean,
        Minimum,
        StandardDeviation,
    )
    from deequ_tpu.runners import AnalysisRunner

    analyzers = []
    for col in ("x", "y", "z"):
        analyzers += [Mean(col), StandardDeviation(col), Minimum(col), Maximum(col)]
    analyzers.append(Completeness("x"))
    return (
        AnalysisRunner.on_data(table)
        .add_analyzers(analyzers)
        .with_tracing(True)
        .run()
    )


def test_noop_span_is_cheap():
    """The disabled probe itself must stay in the tens-of-nanoseconds to
    low-microsecond class — a getattr plus a singleton return."""
    assert _noop_probe_cost(calls=100_000) < 5e-6


# -- forensics disabled path (ISSUE 12) --------------------------------------


def _verify(table, forensics=False):
    from deequ_tpu.checks.check import Check, CheckLevel
    from deequ_tpu.verification.suite import VerificationSuite

    check = (
        Check(CheckLevel.ERROR, "overhead")
        .is_complete("x")
        .has_min("y", lambda v: v > 0.0)
        .satisfies("z >= 0", "z nonneg", lambda r: r >= 1.0)
    )
    builder = VerificationSuite.on_data(table).add_check(check)
    if forensics:
        builder = builder.with_forensics()
    return builder.run()


def _attr_probe_cost(calls=200_000):
    """Seconds per `x is not None` attribute probe — the entire per-batch
    cost of the disabled forensics path in the fused scan."""

    class Holder:
        __slots__ = ("f",)

        def __init__(self):
            self.f = None

    holder = Holder()
    sink = 0
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(calls):
            if holder.f is not None:
                sink += 1
        best = min(best, time.perf_counter() - t0)
    assert sink == 0
    return best / calls


def test_disabled_forensics_overhead_under_three_percent():
    """Forensics off (the default) must cost <3% of verification wall.
    The off path in the fused scan is exactly one `self._forensics is
    not None` attribute probe per decoded batch plus two per plan and
    one env read per run — bounded analytically like the tracing guard
    above: the batch count is taken from a traced run of the same
    workload (host_fold spans, one per batch), ×16 headroom to cover
    the plan-time probes, the env read and any future probe sites."""
    table = _medium_table()
    result = _verify(table)  # warm up compile caches
    assert result.forensics() is None  # off by default

    wall = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        result = _verify(table)
        wall = min(wall, time.perf_counter() - t0)
    assert result.forensics() is None

    with observe.tracing() as tracer:
        _verify(table)
    n_batches = sum(
        1
        for root in tracer.roots
        for sp in _spans(root)
        if sp.name == "host_fold"
    )
    probes = max(1, n_batches) * 16

    overhead = probes * _attr_probe_cost()
    assert overhead < 0.03 * wall, (
        f"disabled-forensics overhead bound {overhead * 1e6:.1f}µs "
        f"({probes} probes) exceeds 3% of {wall * 1e3:.1f}ms "
        "verification wall time"
    )


def _spans(root):
    stack = [root]
    while stack:
        sp = stack.pop()
        yield sp
        stack.extend(sp.children)


# -- chaos harness + controller disabled path (ISSUE 13) ----------------------


def _fault_probe_cost(calls=200_000):
    """Seconds per disarmed `fault_point()` call — one module-global
    read plus a function call, the entire clean-path cost of a chaos
    probe site."""
    from deequ_tpu.testing import faults

    assert faults.active_plan() is None
    fault_point = faults.fault_point
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(calls):
            fault_point("read.pread")
        best = min(best, time.perf_counter() - t0)
    return best / calls


def _controller_probe_cost(calls=200_000):
    """Seconds per `ctl is not None` probe — the per-batch cost of run
    control when no controller is attached (the overwhelmingly common
    case: `FusedScanPass` holds `self._controller = None`)."""

    class Holder:
        __slots__ = ("c",)

        def __init__(self):
            self.c = None

    holder = Holder()
    sink = 0
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(calls):
            if holder.c is not None:
                sink += 1
        best = min(best, time.perf_counter() - t0)
    assert sink == 0
    return best / calls


def test_disabled_chaos_and_controller_overhead_under_two_percent():
    """Fault injection disarmed + no controller (the clean path every
    production run takes) must cost <2% of scan wall. Probe sites per
    batch: a handful of `fault_point` seams in the fetch/decode/stage
    workers plus one controller probe and one beat in the fold loop —
    bounded analytically like the guards above: batch count from a
    traced run (host_fold spans), ×32 headroom to cover every per-unit
    fetch/decode seam, per-row-group retries, and the per-partition
    checks. BENCH_CHAOS.json (make bench-chaos) pins the same bound on
    a real A/B wall-clock run."""
    from deequ_tpu.testing import faults

    assert faults.active_plan() is None
    table = _medium_table()
    _run(table)  # warm up compile caches

    wall = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        _run(table)
        wall = min(wall, time.perf_counter() - t0)

    with observe.tracing() as tracer:
        _run(table)
    n_batches = sum(
        1
        for root in tracer.roots
        for sp in _spans(root)
        if sp.name == "host_fold"
    )
    probes = max(1, n_batches) * 32

    overhead = probes * (_fault_probe_cost() + _controller_probe_cost())
    assert overhead < 0.02 * wall, (
        f"disabled chaos/controller overhead bound {overhead * 1e6:.1f}µs "
        f"({probes} probes) exceeds 2% of {wall * 1e3:.1f}ms scan wall"
    )


def test_disarmed_fault_point_is_cheap():
    """The disarmed probe must stay in the nanoseconds class — a global
    read, a None check, a return."""
    assert _fault_probe_cost(calls=100_000) < 5e-6

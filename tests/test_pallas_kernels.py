"""Pallas HLL register-max kernel: interpret-mode equivalence with the
XLA scatter-max path (the CPU-side proof for the TPU kernel; on real
TPU hardware `usable()` turns it on inside the fused scan)."""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np
import pytest

from deequ_tpu.ops import pallas_kernels
from deequ_tpu.ops.sketches import hll


def reference_registers(codes: np.ndarray) -> np.ndarray:
    regs = np.zeros(pallas_kernels.N_REGISTERS, dtype=np.int32)
    np.maximum.at(regs, codes >> 6, codes & 0x3F)
    return regs


def random_codes(rng, n):
    idx = rng.integers(0, pallas_kernels.N_REGISTERS, n, dtype=np.int32)
    rank = rng.integers(0, 57, n, dtype=np.int32)
    return (idx << 6) | rank


class TestShapeGate:
    def test_supported_shapes(self):
        assert pallas_kernels.shape_supported(1024)
        assert pallas_kernels.shape_supported(1 << 22)
        assert not pallas_kernels.shape_supported(8)
        assert not pallas_kernels.shape_supported(1025)
        assert not pallas_kernels.shape_supported(0)

    def test_usable_is_false_on_cpu(self):
        # the test platform is CPU: the pallas path must gate itself off
        assert pallas_kernels.usable() is False


class TestInterpretModeEquivalence:
    @pytest.mark.parametrize("n", [1024, 4096, 1 << 15])
    def test_random_codes(self, n):
        rng = np.random.default_rng(n)
        codes = random_codes(rng, n)
        got = np.asarray(
            pallas_kernels.hll_register_max(codes, interpret=True)
        )
        np.testing.assert_array_equal(got, reference_registers(codes))

    def test_masked_rows_are_noops(self):
        rng = np.random.default_rng(7)
        codes = random_codes(rng, 2048)
        codes[::3] = 0  # masked/invalid rows carry code 0
        got = np.asarray(
            pallas_kernels.hll_register_max(codes, interpret=True)
        )
        # masked rows must contribute nothing: equal to the registers of
        # the UNMASKED rows alone
        unmasked_only = codes[codes != 0]
        pad = np.zeros(2048 - len(unmasked_only), dtype=np.int32)
        np.testing.assert_array_equal(
            got, reference_registers(np.concatenate([unmasked_only, pad]))
        )

    def test_all_zero(self):
        got = np.asarray(
            pallas_kernels.hll_register_max(
                np.zeros(1024, dtype=np.int32), interpret=True
            )
        )
        np.testing.assert_array_equal(got, np.zeros(512, dtype=np.int32))

    def test_single_register_saturation(self):
        codes = np.full(1024, (511 << 6) | 56, dtype=np.int32)
        got = np.asarray(
            pallas_kernels.hll_register_max(codes, interpret=True)
        )
        assert got[511] == 56
        assert got[:511].sum() == 0

    def test_matches_hll_pack_pipeline(self):
        """End-to-end against the production packer: registers from the
        pallas kernel == registers from the host fold for real values."""
        rng = np.random.default_rng(0)
        values = rng.integers(0, 5000, 4096)
        valid = rng.random(4096) < 0.9
        packed = hll.pack_codes(values, valid)
        got = np.asarray(
            pallas_kernels.hll_register_max(packed, interpret=True)
        )
        expected = np.zeros(hll.M, dtype=np.int32)
        np.maximum.at(expected, packed >> 6, packed & 0x3F)
        np.testing.assert_array_equal(got, expected)
        # and the estimate built from them is the production estimate
        assert hll.estimate(got) == hll.estimate(expected)


class TestHist16RadixSelect:
    """The MXU histogram kernel (one-hot matmuls -> full 16-bit count
    table) + host walk must reproduce the device sort path's decimated
    sample EXACTLY (same ranks in the same float32 value space)."""

    def test_hist16_counts_match_bincount(self):
        rng = np.random.default_rng(0)
        n = 8192
        x = (rng.lognormal(0, 2, n) * np.where(rng.random(n) < 0.4, -1, 1)).astype(
            np.float32
        )
        live = rng.random(n) > 0.1
        bins = np.asarray(
            pallas_kernels.f32_sortable_bin16(jnp.asarray(x), jnp.asarray(live))
        )
        hist = np.asarray(
            pallas_kernels.hist16(jnp.asarray(bins), interpret=True)
        ).reshape(65536)
        u = x.view(np.int32)
        key = np.where(u < 0, ~u, u | np.int32(-(1 << 31)))
        ref_bins = np.where(live, (key.astype(np.int64) >> 16) & 0xFFFF, 65535)
        ref = np.bincount(ref_bins, minlength=65536)
        assert np.array_equal(hist.astype(np.int64), ref)
        # bin order must follow value order (sortable-key property)
        order = np.argsort(x[live], kind="stable")
        assert (np.diff(ref_bins[live][order]) >= 0).all()

    def test_quantile_path_equals_sort_path(self, monkeypatch):
        """End-to-end through the f32 device engine: the hist16 path's
        samples equal the sort path's (identical decimation ranks in the
        identical value space), so the resulting quantiles match
        exactly. Engagement is asserted, not assumed."""
        import deequ_tpu.analyzers.sketch as sketch_mod
        from deequ_tpu.analyzers import ApproxQuantile
        from deequ_tpu.data.table import Table
        from deequ_tpu.ops import runtime
        from deequ_tpu.ops.fused import FusedScanPass

        monkeypatch.setattr(runtime, "compute_dtype", lambda: jnp.float32)
        monkeypatch.setenv("DEEQU_TPU_PLACEMENT", "device")

        rng = np.random.default_rng(8)
        n = 50_000
        x = rng.lognormal(3, 1, n)
        x[rng.random(n) < 0.05] = np.nan
        x = x * np.where(rng.random(n) < 0.3, -1, 1)

        calls = {"hist16": 0}
        real_hist16 = pallas_kernels.hist16

        def interpreted_hist16(bins, interpret=False):
            calls["hist16"] += 1
            return real_hist16(bins, interpret=True)

        def run(use_hist):
            # KLL seeds are content-derived (sketch._batch_seed): equal
            # samples give equal sketches with no counter pinning
            if use_hist:
                monkeypatch.setattr(
                    sketch_mod, "_hist16_available", lambda n: True
                )
                monkeypatch.setattr(pallas_kernels, "hist16", interpreted_hist16)
            else:
                monkeypatch.setattr(
                    sketch_mod, "_hist16_available", lambda n: False
                )
            t = Table.from_numpy({"x": x})
            res = FusedScanPass([ApproxQuantile("x", 0.5)]).run(t)
            state = res[0].state_or_raise()
            return res[0].analyzer.compute_metric_from(state).value.get()

        via_hist = run(True)
        assert calls["hist16"] >= 1  # the kernel actually ran
        via_sort = run(False)
        assert via_hist == via_sort, (via_hist, via_sort)


class TestMaskedMomentFolds:
    """ISSUE 15 satellite: the numeric analyzers' count/sum/min/max (+
    stddev m2) folds as single-HBM-pass pallas kernels, pinned in
    interpret mode against an identically-blocked XLA reference —
    BITWISE for every stat (blocked summation is its own arithmetic;
    that is exactly what the "pallas-folds" plan-signature variant
    isolates), and exactly for the order-insensitive stats vs the naive
    fold."""

    @staticmethod
    def _data(n, seed, all_masked=False):
        rng = np.random.default_rng(seed)
        x = jnp.asarray(rng.normal(size=n).astype(np.float32) * 100.0)
        if all_masked:
            m = jnp.zeros(n, dtype=jnp.float32)
        else:
            m = jnp.asarray((rng.random(n) < 0.8).astype(np.float32))
        return x, m

    @staticmethod
    def _blocked_reference(x, m):
        """The kernel's exact accumulation order in plain jnp ops:
        (8, 128) lane accumulators over the sequential grid, then the
        same tiny lane-reduce epilog."""
        x3 = x.reshape(-1, 8, 128)
        m3 = m.reshape(-1, 8, 128)
        cnt = jnp.zeros((8, 128), jnp.float32)
        tot = jnp.zeros((8, 128), jnp.float32)
        mn = jnp.full((8, 128), jnp.inf, jnp.float32)
        mx = jnp.full((8, 128), -jnp.inf, jnp.float32)
        for blk in range(x3.shape[0]):
            xb, mb = x3[blk], m3[blk]
            live = mb > 0
            cnt = cnt + mb
            tot = tot + xb * mb
            mn = jnp.minimum(mn, jnp.where(live, xb, jnp.inf))
            mx = jnp.maximum(mx, jnp.where(live, xb, -jnp.inf))
        return jnp.sum(cnt), jnp.sum(tot), jnp.min(mn), jnp.max(mx)

    @pytest.mark.parametrize("n", [1024, 4096, 1 << 14])
    def test_bitwise_vs_blocked_xla_reference(self, n):
        x, m = self._data(n, seed=n)
        got = [np.asarray(v) for v in
               pallas_kernels.masked_moments(x, m, interpret=True)]
        ref = [np.asarray(v) for v in self._blocked_reference(x, m)]
        for g, r in zip(got, ref):
            assert g.tobytes() == r.tobytes(), (g, r)

    def test_order_insensitive_stats_match_naive_fold_exactly(self):
        x, m = self._data(4096, seed=3)
        cnt, total, mn, mx = [
            np.asarray(v)
            for v in pallas_kernels.masked_moments(x, m, interpret=True)
        ]
        xn, mn_np = np.asarray(x), np.asarray(m)
        live = xn[mn_np > 0]
        assert cnt == mn_np.sum()
        assert mn == live.min()
        assert mx == live.max()
        # sums reassociate: allclose, not bitwise, vs the naive fold
        np.testing.assert_allclose(
            total, (xn * mn_np).sum(dtype=np.float32), rtol=1e-5
        )

    def test_all_masked_yields_identities(self):
        x, m = self._data(1024, seed=5, all_masked=True)
        cnt, total, mn, mx = [
            np.asarray(v)
            for v in pallas_kernels.masked_moments(x, m, interpret=True)
        ]
        assert cnt == 0.0 and total == 0.0
        assert mn == np.inf and mx == -np.inf

    def test_centered_sumsq_matches_stddev_fold(self):
        x, m = self._data(2048, seed=11)
        xn, mm = np.asarray(x), np.asarray(m)
        avg = np.float32((xn * mm).sum() / mm.sum())
        got = np.asarray(
            pallas_kernels.masked_centered_sumsq(x, m, avg, interpret=True)
        )
        naive = (((xn - avg) * mm) ** 2).sum(dtype=np.float32)
        np.testing.assert_allclose(got, naive, rtol=1e-5)

    def test_gate_is_off_on_cpu(self, monkeypatch):
        # even with the knob on, usable() is False on CPU: the fold
        # returns None and fold_variant stays "" — cached states on CPU
        # never carry the pallas variant
        from deequ_tpu.ops import runtime

        monkeypatch.setenv("DEEQU_TPU_PALLAS_FOLDS", "1")
        x, m = self._data(1024, seed=1)
        assert pallas_kernels.fold_moments_or_none(x, m) is None
        assert runtime.fold_variant() == ""

    def test_gate_rejects_unsupported_shapes(self, monkeypatch):
        monkeypatch.setenv("DEEQU_TPU_PALLAS_FOLDS", "1")
        x, m = self._data(1024, seed=1)
        assert pallas_kernels.fold_moments_or_none(x[:100], m[:100]) is None

    def test_knob_off_disables_fold(self, monkeypatch):
        monkeypatch.setenv("DEEQU_TPU_PALLAS_FOLDS", "0")
        from deequ_tpu.ops import runtime

        assert not runtime.pallas_folds_enabled()
        x, m = self._data(1024, seed=1)
        assert pallas_kernels.fold_moments_or_none(x, m) is None

    def test_fold_variant_enters_plan_signature(self):
        from deequ_tpu.analyzers.scan import Mean
        from deequ_tpu.repository.states import plan_signature

        base = plan_signature([Mean("x")], placement="device",
                              compute_dtype="float32", batch_size=None,
                              batch_rows=None)
        default = plan_signature([Mean("x")], placement="device",
                                 compute_dtype="float32", batch_size=None,
                                 batch_rows=None, variant="")
        pallas = plan_signature([Mean("x")], placement="device",
                                compute_dtype="float32", batch_size=None,
                                batch_rows=None, variant="pallas-folds")
        # empty variant leaves existing signatures unchanged; the pallas
        # arithmetic gets its own cache namespace
        assert base == default
        assert pallas != base

"""Pallas HLL register-max kernel: interpret-mode equivalence with the
XLA scatter-max path (the CPU-side proof for the TPU kernel; on real
TPU hardware `usable()` turns it on inside the fused scan)."""

from __future__ import annotations

import numpy as np
import pytest

from deequ_tpu.ops import pallas_kernels
from deequ_tpu.ops.sketches import hll


def reference_registers(codes: np.ndarray) -> np.ndarray:
    regs = np.zeros(pallas_kernels.N_REGISTERS, dtype=np.int32)
    np.maximum.at(regs, codes >> 6, codes & 0x3F)
    return regs


def random_codes(rng, n):
    idx = rng.integers(0, pallas_kernels.N_REGISTERS, n, dtype=np.int32)
    rank = rng.integers(0, 57, n, dtype=np.int32)
    return (idx << 6) | rank


class TestShapeGate:
    def test_supported_shapes(self):
        assert pallas_kernels.shape_supported(1024)
        assert pallas_kernels.shape_supported(1 << 22)
        assert not pallas_kernels.shape_supported(8)
        assert not pallas_kernels.shape_supported(1025)
        assert not pallas_kernels.shape_supported(0)

    def test_usable_is_false_on_cpu(self):
        # the test platform is CPU: the pallas path must gate itself off
        assert pallas_kernels.usable() is False


class TestInterpretModeEquivalence:
    @pytest.mark.parametrize("n", [1024, 4096, 1 << 15])
    def test_random_codes(self, n):
        rng = np.random.default_rng(n)
        codes = random_codes(rng, n)
        got = np.asarray(
            pallas_kernels.hll_register_max(codes, interpret=True)
        )
        np.testing.assert_array_equal(got, reference_registers(codes))

    def test_masked_rows_are_noops(self):
        rng = np.random.default_rng(7)
        codes = random_codes(rng, 2048)
        codes[::3] = 0  # masked/invalid rows carry code 0
        got = np.asarray(
            pallas_kernels.hll_register_max(codes, interpret=True)
        )
        # masked rows must contribute nothing: equal to the registers of
        # the UNMASKED rows alone
        unmasked_only = codes[codes != 0]
        pad = np.zeros(2048 - len(unmasked_only), dtype=np.int32)
        np.testing.assert_array_equal(
            got, reference_registers(np.concatenate([unmasked_only, pad]))
        )

    def test_all_zero(self):
        got = np.asarray(
            pallas_kernels.hll_register_max(
                np.zeros(1024, dtype=np.int32), interpret=True
            )
        )
        np.testing.assert_array_equal(got, np.zeros(512, dtype=np.int32))

    def test_single_register_saturation(self):
        codes = np.full(1024, (511 << 6) | 56, dtype=np.int32)
        got = np.asarray(
            pallas_kernels.hll_register_max(codes, interpret=True)
        )
        assert got[511] == 56
        assert got[:511].sum() == 0

    def test_matches_hll_pack_pipeline(self):
        """End-to-end against the production packer: registers from the
        pallas kernel == registers from the host fold for real values."""
        rng = np.random.default_rng(0)
        values = rng.integers(0, 5000, 4096)
        valid = rng.random(4096) < 0.9
        packed = hll.pack_codes(values, valid)
        got = np.asarray(
            pallas_kernels.hll_register_max(packed, interpret=True)
        )
        expected = np.zeros(hll.M, dtype=np.int32)
        np.maximum.at(expected, packed >> 6, packed & 0x3F)
        np.testing.assert_array_equal(got, expected)
        # and the estimate built from them is the production estimate
        assert hll.estimate(got) == hll.estimate(expected)

"""Repository, serde, and state-provider tests (mirrors reference
repository tests, AnalysisResultSerdeTest, StateProviderTest, and the
incremental/partitioned-state integration tests)."""

import io
import json

import numpy as np
import pytest

from deequ_tpu.analyzers import (
    ApproxCountDistinct,
    ApproxQuantile,
    ApproxQuantiles,
    Completeness,
    Compliance,
    Correlation,
    CountDistinct,
    DataType,
    Distinctness,
    Entropy,
    Histogram,
    Maximum,
    Mean,
    Minimum,
    MutualInformation,
    PatternMatch,
    Size,
    StandardDeviation,
    Sum,
    UniqueValueRatio,
    Uniqueness,
)
from deequ_tpu.analyzers.state_provider import (
    FileSystemStateProvider,
    InMemoryStateProvider,
)
from deequ_tpu.ops import runtime
from deequ_tpu.repository import (
    FileSystemMetricsRepository,
    InMemoryMetricsRepository,
    ResultKey,
)
from deequ_tpu.repository.serde import (
    deserialize_analysis_results,
    deserialize_analyzer,
    serialize_analysis_results,
    serialize_analyzer,
)
from deequ_tpu.runners import AnalysisRunner

from fixtures import get_df_missing, get_df_with_numeric_values, get_df_full

ALL_SERIALIZABLE_ANALYZERS = [
    Size(),
    Size(where="x > 2"),
    Completeness("col"),
    Completeness("col", where="x > 2"),
    Compliance("rule", "att1 > 0"),
    PatternMatch("col", r"\d+"),
    Sum("col"),
    Mean("col"),
    Minimum("col"),
    Maximum("col"),
    CountDistinct(["a", "b"]),
    Distinctness(["a"]),
    Entropy("col"),
    MutualInformation(["a", "b"]),
    UniqueValueRatio(["a"]),
    Uniqueness(["a", "b"]),
    Histogram("col"),
    Histogram("col", max_detail_bins=10),
    DataType("col"),
    ApproxCountDistinct("col"),
    Correlation("a", "b"),
    StandardDeviation("col"),
    ApproxQuantile("col", 0.5),
    ApproxQuantiles("col", [0.25, 0.5, 0.75]),
]


class TestAnalyzerSerde:
    def test_roundtrip_every_analyzer(self):
        for analyzer in ALL_SERIALIZABLE_ANALYZERS:
            data = serialize_analyzer(analyzer)
            restored = deserialize_analyzer(json.loads(json.dumps(data)))
            assert restored == analyzer, repr(analyzer)

    def test_histogram_with_udf_rejected(self):
        with pytest.raises(ValueError, match="Unable to serialize"):
            serialize_analyzer(Histogram("col", binning_udf=lambda v: v))

    def test_reference_compatible_fields(self):
        data = serialize_analyzer(Completeness("att1", where="x > 1"))
        assert data == {
            "analyzerName": "Completeness",
            "column": "att1",
            "where": "x > 1",
        }


class TestAnalysisResultSerde:
    def make_context(self):
        df = get_df_with_numeric_values()
        return (
            AnalysisRunner.on_data(df)
            .add_analyzers(
                [
                    Size(),
                    Mean("att1"),
                    Uniqueness(["att1"]),
                    DataType("att1"),
                    ApproxQuantiles("att1", [0.5]),
                ]
            )
            .run()
        )

    def test_roundtrip(self):
        from deequ_tpu.repository.base import AnalysisResult

        context = self.make_context()
        key = ResultKey(12345, {"env": "test"})
        payload = serialize_analysis_results([AnalysisResult(key, context)])
        restored = deserialize_analysis_results(payload)
        assert len(restored) == 1
        assert restored[0].result_key == key
        restored_map = restored[0].analyzer_context.metric_map
        assert restored_map[Size()].value.get() == 6.0
        assert restored_map[Mean("att1")].value.get() == 3.5
        assert restored_map[Uniqueness(["att1"])].value.get() == 1.0
        hist = restored_map[DataType("att1")].value.get()
        assert hist["Integral"].ratio == 1.0
        keyed = restored_map[ApproxQuantiles("att1", [0.5])].value.get()
        assert keyed["0.5"] in (3.0, 4.0)


def _make_repo(repo_kind, tmp_path):
    """'objectstore' runs the SAME suite against the in-memory
    object-store fake (core/fsio.MemoryFileSystem): whole-object atomic
    puts, no directories — proving the repository never depends on POSIX
    semantics beyond the fs seam (round-3 verdict, Missing #1)."""
    from deequ_tpu.core.fsio import MemoryFileSystem

    if repo_kind == "memory":
        return InMemoryMetricsRepository()
    if repo_kind == "objectstore":
        return FileSystemMetricsRepository(
            "bucket/prefix/metrics.json", filesystem=MemoryFileSystem()
        )
    return FileSystemMetricsRepository(str(tmp_path / "metrics.json"))


def _make_provider(provider_kind, tmp_path):
    from deequ_tpu.core.fsio import MemoryFileSystem

    if provider_kind == "memory":
        return InMemoryStateProvider()
    if provider_kind == "objectstore":
        return FileSystemStateProvider(
            "bucket/states", allow_overwrite=True, filesystem=MemoryFileSystem()
        )
    if provider_kind == "fs-reference-naming":
        return FileSystemStateProvider(
            str(tmp_path / "states"), allow_overwrite=True, naming="reference"
        )
    return FileSystemStateProvider(str(tmp_path / "states"), allow_overwrite=True)


class TestRepositories:
    @pytest.mark.parametrize("repo_kind", ["memory", "fs", "objectstore"])
    def test_save_and_load_by_key(self, repo_kind, tmp_path):
        repo = _make_repo(repo_kind, tmp_path)
        df = get_df_with_numeric_values()
        key = ResultKey(1000, {"env": "test"})
        (
            AnalysisRunner.on_data(df)
            .add_analyzers([Size(), Mean("att1"), Completeness("nope")])
            .use_repository(repo)
            .save_or_append_result(key)
            .run()
        )
        loaded = repo.load_by_key(key)
        assert loaded is not None
        assert loaded.metric_map[Size()].value.get() == 6.0
        # failed metric filtered on save
        assert Completeness("nope") not in loaded.metric_map

    @pytest.mark.parametrize("repo_kind", ["memory", "fs", "objectstore"])
    def test_loader_queries(self, repo_kind, tmp_path):
        repo = _make_repo(repo_kind, tmp_path)
        df = get_df_with_numeric_values()
        for date, env in [(100, "dev"), (200, "prod"), (300, "prod")]:
            (
                AnalysisRunner.on_data(df)
                .add_analyzers([Size(), Mean("att1")])
                .use_repository(repo)
                .save_or_append_result(ResultKey(date, {"env": env}))
                .run()
            )
        assert len(repo.load().get()) == 3
        assert len(repo.load().with_tag_values({"env": "prod"}).get()) == 2
        assert len(repo.load().after(150).get()) == 2
        assert len(repo.load().before(150).get()) == 1
        assert len(repo.load().after(150).before(250).get()) == 1
        only_size = repo.load().for_analyzers([Size()]).get()
        assert all(
            set(r.analyzer_context.metric_map) == {Size()} for r in only_size
        )

    def test_repository_reuse_short_circuits(self):
        repo = InMemoryMetricsRepository()
        df = get_df_with_numeric_values()
        key = ResultKey(1, {})
        (
            AnalysisRunner.on_data(df)
            .add_analyzer(Distinctness(["att1"]))
            .use_repository(repo)
            .save_or_append_result(key)
            .run()
        )
        # cached distinctness + 2 new analyzers => 1 scan pass only
        with runtime.monitored() as stats:
            context = (
                AnalysisRunner.on_data(df)
                .add_analyzers([Distinctness(["att1"]), Size(), Mean("att1")])
                .use_repository(repo)
                .reuse_existing_results_for_key(key)
                .run()
            )
        assert stats.jobs == 1
        assert len(context.metric_map) == 3

    def test_fail_if_results_missing(self):
        repo = InMemoryMetricsRepository()
        df = get_df_with_numeric_values()
        with pytest.raises(RuntimeError, match="Could not find all necessary results"):
            (
                AnalysisRunner.on_data(df)
                .add_analyzer(Size())
                .use_repository(repo)
                .reuse_existing_results_for_key(ResultKey(9, {}), fail_if_results_missing=True)
                .run()
            )

    def test_loader_json_union_with_tags(self):
        repo = InMemoryMetricsRepository()
        df = get_df_with_numeric_values()
        (
            AnalysisRunner.on_data(df)
            .add_analyzer(Size())
            .use_repository(repo)
            .save_or_append_result(ResultKey(1, {"region": "eu"}))
            .run()
        )
        rows = json.loads(repo.load().get_success_metrics_as_json())
        assert rows[0]["region"] == "eu"
        assert rows[0]["dataset_date"] == 1

    def test_fs_repository_overwrites_same_key(self, tmp_path):
        path = str(tmp_path / "m.json")
        repo = FileSystemMetricsRepository(path)
        df = get_df_with_numeric_values()
        key = ResultKey(5, {})
        for _ in range(2):
            (
                AnalysisRunner.on_data(df)
                .add_analyzer(Size())
                .use_repository(repo)
                .save_or_append_result(key)
                .run()
            )
        assert len(repo.load().get()) == 1


class TestStateProviders:
    def states_to_test(self, df):
        return [
            Size(),
            Completeness("att1"),
            Compliance("r", "att1 > 3"),
            Sum("att1"),
            Mean("att1"),
            Minimum("att1"),
            Maximum("att1"),
            StandardDeviation("att1"),
            Correlation("att1", "att2"),
            DataType("item"),
            ApproxCountDistinct("att1"),
            ApproxQuantile("att1", 0.5),
            Uniqueness(["att1"]),
        ]

    @pytest.mark.parametrize(
        "provider_kind", ["memory", "fs", "objectstore", "fs-reference-naming"]
    )
    def test_roundtrip_states(self, provider_kind, tmp_path):
        df = get_df_with_numeric_values()
        provider = _make_provider(provider_kind, tmp_path)
        for analyzer in self.states_to_test(df):
            state = analyzer.compute_state_from(df)
            assert state is not None, repr(analyzer)
            provider.persist(analyzer, state)
            loaded = provider.load(analyzer)
            metric_a = analyzer.compute_metric_from(state)
            metric_b = analyzer.compute_metric_from(loaded)
            va, vb = metric_a.value.get(), metric_b.value.get()
            if isinstance(va, float):
                assert vb == pytest.approx(va, rel=1e-12), repr(analyzer)
            else:
                assert va == vb, repr(analyzer)


class TestIncrementalStates:
    """The 'multi-node without cluster' contract: metrics from merged
    per-partition states == single-pass metrics (reference:
    StateAggregationIntegrationTest.scala:31-188)."""

    def test_partitioned_equals_whole(self):
        df = get_df_missing()
        partitions = [df.slice(0, 4), df.slice(4, 8), df.slice(8, 12)]
        analyzers = [
            Size(),
            Completeness("att1"),
            Completeness("att2"),
            Uniqueness(["att1"]),
            CountDistinct(["att1"]),
        ]
        providers = []
        for part in partitions:
            provider = InMemoryStateProvider()
            AnalysisRunner.do_analysis_run(
                part, analyzers, save_states_with=provider
            )
            providers.append(provider)

        merged_context = AnalysisRunner.run_on_aggregated_states(
            df.slice(0, 0), analyzers, providers
        )
        direct_context = AnalysisRunner.do_analysis_run(df, analyzers)

        for analyzer in analyzers:
            merged = merged_context.metric_map[analyzer].value
            direct = direct_context.metric_map[analyzer].value
            assert merged.is_success and direct.is_success, repr(analyzer)
            assert merged.get() == pytest.approx(direct.get()), repr(analyzer)

    def test_incremental_update(self):
        df = get_df_with_numeric_values()
        old, new = df.slice(0, 4), df.slice(4, 6)
        provider = InMemoryStateProvider()
        analyzers = [Size(), Mean("att1"), StandardDeviation("att1")]
        AnalysisRunner.do_analysis_run(old, analyzers, save_states_with=provider)
        # incremental: aggregate new data with the stored state
        context = AnalysisRunner.do_analysis_run(
            new, analyzers, aggregate_with=provider
        )
        direct = AnalysisRunner.do_analysis_run(df, analyzers)
        for analyzer in analyzers:
            assert context.metric_map[analyzer].value.get() == pytest.approx(
                direct.metric_map[analyzer].value.get()
            ), repr(analyzer)

    def test_verification_suite_on_aggregated_states(self):
        from deequ_tpu import Check, CheckLevel, CheckStatus, VerificationSuite

        df = get_df_missing()
        parts = [df.slice(0, 6), df.slice(6, 12)]
        providers = []
        check = Check(CheckLevel.ERROR, "agg").has_size(lambda s: s == 12).has_completeness(
            "att1", lambda v: v == 0.5
        )
        analyzers = list(check.required_analyzers())
        for part in parts:
            provider = InMemoryStateProvider()
            AnalysisRunner.do_analysis_run(part, analyzers, save_states_with=provider)
            providers.append(provider)
        result = VerificationSuite.run_on_aggregated_states(
            df.slice(0, 0), [check], providers
        )
        assert result.status == CheckStatus.SUCCESS


class TestFilesystemSeam:
    def test_object_store_spilled_frequencies_roundtrip(self, monkeypatch):
        """A SPILLED (disk-backed, multi-partition) frequency state
        streams into the object-store fake row-group by row-group and
        comes back equal — the heaviest persistence path off POSIX."""
        from deequ_tpu.core.fsio import MemoryFileSystem

        monkeypatch.setenv("DEEQU_TPU_MAX_GROUPS_IN_MEMORY", "50")
        import numpy as np

        from deequ_tpu.analyzers.freq_spill import GroupCountAccumulator
        from deequ_tpu.analyzers.frequency import FrequenciesAndNumRows

        rng = np.random.default_rng(0)
        acc = GroupCountAccumulator(["k"], max_groups_in_memory=50)
        for chunk in range(4):
            keys = np.array(
                [f"v{v}" for v in rng.integers(0, 400, 1000)], dtype=object
            )
            uniq, counts = np.unique(keys, return_counts=True)
            acc.add(
                FrequenciesAndNumRows(
                    ["k"], [uniq.astype(object)], counts.astype(np.int64), 1000
                )
            )
        state = acc.finalize()
        assert getattr(state, "is_spilled", False)

        fs = MemoryFileSystem()
        provider = FileSystemStateProvider(
            "bucket/spilled", allow_overwrite=True, filesystem=fs
        )
        analyzer = Uniqueness(["k"])
        provider.persist(analyzer, state)
        loaded = provider.load(analyzer)
        ma = analyzer.compute_metric_from(state).value.get()
        mb = analyzer.compute_metric_from(loaded).value.get()
        assert mb == pytest.approx(ma, rel=1e-12)

    def test_atomic_publish_discards_on_error(self, tmp_path):
        """A streamed write that raises must leave NO object behind (and
        on the local fs, no leaked tmp file either)."""
        import os

        from deequ_tpu.core.fsio import LocalFileSystem, MemoryFileSystem

        for fs, path in (
            (MemoryFileSystem(), "bucket/x.bin"),
            (LocalFileSystem(), str(tmp_path / "x.bin")),
        ):
            try:
                with fs.open_write(path) as sink:
                    sink.write(b"partial")
                    raise RuntimeError("boom")
            except RuntimeError:
                pass
            assert not fs.exists(path)
        assert os.listdir(tmp_path) == []  # no orphaned .tmp

    def test_fsspec_adapter_defaults_to_atomic_on_posix_backends(self):
        """rename_atomic=None auto-detects: POSIX-like fsspec protocols
        get tmp+mv (a crash mid-write must read as absent, never as a
        truncated file), object stores keep the atomic in-place object
        put (their mv is a non-atomic copy+delete)."""
        from deequ_tpu.core.fsio import FsspecFileSystem

        class FakeFs:
            def __init__(self, protocol):
                self.protocol = protocol
                self.store = {}

            def exists(self, path):
                return path in self.store

            def open(self, path, mode):
                fs = self

                class _W(io.BytesIO):
                    def __exit__(self, *exc):
                        fs.store[path] = self.getvalue()
                        return False

                if "w" in mode:
                    return _W()
                return io.BytesIO(self.store[path])

            def mv(self, src, dst):
                self.store[dst] = self.store.pop(src)

        posix = FsspecFileSystem(FakeFs("file"))
        assert posix._rename_atomic
        s3 = FsspecFileSystem(FakeFs(("s3", "s3a")))
        assert not s3._rename_atomic
        # explicit override still wins
        assert not FsspecFileSystem(FakeFs("file"), rename_atomic=False)._rename_atomic
        # both write paths produce the bytes at the final path
        for fs in (posix, s3):
            fs.write_bytes("bucket/k.bin", b"payload")
            assert fs.read_bytes("bucket/k.bin") == b"payload"
            assert not [p for p in fs._fs.store if p.endswith(".tmp")]
        # a failed atomic publish cleans up its tmp object
        removed = []
        posix._fs.mv = lambda src, dst: (_ for _ in ()).throw(OSError("mv"))
        posix._fs.rm = lambda p: removed.append(posix._fs.store.pop(p))
        with pytest.raises(OSError):
            posix.write_bytes("bucket/fail.bin", b"x")
        assert removed and not [
            p for p in posix._fs.store if p.endswith(".tmp")
        ]

    def test_murmur3_primitives_match_published_x86_32_vectors(self):
        """De-circularized validation: compose the production mix/
        mixLast/finalize primitives into byte-mode murmur3 x86_32
        (little-endian 4-byte blocks, the published algorithm) and check
        them against the well-known public test vectors. stringHash
        shares exactly these primitives; only its UTF-16 pairing loop
        differs, which the hand-derived goldens below cover."""
        from deequ_tpu.analyzers.state_provider import (
            _mm3_finalize,
            _mm3_mix,
            _mm3_mix_k,
        )

        def mm3_bytes(data: bytes, seed: int) -> int:
            h = seed & 0xFFFFFFFF
            n = len(data)
            for i in range(0, n - n % 4, 4):
                h = _mm3_mix(h, int.from_bytes(data[i : i + 4], "little"))
            tail = data[n - n % 4 :]
            if tail:
                h ^= _mm3_mix_k(int.from_bytes(tail, "little"))
            return _mm3_finalize(h, n)

        # published murmur3 x86_32 vectors (Appleby's smhasher /
        # widely-reproduced public tables)
        for data, seed, want in [
            (b"", 0x00000000, 0x00000000),
            (b"", 0x00000001, 0x514E28B7),
            (b"", 0xFFFFFFFF, 0x81F16F39),
            (b"test", 0x00000000, 0xBA6BD213),
            (b"test", 0x9747B28C, 0x704B81DC),
            (b"Hello, world!", 0x00000000, 0xC0363E43),
            (b"Hello, world!", 0x9747B28C, 0x24884CBA),
            (
                b"The quick brown fox jumps over the lazy dog",
                0x9747B28C,
                0x2FA826CD,
            ),
        ]:
            assert mm3_bytes(data, seed) == want, (data, seed)

    def test_reference_naming_uses_murmur3_of_repr(self, tmp_path):
        """naming='reference' mirrors the reference's
        MurmurHash3.stringHash(analyzer.toString, 42) file naming —
        note the EXPLICIT seed 42 at the reference call site
        (StateProvider.scala:81-83), not Scala's default stringSeed.
        Goldens below are hand-derived from the spec (independent
        straight-line computation, not the code under test); cross-JVM
        validation is documented as pending in README (no JVM in this
        image)."""
        from deequ_tpu.analyzers.state_provider import _scala_murmur3_string_hash

        # stringHash("", 42) = avalanche(42 ^ 0); hand trace:
        #   42 ^ (42>>16)        = 0x0000002a
        #   * 0x85EBCA6B (mod32) = 0xf8af358e
        #   ^ >>13               = 0xf8a8f0f7
        #   * 0xC2B2AE35 (mod32) = 0x087fc523
        #   ^ >>16               = 0x087fcd5c = 142593372
        assert _scala_murmur3_string_hash("") == 142593372
        # stringHash("a", 42) = finalize(42 ^ mixK(0x61), 1):
        #   mixK(0x61) = rotl15(0x61*0xCC9E2D51)*0x1B873593 → 42^· =
        #   0x504ba9ff; avalanche(0x504ba9ff ^ 1) = 0xb2e5ae63 (signed
        #   -1293573533)
        assert _scala_murmur3_string_hash("a") == -1293573533
        # one full mix round ((0x61<<16)+0x62 block), derived the same way
        assert _scala_murmur3_string_hash("ab") == 1144373339
        # analyzer-repr goldens (independent derivation, seed 42)
        assert _scala_murmur3_string_hash("Size(None)") == 669792474
        assert (
            _scala_murmur3_string_hash("Completeness(name,None)") == 1342071893
        )
        assert _scala_murmur3_string_hash("ab") != _scala_murmur3_string_hash("ba")

        provider = FileSystemStateProvider(
            str(tmp_path / "ref"), allow_overwrite=True, naming="reference"
        )
        analyzer = Size()
        import os

        provider.persist(analyzer, analyzer.compute_state_from(get_df_full()))
        expected = str(_scala_murmur3_string_hash(repr(analyzer)))
        names = os.listdir(tmp_path)
        assert any(expected in name for name in names), (expected, names)

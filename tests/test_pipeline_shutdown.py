"""Shutdown hardening for the stream pipeline (ISSUE 5, satellite).

Two failure directions, both previously untested:

  * EARLY CONSUMER EXIT — the consumer abandons the generator mid-stream
    (an error in the scan loop, a downstream stage shutting down). Every
    stage thread must terminate within the join timeout and the decode
    iterator must be closed ON its own thread, releasing file handles
    deterministically (no hang on a blocked `q.put`, no leaked
    ParquetFile fd).

  * MID-STREAM PRODUCER EXCEPTION — the decode iterator or a stage `fn`
    raises partway. The exception must re-raise in the consumer, after
    the same cleanup.

Covers `DataSource.batches` (data/source.py) and `pipeline.staged`
(ops/pipeline.py), separately and stacked (staged over batches —
the shape `FusedScanPass._scan_pipelined` runs).
"""

from __future__ import annotations

import os
import threading
import time

import numpy as np
import pytest

from deequ_tpu.data.source import JOIN_TIMEOUT_S, DataSource, ParquetSource
from deequ_tpu.data.table import Column, ColumnType, Table
from deequ_tpu.ops import pipeline


def _threads(prefix: str):
    return [
        t
        for t in threading.enumerate()
        if t.name.startswith(prefix) and t.is_alive()
    ]


def _wait_no_threads(prefix: str, timeout: float = JOIN_TIMEOUT_S) -> bool:
    deadline = time.time() + timeout
    while time.time() < deadline:
        if not _threads(prefix):
            return True
        time.sleep(0.02)
    return False


def _open_fd_targets():
    fd_dir = "/proc/self/fd"
    if not os.path.isdir(fd_dir):  # pragma: no cover - non-Linux
        return None
    targets = []
    for fd in os.listdir(fd_dir):
        try:
            targets.append(os.readlink(os.path.join(fd_dir, fd)))
        except OSError:
            continue
    return targets


def _tiny_table(n=64):
    values = np.arange(n, dtype=np.float64)
    return Table([Column("x", ColumnType.DOUBLE, values, np.ones(n, bool))])


class _ScriptedSource(DataSource):
    """A DataSource whose decode iterator follows a script: yields
    `good` batches, then optionally raises; records whether its
    generator's finally (the close path) ran and on which thread."""

    def __init__(self, good: int, raise_after: bool = False):
        self.good = good
        self.raise_after = raise_after
        self.closed = threading.Event()
        self.close_thread: str = ""

    def _schema(self):
        return [("x", ColumnType.DOUBLE)]

    @property
    def num_rows(self):
        return self.good * 64

    def _iter_tables(self, batch_size):
        try:
            for _ in range(self.good):
                yield _tiny_table()
            if self.raise_after:
                raise RuntimeError("decode blew up mid-stream")
        finally:
            self.close_thread = threading.current_thread().name
            self.closed.set()


@pytest.fixture
def parquet_path(tmp_path):
    pa = pytest.importorskip("pyarrow")
    pq = pytest.importorskip("pyarrow.parquet")
    path = str(tmp_path / "shutdown.parquet")
    table = pa.table({"x": np.arange(200_000, dtype=np.float64)})
    pq.write_table(table, path, row_group_size=10_000)
    return path


# -- DataSource.batches: the decode stage ------------------------------------


def test_consumer_abandon_terminates_decode_thread(parquet_path):
    src = ParquetSource(parquet_path, batch_rows=10_000)
    gen = src.batches(10_000)
    first = next(gen)
    assert first.num_rows == 10_000
    assert _threads("deequ-decode"), "decode thread should be running"
    gen.close()  # early consumer exit, 19 batches unread
    assert _wait_no_threads("deequ-decode"), (
        "decode thread still alive after consumer abandoned the stream"
    )


def test_consumer_abandon_closes_parquet_file(parquet_path):
    targets = _open_fd_targets()
    if targets is None:
        pytest.skip("/proc/self/fd unavailable")
    src = ParquetSource(parquet_path, batch_rows=10_000)
    gen = src.batches(10_000)
    next(gen)
    gen.close()
    assert _wait_no_threads("deequ-decode")
    open_now = [t for t in _open_fd_targets() if t == parquet_path]
    assert not open_now, (
        f"parquet file handle leaked after consumer abandon: {open_now}"
    )


def test_consumer_abandon_closes_iterator_on_producer_thread():
    src = _ScriptedSource(good=50)
    gen = src.batches(64)
    next(gen)
    gen.close()
    assert src.closed.wait(JOIN_TIMEOUT_S), "decode iterator never closed"
    assert src.close_thread == "deequ-decode", (
        "iterator must close ON the producer thread (deterministic file "
        f"release), closed on {src.close_thread!r}"
    )
    assert _wait_no_threads("deequ-decode")


def test_producer_exception_propagates_and_thread_exits():
    src = _ScriptedSource(good=2, raise_after=True)
    seen = 0
    with pytest.raises(RuntimeError, match="decode blew up"):
        for _ in src.batches(64):
            seen += 1
    assert seen == 2
    assert src.closed.is_set()
    assert _wait_no_threads("deequ-decode")


# -- pipeline.staged: prep-style stages --------------------------------------


def test_staged_early_exit_unwinds_stage_and_upstream():
    """Closing the staged() generator must stop the stage thread AND
    close the upstream iterator (transitively: a DataSource.batches
    upstream unwinds its own decode thread the same way)."""
    upstream_closed = threading.Event()

    def upstream():
        try:
            for i in range(1000):
                yield i
        finally:
            upstream_closed.set()

    it = pipeline.staged(upstream(), lambda x: x * 2, name="t-early", depth=2)
    assert next(it) == 0
    it.close()
    assert _wait_no_threads("deequ-pipe-t-early"), "stage thread leaked"
    assert upstream_closed.wait(JOIN_TIMEOUT_S), "upstream never closed"


def test_staged_blocked_put_wakes_on_abandon():
    """The stage thread blocked on a full queue (consumer far behind)
    must wake and exit promptly when the consumer abandons — the
    drain-then-join shutdown path."""
    it = pipeline.staged(iter(range(1000)), lambda x: x, name="t-blocked", depth=1)
    next(it)
    time.sleep(0.2)  # let the stage fill the queue and block in put()
    t0 = time.time()
    it.close()
    assert _wait_no_threads("deequ-pipe-t-blocked", timeout=JOIN_TIMEOUT_S)
    assert time.time() - t0 < JOIN_TIMEOUT_S


def test_staged_fn_exception_propagates_and_unwinds():
    upstream_closed = threading.Event()

    def upstream():
        try:
            for i in range(100):
                yield i
        finally:
            upstream_closed.set()

    def fn(x):
        if x == 3:
            raise ValueError("prep blew up mid-stream")
        return x

    got = []
    with pytest.raises(ValueError, match="prep blew up"):
        for out in pipeline.staged(upstream(), fn, name="t-fnerr", depth=2):
            got.append(out)
    assert got == [0, 1, 2]
    assert _wait_no_threads("deequ-pipe-t-fnerr")
    assert upstream_closed.wait(JOIN_TIMEOUT_S)


def test_staged_upstream_exception_propagates():
    def upstream():
        yield 1
        yield 2
        raise OSError("upstream died")

    got = []
    with pytest.raises(OSError, match="upstream died"):
        for out in pipeline.staged(upstream(), lambda x: x, name="t-uperr"):
            got.append(out)
    assert got == [1, 2]
    assert _wait_no_threads("deequ-pipe-t-uperr")


# -- stacked: staged over DataSource.batches (the executor's shape) ----------


def test_stacked_abandon_unwinds_both_threads(parquet_path):
    src = ParquetSource(parquet_path, batch_rows=10_000)
    it = pipeline.staged(
        src.batches(10_000), lambda t: t.num_rows, name="t-stack", depth=2
    )
    assert next(it) == 10_000
    it.close()
    assert _wait_no_threads("deequ-pipe-t-stack"), "prep stage leaked"
    assert _wait_no_threads("deequ-decode"), "decode thread leaked"
    targets = _open_fd_targets()
    if targets is not None:
        assert parquet_path not in targets, "parquet fd leaked"


def test_stacked_decode_error_reaches_consumer_through_stage():
    src = _ScriptedSource(good=1, raise_after=True)
    got = []
    with pytest.raises(RuntimeError, match="decode blew up"):
        for out in pipeline.staged(
            src.batches(64), lambda t: t.num_rows, name="t-stkerr"
        ):
            got.append(out)
    assert got == [64]
    assert src.closed.is_set()
    assert _wait_no_threads("deequ-pipe-t-stkerr")
    assert _wait_no_threads("deequ-decode")


# -- chaos: injected faults ride the same shutdown contract (ISSUE 13) --------


def test_injected_worker_death_contained_and_leak_free(parquet_path, monkeypatch):
    """A decode worker killed mid-unit re-decodes inline: same batches,
    every thread joined, no parquet fd left open."""
    from deequ_tpu.testing import faults

    # the pool path (where decode.worker lives) needs >1 worker — the
    # single-core CI box would otherwise route through the serial loop
    monkeypatch.setenv("DEEQU_TPU_DECODE_WORKERS", "2")
    clean = [
        t.num_rows
        for t in ParquetSource(parquet_path, batch_rows=10_000).batches(10_000)
    ]
    with faults.install("seed=7,decode.worker:1.0:1") as plan:
        rows = [
            t.num_rows
            for t in ParquetSource(
                parquet_path, batch_rows=10_000
            ).batches(10_000)
        ]
    assert plan.injected.get("decode.worker", 0) >= 1, "fault never fired"
    assert rows == clean
    assert _wait_no_threads("deequ-decode")
    targets = _open_fd_targets()
    if targets is not None:
        assert parquet_path not in targets, "parquet fd leaked past fault"


def test_injected_stage_fault_contained_in_staged():
    """A stage fn raising once mid-batch redoes in place — the stream
    sees every item exactly once and the stage thread still joins."""
    from deequ_tpu.testing import faults

    with faults.install("seed=1,pipeline.stage:1.0:1") as plan:
        got = list(
            pipeline.staged(iter(range(50)), lambda x: x * 2, name="t-chaos")
        )
    assert plan.injected.get("pipeline.stage", 0) == 1
    assert got == [x * 2 for x in range(50)]
    assert _wait_no_threads("deequ-pipe-t-chaos")


def test_service_drain_on_sigterm_joins_all_and_closes_all(parquet_path):
    """SIGTERM drains the DQ service through the same shutdown contract
    as the pipeline: queued work is returned with DQ414, the running
    run either commits or is drained at a boundary, EVERY service /
    pipeline / decode thread joins, and no parquet fd stays open."""
    import signal

    from deequ_tpu.service import DQ_DRAINED, DQService

    gate = threading.Event()

    def slow_data():
        gate.wait(timeout=30)
        return ParquetSource(parquet_path, batch_rows=10_000)

    svc = DQService(workers=1)
    svc.install_sigterm()
    try:
        from deequ_tpu import Check, CheckLevel

        check = Check(CheckLevel.ERROR, "drain").has_size(lambda s: s > 0)
        running = svc.submit("t", "d0", slow_data, checks=[check])
        for _ in range(300):
            if running.status == "running":
                break
            time.sleep(0.01)
        queued = svc.submit("t", "d1", slow_data, checks=[check])
        gate.set()

        # deliver a real SIGTERM to this process; the installed handler
        # runs svc.drain() synchronously in the main thread
        os.kill(os.getpid(), signal.SIGTERM)

        assert queued.done()
        assert queued.status == "drained" and queued.code == DQ_DRAINED
        assert running.done()
        # the in-flight run either finished cleanly before the drain's
        # soft cancel reached a boundary, or was drained — never killed
        # into an undefined state
        assert running.status in ("done", "drained")
    finally:
        svc.uninstall_sigterm()
        gate.set()
        svc.close()

    assert _wait_no_threads("deequ-dq-service"), "service threads leaked"
    assert _wait_no_threads("deequ-pipe"), "pipeline threads leaked"
    assert _wait_no_threads("deequ-decode"), "decode threads leaked"
    targets = _open_fd_targets()
    if targets is not None:
        assert parquet_path not in targets, "parquet fd leaked past drain"

    # post-drain submissions are turned away with the drain code
    late = svc.submit("t", "d2", slow_data, checks=[])
    assert late.done() and late.code == DQ_DRAINED


def test_cancellation_joins_all_stages(parquet_path):
    """RunCancelled raised in the consumer loop (the fold-side
    controller check) unwinds the stacked staged-over-batches shape
    through the same shutdown contract as exhaustion: both threads
    join, fd released."""
    from contextlib import closing

    from deequ_tpu.core.controller import RunCancelled, RunController

    ctl = RunController()
    src = ParquetSource(parquet_path, batch_rows=10_000)
    with pytest.raises(RunCancelled) as exc_info:
        with closing(
            pipeline.staged(
                src.batches(10_000), lambda t: t.num_rows, name="t-cancel",
                depth=2,
            )
        ) as it:
            batches = 0
            for _ in it:
                batches += 1
                if batches == 2:
                    ctl.cancel()
                ctl.check(where="test fold", progress={"batches": batches})
    assert exc_info.value.progress == {"batches": 2}
    assert _wait_no_threads("deequ-pipe-t-cancel"), "prep stage leaked"
    assert _wait_no_threads("deequ-decode"), "decode thread leaked"
    targets = _open_fd_targets()
    if targets is not None:
        assert parquet_path not in targets, "parquet fd leaked past cancel"

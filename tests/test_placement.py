"""Placement: discrete analyzers fold on the host when the device link
is slow (runtime.placement_mode), with results identical to the fused
device pass — the scheduler analogue of Spark's map-side combine
decision (SURVEY.md §2.10; reference: runners/AnalysisRunner.scala:279-326
runs everything through Spark, where the data already lives next to the
executors — here the engine must *choose* where the bytes go)."""

from __future__ import annotations

import numpy as np
import pytest

from deequ_tpu.analyzers import (
    ApproxCountDistinct,
    Completeness,
    Compliance,
    DataType,
    Maximum,
    Mean,
    Minimum,
    PatternMatch,
    Size,
    StandardDeviation,
    Sum,
)
from deequ_tpu.analyzers.sketch import ApproxQuantile
from deequ_tpu.data.table import Table
from deequ_tpu.ops import runtime
from deequ_tpu.ops.fused import FusedScanPass


@pytest.fixture
def mixed_table():
    rng = np.random.default_rng(42)
    x = rng.normal(10.0, 3.0, 5000)
    x[::7] = np.nan
    return Table.from_numpy(
        {
            "x": x,
            "n": rng.integers(0, 1000, 5000),
            "s": np.array(
                [["alpha", "42", "3.14", "true", None][i % 5] for i in range(5000)],
                dtype=object,
            ),
        }
    )


ANALYZERS = [
    Size(),
    Size(where="n > 500"),
    Completeness("x"),
    Completeness("x", where="n > 500"),
    Compliance("big n", "n >= 100"),
    PatternMatch("s", r"^\d+$"),
    ApproxCountDistinct("n"),
    ApproxCountDistinct("s"),
    DataType("s"),
    # non-discrete members stay on device alongside
    Mean("x"),
    Minimum("x"),
    Maximum("x"),
    Sum("x"),
    StandardDeviation("x"),
    ApproxQuantile("x", 0.5),
]


def _metrics(table, placement, monkeypatch):
    monkeypatch.setenv("DEEQU_TPU_PLACEMENT", placement)
    results = FusedScanPass(ANALYZERS, batch_size=1024).run(table)
    out = {}
    for r in results:
        state = r.state_or_raise()
        out[repr(r.analyzer)] = r.analyzer.compute_metric_from(state).value.get()
    return out


def test_host_placement_matches_device(mixed_table, monkeypatch):
    device = _metrics(mixed_table, "device", monkeypatch)
    host = _metrics(mixed_table, "host", monkeypatch)
    assert device.keys() == host.keys()
    for key in device:
        if key.startswith("ApproxQuantile"):
            # the KLL sketch draws fresh per-batch compaction seeds each
            # run; both values are within the declared rank error, not
            # bit-identical across two executions
            assert device[key] == pytest.approx(host[key], rel=0.05), key
        else:
            assert device[key] == pytest.approx(host[key], rel=1e-12), key


def test_host_placement_skips_device_for_all_discrete(mixed_table, monkeypatch):
    monkeypatch.setenv("DEEQU_TPU_PLACEMENT", "host")
    discrete_only = [a for a in ANALYZERS if getattr(a, "discrete_inputs", False)]
    with runtime.monitored() as stats:
        results = FusedScanPass(discrete_only, batch_size=1024).run(mixed_table)
    assert all(r.error is None for r in results)
    # still ONE logical pass over the data, but zero device launches
    assert stats.device_passes == 1
    assert stats.device_launches == 0


def test_host_placement_isolates_failures(mixed_table, monkeypatch):
    monkeypatch.setenv("DEEQU_TPU_PLACEMENT", "host")
    results = FusedScanPass(
        [Completeness("x"), Compliance("bad", "nonexistent_col > 1"), Size()],
        batch_size=1024,
    ).run(mixed_table)
    assert results[0].error is None
    assert results[1].error is not None  # fails alone
    assert results[2].error is None


def test_distributed_host_placement_parity(monkeypatch):
    import jax
    from deequ_tpu.parallel.distributed import data_mesh, run_distributed_analysis

    if len(jax.devices()) < 2:
        pytest.skip("needs a multi-device mesh")
    mesh8 = data_mesh()

    rng = np.random.default_rng(7)
    table = Table.from_numpy(
        {"x": rng.normal(size=4000), "g": rng.integers(0, 30, 4000)}
    )
    analyzers = [
        Size(),
        Completeness("x"),
        ApproxCountDistinct("g"),
        Mean("x"),
        StandardDeviation("x"),
    ]
    monkeypatch.setenv("DEEQU_TPU_PLACEMENT", "device")
    dev = run_distributed_analysis(table, analyzers, mesh=mesh8)
    monkeypatch.setenv("DEEQU_TPU_PLACEMENT", "host")
    host = run_distributed_analysis(table, analyzers, mesh=mesh8)
    for a in analyzers:
        assert dev.metric_map[a].value.get() == pytest.approx(
            host.metric_map[a].value.get(), rel=1e-12
        ), a


def test_host_all_runs_everything_without_device(mixed_table, monkeypatch):
    """Below the bandwidth floor, EVERY analyzer — including the
    device-assisted quantile sketch — folds on the host: zero launches,
    one logical pass, same metrics (parity asserted above)."""
    monkeypatch.setenv("DEEQU_TPU_PLACEMENT", "host")
    with runtime.monitored() as stats:
        results = FusedScanPass(ANALYZERS, batch_size=1024).run(mixed_table)
    assert all(r.error is None for r in results)
    assert stats.device_passes == 1
    assert stats.device_launches == 0


class TestPlacementDiskCache:
    """The bandwidth probe's measurement persists per (platform, device
    kind) with a TTL; corrupt or foreign cache contents must never crash
    placement_mode."""

    def _fresh(self, monkeypatch, tmp_path):
        monkeypatch.setenv("DEEQU_TPU_CACHE_DIR", str(tmp_path))
        monkeypatch.delenv("DEEQU_TPU_PLACEMENT", raising=False)
        monkeypatch.setattr(runtime, "_PLACEMENT_CACHE", None)

    def test_round_trip(self, monkeypatch, tmp_path):
        self._fresh(monkeypatch, tmp_path)
        runtime._save_bandwidth_to_disk(123456789.0)
        assert runtime._load_bandwidth_from_disk() == 123456789.0

    def test_probe_skipped_when_cached(self, monkeypatch, tmp_path):
        self._fresh(monkeypatch, tmp_path)
        runtime._save_bandwidth_to_disk(5e9)  # fast link -> device
        def boom(*a, **k):
            raise AssertionError("probe must not run when cached")
        monkeypatch.setattr(runtime, "measure_device_bandwidth", boom)
        assert runtime.placement_mode() == "device"

    def test_expired_entry_reprobes(self, monkeypatch, tmp_path):
        self._fresh(monkeypatch, tmp_path)
        runtime._save_bandwidth_to_disk(5e9)
        monkeypatch.setattr(
            runtime.time, "time",
            lambda base=runtime.time.time(): base + runtime.PLACEMENT_CACHE_TTL_S + 1,
        )
        assert runtime._load_bandwidth_from_disk() is None

    @pytest.mark.parametrize(
        "content", ["null", "[\"device\"]", "{\"x\": \"y\"", "{\"a\": 1}",
                    '{"cpu:cpu": {"bandwidth": -5, "ts": 0}}']
    )
    def test_corrupt_cache_is_ignored(self, monkeypatch, tmp_path, content):
        self._fresh(monkeypatch, tmp_path)
        (tmp_path / "placement.json").write_text(content)
        assert runtime._load_bandwidth_from_disk() is None
        # and saving over garbage works
        runtime._save_bandwidth_to_disk(1e6)
        assert runtime._load_bandwidth_from_disk() == 1e6

    def test_classification_uses_current_thresholds(self, monkeypatch, tmp_path):
        self._fresh(monkeypatch, tmp_path)
        runtime._save_bandwidth_to_disk(500e6)  # mid-speed link
        assert runtime.placement_mode() == "host-discrete"

"""The plan-subsumption prover (deequ_tpu/lint/subsume.py): static
proofs that "suite A ⊆ scan S", sound under three-valued NaN/NULL
predicate semantics, with plan-environment components never silently
merged (ISSUE 17 tentpole).

Soundness bar: a CONTAINED(-WITH-RESIDUAL) verdict promises the scan's
folded states fan back out to the suite bit-identically over the state
semigroup. Everything the prover cannot PROVE must come back
INCOMPARABLE — in particular one-way where implication, which covers a
superset of rows no post-hoc step can narrow.
"""

from __future__ import annotations

from deequ_tpu.analyzers import ApproxQuantile, Completeness, Compliance, Mean, Size
from deequ_tpu.data.table import ColumnType
from deequ_tpu.lint import FieldInfo, SchemaInfo
from deequ_tpu.lint.explain import sharing_diagnostics
from deequ_tpu.lint.subsume import (
    CONTAINED,
    CONTAINED_WITH_RESIDUAL,
    EQUIVALENT_WHERE,
    EXACT,
    INCOMPARABLE,
    PlanEnv,
    prove_subsumption,
    where_implies,
    wheres_equivalent,
)

SCHEMA = SchemaInfo(
    [
        FieldInfo("item", ColumnType.STRING, nullable=False),
        FieldInfo("att1", ColumnType.STRING, nullable=True),
        FieldInfo("count", ColumnType.LONG, nullable=True),
        FieldInfo("price", ColumnType.DOUBLE, nullable=True),
    ]
)


# ---------------------------------------------------------------------------
# where-clause implication over the Kleene lattice
# ---------------------------------------------------------------------------


def test_where_implies_strict_subset_one_way():
    assert where_implies("count > 1", "count > 0", SCHEMA)
    assert not where_implies("count > 0", "count > 1", SCHEMA)


def test_where_none_is_constant_true():
    # everything is a subset of "no filter"...
    assert where_implies("price > 0", None, SCHEMA)
    # ...but "no filter" includes NULL rows every comparison excludes,
    # so constant-true never implies a comparison on a nullable column
    assert not where_implies(None, "price >= 0", SCHEMA)


def test_wheres_equivalent_mutual_not_one_way():
    assert wheres_equivalent("(count > 0)", "count > 0", SCHEMA)
    assert wheres_equivalent(None, None, SCHEMA)
    assert not wheres_equivalent("count >= 0", "count > 0", SCHEMA)


def test_where_parse_failure_proves_nothing():
    assert not where_implies("count >>> bogus", "count > 0", SCHEMA)
    assert not wheres_equivalent("count >>> bogus", "count >>> bogus2", SCHEMA)


# ---------------------------------------------------------------------------
# verdicts
# ---------------------------------------------------------------------------


def test_exact_subset_is_contained():
    suite = [Completeness("item"), Mean("price")]
    scan = [Completeness("item"), Mean("price"), Size(), Completeness("att1")]
    proof = prove_subsumption(suite, scan, SCHEMA)
    assert proof.verdict == CONTAINED
    assert proof.contained
    assert [o.kind for o in proof.obligations] == [EXACT, EXACT]
    assert all(o.target == o.analyzer for o in proof.obligations)
    assert proof.summary().startswith("CONTAINED: 2/2")


def test_suite_duplicates_dedupe_to_one_obligation():
    suite = [Mean("price"), Mean("price"), Mean("price")]
    proof = prove_subsumption(suite, [Mean("price")], SCHEMA)
    assert proof.verdict == CONTAINED
    assert len(proof.obligations) == 1


def test_equivalent_where_spelling_is_residual_not_exact():
    suite = [Mean("price", where="(count > 0)")]
    scan = [Mean("price", where="count > 0")]
    proof = prove_subsumption(suite, scan, SCHEMA)
    assert proof.verdict == CONTAINED_WITH_RESIDUAL
    assert proof.contained
    (ob,) = proof.obligations
    assert ob.kind == EQUIVALENT_WHERE
    assert ob.target == repr(scan[0])
    assert "equivalent" in ob.detail


def test_one_way_implication_is_never_containment():
    # the scan's weaker predicate folds MORE rows into its state; the
    # suite's metric cannot be recovered from it
    suite = [Mean("price", where="count > 1")]
    scan = [Mean("price", where="count > 0")]
    proof = prove_subsumption(suite, scan, SCHEMA)
    assert proof.verdict == INCOMPARABLE
    assert not proof.contained
    (ob,) = proof.obligations
    assert not ob.satisfied
    assert "cannot be narrowed" in ob.detail
    assert ob.where == "count > 1"


def test_adversarial_near_equivalence_declines():
    # >= vs > differ exactly on the boundary row: not equivalent, and
    # neither direction's one-way fact makes it containment
    suite = [Completeness("att1", where="count >= 0")]
    scan = [Completeness("att1", where="count > 0")]
    proof = prove_subsumption(suite, scan, SCHEMA)
    assert proof.verdict == INCOMPARABLE
    (ob,) = proof.obligations
    assert "not provably equivalent" in ob.detail or "cannot be narrowed" in ob.detail


def test_param_mismatch_is_not_a_where_problem():
    proof = prove_subsumption([Completeness("item")], [Completeness("att1")], SCHEMA)
    assert proof.verdict == INCOMPARABLE
    (ob,) = proof.obligations
    assert "differs in parameters" in ob.detail


def test_missing_family_reports_no_analyzer_of_type():
    proof = prove_subsumption([ApproxQuantile("price", 0.5)], [Size()], SCHEMA)
    assert proof.verdict == INCOMPARABLE
    (ob,) = proof.obligations
    assert ob.detail == "no scan analyzer of this type"


def test_compliance_predicate_is_a_param_not_a_where():
    # the Compliance PREDICATE is identity, not filtering: two different
    # predicates are different analyzers even with equivalent wheres
    a = Compliance("rule", "count > 1")
    s = Compliance("rule", "count > 0")
    proof = prove_subsumption([a], [s], SCHEMA)
    assert proof.verdict == INCOMPARABLE


# ---------------------------------------------------------------------------
# plan environments: signature components are never merged
# ---------------------------------------------------------------------------


def test_env_component_mismatch_is_incomparable_even_for_equal_sets():
    suite = [Mean("price")]
    host = PlanEnv(placement="host", compute_dtype="float64", fold_variant="pairwise")
    for other in (
        PlanEnv(placement="device", compute_dtype="float64", fold_variant="pairwise"),
        PlanEnv(placement="host", compute_dtype="float32", fold_variant="pairwise"),
        PlanEnv(placement="host", compute_dtype="float64", fold_variant="linear"),
        PlanEnv(
            placement="host",
            compute_dtype="float64",
            fold_variant="pairwise",
            batch_rows=4096,
        ),
    ):
        proof = prove_subsumption(
            suite, suite, SCHEMA, suite_env=host, scan_env=other
        )
        assert proof.verdict == INCOMPARABLE, other
        assert proof.env_mismatches
        assert "environments differ" in proof.summary()


def test_equal_envs_do_not_disturb_the_verdict():
    env = PlanEnv(placement="device", compute_dtype="float64", fold_variant="pairwise")
    proof = prove_subsumption(
        [Mean("price")], [Mean("price")], SCHEMA, suite_env=env, scan_env=env
    )
    assert proof.verdict == CONTAINED
    assert proof.env_mismatches == ()


# ---------------------------------------------------------------------------
# proof pinning against traced execution
# ---------------------------------------------------------------------------


def test_pin_zero_drift_when_targets_executed():
    suite = [Completeness("item"), Mean("price", where="(count > 0)")]
    scan = [Completeness("item"), Mean("price", where="count > 0")]
    proof = prove_subsumption(suite, scan, SCHEMA)
    assert proof.contained
    executed = [repr(a) for a in scan]
    assert proof.pin(executed) == {
        "obligations_unexecuted": 0,
        "obligations_unproven": 0,
        "env_mismatches": 0,
    }


def test_pin_counts_unexecuted_targets():
    suite = [Completeness("item"), Mean("price")]
    proof = prove_subsumption(suite, suite, SCHEMA)
    drift = proof.pin([repr(Completeness("item"))])
    assert drift["obligations_unexecuted"] == 1


def test_to_dict_is_json_shaped():
    import json

    proof = prove_subsumption([Mean("price")], [Size()], SCHEMA)
    payload = proof.to_dict()
    assert json.loads(json.dumps(payload)) == payload
    assert payload["verdict"] == INCOMPARABLE


# ---------------------------------------------------------------------------
# DQ321 / DQ322 diagnostics
# ---------------------------------------------------------------------------


def test_dq321_on_contained_proof():
    proof = prove_subsumption([Mean("price")], [Mean("price"), Size()], SCHEMA)
    diags = sharing_diagnostics(proof)
    assert [d.code for d in diags] == ["DQ321"]
    assert "superset scan" in diags[0].message


def test_dq322_caret_lands_on_the_offending_where():
    proof = prove_subsumption(
        [Mean("price", where="count >= 0")],
        [Mean("price", where="count > 0")],
        SCHEMA,
    )
    diags = sharing_diagnostics(proof)
    assert [d.code for d in diags] == ["DQ322"]
    d = diags[0]
    assert d.source == "count >= 0"
    assert d.span == (0, len("count >= 0"))
    rendered = d.render()
    assert "^" in rendered


def test_dq322_per_env_mismatch():
    env_a = PlanEnv(fold_variant="pairwise")
    env_b = PlanEnv(fold_variant="linear")
    proof = prove_subsumption(
        [Mean("price")], [Mean("price")], SCHEMA, suite_env=env_a, scan_env=env_b
    )
    diags = sharing_diagnostics(proof)
    assert [d.code for d in diags] == ["DQ322"]
    assert "fold_variant" in diags[0].message


def test_validate_plan_carries_sharing_diagnostics():
    from deequ_tpu import Check, CheckLevel
    from deequ_tpu.lint.planlint import validate_plan

    check = Check(CheckLevel.ERROR, "shared").has_mean("price", lambda m: True)
    scan = [Mean("price"), Completeness("item")]
    report = validate_plan(
        SCHEMA, [check], mode="lenient", num_rows=100, sharing_with=scan
    )
    assert "DQ321" in [d.code for d in report.diagnostics]


def test_explain_renders_the_sharing_line():
    from deequ_tpu.lint.explain import explain_plan

    result = explain_plan(
        SCHEMA,
        analyzers=[Mean("price")],
        num_rows=100,
        sharing_with=[Mean("price"), Size()],
    )
    assert result.sharing is not None
    assert result.sharing.verdict == CONTAINED
    text = result.render()
    assert "sharing: CONTAINED" in text


def test_explain_sharing_line_absent_without_candidate():
    from deequ_tpu.lint.explain import explain_plan

    result = explain_plan(SCHEMA, analyzers=[Mean("price")], num_rows=100)
    assert result.sharing is None
    assert "sharing:" not in result.render()

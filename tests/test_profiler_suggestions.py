"""Profiler, suggestion, applicability and schema-validator tests
(mirrors reference ColumnProfilerTest, ConstraintRulesTest,
ConstraintSuggestionsIntegrationTest, ApplicabilityTest,
RowLevelSchemaValidatorTest)."""

import json

import numpy as np
import pytest

from deequ_tpu.data.table import ColumnType, Table
from deequ_tpu.ops import runtime
from deequ_tpu.profiles import (
    ColumnProfilerRunner,
    NumericColumnProfile,
    StandardColumnProfile,
)
from deequ_tpu.suggestions import (
    CategoricalRangeRule,
    CompleteIfCompleteRule,
    ConstraintSuggestionRunner,
    NonNegativeNumbersRule,
    RetainCompletenessRule,
    RetainTypeRule,
    Rules,
    UniqueIfApproximatelyUniqueRule,
)


def example_table(n=120):
    rng = np.random.default_rng(0)
    return Table.from_pydict(
        {
            "id": list(range(n)),
            "name": [f"name_{i}" for i in range(n)],
            "status": [["active", "inactive", "pending"][i % 3] for i in range(n)],
            "amountStr": [str(i * 10) for i in range(n)],
            "score": [float(i) / 2 if i % 10 != 0 else None for i in range(n)],
            "flag": [bool(i % 2) for i in range(n)],
        }
    )


class TestColumnProfiler:
    def test_pass_budget(self):
        # the reference always pays 3 scans; ours pays ONE: pass-2
        # numeric stats for inferred-numeric strings (amountStr) ride
        # pass 1 optimistically (_OptimisticNumericStats — sound because
        # a numeric inference verdict implies every value cast cleanly)
        # and pass-3 histogram counting folds in via _LowCardCounts
        data = example_table()
        with runtime.monitored() as stats:
            profiles = ColumnProfilerRunner.on_data(data).run()
        assert stats.jobs == 1
        assert profiles.num_records == 120

    def test_repository_reuse_covers_both_passes(self):
        """Every pass threads the repository options (the reference does
        too, ColumnProfiler.scala:128-153): a saved key holds metrics
        from pass 1 AND the cast pass, and a strict reuse-run against it
        recomputes nothing."""
        from deequ_tpu.repository.base import ResultKey
        from deequ_tpu.repository.memory import InMemoryMetricsRepository

        data = example_table()
        repo = InMemoryMetricsRepository()
        key = ResultKey(1234, {"run": "a"})
        first = (
            ColumnProfilerRunner.on_data(data)
            .use_repository(repo)
            .save_or_append_result(key)
            .run()
        )
        # amountStr is an inferred-numeric STRING column -> its stats come
        # from the cast pass and must have been saved too
        saved = repo.load_by_key(key)
        assert any(
            getattr(a, "column", None) == "amountStr" and a.name == "Mean"
            for a in saved.metric_map
        )
        with runtime.monitored() as stats:
            second = (
                ColumnProfilerRunner.on_data(data)
                .use_repository(repo)
                .reuse_existing_results_for_key(key, fail_if_results_missing=True)
                .run()
            )
        assert stats.device_launches == 0  # everything served from the repo
        assert second.profiles["amountStr"].mean == first.profiles["amountStr"].mean
        assert second.profiles["id"].mean == first.profiles["id"].mean

    def test_two_passes_without_numeric_strings(self):
        # no inferred-numeric string columns -> still one fused pass
        # (histograms fold into pass 1 via _LowCardCounts)
        data = Table.from_pydict(
            {
                "id": list(range(50)),
                "score": [float(i) for i in range(50)],
                "status": [["a", "b"][i % 2] for i in range(50)],
            }
        )
        with runtime.monitored() as stats:
            profiles = ColumnProfilerRunner.on_data(data).run()
        assert stats.jobs == 1
        # schema-numeric stats still fully populated from pass 1
        assert profiles.profiles["id"].mean == pytest.approx(24.5)
        assert profiles.profiles["score"].maximum == 49.0

    def test_profile_contents(self):
        data = example_table()
        profiles = ColumnProfilerRunner.on_data(data).run()

        id_profile = profiles.profiles["id"]
        assert isinstance(id_profile, NumericColumnProfile)
        assert id_profile.data_type == "Integral"
        assert not id_profile.is_data_type_inferred
        assert id_profile.completeness == 1.0
        assert id_profile.minimum == 0.0
        assert id_profile.maximum == 119.0
        assert id_profile.mean == pytest.approx(59.5)
        assert id_profile.sum == pytest.approx(7140.0)
        assert len(id_profile.approx_percentiles) == 100

        # string column inferred integral -> numeric profile with stats
        amount = profiles.profiles["amountStr"]
        assert isinstance(amount, NumericColumnProfile)
        assert amount.data_type == "Integral"
        assert amount.is_data_type_inferred
        assert amount.minimum == 0.0
        assert amount.maximum == 1190.0

        status = profiles.profiles["status"]
        assert isinstance(status, StandardColumnProfile)
        assert status.data_type == "String"
        assert status.histogram is not None
        assert status.histogram["active"].absolute == 40

        score = profiles.profiles["score"]
        assert score.completeness == pytest.approx(108 / 120)

        flag = profiles.profiles["flag"]
        assert flag.data_type == "Boolean"
        assert flag.histogram is not None
        assert flag.histogram["true"].absolute == 60

    def test_restrict_to_columns(self):
        data = example_table()
        profiles = (
            ColumnProfilerRunner.on_data(data).restrict_to_columns(["id", "status"]).run()
        )
        assert set(profiles.profiles) == {"id", "status"}

    def test_cardinality_threshold(self):
        data = example_table()
        profiles = (
            ColumnProfilerRunner.on_data(data)
            .with_low_cardinality_histogram_threshold(2)
            .run()
        )
        assert profiles.profiles["status"].histogram is None

    def test_json_export(self, tmp_path):
        data = example_table()
        path = str(tmp_path / "profiles.json")
        ColumnProfilerRunner.on_data(data).save_column_profiles_json_to_path(path).run()
        with open(path) as f:
            parsed = json.load(f)
        by_column = {c["column"]: c for c in parsed["columns"]}
        assert by_column["id"]["dataType"] == "Integral"
        assert "histogram" in by_column["status"]


class TestSuggestionRules:
    def profile_for(self, data):
        from deequ_tpu.profiles import ColumnProfiler

        return ColumnProfilerRunner.on_data(data).run()

    def test_complete_if_complete(self):
        profiles = self.profile_for(example_table())
        rule = CompleteIfCompleteRule()
        assert rule.should_be_applied(profiles.profiles["id"], 120)
        assert not rule.should_be_applied(profiles.profiles["score"], 120)
        suggestion = rule.candidate(profiles.profiles["id"], 120)
        assert suggestion.code_for_constraint == '.is_complete("id")'

    def test_retain_completeness(self):
        profiles = self.profile_for(example_table())
        rule = RetainCompletenessRule()
        assert rule.should_be_applied(profiles.profiles["score"], 120)
        suggestion = rule.candidate(profiles.profiles["score"], 120)
        assert ".has_completeness" in suggestion.code_for_constraint

    def test_retain_type(self):
        profiles = self.profile_for(example_table())
        rule = RetainTypeRule()
        assert rule.should_be_applied(profiles.profiles["amountStr"], 120)
        assert not rule.should_be_applied(profiles.profiles["id"], 120)  # not inferred
        suggestion = rule.candidate(profiles.profiles["amountStr"], 120)
        assert "ConstrainableDataTypes.INTEGRAL" in suggestion.code_for_constraint

    def test_categorical_range(self):
        profiles = self.profile_for(example_table())
        rule = CategoricalRangeRule()
        assert rule.should_be_applied(profiles.profiles["status"], 120)
        suggestion = rule.candidate(profiles.profiles["status"], 120)
        assert '"active"' in suggestion.code_for_constraint

    def test_non_negative(self):
        profiles = self.profile_for(example_table())
        rule = NonNegativeNumbersRule()
        assert rule.should_be_applied(profiles.profiles["id"], 120)
        suggestion = rule.candidate(profiles.profiles["id"], 120)
        assert suggestion.code_for_constraint == '.is_non_negative("id")'

    def test_unique_if_approximately_unique(self):
        profiles = self.profile_for(example_table())
        rule = UniqueIfApproximatelyUniqueRule()
        assert rule.should_be_applied(profiles.profiles["id"], 120)
        assert not rule.should_be_applied(profiles.profiles["status"], 120)


class TestSuggestionRunner:
    def test_end_to_end(self):
        data = example_table()
        result = (
            ConstraintSuggestionRunner.on_data(data)
            .add_constraint_rules(Rules.DEFAULT)
            .run()
        )
        codes = [s.code_for_constraint for s in result.all_suggestions()]
        assert '.is_complete("id")' in codes
        assert any(".is_contained_in" in c for c in codes)
        parsed = json.loads(result.suggestions_as_json())
        assert len(parsed["constraint_suggestions"]) == len(codes)

    def test_train_test_split_evaluation(self):
        data = example_table(400)
        result = (
            ConstraintSuggestionRunner.on_data(data)
            .add_constraint_rules(Rules.DEFAULT)
            .use_train_test_split_with_test_set_ratio(0.25, seed=7)
            .run()
        )
        assert result.verification_result is not None
        # generated constraints should mostly hold on the test split
        check_result = list(result.verification_result.check_results.values())[0]
        from deequ_tpu.constraints.constraint import ConstraintStatus

        statuses = [r.status for r in check_result.constraint_results]
        assert statuses.count(ConstraintStatus.SUCCESS) >= len(statuses) - 1


class TestApplicability:
    def test_applicable_check(self):
        from deequ_tpu import Check, CheckLevel
        from deequ_tpu.applicability import Applicability
        from deequ_tpu.applicability.applicability import SchemaField

        schema = [
            SchemaField("item", ColumnType.STRING),
            SchemaField("count", ColumnType.LONG, nullable=False),
        ]
        check = (
            Check(CheckLevel.ERROR, "c")
            .is_complete("count")
            .has_min("count", lambda v: v > -(2**32))
        )
        result = Applicability().is_applicable(check, schema)
        assert result.is_applicable

    def test_detects_missing_column(self):
        from deequ_tpu import Check, CheckLevel
        from deequ_tpu.applicability import Applicability
        from deequ_tpu.applicability.applicability import SchemaField

        schema = [SchemaField("item", ColumnType.STRING)]
        check = Check(CheckLevel.ERROR, "c").is_complete("notHere")
        result = Applicability().is_applicable(check, schema)
        assert not result.is_applicable
        assert len(result.failures) == 1

    def test_detects_invalid_sql(self):
        from deequ_tpu import Check, CheckLevel
        from deequ_tpu.applicability import Applicability
        from deequ_tpu.applicability.applicability import SchemaField

        schema = [SchemaField("item", ColumnType.STRING)]
        check = Check(CheckLevel.ERROR, "c").satisfies("!!invalid sql!!", "bad")
        result = Applicability().is_applicable(check, schema)
        assert not result.is_applicable

    def test_generated_data_shapes(self):
        from deequ_tpu.applicability.applicability import SchemaField, generate_random_data

        schema = [
            SchemaField("s", ColumnType.STRING),
            SchemaField("i", ColumnType.LONG),
            SchemaField("f", ColumnType.DOUBLE),
            SchemaField("b", ColumnType.BOOLEAN),
            SchemaField("d", ColumnType.DECIMAL, precision=6, scale=2),
            SchemaField("t", ColumnType.TIMESTAMP),
            SchemaField("nn", ColumnType.LONG, nullable=False),
        ]
        data = generate_random_data(schema, 1000, seed=1)
        assert data.num_rows == 1000
        assert data["nn"].null_count == 0
        # ~1% nulls for nullable fields
        assert 0 <= data["s"].null_count <= 50


class TestRowLevelSchemaValidator:
    def test_valid_invalid_split(self):
        from deequ_tpu.schema import RowLevelSchema, RowLevelSchemaValidator

        data = Table.from_pydict(
            {
                "id": ["1", "2", "x", "4", None],
                "name": ["a", "bb", "ccc", "", "e"],
                "ts": [
                    "2024-01-01 10:00:00",
                    "2024-02-30 10:00:00",  # invalid date
                    "2024-03-01 11:00:00",
                    "2024-04-01 12:00:00",
                    "2024-05-01 13:00:00",
                ],
            }
        )
        schema = (
            RowLevelSchema()
            .with_int_column("id", is_nullable=False, min_value=1)
            .with_string_column("name", min_length=1)
            .with_timestamp_column("ts", mask="yyyy-MM-dd HH:mm:ss")
        )
        result = RowLevelSchemaValidator.validate(data, schema)
        # row0 ok; row1 bad ts; row2 bad int; row3 empty name; row4 null id
        assert result.num_valid_rows == 1
        assert result.num_invalid_rows == 4
        assert result.valid_rows["id"].ctype == ColumnType.LONG
        assert int(result.valid_rows["id"].values[0]) == 1

    def test_int_bounds(self):
        from deequ_tpu.schema import RowLevelSchema, RowLevelSchemaValidator

        data = Table.from_pydict({"v": ["5", "15", "25"]})
        schema = RowLevelSchema().with_int_column("v", min_value=10, max_value=20)
        result = RowLevelSchemaValidator.validate(data, schema)
        assert result.num_valid_rows == 1
        assert int(result.valid_rows["v"].values[0]) == 15

    def test_string_regex(self):
        from deequ_tpu.schema import RowLevelSchema, RowLevelSchemaValidator

        data = Table.from_pydict({"code": ["AB-1", "XY-2", "bad"]})
        schema = RowLevelSchema().with_string_column("code", matches=r"^[A-Z]{2}-\d$")
        result = RowLevelSchemaValidator.validate(data, schema)
        assert result.num_valid_rows == 2


class TestLowCardCountsCap:
    def test_cumulative_distinct_cap_aborts_merge(self):
        """A stream whose batches each stay under the cap but whose
        cumulative dictionary does not must abort (bounded memory), not
        grow without bound (reviewer finding, round 4)."""
        from deequ_tpu.profiles.internal_analyzers import LowCardCountsState

        state = None
        for batch in range(10):
            partial = LowCardCountsState(
                tuple((f"v{batch}_{i}", 1) for i in range(100)), 0, False, 300
            )
            state = partial if state is None else state.merge(partial)
        assert state.aborted
        assert state.counts == ()

    def test_streamed_rotating_values_fall_back_to_straggler_pass(self, tmp_path):
        """End-to-end: rotating per-batch dictionaries abort the fused
        counting; the profiler's straggler pass never runs because the
        HLL estimate exceeds the threshold (no histogram wanted)."""
        import pyarrow as pa
        import pyarrow.parquet as pq

        rows = []
        for g in range(6):
            rows.extend([f"g{g}_v{i}" for i in range(200)] * 5)
        table = pa.table({"s": rows, "x": list(range(len(rows)))})
        path = str(tmp_path / "rot.parquet")
        pq.write_table(table, path, row_group_size=1000)
        profiles = ColumnProfilerRunner.on_data(
            Table.scan_parquet(path, batch_rows=1000)
        ).run()
        assert profiles.profiles["s"].histogram is None  # 1200 distinct > 120


class TestOptimisticPass2Fallback:
    def test_regex_numeric_but_uncastable_falls_back_to_pass2(self):
        """THE soundness edge: '+ 5' matches the Integral regex
        (reference: StatefulDataType.scala:37 allows one space after the
        sign) but float() cannot parse it. Inference says Integral, the
        optimistic state dies, and the profiler must pay a real pass 2
        whose cast nulls the unparseable value — same as the reference's
        cast semantics."""
        data = Table.from_pydict(
            {"v": ["+ 5", "3", "7", None] * 30}
        )
        with runtime.monitored() as stats:
            profiles = ColumnProfilerRunner.on_data(data).run()
        p = profiles.profiles["v"]
        assert p.data_type == "Integral"  # regex-based inference
        # cast: '+ 5' -> null; mean over {3,7}
        assert p.mean == pytest.approx(5.0)
        assert stats.jobs == 2  # optimistic died -> classic pass 2 ran

    def test_differential_profile_vs_pandas(self):
        """Randomized differential: the one-pass profile must match a
        straightforward pandas ground truth on exact statistics for
        mixed schemas with nulls, numeric strings, empty strings and
        unicode."""
        import pandas as pd

        rng = np.random.default_rng(123)
        for trial in range(5):
            n = int(rng.integers(200, 3000))
            num = rng.normal(10, 3, n)
            num[rng.random(n) < 0.1] = np.nan
            codes = np.array(
                [str(v) for v in rng.integers(-50, 50, n)], dtype=object
            )
            cats = np.array(
                ["α", "beta", "", "Ωmega", None], dtype=object
            )[rng.integers(0, 5, n)]
            flags = np.where(rng.random(n) > 0.2, rng.random(n) < 0.5, None)
            t = Table.from_numpy(
                {"num": num, "code": codes, "cat": cats, "flag": flags}
            )
            profiles = ColumnProfilerRunner.on_data(t).run()

            s = pd.Series(num)
            p = profiles.profiles["num"]
            assert p.completeness == pytest.approx(s.notna().mean())
            assert p.mean == pytest.approx(s.mean(), rel=1e-9)
            assert p.minimum == s.min() and p.maximum == s.max()
            assert p.std_dev == pytest.approx(s.std(ddof=0), rel=1e-9)

            pc = profiles.profiles["code"]
            cast = pd.to_numeric(pd.Series(codes), errors="coerce")
            assert pc.data_type == "Integral"
            assert pc.mean == pytest.approx(cast.mean(), rel=1e-9)
            assert pc.sum == pytest.approx(cast.sum(), rel=1e-9)

            pcat = profiles.profiles["cat"]
            counts = pd.Series(cats).value_counts(dropna=False)
            hist = {k: v.absolute for k, v in pcat.histogram.values.items()}
            want = {
                ("NullValue" if pd.isna(k) else str(k)): int(c)
                for k, c in counts.items()
            }
            assert hist == want, (trial, hist, want)

            pf = profiles.profiles["flag"]
            fs = pd.Series(list(flags))
            assert pf.completeness == pytest.approx(fs.notna().mean())

"""Row-group pushdown tests (ISSUE 7 tentpole + satellites).

Covers the interval lattice, the three-valued stats interpreter and its
NaN/NULL soundness edge cases (all-NULL groups, NaN-polluted float
min/max, untrusted string min/max, absent statistics), the prune-plan
skip/elision rules and the exact decode-batch replay, the
ParquetSource prune/projection composition, the end-to-end skip path
(trace counters, bit-identical metrics vs DEEQU_TPU_PUSHDOWN=0,
predicted == observed skipped groups), and the DQ310/DQ311 lints.
"""

from __future__ import annotations

import math

import numpy as np
import pytest

from deequ_tpu.analyzers import Completeness, Compliance, Maximum, Mean, Size
from deequ_tpu.data.expr import parse
from deequ_tpu.data.table import ColumnType, Table
from deequ_tpu.lint import explain_plan
from deequ_tpu.lint.cost import cost_drift
from deequ_tpu.lint.fold import dnf_branches
from deequ_tpu.lint.interval import Interval
from deequ_tpu.lint.pushdown import (
    ALL_FALSE,
    ALL_TRUE,
    UNKNOWN,
    ColumnStats,
    RowGroupStats,
    build_prune_plan,
    predicate_verdict,
)
from deequ_tpu.runners import AnalysisRunner

TYPES = {
    "k": ColumnType.LONG,
    "v": ColumnType.DOUBLE,
    "s": ColumnType.STRING,
}


def group(rows=1000, index=0, **cols):
    """RowGroupStats from kwargs: k=(min, max, null_count) tuples."""
    built = {
        name: ColumnStats(min_value=mn, max_value=mx, null_count=nc)
        for name, (mn, mx, nc) in cols.items()
    }
    return RowGroupStats(index=index, num_rows=rows, columns=built)


def verdict(text, grp, types=TYPES):
    branches = dnf_branches(parse(text))
    assert branches is not None
    return predicate_verdict(branches, grp, types)


# ---------------------------------------------------------------------------
# interval lattice
# ---------------------------------------------------------------------------


class TestInterval:
    def test_from_cmp_shapes(self):
        assert Interval.from_cmp("eq", 3.0) == Interval.point(3.0)
        lt = Interval.from_cmp("lt", 3.0)
        assert lt.hi == 3.0 and lt.hi_strict and lt.lo == -math.inf
        ge = Interval.from_cmp("ge", 3.0)
        assert ge.lo == 3.0 and not ge.lo_strict and ge.hi == math.inf
        with pytest.raises(ValueError):
            Interval.from_cmp("ne", 3.0)

    def test_narrow_tightens_and_strictness_wins_on_ties(self):
        iv = Interval.top().narrow("ge", 0.0).narrow("le", 10.0)
        assert iv == Interval.closed(0.0, 10.0)
        # same bound, strict beats non-strict
        assert iv.narrow("gt", 0.0).lo_strict
        # looser bound never widens
        assert iv.narrow("ge", -5.0) == iv

    def test_emptiness_and_points(self):
        assert Interval.closed(5.0, 1.0).is_empty
        assert Interval.top().narrow("gt", 3.0).narrow("lt", 3.0).is_empty
        assert Interval.top().narrow("ge", 3.0).narrow("le", 3.0).is_point
        assert not Interval.closed(1.0, 2.0).is_empty

    def test_contains_and_disjoint(self):
        dom = Interval.closed(0.0, 10.0)
        assert Interval.from_cmp("ge", -1.0).contains(dom)
        assert not Interval.from_cmp("gt", 0.0).contains(dom)
        assert dom.disjoint(Interval.from_cmp("gt", 10.0))
        assert not dom.disjoint(Interval.from_cmp("ge", 10.0))
        assert dom.contains_point(10.0)
        assert not Interval.from_cmp("lt", 10.0).contains_point(10.0)


# ---------------------------------------------------------------------------
# atom/predicate verdicts over synthetic statistics
# ---------------------------------------------------------------------------


class TestVerdicts:
    def test_long_range_reasoning(self):
        g = group(k=(0, 10, 0))
        assert verdict("k > 100", g) == ALL_FALSE
        assert verdict("k < 0", g) == ALL_FALSE
        assert verdict("k > 5", g) == UNKNOWN
        assert verdict("k >= 0", g) == ALL_TRUE
        assert verdict("k <= 10", g) == ALL_TRUE

    def test_long_all_true_needs_zero_nulls(self):
        # a null row evaluates FALSE under any comparison, so containment
        # alone cannot prove all-true
        g = group(k=(0, 10, 3))
        assert verdict("k >= 0", g) == UNKNOWN
        assert verdict("k > 100", g) == ALL_FALSE

    def test_double_never_proves_all_true(self):
        # parquet stats ignore NaN and the engine folds NaN into the null
        # mask at decode: null_count==0 does NOT mean no runtime nulls
        g = group(v=(0.0, 10.0, 0))
        assert verdict("v >= -5", g) == UNKNOWN
        assert verdict("v > 100", g) == ALL_FALSE

    def test_all_null_group_falsifies_comparisons(self):
        g = group(rows=100, v=(None, None, 100), k=(None, None, 100))
        assert verdict("v > 0", g) == ALL_FALSE
        assert verdict("k != 7", g) == ALL_FALSE
        assert verdict("v IS NULL", g) == ALL_TRUE
        assert verdict("v IS NOT NULL", g) == ALL_FALSE

    def test_nan_polluted_min_max_degrades_to_unknown(self):
        g = group(v=(float("nan"), float("nan"), 0))
        assert verdict("v > 100", g) == UNKNOWN
        assert verdict("v < -100", g) == UNKNOWN

    def test_string_min_max_never_consulted(self):
        # even "usable-looking" string bounds stay untrusted (writers may
        # truncate them); only null_count reasoning applies to strings
        g = group(s=("aaa", "bbb", 0))
        assert verdict("s > 'zzz'", g) == UNKNOWN
        assert verdict("s = 'x'", g) == UNKNOWN
        assert verdict("s IS NOT NULL", g) == ALL_TRUE
        assert verdict("s IS NULL", g) == ALL_FALSE

    def test_double_null_atom_stays_unknown_at_zero_nulls(self):
        # null_count is only a LOWER bound for DOUBLE (hidden NaN)
        g = group(v=(0.0, 1.0, 0))
        assert verdict("v IS NOT NULL", g) == UNKNOWN
        assert verdict("v IS NULL", g) == UNKNOWN

    def test_missing_stats_degrade_to_unknown(self):
        g = RowGroupStats(index=0, num_rows=10, columns={})
        assert verdict("k > 5", g) == UNKNOWN
        assert verdict("k IS NULL", g) == UNKNOWN

    def test_empty_group_is_all_false(self):
        g = group(rows=0, k=(None, None, 0))
        assert verdict("k >= 0", g) == ALL_FALSE

    def test_ne_semantics(self):
        const = group(k=(7, 7, 0))
        assert verdict("k != 7", const) == ALL_FALSE
        wide = group(k=(0, 10, 0))
        assert verdict("k != 100", wide) == ALL_TRUE
        assert verdict("k != 5", wide) == UNKNOWN
        # DOUBLE: outside-range != cannot prove all-true (hidden NaN)
        dbl = group(v=(0.0, 10.0, 0))
        assert verdict("v != 100", dbl) == UNKNOWN
        assert verdict("v != 7", group(v=(7.0, 7.0, 0))) == ALL_FALSE

    def test_boolean_combinations(self):
        g = group(k=(0, 10, 0))
        assert verdict("k > 100 or k < -5", g) == ALL_FALSE
        assert verdict("k >= 0 and k <= 10", g) == ALL_TRUE
        assert verdict("k > 5 or k >= 0", g) == ALL_TRUE
        # atoms are judged independently against the statistics;
        # intra-clause unsatisfiability (k > 5 and k < 3) is DQ204's job
        assert verdict("k > 5 and k < 3", g) == UNKNOWN
        assert verdict("k > 5 and k > 100", g) == ALL_FALSE
        assert verdict("k > 5 or s = 'x'", g) == UNKNOWN


# ---------------------------------------------------------------------------
# prune plan: skip rule, elision, decode replay
# ---------------------------------------------------------------------------


GROUPS = [
    group(rows=100, index=0, k=(0, 9, 0)),
    group(rows=100, index=1, k=(10, 19, 0)),
    group(rows=100, index=2, k=(20, 29, 0)),
]


class TestPrunePlan:
    def test_skips_groups_proven_all_false_by_every_predicate(self):
        plan = build_prune_plan(["k < 10", "k < 15"], GROUPS, TYPES)
        assert plan.prunable
        # group 1 overlaps "k < 15" -> survives; group 2 is all-false for both
        assert plan.skip == frozenset({2})
        assert plan.skipped_rows == 100 and plan.decoded_rows == 200

    def test_unfiltered_member_blocks_all_skipping(self):
        plan = build_prune_plan(["k < 10", None], GROUPS, TYPES)
        assert not plan.prunable
        assert plan.skip == frozenset()
        # verdicts still computed (EXPLAIN shows them) — just never acted on
        assert plan.predicates[0].verdicts[2] == ALL_FALSE

    def test_no_members_means_nothing_to_prune(self):
        plan = build_prune_plan([], GROUPS, TYPES)
        assert not plan.prunable and plan.skip == frozenset()

    def test_duplicate_texts_analyzed_once(self):
        plan = build_prune_plan(["k < 10", "k < 10"], GROUPS, TYPES)
        assert len(plan.predicates) == 1

    def test_elision_judged_on_surviving_groups_only(self):
        # "k >= 10" is FALSE on group 0 and TRUE on groups 1-2; with
        # group 0 skipped, the filter is constant-true on what decodes
        plan = build_prune_plan(["k >= 10"], GROUPS, TYPES)
        assert plan.skip == frozenset({0})
        assert plan.elided_wheres() == ("k >= 10",)

    def test_proven_empty_keeps_one_sentinel_group(self):
        # everything provably all-false: one group (the cheapest) still
        # decodes so the filtered-empty result matches an unpruned scan
        plan = build_prune_plan(["k < -1"], GROUPS, TYPES)
        assert plan.proven_empty
        assert plan.skip == frozenset({1, 2})
        assert plan.elided_wheres() == ()

    def test_ineligible_predicate_never_elides(self):
        plan = build_prune_plan(["s = 'x'"], GROUPS, TYPES)
        assert plan.skip == frozenset()
        assert not plan.predicates[0].eligible
        assert plan.elided_wheres() == ()

    def test_batch_replay_coalesces_tiny_groups(self):
        # replays _iter_tables: groups under size//4 accumulate until a
        # flush; big groups flush pending first, then slice themselves
        plan = build_prune_plan(
            ["k < 0"],
            [
                group(rows=10, index=0, k=(0, 1, 0)),
                group(rows=10, index=1, k=(2, 3, 0)),
                group(rows=10, index=2, k=(4, 5, 0)),
                group(rows=1000, index=3, k=(6, 7, 0)),
            ],
            TYPES,
        )
        assert plan.predicted_batch_rows(100, pruned=False) == (
            30,
        ) + (100,) * 10
        # proven empty -> the cheapest group (10 rows, lowest index)
        # survives as the sentinel and becomes the only batch
        assert plan.skip == frozenset({1, 2, 3})
        assert plan.predicted_batch_rows(100, pruned=True) == (10,)

    def test_batch_replay_respects_skip_set(self):
        plan = build_prune_plan(["k < 15"], GROUPS, TYPES)
        assert plan.skip == frozenset({2})
        # 100-row groups are not tiny at batch 150 (tiny = 37): each
        # flushes as its own batch, exactly as _iter_tables does
        assert plan.predicted_batch_rows(150, pruned=True) == (100, 100)
        assert plan.predicted_batch_rows(150, pruned=False) == (100, 100, 100)


# ---------------------------------------------------------------------------
# eligibility reasons (DQ310 inputs)
# ---------------------------------------------------------------------------


class TestEligibility:
    def pred(self, text, groups=GROUPS, types=TYPES):
        return build_prune_plan([text], groups, types).predicates[0]

    def test_string_comparison_blocked_with_span(self):
        p = self.pred("k < 10 and s = 'x'")
        assert not p.eligible
        assert "string min/max" in p.reason
        # the caret anchors on the offending subexpression, not the whole
        a, b = p.span
        assert "s = 'x'" == "k < 10 and s = 'x'"[a:b]

    def test_computed_expression_blocked(self):
        p = self.pred("k + 1 > 3")
        assert not p.eligible
        assert "column-vs-literal" in p.reason

    def test_missing_column_blocked(self):
        p = self.pred("zz > 3")
        assert not p.eligible and "not in the scanned schema" in p.reason

    def test_unparseable_blocked(self):
        p = self.pred("k <<< 3")
        assert not p.eligible and p.reason == "predicate does not parse"
        assert p.verdicts == (UNKNOWN,) * len(GROUPS)

    def test_absent_statistics_reported(self):
        bare = [RowGroupStats(index=0, num_rows=10, columns={})]
        p = self.pred("k > 3", groups=bare)
        assert not p.eligible
        assert "no statistics recorded for column 'k'" in p.reason

    def test_eligible_but_overlapping_stays_silent(self):
        p = self.pred("k > 5", groups=[group(k=(0, 10, 1))])
        assert p.eligible and p.reason is None


# ---------------------------------------------------------------------------
# parquet fixture for source + end-to-end coverage
# ---------------------------------------------------------------------------

N_ROWS = 10_000
GROUP_ROWS = 1_000


@pytest.fixture(scope="module")
def parquet_path(tmp_path_factory):
    """10 row groups of 1000 rows, sorted by k so group min/max are
    selective; group 2's v column is entirely NULL; v carries NaN."""
    k = list(range(N_ROWS))
    v = [float(i % 97) - 48.0 for i in range(N_ROWS)]
    for i in range(0, N_ROWS, 53):
        v[i] = float("nan")
    for i in range(2 * GROUP_ROWS, 3 * GROUP_ROWS):
        v[i] = None
    s = [None if i % 11 == 0 else f"v{i % 5}" for i in range(N_ROWS)]
    table = Table.from_pydict(
        {"k": k, "v": v, "s": s},
        types={"k": ColumnType.LONG, "v": ColumnType.DOUBLE, "s": ColumnType.STRING},
    )
    path = str(tmp_path_factory.mktemp("pushdown") / "data.parquet")
    table.to_parquet(path, row_group_size=GROUP_ROWS)
    return path


def scan(path, batch_rows=2048):
    return Table.scan_parquet(path, batch_rows=batch_rows)


class TestParquetSourceStats:
    def test_row_group_stats_shape(self, parquet_path):
        stats = scan(parquet_path).row_group_stats()
        assert [g.num_rows for g in stats] == [GROUP_ROWS] * 10
        assert [g.index for g in stats] == list(range(10))
        first = stats[0].columns["k"]
        assert float(first.min_value) == 0.0
        assert float(first.max_value) == float(GROUP_ROWS - 1)
        assert first.null_count == 0

    def test_all_null_group_visible_in_stats(self, parquet_path):
        stats = scan(parquet_path).row_group_stats()
        assert stats[2].columns["v"].null_count == GROUP_ROWS
        types = {"k": ColumnType.LONG, "v": ColumnType.DOUBLE}
        assert verdict("v > 0", stats[2], types) == ALL_FALSE

    def test_prune_skips_groups_and_adjusts_num_rows(self, parquet_path):
        src = scan(parquet_path).with_prune(frozenset({0, 1, 2}))
        assert src.num_rows == 7 * GROUP_ROWS
        decoded = sum(t.num_rows for t in src.batches(4096))
        assert decoded == 7 * GROUP_ROWS

    def test_prune_and_projection_compose_both_ways(self, parquet_path):
        a = scan(parquet_path).with_prune(frozenset({9})).with_columns(["k"])
        b = scan(parquet_path).with_columns(["k"]).with_prune(frozenset({9}))
        for src in (a, b):
            assert src.prune_groups == frozenset({9})
            assert src.num_rows == 9 * GROUP_ROWS
            assert [n for n, _ in src.schema] == ["k"]

    def test_prune_sets_union(self, parquet_path):
        src = scan(parquet_path).with_prune(frozenset({1}))
        src = src.with_prune(frozenset({2}))
        assert src.prune_groups == frozenset({1, 2})

    def test_prune_everything_yields_empty_fallback(self, parquet_path):
        src = scan(parquet_path).with_prune(frozenset(range(10)))
        batches = list(src.batches(4096))
        assert len(batches) == 1 and batches[0].num_rows == 0


# ---------------------------------------------------------------------------
# end to end: skip counters, bit-identical metrics, prediction == trace
# ---------------------------------------------------------------------------


WHERE = f"k < {GROUP_ROWS + GROUP_ROWS // 2}"  # groups 0-1 survive
ANALYZERS = [
    Size(where=WHERE),
    Mean("v", where=WHERE),
    Completeness("s", where=WHERE),
    Compliance("v in range", "v >= -48", where=WHERE),
]


def run_traced(path, monkeypatch, pushdown, analyzers=ANALYZERS):
    monkeypatch.setenv("DEEQU_TPU_PUSHDOWN", pushdown)
    monkeypatch.setenv("DEEQU_TPU_PLACEMENT", "host")
    return (
        AnalysisRunner.on_data(scan(path))
        .with_tracing(True)
        .add_analyzers(analyzers)
        .run()
    )


def metric_values(ctx):
    out = {}
    for analyzer, metric in ctx.metric_map.items():
        v = metric.value
        if v.is_success:
            value = v.get()
            if isinstance(value, float) and math.isnan(value):
                value = "nan"  # nan != nan would defeat the comparison
            out[repr(analyzer)] = ("OK", value)
        else:
            out[repr(analyzer)] = ("FAIL", type(v.exception).__name__)
    return out


class TestEndToEnd:
    def test_skips_counted_and_metrics_bit_identical(self, parquet_path, monkeypatch):
        on = run_traced(parquet_path, monkeypatch, "1")
        off = run_traced(parquet_path, monkeypatch, "0")
        assert on.run_trace.counters["rg_total"] == 10
        assert on.run_trace.counters["rg_skipped"] == 8
        assert "rg_skipped" not in off.run_trace.counters
        assert metric_values(on) == metric_values(off)

    def test_prune_span_records_decision(self, parquet_path, monkeypatch):
        ctx = run_traced(parquet_path, monkeypatch, "1")
        spans = [sp for sp in ctx.run_trace.spans() if sp.name == "prune"]
        assert len(spans) == 1
        attrs = spans[0].attrs
        assert attrs["groups_total"] == 10
        assert attrs["groups_skipped"] == 8
        assert attrs["rows_skipped"] == 8 * GROUP_ROWS

    def test_predicted_skips_match_observed_trace(self, parquet_path, monkeypatch):
        ctx = run_traced(parquet_path, monkeypatch, "1")
        scan_cost = ctx.plan_cost.scan_pass
        assert scan_cost.rg_total == 10
        assert scan_cost.rg_skipped == 8
        assert scan_cost.saved_read_bytes > 0
        drift = cost_drift(ctx.plan_cost, ctx.run_trace)
        assert drift["drift.rg_skipped"] == 0.0
        assert drift["drift.batches"] == 0.0

    def test_pushdown_off_predicts_zero_skips(self, parquet_path, monkeypatch):
        ctx = run_traced(parquet_path, monkeypatch, "0")
        scan_cost = ctx.plan_cost.scan_pass
        assert scan_cost.rg_total == 10
        assert scan_cost.rg_skipped == 0
        assert cost_drift(ctx.plan_cost, ctx.run_trace)["drift.batches"] == 0.0

    def test_unfiltered_member_disables_skipping(self, parquet_path, monkeypatch):
        ctx = run_traced(
            parquet_path, monkeypatch, "1", analyzers=ANALYZERS + [Maximum("k")]
        )
        assert ctx.run_trace.counters.get("rg_skipped", 0) == 0
        assert ctx.run_trace.counters["rg_total"] == 10

    def test_all_groups_skipped_matches_off(self, parquet_path, monkeypatch):
        impossible = [
            Size(where="k < 0"),
            Mean("v", where="k < 0"),
            Completeness("s", where="k < 0"),
        ]
        on = run_traced(parquet_path, monkeypatch, "1", analyzers=impossible)
        off = run_traced(parquet_path, monkeypatch, "0", analyzers=impossible)
        # one sentinel group decodes (filtered-empty == unpruned scan)
        assert on.run_trace.counters["rg_skipped"] == 9
        assert metric_values(on) == metric_values(off)

    def test_all_true_where_elides(self, parquet_path, monkeypatch):
        # k >= 0 holds on every group: nothing skips, but the filter
        # becomes a constant mask (no runtime predicate evaluation)
        always = [Size(where="k >= 0"), Completeness("s", where="k >= 0")]
        on = run_traced(parquet_path, monkeypatch, "1", analyzers=always)
        off = run_traced(parquet_path, monkeypatch, "0", analyzers=always)
        spans = [sp for sp in on.run_trace.spans() if sp.name == "prune"]
        assert spans and spans[0].attrs["wheres_elided"] == 1
        assert spans[0].attrs["groups_skipped"] == 0
        assert metric_values(on) == metric_values(off)


# ---------------------------------------------------------------------------
# EXPLAIN + DQ310/DQ311
# ---------------------------------------------------------------------------


class TestExplainIntegration:
    def test_explain_reports_row_group_prediction(self, parquet_path):
        result = explain_plan(scan(parquet_path), analyzers=ANALYZERS)
        scan_cost = result.cost.scan_pass
        assert scan_cost.rg_total == 10 and scan_cost.rg_skipped == 8
        text = result.render()
        assert "row groups: 2 decoded, 8 skipped statically" in text

    def test_dq310_fires_on_ineligible_where_with_caret(self, parquet_path):
        analyzers = [
            Size(where="s = 'v1'"),
            Completeness("v", where="s = 'v1'"),
        ]
        result = explain_plan(scan(parquet_path), analyzers=analyzers)
        diags = [d for d in result.diagnostics if d.code == "DQ310"]
        assert len(diags) == 1  # distinct texts analyzed once
        d = diags[0]
        assert d.source == "s = 'v1'" and d.span is not None
        assert "^" in d.render()
        assert "string min/max" in d.message

    def test_dq310_silent_on_eligible_wheres(self, parquet_path):
        result = explain_plan(scan(parquet_path), analyzers=ANALYZERS)
        assert "DQ310" not in [d.code for d in result.diagnostics]

    def test_dq311_fires_when_everything_prunes(self, parquet_path):
        analyzers = [Size(where="k < 0"), Mean("v", where="k < 0")]
        result = explain_plan(scan(parquet_path), analyzers=analyzers)
        assert "DQ311" in [d.code for d in result.diagnostics]

    def test_dq311_silent_when_groups_survive(self, parquet_path):
        result = explain_plan(scan(parquet_path), analyzers=ANALYZERS)
        assert "DQ311" not in [d.code for d in result.diagnostics]

    def test_in_memory_table_unaffected(self):
        table = Table.from_pydict({"v": np.arange(50, dtype=np.float64)})
        result = explain_plan(table, analyzers=[Mean("v", where="v < 10")])
        assert result.cost.scan_pass.rg_total is None
        assert result.cost.prune is None

"""Runs the repo's own lint (tools/lint.py) as a tier-1 test, so a
hot-loop host sync in ops/fused.py, an unused import, or a bare except
fails the suite — not just `make lint` (ISSUE 2, satellite).

Also unit-tests the checkers themselves against synthetic sources.
"""

from __future__ import annotations

import importlib.util
import os
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _lint_module():
    spec = importlib.util.spec_from_file_location(
        "repo_lint", os.path.join(REPO, "tools", "lint.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_repo_is_lint_clean(capsys):
    lint = _lint_module()
    rc = lint.main()
    out = capsys.readouterr().out
    assert rc == 0, f"repo lint found problems:\n{out}"


def _tmp_source(code: str) -> str:
    fd, path = tempfile.mkstemp(suffix=".py")
    with os.fdopen(fd, "w") as f:
        f.write(code)
    return path


def test_hot_loop_checker_flags_device_get_in_loop():
    lint = _lint_module()
    path = _tmp_source(
        "import jax\n"
        "def f(batches):\n"
        "    out = []\n"
        "    for b in batches:\n"
        "        out.append(jax.device_get(b))\n"
        "    return out\n"
    )
    try:
        findings = lint.check_hot_loops(path)
        assert len(findings) == 1
        assert "device_get" in findings[0]
    finally:
        os.unlink(path)


def test_hot_loop_checker_flags_block_until_ready():
    lint = _lint_module()
    path = _tmp_source(
        "def f(xs):\n"
        "    while xs:\n"
        "        xs.pop().block_until_ready()\n"
    )
    try:
        findings = lint.check_hot_loops(path)
        assert len(findings) == 1
        assert "block_until_ready" in findings[0]
    finally:
        os.unlink(path)


def test_hot_loop_checker_allows_calls_outside_loops():
    lint = _lint_module()
    path = _tmp_source(
        "import jax\n"
        "def f(out):\n"
        "    return jax.device_get(out)\n"
    )
    try:
        assert lint.check_hot_loops(path) == []
    finally:
        os.unlink(path)


def test_timing_checker_flags_clock_reads():
    lint = _lint_module()
    path = _tmp_source(
        "import time\n"
        "from time import monotonic as mono\n"
        "def f():\n"
        "    t0 = time.perf_counter()\n"
        "    t1 = mono()\n"
        "    return time.perf_counter_ns() - t0 + t1\n"
    )
    try:
        findings = lint.check_timing_calls(path)
        assert len(findings) == 3
        assert any("time.perf_counter" in f for f in findings)
        assert any("mono" in f for f in findings)
    finally:
        os.unlink(path)


def test_timing_checker_allows_wall_clock_and_observe():
    lint = _lint_module()
    path = _tmp_source(
        "import time\n"
        "from deequ_tpu.observe.spans import timed_call\n"
        "def f(fn):\n"
        "    ts = time.time()  # wall-clock timestamps (TTL caches) ok\n"
        "    out, dt = timed_call(fn)\n"
        "    return ts, out, dt\n"
    )
    try:
        assert lint.check_timing_calls(path) == []
    finally:
        os.unlink(path)


def test_timing_rule_scopes_to_engine_dirs():
    """The ban covers deequ_tpu/runners + deequ_tpu/ops only — observe/
    (the timing implementation itself) and bench.py stay free to read
    clocks directly."""
    lint = _lint_module()
    sep = os.sep
    covered = f"deequ_tpu{sep}ops{sep}runtime.py"
    exempt = f"deequ_tpu{sep}observe{sep}spans.py"
    in_scope = lambda rel: any(  # noqa: E731 - mirror of main()'s filter
        rel == d or rel.startswith(d + sep) for d in lint.TIMING_DIRS
    )
    assert in_scope(covered)
    assert not in_scope(exempt)


def test_unused_import_checker():
    lint = _lint_module()
    path = _tmp_source(
        "from __future__ import annotations\n"
        "import os\n"
        "import sys\n"
        "from typing import TYPE_CHECKING, List\n"
        "x: List[int] = []\n"
        "print(sys.argv)\n"
    )
    try:
        findings = lint.check_unused_imports(path)
        # os and TYPE_CHECKING unused; __future__, sys, List used/exempt
        flagged = {f.split("`")[1] for f in findings}
        assert flagged == {"os", "TYPE_CHECKING"}
    finally:
        os.unlink(path)


def test_bare_except_checker():
    lint = _lint_module()
    path = _tmp_source(
        "try:\n    pass\nexcept:\n    pass\n"
        "try:\n    pass\nexcept ValueError:\n    pass\n"
    )
    try:
        findings = lint.check_bare_except(path)
        assert len(findings) == 1
    finally:
        os.unlink(path)


def test_lint_main_is_invocable_as_script():
    import subprocess

    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "lint.py")],
        capture_output=True,
        text=True,
        timeout=120,
        cwd=REPO,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr


# -- PIPELINE: host syncs in stage-worker files (ISSUE 5 satellite) ----------


def test_pipeline_checker_flags_sync_anywhere():
    """Unlike HOTLOOP, the PIPELINE rule bans syncs even OUTSIDE loops:
    all of a stage-worker file runs on (or schedules onto) stage
    threads, where one sync serializes the overlap."""
    lint = _lint_module()
    path = _tmp_source(
        "import jax\n"
        "def prep(batch):\n"
        "    return jax.device_get(batch)\n"
        "def wait(x):\n"
        "    x.block_until_ready()\n"
    )
    try:
        findings = lint.check_pipeline_syncs(path)
    finally:
        os.unlink(path)
    assert len(findings) == 2
    assert all("PIPELINE" in f for f in findings)
    assert any("device_get" in f for f in findings)
    assert any("block_until_ready" in f for f in findings)


def test_pipeline_checker_allows_async_stage_code():
    lint = _lint_module()
    path = _tmp_source(
        "import queue\n"
        "def worker(q, fn, items):\n"
        "    for item in items:\n"
        "        q.put(fn(item))\n"
    )
    try:
        findings = lint.check_pipeline_syncs(path)
    finally:
        os.unlink(path)
    assert findings == []


def test_pipeline_rule_covers_stage_worker_files():
    """The rule is wired to the actual stage-worker files, and those
    files exist — a rename must update the lint scope with it."""
    lint = _lint_module()
    rels = set(lint.PIPELINE_FILES)
    assert os.path.join("deequ_tpu", "ops", "pipeline.py") in rels
    assert os.path.join("deequ_tpu", "data", "source.py") in rels
    for rel in rels:
        assert os.path.exists(os.path.join(REPO, rel)), rel


# -- GLOBALMUT: unguarded module-global mutation (ISSUE 4 satellite) ---------


def test_globalmut_flags_unguarded_cache_write():
    lint = _lint_module()
    path = _tmp_source(
        "_CACHE = {}\n"
        "def get(key):\n"
        "    if key not in _CACHE:\n"
        "        _CACHE[key] = object()\n"
        "    return _CACHE[key]\n"
    )
    try:
        findings = lint.check_global_mutation(path)
    finally:
        os.unlink(path)
    assert any("GLOBALMUT" in f and "_CACHE" in f for f in findings)


def test_globalmut_flags_mutator_method_calls():
    lint = _lint_module()
    path = _tmp_source(
        "_SEEN = []\n"
        "_IDX = {}\n"
        "def add(x):\n"
        "    _SEEN.append(x)\n"
        "def index(k, v):\n"
        "    _IDX.setdefault(k, v)\n"
    )
    try:
        findings = lint.check_global_mutation(path)
    finally:
        os.unlink(path)
    assert sum("GLOBALMUT" in f for f in findings) == 2


def test_globalmut_allows_lock_guarded_mutation():
    lint = _lint_module()
    path = _tmp_source(
        "import threading\n"
        "_CACHE = {}\n"
        "_CACHE_LOCK = threading.Lock()\n"
        "def get(key, value):\n"
        "    with _CACHE_LOCK:\n"
        "        _CACHE[key] = value\n"
        "    return _CACHE[key]\n"
    )
    try:
        findings = lint.check_global_mutation(path)
    finally:
        os.unlink(path)
    assert findings == []


def test_globalmut_allows_allowlisted_assignment():
    lint = _lint_module()
    path = _tmp_source(
        "_REGISTRY = {}  # global-ok: populated once at import time\n"
        "def register(name, fn):\n"
        "    _REGISTRY[name] = fn\n"
    )
    try:
        findings = lint.check_global_mutation(path)
    finally:
        os.unlink(path)
    assert findings == []


def test_globalmut_respects_local_shadowing_and_global_decl():
    lint = _lint_module()
    path = _tmp_source(
        "_STATE = {}\n"
        "def shadowed():\n"
        "    _STATE = {}\n"
        "    _STATE['k'] = 1\n"  # local: fine
        "    return _STATE\n"
        "def declared():\n"
        "    global _STATE\n"
        "    _STATE = {}\n"  # rebind only, not a mutation finding
        "    _STATE['k'] = 1\n"  # mutation of the module global
        "    return _STATE\n"
    )
    try:
        findings = lint.check_global_mutation(path)
    finally:
        os.unlink(path)
    assert sum("GLOBALMUT" in f for f in findings) == 1
    assert all("declared" not in f or "'k'" not in f for f in findings)


# -- OBSPRINT: print() in observability code (ISSUE 6 satellite) -------------


def test_obsprint_checker_flags_print():
    lint = _lint_module()
    path = _tmp_source(
        "def emit(snapshot):\n"
        "    print(snapshot)\n"
    )
    try:
        findings = lint.check_observe_prints(path)
    finally:
        os.unlink(path)
    assert len(findings) == 1
    assert "OBSPRINT" in findings[0]


def test_obsprint_allows_stderr_write():
    lint = _lint_module()
    path = _tmp_source(
        "import sys\n"
        "def emit(line):\n"
        "    sys.stderr.write(line)\n"
    )
    try:
        findings = lint.check_observe_prints(path)
    finally:
        os.unlink(path)
    assert findings == []


def test_obsprint_rule_scopes_to_observe_dir():
    """The ban covers deequ_tpu/observe only — results code elsewhere
    may still print to stdout deliberately."""
    lint = _lint_module()
    sep = os.sep
    covered = f"deequ_tpu{sep}observe{sep}heartbeat.py"
    exempt = f"deequ_tpu{sep}runners{sep}analysis_runner.py"
    in_scope = lambda rel: any(  # noqa: E731 - mirror of main()'s filter
        rel == d or rel.startswith(d + sep) for d in lint.OBSPRINT_DIRS
    )
    assert in_scope(covered)
    assert not in_scope(exempt)


# -- PUSHDOWN: purity of the stats interpreter (ISSUE 7 satellite) -----------


def test_pushdown_checker_flags_pyarrow_import_even_lazy():
    lint = _lint_module()
    path = _tmp_source(
        "def read_stats(path):\n"
        "    import pyarrow.parquet as pq\n"
        "    return pq.ParquetFile(path)\n"
    )
    try:
        findings = lint.check_pushdown_purity(path)
    finally:
        os.unlink(path)
    assert len(findings) == 1
    assert "PUSHDOWN" in findings[0] and "pyarrow" in findings[0]


def test_pushdown_checker_flags_open_call():
    lint = _lint_module()
    path = _tmp_source(
        "def sniff(path):\n"
        "    with open(path, 'rb') as f:\n"
        "        return f.read(4)\n"
    )
    try:
        findings = lint.check_pushdown_purity(path)
    finally:
        os.unlink(path)
    assert len(findings) == 1
    assert "PUSHDOWN" in findings[0] and "open" in findings[0]


def test_pushdown_checker_allows_pure_interpreter_code():
    lint = _lint_module()
    path = _tmp_source(
        "import math\n"
        "from deequ_tpu.lint.interval import Interval\n"
        "def verdict(lo, hi):\n"
        "    return Interval.closed(lo, hi).is_empty or math.isnan(lo)\n"
    )
    try:
        findings = lint.check_pushdown_purity(path)
    finally:
        os.unlink(path)
    assert findings == []


def test_pushdown_rule_covers_the_interpreter_file():
    lint = _lint_module()
    sep = os.sep
    assert f"deequ_tpu{sep}lint{sep}pushdown.py" in lint.PUSHDOWN_FILES


# -- SUBSUME: purity of the plan-subsumption prover (ISSUE 17 satellite) -----


def test_subsume_checker_flags_jax_import_even_lazy():
    lint = _lint_module()
    path = _tmp_source(
        "def fold(xs):\n"
        "    import jax.numpy as jnp\n"
        "    return jnp.sum(jnp.asarray(xs))\n"
    )
    try:
        findings = lint.check_subsume_purity(path)
    finally:
        os.unlink(path)
    assert len(findings) == 1
    assert "SUBSUME" in findings[0] and "jax" in findings[0]


def test_subsume_checker_flags_service_and_relative_runtime_imports():
    lint = _lint_module()
    path = _tmp_source(
        "from deequ_tpu.service.sharing import plan_share_group\n"
        "def peek():\n"
        "    from ..ops import runtime\n"
        "    return runtime\n"
    )
    try:
        findings = lint.check_subsume_purity(path)
    finally:
        os.unlink(path)
    assert len(findings) == 2
    assert all("SUBSUME" in f for f in findings)
    assert any("deequ_tpu.service" in f for f in findings)
    assert any("deequ_tpu.ops" in f for f in findings)


def test_subsume_checker_flags_open_call():
    lint = _lint_module()
    path = _tmp_source(
        "def sniff(path):\n"
        "    with open(path) as f:\n"
        "        return f.read()\n"
    )
    try:
        findings = lint.check_subsume_purity(path)
    finally:
        os.unlink(path)
    assert len(findings) == 1
    assert "SUBSUME" in findings[0] and "open" in findings[0]


def test_subsume_checker_allows_the_pure_prover_imports():
    lint = _lint_module()
    path = _tmp_source(
        "from deequ_tpu.data.expr import parse\n"
        "from deequ_tpu.lint.fold import satisfiability\n"
        "from deequ_tpu.lint.schema import SchemaInfo\n"
        "def implies(a, b, schema):\n"
        "    return satisfiability(parse(a), schema)\n"
    )
    try:
        findings = lint.check_subsume_purity(path)
    finally:
        os.unlink(path)
    assert findings == []


def test_subsume_rule_covers_the_prover_file_and_it_is_clean():
    lint = _lint_module()
    sep = os.sep
    rel = f"deequ_tpu{sep}lint{sep}subsume.py"
    assert rel in lint.SUBSUME_FILES
    path = os.path.join(lint.REPO, rel)
    assert lint.check_subsume_purity(path) == []


def test_globalmut_reads_are_not_findings():
    lint = _lint_module()
    path = _tmp_source(
        "_TABLE = {'a': 1}\n"
        "def read(k):\n"
        "    return _TABLE.get(k, 0) + len(_TABLE)\n"
    )
    try:
        findings = lint.check_global_mutation(path)
    finally:
        os.unlink(path)
    assert findings == []


def test_decode_checker_flags_to_numpy_outside_fallback():
    lint = _lint_module()
    path = _tmp_source(
        "def decode_fast_column(arr):\n"
        "    return arr.to_numpy(zero_copy_only=False)\n"
    )
    try:
        findings = lint.check_decode_copies(path)
    finally:
        os.unlink(path)
    assert len(findings) == 1
    assert "DECODE" in findings[0] and "to_numpy" in findings[0]


def test_decode_checker_flags_frombuffer_copy_idiom():
    lint = _lint_module()
    path = _tmp_source(
        "import numpy as np\n"
        "def decode_fast_column(buf):\n"
        "    return np.frombuffer(buf, dtype=np.int64)\n"
    )
    try:
        findings = lint.check_decode_copies(path)
    finally:
        os.unlink(path)
    assert len(findings) == 1
    assert "frombuffer" in findings[0]


def test_decode_checker_allows_designated_fallback_functions():
    lint = _lint_module()
    path = _tmp_source(
        "import numpy as np\n"
        "def dictionary_uniques_fallback(dictionary):\n"
        "    return dictionary.to_numpy(zero_copy_only=False)\n"
        "def column_fallback(arr):\n"
        "    def inner(b):\n"
        "        return np.frombuffer(b, dtype=np.uint8)\n"
        "    return inner(arr)\n"
    )
    try:
        findings = lint.check_decode_copies(path)
    finally:
        os.unlink(path)
    assert findings == []


def test_decode_checker_allows_buffer_level_code():
    lint = _lint_module()
    path = _tmp_source(
        "import numpy as np\n"
        "def decode(ch, native, out_vals, out_valid):\n"
        "    bufs = ch.buffers()\n"
        "    return native.decode_primitive(\n"
        "        'double', bufs[1].address, None, ch.offset, len(ch),\n"
        "        out_vals, out_valid)\n"
    )
    try:
        findings = lint.check_decode_copies(path)
    finally:
        os.unlink(path)
    assert findings == []


def test_decode_rule_covers_the_fastpath_modules():
    lint = _lint_module()
    sep = os.sep
    assert f"deequ_tpu{sep}data{sep}arrow_decode.py" in lint.DECODE_FILES
    assert f"deequ_tpu{sep}ops{sep}native{sep}__init__.py" in lint.DECODE_FILES


# -- READER: no pyarrow on the native-reader path (ISSUE 11 satellite) --------


def test_reader_checker_flags_pyarrow_import_even_lazy():
    lint = _lint_module()
    path = _tmp_source(
        "def fetch_chunk(fd, meta):\n"
        "    import pyarrow.parquet as pq\n"
        "    return pq.ParquetFile(meta.path)\n"
    )
    try:
        findings = lint.check_reader_purity(path)
    finally:
        os.unlink(path)
    assert len(findings) == 1
    assert "READER" in findings[0] and "pyarrow" in findings[0]


def test_reader_checker_flags_top_level_pyarrow_import():
    lint = _lint_module()
    path = _tmp_source(
        "import pyarrow as pa\n"
        "def decode(raw):\n"
        "    return pa.py_buffer(raw)\n"
    )
    try:
        findings = lint.check_reader_purity(path)
    finally:
        os.unlink(path)
    assert len(findings) == 1


def test_reader_checker_allows_designated_fallback_functions():
    lint = _lint_module()
    path = _tmp_source(
        "def _assemble_column_numpy_fallback(segments):\n"
        "    import pyarrow as pa\n"
        "    return pa.nulls(0)\n"
    )
    try:
        findings = lint.check_reader_purity(path)
    finally:
        os.unlink(path)
    assert findings == []


def test_reader_checker_allows_native_path_code():
    lint = _lint_module()
    path = _tmp_source(
        "import os\n"
        "import numpy as np\n"
        "from deequ_tpu.ops import native\n"
        "def fetch_chunk(fd, meta):\n"
        "    return os.pread(fd, meta.nbytes, meta.offset)\n"
    )
    try:
        findings = lint.check_reader_purity(path)
    finally:
        os.unlink(path)
    assert findings == []


def test_reader_rule_covers_the_dispatch_module():
    lint = _lint_module()
    sep = os.sep
    rels = set(lint.READER_FILES)
    assert f"deequ_tpu{sep}data{sep}native_reader.py" in rels
    assert f"deequ_tpu{sep}data{sep}encfold.py" in rels
    for rel in rels:
        assert os.path.exists(os.path.join(REPO, rel)), rel
    # and the encoded-fold module must actually be clean today: it owns
    # the (run, code) streams end to end, so pyarrow never appears
    assert lint.check_reader_purity(
        os.path.join(REPO, "deequ_tpu", "data", "encfold.py")
    ) == []


# -- FORENSICS: no row samples on telemetry surfaces -------------------------


def test_forensics_checker_flags_module_import():
    lint = _lint_module()
    path = _tmp_source(
        "def snapshot():\n"
        "    from deequ_tpu.observe.forensics import ForensicsReport\n"
        "    return ForensicsReport\n"
    )
    try:
        findings = lint.check_forensics_leak(path)
    finally:
        os.unlink(path)
    assert findings
    assert any("FORENSICS" in f for f in findings)


def test_forensics_checker_flags_plain_import():
    lint = _lint_module()
    path = _tmp_source(
        "import deequ_tpu.observe.forensics as fo\n"
        "def record():\n"
        "    return fo\n"
    )
    try:
        findings = lint.check_forensics_leak(path)
    finally:
        os.unlink(path)
    assert len(findings) == 1
    assert "FORENSICS" in findings[0]


def test_forensics_checker_flags_sample_identifiers():
    lint = _lint_module()
    path = _tmp_source(
        "def emit(report):\n"
        "    # even without the import, touching the sample types leaks\n"
        "    return [s.values for s in report.constraints[0].samples\n"
        "            if isinstance(s, ViolationSample)]\n"
    )
    try:
        findings = lint.check_forensics_leak(path)
    finally:
        os.unlink(path)
    assert len(findings) == 1
    assert "ViolationSample" in findings[0]


def test_forensics_checker_allows_ordinary_telemetry_code():
    lint = _lint_module()
    path = _tmp_source(
        "import json\n"
        "def engine_metric_record(name, value):\n"
        "    return json.dumps({'series': f'engine.{name}', 'value': value})\n"
    )
    try:
        findings = lint.check_forensics_leak(path)
    finally:
        os.unlink(path)
    assert findings == []


def test_forensics_rule_covers_the_telemetry_surfaces():
    lint = _lint_module()
    sep = os.sep
    rels = set(lint.FORENSICS_FILES)
    assert f"deequ_tpu{sep}observe{sep}telemetry.py" in rels
    assert f"deequ_tpu{sep}observe{sep}heartbeat.py" in rels
    assert f"deequ_tpu{sep}repository{sep}engine.py" in rels
    for rel in rels:
        assert os.path.exists(os.path.join(REPO, rel)), rel


def test_serde_rule_covers_the_audit_envelope():
    lint = _lint_module()
    sep = os.sep
    assert f"deequ_tpu{sep}repository{sep}audit.py" in set(lint.SERDE_FILES)


# -- FAULTS: no swallowed exceptions on fault-containment paths ---------------


def test_faults_checker_flags_bare_except():
    lint = _lint_module()
    path = _tmp_source(
        "def worker(q):\n"
        "    try:\n"
        "        q.get_nowait()\n"
        "    except:\n"
        "        return None\n"
    )
    try:
        findings = lint.check_fault_containment(path)
    finally:
        os.unlink(path)
    assert len(findings) == 1
    assert "bare `except:`" in findings[0]


def test_faults_checker_flags_swallowed_exception():
    lint = _lint_module()
    path = _tmp_source(
        "def fetch_unit(fd, meta):\n"
        "    try:\n"
        "        return read(fd, meta)\n"
        "    except OSError:\n"
        "        pass\n"
    )
    try:
        findings = lint.check_fault_containment(path)
    finally:
        os.unlink(path)
    assert len(findings) == 1
    assert "silently swallowed" in findings[0]


def test_faults_checker_allows_fallback_functions_and_fault_ok():
    lint = _lint_module()
    path = _tmp_source(
        "def _close_all_fallback(fds):\n"
        "    for fd in fds:\n"
        "        try:\n"
        "            close(fd)\n"
        "        except OSError:\n"
        "            pass\n"
        "def drain(q):\n"
        "    try:\n"
        "        while True:\n"
        "            q.get_nowait()\n"
        "    except Empty:  # fault-ok: drained\n"
        "        pass\n"
    )
    try:
        findings = lint.check_fault_containment(path)
    finally:
        os.unlink(path)
    assert findings == []


def test_faults_checker_allows_counted_handlers():
    lint = _lint_module()
    path = _tmp_source(
        "def worker(item):\n"
        "    try:\n"
        "        return fn(item)\n"
        "    except Exception:\n"
        "        runtime.record_fault(injected=1)\n"
        "        return fn(item)\n"
    )
    try:
        findings = lint.check_fault_containment(path)
    finally:
        os.unlink(path)
    assert findings == []


def test_faults_registry_parses_harness_points():
    lint = _lint_module()
    registered = lint._registered_fault_points()
    assert registered is not None
    # the harness's public registry and the lint's AST view must agree
    from deequ_tpu.testing import faults

    assert registered == set(faults.FAULT_KINDS)


def test_faults_registration_flags_unknown_point():
    lint = _lint_module()
    registered = lint._registered_fault_points()
    path = _tmp_source(
        "from deequ_tpu.testing import faults\n"
        "def step():\n"
        "    faults.fault_point('read.pread')\n"
        "    faults.fault_point('no.such.point')\n"
    )
    try:
        findings = lint.check_fault_registration(path, registered)
    finally:
        os.unlink(path)
    assert len(findings) == 1
    assert "no.such.point" in findings[0]


def test_faults_rule_covers_stage_worker_and_readahead_files():
    lint = _lint_module()
    sep = os.sep
    rels = set(lint.FAULTS_FILES)
    assert f"deequ_tpu{sep}ops{sep}pipeline.py" in rels
    assert f"deequ_tpu{sep}data{sep}source.py" in rels
    assert f"deequ_tpu{sep}data{sep}native_reader.py" in rels
    assert f"deequ_tpu{sep}data{sep}encfold.py" in rels
    registered = lint._registered_fault_points()
    assert "decode.runs" in registered
    for rel in rels:
        assert os.path.exists(os.path.join(REPO, rel)), rel


def test_faults_rule_covers_service_files():
    """The DQ service files carry multi-tenant blast radius: the
    containment rule must audit them, and the chaos registry must carry
    the service.* points their fault_point() literals name."""
    lint = _lint_module()
    sep = os.sep
    rels = set(lint.FAULTS_FILES)
    assert f"deequ_tpu{sep}service{sep}service.py" in rels
    assert f"deequ_tpu{sep}service{sep}admission.py" in rels
    assert f"deequ_tpu{sep}service{sep}breaker.py" in rels

    registered = lint._registered_fault_points()
    for point in (
        "service.worker",
        "service.scheduler",
        "service.admission",
        "service.queue",
    ):
        assert point in registered, point

    # and the audited files must actually be clean today
    for rel in rels:
        path = os.path.join(REPO, rel)
        assert lint.check_fault_containment(path) == [], rel
        assert lint.check_fault_registration(path, registered) == [], rel


# -- WINDOWS: purity of the windowed state algebra + drift math ---------------


def test_windows_checker_flags_jax_import_even_lazy():
    lint = _lint_module()
    path = _tmp_source(
        "def merge(entries):\n"
        "    import jax.numpy as jnp\n"
        "    return jnp.sum(jnp.asarray(entries))\n"
    )
    try:
        findings = lint.check_windows_purity(path)
    finally:
        os.unlink(path)
    assert len(findings) == 1
    assert "WINDOWS" in findings[0] and "jax" in findings[0]


def test_windows_checker_flags_pyarrow_and_ops_imports():
    lint = _lint_module()
    path = _tmp_source(
        "import pyarrow.parquet as pq\n"
        "def peek():\n"
        "    from deequ_tpu.ops import runtime\n"
        "    return runtime\n"
    )
    try:
        findings = lint.check_windows_purity(path)
    finally:
        os.unlink(path)
    assert len(findings) == 2
    assert all("WINDOWS" in f for f in findings)
    assert any("pyarrow" in f for f in findings)
    assert any("deequ_tpu.ops" in f for f in findings)


def test_windows_checker_flags_open_call():
    lint = _lint_module()
    path = _tmp_source(
        "def load(path):\n"
        "    with open(path, 'rb') as f:\n"
        "        return f.read()\n"
    )
    try:
        findings = lint.check_windows_purity(path)
    finally:
        os.unlink(path)
    assert len(findings) == 1
    assert "WINDOWS" in findings[0] and "open" in findings[0]


def test_windows_checker_allows_numpy_and_state_imports():
    lint = _lint_module()
    path = _tmp_source(
        "import numpy as np\n"
        "from deequ_tpu.repository.states import decode_states\n"
        "from deequ_tpu.testing import faults\n"
        "def fold(blobs, analyzers):\n"
        "    return [decode_states(b, analyzers) for b in blobs]\n"
    )
    try:
        findings = lint.check_windows_purity(path)
    finally:
        os.unlink(path)
    assert findings == []


def test_windows_rule_covers_the_subsystem_and_it_is_clean():
    lint = _lint_module()
    sep = os.sep
    assert f"deequ_tpu{sep}analyzers{sep}drift.py" in lint.WINDOWS_EXTRA_FILES
    windows_dir = os.path.join(lint.REPO, lint.WINDOWS_DIR)
    files = [
        os.path.join(windows_dir, f)
        for f in os.listdir(windows_dir)
        if f.endswith(".py")
    ]
    assert files, "windows/ package has no modules?"
    for path in files + [
        os.path.join(lint.REPO, rel) for rel in lint.WINDOWS_EXTRA_FILES
    ]:
        assert lint.check_windows_purity(path) == [], path

"""Analyzer unit tests: toy tables -> exact metric values incl. NaN /
empty / failure cases (mirrors reference analyzers/AnalyzerTests.scala and
NullHandlingTests.scala)."""

import numpy as np
import pytest

from deequ_tpu.analyzers import (
    Completeness,
    Compliance,
    Correlation,
    DataType,
    DataTypeInstances,
    Maximum,
    Mean,
    Minimum,
    NumMatches,
    NumMatchesAndCount,
    PatternMatch,
    Patterns,
    Size,
    StandardDeviation,
    Sum,
)
from deequ_tpu.analyzers.scan import determine_type
from deequ_tpu.core.exceptions import (
    EmptyStateException,
    NoSuchColumnException,
    WrongColumnTypeException,
)
from deequ_tpu.data.table import Table

from fixtures import (
    get_df_full,
    get_df_missing,
    get_df_with_numeric_values,
    get_full_nulls,
)


def value_of(metric):
    assert metric.value.is_success, f"expected success, got {metric.value}"
    return metric.value.get()


def failure_of(metric):
    assert metric.value.is_failure, f"expected failure, got {metric.value}"
    return metric.value.exception


class TestSize:
    def test_size(self):
        assert value_of(Size().calculate(get_df_full())) == 4.0
        assert value_of(Size().calculate(get_df_missing())) == 12.0

    def test_size_with_filter(self):
        df = get_df_with_numeric_values()
        assert value_of(Size(where="att1 > 3").calculate(df)) == 3.0


class TestCompleteness:
    def test_completeness(self):
        df = get_df_missing()
        assert value_of(Completeness("att1").calculate(df)) == 0.5
        assert value_of(Completeness("att2").calculate(df)) == 0.75

    def test_completeness_with_filter(self):
        # rows where att2 is defined: 6 of them; att1 defined on 4 of those
        df = Table.from_pydict(
            {
                "att1": ["a", None, "b", "c", None, "d"],
                "att2": ["x", "x", "x", None, None, "x"],
            }
        )
        m = Completeness("att1", where="att2 IS NOT NULL").calculate(df)
        assert value_of(m) == 0.75

    def test_fully_null_is_zero(self):
        assert value_of(Completeness("att1").calculate(get_full_nulls())) == 0.0

    def test_missing_column_fails(self):
        err = failure_of(Completeness("nope").calculate(get_df_full()))
        assert isinstance(err, NoSuchColumnException)


class TestCompliance:
    def test_compliance(self):
        df = get_df_with_numeric_values()
        assert value_of(Compliance("rule1", "att1 > 3").calculate(df)) == 0.5
        assert value_of(Compliance("rule2", "att1 > 0").calculate(df)) == 1.0

    def test_compliance_with_filter(self):
        df = get_df_with_numeric_values()
        m = Compliance("rule", "att2 = 0", where="att1 < 4").calculate(df)
        assert value_of(m) == 1.0

    def test_bad_predicate_fails(self):
        df = get_df_with_numeric_values()
        m = Compliance("rule", "!!not valid sql!!").calculate(df)
        assert m.value.is_failure


class TestPatternMatch:
    def test_pattern(self):
        df = Table.from_pydict({"s": ["123", "abc", "12b", None]})
        m = PatternMatch("s", r"\d+").calculate(df)
        assert value_of(m) == 0.5

    def test_email(self):
        df = Table.from_pydict(
            {"s": ["someone@somewhere.org", "someone@else", "x", None]}
        )
        assert value_of(PatternMatch("s", Patterns.EMAIL).calculate(df)) == 0.25

    def test_url(self):
        df = Table.from_pydict(
            {
                "s": [
                    "http://foo.com/blah_blah",
                    "https://www.example.com/foo/?bar=baz",
                    "not a url",
                    None,
                ]
            }
        )
        assert value_of(PatternMatch("s", Patterns.URL).calculate(df)) == 0.5

    def test_ssn_and_creditcard(self):
        df = Table.from_pydict({"s": ["123-45-6789", "000-00-0000", "x"]})
        m = PatternMatch("s", Patterns.SOCIAL_SECURITY_NUMBER_US).calculate(df)
        assert value_of(m) == pytest.approx(1 / 3)
        df2 = Table.from_pydict({"s": ["4012888888881881", "9999999999999999"]})
        m2 = PatternMatch("s", Patterns.CREDITCARD).calculate(df2)
        assert value_of(m2) == 0.5

    def test_non_string_column_fails(self):
        df = get_df_with_numeric_values()
        err = failure_of(PatternMatch("att1", r"\d+").calculate(df))
        assert isinstance(err, WrongColumnTypeException)


class TestNumericAnalyzers:
    def test_mean_min_max_sum(self):
        df = get_df_with_numeric_values()
        assert value_of(Mean("att1").calculate(df)) == 3.5
        assert value_of(Minimum("att1").calculate(df)) == 1.0
        assert value_of(Maximum("att1").calculate(df)) == 6.0
        assert value_of(Sum("att1").calculate(df)) == 21.0

    def test_with_filter(self):
        df = get_df_with_numeric_values()
        assert value_of(Mean("att1", where="att2 = 0").calculate(df)) == 2.0
        assert value_of(Minimum("att1", where="att1 > 3").calculate(df)) == 4.0
        assert value_of(Maximum("att1", where="att1 < 4").calculate(df)) == 3.0
        assert value_of(Sum("att1", where="att2 > 0").calculate(df)) == 15.0

    def test_stddev(self):
        df = get_df_with_numeric_values()
        expected = float(np.std(np.arange(1, 7)))  # population stddev
        assert value_of(StandardDeviation("att1").calculate(df)) == pytest.approx(
            expected, abs=1e-12
        )

    def test_correlation_perfect(self):
        df = Table.from_pydict({"att1": [1.0, 2.0, 3.0], "att2": [4.0, 5.0, 6.0]})
        assert value_of(Correlation("att1", "att2").calculate(df)) == pytest.approx(
            1.0, abs=1e-12
        )

    def test_correlation_exact(self):
        df = get_df_with_numeric_values()
        expected = float(
            np.corrcoef(np.array([1, 2, 3, 4, 5, 6]), np.array([0, 0, 0, 5, 6, 7]))[0, 1]
        )
        assert value_of(Correlation("att1", "att2").calculate(df)) == pytest.approx(
            expected, abs=1e-12
        )

    def test_non_numeric_fails(self):
        df = get_df_full()
        err = failure_of(Mean("att1").calculate(df))
        assert isinstance(err, WrongColumnTypeException)

    def test_empty_state_on_all_null(self):
        df = Table.from_pydict({"x": [None, None]}, types=None)
        # all-None infers STRING; use numeric column with all nulls instead
        df = Table.from_numpy(
            {"x": np.array([np.nan, np.nan])},
        )
        for analyzer in [Mean("x"), Minimum("x"), Maximum("x"), Sum("x"), StandardDeviation("x")]:
            err = failure_of(analyzer.calculate(df))
            assert isinstance(err, EmptyStateException)

    def test_empty_state_message_contains_analyzer(self):
        df = Table.from_numpy({"numericCol": np.array([np.nan] * 8)})
        err = failure_of(Mean("numericCol").calculate(df))
        assert (
            str(err)
            == "Empty state for analyzer Mean(numericCol,None), all input values were NULL."
        )


class TestStates:
    def test_state_merges(self):
        df = get_df_with_numeric_values()
        left = df.slice(0, 3)
        right = df.slice(3, 6)
        for analyzer in [
            Size(),
            Completeness("att1"),
            Mean("att1"),
            Minimum("att1"),
            Maximum("att1"),
            Sum("att1"),
            StandardDeviation("att1"),
            Correlation("att1", "att2"),
        ]:
            sa = analyzer.compute_state_from(left)
            sb = analyzer.compute_state_from(right)
            merged_metric = analyzer.compute_metric_from(sa.merge(sb))
            direct_metric = analyzer.calculate(df)
            assert value_of(merged_metric) == pytest.approx(
                value_of(direct_metric), abs=1e-9
            ), repr(analyzer)

    def test_null_column_states(self):
        df = Table.from_numpy({"x": np.array([np.nan] * 8)})
        assert Size().compute_state_from(df) == NumMatches(8)
        assert Completeness("x").compute_state_from(df) == NumMatchesAndCount(0, 8)
        assert Mean("x").compute_state_from(df) is None
        assert StandardDeviation("x").compute_state_from(df) is None
        assert Minimum("x").compute_state_from(df) is None
        assert Maximum("x").compute_state_from(df) is None
        assert Sum("x").compute_state_from(df) is None
        assert Correlation("x", "x").compute_state_from(df) is None


class TestDataType:
    def test_datatype_histogram(self):
        df = Table.from_pydict({"s": ["1", "2.0", "true", "xyz", None]})
        dist = value_of(DataType("s").calculate(df))
        assert dist[DataTypeInstances.INTEGRAL].absolute == 1
        assert dist[DataTypeInstances.FRACTIONAL].absolute == 1
        assert dist[DataTypeInstances.BOOLEAN].absolute == 1
        assert dist[DataTypeInstances.STRING].absolute == 1
        assert dist[DataTypeInstances.UNKNOWN].absolute == 1
        assert dist[DataTypeInstances.INTEGRAL].ratio == pytest.approx(0.2)

    def test_fully_null(self):
        df = get_full_nulls()
        dist = value_of(DataType("att1").calculate(df))
        assert dist[DataTypeInstances.UNKNOWN].ratio == 1.0

    def test_determine_type(self):
        df = Table.from_pydict({"s": ["1", "2", None]})
        dist = value_of(DataType("s").calculate(df))
        assert determine_type(dist) == DataTypeInstances.INTEGRAL
        df2 = Table.from_pydict({"s": ["1", "2.0"]})
        assert determine_type(value_of(DataType("s").calculate(df2))) == DataTypeInstances.FRACTIONAL
        df3 = Table.from_pydict({"s": ["true", "false"]})
        assert determine_type(value_of(DataType("s").calculate(df3))) == DataTypeInstances.BOOLEAN
        df4 = Table.from_pydict({"s": ["true", "1"]})
        assert determine_type(value_of(DataType("s").calculate(df4))) == DataTypeInstances.STRING

    def test_typed_columns(self):
        df = get_df_with_numeric_values()
        dist = value_of(DataType("att1").calculate(df))
        assert dist[DataTypeInstances.INTEGRAL].ratio == 1.0


class TestBatching:
    def test_multi_batch_equals_single_batch(self):
        from deequ_tpu.ops.fused import FusedScanPass

        rng = np.random.default_rng(0)
        x = rng.normal(size=1000) * 10
        y = rng.normal(size=1000) + 0.3 * x
        x[::7] = np.nan
        df = Table.from_numpy({"x": x, "y": y})
        analyzers = [
            Size(),
            Completeness("x"),
            Mean("x"),
            Minimum("x"),
            Maximum("x"),
            Sum("x"),
            StandardDeviation("x"),
            Correlation("x", "y"),
        ]
        single = FusedScanPass(analyzers, batch_size=1 << 22).run(df)
        multi = FusedScanPass(analyzers, batch_size=64).run(df)
        for s, m in zip(single, multi):
            ms = s.analyzer.compute_metric_from(s.state_or_raise())
            mm = m.analyzer.compute_metric_from(m.state_or_raise())
            if ms.value.is_success:
                assert value_of(mm) == pytest.approx(value_of(ms), rel=1e-12), repr(
                    s.analyzer
                )

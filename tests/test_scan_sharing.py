"""Fleet-wide scan sharing (service/sharing.py + the DQService group
scheduler): one proven superset scan per table, fanned back out to
every participating tenant BIT-identically to their solo runs
(ISSUE 17).

The load-bearing invariants:

* fan-out exactness — every participant's metrics, check statuses, and
  forensics samples equal its solo run's, because the union scan folds
  the identical per-analyzer states over the same semigroup;
* proofs pinned — each participant carries a CONTAINED subsumption
  proof whose post-execution drift counters are all zero;
* isolation — pro-rata quota charges (one scan's bytes split across
  the group, never K scans'), per-tenant forensics reservoirs, and
  per-tenant state-cache entries the shared scan warms;
* consistency under scheduling — preemption/cancellation of a shared
  scan re-queues or finalizes EVERY participant, never a partial
  fan-out; the prover declining a member falls it back to a solo run.
"""

from __future__ import annotations

import time

import numpy as np
import pytest

from deequ_tpu import Check, CheckLevel, VerificationSuite
from deequ_tpu.core.controller import DQ_QUOTA
from deequ_tpu.data.table import Table
from deequ_tpu.repository.states import FileSystemStateRepository
from deequ_tpu.service import DQService, TenantQuota
from deequ_tpu.service import sharing

from test_suite_differential_fuzz import (
    _write_partition,
    random_table,
    suite_snapshot,
)


# ---------------------------------------------------------------------------
# fixtures & helpers
# ---------------------------------------------------------------------------


def _make_dataset(tmp_path, seed=7, parts=3):
    data_dir = tmp_path / "ds"
    data_dir.mkdir()
    rng = np.random.default_rng(seed)
    for i in range(parts):
        _write_partition(random_table(rng), str(data_dir / f"p{i}.parquet"))
    return data_dir


def _factory(data_dir):
    return lambda: Table.scan_parquet_dataset(str(data_dir))


def _tenant_checks():
    return {
        "t1": Check(CheckLevel.ERROR, "c1")
        .is_complete("x")
        .has_mean("x", lambda m: True),
        "t2": Check(CheckLevel.ERROR, "c2")
        .is_complete("s")
        .has_mean("x", lambda m: True),
        "t3": Check(CheckLevel.ERROR, "c3")
        .has_size(lambda v: v > 0)
        .has_standard_deviation("x", lambda s: True),
    }


def _solo_snapshots(factory, checks):
    out = {}
    for tenant, check in checks.items():
        result = (
            VerificationSuite()
            .on_data(factory())
            .add_check(check)
            .with_engine("single")
            .run()
        )
        out[tenant] = suite_snapshot(result)
    return out


def _blocker():
    """A submission over a DIFFERENT (in-memory, unshareable) dataset
    whose slow assertion occupies the single worker long enough for
    the real group to queue up behind it."""
    table = Table.from_pydict({"k": ["a", "b", "c"]})
    check = Check(CheckLevel.ERROR, "blocker").has_size(
        lambda v: (time.sleep(0.8) or v >= 0)
    )
    return (lambda: table), check


def _submit_group(svc, factory, checks):
    bdata, bcheck = _blocker()
    blocker = svc.submit("blocker", "other", bdata, checks=[bcheck])
    time.sleep(0.25)
    handles = {
        tenant: svc.submit(tenant, "ds", factory, checks=[check])
        for tenant, check in checks.items()
    }
    return blocker, handles


def _await_done(handles, timeout=60):
    for tenant, handle in handles.items():
        assert handle.wait(timeout), (tenant, handle.status)


# ---------------------------------------------------------------------------
# fan-out exactness + pinned proofs
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("placement", ["host", "device"])
def test_shared_scan_bit_identical_to_solo_with_pinned_proofs(
    placement, monkeypatch, tmp_path
):
    monkeypatch.setenv("DEEQU_TPU_PLACEMENT", placement)
    data_dir = _make_dataset(tmp_path)
    factory = _factory(data_dir)
    checks = _tenant_checks()
    solo = _solo_snapshots(factory, checks)

    with DQService(workers=1) as svc:
        blocker, handles = _submit_group(svc, factory, checks)
        _await_done({**handles, "blocker": blocker})
        assert svc.telemetry.value("shared_scans") >= 1
        shared = [t for t, h in handles.items() if h.sharing and h.sharing["shared"]]
        assert len(shared) >= 2, "group never formed"
        for tenant, handle in handles.items():
            assert handle.status == "done", (tenant, handle.reason, handle.error)
            assert suite_snapshot(handle.result) == solo[tenant], tenant
        for tenant in shared:
            info = handles[tenant].sharing
            assert info["proof"]["verdict"] == "CONTAINED"
            assert info["participants"] == len(shared)
            assert all(v == 0 for v in info["drift"].values()), (tenant, info)


def test_kill_switch_disables_grouping_but_not_results(monkeypatch, tmp_path):
    monkeypatch.setenv("DEEQU_TPU_SCAN_SHARING", "0")
    data_dir = _make_dataset(tmp_path)
    factory = _factory(data_dir)
    checks = _tenant_checks()
    solo = _solo_snapshots(factory, checks)

    with DQService(workers=1) as svc:
        blocker, handles = _submit_group(svc, factory, checks)
        _await_done({**handles, "blocker": blocker})
        assert svc.telemetry.value("shared_scans") == 0
        for tenant, handle in handles.items():
            assert handle.status == "done"
            assert handle.sharing is None
            assert suite_snapshot(handle.result) == solo[tenant], tenant


def test_share_group_max_caps_participation(monkeypatch, tmp_path):
    monkeypatch.setenv("DEEQU_TPU_SHARE_GROUP_MAX", "2")
    data_dir = _make_dataset(tmp_path)
    factory = _factory(data_dir)
    checks = _tenant_checks()
    solo = _solo_snapshots(factory, checks)

    with DQService(workers=1) as svc:
        blocker, handles = _submit_group(svc, factory, checks)
        _await_done({**handles, "blocker": blocker})
        for tenant, handle in handles.items():
            assert handle.status == "done"
            assert suite_snapshot(handle.result) == solo[tenant], tenant
            if handle.sharing and handle.sharing["shared"]:
                assert handle.sharing["participants"] <= 2


# ---------------------------------------------------------------------------
# pro-rata quota accounting
# ---------------------------------------------------------------------------


def test_prorata_weights_sum_to_one_scan():
    union, shares = sharing.prorata_weights([300.0, 100.0, 100.0])
    assert union == 300.0
    assert shares == pytest.approx([180.0, 60.0, 60.0])
    assert sum(shares) == pytest.approx(union)
    assert sharing.prorata_weights([]) == (0.0, [])
    assert sharing.prorata_weights([0.0, 0.0]) == (0.0, [0.0, 0.0])


def test_shared_scan_charges_one_scan_pro_rata(monkeypatch, tmp_path):
    data_dir = _make_dataset(tmp_path)
    factory = _factory(data_dir)
    checks = _tenant_checks()

    # empirical solo baseline: what each tenant pays when it scans alone
    solo_charge = {}
    for tenant, check in checks.items():
        with DQService(workers=1) as ref:
            handle = ref.submit(tenant, "ds", factory, checks=[check])
            assert handle.wait(60) and handle.status == "done"
            solo_charge[tenant] = ref.ledger.bytes_total(tenant)
    assert all(b > 0 for b in solo_charge.values()), solo_charge

    with DQService(workers=1) as svc:
        blocker, handles = _submit_group(svc, factory, checks)
        _await_done({**handles, "blocker": blocker})
        shared = [t for t, h in handles.items() if h.sharing and h.sharing["shared"]]
        assert len(shared) >= 2
        per_tenant = {t: svc.ledger.bytes_total(t) for t in shared}
        assert all(b > 0 for b in per_tenant.values()), per_tenant
        # together the group paid for ONE union scan — the WIDEST
        # participant's solo bill, split pro-rata — not K scans
        total = sum(per_tenant.values())
        solo_shared = [solo_charge[t] for t in shared]
        assert total == pytest.approx(max(solo_shared), rel=0.05), (
            per_tenant,
            solo_charge,
        )
        assert total < 0.8 * sum(solo_shared)
        # and no participant pays more shared than it would have alone
        for tenant in shared:
            assert per_tenant[tenant] <= solo_charge[tenant] * 1.05, tenant


def test_overdrawn_tenant_dropped_at_fanout_scan_continues(monkeypatch, tmp_path):
    data_dir = _make_dataset(tmp_path)
    factory = _factory(data_dir)
    checks = _tenant_checks()
    solo = _solo_snapshots(factory, checks)
    window = 50 * 1024 * 1024

    quotas = {"t1": TenantQuota(scan_bytes_per_window=float(window), window_s=3600.0)}
    with DQService(workers=1, quotas=quotas) as svc:
        # t1's window is already blown before its run starts; admission
        # still admits (the plan itself fits the window) but the shared
        # scan's boundary probe marks it overdrawn and drops it at
        # fan-out — while its co-tenants' scan completes untouched
        svc.ledger.charge_scan("t1", float(window) + 1.0)
        blocker, handles = _submit_group(svc, factory, checks)
        _await_done({**handles, "blocker": blocker})
        assert handles["t1"].status == "quota", handles["t1"].reason
        assert handles["t1"].code == DQ_QUOTA
        for tenant in ("t2", "t3"):
            assert handles[tenant].status == "done", handles[tenant].reason
            assert suite_snapshot(handles[tenant].result) == solo[tenant]


# ---------------------------------------------------------------------------
# consistency under preemption: never a partial fan-out
# ---------------------------------------------------------------------------


def test_preempted_shared_scan_requeues_every_participant(monkeypatch, tmp_path):
    data_dir = _make_dataset(tmp_path, parts=4)
    factory = _factory(data_dir)
    checks = _tenant_checks()
    solo = _solo_snapshots(factory, checks)

    fired = {"n": 0}
    real_probe = DQService._shared_boundary_probe

    def preempting_probe(self, subs, overdrawn):
        inner = real_probe(self, subs, overdrawn)

        def probe(progress):
            if fired["n"] == 0 and int(progress.get("partitions_done", 0)) >= 1:
                fired["n"] += 1
                return "preempted"
            return inner(progress)

        return probe

    monkeypatch.setattr(DQService, "_shared_boundary_probe", preempting_probe)

    repo = FileSystemStateRepository(str(tmp_path / "cache"))
    with DQService(workers=1, state_repository=repo) as svc:
        blocker, handles = _submit_group(svc, factory, checks)
        _await_done({**handles, "blocker": blocker})
        assert fired["n"] == 1, "shared scan was never preempted"
        shared = [t for t, h in handles.items() if h.sharing and h.sharing["shared"]]
        assert len(shared) >= 2
        # EVERY participant was re-queued (attempts > 1) and completed
        # bit-identically — committed partition states made the retry
        # incremental, never a partial fan-out
        for tenant, handle in handles.items():
            assert handle.status == "done", (tenant, handle.reason)
            assert handle.preemptions == 1, tenant
            assert handle.attempts >= 2, tenant
            assert suite_snapshot(handle.result) == solo[tenant], tenant


def test_declined_member_falls_back_to_solo_run(monkeypatch, tmp_path):
    data_dir = _make_dataset(tmp_path)
    factory = _factory(data_dir)
    checks = _tenant_checks()
    solo = _solo_snapshots(factory, checks)

    real = sharing.plan_share_group

    def declining(plans, table):
        union, proofs, declines = real(plans, table)
        if len(plans) > 1:
            declines = list(declines)
            declines[-1] = "forced decline (test)"
        return union, proofs, declines

    monkeypatch.setattr(sharing, "plan_share_group", declining)

    with DQService(workers=1) as svc:
        blocker, handles = _submit_group(svc, factory, checks)
        _await_done({**handles, "blocker": blocker})
        assert svc.telemetry.value("sharing_declined") >= 1
        declined = [
            t
            for t, h in handles.items()
            if h.sharing and not h.sharing["shared"]
        ]
        assert declined, "no member was declined"
        for tenant, handle in handles.items():
            assert handle.status == "done", (tenant, handle.reason)
            assert suite_snapshot(handle.result) == solo[tenant], tenant
        for tenant in declined:
            assert handles[tenant].sharing["reason"] == "forced decline (test)"


# ---------------------------------------------------------------------------
# per-tenant state fan-out: the shared scan warms every solo cache
# ---------------------------------------------------------------------------


def test_shared_scan_warms_each_tenants_solo_cache(monkeypatch, tmp_path):
    monkeypatch.setenv("DEEQU_TPU_STATE_CACHE", "1")
    data_dir = _make_dataset(tmp_path)
    factory = _factory(data_dir)
    checks = _tenant_checks()
    solo = _solo_snapshots(factory, checks)
    repo = FileSystemStateRepository(str(tmp_path / "cache"))

    with DQService(workers=1, state_repository=repo) as svc:
        blocker, handles = _submit_group(svc, factory, checks)
        _await_done({**handles, "blocker": blocker})
        shared = [t for t, h in handles.items() if h.sharing and h.sharing["shared"]]
        assert len(shared) >= 2

    table = factory()
    fingerprints = [p.fingerprint for p in table.partitions()]
    for tenant in shared:
        plan = sharing.submission_plan([checks[tenant]], [])
        tsp = sharing.TenantStatePlan(f"{tenant}/ds", plan, table)
        assert tsp.analyzers, tenant
        for fp in fingerprints:
            assert repo.has_states(f"{tenant}/ds", fp, tsp.signature), (tenant, fp)
        # an all-warm solo run off the fanned-out entries stays exact
        result = (
            VerificationSuite()
            .on_data(factory())
            .add_check(checks[tenant])
            .with_engine("single")
            .with_state_repository(repo, f"{tenant}/ds")
            .run()
        )
        assert suite_snapshot(result) == solo[tenant], tenant


def test_fanout_repository_assembles_union_from_tenant_entries(tmp_path):
    """Unit: loads fall back to per-tenant solo entries, so a re-formed
    group resumes partitions an earlier (different) group committed."""

    class DictRepo:
        def __init__(self):
            self.store = {}

        def has_states(self, dataset, fingerprint, signature):
            return (dataset, fingerprint, signature) in self.store

        def load_states(self, dataset, fingerprint, signature, analyzers):
            entry = self.store.get((dataset, fingerprint, signature))
            if entry is None:
                return None
            try:
                return [entry[a] for a in analyzers]
            except KeyError:
                return None

        def save_states(self, dataset, fingerprint, signature, pairs):
            self.store[(dataset, fingerprint, signature)] = dict(pairs)
            return True

        def disk_usage(self, dataset):
            return 0

    from deequ_tpu.analyzers import Completeness, Mean

    table = Table.from_pydict({"x": [1.0, 2.0], "s": ["a", None]})
    a1, a2 = Completeness("x"), Mean("x")
    t1 = sharing.TenantStatePlan("t1/ds", [a1], table)
    t2 = sharing.TenantStatePlan("t2/ds", [a1, a2], table)
    inner = DictRepo()
    fan = sharing.FanoutStateRepository(inner, [t1, t2])

    saved = fan.save_states("shared/x", "fp0", "sig-union", [(a1, "s1"), (a2, "s2")])
    assert saved
    # every tenant's solo entry exists under its own dataset + signature
    assert inner.has_states("t1/ds", "fp0", t1.signature)
    assert inner.has_states("t2/ds", "fp0", t2.signature)
    assert inner.load_states("t1/ds", "fp0", t1.signature, [a1]) == ["s1"]

    # drop the shared entry: the union still assembles from the tenants
    del inner.store[("shared/x", "fp0", "sig-union")]
    assert fan.has_states("shared/x", "fp0", "sig-union")
    assert fan.load_states("shared/x", "fp0", "sig-union", [a1, a2]) == ["s1", "s2"]
    # a union member no tenant persisted is a miss, not a partial load
    a3 = Completeness("s")
    assert fan.load_states("shared/x", "fp0", "sig-union", [a1, a3]) is None


# ---------------------------------------------------------------------------
# per-tenant forensics isolation
# ---------------------------------------------------------------------------


def test_forensics_reservoirs_isolated_and_identical_to_solo(monkeypatch, tmp_path):
    monkeypatch.setenv("DEEQU_TPU_FORENSICS", "1")
    data_dir = _make_dataset(tmp_path)
    factory = _factory(data_dir)
    checks = {
        "t1": Check(CheckLevel.ERROR, "f1").is_complete("x"),
        "t2": Check(CheckLevel.ERROR, "f2").is_complete("s").is_complete("x"),
    }

    def solo_forensics(tenant):
        result = (
            VerificationSuite()
            .on_data(factory())
            .add_check(checks[tenant])
            .with_engine("single")
            .run()
        )
        assert result.forensics_report is not None
        return [c.to_dict() for c in result.forensics_report.constraints]

    solo = {t: solo_forensics(t) for t in checks}
    solo_snap = _solo_snapshots(factory, checks)

    with DQService(workers=1) as svc:
        blocker, handles = _submit_group(svc, factory, checks)
        _await_done({**handles, "blocker": blocker})
        shared = [t for t, h in handles.items() if h.sharing and h.sharing["shared"]]
        assert sorted(shared) == ["t1", "t2"]
        for tenant, handle in handles.items():
            assert suite_snapshot(handle.result) == solo_snap[tenant]
            report = handle.result.forensics_report
            assert report is not None, tenant
            # reservoirs are seeded from violating-row content, so each
            # tenant's shared-scan samples are BIT-identical to solo —
            # and contain only that tenant's own constraints
            assert [c.to_dict() for c in report.constraints] == solo[tenant], tenant


# ---------------------------------------------------------------------------
# grouping key
# ---------------------------------------------------------------------------


def test_dataset_fingerprint_rules(tmp_path):
    data_dir = _make_dataset(tmp_path)
    t1 = Table.scan_parquet_dataset(str(data_dir))
    t2 = Table.scan_parquet_dataset(str(data_dir))
    f1 = sharing.dataset_fingerprint(lambda: t1, t1)
    f2 = sharing.dataset_fingerprint(lambda: t2, t2)
    assert f1 is not None and f1 == f2, "content identity must survive re-opens"

    mem = Table.from_pydict({"x": [1.0]})
    direct = sharing.dataset_fingerprint(mem, mem)
    assert direct == f"obj:{id(mem)}"
    # a factory-opened in-memory table has no stable identity
    assert sharing.dataset_fingerprint(lambda: mem, mem) is None

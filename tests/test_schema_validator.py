"""Dedicated row-level schema-validator tests — the mirror of the
reference's RowLevelSchemaValidatorTest.scala (265 LoC): null/string/
regex/int/decimal/timestamp constraints and valid-vs-invalid row splits
with casts."""

from __future__ import annotations

import numpy as np
import pytest

from deequ_tpu.data.table import ColumnType, Table
from deequ_tpu.schema.row_level_schema_validator import (
    RowLevelSchema,
    RowLevelSchemaValidator,
)


def validate(table, schema):
    return RowLevelSchemaValidator.validate(table, schema)


class TestNullConstraints:
    """reference: RowLevelSchemaValidatorTest.scala:27-56."""

    def test_non_nullable_rejects_nulls(self):
        t = Table.from_pydict({"id": ["1", None, "3", None]})
        schema = RowLevelSchema().with_string_column("id", is_nullable=False)
        result = validate(t, schema)
        assert result.num_valid_rows == 2
        assert result.num_invalid_rows == 2
        assert list(result.valid_rows.column("id").values) == ["1", "3"]

    def test_nullable_keeps_nulls(self):
        t = Table.from_pydict({"id": ["1", None, "3"]})
        schema = RowLevelSchema().with_string_column("id", is_nullable=True)
        result = validate(t, schema)
        assert result.num_valid_rows == 3
        assert result.num_invalid_rows == 0


class TestStringConstraints:
    """reference: RowLevelSchemaValidatorTest.scala:58-117."""

    def test_length_bounds(self):
        t = Table.from_pydict({"name": ["a", "abc", "abcdef", ""]})
        schema = RowLevelSchema().with_string_column(
            "name", is_nullable=False, min_length=1, max_length=3
        )
        result = validate(t, schema)
        assert result.num_valid_rows == 2
        assert list(result.valid_rows.column("name").values) == ["a", "abc"]

    def test_regex_filter(self):
        t = Table.from_pydict({"code": ["AB-1", "XY-2", "nope", "CD-9"]})
        schema = RowLevelSchema().with_string_column(
            "code", is_nullable=False, matches=r"^[A-Z]{2}-\d$"
        )
        result = validate(t, schema)
        assert result.num_valid_rows == 3
        assert "nope" in list(result.invalid_rows.column("code").values)

    def test_null_passes_string_constraints_when_nullable(self):
        # constraints only apply to present values (reference semantics)
        t = Table.from_pydict({"name": [None, "ab"]})
        schema = RowLevelSchema().with_string_column(
            "name", is_nullable=True, min_length=2
        )
        result = validate(t, schema)
        assert result.num_valid_rows == 2


class TestIntConstraints:
    """reference: RowLevelSchemaValidatorTest.scala:119-147."""

    def test_range_and_parse(self):
        t = Table.from_pydict({"v": ["1", "17", "99", "x", "3.5"]})
        schema = RowLevelSchema().with_int_column(
            "v", is_nullable=False, min_value=1, max_value=50
        )
        result = validate(t, schema)
        # '99' out of range, 'x' unparseable, '3.5' not a strict int
        assert result.num_valid_rows == 2
        assert result.num_invalid_rows == 3
        # valid rows are CAST to the target type
        col = result.valid_rows.column("v")
        assert col.ctype == ColumnType.LONG
        assert list(col.values) == [1, 17]

    def test_min_only(self):
        t = Table.from_pydict({"v": ["-5", "0", "5"]})
        schema = RowLevelSchema().with_int_column("v", is_nullable=False, min_value=0)
        result = validate(t, schema)
        assert result.num_valid_rows == 2

    def test_strict_integer_parse_rejects_whitespace_garbage(self):
        t = Table.from_pydict({"v": ["12", "1 2", "+3", "-4", "4x"]})
        schema = RowLevelSchema().with_int_column("v", is_nullable=False)
        result = validate(t, schema)
        assert result.num_valid_rows == 3  # 12, +3, -4


class TestDecimalConstraints:
    """reference: RowLevelSchemaValidatorTest.scala:149-177."""

    def test_precision_and_scale(self):
        t = Table.from_pydict({"d": ["1.23", "12.345", "123456789.12", "abc"]})
        schema = RowLevelSchema().with_decimal_column(
            "d", precision=6, scale=2, is_nullable=False
        )
        result = validate(t, schema)
        # 12.345 rounds to scale 2 (half-up) and fits; 123456789.12
        # exceeds precision; abc unparseable
        assert result.num_valid_rows == 2
        col = result.valid_rows.column("d")
        assert col.ctype == ColumnType.DECIMAL
        assert list(col.values) == pytest.approx([1.23, 12.35])

    def test_scale_zero(self):
        t = Table.from_pydict({"d": ["5", "5.4", "5.6"]})
        schema = RowLevelSchema().with_decimal_column(
            "d", precision=3, scale=0, is_nullable=False
        )
        result = validate(t, schema)
        assert result.num_valid_rows == 3
        assert list(result.valid_rows.column("d").values) == pytest.approx(
            [5.0, 5.0, 6.0]  # half-up rounding at scale 0
        )


class TestTimestampConstraints:
    """reference: RowLevelSchemaValidatorTest.scala:179-205."""

    def test_mask_parse(self):
        t = Table.from_pydict(
            {
                "ts": [
                    "2024-03-01 10:00:00",
                    "01/03/2024",
                    "2024-03-02 23:59:59",
                ]
            }
        )
        schema = RowLevelSchema().with_timestamp_column(
            "ts", mask="yyyy-MM-dd HH:mm:ss", is_nullable=False
        )
        result = validate(t, schema)
        assert result.num_valid_rows == 2
        col = result.valid_rows.column("ts")
        assert col.ctype == ColumnType.TIMESTAMP
        assert np.datetime64("2024-03-01T10:00:00") in list(col.values)

    def test_alternative_mask(self):
        t = Table.from_pydict({"ts": ["01/03/2024", "2024-03-01"]})
        schema = RowLevelSchema().with_timestamp_column(
            "ts", mask="dd/MM/yyyy", is_nullable=False
        )
        result = validate(t, schema)
        assert result.num_valid_rows == 1


class TestIntegration:
    """reference: RowLevelSchemaValidatorTest.scala:207-264 — multiple
    constrained columns, valid and invalid split preserved row-wise."""

    def test_multi_column_split(self):
        t = Table.from_pydict(
            {
                "id": ["1", "2", "x", "4", "5"],
                "name": ["ann", "bob", "cat", None, "eve"],
                "age": ["30", "17", "45", "22", "200"],
            }
        )
        schema = (
            RowLevelSchema()
            .with_int_column("id", is_nullable=False)
            .with_string_column("name", is_nullable=False, min_length=3)
            .with_int_column("age", is_nullable=False, min_value=18, max_value=120)
        )
        result = validate(t, schema)
        # row1: ok; row2: age 17; row3: id x; row4: name null; row5: age 200
        assert result.num_valid_rows == 1
        assert result.num_invalid_rows == 4
        assert list(result.valid_rows.column("name").values) == ["ann"]
        assert list(result.valid_rows.column("id").values) == [1]
        # invalid rows keep their ORIGINAL (uncast) values
        assert "x" in list(result.invalid_rows.column("id").values)

    def test_counts_sum_to_total(self):
        t = Table.from_pydict({"v": [str(i) for i in range(50)]})
        schema = RowLevelSchema().with_int_column(
            "v", is_nullable=False, max_value=24
        )
        result = validate(t, schema)
        assert result.num_valid_rows + result.num_invalid_rows == 50
        assert result.num_valid_rows == 25

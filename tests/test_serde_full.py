"""Full AnalysisResult serde: real computed metrics for every analyzer
type round-trip through the Gson-compatible JSON — the equivalent of the
reference's AnalysisResultSerdeTest.scala (240 LoC): serialize ->
deserialize -> every metric value, entity, and composite structure
(Distribution, keyed quantiles) survives, including failure metrics."""

from __future__ import annotations

import json

import numpy as np
import pytest

from deequ_tpu.analyzers import (
    ApproxCountDistinct,
    Completeness,
    Compliance,
    Correlation,
    CountDistinct,
    DataType,
    Distinctness,
    Entropy,
    Histogram,
    Maximum,
    Mean,
    Minimum,
    MutualInformation,
    PatternMatch,
    Size,
    StandardDeviation,
    Sum,
    Uniqueness,
    UniqueValueRatio,
)
from deequ_tpu.analyzers.sketch import ApproxQuantile, ApproxQuantiles
from deequ_tpu.core.metrics import HistogramMetric, KeyedDoubleMetric
from deequ_tpu.data.table import Table
from deequ_tpu.repository.base import ResultKey
from deequ_tpu.repository.serde import (
    deserialize_analysis_results,
    serialize_analysis_results,
)
from deequ_tpu.repository.base import AnalysisResult
from deequ_tpu.runners.analysis_runner import AnalysisRunner

ALL_ANALYZERS = [
    Size(),
    Size(where="x > 0"),
    Completeness("x"),
    Compliance("x positive", "x > 0"),
    PatternMatch("s", r"^\d+$"),
    Mean("x"),
    Minimum("x"),
    Maximum("x"),
    Sum("x"),
    StandardDeviation("x"),
    Correlation("x", "y"),
    DataType("s"),
    ApproxCountDistinct("g"),
    ApproxQuantile("x", 0.5),
    ApproxQuantiles("x", (0.25, 0.5, 0.75)),
    Uniqueness(("g",)),
    Distinctness(("g",)),
    UniqueValueRatio(("g",)),
    CountDistinct(("g",)),
    Entropy("g"),
    MutualInformation(("g", "h")),
    Histogram("s"),
]


@pytest.fixture(scope="module")
def computed_context():
    rng = np.random.default_rng(17)
    n = 500
    x = rng.normal(3.0, 2.0, n)
    x[::11] = np.nan
    table = Table.from_numpy(
        {
            "x": x,
            "y": rng.normal(size=n),
            "g": rng.integers(0, 12, n),
            "h": rng.integers(0, 5, n),
            "s": np.array(
                [["7", "abc", "2.5", "true"][i % 4] for i in range(n)], dtype=object
            ),
        }
    )
    return AnalysisRunner.do_analysis_run(table, ALL_ANALYZERS)


def test_full_round_trip_every_analyzer(computed_context):
    key = ResultKey(123456789, {"dataset": "unit", "env": "ci"})
    results = [AnalysisResult(key, computed_context)]
    payload = serialize_analysis_results(results)
    # the payload must be plain JSON
    parsed = json.loads(payload)
    assert isinstance(parsed, list) and len(parsed) == 1

    restored = deserialize_analysis_results(payload)
    assert len(restored) == 1
    assert restored[0].result_key == key
    restored_map = restored[0].analyzer_context.metric_map

    assert set(restored_map) == set(computed_context.metric_map)
    for analyzer, metric in computed_context.metric_map.items():
        other = restored_map[analyzer]
        assert metric.name == other.name and metric.instance == other.instance
        assert metric.entity == other.entity
        if isinstance(metric, HistogramMetric):
            a, b = metric.value.get(), other.value.get()
            assert a.number_of_bins == b.number_of_bins
            assert set(a.values) == set(b.values)
            for k in a.values:
                assert a.values[k].absolute == b.values[k].absolute
                assert a.values[k].ratio == pytest.approx(b.values[k].ratio)
        elif isinstance(metric, KeyedDoubleMetric):
            assert metric.value.get() == pytest.approx(other.value.get())
        else:
            assert metric.value.get() == pytest.approx(other.value.get(), rel=1e-12)


def test_failure_metrics_are_skipped_like_gson(computed_context):
    """Non-finite / failed metrics: the reference's Gson writer refuses
    them; our serializer mirrors that by skipping failures on save (see
    repository/serde.py docstring note)."""
    table = Table.from_numpy({"x": np.array([np.nan, np.nan])})
    ctx = AnalysisRunner.do_analysis_run(table, [Mean("x"), Size()])
    assert ctx.metric_map[Mean("x")].value.is_failure  # empty state
    payload = serialize_analysis_results(
        [AnalysisResult(ResultKey(1, {}), ctx)]
    )
    restored = deserialize_analysis_results(payload)
    restored_map = restored[0].analyzer_context.metric_map
    assert Size() in restored_map
    assert Mean("x") not in restored_map  # failure not persisted


def test_multiple_results_with_distinct_tags(computed_context):
    keys = [ResultKey(t, {"run": str(t)}) for t in (1, 2, 3)]
    results = [AnalysisResult(k, computed_context) for k in keys]
    restored = deserialize_analysis_results(serialize_analysis_results(results))
    assert [r.result_key for r in restored] == keys

"""The fleet-scale DQ service (deequ_tpu/service/): admission control,
tenant quotas, circuit breakers, preemptive scheduling, and the
preempt→resume bit-identity contract.

The load-bearing guarantee is the last one: a heavy partitioned run
preempted by interactive work (DQ405 at a partition boundary) must,
when it resumes, merge its committed partition states with a scan of
only the remainder and produce a result BIT-identical to an
uninterrupted run — on both placements. Everything else (queues,
sheds, quotas) is scheduling policy around that invariant.
"""

from __future__ import annotations

import threading
import time

import numpy as np
import pytest

from deequ_tpu import Check, CheckLevel, CheckStatus, VerificationSuite
from deequ_tpu.core.controller import (
    DQ_DRAIN,
    DQ_PREEMPTED,
    DQ_QUOTA,
    RunCancelled,
    RunController,
)
from deequ_tpu.data.table import Table
from deequ_tpu.lint.explain import explain_plan
from deequ_tpu.repository import InMemoryMetricsRepository
from deequ_tpu.repository.engine import engine_series
from deequ_tpu.repository.states import FileSystemStateRepository
from deequ_tpu.service import (
    DQ_BREAKER_OPEN,
    DQ_DRAINED,
    DQ_QUOTA_EXCEEDED,
    DQ_REJECTED,
    DQ_SHED,
    BreakerBoard,
    DQService,
    QuotaLedger,
    TenantQuota,
)
from deequ_tpu.service.admission import AdmissionController

from test_suite_differential_fuzz import (
    _write_partition,
    random_check,
    random_table,
    suite_snapshot,
)


def _small_table() -> Table:
    return Table.from_pydict(
        {
            "item": ["1", "2", "3", "4"],
            "att1": ["a", "a", "a", "b"],
        }
    )


def _basic_check() -> Check:
    return Check(CheckLevel.ERROR, "basic").is_complete("item")


# ---------------------------------------------------------------------------
# quotas: the sliding scan-bytes ledger
# ---------------------------------------------------------------------------


class FakeClock:
    def __init__(self) -> None:
        self.t = 0.0

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


def test_quota_ledger_sliding_window():
    clock = FakeClock()
    ledger = QuotaLedger(
        {"acme": TenantQuota(scan_bytes_per_window=100.0, window_s=10.0)},
        clock=clock,
    )
    ledger.charge_scan("acme", 60.0)
    assert ledger.bytes_in_window("acme") == 60.0
    assert ledger.scan_headroom("acme") == 40.0
    assert not ledger.over_scan_budget("acme")

    ledger.charge_scan("acme", 60.0)
    assert ledger.scan_headroom("acme") == -20.0
    assert ledger.over_scan_budget("acme")

    # the window slides: old charges expire and the tenant is whole
    clock.advance(11.0)
    assert ledger.bytes_in_window("acme") == 0.0
    assert not ledger.over_scan_budget("acme")
    # lifetime totals survive the pruning (telemetry)
    assert ledger.bytes_total("acme") == 120.0


def test_quota_ledger_unmetered_tenant_has_no_headroom_concept():
    ledger = QuotaLedger()
    ledger.charge_scan("anon", 1e12)
    assert ledger.scan_headroom("anon") is None
    assert not ledger.over_scan_budget("anon")


# ---------------------------------------------------------------------------
# circuit breakers
# ---------------------------------------------------------------------------


def test_breaker_trips_after_threshold_and_cools_down():
    clock = FakeClock()
    board = BreakerBoard(threshold=3, cooldown_s=30.0, clock=clock)
    pair = ("acme", "orders")

    for _ in range(2):
        board.record_failure(*pair)
        assert board.allow(*pair)  # still closed below threshold
    board.record_failure(*pair)
    assert board.state(*pair) == "open"
    assert not board.allow(*pair)
    assert board.open_count() == 1

    # cooldown elapses -> half-open grants exactly ONE probe
    clock.advance(31.0)
    assert board.allow(*pair)
    assert board.state(*pair) == "half_open"
    assert not board.allow(*pair)  # second caller: probe slot taken

    # probe succeeds -> closed, failures reset
    board.record_success(*pair)
    assert board.state(*pair) == "closed"
    assert board.allow(*pair)


def test_breaker_half_open_failure_reopens_with_fresh_cooldown():
    clock = FakeClock()
    board = BreakerBoard(threshold=1, cooldown_s=10.0, clock=clock)
    board.record_failure("t", "d")
    clock.advance(11.0)
    assert board.allow("t", "d")
    board.record_failure("t", "d")  # probe failed
    assert board.state("t", "d") == "open"
    clock.advance(5.0)
    assert not board.allow("t", "d")  # fresh cooldown, not the old one
    clock.advance(6.0)
    assert board.allow("t", "d")


def test_breaker_neutral_probe_releases_slot_stays_half_open():
    clock = FakeClock()
    board = BreakerBoard(threshold=1, cooldown_s=10.0, clock=clock)
    board.record_failure("t", "d")
    clock.advance(11.0)
    assert board.allow("t", "d")
    # the probe was preempted/drained: says nothing about health
    board.record_neutral("t", "d")
    assert board.state("t", "d") == "half_open"
    assert board.allow("t", "d")  # next submission probes again


def test_breaker_isolates_pairs():
    board = BreakerBoard(threshold=1)
    board.record_failure("acme", "orders")
    assert not board.allow("acme", "orders")
    assert board.allow("acme", "payments")
    assert board.allow("globex", "orders")


# ---------------------------------------------------------------------------
# admission control: EXPLAIN-first gates
# ---------------------------------------------------------------------------


def _admission(quotas=None):
    ledger = QuotaLedger(quotas)
    board = BreakerBoard(threshold=1)
    return AdmissionController(ledger, board), ledger, board


def test_admission_admits_small_plan_as_interactive():
    ctl, _, _ = _admission()
    d = ctl.evaluate(
        "acme", "orders", _small_table(), [_basic_check()], [],
        pending_count=0,
    )
    assert d.admitted
    assert d.tier == "interactive"
    assert d.cost is not None and d.cost.admission_tier == "interactive"


def test_admission_rejects_never_admittable_plan_dq410():
    """A plan predicting more scan than the tenant's whole window is
    DQ319 at EXPLAIN and DQ410 at admission — it never reaches a
    worker, today or ever."""
    ctl, _, _ = _admission(
        {"acme": TenantQuota(scan_bytes_per_window=1.0, window_s=60.0)}
    )
    d = ctl.evaluate(
        "acme", "orders", _small_table(), [_basic_check()], [],
        pending_count=0,
    )
    assert not d.admitted
    assert d.code == DQ_REJECTED
    assert "never admittable" in d.reason


def test_admission_rejects_at_max_pending_dq411():
    ctl, _, _ = _admission({"acme": TenantQuota(max_pending=2)})
    d = ctl.evaluate(
        "acme", "orders", _small_table(), [_basic_check()], [],
        pending_count=2,
    )
    assert not d.admitted
    assert d.code == DQ_QUOTA_EXCEEDED


def test_admission_rejects_blown_state_disk_budget_dq411():
    ctl, _, _ = _admission({"acme": TenantQuota(state_disk_bytes=100)})
    d = ctl.evaluate(
        "acme", "orders", _small_table(), [_basic_check()], [],
        pending_count=0, state_disk_usage=101,
    )
    assert not d.admitted
    assert d.code == DQ_QUOTA_EXCEEDED


def test_admission_breaker_open_dq413_and_checked_last():
    ctl, _, board = _admission({"acme": TenantQuota(max_pending=1)})
    board.record_failure("acme", "orders")

    d = ctl.evaluate(
        "acme", "orders", _small_table(), [_basic_check()], [],
        pending_count=0,
    )
    assert not d.admitted and d.code == DQ_BREAKER_OPEN

    # breaker runs LAST: a quota-rejected submission must not consume
    # the half-open probe slot
    board2 = BreakerBoard(threshold=1, cooldown_s=0.0)
    ctl2 = AdmissionController(
        QuotaLedger({"acme": TenantQuota(max_pending=1)}), board2
    )
    board2.record_failure("acme", "orders")
    time.sleep(0.01)  # past the zero cooldown -> half-open on next allow
    d = ctl2.evaluate(
        "acme", "orders", _small_table(), [_basic_check()], [],
        pending_count=1,  # quota-rejected before the breaker is asked
    )
    assert d.code == DQ_QUOTA_EXCEEDED
    assert board2.allow("acme", "orders")  # probe slot still available


# ---------------------------------------------------------------------------
# EXPLAIN: the admission line and the DQ319 lint
# ---------------------------------------------------------------------------


def test_explain_renders_admission_line_with_headroom():
    report = explain_plan(
        _small_table(), checks=[_basic_check()], quota_scan_bytes=1 << 20
    )
    text = report.render()
    assert "admission: tier=interactive" in text
    assert "quota headroom" in text
    assert not any(d.code == "DQ319" for d in report.diagnostics)


def test_explain_dq319_when_plan_exceeds_quota_window():
    report = explain_plan(
        _small_table(), checks=[_basic_check()], quota_scan_bytes=1.0
    )
    codes = [d.code for d in report.diagnostics]
    assert "DQ319" in codes
    assert "quota overdrawn" in report.render()


def test_cost_tier_thresholds(monkeypatch):
    from deequ_tpu.lint import cost as cost_mod

    report = explain_plan(_small_table(), checks=[_basic_check()])
    cost = report.cost
    assert cost.predicted_scan_bytes is not None
    assert cost.admission_tier == "interactive"

    # force the thresholds around the plan's actual prediction
    monkeypatch.setattr(cost_mod, "ADMISSION_INTERACTIVE_BYTES", 0.0)
    monkeypatch.setattr(cost_mod, "ADMISSION_HEAVY_BYTES", 1e18)
    assert cost_mod.cost_tier(cost) == "batch"
    monkeypatch.setattr(cost_mod, "ADMISSION_HEAVY_BYTES", 1.0)
    assert cost_mod.cost_tier(cost) == "heavy"


# ---------------------------------------------------------------------------
# controller: soft cancel at partition boundaries
# ---------------------------------------------------------------------------


def test_soft_cancel_only_trips_at_boundary():
    ctl = RunController()
    ctl.cancel_at_boundary("preempted")
    ctl.check(where="mid-batch")  # non-boundary checks sail through
    with pytest.raises(RunCancelled) as exc:
        ctl.check(where="partition 2", boundary=True)
    assert exc.value.code == DQ_PREEMPTED
    assert exc.value.reason == "preempted"


def test_boundary_probe_can_stop_run_with_quota():
    ctl = RunController()
    ctl.set_boundary_probe(
        lambda progress: "quota" if progress.get("partitions_done", 0) >= 2 else None
    )
    ctl.check(progress={"partitions_done": 1}, boundary=True)
    with pytest.raises(RunCancelled) as exc:
        ctl.check(progress={"partitions_done": 2}, boundary=True)
    assert exc.value.code == DQ_QUOTA


def test_drain_reason_maps_to_dq407():
    ctl = RunController()
    ctl.cancel_at_boundary("drain")
    with pytest.raises(RunCancelled) as exc:
        ctl.check(boundary=True)
    assert exc.value.code == DQ_DRAIN


# ---------------------------------------------------------------------------
# preempt → resume bit-identity (the tentpole invariant)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("placement", ["host", "device"])
def test_preempt_resume_bit_identical_both_placements(
    placement, monkeypatch, tmp_path
):
    """Soft-cancel a partitioned run after its first committed
    partition, then rerun against the same repository: the resumed run
    loads the committed states, scans only the remainder, and its
    result is EXACTLY the uninterrupted run's — snapshot equality,
    sketches included."""
    monkeypatch.setenv("DEEQU_TPU_PLACEMENT", placement)
    rng = np.random.default_rng(41_000)
    checks = [random_check(rng) for _ in range(2)]
    data_dir = tmp_path / "dataset"
    data_dir.mkdir()
    n_parts = 4
    for i in range(n_parts):
        _write_partition(random_table(rng), str(data_dir / f"part-{i}.parquet"))

    def build(repo=None):
        data = Table.scan_parquet_dataset(str(data_dir))
        builder = VerificationSuite().on_data(data)
        for check in checks:
            builder = builder.add_check(check)
        if repo is not None:
            builder = builder.with_state_repository(repo, "svc")
        return builder.with_engine("single")

    baseline = suite_snapshot(build().run())

    repo = FileSystemStateRepository(str(tmp_path / "states"))
    ctl = RunController()
    seen = {"parts": 0}

    def preempt_after_first(progress):
        seen["parts"] = progress.get("partitions_done", 0)
        return "preempted" if seen["parts"] >= 1 else None

    ctl.set_boundary_probe(preempt_after_first)
    with pytest.raises(RunCancelled) as exc:
        build(repo).with_controller(ctl).run()
    assert exc.value.code == DQ_PREEMPTED
    done_at_preempt = exc.value.progress.get("partitions_done", seen["parts"])
    assert 1 <= done_at_preempt < n_parts

    # resume: committed partitions load from the repository
    resumed = build(repo).with_tracing(True).run()
    counters = resumed.run_trace.counters
    assert counters["partitions_cached"] == done_at_preempt
    assert counters["partitions_scanned"] == n_parts - done_at_preempt
    assert suite_snapshot(resumed) == baseline


# ---------------------------------------------------------------------------
# the service end to end
# ---------------------------------------------------------------------------


def _wait_all(handles, timeout=60.0):
    deadline = time.monotonic() + timeout
    for h in handles:
        assert h.wait(timeout=max(0.1, deadline - time.monotonic())), h
    return handles


def test_service_runs_suite_to_done():
    with DQService(workers=2) as svc:
        h = svc.submit("acme", "orders", _small_table(), checks=[_basic_check()])
        assert h.wait(timeout=60)
        assert h.status == "done" and h.code is None
        assert h.tier == "interactive"
        assert h.result.status == CheckStatus.SUCCESS
        snap = svc.telemetry_snapshot()
        assert snap["engine.service.completed"] == 1.0
        assert snap["engine.service.admitted"] == 1.0


def test_service_rejects_after_close_dq414():
    svc = DQService(workers=1)
    svc.close()
    h = svc.submit("acme", "orders", _small_table(), checks=[_basic_check()])
    assert h.done() and h.status == "drained" and h.code == DQ_DRAINED


def test_service_rejects_never_admittable_dq410():
    quotas = {"acme": TenantQuota(scan_bytes_per_window=1.0)}
    with DQService(workers=1, quotas=quotas) as svc:
        h = svc.submit("acme", "orders", _small_table(), checks=[_basic_check()])
        assert h.done()  # rejected synchronously, pre-dispatch
        assert h.status == "rejected" and h.code == DQ_REJECTED


def test_service_breaker_trips_on_corrupt_dataset(tmp_path):
    """Three runs against an unreadable dataset trip the (tenant,
    dataset) breaker; the fourth is DQ413 without touching a worker,
    while the tenant's OTHER dataset still runs fine."""
    bad = tmp_path / "corrupt.parquet"
    bad.write_bytes(b"PAR1 this is not parquet")

    def bad_data():
        return Table.scan_parquet_dataset(str(tmp_path))

    with DQService(workers=1, breaker_threshold=3, breaker_cooldown_s=3600) as svc:
        for _ in range(3):
            h = svc.submit("acme", "bad", bad_data, checks=[_basic_check()])
            assert h.wait(timeout=60)
            assert h.status == "failed"
        assert svc.breakers.state("acme", "bad") == "open"

        h = svc.submit("acme", "bad", bad_data, checks=[_basic_check()])
        assert h.done() and h.code == DQ_BREAKER_OPEN

        ok = svc.submit("acme", "good", _small_table(), checks=[_basic_check()])
        assert ok.wait(timeout=60) and ok.status == "done"


def test_service_sheds_on_saturated_queue_dq412(monkeypatch):
    """With zero idle capacity and a 2-deep interactive queue, the
    fourth low-priority submission is shed — and a high-priority
    arrival displaces a queued low-priority one instead."""
    gate = threading.Event()

    def slow_data():
        gate.wait(timeout=30)
        return _small_table()

    svc = DQService(workers=1, queue_limits={"interactive": 2})
    try:
        running = svc.submit("t", "d0", slow_data, checks=[_basic_check()])
        # wait until the worker picked it up so the queue drains to it
        for _ in range(200):
            if running.status == "running":
                break
            time.sleep(0.01)
        q1 = svc.submit("t", "d1", _small_table(), checks=[_basic_check()])
        q2 = svc.submit("t", "d2", _small_table(), checks=[_basic_check()])
        shed = svc.submit("t", "d3", _small_table(), checks=[_basic_check()])
        assert shed.done() and shed.status == "shed" and shed.code == DQ_SHED

        vip = svc.submit(
            "t", "vip", _small_table(), checks=[_basic_check()], priority=5
        )
        # the worst queued low-priority item was displaced
        displaced = [h for h in (q1, q2) if h.done() and h.status == "shed"]
        assert len(displaced) == 1
        gate.set()
        survivors = [h for h in (running, q1, q2, vip) if not h.done()]
        _wait_all(survivors)
        assert vip.status == "done"
        assert svc.telemetry.value("shed") == 2
    finally:
        gate.set()
        svc.close()


def test_service_quota_stop_mid_run_dq406(tmp_path):
    """The tenant's sliding window already holds charges from an
    earlier run on another dataset; the next run is admissible (DQ319
    needs predicted > whole window) but overdraws the window at a
    partition boundary — DQ406 AFTER the boundary's partition
    committed, so a later run resumes instead of restarting."""
    rng = np.random.default_rng(7)
    check = Check(CheckLevel.ERROR, "x").is_complete("x")
    dirs = []
    for d in ("d1", "d2"):
        data_dir = tmp_path / d
        data_dir.mkdir()
        for i in range(4):
            _write_partition(
                random_table(rng), str(data_dir / f"part-{i}.parquet")
            )
        dirs.append(str(data_dir))

    repo = FileSystemStateRepository(str(tmp_path / "states"))
    p1 = explain_plan(
        Table.scan_parquet_dataset(dirs[0]), checks=[check]
    ).cost.predicted_scan_bytes
    p2 = explain_plan(
        Table.scan_parquet_dataset(dirs[1]), checks=[check]
    ).cost.predicted_scan_bytes
    assert p1 and p2
    # fits either run alone (no DQ319) but not both inside one window
    window = max(p1, p2) * 1.25
    quotas = {"tight": TenantQuota(scan_bytes_per_window=window, window_s=3600)}

    with DQService(workers=1, quotas=quotas, state_repository=repo) as svc:
        first = svc.submit(
            "tight", "d1",
            lambda: Table.scan_parquet_dataset(dirs[0]), checks=[check],
        )
        assert first.wait(timeout=120) and first.status == "done", first.reason
        h = svc.submit(
            "tight", "d2",
            lambda: Table.scan_parquet_dataset(dirs[1]), checks=[check],
        )
        assert h.wait(timeout=120)
        assert h.status == "quota"
        assert h.code == DQ_QUOTA
        assert svc.telemetry.value("quota_stops") == 1
    # the stopped run committed the partitions it finished
    committed = list((tmp_path / "states").rglob("*.dqstate"))
    assert committed


def test_service_preempts_heavy_for_interactive_then_resumes(
    monkeypatch, tmp_path
):
    """End-to-end preemptive scheduling on one worker: a running heavy
    profile is soft-cancelled when interactive work arrives, the
    interactive check runs first, and the heavy run resumes from its
    committed partition states to a result bit-identical to a solo
    run."""
    from deequ_tpu.lint import cost as cost_mod

    rng = np.random.default_rng(31_337)
    check = Check(CheckLevel.ERROR, "x").is_complete("x")
    data_dir = tmp_path / "heavy"
    data_dir.mkdir()
    n_parts = 4
    for i in range(n_parts):
        _write_partition(random_table(rng), str(data_dir / f"part-{i}.parquet"))

    def heavy_data():
        return Table.scan_parquet_dataset(str(data_dir))

    solo = suite_snapshot(
        VerificationSuite()
        .on_data(heavy_data())
        .add_check(check)
        .with_engine("single")
        .run()
    )

    # slow each row-group read so the preemption window is wide
    monkeypatch.setenv("DEEQU_TPU_SOURCE_STALL_MS", "150")
    repo = FileSystemStateRepository(str(tmp_path / "states"))
    with DQService(workers=1, state_repository=repo) as svc:
        # classify the big submission as heavy regardless of its size
        monkeypatch.setattr(cost_mod, "ADMISSION_INTERACTIVE_BYTES", 0.0)
        monkeypatch.setattr(cost_mod, "ADMISSION_HEAVY_BYTES", 1.0)
        heavy = svc.submit("works", "big", heavy_data, checks=[check])
        assert heavy.tier == "heavy"
        monkeypatch.setattr(cost_mod, "ADMISSION_INTERACTIVE_BYTES", 64 << 20)
        monkeypatch.setattr(cost_mod, "ADMISSION_HEAVY_BYTES", 1 << 30)

        for _ in range(500):
            if heavy.status == "running":
                break
            time.sleep(0.01)
        assert heavy.status == "running"

        monkeypatch.delenv("DEEQU_TPU_SOURCE_STALL_MS")
        inter = svc.submit("ops", "ping", _small_table(), checks=[_basic_check()])
        assert inter.tier == "interactive"
        assert inter.wait(timeout=120) and inter.status == "done"
        assert heavy.wait(timeout=180)
        assert heavy.status == "done", (heavy.status, heavy.reason)
        if heavy.preemptions:
            # the interactive check finished BEFORE the preempted heavy
            # run was resumed, and resumption was a real resume: some
            # partitions loaded from the repository
            assert svc.telemetry.value("preempted") >= 1
        assert suite_snapshot(heavy.result) == solo
    committed = list((tmp_path / "states").rglob("*.dqstate"))
    assert committed


def test_service_telemetry_persists_via_engine_repository():
    metrics = InMemoryMetricsRepository()
    with DQService(workers=1, metrics_repository=metrics) as svc:
        h = svc.submit("acme", "orders", _small_table(), checks=[_basic_check()])
        assert h.wait(timeout=60) and h.status == "done"
        svc.publish_telemetry()
    series = engine_series(
        metrics, "engine.service.completed", instance="service"
    )
    assert series and series[-1].metric_value >= 1.0


def test_service_multi_tenant_fuzz_isolation(tmp_path):
    """N tenants submit concurrently; every run's result is
    bit-identical to the same suite run solo — no cross-tenant state
    bleed through the shared pool, repository, or ledger."""
    rng = np.random.default_rng(9_900)
    tenants = []
    for t in range(4):
        table = random_table(rng)
        checks = [random_check(rng)]
        builder = VerificationSuite().on_data(table)
        for c in checks:
            builder = builder.add_check(c)
        solo = suite_snapshot(builder.with_engine("single").run())
        tenants.append((f"tenant-{t}", table, checks, solo))

    repo = FileSystemStateRepository(str(tmp_path / "states"))
    with DQService(workers=3, state_repository=repo) as svc:
        handles = []
        for name, table, checks, _ in tenants * 2:  # two rounds each
            handles.append(svc.submit(name, "ds", table, checks=checks))
        _wait_all(handles, timeout=180)
        for h, (name, _, _, solo) in zip(handles, tenants * 2):
            assert h.status == "done", (name, h.status, h.reason)
            assert suite_snapshot(h.result) == solo, name

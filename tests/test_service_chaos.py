"""Chaos containment for the DQ service (`service.*` fault points).

The fleet-scale claim is BLAST RADIUS: a fault injected into the
service's own machinery — admission bookkeeping, a queue pop, a worker,
the scheduler tick — may fail or delay the submission it hits, but it
must never (a) take the pool down, (b) leak into another tenant's
result bits, or (c) leave threads behind after close(). Every test
here runs two tenants and asserts the untouched tenant's snapshot is
bit-identical to a clean solo run.
"""

from __future__ import annotations

import threading
import time

import pytest

from deequ_tpu import Check, CheckLevel, VerificationSuite
from deequ_tpu.data.table import Table
from deequ_tpu.service import DQService
from deequ_tpu.testing import faults

from test_suite_differential_fuzz import suite_snapshot


def _table(seed: int) -> Table:
    return Table.from_pydict(
        {
            "item": [str(i) for i in range(1, 7)],
            "att1": ["a", "b", "a", None, "b", "a"][seed % 2 :]
            + ["a"] * (seed % 2),
        }
    )


def _check() -> Check:
    return Check(CheckLevel.ERROR, "chaos").is_complete("item")


def _solo_snapshot(table: Table) -> tuple:
    return suite_snapshot(
        VerificationSuite()
        .on_data(table)
        .add_check(_check())
        .with_engine("single")
        .run()
    )


def _service_threads() -> list:
    return [
        t for t in threading.enumerate() if "-service-" in (t.name or "")
    ]


# one spec per service fault point: persistent and transient shapes
SERVICE_CHAOS_MATRIX = [
    "seed=201,service.admission:1.0:1",   # one admission failure
    "seed=202,service.admission:0.6:3",   # flaky admission bookkeeping
    "seed=203,service.worker:1.0:1",      # one worker death mid-run
    "seed=204,service.worker:0.5:2",      # flaky workers
    "seed=205,service.queue:1.0:2",       # two queue-pop corruptions
    "seed=206,stall=0.02,service.scheduler:1.0:4",  # wedged housekeeping
]


@pytest.mark.parametrize("spec", SERVICE_CHAOS_MATRIX)
def test_service_faults_contained_no_cross_tenant_blast(spec):
    """Inject each service.* fault shape while two tenants submit; the
    pool must survive, at least one submission must still complete, and
    every COMPLETED result must be bit-identical to its solo run —
    faults fail submissions, never corrupt them."""
    table_a, table_b = _table(0), _table(1)
    solo = {"a": _solo_snapshot(table_a), "b": _solo_snapshot(table_b)}

    svc = DQService(workers=2, tick_s=0.02)
    try:
        with faults.install(spec) as plan:
            handles = []
            for round_i in range(3):
                handles.append(
                    ("a", svc.submit("tenant-a", "ds", table_a, checks=[_check()]))
                )
                handles.append(
                    ("b", svc.submit("tenant-b", "ds", table_b, checks=[_check()]))
                )
            for _, h in handles:
                assert h.wait(timeout=120), h
            injected = sum(plan.injected.values())

        done = [(t, h) for t, h in handles if h.status == "done"]
        assert done, "chaos must not starve the pool entirely"
        for tenant, h in done:
            assert suite_snapshot(h.result) == solo[tenant], (spec, tenant)
        # a failed submission carries forensics, not silence
        for _, h in handles:
            if h.status == "failed":
                assert h.reason or h.error is not None
        if "scheduler" not in spec:
            assert injected >= 1, spec
    finally:
        svc.close()
    assert _service_threads() == []


def test_admission_fault_rejects_submission_but_pool_survives():
    """A raise-kind fault inside admission bookkeeping turns into a
    DQ410 rejection for THAT submission; the next submission (fault
    budget spent) is admitted and runs to done."""
    table = _table(0)
    with DQService(workers=1) as svc:
        with faults.install("seed=42,service.admission:1.0:1"):
            h1 = svc.submit("t", "ds", table, checks=[_check()])
            assert h1.done() and h1.status == "rejected"
            assert "admission unavailable" in h1.reason
            h2 = svc.submit("t", "ds", table, checks=[_check()])
            assert h2.wait(timeout=60) and h2.status == "done"
        assert svc.telemetry.value("admission_faults") == 1


def test_worker_fault_feeds_breaker_not_pool():
    """A persistent worker fault fails every run of the hit tenant and
    eventually trips its breaker — while the OTHER tenant's runs on the
    same two workers keep completing bit-identically."""
    table_a, table_b = _table(0), _table(1)
    solo_b = _solo_snapshot(table_b)
    with DQService(workers=2, breaker_threshold=3, breaker_cooldown_s=3600) as svc:
        with faults.install("seed=7,service.worker:1.0"):
            failed = []
            for _ in range(3):
                h = svc.submit("victim", "ds", table_a, checks=[_check()])
                assert h.wait(timeout=60)
                failed.append(h.status)
        assert failed == ["failed", "failed", "failed"]
        assert svc.breakers.state("victim", "ds") == "open"
        assert svc.telemetry.value("worker_faults") == 3

        ok = svc.submit("bystander", "ds", table_b, checks=[_check()])
        assert ok.wait(timeout=60) and ok.status == "done"
        assert suite_snapshot(ok.result) == solo_b


def test_queue_fault_delays_but_never_drops_work():
    """Raise-kind faults on the tier-queue pop happen BEFORE the item
    is removed: the worker counts the fault, retries, and the queued
    submission still runs — delayed, never lost."""
    table = _table(0)
    with DQService(workers=1) as svc:
        with faults.install("seed=11,service.queue:1.0:3") as plan:
            h = svc.submit("t", "ds", table, checks=[_check()])
            assert h.wait(timeout=120) and h.status == "done"
            assert sum(plan.injected.values()) >= 1
        assert svc.telemetry.value("queue_faults") >= 1


def test_scheduler_stall_does_not_block_execution():
    """Sleep-kind faults wedge the scheduler's housekeeping tick; the
    worker path is independent of it, so submissions still complete."""
    table = _table(0)
    with DQService(workers=1, tick_s=0.01) as svc:
        with faults.install("seed=13,stall=0.05,service.scheduler:1.0:10"):
            h = svc.submit("t", "ds", table, checks=[_check()])
            assert h.wait(timeout=60) and h.status == "done"


def test_close_joins_all_threads_even_under_faults():
    """drain() must leave zero service threads behind even while chaos
    is armed on every service point."""
    table = _table(0)
    spec = (
        "seed=99,service.worker:0.5:2,service.queue:0.5:2,"
        "stall=0.01,service.scheduler:0.5:5"
    )
    svc = DQService(workers=3, tick_s=0.01)
    with faults.install(spec):
        for _ in range(4):
            svc.submit("t", "ds", table, checks=[_check()])
        time.sleep(0.05)
        svc.close()
    assert _service_threads() == []
    # idempotent: a second close is a no-op
    svc.close()
    assert _service_threads() == []

"""Shard planner (parallel/shard.py): deterministic rendezvous
assignment over partition fingerprints — every shard computes the same
plan independently, membership change moves the minimum number of
partitions, and the global merge order is preserved."""

from __future__ import annotations

import pytest

from deequ_tpu.parallel.shard import (
    ShardPlan,
    plan_shards,
    rendezvous_weight,
)
from deequ_tpu.testing import faults


class FakePartition:
    def __init__(self, i):
        self.name = f"part-{i:03d}.parquet"
        self.path = f"/data/{self.name}"
        self.fingerprint = f"fp-{i:03d}-{i * 2654435761 % 997:x}"


def parts(n):
    return [FakePartition(i) for i in range(n)]


class TestPlanShards:
    def test_every_partition_assigned_exactly_once(self):
        plan = plan_shards(parts(23), 4)
        seen = []
        for k in range(4):
            seen.extend(plan.assignment(k).names)
        assert sorted(seen) == [p.name for p in parts(23)]

    def test_deterministic_across_processes(self):
        # every process plans independently; identical inputs must yield
        # identical plans (this IS the coordination mechanism)
        a = plan_shards(parts(31), 5)
        b = plan_shards(parts(31), 5)
        assert a == b

    def test_global_order_preserved(self):
        plan = plan_shards(parts(12), 3)
        assert [n for n, _p, _f in plan.order] == [p.name for p in parts(12)]
        for k in range(3):
            names = plan.assignment(k).names
            # each shard's slice keeps dataset order
            assert list(names) == [
                n for n, _p, _f in plan.order if n in set(names)
            ]

    def test_owner_of_matches_assignments(self):
        plan = plan_shards(parts(17), 3)
        for k in range(3):
            for name in plan.assignment(k).names:
                assert plan.owner_of(name) == k

    def test_minimal_movement_on_exclusion(self):
        # losing shard 1 must ONLY move shard 1's partitions; everything
        # owned by a surviving shard stays put (the rendezvous property)
        ps = parts(40)
        before = plan_shards(ps, 4)
        after = plan_shards(ps, 4, exclude=(1,))
        assert after.assignment(1).names == ()
        for k in (0, 2, 3):
            assert set(before.assignment(k).names) <= set(
                after.assignment(k).names
            )
        moved = set(before.assignment(1).names)
        gained = set()
        for k in (0, 2, 3):
            gained |= set(after.assignment(k).names) - set(
                before.assignment(k).names
            )
        assert gained == moved

    def test_skew_is_bounded_and_reported(self):
        plan = plan_shards(parts(64), 4)
        assert plan.max_partitions >= 64 // 4
        assert plan.skew >= 1.0
        # rendezvous over 64 partitions should not degenerate
        assert plan.skew < 2.0

    def test_single_shard_owns_everything(self):
        plan = plan_shards(parts(9), 1)
        assert plan.assignment(0).num_partitions == 9
        assert plan.skew == 1.0

    def test_weight_is_stable(self):
        assert rendezvous_weight("fp-a", 0) == rendezvous_weight("fp-a", 0)
        assert rendezvous_weight("fp-a", 0) != rendezvous_weight("fp-a", 1)

    def test_rejects_bad_inputs(self):
        with pytest.raises(ValueError):
            plan_shards(parts(4), 0)
        with pytest.raises(ValueError):
            plan_shards(parts(4), 2, exclude=(0, 1))

    def test_empty_dataset_plans_empty(self):
        plan = plan_shards([], 3)
        assert plan.order == ()
        assert plan.assignment(0).names == ()
        assert plan.skew == 1.0

    def test_assign_fault_point_raises(self):
        with faults.install("shard.assign:1"):
            with pytest.raises(faults.InjectedFaultError):
                plan_shards(parts(8), 2)


class TestShardPlanShape:
    def test_counts(self):
        plan = plan_shards(parts(10), 3)
        total = sum(plan.assignment(k).num_partitions for k in range(3))
        assert total == 10
        assert plan.max_partitions == max(
            plan.assignment(k).num_partitions for k in range(3)
        )
        assert plan.min_partitions == min(
            plan.assignment(k).num_partitions for k in range(3)
        )
        assert isinstance(plan, ShardPlan)

"""Sharded streaming scan (ISSUE 15 tentpole): N processes each fold
their own partition range through the full streamed path, then all-merge
per-partition DQST state envelopes over the semigroup.

The load-bearing contract pinned here: a sharded run at ANY shard count
— including after host loss, corrupt envelopes, mid-run cancellation
and resume — is BIT-identical to a solo run over the same dataset, and
the two populate/consume the same state cache.

The cross-process gather is injectable, so an N-shard mesh runs as N
threads with a barrier gather (the real DCN path is exercised by the
procspawn test at the bottom, which uses a file-exchange gather between
real interpreters)."""

from __future__ import annotations

import os
import textwrap
import threading
import warnings

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from deequ_tpu.analyzers.frequency import Uniqueness
from deequ_tpu.analyzers.scan import (
    Completeness,
    Maximum,
    Mean,
    Minimum,
    StandardDeviation,
    Sum,
)
from deequ_tpu.analyzers.sketch import ApproxCountDistinct
from deequ_tpu.core.controller import RunCancelled, RunController, SharedCancelToken
from deequ_tpu.data.source import PartitionedParquetSource
from deequ_tpu.parallel import plan_shards, run_sharded_analysis
from deequ_tpu.parallel.multihost import run_multihost_analysis
from deequ_tpu.repository.states import (
    FileSystemStateRepository,
    StateDecodeError,
    decode_shard_states,
    encode_shard_states,
)
from deequ_tpu.runners.analysis_runner import AnalysisRunner
from deequ_tpu.testing import faults

N_PARTS = 9


def make_dataset(root, n_parts=N_PARTS, seed=0):
    """n_parts uneven parquet partitions with NULLs in the numeric
    column (fold identities and empty-state paths stay exercised)."""
    os.makedirs(root, exist_ok=True)
    rng = np.random.default_rng(seed)
    paths = []
    for i in range(n_parts):
        n = 300 + 131 * i
        x = rng.normal(3.0, 2.0, n)
        x[:: max(5, i + 3)] = np.nan
        t = pa.table(
            {
                "x": pa.array(x, mask=np.isnan(x)),
                "g": pa.array(rng.integers(0, 40, n)),
            }
        )
        p = os.path.join(root, f"part-{i:03d}.parquet")
        pq.write_table(t, p, row_group_size=256)
        paths.append(p)
    return paths


def analyzer_suite():
    return [
        Mean("x"),
        Sum("x"),
        Minimum("x"),
        Maximum("x"),
        StandardDeviation("x"),
        Completeness("x"),
        ApproxCountDistinct("g"),
        Uniqueness(("g",)),  # grouping: rides the `rest` gather
    ]


def metric_values(ctx):
    out = {}
    for a, m in ctx.metric_map.items():
        if m.value.is_failure:
            out[repr(a)] = ("FAIL", type(m.value.exception).__name__)
        else:
            out[repr(a)] = m.value.get()
    return out


class ThreadGather:
    """Barrier allgather for an in-process N-shard mesh: every
    participant deposits its payload, waits for the full round, reads
    all in shard order. Each thread binds its rank once; rounds advance
    independently per thread so the shareable and `rest` gathers both
    work."""

    def __init__(self, n):
        self.n = n
        self.barrier = threading.Barrier(n)
        self.rounds = {}
        self.lock = threading.Lock()
        self.local = threading.local()

    def bind(self, rank):
        self.local.rank = rank
        self.local.round = 0

    def __call__(self, payload):
        r = self.local.round
        self.local.round += 1
        with self.lock:
            self.rounds.setdefault(r, {})[self.local.rank] = payload
        self.barrier.wait(timeout=120)
        ranks = sorted(self.rounds[r])
        out = [self.rounds[r][i] for i in ranks]
        self.barrier.wait(timeout=120)
        return out


def run_sharded_threads(src, analyzers, shards, num_shards, **kw):
    """Run the given shard ids as threads over a barrier gather.
    Returns (contexts, errors), both keyed by position in `shards`."""
    tg = ThreadGather(len(shards))
    out = [None] * len(shards)
    errs = [None] * len(shards)

    def work(pos, k):
        tg.bind(k)
        try:
            out[pos] = run_sharded_analysis(
                src, analyzers, shard=k, num_shards=num_shards, gather=tg, **kw
            )
        except BaseException as e:  # noqa: BLE001 - reported to the caller
            errs[pos] = e
            tg.barrier.abort()

    threads = [
        threading.Thread(target=work, args=(pos, k))
        for pos, k in enumerate(shards)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=180)
        assert not t.is_alive(), "sharded run deadlocked"
    return out, errs


@pytest.fixture(scope="module")
def dataset(tmp_path_factory):
    root = tmp_path_factory.mktemp("sharded")
    paths = make_dataset(str(root))
    src = PartitionedParquetSource(paths)
    solo = AnalysisRunner.do_analysis_run(src, analyzer_suite())
    return {"paths": paths, "solo": metric_values(solo)}


class TestShardedVsSoloBitwise:
    """The acceptance differential: fuzz shard counts × partition
    placements; every shard's context must equal the solo run EXACTLY
    (float equality, not approx — merge order is global on every path)."""

    @pytest.mark.parametrize("num_shards", [1, 2, 3, 4])
    def test_every_shard_count_is_bit_identical(self, dataset, num_shards):
        src = PartitionedParquetSource(dataset["paths"])
        ctxs, errs = run_sharded_threads(
            src, analyzer_suite(), list(range(num_shards)), num_shards
        )
        assert errs == [None] * num_shards
        for ctx in ctxs:
            assert metric_values(ctx) == dataset["solo"]

    def test_excluded_shard_placement_is_bit_identical(self, dataset):
        # membership change (lost shard 1 of 3) re-places its partitions
        # on the survivors; the merged result must not move a bit
        src = PartitionedParquetSource(dataset["paths"])
        ctxs, errs = run_sharded_threads(
            src, analyzer_suite(), [0, 2], num_shards=3, exclude=(1,)
        )
        assert errs == [None, None]
        for ctx in ctxs:
            assert metric_values(ctx) == dataset["solo"]

    def test_fuzzed_datasets_and_placements(self, tmp_path):
        rng = np.random.default_rng(42)
        for trial in range(2):
            paths = make_dataset(
                str(tmp_path / f"ds{trial}"), n_parts=6, seed=100 + trial
            )
            src = PartitionedParquetSource(paths)
            analyzers = [Mean("x"), Sum("x"), StandardDeviation("x")]
            solo = metric_values(
                AnalysisRunner.do_analysis_run(src, analyzers)
            )
            num_shards = int(rng.integers(2, 5))
            ctxs, errs = run_sharded_threads(
                src, analyzers, list(range(num_shards)), num_shards
            )
            assert errs == [None] * num_shards
            for ctx in ctxs:
                assert metric_values(ctx) == solo


class TestStateCacheInterop:
    """Sharded and solo runs commit partition states under the SAME
    (dataset, signature, fingerprint) keys: each resumes the other."""

    def test_sharded_commits_feed_a_solo_resume(self, dataset, tmp_path):
        src = PartitionedParquetSource(dataset["paths"])
        repo = FileSystemStateRepository(str(tmp_path / "cache"))
        analyzers = [Mean("x"), Minimum("x"), StandardDeviation("x")]
        ctxs, errs = run_sharded_threads(
            src, analyzers, [0, 1], 2,
            state_repository=repo, dataset_name="ds",
        )
        assert errs == [None, None]
        from deequ_tpu import observe

        with observe.traced_run("solo-resume", enable=True) as handle:
            solo = AnalysisRunner.do_analysis_run(
                src, analyzers, state_repository=repo, dataset_name="ds"
            )
        assert metric_values(solo) == metric_values(ctxs[0])
        counters = handle.trace.counters
        # every partition the sharded mesh committed loads as a cache
        # hit — the solo resume scans NOTHING
        assert counters.get("partitions_cached") == N_PARTS
        assert counters.get("partitions_scanned", 0) == 0

    def test_solo_commits_feed_a_sharded_resume(self, dataset, tmp_path):
        src = PartitionedParquetSource(dataset["paths"])
        repo = FileSystemStateRepository(str(tmp_path / "cache"))
        analyzers = [Mean("x"), Maximum("x")]
        solo = AnalysisRunner.do_analysis_run(
            src, analyzers, state_repository=repo, dataset_name="ds"
        )
        calls = []
        import deequ_tpu.ops.fused as fused

        orig = fused.scan_partition

        def counting(*a, **kw):
            calls.append(1)
            return orig(*a, **kw)

        fused.scan_partition = counting
        try:
            ctxs, errs = run_sharded_threads(
                src, analyzers, [0, 1, 2], 3,
                state_repository=repo, dataset_name="ds",
            )
        finally:
            fused.scan_partition = orig
        assert errs == [None] * 3
        for ctx in ctxs:
            assert metric_values(ctx) == metric_values(solo)
        # the sharded mesh resumed entirely from the solo run's commits
        assert not calls


class TestCancellationAndResume:
    def test_cancel_propagates_through_the_gather(self, dataset):
        # shard 0 is told to stop before it scans anything; shard 1 is
        # healthy. BOTH must raise RunCancelled (the cancelled envelope
        # crosses the gather) and neither may deadlock in the collective.
        src = PartitionedParquetSource(dataset["paths"])
        ctl = RunController()
        ctl.cancel_at_boundary("preempted")
        analyzers = [Mean("x"), Sum("x")]
        tg = ThreadGather(2)
        errs = [None, None]

        def work(k):
            tg.bind(k)
            try:
                run_sharded_analysis(
                    src, analyzers, shard=k, num_shards=2, gather=tg,
                    controller=ctl if k == 0 else None,
                )
            except BaseException as e:  # noqa: BLE001
                errs[k] = e

        threads = [threading.Thread(target=work, args=(k,)) for k in (0, 1)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
            assert not t.is_alive(), "cancelled mesh deadlocked"
        assert isinstance(errs[0], RunCancelled)
        assert isinstance(errs[1], RunCancelled)
        assert errs[1].reason == "preempted"

    def test_mid_run_cancel_resumes_bit_identically(self, dataset, tmp_path):
        # shard 1 dies after committing ONE partition; the rerun picks
        # up from the committed states and lands exactly on solo
        src = PartitionedParquetSource(dataset["paths"])
        repo = FileSystemStateRepository(str(tmp_path / "cache"))
        analyzers = [Mean("x"), StandardDeviation("x")]
        ctl = RunController()
        seen = []

        def probe(progress):
            seen.append(progress)
            if progress.get("partitions_done", 0) >= 1:
                return "preempted"
            return None

        ctl.set_boundary_probe(probe)
        tg = ThreadGather(2)
        errs = [None, None]

        def work(k):
            tg.bind(k)
            try:
                run_sharded_analysis(
                    src, analyzers, shard=k, num_shards=2, gather=tg,
                    controller=ctl if k == 1 else None,
                    state_repository=repo, dataset_name="ds",
                )
            except BaseException as e:  # noqa: BLE001
                errs[k] = e

        threads = [threading.Thread(target=work, args=(k,)) for k in (0, 1)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
            assert not t.is_alive()
        assert isinstance(errs[0], RunCancelled)
        assert isinstance(errs[1], RunCancelled)

        # resume: same mesh, same repo — completes and matches solo
        ctxs, errs2 = run_sharded_threads(
            src, analyzers, [0, 1], 2,
            state_repository=repo, dataset_name="ds",
        )
        assert errs2 == [None, None]
        solo = AnalysisRunner.do_analysis_run(src, analyzers)
        for ctx in ctxs:
            assert metric_values(ctx) == metric_values(solo)

    def test_shared_cancel_token_stops_a_shard(self, dataset, tmp_path):
        src = PartitionedParquetSource(dataset["paths"])
        token = SharedCancelToken(str(tmp_path / "cancel.token"))
        token.trip("drain")
        assert token.tripped and token.reason() == "drain"
        ctl = RunController()
        with pytest.raises(RunCancelled) as exc:
            run_sharded_analysis(
                PartitionedParquetSource(dataset["paths"]),
                [Mean("x")],
                shard=0,
                num_shards=1,
                controller=ctl,
                cancel_token=token,
            )
        assert exc.value.reason == "drain"


class TestChaosRecovery:
    """The chaos points: a lost shard envelope or a corrupt partition
    entry recovers from committed states (or a local rescan) and
    converges bit-identically — DQ320 warns, nothing silently drops."""

    def _populate(self, dataset, tmp_path, analyzers):
        src = PartitionedParquetSource(dataset["paths"])
        repo = FileSystemStateRepository(str(tmp_path / "cache"))
        ctxs, errs = run_sharded_threads(
            src, analyzers, [0, 1], 2,
            state_repository=repo, dataset_name="ds",
        )
        assert errs == [None, None]
        return src, repo, metric_values(ctxs[0])

    def test_host_loss_recovers_from_committed_states(
        self, dataset, tmp_path
    ):
        analyzers = [Mean("x"), Sum("x"), Minimum("x")]
        src, repo, expected = self._populate(dataset, tmp_path, analyzers)
        with faults.install("shard.host_loss:1:1"):
            with warnings.catch_warnings(record=True) as caught:
                warnings.simplefilter("always")
                ctx = run_sharded_analysis(
                    src, analyzers, shard=0, num_shards=1,
                    state_repository=repo, dataset_name="ds",
                )
        assert metric_values(ctx) == expected
        assert any("DQ320" in str(w.message) for w in caught)

    def test_host_loss_without_cache_rescans(self, dataset):
        # no repository: the lost envelope's partitions rescan locally —
        # slower, never wrong
        src = PartitionedParquetSource(dataset["paths"])
        analyzers = [Mean("x"), Maximum("x")]
        solo = metric_values(AnalysisRunner.do_analysis_run(src, analyzers))
        with faults.install("shard.host_loss:1:1"):
            with warnings.catch_warnings(record=True):
                warnings.simplefilter("always")
                ctx = run_sharded_analysis(
                    src, analyzers, shard=0, num_shards=1
                )
        assert metric_values(ctx) == solo

    def test_corrupt_merge_entry_recovers(self, dataset, tmp_path):
        analyzers = [Mean("x"), StandardDeviation("x")]
        src, repo, expected = self._populate(dataset, tmp_path, analyzers)
        with faults.install("shard.merge:1:1"):
            with warnings.catch_warnings(record=True) as caught:
                warnings.simplefilter("always")
                ctx = run_sharded_analysis(
                    src, analyzers, shard=0, num_shards=1,
                    state_repository=repo, dataset_name="ds",
                )
        assert metric_values(ctx) == expected
        assert any("DQ320" in str(w.message) for w in caught)

    def test_two_shard_mesh_survives_host_loss_fault(self, dataset, tmp_path):
        # the fault fires inside a live 2-shard mesh (budget 1: one
        # shard drops its neighbour's envelope post-gather); both still
        # converge on solo
        analyzers = [Mean("x"), Sum("x")]
        src, repo, expected = self._populate(dataset, tmp_path, analyzers)
        with faults.install("shard.host_loss:1:1"):
            with warnings.catch_warnings(record=True):
                warnings.simplefilter("always")
                ctxs, errs = run_sharded_threads(
                    src, analyzers, [0, 1], 2,
                    state_repository=repo, dataset_name="ds",
                )
        assert errs == [None, None]
        for ctx in ctxs:
            assert metric_values(ctx) == expected


class TestShardEnvelope:
    def test_round_trip(self):
        entries = [("fp-a", b"blob-a"), ("fp-b", b"blob-b" * 100)]
        blob = encode_shard_states(3, "sig123", entries)
        env = decode_shard_states(blob)
        assert env.shard == 3
        assert env.signature == "sig123"
        assert env.cancelled is False and env.reason == ""
        assert env.entries == entries

    def test_cancelled_flag_round_trips(self):
        blob = encode_shard_states(
            1, "sig", [], cancelled=True, reason="preempted"
        )
        env = decode_shard_states(blob)
        assert env.cancelled is True
        assert env.reason == "preempted"
        assert env.entries == []

    @pytest.mark.parametrize(
        "mutate",
        [
            lambda b: b[:-1],  # truncated digest
            lambda b: b"XXXX" + b[4:],  # wrong magic
            lambda b: b[:10] + bytes([b[10] ^ 0xFF]) + b[11:],  # bit flip
            lambda b: b"",  # empty (lost host)
            lambda b: b + b"\x00",  # trailing bytes
        ],
    )
    def test_any_defect_is_a_decode_error(self, mutate):
        blob = encode_shard_states(0, "sig", [("fp", b"x" * 32)])
        with pytest.raises(StateDecodeError):
            decode_shard_states(mutate(blob))


class TestDeprecatedTableEntry:
    def test_run_multihost_analysis_warns_and_still_works(self):
        from deequ_tpu.data.table import Table

        rng = np.random.default_rng(5)
        table = Table.from_pydict({"x": rng.normal(size=1000)})
        with pytest.warns(DeprecationWarning, match="run_sharded_analysis"):
            ctx = run_multihost_analysis(table, [Mean("x")])
        (metric,) = ctx.metric_map.values()
        assert metric.value.get() == pytest.approx(
            float(np.mean(np.asarray(table.column("x").values))), rel=1e-6
        )


class TestExplainAndDrift:
    def test_explain_renders_shards_line(self, dataset):
        from deequ_tpu.lint.explain import explain_plan

        src = PartitionedParquetSource(dataset["paths"])
        plan = plan_shards(list(src.partitions()), 4)
        counts = [plan.assignment(k).num_partitions for k in range(4)]
        res = explain_plan(
            src,
            [Mean("x")],
            num_shards=4,
            shard_partitions=counts,
        )
        text = res.rendered if hasattr(res, "rendered") else str(res)
        assert "shards: 4 processes ×" in text
        assert "max skew" in text

    def test_shard_drift_pins_to_zero(self, dataset):
        from deequ_tpu import observe
        from deequ_tpu.lint.cost import analyze_plan, cost_drift
        from deequ_tpu.lint.schema import SchemaInfo

        src = PartitionedParquetSource(dataset["paths"])
        analyzers = [Mean("x"), Sum("x")]
        num_shards = 4
        plan = plan_shards(list(src.partitions()), num_shards)
        counts = [
            plan.assignment(k).num_partitions for k in range(num_shards)
        ]

        # capture the other shards' payloads once, then trace shard 0
        # against the full gathered set
        class Captured(Exception):
            pass

        payloads = {}
        for k in range(1, num_shards):
            def cap(payload, k=k):
                payloads[k] = payload
                raise Captured()

            with pytest.raises(Captured):
                run_sharded_analysis(
                    src, analyzers, shard=k, num_shards=num_shards, gather=cap
                )

        def full(payload):
            return [payload] + [payloads[i] for i in range(1, num_shards)]

        cost = analyze_plan(
            analyzers,
            SchemaInfo.from_table(src),
            num_shards=num_shards,
            shard_partitions=counts,
        )
        with observe.traced_run("shard0", enable=True) as handle:
            run_sharded_analysis(
                src, analyzers, shard=0, num_shards=num_shards, gather=full
            )
        drift = cost_drift(cost, handle.trace)
        # the planner and the runtime compute the SAME deterministic
        # shard split: zero drift, by construction
        assert drift["drift.shard_count"] == 0.0
        assert drift["drift.shard_partitions_max"] == 0.0

    def test_telemetry_derives_shard_series(self, dataset):
        from deequ_tpu import observe
        from deequ_tpu.observe.telemetry import engine_metric_record

        src = PartitionedParquetSource(dataset["paths"])
        with observe.traced_run("solo-shard", enable=True) as handle:
            run_sharded_analysis(src, [Mean("x")], shard=0, num_shards=1)
        rec = engine_metric_record(handle.trace)
        assert rec["engine.shard.skew_ratio"] == 1.0
        assert rec["engine.shard.merge_bytes"] > 0.0
        assert rec["engine.shard.rows_per_s"] > 0.0


class TestSourceSubset:
    def test_subset_preserves_order_and_validates(self, dataset):
        src = PartitionedParquetSource(dataset["paths"])
        pick = [dataset["paths"][4], dataset["paths"][1]]
        sub = src.subset(pick)
        # dataset (basename) order, not argument order
        assert [p.name for p in sub.partitions()] == [
            "part-001.parquet",
            "part-004.parquet",
        ]
        with pytest.raises(ValueError, match="not in this dataset"):
            src.subset(["/nope.parquet"])
        with pytest.raises(ValueError, match="no partitions"):
            src.subset([])


WORKER = textwrap.dedent(
    """
    import json, os, sys, time

    os.environ["JAX_PLATFORMS"] = "cpu"
    rank, _port, tmpdir = int(sys.argv[1]), sys.argv[2], sys.argv[3]

    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq

    data_dir = os.path.join(tmpdir, "data")
    done = os.path.join(tmpdir, "data.done")
    if rank == 0:
        os.makedirs(data_dir, exist_ok=True)
        rng = np.random.default_rng(7)
        for i in range(6):
            n = 200 + 90 * i
            x = rng.normal(1.0, 3.0, n)
            x[::7] = np.nan
            pq.write_table(
                pa.table({"x": pa.array(x, mask=np.isnan(x))}),
                os.path.join(data_dir, f"part-{i:03d}.parquet"),
            )
        open(done, "w").close()
    else:
        while not os.path.exists(done):
            time.sleep(0.05)

    os.environ["DEEQU_TPU_SHARD"] = str(rank)

    from deequ_tpu.analyzers.scan import Maximum, Mean, StandardDeviation, Sum
    from deequ_tpu.data.source import PartitionedParquetSource
    from deequ_tpu.parallel import run_sharded_analysis

    # file-exchange allgather between the two real interpreters: atomic
    # rename publish, poll for the peer
    _round = [0]

    def gather(payload):
        r = _round[0]
        _round[0] += 1
        gdir = os.path.join(tmpdir, f"gather-{r}")
        os.makedirs(gdir, exist_ok=True)
        tmp = os.path.join(gdir, f"{rank}.tmp")
        with open(tmp, "wb") as f:
            f.write(payload)
        os.replace(tmp, os.path.join(gdir, f"{rank}.bin"))
        out = []
        for i in range(2):
            p = os.path.join(gdir, f"{i}.bin")
            deadline = time.time() + 90
            while not os.path.exists(p):
                if time.time() > deadline:
                    raise TimeoutError(f"peer {i} never published round {r}")
                time.sleep(0.02)
            with open(p, "rb") as f:
                out.append(f.read())
        return out

    src = PartitionedParquetSource(
        sorted(
            os.path.join(data_dir, f)
            for f in os.listdir(data_dir)
            if f.endswith(".parquet")
        )
    )
    analyzers = [Mean("x"), Sum("x"), Maximum("x"), StandardDeviation("x")]
    ctx = run_sharded_analysis(
        src, analyzers, shard=rank, num_shards=2, gather=gather
    )
    out = {repr(a): ctx.metric_map[a].value.get() for a in analyzers}
    print("RESULT:" + json.dumps(out), flush=True)
    """
)


def test_two_process_sharded_scan(tmp_path):
    """Two REAL interpreters shard the dataset between themselves and
    must land on identical metrics — equal to a solo pass in THIS
    process over an identically-generated dataset."""
    from deequ_tpu.parallel.procspawn import WorkerFailure, run_worker_processes

    try:
        results = run_worker_processes(WORKER, 2, timeout=150)
    except WorkerFailure as e:
        if not e.runtime_unavailable:
            raise
        pytest.skip(f"two-process runtime unavailable: {e}")

    assert results[0] == results[1]

    # regenerate the same dataset (same seed) and solo-scan it here
    root = tmp_path / "data"
    os.makedirs(root)
    rng = np.random.default_rng(7)
    paths = []
    for i in range(6):
        n = 200 + 90 * i
        x = rng.normal(1.0, 3.0, n)
        x[::7] = np.nan
        p = str(root / f"part-{i:03d}.parquet")
        pq.write_table(
            pa.table({"x": pa.array(x, mask=np.isnan(x))}), p
        )
        paths.append(p)
    analyzers = [Mean("x"), Sum("x"), Maximum("x"), StandardDeviation("x")]
    solo = AnalysisRunner.do_analysis_run(
        PartitionedParquetSource(paths), analyzers
    )
    expected = {repr(a): solo.metric_map[a].value.get() for a in analyzers}
    assert results[0] == expected

"""Golden byte-level tests for the binary state layouts — pins the
reference's per-type formats (reference: StateProvider.scala:85-174) so
a refactor can't silently change the wire/checkpoint format that
`runOnAggregatedStates`-style workflows and the multihost envelope
depend on."""

from __future__ import annotations

import struct

import numpy as np
import pytest

from deequ_tpu.analyzers import (
    Completeness,
    Compliance,
    DataType,
    Maximum,
    Mean,
    Minimum,
    PatternMatch,
    Size,
    StandardDeviation,
    Sum,
    Correlation,
)
from deequ_tpu.analyzers.state_provider import deserialize_state, serialize_state
from deequ_tpu.analyzers.states import (
    CorrelationState,
    DataTypeHistogram,
    MaxState,
    MeanState,
    MinState,
    NumMatches,
    NumMatchesAndCount,
    StandardDeviationState,
    SumState,
)


class TestScalarStateGoldenBytes:
    """Big-endian fixed layouts, exactly as the reference writes them."""

    def test_size_is_one_long(self):
        # reference: StateProvider.scala Long layout for NumMatches
        blob = serialize_state(Size(), NumMatches(12345))
        assert blob == struct.pack(">q", 12345)
        assert len(blob) == 8

    @pytest.mark.parametrize(
        "analyzer",
        [Completeness("c"), Compliance("n", "c > 0"), PatternMatch("c", r"\d")],
        ids=lambda a: a.name,
    )
    def test_ratio_states_are_two_longs(self, analyzer):
        blob = serialize_state(analyzer, NumMatchesAndCount(7, 9))
        assert blob == struct.pack(">qq", 7, 9)
        assert len(blob) == 16

    def test_sum_min_max_are_one_double(self):
        assert serialize_state(Sum("c"), SumState(2.5)) == struct.pack(">d", 2.5)
        assert serialize_state(Minimum("c"), MinState(-1.5)) == struct.pack(
            ">d", -1.5
        )
        assert serialize_state(Maximum("c"), MaxState(9.25)) == struct.pack(
            ">d", 9.25
        )

    def test_mean_is_double_plus_long(self):
        blob = serialize_state(Mean("c"), MeanState(10.5, 4))
        assert blob == struct.pack(">dq", 10.5, 4)
        assert len(blob) == 16

    def test_stddev_is_three_doubles(self):
        blob = serialize_state(
            StandardDeviation("c"), StandardDeviationState(4.0, 2.5, 1.25)
        )
        assert blob == struct.pack(">ddd", 4.0, 2.5, 1.25)
        assert len(blob) == 24

    def test_correlation_is_six_doubles(self):
        state = CorrelationState(3.0, 1.0, 2.0, 0.5, 0.25, 0.125)
        blob = serialize_state(Correlation("a", "b"), state)
        assert blob == struct.pack(">dddddd", 3.0, 1.0, 2.0, 0.5, 0.25, 0.125)
        assert len(blob) == 48

    def test_datatype_is_length_prefixed_five_longs(self):
        # reference: 40-byte DataTypeHistogram (DataType.scala:58-100)
        state = DataTypeHistogram(1, 2, 3, 4, 5)
        blob = serialize_state(DataType("c"), state)
        (length,) = struct.unpack(">i", blob[:4])
        assert length == 40
        assert struct.unpack(">qqqqq", blob[4:]) == (1, 2, 3, 4, 5)

    def test_big_endianness_pinned(self):
        # a value whose little-endian bytes differ makes endianness explicit
        blob = serialize_state(Size(), NumMatches(1))
        assert blob == b"\x00\x00\x00\x00\x00\x00\x00\x01"

    def test_hand_derived_literal_goldens_per_format(self):
        """Literal byte goldens hand-derived from the reference layout
        spec (StateProvider.scala:85-174): big-endian Java primitives,
        IEEE-754 doubles written out by hand (2.5 = 0x4004<<48,
        10.5 = 0x4025<<48, 1.25 = 0x3FF4<<48, ...). Nothing here calls
        struct or the serializer to produce the expected side — these
        bytes were derived on paper, so a shared encoding bug in both
        producer and expectation cannot hide."""
        # Size → one big-endian long: 12345 = 0x3039
        assert serialize_state(Size(), NumMatches(12345)) == (
            b"\x00\x00\x00\x00\x00\x00\x30\x39"
        )
        # Completeness → (matches, count) two longs: (7, 9)
        assert serialize_state(
            Completeness("c"), NumMatchesAndCount(7, 9)
        ) == (
            b"\x00\x00\x00\x00\x00\x00\x00\x07"
            b"\x00\x00\x00\x00\x00\x00\x00\x09"
        )
        # Sum → one double: 2.5 = sign 0, exp 1024 (0x400), mantissa
        # .25 → 0x4004000000000000
        assert serialize_state(Sum("c"), SumState(2.5)) == (
            b"\x40\x04\x00\x00\x00\x00\x00\x00"
        )
        # Mean → double + long: 10.5 = 0x4025000000000000, count 4
        assert serialize_state(Mean("c"), MeanState(10.5, 4)) == (
            b"\x40\x25\x00\x00\x00\x00\x00\x00"
            b"\x00\x00\x00\x00\x00\x00\x00\x04"
        )
        # StdDev → three doubles (n, avg, m2) = (4.0, 2.5, 1.25):
        # 4.0 = 0x4010…, 2.5 = 0x4004…, 1.25 = 0x3FF4…
        assert serialize_state(
            StandardDeviation("c"), StandardDeviationState(4.0, 2.5, 1.25)
        ) == (
            b"\x40\x10\x00\x00\x00\x00\x00\x00"
            b"\x40\x04\x00\x00\x00\x00\x00\x00"
            b"\x3f\xf4\x00\x00\x00\x00\x00\x00"
        )
        # Correlation → six doubles (n,xAvg,yAvg,ck,xMk,yMk) =
        # (3.0, 1.0, 2.0, 0.5, 0.25, 0.125) = 0x4008…, 0x3FF0…,
        # 0x4000…, 0x3FE0…, 0x3FD0…, 0x3FC0…
        assert serialize_state(
            Correlation("a", "b"),
            CorrelationState(3.0, 1.0, 2.0, 0.5, 0.25, 0.125),
        ) == (
            b"\x40\x08\x00\x00\x00\x00\x00\x00"
            b"\x3f\xf0\x00\x00\x00\x00\x00\x00"
            b"\x40\x00\x00\x00\x00\x00\x00\x00"
            b"\x3f\xe0\x00\x00\x00\x00\x00\x00"
            b"\x3f\xd0\x00\x00\x00\x00\x00\x00"
            b"\x3f\xc0\x00\x00\x00\x00\x00\x00"
        )
        # DataType → int length prefix 40 (0x28) + five longs
        assert serialize_state(
            DataType("c"), DataTypeHistogram(1, 2, 3, 4, 5)
        ) == (
            b"\x00\x00\x00\x28"
            b"\x00\x00\x00\x00\x00\x00\x00\x01"
            b"\x00\x00\x00\x00\x00\x00\x00\x02"
            b"\x00\x00\x00\x00\x00\x00\x00\x03"
            b"\x00\x00\x00\x00\x00\x00\x00\x04"
            b"\x00\x00\x00\x00\x00\x00\x00\x05"
        )


class TestHllGoldenLayout:
    def test_words_are_length_prefixed_52_longs(self):
        """reference: 512 6-bit registers packed into NUM_WORDS=52 longs
        (StatefulHyperloglogPlus.scala:154)."""
        from deequ_tpu.analyzers import ApproxCountDistinct
        from deequ_tpu.analyzers.sketch import ApproxCountDistinctState
        from deequ_tpu.ops.sketches import hll

        registers = np.zeros(hll.M, dtype=np.int32)
        registers[0] = 5
        registers[10] = 63
        blob = serialize_state(
            ApproxCountDistinct("c"), ApproxCountDistinctState(registers)
        )
        (length,) = struct.unpack(">i", blob[:4])
        assert length == 52 * 8
        words = struct.unpack(">52q", blob[4:])
        # register 0 lives in the low 6 bits of word 0
        assert words[0] & 0x3F == 5
        restored = deserialize_state(ApproxCountDistinct("c"), blob)
        assert np.array_equal(restored.registers, registers)

    def test_register_count_is_512(self):
        from deequ_tpu.ops.sketches import hll

        assert hll.M == 512  # p=9, from RELATIVE_SD=0.05


class TestRoundTripIdentity:
    """serialize∘deserialize is the identity on every scalar state."""

    @pytest.mark.parametrize(
        "analyzer, state",
        [
            (Size(), NumMatches(0)),
            (Size(), NumMatches(2**40)),
            (Completeness("c"), NumMatchesAndCount(0, 0)),
            (Sum("c"), SumState(float("inf"))),
            (Minimum("c"), MinState(-0.0)),
            (Mean("c"), MeanState(-1e300, 2**31)),
            (StandardDeviation("c"), StandardDeviationState(1.0, 0.0, 0.0)),
            (
                Correlation("a", "b"),
                CorrelationState(2.0, 1e-300, -1e300, 0.0, 1.0, 2.0),
            ),
            (DataType("c"), DataTypeHistogram(0, 0, 0, 0, 2**62)),
        ],
        ids=lambda v: repr(v)[:40],
    )
    def test_round_trip(self, analyzer, state):
        blob = serialize_state(analyzer, state)
        restored = deserialize_state(analyzer, blob)
        assert type(restored) is type(state)
        assert restored == state
        # byte-level identity: re-serializing must reproduce the blob,
        # which pins sign bits (-0.0) and other ==-invisible detail
        assert serialize_state(analyzer, restored) == blob

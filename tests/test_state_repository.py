"""Persistent partition-state cache (repository/states.py): envelope
serde round trips per state family, corruption/truncation/version-bump
fallback, write atomicity + concurrent-writer locking, partition
fingerprints, plan signatures, `merge_range`, and the cached-vs-scanned
split of `FusedScanPass._run_partitioned` — all under the bit-identity
contract: a cache hit must reproduce the exact bytes a rescan would.
"""

from __future__ import annotations

import glob
import os
import struct
import threading

import numpy as np
import pytest

from deequ_tpu.analyzers import (
    ApproxCountDistinct,
    ApproxQuantile,
    Completeness,
    Correlation,
    CountDistinct,
    DataType,
    Maximum,
    Mean,
    Minimum,
    Size,
    StandardDeviation,
    Sum,
)
from deequ_tpu.analyzers import states as S
from deequ_tpu.analyzers.frequency import FrequenciesAndNumRows
from deequ_tpu.data.table import ColumnType, Table
from deequ_tpu.ops.fused import FusedScanPass
from deequ_tpu.repository.states import (
    STATE_FORMAT_VERSION,
    STATE_MAGIC,
    FileSystemStateRepository,
    InMemoryStateRepository,
    StateDecodeError,
    decode_states,
    encode_states,
    merge_states,
    plan_signature,
    plan_signature_for,
)
from deequ_tpu.runners.analysis_runner import AnalysisRunner


def _bits(x: float) -> bytes:
    """Bit pattern of a float64 — distinguishes -0.0 from +0.0 and
    pins the exact NaN payload."""
    return struct.pack(">d", float(x))


def _random_table(rng: np.random.Generator, n: int = 500) -> Table:
    x = rng.normal(0.0, 10.0, n)
    x[rng.random(n) < 0.1] = np.nan
    x[rng.random(n) < 0.05] = -0.0
    y = x * 0.5 + rng.normal(0, 1.0, n)
    g = rng.integers(0, 40, n)
    return Table.from_pydict(
        {"x": list(x), "y": list(y), "g": [int(v) for v in g]},
        types={
            "x": ColumnType.DOUBLE,
            "y": ColumnType.DOUBLE,
            "g": ColumnType.LONG,
        },
    )


def _fold(analyzers, table):
    """(analyzer, state) pairs from one fused pass over `table`."""
    results = FusedScanPass(list(analyzers)).run(table)
    for r in results:
        assert r.error is None, r.error
    return [(r.analyzer, r.state) for r in results]


# ---------------------------------------------------------------------------
# envelope round trips, per state family
# ---------------------------------------------------------------------------


class TestSerdeRoundTrip:
    def test_moment_states_bit_exact(self):
        """Hand-built moment states with the nasty float values: -0.0,
        NaN, infinities must survive the envelope with the exact bit
        pattern (not just ==, which -0.0/NaN would launder)."""
        pairs = [
            (Size(), S.NumMatches(0)),
            (Completeness("x"), S.NumMatchesAndCount(3, 7)),
            (Sum("x"), S.SumState(-0.0)),
            (Mean("x"), S.MeanState(float("nan"), 4)),
            (Minimum("x"), S.MinState(float("-inf"))),
            (Maximum("x"), S.MaxState(float("inf"))),
            (StandardDeviation("x"), S.StandardDeviationState(5.0, -0.0, 2.5)),
            (
                Correlation("x", "y"),
                S.CorrelationState(3.0, 1.5, float("nan"), -0.0, 0.25, 4.0),
            ),
            (DataType("x"), S.DataTypeHistogram(1, 2, 3, 4, 5)),
        ]
        blob = encode_states(pairs)
        decoded = decode_states(blob, [a for a, _ in pairs])
        for (analyzer, original), restored in zip(pairs, decoded):
            assert type(restored) is type(original), repr(analyzer)
            for name in getattr(original, "__dataclass_fields__", {}):
                a = getattr(original, name)
                b = getattr(restored, name)
                if isinstance(a, float):
                    assert _bits(a) == _bits(b), (repr(analyzer), name)
                else:
                    assert a == b, (repr(analyzer), name)

    def test_frequency_state_round_trip(self):
        state = FrequenciesAndNumRows(
            ["s"],
            [np.array(["", "a b", "it's", "v1"], dtype=object)],
            np.array([3, 1, 4, 1], dtype=np.int64),
            9,
        )
        analyzer = CountDistinct(["s"])
        decoded = decode_states(encode_states([(analyzer, state)]), [analyzer])[0]
        assert decoded.columns == state.columns
        assert decoded.num_rows == state.num_rows
        assert np.array_equal(decoded.counts, state.counts)
        for a, b in zip(decoded.key_columns, state.key_columns):
            assert list(a) == list(b)

    def test_none_state_round_trips_as_identity(self):
        analyzers = [Size(), Mean("x")]
        blob = encode_states([(analyzers[0], S.NumMatches(5)), (analyzers[1], None)])
        decoded = decode_states(blob, analyzers)
        assert decoded[0] == S.NumMatches(5)
        assert decoded[1] is None
        assert merge_states(None, decoded[0]) == S.NumMatches(5)
        assert merge_states(decoded[0], None) == S.NumMatches(5)

    @pytest.mark.parametrize("seed", range(4))
    def test_folded_states_round_trip_and_merge_bit_identical(self, seed):
        """The property that makes the cache sound: for every cacheable
        family (moments, HLL, KLL), metric(merge(decode(encode(s1)),
        decode(encode(s2)))) must equal metric(merge(s1, s2)) BIT-exactly
        — including the KLL sketch, whose merge draws compaction offsets
        from its serialized rng position."""
        rng = np.random.default_rng(9_100 + seed)
        analyzers = [
            Size(),
            Completeness("x"),
            Sum("x"),
            Mean("x"),
            Minimum("x"),
            Maximum("x"),
            StandardDeviation("x"),
            Correlation("x", "y"),
            DataType("x"),
            ApproxCountDistinct("g"),
            ApproxQuantile("x", 0.5),
        ]
        pairs_a = _fold(analyzers, _random_table(rng, int(rng.integers(50, 1200))))
        pairs_b = _fold(analyzers, _random_table(rng, int(rng.integers(50, 1200))))

        direct = [
            merge_states(sa, sb)
            for (_, sa), (_, sb) in zip(pairs_a, pairs_b)
        ]
        cached = [
            merge_states(sa, sb)
            for sa, sb in zip(
                decode_states(encode_states(pairs_a), analyzers),
                decode_states(encode_states(pairs_b), analyzers),
            )
        ]
        for analyzer, s_direct, s_cached in zip(analyzers, direct, cached):
            m_direct = analyzer.compute_metric_from(s_direct)
            m_cached = analyzer.compute_metric_from(s_cached)
            assert m_direct.value.is_success == m_cached.value.is_success, (
                repr(analyzer)
            )
            if m_direct.value.is_success:
                va, vb = m_direct.value.get(), m_cached.value.get()
                if isinstance(va, float):
                    assert _bits(va) == _bits(vb), (repr(analyzer), va, vb)
                else:
                    assert va == vb, repr(analyzer)

    def test_kll_rng_position_survives_serde(self):
        """The sketch's generator position is part of its state: without
        it, a deserialized partial merges differently from the live one."""
        rng = np.random.default_rng(7)
        analyzer = ApproxQuantile("x", 0.25)
        ((_, state),) = _fold([analyzer], _random_table(rng, 3000))
        restored = decode_states(
            encode_states([(analyzer, state)]), [analyzer]
        )[0]
        assert state.digest.rng_state_bytes() == restored.digest.rng_state_bytes()
        other = _fold([analyzer], _random_table(rng, 2000))[0][1]
        assert _bits(state.merge(other).digest.quantile(0.25)) == _bits(
            restored.merge(other).digest.quantile(0.25)
        )


# ---------------------------------------------------------------------------
# corruption / truncation / version drift -> rescan, never a wrong answer
# ---------------------------------------------------------------------------


class TestEnvelopeDefects:
    def _blob(self):
        analyzers = [Size(), Mean("x")]
        pairs = [(analyzers[0], S.NumMatches(11)), (analyzers[1], S.MeanState(2.5, 4))]
        return encode_states(pairs), analyzers

    def test_bit_flip_raises_digest_mismatch(self):
        blob, analyzers = self._blob()
        corrupt = bytearray(blob)
        corrupt[len(blob) // 2] ^= 0x40
        with pytest.raises(StateDecodeError, match="digest mismatch"):
            decode_states(bytes(corrupt), analyzers)

    @pytest.mark.parametrize("keep", [0, 3, 11, -1])
    def test_truncation_raises(self, keep):
        blob, analyzers = self._blob()
        with pytest.raises(StateDecodeError):
            decode_states(blob[: keep if keep >= 0 else len(blob) - 5], analyzers)

    def test_version_bump_raises(self):
        """A well-formed envelope from a FUTURE serde version (valid
        digest, different version word) must be refused, not guessed at."""
        blob, analyzers = self._blob()
        body = bytearray(blob[:-32])
        struct.pack_into(">I", body, len(STATE_MAGIC), STATE_FORMAT_VERSION + 1)
        import hashlib

        rebuilt = bytes(body) + hashlib.sha256(bytes(body)).digest()
        with pytest.raises(StateDecodeError, match="version"):
            decode_states(rebuilt, analyzers)

    def test_missing_analyzer_raises(self):
        blob, _ = self._blob()
        with pytest.raises(StateDecodeError, match="no state for analyzer"):
            decode_states(blob, [Size(), Minimum("x")])

    def test_load_states_degrades_to_none_with_dq314(self):
        repo = InMemoryStateRepository()
        blob, analyzers = self._blob()
        corrupt = bytearray(blob)
        corrupt[-1] ^= 0xFF
        repo._put("ds", "sig", "fp0", bytes(corrupt))
        with pytest.warns(RuntimeWarning, match="DQ314"):
            assert repo.load_states("ds", "fp0", "sig", analyzers) is None

    def test_corrupt_entry_falls_back_to_rescan_end_to_end(self, tmp_path, monkeypatch):
        """Corrupt one on-disk .dqstate: the warm run warns DQ314, scans
        exactly that partition, and the metrics stay bit-identical."""
        monkeypatch.delenv("DEEQU_TPU_STATE_CACHE", raising=False)
        rng = np.random.default_rng(42)
        data_dir = tmp_path / "data"
        data_dir.mkdir()
        for i in range(3):
            _random_table(rng, 400 + 13 * i).to_parquet(
                str(data_dir / f"p{i}.parquet"), row_group_size=128
            )
        analyzers = [Size(), Mean("x"), StandardDeviation("x")]
        repo = FileSystemStateRepository(str(tmp_path / "cache"))

        cold = AnalysisRunner.do_analysis_run(
            Table.scan_parquet_dataset(str(data_dir)), analyzers,
            state_repository=repo, dataset_name="defects",
        )
        entries = sorted(glob.glob(str(tmp_path / "cache" / "**" / "*.dqstate"),
                                   recursive=True))
        assert len(entries) == 3
        raw = bytearray(open(entries[1], "rb").read())
        raw[len(raw) // 3] ^= 0x01
        with open(entries[1], "wb") as fh:
            fh.write(raw)

        with pytest.warns(RuntimeWarning, match="DQ314"):
            warm = AnalysisRunner.do_analysis_run(
                Table.scan_parquet_dataset(str(data_dir)), analyzers,
                state_repository=repo, dataset_name="defects", tracing=True,
            )
        counters = warm.run_trace.counters
        assert counters["partitions_cached"] == 2
        assert counters["partitions_scanned"] == 1
        for a in analyzers:
            assert _bits(cold.metric_map[a].value.get()) == _bits(
                warm.metric_map[a].value.get()
            )


# ---------------------------------------------------------------------------
# filesystem backend: atomicity + concurrent writers
# ---------------------------------------------------------------------------


class TestFileSystemBackend:
    def test_writes_are_atomic_no_tmp_left_behind(self, tmp_path):
        repo = FileSystemStateRepository(str(tmp_path))
        pairs = [(Size(), S.NumMatches(1))]
        assert repo.save_states("ds", "fp", "sig", pairs)
        leftovers = [
            p for p in glob.glob(str(tmp_path / "**" / "*"), recursive=True)
            if p.endswith(".tmp")
        ]
        assert leftovers == []
        assert repo.load_states("ds", "fp", "sig", [Size()]) == [S.NumMatches(1)]

    def test_unserializable_state_is_not_cached(self, tmp_path):
        class OpaqueAnalyzer:
            """No serialize_state family handles this analyzer."""

            def __repr__(self):
                return "OpaqueAnalyzer()"

        class OpaqueState:
            def merge(self, other):
                return self

        repo = FileSystemStateRepository(str(tmp_path))
        assert not repo.save_states(
            "ds", "fp", "sig", [(OpaqueAnalyzer(), OpaqueState())]
        )
        assert not repo.has_states("ds", "fp", "sig")

    def test_two_concurrent_writers_never_interleave(self, tmp_path):
        """Regression: two threads hammering the same dataset (including
        the same partition key) must leave every entry decodable — the
        per-dataset lock plus tmp+rename forbids torn or mixed files."""
        repo = FileSystemStateRepository(str(tmp_path))
        analyzers = [Size(), Mean("x")]
        barrier = threading.Barrier(2)
        errors: list = []

        def writer(tid: int) -> None:
            try:
                barrier.wait()
                for i in range(40):
                    pairs = [
                        (analyzers[0], S.NumMatches(1000 * tid + i)),
                        (analyzers[1], S.MeanState(float(tid), i + 1)),
                    ]
                    # fp-shared is contended by both threads; fp-<tid>-<i>
                    # is private — both must end up internally consistent
                    repo.save_states("ds", "fp-shared", "sig", pairs)
                    repo.save_states("ds", f"fp-{tid}-{i}", "sig", pairs)
                    loaded = repo.load_states("ds", "fp-shared", "sig", analyzers)
                    if loaded is not None:
                        size, mean = loaded
                        # an entry is one thread's write in full or the
                        # other's — never a mixture
                        assert size.num_matches // 1000 == int(mean.total), (
                            size, mean,
                        )
            except BaseException as e:  # noqa: BLE001
                errors.append(e)

        threads = [threading.Thread(target=writer, args=(t,)) for t in (1, 2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert errors == []
        for tid in (1, 2):
            for i in range(40):
                assert repo.load_states(
                    "ds", f"fp-{tid}-{i}", "sig", analyzers
                ) is not None

    def test_exotic_dataset_names_stay_one_path_component(self, tmp_path):
        repo = FileSystemStateRepository(str(tmp_path))
        pairs = [(Size(), S.NumMatches(2))]
        for name in ("../escape", "a/b", "sp ace", ""):
            assert repo.save_states(name, "fp", "sig", pairs)
            assert repo.load_states(name, "fp", "sig", [Size()]) == [
                S.NumMatches(2)
            ]
        assert not os.path.exists(str(tmp_path.parent / "escape"))


# ---------------------------------------------------------------------------
# fingerprints + plan signatures
# ---------------------------------------------------------------------------


class TestKeys:
    def test_fingerprint_stable_and_content_sensitive(self, tmp_path):
        from deequ_tpu.data.source import partition_fingerprint

        rng = np.random.default_rng(3)
        path = str(tmp_path / "p0.parquet")
        _random_table(rng, 300).to_parquet(path, row_group_size=100)
        fp1 = partition_fingerprint(path)
        assert fp1 == partition_fingerprint(path)

        # same basename in another directory (dataset relocated):
        # fingerprint survives, so the cache stays warm after a move
        moved = tmp_path / "moved"
        moved.mkdir()
        import shutil

        shutil.copy(path, str(moved / "p0.parquet"))
        assert partition_fingerprint(str(moved / "p0.parquet")) == fp1

        # rewritten content self-invalidates
        _random_table(rng, 301).to_parquet(path, row_group_size=100)
        assert partition_fingerprint(path) != fp1

    def test_fingerprint_memoized_by_stat_signature(self, tmp_path, monkeypatch):
        """An unchanged file (same device/inode/size/mtime_ns) must hit
        the fingerprint memo without re-reading the parquet footer —
        that's what keeps a preempted run's time-to-first-resume-boundary
        flat in partition count. Any rewrite changes the stat signature
        and recomputes."""
        import pyarrow.parquet as pq

        from deequ_tpu.data.source import partition_fingerprint

        rng = np.random.default_rng(5)
        path = str(tmp_path / "m0.parquet")
        _random_table(rng, 200).to_parquet(path, row_group_size=100)
        fp1 = partition_fingerprint(path)

        def boom(*args, **kwargs):
            raise AssertionError("footer re-read on unchanged file")

        monkeypatch.setattr(pq, "ParquetFile", boom)
        assert partition_fingerprint(path) == fp1
        monkeypatch.undo()

        _random_table(rng, 201).to_parquet(path, row_group_size=100)
        assert partition_fingerprint(path) != fp1

    def test_plan_signature_sensitivity(self):
        base = dict(
            placement="device", compute_dtype="float64",
            batch_size=None, batch_rows=1 << 20,
        )
        sig = plan_signature([Size(), Mean("x")], **base)
        assert sig == plan_signature([Size(), Mean("x")], **base)
        assert sig != plan_signature([Mean("x"), Size()], **base)
        assert sig != plan_signature([Size()], **base)
        assert sig != plan_signature(
            [Size(), Mean("x")], **{**base, "placement": "host"}
        )
        assert sig != plan_signature(
            [Size(), Mean("x")], **{**base, "compute_dtype": "float32"}
        )
        assert sig != plan_signature(
            [Size(), Mean("x")], **{**base, "batch_rows": 1 << 19}
        )


# ---------------------------------------------------------------------------
# merge_range: zero-scan range metrics
# ---------------------------------------------------------------------------


class TestMergeRange:
    def test_merge_range_matches_full_scan(self, tmp_path, monkeypatch):
        monkeypatch.delenv("DEEQU_TPU_STATE_CACHE", raising=False)
        rng = np.random.default_rng(11)
        data_dir = tmp_path / "data"
        data_dir.mkdir()
        for i in range(4):
            _random_table(rng, 200 + 31 * i).to_parquet(
                str(data_dir / f"p{i}.parquet"), row_group_size=64
            )
        analyzers = [Size(), Mean("x"), ApproxQuantile("x", 0.5)]
        repo = FileSystemStateRepository(str(tmp_path / "cache"))
        source = Table.scan_parquet_dataset(str(data_dir))
        full = AnalysisRunner.do_analysis_run(
            source, analyzers, state_repository=repo, dataset_name="range",
        )

        signature = plan_signature_for(analyzers, source)
        fingerprints = [p.fingerprint for p in source.partitions()]
        ranged = repo.merge_range("range", fingerprints, analyzers, signature)
        for a in analyzers:
            assert _bits(full.metric_map[a].value.get()) == _bits(
                ranged.metric_map[a].value.get()
            )

        # a strict subset must equal a direct scan of those files
        subset = source.partitions()[1:3]
        sub_source = Table.scan_parquet_dataset([p.path for p in subset])
        direct = AnalysisRunner.do_analysis_run(sub_source, analyzers)
        ranged_subset = repo.merge_range(
            "range", [p.fingerprint for p in subset], analyzers, signature
        )
        for a in analyzers:
            assert _bits(direct.metric_map[a].value.get()) == _bits(
                ranged_subset.metric_map[a].value.get()
            )

    def test_merge_range_missing_partition_raises(self):
        repo = InMemoryStateRepository()
        with pytest.raises(KeyError):
            repo.merge_range("ds", ["nope"], [Size()], "sig")


# ---------------------------------------------------------------------------
# the kill switch
# ---------------------------------------------------------------------------


def test_state_cache_kill_switch(tmp_path, monkeypatch):
    rng = np.random.default_rng(5)
    data_dir = tmp_path / "data"
    data_dir.mkdir()
    for i in range(3):
        _random_table(rng, 150).to_parquet(str(data_dir / f"p{i}.parquet"))
    analyzers = [Size(), Mean("x")]
    repo = FileSystemStateRepository(str(tmp_path / "cache"))

    monkeypatch.delenv("DEEQU_TPU_STATE_CACHE", raising=False)
    warm_prep = AnalysisRunner.do_analysis_run(
        Table.scan_parquet_dataset(str(data_dir)), analyzers,
        state_repository=repo, dataset_name="kill",
    )
    monkeypatch.setenv("DEEQU_TPU_STATE_CACHE", "0")
    off = AnalysisRunner.do_analysis_run(
        Table.scan_parquet_dataset(str(data_dir)), analyzers,
        state_repository=repo, dataset_name="kill", tracing=True,
    )
    counters = off.run_trace.counters
    assert counters["partitions_scanned"] == 3
    assert "partitions_cached" not in counters
    for a in analyzers:
        assert _bits(warm_prep.metric_map[a].value.get()) == _bits(
            off.metric_map[a].value.get()
        )

"""Mesh × streaming integration: the production shape of the 1B-row
target — DistributedScanPass and the grouping path fed by a ParquetSource
on the 8-device CPU mesh, asserted against the in-memory single-device
run (the streaming analogue of StateAggregationIntegrationTest)."""

from __future__ import annotations

import numpy as np
import pytest

from deequ_tpu.analyzers import (
    ApproxCountDistinct,
    Completeness,
    CountDistinct,
    Entropy,
    Maximum,
    Mean,
    Minimum,
    Size,
    StandardDeviation,
    Sum,
    Uniqueness,
)
from deequ_tpu.analyzers.sketch import ApproxQuantile
from deequ_tpu.data.source import ParquetSource
from deequ_tpu.data.table import Table
from deequ_tpu.parallel.distributed import DistributedScanPass, data_mesh
from deequ_tpu.runners.analysis_runner import AnalysisRunner

N_ROWS = 200_000


@pytest.fixture(scope="module")
def parquet_path(tmp_path_factory):
    import pyarrow as pa
    import pyarrow.parquet as pq

    rng = np.random.default_rng(3)
    x = rng.normal(5.0, 3.0, N_ROWS)
    x[::17] = np.nan
    cat = np.array(["red", "green", "blue", None], dtype=object)[
        rng.integers(0, 4, N_ROWS)
    ]
    g = rng.integers(0, 500, N_ROWS)
    path = tmp_path_factory.mktemp("streammesh") / "data.parquet"
    table = pa.table(
        {
            "x": pa.array(x, mask=np.isnan(x)),
            "cat": pa.array(list(cat)),
            "g": pa.array(g),
        }
    )
    # several row groups so streaming actually iterates
    pq.write_table(table, str(path), row_group_size=50_000)
    return str(path)


@pytest.fixture(scope="module")
def in_memory(parquet_path):
    return Table.from_parquet(parquet_path)


SCAN_ANALYZERS = [
    Size(),
    Completeness("x"),
    Mean("x"),
    Minimum("x"),
    Maximum("x"),
    Sum("x"),
    StandardDeviation("x"),
    ApproxCountDistinct("g"),
    ApproxCountDistinct("cat"),
]


def test_distributed_scan_over_parquet_source(parquet_path, in_memory):
    """DistributedScanPass fed by a ParquetSource (stream + shard) equals
    the in-memory single-device run."""
    source = ParquetSource(parquet_path, batch_rows=1 << 16)
    mesh = data_mesh()
    sharded = DistributedScanPass(
        SCAN_ANALYZERS, mesh=mesh, batch_size_per_device=1 << 13
    ).run(source)
    single = AnalysisRunner.do_analysis_run(
        in_memory, SCAN_ANALYZERS, engine="single"
    )
    for result in sharded:
        got = result.analyzer.compute_metric_from(result.state_or_raise())
        want = single.metric_map[result.analyzer]
        assert got.value.is_success and want.value.is_success, result.analyzer
        assert got.value.get() == pytest.approx(want.value.get(), rel=1e-9), (
            result.analyzer
        )


def test_grouping_over_parquet_source_on_mesh(parquet_path, in_memory):
    """Uniqueness/Entropy/CountDistinct (the frequency family) streamed
    from Parquet under the mesh engine equal the in-memory run."""
    grouping = [
        Uniqueness(("g",)),
        Entropy("cat"),
        CountDistinct(("cat",)),
        Uniqueness(("cat", "g")),
    ]
    source = ParquetSource(parquet_path, batch_rows=1 << 16)
    mesh = data_mesh()
    ctx_stream = AnalysisRunner.do_analysis_run(
        source, grouping, engine="distributed", mesh=mesh
    )
    ctx_mem = AnalysisRunner.do_analysis_run(in_memory, grouping, engine="single")
    for analyzer in grouping:
        assert ctx_stream.metric_map[analyzer].value.get() == pytest.approx(
            ctx_mem.metric_map[analyzer].value.get(), rel=1e-9
        ), analyzer


def test_quantile_stream_mesh_within_rank_bound(parquet_path, in_memory):
    """ApproxQuantile streamed+sharded stays within the KLL rank-error
    bound of the true data (eps·n ranks, ops/sketches/kll.py)."""
    analyzer = ApproxQuantile("x", 0.5)
    source = ParquetSource(parquet_path, batch_rows=1 << 16)
    ctx = AnalysisRunner.do_analysis_run(
        source, [analyzer], engine="distributed", mesh=data_mesh()
    )
    got = ctx.metric_map[analyzer].value.get()

    col = in_memory.column("x")
    x_sorted = np.sort(np.asarray(col.values, dtype=np.float64)[col.valid])
    n = len(x_sorted)
    eps = analyzer.relative_error
    # 2*eps: one eps for the sketch, one for the shard merge tree
    lo = x_sorted[max(0, int(np.floor((0.5 - 2 * eps) * n)))]
    hi = x_sorted[min(n - 1, int(np.ceil((0.5 + 2 * eps) * n)))]
    assert lo <= got <= hi


def test_where_predicates_survive_column_pruning(parquet_path, in_memory):
    """A where clause's referenced columns join the pruned read set even
    when no analyzer consumes them directly; filtered metrics over the
    streamed source equal the in-memory run."""
    analyzers = [
        Size(where="g >= 250"),
        Mean("x", where="g >= 250"),
        Completeness("x", where="g < 100"),
    ]
    source = ParquetSource(parquet_path, batch_rows=1 << 16)
    ctx_stream = AnalysisRunner.do_analysis_run(source, analyzers, engine="single")
    ctx_mem = AnalysisRunner.do_analysis_run(in_memory, analyzers, engine="single")
    for analyzer in analyzers:
        assert ctx_stream.metric_map[analyzer].value.get() == pytest.approx(
            ctx_mem.metric_map[analyzer].value.get(), rel=1e-12
        ), analyzer


def test_stream_profile_equals_in_memory(parquet_path, in_memory):
    """Full ColumnProfiler over the streaming source == over the
    in-memory table (the parity spot-check backing the 100M-row bench
    run at smaller scale)."""
    from deequ_tpu.profiles.column_profiler import ColumnProfiler

    p_stream = ColumnProfiler.profile(ParquetSource(parquet_path, batch_rows=1 << 16))
    p_mem = ColumnProfiler.profile(in_memory)
    assert p_stream.num_records == p_mem.num_records == N_ROWS
    for name in ("x", "cat", "g"):
        s, m = p_stream.profiles[name], p_mem.profiles[name]
        assert s.completeness == pytest.approx(m.completeness, rel=1e-12)
        assert s.approximate_num_distinct_values == m.approximate_num_distinct_values
        assert s.data_type == m.data_type
        if getattr(m, "mean", None) is not None:
            assert s.mean == pytest.approx(m.mean, rel=1e-9)
            assert s.minimum == pytest.approx(m.minimum, rel=1e-9)
            assert s.maximum == pytest.approx(m.maximum, rel=1e-9)
        if m.histogram is not None:
            assert s.histogram is not None
            assert {
                (k, v.absolute) for k, v in s.histogram.values.items()
            } == {(k, v.absolute) for k, v in m.histogram.values.items()}

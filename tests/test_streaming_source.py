"""Out-of-core streaming input: every pass over a ParquetSource must
produce exactly the metrics of the same data held in memory
(reference scale claim: README.md:43 — "billions of rows" via streamed
partitions; here streamed Arrow batches)."""

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from deequ_tpu.analyzers import (
    ApproxCountDistinct,
    ApproxQuantile,
    Completeness,
    CountDistinct,
    DataType,
    Distinctness,
    Entropy,
    Histogram,
    Maximum,
    Mean,
    MutualInformation,
    PatternMatch,
    Size,
    StandardDeviation,
    Uniqueness,
)
from deequ_tpu.checks import Check, CheckLevel
from deequ_tpu.data.table import Table
from deequ_tpu.profiles.column_profiler import ColumnProfiler
from deequ_tpu.runners.analysis_runner import AnalysisRunner
from deequ_tpu.verification import VerificationSuite


@pytest.fixture(scope="module")
def parquet_path(tmp_path_factory):
    rng = np.random.default_rng(7)
    n = 30_000
    x = rng.normal(5.0, 2.0, n)
    x[rng.random(n) < 0.05] = np.nan
    cats = np.array(["red", "green", "blue", None], dtype=object)
    table = pa.table(
        {
            "x": x,
            "qty": rng.integers(0, 50, n),
            "cat": cats[rng.integers(0, 4, n)],
            "code": [str(v) for v in rng.integers(0, 500, n)],
        }
    )
    path = str(tmp_path_factory.mktemp("pq") / "data.parquet")
    pq.write_table(table, path, row_group_size=4096)
    return path


ANALYZERS = [
    Size(),
    Completeness("x"),
    Mean("x"),
    Maximum("x"),
    StandardDeviation("x"),
    ApproxCountDistinct("qty"),
    ApproxQuantile("x", 0.5),
    DataType("code"),
    PatternMatch("cat", r"^re"),
    Uniqueness(["cat"]),
    Distinctness(["cat"]),
    Entropy("cat"),
    CountDistinct(["cat", "qty"]),
    MutualInformation("cat", "qty"),
    Histogram("cat"),
]


class TestStreamingParity:
    def test_all_analyzers_match_in_memory(self, parquet_path):
        source = Table.scan_parquet(parquet_path, batch_rows=4096)
        memory = Table.from_parquet(parquet_path)
        ctx_s = AnalysisRunner.on_data(source).add_analyzers(ANALYZERS).run()
        ctx_m = AnalysisRunner.on_data(memory).add_analyzers(ANALYZERS).run()
        for analyzer in ANALYZERS:
            ms, mm = ctx_s.metric_map[analyzer], ctx_m.metric_map[analyzer]
            assert ms.value.is_success, (analyzer, ms.value)
            assert mm.value.is_success, (analyzer, mm.value)
            vs, vm = ms.value.get(), mm.value.get()
            if isinstance(vs, float):
                if repr(analyzer).startswith("ApproxQuantile"):
                    # KLL partials differ by batching; equal within error
                    assert vs == pytest.approx(vm, abs=0.1), analyzer
                else:
                    assert vs == pytest.approx(vm, rel=1e-9), analyzer
            else:
                assert vs == vm, analyzer

    def test_profiler_matches_in_memory(self, parquet_path):
        source = Table.scan_parquet(parquet_path, batch_rows=4096)
        memory = Table.from_parquet(parquet_path)
        ps = ColumnProfiler.profile(source)
        pm = ColumnProfiler.profile(memory)
        assert ps.num_records == pm.num_records
        for name in ("x", "qty", "cat", "code"):
            s, m = ps.profiles[name], pm.profiles[name]
            assert s.data_type == m.data_type, name
            assert s.completeness == pytest.approx(m.completeness, rel=1e-9)
            assert s.approximate_num_distinct_values == (
                m.approximate_num_distinct_values
            )
            if getattr(s, "mean", None) is not None:
                assert s.mean == pytest.approx(m.mean, rel=1e-9)
        # histogram for the low-cardinality string column, incl. nulls
        hs = ps.profiles["cat"].histogram
        hm = pm.profiles["cat"].histogram
        assert hs is not None and hm is not None
        assert {k: v.absolute for k, v in hs.values.items()} == {
            k: v.absolute for k, v in hm.values.items()
        }

    def test_verification_suite_on_source(self, parquet_path):
        source = Table.scan_parquet(parquet_path, batch_rows=8192)
        check = (
            Check(CheckLevel.ERROR, "stream checks")
            .has_size(lambda s: s == 30_000)
            .has_completeness("x", lambda v: 0.9 < v < 1.0)
            .has_entropy("cat", lambda v: v > 0.5)
        )
        result = VerificationSuite.on_data(source).add_check(check).run()
        assert result.status.name == "SUCCESS", [
            (cr.constraint, cr.message)
            for cr in result.check_results[check].constraint_results
        ]

    def test_source_schema_and_preconditions(self, parquet_path):
        from deequ_tpu.core.exceptions import NoSuchColumnException

        source = Table.scan_parquet(parquet_path)
        assert source.num_rows == 30_000
        assert set(source.column_names) == {"x", "qty", "cat", "code"}
        with pytest.raises(NoSuchColumnException):
            source.column("nope")
        ctx = AnalysisRunner.on_data(source).add_analyzers([Mean("cat")]).run()
        assert ctx.metric_map[Mean("cat")].value.is_failure  # not numeric

    def test_empty_parquet(self, tmp_path):
        path = str(tmp_path / "empty.parquet")
        pq.write_table(pa.table({"a": pa.array([], type=pa.float64())}), path)
        source = Table.scan_parquet(path)
        ctx = AnalysisRunner.on_data(source).add_analyzers([Size(), Mean("a")]).run()
        assert ctx.metric_map[Size()].value.get() == 0.0
        assert ctx.metric_map[Mean("a")].value.is_failure  # empty state

    def test_bounded_prefetch(self, parquet_path):
        """Decode stays at most (queue=2)+1 batches ahead of the
        consumer — the structural bound behind constant host memory."""
        import time

        from deequ_tpu.data.source import ParquetSource

        class Counting(ParquetSource):
            def __init__(self, *a, **kw):
                super().__init__(*a, **kw)
                self.decoded = 0

            def _iter_tables(self, batch_size):
                for t in super()._iter_tables(batch_size):
                    self.decoded += 1
                    yield t

        source = Counting(parquet_path, batch_rows=1024)  # ~30 batches
        gen = source.batches(1024)
        next(gen)
        time.sleep(0.3)  # give the producer every chance to run ahead
        assert source.decoded <= 4  # 1 consumed + queue(2) + 1 in-flight
        consumed = 1 + sum(1 for _ in gen)
        assert consumed == 30  # all batches arrive
        assert source.decoded == 30

    def test_column_projection(self, parquet_path):
        source = Table.scan_parquet(parquet_path, columns=["x", "cat"])
        assert set(source.column_names) == {"x", "cat"}
        ctx = AnalysisRunner.on_data(source).add_analyzers([Completeness("cat")]).run()
        assert ctx.metric_map[Completeness("cat")].value.is_success

    def test_mapped_source_undeclared_fn_is_not_pruned(self, parquet_path):
        """A MappedSource whose fn derives one column from another must
        not have its base pruned to the analyzer-consumed columns: the
        derivation input would go missing and silently skew the metric
        (advisor finding, round 3). Undeclared read set => no pruning;
        declared => base keeps names ∪ fn_columns."""
        from deequ_tpu.data.source import MappedSource
        from deequ_tpu.data.table import Column, ColumnType

        base = Table.scan_parquet(parquet_path)

        def scale_x_by_qty(batch):
            x = batch.column("x")
            qty = batch.column("qty")  # NOT analyzed below: prune bait
            return batch.with_column(
                Column(
                    "x",
                    ColumnType.DOUBLE,
                    np.asarray(x.values, dtype=np.float64)
                    * np.asarray(qty.values, dtype=np.float64),
                    x.valid & qty.valid,
                )
            )

        expected = (
            AnalysisRunner.on_data(
                MappedSource(Table.scan_parquet(parquet_path), scale_x_by_qty)
            )
            .add_analyzers([Mean("x")])
            .run()
            .metric_map[Mean("x")]
            .value.get()
        )

        # undeclared: with_columns must be a no-op (fn still sees qty)
        undeclared = MappedSource(base, scale_x_by_qty)
        pruned = undeclared.with_columns(["x"])
        got = (
            AnalysisRunner.on_data(pruned)
            .add_analyzers([Mean("x")])
            .run()
            .metric_map[Mean("x")]
            .value.get()
        )
        assert got == pytest.approx(expected, rel=1e-12)

        # declared: base is pruned to names ∪ fn_columns, fn still works
        declared = MappedSource(
            Table.scan_parquet(parquet_path),
            scale_x_by_qty,
            fn_columns=["x", "qty"],
        )
        got2 = (
            AnalysisRunner.on_data(declared.with_columns(["x"]))
            .add_analyzers([Mean("x")])
            .run()
            .metric_map[Mean("x")]
            .value.get()
        )
        assert got2 == pytest.approx(expected, rel=1e-12)
        assert "cat" not in declared.with_columns(["x"]).base.column_names

    def test_timestamp_and_decimal_parity(self, tmp_path):
        """Timestamp and decimal columns behave identically in-memory
        and streamed through the (round-4) zero-copy materialization:
        decimals compute numerics, Min/Max on timestamps raise the
        reference's WrongColumnTypeException (isNumeric precondition),
        completeness counts nulls exactly."""
        import decimal

        from deequ_tpu.analyzers import Minimum
        from deequ_tpu.core.exceptions import WrongColumnTypeException

        rng = np.random.default_rng(5)
        n = 20_000
        ts = pa.array(
            [
                None
                if i % 17 == 0
                else v
                for i, v in enumerate(
                    (
                        rng.integers(1_500_000_000, 1_700_000_000, n)
                        * 1_000_000
                    ).astype("datetime64[us]")
                )
            ]
        )
        dec = pa.array(
            [
                None
                if i % 13 == 0
                else decimal.Decimal(
                    f"{rng.integers(0, 10000)}.{rng.integers(0, 100):02d}"
                )
                for i in range(n)
            ],
            type=pa.decimal128(12, 2),
        )
        path = str(tmp_path / "tsdec.parquet")
        pq.write_table(pa.table({"ts": ts, "dec": dec}), path, row_group_size=4096)

        analyzers = [
            Completeness("ts"),
            Completeness("dec"),
            Mean("dec"),
            Minimum("dec"),
            Maximum("dec"),
            Minimum("ts"),
        ]  # Mean/Maximum come from the module-level import
        results = {}
        for label, tab in (
            ("mem", Table.from_parquet(path)),
            ("stream", Table.scan_parquet(path)),
        ):
            ctx = AnalysisRunner.on_data(tab).add_analyzers(analyzers).run()
            results[label] = ctx.metric_map
        for analyzer in analyzers:
            m, s = results["mem"][analyzer], results["stream"][analyzer]
            assert m.value.is_success == s.value.is_success, repr(analyzer)
            if m.value.is_success:
                assert m.value.get() == pytest.approx(s.value.get(), rel=1e-12)
        assert results["mem"][Completeness("ts")].value.get() == pytest.approx(
            sum(1 for i in range(n) if i % 17 != 0) / n
        )
        failure = results["mem"][Minimum("ts")].value
        assert not failure.is_success
        assert isinstance(failure.exception, WrongColumnTypeException)

    def test_tiny_row_groups_coalesce(self, tmp_path):
        """Files written with tiny row groups (incremental writers)
        coalesce into batch-sized chunks — per-batch fold machinery must
        not multiply 100x — while ~batch-sized groups pass through
        without the dictionary-unifying concat (reviewer finding +
        measured tradeoff, round 4)."""
        import collections

        rng = np.random.default_rng(1)
        n = 200_000
        table = pa.table(
            {
                "x": rng.normal(0, 1, n),
                "c": np.array(["p", "q", "r"], dtype=object)[
                    rng.integers(0, 3, n)
                ],
            }
        )
        path = str(tmp_path / "tiny_groups.parquet")
        pq.write_table(table, path, row_group_size=2000)  # 100 tiny groups

        source = Table.scan_parquet(path, batch_rows=1 << 20)
        batches = list(source.batches(1 << 20))
        assert len(batches) <= 2  # coalesced, not 100
        assert sum(b.num_rows for b in batches) == n

        ctx = (
            AnalysisRunner.on_data(Table.scan_parquet(path))
            .add_analyzers([Size(), Mean("x"), Histogram("c")])
            .run()
        )
        assert ctx.metric_map[Size()].value.get() == n
        hist = {
            k: v.absolute
            for k, v in ctx.metric_map[Histogram("c")].value.get().values.items()
        }
        assert hist == dict(collections.Counter(table.column("c").to_pylist()))

    def test_source_stall_knob_is_inert_on_results(self, tmp_path, monkeypatch):
        """DEEQU_TPU_SOURCE_STALL_MS (the object-store latency model used
        by bench.py's pipeline A/B) delays the decoding thread but must
        never change what the stream yields — same batches, same metrics
        — and malformed values fall back to off."""
        from deequ_tpu.ops import runtime

        monkeypatch.setenv("DEEQU_TPU_SOURCE_STALL_MS", "garbage")
        assert runtime.source_stall_s() == 0.0
        monkeypatch.setenv("DEEQU_TPU_SOURCE_STALL_MS", "-5")
        assert runtime.source_stall_s() == 0.0
        monkeypatch.setenv("DEEQU_TPU_SOURCE_STALL_MS", "2.5")
        assert runtime.source_stall_s() == 0.0025

        rng = np.random.default_rng(3)
        n = 30_000
        table = pa.table(
            {
                "x": rng.normal(0, 1, n),
                "c": np.array(["p", "q"], dtype=object)[rng.integers(0, 2, n)],
            }
        )
        path = str(tmp_path / "stalled.parquet")
        pq.write_table(table, path, row_group_size=10_000)

        def metrics():
            ctx = (
                AnalysisRunner.on_data(Table.scan_parquet(path))
                .add_analyzers([Size(), Mean("x")])
                .run()
            )
            return (
                ctx.metric_map[Size()].value.get(),
                ctx.metric_map[Mean("x")].value.get(),
            )

        stalled = metrics()  # 3 row groups x 2.5ms, exercises the sleep
        monkeypatch.delenv("DEEQU_TPU_SOURCE_STALL_MS")
        assert metrics() == stalled

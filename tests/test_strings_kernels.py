"""Vectorized string kernels (ops/strings.py) vs their specs.

The classifier's spec is the reference's regex triple
(catalyst/StatefulDataType.scala:36-38) — asserted here by running the
actual regexes (ASCII-digit form, like Java's default `\\d`) over an
adversarial corpus plus random fuzz, and requiring the vectorized
classifier to agree on every value.
"""

import re

import numpy as np
import pytest

from deequ_tpu.ops import strings

_FRACTIONAL = re.compile(r"(-|\+)? ?[0-9]*\.[0-9]*")
_INTEGRAL = re.compile(r"(-|\+)? ?[0-9]*")
_BOOLEAN = re.compile(r"(true|false)")


def _strip_java_final_terminator(value: str) -> str:
    """Java's `$` matches before ONE final line terminator; emulate by
    stripping it and fullmatching the rest."""
    for term in ("\r\n", "\n", "\r", "", " ", " "):
        if value.endswith(term):
            return value[: -len(term)]
    return value


def reference_classify(value: str) -> int:
    body = _strip_java_final_terminator(value)
    if _FRACTIONAL.fullmatch(body):
        return strings.CODE_FRACTIONAL
    if _INTEGRAL.fullmatch(body):
        return strings.CODE_INTEGRAL
    if _BOOLEAN.fullmatch(body):
        return strings.CODE_BOOLEAN
    return strings.CODE_STRING


ADVERSARIAL = [
    "", " ", "  ", ".", "+", "-", "+ ", "- ", "+ 5", "- 5", "+5", "-5",
    "5", "55", "5.5", ".5", "5.", "+.5", "-.", " .", " 5", "  5", "5 ",
    "++5", "+-5", "5+", "5.5.5", "..", "5..5", "1e5", "inf", "nan",
    "true", "false", "True", "FALSE", "truee", "xtrue", " true",
    "123456789012345678901234567890", "-123.456", "+ 123.", "- .",
    "abc", "12a", "a12", "1 2", "1.2 ", "\t5", "5\n", "5\r\n", "5\r",
    "5 ", "true\n", "5\n6", "\n", "5\n\n", "０１２",  # unicode digits
    "١٢٣",  # arabic-indic digits (Python \d matches; Java/ours must not)
    "trué", "12½", "𝟓", "ｔｒｕｅ",
]


class TestClassify:
    def test_adversarial_corpus(self):
        arr = np.array(ADVERSARIAL, dtype=object).astype(str)
        got = strings.classify(arr)
        for value, code in zip(ADVERSARIAL, got):
            assert code == reference_classify(value), repr(value)

    def test_random_fuzz(self):
        rng = np.random.default_rng(1234)
        alphabet = list("0123456789+-. truefalsexyz\n\r")
        values = [
            "".join(rng.choice(alphabet, size=rng.integers(0, 12)))
            for _ in range(3000)
        ]
        got = strings.classify(np.array(values, dtype=str))
        for value, code in zip(values, got):
            assert code == reference_classify(value), repr(value)

    def test_empty_input(self):
        assert len(strings.classify(np.array([], dtype=str))) == 0


class TestLengthBuckets:
    def test_long_outlier_does_not_widen_short_values(self):
        # one 10k-char blob among short values: classification and hash
        # must still be correct (and not allocate an n x 10k matrix)
        blob = "9" * 10_000
        values = np.array(["1", "2.5", "true", "zz", blob], dtype=object)
        got = strings.classify(values)
        assert got.tolist() == [
            strings.CODE_INTEGRAL,
            strings.CODE_FRACTIONAL,
            strings.CODE_BOOLEAN,
            strings.CODE_STRING,
            strings.CODE_INTEGRAL,  # 10k digits is still ^\d*$
        ]
        hashes = strings.hash_strings(values)
        assert len(np.unique(hashes)) == 5

    def test_hash_independent_of_batch_composition(self):
        # the hash of a value must not depend on what else was hashed
        # with it (bucketed width is a function of the value alone)
        alone = strings.hash_strings(np.array(["abc"], dtype=object))[0]
        with_long = strings.hash_strings(
            np.array(["abc", "x" * 100], dtype=object)
        )[0]
        assert alone == with_long

    def test_classify_each_bucket_boundary(self):
        for n in (7, 8, 9, 16, 17, 64, 65, 128, 129, 400):
            digits = "1" * n
            text = "a" * n
            got = strings.classify(np.array([digits, text], dtype=object))
            assert got[0] == strings.CODE_INTEGRAL, n
            assert got[1] == strings.CODE_STRING, n


class TestHashStrings:
    def test_distinct_strings_distinct_hashes(self):
        values = np.array(
            [f"value-{i}" for i in range(100_000)] + ["a", "ab", "abc", ""],
            dtype=str,
        )
        hashes = strings.hash_strings(values)
        assert len(np.unique(hashes)) == len(values)  # no collisions here

    def test_deterministic(self):
        v = np.array(["x", "yy", "zzz"], dtype=str)
        assert np.array_equal(strings.hash_strings(v), strings.hash_strings(v))

    def test_uniformity_top_bits(self):
        # HLL uses the top 9 bits as the register index: all 512 buckets
        # should be hit roughly uniformly
        values = np.array([f"k{i}" for i in range(51_200)], dtype=str)
        idx = (strings.hash_strings(values) >> np.uint64(55)).astype(int)
        counts = np.bincount(idx, minlength=512)
        assert counts.min() > 40 and counts.max() < 180  # ~100 expected


class TestParseFloats:
    def test_accepted_forms(self):
        vals, ok = strings.parse_floats(
            np.array(["1", "-2.5", "1e3", "+4", " 5 ", "inf", "abc", ""], dtype=object)
        )
        assert ok.tolist() == [True, True, True, True, True, True, False, False]
        assert vals[0] == 1.0 and vals[1] == -2.5 and vals[2] == 1000.0

    def test_nan_not_ok(self):
        _, ok = strings.parse_floats(np.array(["nan"], dtype=object))
        assert not ok[0]


class TestMatchPattern:
    def test_spark_empty_match_is_miss(self):
        hit = strings.match_pattern(np.array(["", "a", "aa"], dtype=str), "a*")
        # "a*" matches everything, but with an EMPTY match on "" -> miss
        assert hit.tolist() == [False, True, True]


class TestAnalyzerIntegrationAfterVectorization:
    """End-to-end: the analyzers that now route through ops/strings."""

    def test_datatype_distribution_unchanged(self):
        from deequ_tpu.analyzers import DataType
        from deequ_tpu.data.table import Table
        from deequ_tpu.ops.fused import FusedScanPass

        t = Table.from_pydict({"s": ["1", "2.5", "true", "abc", None, "+ 7"]})
        result = FusedScanPass([DataType("s")]).run(t)[0]
        dist = result.analyzer.compute_metric_from(result.state_or_raise()).value.get()
        assert dist["Integral"].absolute == 2  # "1", "+ 7"
        assert dist["Fractional"].absolute == 1
        assert dist["Boolean"].absolute == 1
        assert dist["String"].absolute == 1
        assert dist["Unknown"].absolute == 1

    def test_pattern_match_via_uniques(self):
        from deequ_tpu.analyzers.scan import PatternMatch, Patterns
        from deequ_tpu.data.table import Table
        from deequ_tpu.ops.fused import FusedScanPass

        t = Table.from_pydict(
            {"email": ["a@x.com", "bad", "b@y.org", None, "a@x.com"]}
        )
        result = FusedScanPass([PatternMatch("email", Patterns.EMAIL)]).run(t)[0]
        m = result.analyzer.compute_metric_from(result.state_or_raise())
        # reference denominator is conditionalCount(where): ALL 5 rows,
        # NULL included (reference: analyzers/PatternMatch.scala:48-54)
        assert m.value.get() == pytest.approx(3 / 5)

    def test_hll_string_estimate_within_rsd(self):
        from deequ_tpu.analyzers import ApproxCountDistinct
        from deequ_tpu.data.table import Table
        from deequ_tpu.ops.fused import FusedScanPass

        n = 20_000
        values = [f"user-{i % 5000}" for i in range(n)]
        t = Table.from_pydict({"u": values})
        result = FusedScanPass([ApproxCountDistinct("u")]).run(t)[0]
        est = result.analyzer.compute_metric_from(result.state_or_raise()).value.get()
        assert est == pytest.approx(5000, rel=0.15)  # rsd=0.05, 3 sigma

    def test_string_numeric_values_parse(self):
        from deequ_tpu.data.table import Table

        t = Table.from_pydict({"s": ["1", "2.5", "x", None, "1e2"]})
        vals, valid = t.column("s").numeric_values()
        assert valid.tolist() == [True, True, False, False, True]
        assert vals[1] == 2.5 and vals[4] == 100.0

    def test_expr_and_analyzers_agree_on_string_numerics(self):
        """A Compliance predicate and Mean must see the same rows as
        numeric (both route through ops/strings.parse_floats)."""
        from deequ_tpu.analyzers import Compliance, Mean
        from deequ_tpu.data.table import Table
        from deequ_tpu.ops.fused import FusedScanPass

        t = Table.from_pydict({"s": ["10", "1_0", "٥", "30", "x"]})
        results = FusedScanPass(
            [Compliance("c", "s >= 0"), ]
        ).run(t)
        compliance = results[0].analyzer.compute_metric_from(
            results[0].state_or_raise()
        ).value.get()
        vals, valid = t.column("s").numeric_values()
        # identical verdicts: "1_0" and the unicode digit parse (or not)
        # the same way in both paths
        assert compliance == valid.sum() / 5
        assert valid.tolist() == [True, False, False, True, False]

    def test_hll_string_registers_batch_invariant(self):
        """Same values split across batches must produce the same HLL
        registers as one batch (hash must not depend on batch width)."""
        from deequ_tpu.analyzers import ApproxCountDistinct
        from deequ_tpu.data.table import Table
        from deequ_tpu.ops.fused import FusedScanPass

        values = [f"v{i % 300}" + ("x" * (i % 23)) for i in range(4000)]
        t = Table.from_pydict({"s": values})
        one = FusedScanPass([ApproxCountDistinct("s")]).run(t)[0]
        many = FusedScanPass([ApproxCountDistinct("s")], batch_size=512).run(t)[0]
        assert np.array_equal(
            one.state_or_raise().registers, many.state_or_raise().registers
        )


class TestDecimalHalfUp:
    def test_exact_half_rounds_up_like_bigdecimal(self):
        from deequ_tpu.data.table import Table
        from deequ_tpu.schema.row_level_schema_validator import (
            RowLevelSchema,
            RowLevelSchemaValidator,
        )

        t = Table.from_pydict({"d": ["9.995", "2.675", "1.005", "-9.995"]})
        schema = RowLevelSchema().with_decimal_column(
            "d", is_nullable=False, precision=3, scale=2
        )
        res = RowLevelSchemaValidator.validate(t, schema)
        # BigDecimal("9.995") HALF_UP at scale 2 -> 10.00: 3 int digits
        # overflow precision 3 -> rejected (float rounding would accept)
        assert res.num_valid_rows == 2  # 2.675 -> 2.68, 1.005 -> 1.01
        assert res.num_invalid_rows == 2  # ±9.995 -> ±10.00 overflow
        kept = res.valid_rows.column("d").values
        assert sorted(np.round(kept, 2).tolist()) == [1.01, 2.68]

"""Full-suite differential fuzz (round-4 verdict item 8): random tables
× random Check DSL programs through THREE execution paths — the
single-device engine, the 8-device mesh engine, and the pure host fold
— asserting end-to-end agreement of the VerificationSuite outputs:
overall status, per-check status, per-constraint status, and the
underlying metric values (exact for counts/statuses, 1e-9 for scalar
floats, rank-error-loose for sketches).

This is the VerificationSuite-level generalization of
tests/test_differential_random.py (which fuzzes analyzers directly).
Assertion thresholds for SKETCH-backed constraints are drawn far from
plausible metric values so legitimate sketch randomization across merge
trees can never flip a constraint status (the reference makes no
cross-engine bit-equality promise for approximate metrics either).

Reference end-to-end behavior being preserved:
checks/CheckTest.scala (status semantics per DSL method),
VerificationSuite.scala:263-281 (overall status = max over checks).
"""

from __future__ import annotations

import numpy as np
import pytest

from deequ_tpu.checks import Check, CheckLevel
from deequ_tpu.constraints import ConstrainableDataTypes
from deequ_tpu.data.table import ColumnType, Table
from deequ_tpu.parallel.distributed import data_mesh
from deequ_tpu.verification import VerificationSuite

N_SEEDS = 44


def random_table(rng: np.random.Generator) -> Table:
    n = int(rng.integers(1, 2500))
    null_density = float(rng.choice([0.0, 0.02, 0.4, 0.9]))
    x = rng.normal(rng.uniform(-50, 50), rng.uniform(0.0, 20.0), n)
    x[rng.random(n) < null_density] = np.nan
    y = x * rng.uniform(0.5, 2.0) + rng.normal(0, 1.0, n)
    cardinality = int(rng.choice([1, 2, 23, 900]))
    pool = np.array(
        ["", "x", "-3", "7.5", "true", "a b", "it's", "user@example.com"][
            : max(1, min(8, cardinality))
        ]
        + [f"v{i}" for i in range(max(0, cardinality - 8))],
        dtype=object,
    )
    s = pool[rng.integers(0, len(pool), n)]
    s[rng.random(n) < null_density] = None
    g = rng.integers(0, max(1, cardinality), n)
    # low-cardinality float (discount/tax-style): exercises the
    # hash-count family fast path across every engine, with explicit
    # -0.0 keys (a distinct bit pattern the f64_key order must place
    # before +0.0)
    r = rng.integers(-2, 11, n) / 100.0
    r[rng.random(n) < 0.1] = -0.0
    r[rng.random(n) < null_density] = np.nan
    return Table.from_pydict(
        {
            "x": list(x),
            "y": list(y),
            "s": list(s),
            "g": [int(v) for v in g],
            "r": list(r),
        },
        types={
            "x": ColumnType.DOUBLE,
            "y": ColumnType.DOUBLE,
            "s": ColumnType.STRING,
            "g": ColumnType.LONG,
            "r": ColumnType.DOUBLE,
        },
    )


def wide_table(rng: np.random.Generator) -> Table:
    """50-column layout (the BENCH_STREAM_1B_WIDE shape, shrunk): 20
    doubles, 15 longs at mixed cardinalities, 10 dictionary-encoded
    strings, 5 low-cardinality floats — so the counts fast paths,
    dictionary memos, int narrowing and the stream pipeline's packing
    all interact across many columns at once."""
    n = int(rng.integers(500, 2500))
    null_density = float(rng.choice([0.0, 0.05, 0.3]))
    cols: dict = {}
    types: dict = {}
    for i in range(20):
        v = rng.normal(rng.uniform(-50, 50), rng.uniform(0.1, 10.0), n)
        v[rng.random(n) < null_density] = np.nan
        cols[f"d{i:02d}"] = list(v)
        types[f"d{i:02d}"] = ColumnType.DOUBLE
    for i in range(15):
        card = int(rng.choice([2, 100, 10_000]))
        cols[f"l{i:02d}"] = [int(v) for v in rng.integers(0, card, n)]
        types[f"l{i:02d}"] = ColumnType.LONG
    for i in range(10):
        card = int(rng.choice([1, 3, 50]))
        pool = np.array(
            [f"s{i}_{j}" for j in range(card)] + ["v1"], dtype=object
        )
        sv = pool[rng.integers(0, len(pool), n)]
        sv[rng.random(n) < null_density] = None
        cols[f"s{i:02d}"] = list(sv)
        types[f"s{i:02d}"] = ColumnType.STRING
    for i in range(5):
        v = rng.integers(-2, 11, n) / 100.0
        v[rng.random(n) < null_density] = np.nan
        cols[f"r{i}"] = list(v)
        types[f"r{i}"] = ColumnType.DOUBLE
    return Table.from_pydict(cols, types=types)


def lineitem_table(rng: np.random.Generator) -> Table:
    """TPC-H lineitem-like layout: quantities, prices, the canonical
    low-cardinality .00-.10 discount/tax floats (the hash-count family
    fast path), tiny-alphabet flag strings, a high-cardinality comment
    column (dictionary memos under pressure), and skewed join keys."""
    n = int(rng.integers(500, 3000))
    qty = rng.integers(1, 51, n).astype(np.float64)
    price = np.round(qty * rng.uniform(900.0, 1100.0, n), 2)
    null_density = float(rng.choice([0.0, 0.02]))
    price[rng.random(n) < null_density] = np.nan
    flags = np.array(["A", "N", "R"], dtype=object)
    status = np.array(["O", "F"], dtype=object)
    modes = np.array(
        ["AIR", "RAIL", "SHIP", "TRUCK", "MAIL", "FOB", "REG AIR"],
        dtype=object,
    )
    comments = np.array(
        [f"comment {i} about v1" for i in range(max(16, n // 3))],
        dtype=object,
    )
    return Table.from_pydict(
        {
            "l_orderkey": [int(v) for v in rng.integers(0, max(1, n // 4), n)],
            "l_suppkey": [int(v) for v in rng.integers(0, 100, n)],
            "l_quantity": list(qty),
            "l_extendedprice": list(price),
            "l_discount": list(rng.integers(0, 11, n) / 100.0),
            "l_tax": list(rng.integers(0, 9, n) / 100.0),
            "l_returnflag": list(flags[rng.integers(0, 3, n)]),
            "l_linestatus": list(status[rng.integers(0, 2, n)]),
            "l_shipmode": list(modes[rng.integers(0, 7, n)]),
            "l_comment": list(comments[rng.integers(0, len(comments), n)]),
        },
        types={
            "l_orderkey": ColumnType.LONG,
            "l_suppkey": ColumnType.LONG,
            "l_quantity": ColumnType.DOUBLE,
            "l_extendedprice": ColumnType.DOUBLE,
            "l_discount": ColumnType.DOUBLE,
            "l_tax": ColumnType.DOUBLE,
            "l_returnflag": ColumnType.STRING,
            "l_linestatus": ColumnType.STRING,
            "l_shipmode": ColumnType.STRING,
            "l_comment": ColumnType.STRING,
        },
    )


LAYOUTS = {
    "narrow": random_table,
    "wide": wide_table,
    "lineitem": lineitem_table,
}


def layout_roles(layout: str, rng: np.random.Generator) -> tuple:
    """Map a layout's columns onto `random_check`'s five roles
    (num1, num2, string, int, lowcard_float)."""
    if layout == "narrow":
        return ("x", "y", "s", "g", "r")
    if layout == "wide":
        return (
            f"d{int(rng.integers(0, 20)):02d}",
            f"d{int(rng.integers(0, 20)):02d}",
            f"s{int(rng.integers(0, 10)):02d}",
            f"l{int(rng.integers(0, 15)):02d}",
            f"r{int(rng.integers(0, 5))}",
        )
    return (
        "l_extendedprice",
        str(rng.choice(["l_quantity", "l_tax"])),
        str(rng.choice(["l_returnflag", "l_shipmode", "l_comment"])),
        str(rng.choice(["l_suppkey", "l_orderkey"])),
        "l_discount",
    )


def random_check(
    rng: np.random.Generator,
    cols: tuple = ("x", "y", "s", "g", "r"),
) -> Check:
    """3-9 random DSL constraints over role-mapped columns
    `(num1, num2, string, int, lowcard_float)` — ("x","y","s","g","r")
    in the canonical narrow layout; the wide/lineitem layouts map their
    own columns onto the same roles. Exact-metric constraints use
    thresholds drawn continuously (probability ~0 of landing within
    engine FP jitter of the metric); sketch-backed constraints use
    far-out bounds so rank-error randomization cannot flip them."""
    x, y, s, g, r = cols
    size_t = float(rng.uniform(0, 3000))
    frac_t = float(rng.uniform(0, 1))
    stat_t = float(rng.uniform(-120, 120))
    far = float(rng.choice([-1e15, 1e15]))

    builders = [
        lambda c: c.has_size(lambda v, t=size_t: v >= t),
        lambda c: c.has_size(lambda v, t=size_t: v >= t).where(f"{g} > 1"),
        lambda c: c.is_complete(x),
        lambda c: c.is_complete(s),
        lambda c: c.has_completeness(x, lambda v, t=frac_t: v >= t),
        lambda c: c.has_completeness(
            s, lambda v, t=frac_t: v >= t
        ).where(f"{g} >= 0"),
        lambda c: c.is_unique(g),
        lambda c: c.has_uniqueness((g,), lambda v, t=frac_t: v >= t),
        lambda c: c.has_distinctness((s,), lambda v, t=frac_t: v >= t),
        lambda c: c.has_unique_value_ratio(
            (g,), lambda v, t=frac_t: v >= t
        ),
        lambda c: c.has_number_of_distinct_values(
            g, lambda v, t=size_t: v <= max(t, 1)
        ),
        lambda c: c.has_entropy(g, lambda v, t=frac_t: v >= t),
        lambda c: c.has_mutual_information(
            s, g, lambda v, t=frac_t: v >= t * 0.1
        ),
        lambda c: c.has_min(x, lambda v, t=stat_t: v <= t),
        lambda c: c.has_max(x, lambda v, t=stat_t: v >= t),
        lambda c: c.has_mean(x, lambda v, t=stat_t: v >= t),
        # low-card float column: the hash-count family path
        lambda c: c.has_mean(r, lambda v, t=frac_t: v >= t * 0.1),
        lambda c: c.has_min(r, lambda v: v >= -0.02),
        lambda c: c.has_standard_deviation(
            r, lambda v, t=frac_t: v <= max(t, 0.2)
        ),
        lambda c: c.has_approx_quantile(
            r, 0.5, lambda v, t=far: (v >= t) if t < 0 else (v <= t)
        ),
        lambda c: c.has_approx_count_distinct(
            r, lambda v, t=far: (v >= t) if t < 0 else (v <= t)
        ),
        lambda c: c.has_sum(x, lambda v, t=stat_t: v >= t),
        lambda c: c.has_standard_deviation(x, lambda v, t=frac_t: v >= t),
        lambda c: c.has_correlation(
            x, y, lambda v, t=frac_t: abs(v) >= t * 0.5
        ),
        # sketch-backed: far-out bounds, immune to rank-error jitter
        lambda c: c.has_approx_quantile(
            x, 0.5, lambda v, t=far: (v >= t) if t < 0 else (v <= t)
        ),
        lambda c: c.has_approx_count_distinct(
            g, lambda v, t=far: (v >= t) if t < 0 else (v <= t)
        ),
        lambda c: c.satisfies(f"{x} > 0", "pos", lambda v, t=frac_t: v >= t),
        lambda c: c.has_pattern(
            s, r"^v\d+$", lambda v, t=frac_t: v >= t
        ),
        lambda c: c.contains_email(s, lambda v, t=frac_t: v <= max(t, 0.5)),
        lambda c: c.has_data_type(
            s,
            ConstrainableDataTypes.INTEGRAL,
            lambda v, t=frac_t: v <= max(t, 0.5),
        ),
        lambda c: c.is_non_negative(x),
        lambda c: c.is_positive(x).where(f"{g} >= 1"),
        lambda c: c.is_less_than(x, y),
        lambda c: c.is_greater_than_or_equal_to(y, x),
        lambda c: c.is_contained_in(s, ["x", "-3", "7.5", "v1"]),
        lambda c: c.is_contained_in(
            g, lower_bound=0.0, upper_bound=1000.0
        ),
    ]
    level = CheckLevel.ERROR if rng.random() < 0.5 else CheckLevel.WARNING
    check = Check(level, f"fuzz-{rng.integers(1 << 30)}")
    k = int(rng.integers(3, 10))
    for i in rng.choice(len(builders), size=k, replace=False):
        check = builders[int(i)](check)
    return check


def suite_snapshot(result):
    """Engine-comparable projection of a VerificationResult: overall
    status, per-check status, per-constraint status, and the metric
    values keyed by analyzer repr."""
    checks = []
    for check, cres in result.check_results.items():
        checks.append(
            (
                check.description,
                cres.status.name,
                tuple(
                    (str(cr.constraint), cr.status.name)
                    for cr in cres.constraint_results
                ),
            )
        )
    metrics = {}
    for analyzer, metric in result.metrics.items():
        v = metric.value
        if v.is_failure:
            metrics[repr(analyzer)] = ("FAIL", type(v.exception).__name__)
        else:
            value = v.get()
            if hasattr(value, "values"):  # Distribution
                value = tuple(
                    sorted(
                        (k, dv.absolute) for k, dv in value.values.items()
                    )
                )
            elif isinstance(value, dict):
                value = tuple(sorted(value.items()))
            metrics[repr(analyzer)] = ("OK", value)
    return result.status.name, tuple(checks), metrics


def assert_snapshots_agree(a, b, context: str) -> None:
    status_a, checks_a, metrics_a = a
    status_b, checks_b, metrics_b = b
    assert status_a == status_b, (context, status_a, status_b)
    assert checks_a == checks_b, (context, checks_a, checks_b)
    assert metrics_a.keys() == metrics_b.keys(), context
    for key in metrics_a:
        sa, va = metrics_a[key]
        sb, vb = metrics_b[key]
        assert sa == sb, (context, key, metrics_a[key], metrics_b[key])
        if sa == "FAIL":
            assert va == vb, (context, key)
        elif key.startswith(("ApproxQuantile", "ApproxCountDistinct")):
            # sketch merge trees differ across engines: rank-error loose
            if isinstance(va, tuple):
                assert len(va) == len(vb), (context, key)
                for (ka, xa), (kb, xb) in zip(va, vb):
                    assert ka == kb, (context, key)
                    assert xb == pytest.approx(xa, rel=0.25, abs=2.0), (
                        context, key,
                    )
            else:
                assert vb == pytest.approx(va, rel=0.25, abs=2.0), (
                    context, key, va, vb,
                )
        elif isinstance(va, float):
            assert vb == pytest.approx(va, rel=1e-9, abs=1e-12), (
                context, key, va, vb,
            )
        else:
            assert va == vb, (context, key)


@pytest.mark.parametrize("seed", range(N_SEEDS))
def test_suite_agrees_across_engines(seed, monkeypatch):
    rng = np.random.default_rng(7000 + seed)
    table = random_table(rng)
    checks = [random_check(rng) for _ in range(int(rng.integers(1, 3)))]

    def run(engine, mesh=None, placement=None):
        if placement is None:
            monkeypatch.delenv("DEEQU_TPU_PLACEMENT", raising=False)
        else:
            monkeypatch.setenv("DEEQU_TPU_PLACEMENT", placement)
        builder = VerificationSuite().on_data(table)
        for check in checks:
            builder = builder.add_check(check)
        return suite_snapshot(builder.with_engine(engine, mesh).run())

    host_fold = run("single", placement="host")
    single_dev = run("single", placement="device")
    mesh = run("distributed", mesh=data_mesh())

    assert_snapshots_agree(host_fold, single_dev, "host-vs-device")
    assert_snapshots_agree(host_fold, mesh, "host-vs-mesh")


@pytest.mark.parametrize("seed", range(0, N_SEEDS, 4))
def test_suite_agrees_streamed_vs_in_memory(seed, monkeypatch, tmp_path):
    """The STREAMED engine dimension: the same random table written to
    Parquet with tiny row groups (many batches — the counts fast paths,
    dictionary memos and per-batch folds all cross batch boundaries)
    must produce the same VerificationSuite outcome as the in-memory
    host fold."""
    from deequ_tpu.data.table import Table as TableCls

    rng = np.random.default_rng(9000 + seed)
    table = random_table(rng)
    checks = [random_check(rng) for _ in range(int(rng.integers(1, 3)))]

    path = str(tmp_path / "fuzz.parquet")
    table.to_parquet(
        path,
        row_group_size=max(64, len(table.column("x")) // 7),
        dictionary_encode_strings=True,
    )

    def run(data):
        monkeypatch.setenv("DEEQU_TPU_PLACEMENT", "host")
        builder = VerificationSuite().on_data(data)
        for check in checks:
            builder = builder.add_check(check)
        return suite_snapshot(builder.with_engine("single").run())

    in_memory = run(table)
    streamed = run(
        TableCls.scan_parquet(path, batch_rows=max(64, len(table.column("x")) // 5))
    )
    assert_snapshots_agree(in_memory, streamed, "memory-vs-stream")


# -- layout fuzz + the pipeline on/off differential (ISSUE 5) ----------------


def _count_spans(roots, name: str) -> int:
    total = 0
    stack = list(roots)
    while stack:
        sp = stack.pop()
        if sp.name == name:
            total += 1
        stack.extend(sp.children)
    return total


@pytest.mark.parametrize(
    "layout,seed",
    [(layout, seed) for layout in ("narrow", "wide", "lineitem") for seed in range(4)],
)
def test_pipeline_on_off_bit_identical(layout, seed, monkeypatch, tmp_path):
    """The DEEQU_TPU_PIPELINE=0 serial fallback must be BIT-identical to
    the pipelined streaming path — exact snapshot equality, sketches
    included (same engine, same fold order, same inputs: nothing may
    diverge). Runs every layout so wide packing, dictionary memos and
    the lineitem fast paths all cross the stage boundary. Also pins
    tracing-inertness: running under a tracer must not change one bit
    of the result, and the trace must show the pipeline actually
    engaged (pipe_stage spans for every stage)."""
    from deequ_tpu import observe
    from deequ_tpu.data.table import Table as TableCls

    rng = np.random.default_rng(11_000 + seed)
    table = LAYOUTS[layout](rng)
    n = table.num_rows
    roles = layout_roles(layout, rng)
    checks = [random_check(rng, roles) for _ in range(int(rng.integers(1, 3)))]
    # alternate placements so both the H2D prep path (device) and the
    # family-kernel host path cross the pipeline's stage boundary
    placement = "device" if seed % 2 else "host"

    path = str(tmp_path / "fuzz.parquet")
    table.to_parquet(
        path, row_group_size=max(64, n // 7), dictionary_encode_strings=True
    )

    def run(pipeline_env):
        monkeypatch.setenv("DEEQU_TPU_PLACEMENT", placement)
        monkeypatch.setenv("DEEQU_TPU_PIPELINE", pipeline_env)
        data = TableCls.scan_parquet(path, batch_rows=max(64, n // 5))
        builder = VerificationSuite().on_data(data)
        for check in checks:
            builder = builder.add_check(check)
        return suite_snapshot(builder.with_engine("single").run())

    serial = run("0")
    pipelined = run("1")
    assert serial == pipelined, (layout, seed, placement)

    with observe.tracing() as tracer:
        traced = run("1")
    assert traced == pipelined, ("tracing changed results", layout, seed)
    stages = {
        sp.attrs.get("stage")
        for root in tracer.roots
        for sp in _iter_spans(root)
        if sp.name == "pipe_stage"
    }
    assert {"decode", "prep", "fold"} <= stages, (
        "pipeline did not engage under tracing",
        stages,
    )


def _iter_spans(root):
    stack = [root]
    while stack:
        sp = stack.pop()
        yield sp
        stack.extend(sp.children)


# -- row-group pushdown on/off differential (ISSUE 7) ------------------------


def pushdown_table(rng: np.random.Generator) -> Table:
    """Sorted-key layout: parquet row-group min/max over `k` are disjoint
    ranges, so comparison wheres are genuinely selective. `v` carries
    NaN (runtime nulls invisible to parquet stats) and real nulls; `s`
    is a string column (never stats-decidable)."""
    n = int(rng.integers(1200, 4000))
    k = np.sort(rng.integers(0, 10_000, n))
    v = rng.normal(0.0, 50.0, n)
    v[rng.random(n) < 0.05] = np.nan
    v_list = [None if rng.random() < 0.02 else float(x) for x in v]
    s = np.array(["a", "b", "v1", "zz"], dtype=object)[rng.integers(0, 4, n)]
    s[rng.random(n) < 0.1] = None
    return Table.from_pydict(
        {"k": [int(x) for x in k], "v": v_list, "s": list(s)},
        types={
            "k": ColumnType.LONG,
            "v": ColumnType.DOUBLE,
            "s": ColumnType.STRING,
        },
    )


def random_pushdown_where(rng: np.random.Generator) -> str:
    """Mixed eligibility: selective sorted-key comparisons, NaN-hampered
    float ranges, stats-opaque string predicates, and/or combinations."""
    cut = int(rng.integers(-100, 10_100))
    roll = rng.random()
    if roll < 0.5:
        op = str(rng.choice(["<", "<=", ">", ">=", "=", "!="]))
        return f"k {op} {cut}"
    if roll < 0.7:
        return f"k < {cut} and v > {float(rng.uniform(-100, 100)):.1f}"
    if roll < 0.85:
        lo = int(rng.integers(0, 2500))
        hi = int(rng.integers(7500, 10_000))
        return f"k < {lo} or k > {hi}"
    return str(rng.choice(["s != 'zz'", "v is not null", f"k >= {cut}"]))


def pushdown_check(rng: np.random.Generator, wheres) -> Check:
    """Every constraint filters (an unfiltered fused member disables all
    skipping), drawn from scan-shareable, exactly-folded builders —
    sketch metrics are excluded because pruning changes decode batch
    boundaries and sketch compaction is partition-sensitive."""
    frac_t = float(rng.uniform(0, 1))
    stat_t = float(rng.uniform(-120, 120))
    builders = [
        lambda c: c.has_size(lambda v, t=stat_t: v >= t),
        lambda c: c.has_completeness("v", lambda v, t=frac_t: v >= t),
        lambda c: c.has_completeness("s", lambda v, t=frac_t: v >= t),
        lambda c: c.has_mean("v", lambda v, t=stat_t: v >= t),
        lambda c: c.has_min("v", lambda v, t=stat_t: v <= t),
        lambda c: c.has_max("k", lambda v, t=stat_t: v >= t),
        lambda c: c.has_sum("v", lambda v, t=stat_t: v >= t),
        lambda c: c.has_standard_deviation("v", lambda v, t=frac_t: v >= t),
        lambda c: c.satisfies("v > 0", "pos", lambda v, t=frac_t: v >= t),
    ]
    check = Check(CheckLevel.ERROR, f"pushdown-{rng.integers(1 << 30)}")
    k = int(rng.integers(3, 8))
    for i in rng.choice(len(builders), size=k, replace=False):
        check = builders[int(i)](check).where(str(rng.choice(wheres)))
    return check


@pytest.mark.parametrize("seed", range(8))
def test_pushdown_on_off_bit_identical(seed, monkeypatch, tmp_path):
    """DEEQU_TPU_PUSHDOWN=0 must be BIT-identical to the pruning path —
    exact snapshot equality (same engine, same surviving rows, masked
    folds are exact): statically skipping a row group may never change
    one bit of any metric. Even seeds share one aggressively selective
    where across all constraints and assert groups actually skipped;
    odd seeds draw independent mixed-eligibility wheres (string
    predicates, NaN floats, or-clauses) where skipping is incidental."""
    from deequ_tpu import observe
    from deequ_tpu.data.table import Table as TableCls

    rng = np.random.default_rng(13_000 + seed)
    table = pushdown_table(rng)
    n = table.num_rows
    if seed % 2 == 0:
        wheres = [f"k < {int(rng.integers(500, 2500))}"]
    else:
        wheres = [random_pushdown_where(rng) for _ in range(3)]
    checks = [
        pushdown_check(rng, wheres) for _ in range(int(rng.integers(1, 3)))
    ]

    path = str(tmp_path / "pushdown.parquet")
    table.to_parquet(
        path, row_group_size=max(64, n // 7), dictionary_encode_strings=True
    )

    def run(pushdown_env):
        monkeypatch.setenv("DEEQU_TPU_PLACEMENT", "device" if seed % 4 >= 2 else "host")
        monkeypatch.setenv("DEEQU_TPU_PUSHDOWN", pushdown_env)
        data = TableCls.scan_parquet(path, batch_rows=max(64, n // 5))
        builder = VerificationSuite().on_data(data)
        for check in checks:
            builder = builder.add_check(check)
        return suite_snapshot(builder.with_engine("single").run())

    off = run("0")
    on = run("1")
    assert off == on, (seed, wheres)

    with observe.tracing() as tracer:
        traced = run("1")
    assert traced == on, ("tracing changed results", seed)
    prunes = [
        sp
        for root in tracer.roots
        for sp in _iter_spans(root)
        if sp.name == "prune"
    ]
    assert prunes, "pushdown never produced a prune decision"
    if seed % 2 == 0:
        assert sum(sp.attrs["groups_skipped"] for sp in prunes) > 0, (
            "selective shared where skipped nothing",
            wheres,
        )


# -- decode fast path + workers on/off differential (ISSUE 8) ----------------


@pytest.mark.parametrize(
    "layout,seed",
    [(layout, seed) for layout in ("narrow", "wide", "lineitem") for seed in range(3)],
)
def test_decode_fastpath_workers_bit_identical(layout, seed, monkeypatch, tmp_path):
    """DEEQU_TPU_DECODE_FASTPATH=0 (the host from_arrow chain) and
    DEEQU_TPU_DECODE_WORKERS at 1 vs 3 must all be BIT-identical —
    exact snapshot equality, sketches included: the fast path and the
    worker pool change WHERE and HOW columns decode, never one bit of
    any value, mask, or dictionary code. Runs every layout so numeric
    primitives, bool bitmaps, dictionary codes, NaN folds and the
    tiny-group coalescer all cross both decode routes. Also pins that
    under a tracer the decode planner actually engaged (decode_fastpath
    span with fast columns) and the worker pool actually fanned out
    (decode_unit spans)."""
    from deequ_tpu import observe
    from deequ_tpu.data.table import Table as TableCls

    rng = np.random.default_rng(14_000 + seed)
    table = LAYOUTS[layout](rng)
    n = table.num_rows
    roles = layout_roles(layout, rng)
    checks = [random_check(rng, roles) for _ in range(int(rng.integers(1, 3)))]
    placement = "device" if seed % 2 else "host"

    path = str(tmp_path / "decode.parquet")
    table.to_parquet(
        path, row_group_size=max(64, n // 7), dictionary_encode_strings=True
    )

    def run(fastpath_env, workers_env):
        monkeypatch.setenv("DEEQU_TPU_PLACEMENT", placement)
        monkeypatch.setenv("DEEQU_TPU_DECODE_FASTPATH", fastpath_env)
        monkeypatch.setenv("DEEQU_TPU_DECODE_WORKERS", workers_env)
        data = TableCls.scan_parquet(path, batch_rows=max(64, n // 5))
        builder = VerificationSuite().on_data(data)
        for check in checks:
            builder = builder.add_check(check)
        return suite_snapshot(builder.with_engine("single").run())

    baseline = run("0", "1")
    for fp, workers in (("1", "1"), ("0", "3"), ("1", "3")):
        assert run(fp, workers) == baseline, (layout, seed, fp, workers)

    with observe.tracing() as tracer:
        traced = run("1", "3")
    assert traced == baseline, ("tracing changed results", layout, seed)
    spans = [
        sp for root in tracer.roots for sp in _iter_spans(root)
    ]
    plans = [sp for sp in spans if sp.name == "decode_fastpath"]
    assert plans, "decode planner never produced a plan"
    assert all(sp.attrs["workers"] == 3 for sp in plans)
    assert sum(sp.attrs["cols_fast"] for sp in plans) > 0, (
        "no column took the fast path",
        layout,
    )
    # the pool's per-unit span is decode_unit on the pyarrow parallel
    # path and page_decode on the native-reader path (ISSUE 11), which
    # takes over the scan whenever any column has a native page recipe
    assert any(sp.name in ("decode_unit", "page_decode") for sp in spans), (
        "parallel decode workers never engaged"
    )


# -- decode-to-wire fusion on/off differential (ISSUE 9) ---------------------


@pytest.mark.parametrize(
    "layout,seed",
    [(layout, seed) for layout in ("narrow", "wide", "lineitem") for seed in range(2)],
)
def test_wire_fusion_bit_identical(layout, seed, monkeypatch, tmp_path):
    """DEEQU_TPU_WIRE_FUSED=0 (Column intermediate + numpy pack) vs =1
    (decode straight into packed wire slices) must be BIT-identical —
    exact snapshot equality, sketches included — across worker counts 1
    vs 3 and BOTH placements: the wire kernels change where masks pack
    and values narrow/shift, never one bit of any metric. Every layout
    runs so bitpacked NaN folds, narrowed ints, f32 shift handshakes and
    valid-only bool masks all cross both routes. Under a tracer the wire
    verdict must actually have run (wire_cols_total counter recorded,
    cols_wire_fused attr on the decode plan span)."""
    from deequ_tpu import observe
    from deequ_tpu.data.table import Table as TableCls

    rng = np.random.default_rng(15_000 + seed)
    table = LAYOUTS[layout](rng)
    n = table.num_rows
    roles = layout_roles(layout, rng)
    checks = [random_check(rng, roles) for _ in range(int(rng.integers(1, 3)))]

    path = str(tmp_path / "wire.parquet")
    table.to_parquet(
        path, row_group_size=max(64, n // 7), dictionary_encode_strings=True
    )

    def run(wire_env, workers_env, placement):
        monkeypatch.setenv("DEEQU_TPU_PLACEMENT", placement)
        monkeypatch.setenv("DEEQU_TPU_WIRE_FUSED", wire_env)
        monkeypatch.setenv("DEEQU_TPU_DECODE_WORKERS", workers_env)
        data = TableCls.scan_parquet(path, batch_rows=max(64, n // 5))
        builder = VerificationSuite().on_data(data)
        for check in checks:
            builder = builder.add_check(check)
        return suite_snapshot(builder.with_engine("single").run())

    for placement in ("host", "device"):
        baseline = run("0", "1", placement)
        for wire, workers in (("1", "1"), ("0", "3"), ("1", "3")):
            assert run(wire, workers, placement) == baseline, (
                layout, seed, placement, wire, workers,
            )

    device_baseline = run("0", "1", "device")
    with observe.tracing() as tracer:
        traced = run("1", "3", "device")
    assert traced == device_baseline, ("tracing changed results", layout, seed)
    plans = [
        sp
        for root in tracer.roots
        for sp in _iter_spans(root)
        if sp.name == "decode_fastpath"
    ]
    assert plans, "decode planner never produced a plan"
    assert all("cols_wire_fused" in sp.attrs for sp in plans), (
        "wire verdict missing from the decode plan span"
    )
    assert tracer.counters.get("wire_cols_total", 0) > 0, (
        "wire planning never recorded its verdict"
    )


# -- native parquet reader on/off differential (ISSUE 11) --------------------


@pytest.mark.parametrize(
    "layout,seed",
    [(layout, seed) for layout in ("narrow", "wide", "lineitem") for seed in range(2)],
)
def test_native_reader_bit_identical(layout, seed, monkeypatch, tmp_path):
    """DEEQU_TPU_NATIVE_READER=0 (pyarrow produces every buffer) vs =1
    (planner-approved chunks pread and page-decoded by parquet_read.c)
    must be BIT-identical — exact snapshot equality, sketches included —
    across worker counts 1 vs 3, BOTH placements, and BOTH parquet
    format versions (V1 and V2 data pages): the reader changes who
    produces the bytes, never one bit of any value, mask or dictionary
    code. NaN/NULL-heavy layouts run so validity bitmaps and NaN folds
    cross both producers. Under a tracer the reader must actually
    engage (page_read/page_decode spans, reader_chunks_native > 0) and
    the traced per-unit chunk counts must sum to exactly the planner's
    static prediction — the runtime twin of drift.reader_chunks_native
    staying pinned at 0."""
    import pyarrow.parquet as pq

    from deequ_tpu import observe
    from deequ_tpu.data.table import Table as TableCls
    from deequ_tpu.ops import native

    if not native.available():
        pytest.skip("native library unavailable")

    rng = np.random.default_rng(16_000 + seed)
    table = LAYOUTS[layout](rng)
    n = table.num_rows
    roles = layout_roles(layout, rng)
    checks = [random_check(rng, roles) for _ in range(int(rng.integers(1, 3)))]
    version = "1.0" if seed % 2 == 0 else "2.6"

    path = str(tmp_path / "reader.parquet")
    table.to_parquet(
        path, row_group_size=max(64, n // 7), dictionary_encode_strings=True
    )
    # rewrite at the target format version: V1 data pages compress the
    # definition levels with the values, V2 pages carry them raw — the
    # native page parser must take both to the same bits
    pq.write_table(
        pq.read_table(path),
        path,
        version=version,
        row_group_size=max(64, n // 7),
        data_page_size=4096,
    )

    def run(reader_env, workers_env, placement):
        monkeypatch.setenv("DEEQU_TPU_PLACEMENT", placement)
        monkeypatch.setenv("DEEQU_TPU_NATIVE_READER", reader_env)
        monkeypatch.setenv("DEEQU_TPU_DECODE_WORKERS", workers_env)
        data = TableCls.scan_parquet(path, batch_rows=max(64, n // 5))
        builder = VerificationSuite().on_data(data)
        for check in checks:
            builder = builder.add_check(check)
        return suite_snapshot(builder.with_engine("single").run())

    for placement in ("host", "device"):
        baseline = run("0", "1", placement)
        for reader, workers in (("1", "1"), ("0", "3"), ("1", "3")):
            assert run(reader, workers, placement) == baseline, (
                layout, seed, placement, reader, workers,
            )

    host_baseline = run("0", "1", "host")
    with observe.tracing() as tracer:
        traced = run("1", "3", "host")
    assert traced == host_baseline, ("tracing changed results", layout, seed)
    spans = [sp for root in tracer.roots for sp in _iter_spans(root)]
    reads = [sp for sp in spans if sp.name == "page_read"]
    decodes = [sp for sp in spans if sp.name == "page_decode"]
    assert reads, "read-ahead fetch thread never produced a page_read span"
    assert decodes, "native reader never produced a page_decode span"
    runtime_native = sum(sp.attrs.get("chunks_native", 0) for sp in decodes)
    assert runtime_native > 0, ("no chunk decoded natively", layout, seed)
    planned_native = tracer.counters.get("reader_chunks_native", 0)
    assert tracer.counters.get("reader_chunks_total", 0) > 0, (
        "reader verdict never recorded"
    )
    assert runtime_native == planned_native, (
        "runtime chunk split drifted from the static plan",
        layout, seed, runtime_native, planned_native,
    )


# -- encoded fold on/off differential (ISSUE 20) -----------------------------


@pytest.mark.parametrize(
    "layout,seed",
    [(layout, seed) for layout in ("narrow", "wide", "lineitem") for seed in range(2)],
)
def test_encoded_fold_bit_identical(layout, seed, monkeypatch, tmp_path):
    """DEEQU_TPU_ENCODED_FOLD=0 (every planner-approved chunk expands
    to row width before folding) vs =1 (eligible columns fold moments
    over (run_len, value) streams and roll dictionary codes up into the
    sketch families) must be BIT-identical — exact snapshot equality,
    sketches included — across worker counts 1 vs 3, BOTH placements,
    BOTH parquet format versions (V1/V2 data pages) and all three
    reader codecs (uncompressed/snappy/zstd): the encoded fold changes
    the arithmetic ORDER, never one bit of any published metric (the
    planner only approves columns whose memo publication it can prove
    exact). A pinned anchor check keeps the low-cardinality float role
    a sketch consumer so at least one column is provably eligible in
    every draw; under a tracer the fold must actually engage
    (encfold_cols > 0, run/fallback chunk counters flowing) and the
    per-span runs_native counts must sum to the traced encfold_runs
    counter — the runtime twin of drift.encfold_columns staying 0."""
    import pyarrow.parquet as pq

    from deequ_tpu import observe
    from deequ_tpu.data.table import Table as TableCls
    from deequ_tpu.ops import native

    if not native.available():
        pytest.skip("native library unavailable")

    rng = np.random.default_rng(20_000 + seed)
    table = LAYOUTS[layout](rng)
    n = table.num_rows
    roles = layout_roles(layout, rng)
    checks = [random_check(rng, roles) for _ in range(int(rng.integers(1, 3)))]
    # the low-cardinality float role with a far-out sketch constraint
    # and no where filter: a memo-servable consumer the classifier must
    # approve, whatever the random checks drew
    lowcard = roles[4]
    checks.append(
        Check(CheckLevel.WARNING, "encfold-anchor")
        .has_approx_count_distinct(lowcard, lambda v: v >= -1e15)
        .has_mean(lowcard, lambda v: v >= -1e15)
    )
    version = "1.0" if seed % 2 == 0 else "2.6"
    codec = ("none", "snappy", "zstd")[
        ({"narrow": 0, "wide": 1, "lineitem": 2}[layout] + seed) % 3
    ]

    path = str(tmp_path / "encfold.parquet")
    table.to_parquet(
        path, row_group_size=max(64, n // 7), dictionary_encode_strings=True
    )
    pq.write_table(
        pq.read_table(path),
        path,
        version=version,
        compression=codec,
        row_group_size=max(64, n // 7),
        data_page_size=4096,
    )

    def run(encfold_env, workers_env, placement):
        monkeypatch.setenv("DEEQU_TPU_PLACEMENT", placement)
        monkeypatch.setenv("DEEQU_TPU_NATIVE_READER", "1")
        monkeypatch.setenv("DEEQU_TPU_ENCODED_FOLD", encfold_env)
        monkeypatch.setenv("DEEQU_TPU_DECODE_WORKERS", workers_env)
        data = TableCls.scan_parquet(path, batch_rows=max(64, n // 5))
        builder = VerificationSuite().on_data(data)
        for check in checks:
            builder = builder.add_check(check)
        return suite_snapshot(builder.with_engine("single").run())

    for placement in ("host", "device"):
        baseline = run("0", "1", placement)
        for encfold, workers in (("1", "1"), ("0", "3"), ("1", "3")):
            assert run(encfold, workers, placement) == baseline, (
                layout, seed, placement, encfold, workers,
            )

    host_baseline = run("0", "1", "host")
    with observe.tracing() as tracer:
        traced = run("1", "3", "host")
    assert traced == host_baseline, ("tracing changed results", layout, seed)
    assert tracer.counters.get("encfold_cols_total", 0) > 0, (
        "encoded-fold verdict never recorded"
    )
    assert tracer.counters.get("encfold_cols", 0) > 0, (
        "the anchored sketch consumer was never approved", layout, seed,
    )
    folded = tracer.counters.get("encfold_chunks", 0)
    fallback = tracer.counters.get("encfold_chunks_fallback", 0)
    assert folded + fallback > 0, (
        "no chunk of an approved column reached the run decoder",
        layout, seed,
    )
    spans = [sp for root in tracer.roots for sp in _iter_spans(root)]
    decodes = [sp for sp in spans if sp.name == "page_decode"]
    assert decodes, "native reader never produced a page_decode span"
    span_runs = sum(sp.attrs.get("runs_native", 0) for sp in decodes)
    assert span_runs == tracer.counters.get("encfold_runs", 0), (
        "per-span run counts drifted from the traced total",
        layout, seed,
    )


@pytest.mark.parametrize(
    "layout,seed",
    [("wide", 0), ("wide", 1), ("lineitem", 0), ("lineitem", 1)],
)
def test_suite_layouts_agree_across_engines(layout, seed, monkeypatch):
    """Wide/lineitem layouts through the three in-memory engines — the
    layout generalization of `test_suite_agrees_across_engines`."""
    rng = np.random.default_rng(12_000 + seed)
    table = LAYOUTS[layout](rng)
    roles = layout_roles(layout, rng)
    checks = [random_check(rng, roles) for _ in range(int(rng.integers(1, 3)))]

    def run(engine, mesh=None, placement=None):
        if placement is None:
            monkeypatch.delenv("DEEQU_TPU_PLACEMENT", raising=False)
        else:
            monkeypatch.setenv("DEEQU_TPU_PLACEMENT", placement)
        builder = VerificationSuite().on_data(table)
        for check in checks:
            builder = builder.add_check(check)
        return suite_snapshot(builder.with_engine(engine, mesh).run())

    host_fold = run("single", placement="host")
    single_dev = run("single", placement="device")
    mesh = run("distributed", mesh=data_mesh())

    assert_snapshots_agree(host_fold, single_dev, f"{layout}:host-vs-device")
    assert_snapshots_agree(host_fold, mesh, f"{layout}:host-vs-mesh")


# ---------------------------------------------------------------------------
# persistent partition-state cache: incremental scans (repository/states.py)
# ---------------------------------------------------------------------------


def _write_partition(table, path: str) -> None:
    table.to_parquet(
        path,
        row_group_size=max(64, table.num_rows // 5),
        dictionary_encode_strings=True,
    )


@pytest.mark.parametrize("seed", range(3))
def test_state_cache_on_off_bit_identical(seed, monkeypatch, tmp_path):
    """The persistent partition-state cache is a pure scan-for-load
    swap: with a repository attached, every run must be BIT-identical
    to a cache-off full rescan — exact snapshot equality, sketches
    included — through the whole dataset lifecycle (cold fill, all-hit
    warm run, appended partition, mutated partition, renamed files that
    reorder the partition merge) and on BOTH placements. Placement is
    part of the plan signature, so each placement fills and hits its
    own namespace."""
    from deequ_tpu.data.table import Table as TableCls
    from deequ_tpu.repository.states import FileSystemStateRepository

    rng = np.random.default_rng(17_000 + seed)
    checks = [random_check(rng) for _ in range(int(rng.integers(1, 3)))]
    data_dir = tmp_path / "dataset"
    data_dir.mkdir()
    for i in range(3):
        _write_partition(random_table(rng), str(data_dir / f"part-{i}.parquet"))

    repo = FileSystemStateRepository(str(tmp_path / "cache"))

    def run(placement, cached):
        monkeypatch.setenv("DEEQU_TPU_PLACEMENT", placement)
        monkeypatch.setenv("DEEQU_TPU_STATE_CACHE", "1" if cached else "0")
        data = TableCls.scan_parquet_dataset(str(data_dir))
        builder = VerificationSuite().on_data(data)
        for check in checks:
            builder = builder.add_check(check)
        if cached:
            builder = builder.with_state_repository(repo, "fuzz")
        return suite_snapshot(builder.with_engine("single").run())

    def check_step(step):
        for placement in ("host", "device"):
            baseline = run(placement, False)
            assert run(placement, True) == baseline, (step, seed, placement)

    check_step("cold")  # first cache-on run fills the repository
    check_step("warm")  # second is all hits: merge of loaded states only

    _write_partition(random_table(rng), str(data_dir / "part-3.parquet"))
    check_step("append")  # only the new partition lacks an entry

    _write_partition(random_table(rng), str(data_dir / "part-1.parquet"))
    check_step("mutate")  # rewritten fingerprint self-invalidates

    (data_dir / "part-0.parquet").rename(data_dir / "part-9.parquet")
    check_step("reorder")  # new basename = new fingerprint AND new merge order


def test_state_cache_drift_pins_zero_and_traces(monkeypatch, tmp_path):
    """Warm incremental run end to end: the planner's cached/scanned
    prediction must pin observed drift to exactly zero, the trace must
    carry the state_cache spans and partition counters, and the engine
    telemetry record must expose `engine.state_cache_hit_ratio == 1`."""
    from deequ_tpu.data.table import Table as TableCls
    from deequ_tpu.lint.cost import cost_drift
    from deequ_tpu.observe.telemetry import engine_metric_record
    from deequ_tpu.repository.states import FileSystemStateRepository

    rng = np.random.default_rng(23)
    data_dir = tmp_path / "dataset"
    data_dir.mkdir()
    for i in range(4):
        _write_partition(random_table(rng), str(data_dir / f"p{i}.parquet"))
    check = (
        Check(CheckLevel.ERROR, "incremental")
        .has_size(lambda s: s > 0)
        .is_complete("x")
        .has_mean("x", lambda m: True)
        .has_standard_deviation("x", lambda s: True)
        .has_approx_quantile("x", 0.5, lambda q: True)
    )
    repo = FileSystemStateRepository(str(tmp_path / "cache"))
    monkeypatch.delenv("DEEQU_TPU_STATE_CACHE", raising=False)
    monkeypatch.setenv("DEEQU_TPU_PLACEMENT", "device")

    def run():
        return (
            VerificationSuite()
            .on_data(TableCls.scan_parquet_dataset(str(data_dir)))
            .add_check(check)
            .with_state_repository(repo, "drift")
            .with_engine("single")
            .with_tracing(True)
            .run()
        )

    cold = run()
    assert cold.run_trace.counters["partitions_scanned"] == 4
    assert cold.run_trace.counters["partitions_total"] == 4

    warm = run()
    counters = warm.run_trace.counters
    assert counters["partitions_cached"] == 4
    assert counters["partitions_total"] == 4
    assert "partitions_scanned" not in counters

    # predicted == observed, both directions, exactly zero
    drift = cost_drift(warm.plan_cost, warm.run_trace)
    assert drift["drift.partitions_cached"] == 0.0
    assert drift["drift.partitions_scanned"] == 0.0

    cache_spans = [sp for sp in warm.run_trace.spans() if sp.name == "state_cache"]
    assert len(cache_spans) == 4
    assert all(sp.attrs.get("hit") for sp in cache_spans)

    rec = engine_metric_record(warm.run_trace, warm.plan_cost)
    assert rec["engine.state_cache_hit_ratio"] == 1.0
    assert rec["engine.drift.partitions_cached"] == 0.0


# -- forensics on/off differential (ISSUE 12) --------------------------------


@pytest.mark.parametrize("seed", range(3))
def test_forensics_on_off_bit_identical(seed, monkeypatch, tmp_path):
    """with_forensics() must be provably inert: exact snapshot equality
    (metrics, check statuses, sketches included) with row-level capture
    on vs off — on both placements, with the streaming pipeline on and
    off, and through a state-cache cold fill and all-hit warm run
    (cached partitions reduce forensics to provenance, never change
    results). Capture reads the decoded batch through its own masks and
    never touches the fold inputs, so nothing may diverge by one bit."""
    from deequ_tpu.data.table import Table as TableCls
    from deequ_tpu.repository.states import FileSystemStateRepository

    rng = np.random.default_rng(19_000 + seed)
    checks = [random_check(rng) for _ in range(int(rng.integers(1, 3)))]
    data_dir = tmp_path / "dataset"
    data_dir.mkdir()
    for i in range(3):
        _write_partition(random_table(rng), str(data_dir / f"part-{i}.parquet"))
    repo = FileSystemStateRepository(str(tmp_path / "cache"))

    def run(placement, pipeline, forensics, cached=False):
        monkeypatch.setenv("DEEQU_TPU_PLACEMENT", placement)
        monkeypatch.setenv("DEEQU_TPU_PIPELINE", pipeline)
        monkeypatch.setenv("DEEQU_TPU_STATE_CACHE", "1" if cached else "0")
        data = TableCls.scan_parquet_dataset(str(data_dir))
        builder = VerificationSuite().on_data(data)
        for check in checks:
            builder = builder.add_check(check)
        if cached:
            builder = builder.with_state_repository(repo, "forensics-fuzz")
        if forensics:
            builder = builder.with_forensics()
        result = builder.with_engine("single").run()
        # the report rides the result exactly when capture was on
        assert (result.forensics() is not None) == forensics
        return suite_snapshot(result)

    for placement in ("host", "device"):
        for pipeline in ("0", "1"):
            off = run(placement, pipeline, False)
            on = run(placement, pipeline, True)
            assert off == on, (seed, placement, pipeline)

    baseline = run("host", "1", False)
    # cold: capture rides the scans that fill the cache
    assert run("host", "1", True, cached=True) == baseline, (seed, "cold")
    # warm: every partition merges from cache; capture sees no batches
    assert run("host", "1", True, cached=True) == baseline, (seed, "warm-on")
    assert run("host", "1", False, cached=True) == baseline, (seed, "warm-off")


# -- chaos differential: injected faults change nothing (ISSUE 13) -----------


#: the fault matrix `make chaos` also sweeps: transient IO errors,
#: short reads, corrupt pages, decode failures, worker deaths, stage
#: faults and stalls — every containment path must stay bit-identical
CHAOS_MATRIX = [
    "seed=101,read.pread:0.4:4",
    "seed=102,read.short:0.5:3",
    "seed=103,read.corrupt:0.5:2",
    "seed=104,decode.chunk:0.6:3",
    "seed=105,decode.worker:1.0:1",
    "seed=106,pipeline.stage:1.0:1",
    "seed=107,stall=0.005,pipeline.stall:1.0:2",
    "seed=108,stall=0.005,read.latency:1.0:3",
]


@pytest.mark.parametrize("spec", CHAOS_MATRIX)
def test_chaos_faults_bit_identical_both_placements(spec, monkeypatch, tmp_path):
    """The chaos differential: a seeded fault plan injecting IO errors,
    short reads, corrupt pages, worker deaths or stalls into the scan
    must produce EXACTLY the clean run's snapshot on both placements —
    every containment path (retry, inline redo, pyarrow fallback)
    degrades to the same bits, never a wrong answer."""
    from deequ_tpu.data.table import Table as TableCls
    from deequ_tpu.testing import faults

    rng = np.random.default_rng(23_000)
    table = random_table(rng)
    checks = [random_check(rng) for _ in range(2)]
    path = str(tmp_path / "chaos.parquet")
    table.to_parquet(
        path, row_group_size=max(64, table.num_rows // 7),
        dictionary_encode_strings=True,
    )

    def run(placement):
        monkeypatch.setenv("DEEQU_TPU_PLACEMENT", placement)
        monkeypatch.setenv("DEEQU_TPU_PIPELINE", "1")
        # the worker-pool decode path needs >1 worker on a 1-core box
        monkeypatch.setenv("DEEQU_TPU_DECODE_WORKERS", "2")
        data = TableCls.scan_parquet(
            path, batch_rows=max(64, table.num_rows // 5)
        )
        builder = VerificationSuite().on_data(data)
        for check in checks:
            builder = builder.add_check(check)
        return suite_snapshot(builder.with_engine("single").run())

    for placement in ("host", "device"):
        clean = run(placement)
        with faults.install(spec) as plan:
            faulted = run(placement)
        assert sum(plan.injected.values()) >= 1, (
            f"spec {spec!r} never fired on {placement} — the matrix "
            f"entry exercises nothing"
        )
        assert clean == faulted, (spec, placement, plan.injected)


def test_sigkill_resume_scans_only_remaining_partitions(tmp_path):
    """Crash-safe partial progress end to end: SIGKILL a scan subprocess
    after its first partition-state commit; the in-process rerun loads
    the committed partitions from the FileSystemStateRepository, scans
    ONLY the remainder, and lands bit-equal to a clean full run."""
    import glob
    import signal
    import struct
    import subprocess
    import sys
    import time

    from deequ_tpu.analyzers import Completeness, Mean, Size, StandardDeviation
    from deequ_tpu.data.table import Table as TableCls
    from deequ_tpu.repository.states import FileSystemStateRepository
    from deequ_tpu.runners.analysis_runner import AnalysisRunner

    rng = np.random.default_rng(31_000)
    data_dir = tmp_path / "dataset"
    data_dir.mkdir()
    n_parts = 3
    for i in range(n_parts):
        _write_partition(random_table(rng), str(data_dir / f"part-{i}.parquet"))
    cache_dir = str(tmp_path / "cache")

    child_src = (
        "from deequ_tpu.analyzers import Completeness, Mean, Size, StandardDeviation\n"
        "from deequ_tpu.data.table import Table\n"
        "from deequ_tpu.repository.states import FileSystemStateRepository\n"
        "from deequ_tpu.runners.analysis_runner import AnalysisRunner\n"
        f"repo = FileSystemStateRepository({cache_dir!r})\n"
        f"AnalysisRunner.do_analysis_run(\n"
        f"    Table.scan_parquet_dataset({str(data_dir)!r}),\n"
        "    [Size(), Mean('x'), StandardDeviation('x'), Completeness('x')],\n"
        "    state_repository=repo, dataset_name='sigkill',\n"
        ")\n"
    )
    import os as _os

    env = dict(_os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("DEEQU_TPU_STATE_CACHE", None)
    # slow every row-group read so the kill lands mid-run, not post-run
    env["DEEQU_TPU_SOURCE_STALL_MS"] = "400"
    repo_root = _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo_root + _os.pathsep + env.get("PYTHONPATH", "")

    child = subprocess.Popen(
        [sys.executable, "-c", child_src],
        env=env, cwd=repo_root,
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )
    try:
        deadline = time.monotonic() + 120.0
        committed = []
        while time.monotonic() < deadline:
            committed = glob.glob(cache_dir + "/**/*.dqstate", recursive=True)
            if committed:
                break
            if child.poll() is not None:
                pytest.fail("scan subprocess exited before any commit")
            time.sleep(0.02)
        assert committed, "no partition state committed within the window"
    finally:
        if child.poll() is None:
            child.send_signal(signal.SIGKILL)
        child.wait(timeout=30)

    cached_n = len(
        glob.glob(cache_dir + "/**/*.dqstate", recursive=True)
    )
    assert 1 <= cached_n < n_parts, (
        f"kill landed outside the run: {cached_n}/{n_parts} committed"
    )

    analyzers = [Size(), Mean("x"), StandardDeviation("x"), Completeness("x")]
    clean = AnalysisRunner.do_analysis_run(
        TableCls.scan_parquet_dataset(str(data_dir)), analyzers
    )
    resumed = AnalysisRunner.do_analysis_run(
        TableCls.scan_parquet_dataset(str(data_dir)), analyzers,
        state_repository=FileSystemStateRepository(cache_dir),
        dataset_name="sigkill", tracing=True,
    )
    counters = resumed.run_trace.counters
    assert counters["partitions_cached"] == cached_n
    assert counters["partitions_scanned"] == n_parts - cached_n
    for a in analyzers:
        assert struct.pack(">d", clean.metric_map[a].value.get()) == struct.pack(
            ">d", resumed.metric_map[a].value.get()
        ), repr(a)


# ---------------------------------------------------------------------------
# fleet-wide scan sharing: shared vs solo (ISSUE 17)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("seed", range(3))
def test_scan_sharing_shared_vs_solo_bit_identical(seed, monkeypatch, tmp_path):
    """N randomly-overlapping suites submitted to the DQService over
    one table must land BIT-identical to each suite's solo run — exact
    snapshot equality, sketches included — whether the scheduler put
    them on a shared superset scan or not, on BOTH placements. Every
    shared participant must carry a CONTAINED subsumption proof pinned
    with zero drift."""
    import time as _time

    from deequ_tpu.data.table import Table as TableCls
    from deequ_tpu.service import DQService

    rng = np.random.default_rng(41_000 + seed)
    data_dir = tmp_path / "dataset"
    data_dir.mkdir()
    for i in range(3):
        _write_partition(random_table(rng), str(data_dir / f"part-{i}.parquet"))

    def factory():
        return TableCls.scan_parquet_dataset(str(data_dir))

    # overlapping suites: constraints drawn from one pool, so tenants
    # randomly share analyzers (the union-dedup path) and randomly
    # bring their own (the superset path)
    n_tenants = int(rng.integers(2, 5))
    checks = {
        f"tenant{i}": random_check(rng) for i in range(n_tenants)
    }

    for placement in ("host", "device"):
        monkeypatch.setenv("DEEQU_TPU_PLACEMENT", placement)
        solo = {}
        for tenant, check in checks.items():
            builder = VerificationSuite().on_data(factory()).add_check(check)
            solo[tenant] = suite_snapshot(builder.with_engine("single").run())

        blocker_table = TableCls.from_pydict({"k": ["a"]})
        blocker_check = Check(CheckLevel.ERROR, "blocker").has_size(
            lambda v: (_time.sleep(0.8) or v >= 0)
        )
        with DQService(workers=1) as svc:
            blocker = svc.submit(
                "blocker", "other", lambda: blocker_table,
                checks=[blocker_check],
            )
            _time.sleep(0.25)
            handles = {
                tenant: svc.submit(tenant, "ds", factory, checks=[check])
                for tenant, check in checks.items()
            }
            assert blocker.wait(120)
            for tenant, handle in handles.items():
                assert handle.wait(120), (placement, tenant)
                assert handle.status == "done", (
                    placement, tenant, handle.reason, handle.error,
                )
                assert suite_snapshot(handle.result) == solo[tenant], (
                    placement, tenant,
                )
                if handle.sharing is not None and handle.sharing["shared"]:
                    assert handle.sharing["proof"]["verdict"] == "CONTAINED"
                    assert all(
                        v == 0 for v in handle.sharing["drift"].values()
                    ), (placement, tenant, handle.sharing["drift"])
            shared_n = sum(
                1
                for h in handles.values()
                if h.sharing is not None and h.sharing["shared"]
            )
            assert shared_n >= 2, f"group never formed on {placement}"


# -- windowed state algebra: window query vs full rescan (ISSUE 18) -----------


def _context_bits(context) -> dict:
    """Bit-exact snapshot of an AnalyzerContext's metric map: floats
    compare by their f64 bit pattern (NaN payloads and -0.0 included),
    everything else by value."""
    import struct as _struct

    snap = {}
    for analyzer, metric in context.metric_map.items():
        v = (
            metric.value.get()
            if metric.value.is_success
            else type(metric.value.exception).__name__
        )
        if isinstance(v, float):
            v = _struct.pack(">d", v)
        snap[repr(analyzer)] = v
    return snap


@pytest.mark.parametrize("seed", range(6))
def test_window_query_vs_full_rescan_bit_identical(seed, monkeypatch, tmp_path):
    """A window query answered from the segment-merge tree must be
    BIT-identical to scanning exactly the window's member partitions —
    across random specs (tumbling/sliding/last-N), sparse calendars,
    cold and warm repositories, a late-arriving partition, and a
    re-stated (rewritten) partition. The merge is the engine's own
    sequential name-order fold, so equality here is exact snapshot
    equality, sketches included."""
    import datetime as _dt

    from deequ_tpu.analyzers import (
        ApproxCountDistinct,
        ApproxQuantile,
        Completeness,
        Maximum,
        Mean,
        Minimum,
        Size,
        StandardDeviation,
    )
    from deequ_tpu.data.table import Table as TableCls
    from deequ_tpu.repository.states import FileSystemStateRepository
    from deequ_tpu.runners.analysis_runner import AnalysisRunner
    from deequ_tpu.windows import LastN, Sliding, Tumbling, WindowQuery

    rng = np.random.default_rng(18_000 + seed)
    monkeypatch.setenv(
        "DEEQU_TPU_PLACEMENT", str(rng.choice(["host", "device"]))
    )
    monkeypatch.delenv("DEEQU_TPU_STATE_CACHE", raising=False)

    day0 = _dt.date(2026, 3, 1)
    n_parts = int(rng.integers(8, 17))
    if rng.random() < 0.5:  # sparse calendar: gaps inside the cover
        days = sorted(
            int(d)
            for d in rng.choice(n_parts * 2, size=n_parts, replace=False)
        )
    else:
        days = list(range(n_parts))

    data_dir = tmp_path / "dataset"
    data_dir.mkdir()

    def day_path(d: int) -> str:
        name = f"part-{(day0 + _dt.timedelta(days=d)).isoformat()}.parquet"
        return str(data_dir / name)

    for d in days:
        _write_partition(random_table(rng), day_path(d))

    analyzers = [
        Size(),
        Completeness("x"),
        Mean("x"),
        StandardDeviation("x"),
        Minimum("x"),
        Maximum("y"),
        ApproxCountDistinct("g"),
        ApproxQuantile("x", 0.5),
    ]
    span = int(rng.integers(2, 9))
    spec = [
        Tumbling(span),
        Sliding(span),
        LastN(span, unit=str(rng.choice(["days", "partitions"]))),
    ][int(rng.integers(0, 3))]
    repo = FileSystemStateRepository(str(tmp_path / "cache"))

    def check_step(step):
        source = TableCls.scan_parquet_dataset(str(data_dir))
        query = WindowQuery(
            source, analyzers, repository=repo, dataset="fuzz"
        )
        frame = spec.resolve(query.timeline())
        if not frame.indices:
            return
        window_ctx = query.run(frame)
        parts = source.partitions()
        rescan_ctx = AnalysisRunner.do_analysis_run(
            source.subset([parts[i].path for i in frame.indices]), analyzers
        )
        assert _context_bits(window_ctx) == _context_bits(rescan_ctx), (
            step,
            seed,
            repr(spec),
        )

    check_step("cold")  # rescan-fill + segment publish
    check_step("warm")  # pure segment merges

    late = max(days) + int(rng.integers(1, 4))
    _write_partition(random_table(rng), day_path(late))
    days.append(late)
    check_step("late")  # late arrival invalidates only covering spans

    _write_partition(
        random_table(rng), day_path(days[int(rng.integers(0, len(days)))])
    )
    check_step("restate")  # rewritten fingerprint self-invalidates
    check_step("warm2")  # the rebuilt covers serve the repeat

import numpy as np
import pytest

from deequ_tpu.core.maybe import Failure, Try
from deequ_tpu.data.expr import ExpressionParseError, Predicate, eval_predicate
from deequ_tpu.data.table import ColumnType, Table


class TestTry:
    def test_success(self):
        t = Try.of(lambda: 42)
        assert t.is_success and t.get() == 42
        assert t.map(lambda x: x + 1).get() == 43

    def test_failure(self):
        t = Try.of(lambda: 1 / 0)
        assert t.is_failure
        assert t.get_or_else(7) == 7
        assert isinstance(t, Failure)

    def test_failure_equality_by_class_and_message(self):
        a = Try.of(lambda: (_ for _ in ()).throw(ValueError("x")))
        b = Failure(ValueError("x"))
        assert a == b


class TestTable:
    def test_infer_types(self):
        t = Table.from_pydict(
            {"s": ["a", None], "i": [1, 2], "f": [1.0, None], "b": [True, False]}
        )
        assert dict(t.schema) == {
            "s": ColumnType.STRING,
            "i": ColumnType.LONG,
            "f": ColumnType.DOUBLE,
            "b": ColumnType.BOOLEAN,
        }
        assert t.num_rows == 2
        assert t["s"].null_count == 1
        assert t["f"].null_count == 1

    def test_batches(self):
        t = Table.from_pydict({"x": list(range(10))})
        sizes = [b.num_rows for b in t.batches(4)]
        assert sizes == [4, 4, 2]

    def test_dict_encode(self):
        # contract: codes index uniques row-by-row, nulls get -1; the
        # dictionary ORDER is unspecified (arrow returns first-seen,
        # the numpy fallback sorted — both valid)
        t = Table.from_pydict({"x": ["b", "a", None, "b"]})
        codes, uniques = t["x"].dict_encode()
        assert sorted(uniques) == ["a", "b"]
        assert codes[2] == -1
        decoded = [
            uniques[c] if c >= 0 else None for c in codes
        ]
        assert decoded == ["b", "a", None, "b"]
        # same value -> same code
        assert codes[0] == codes[3]

    def test_dict_encode_non_string_backing(self):
        # a STRING-typed column whose object backing holds non-str values
        # must stringify (the arrow fast path can't; the fallback does)
        import numpy as np

        from deequ_tpu.data.table import Column, ColumnType
        from deequ_tpu.data.table import Table as T

        vals = np.array([1, "a", 2, 1], dtype=object)
        col = Column("x", ColumnType.STRING, vals, np.ones(4, dtype=np.bool_))
        codes, uniques = T([col])["x"].dict_encode()
        decoded = [uniques[c] for c in codes]
        assert [str(d) for d in decoded] == ["1", "a", "2", "1"]
        assert codes[0] == codes[3]

    def test_roundtrip_pandas(self):
        t = Table.from_pydict({"x": [1, 2, None], "y": ["a", None, "c"]})
        t2 = Table.from_pandas(t.to_pandas())
        assert t2.num_rows == 3
        assert t2["y"].null_count == 1

    def test_arrow_roundtrip(self, tmp_path):
        import pyarrow as pa
        import pyarrow.parquet as pq

        at = pa.table({"a": [1, 2, None], "b": [1.5, None, 2.5], "c": ["x", "y", None]})
        p = str(tmp_path / "t.parquet")
        pq.write_table(at, p)
        t = Table.from_parquet(p)
        assert t.num_rows == 3
        assert t["a"].null_count == 1
        assert t["b"].null_count == 1
        assert t["c"].null_count == 1
        assert t["a"].ctype == ColumnType.LONG

    def test_missing_column_raises(self):
        from deequ_tpu.core.exceptions import NoSuchColumnException

        t = Table.from_pydict({"x": [1]})
        with pytest.raises(NoSuchColumnException):
            t.column("nope")


class TestPredicate:
    def table(self):
        return Table.from_pydict(
            {
                "att1": [1, 2, 3, None, 5, 6],
                "att2": [0, 0, 0, 5, 6, 7],
                "name": ["a", "b", None, "a", "c", "ab"],
            }
        )

    def test_comparison(self):
        m = eval_predicate("att1 > 3", self.table())
        assert list(m) == [False, False, False, False, True, True]

    def test_null_propagates_to_false(self):
        m = eval_predicate("att1 >= 1", self.table())
        assert list(m) == [True, True, True, False, True, True]

    def test_and_or(self):
        m = eval_predicate("att1 > 1 AND att2 = 0", self.table())
        assert list(m) == [False, True, True, False, False, False]
        m = eval_predicate("att1 > 5 OR att2 > 5", self.table())
        assert list(m) == [False, False, False, False, True, True]

    def test_is_null(self):
        m = eval_predicate("att1 IS NULL", self.table())
        assert list(m) == [False, False, False, True, False, False]
        m = eval_predicate("name IS NOT NULL", self.table())
        assert list(m) == [True, True, False, True, True, True]

    def test_in_list(self):
        m = eval_predicate("name IN ('a', 'c')", self.table())
        assert list(m) == [True, False, False, True, True, False]

    def test_null_or_in(self):
        # the isContainedIn shape: `col` IS NULL OR `col` IN (...)
        m = eval_predicate("`name` IS NULL OR `name` IN ('a','b')", self.table())
        assert list(m) == [True, True, True, True, False, False]

    def test_coalesce(self):
        # the isNonNegative shape: COALESCE(col, 0.0) >= 0
        m = eval_predicate("COALESCE(att1, 0.0) >= 0", self.table())
        assert list(m) == [True] * 6

    def test_arithmetic(self):
        m = eval_predicate("att1 * 2 + 1 >= att2 + 6", self.table())
        # att1*2+1: 3,5,7,null,11,13 ; att2+6: 6,6,6,11,12,13
        assert list(m) == [False, False, True, False, False, True]

    def test_between(self):
        m = eval_predicate("att2 BETWEEN 5 AND 6", self.table())
        assert list(m) == [False, False, False, True, True, False]

    def test_like_rlike(self):
        m = eval_predicate("name LIKE 'a%'", self.table())
        assert list(m) == [True, False, False, True, False, True]
        m = eval_predicate("name RLIKE '^a$'", self.table())
        assert list(m) == [True, False, False, True, False, False]

    def test_string_numeric_coercion(self):
        t = Table.from_pydict({"s": ["1", "2", "x", None]})
        m = eval_predicate("s >= 2", t)
        assert list(m) == [False, True, False, False]

    def test_division_by_zero_is_null(self):
        m = eval_predicate("att1 / att2 > 0", self.table())
        # att2 = 0 on rows 0-2 -> NULL -> False; row 3 att1 NULL -> False
        assert list(m) == [False, False, False, False, True, True]

    def test_parse_error(self):
        with pytest.raises(ExpressionParseError):
            Predicate("att1 >>> 3")
        with pytest.raises(ExpressionParseError):
            Predicate("someInvalidExpression !!")

    def test_referenced_columns(self):
        p = Predicate("att1 > 3 AND COALESCE(att2, 0) = 0 OR name IN ('a')")
        assert set(p.referenced_columns()) == {"att1", "att2", "name"}

    def test_not(self):
        m = eval_predicate("NOT att2 = 0", self.table())
        assert list(m) == [False, False, False, True, True, True]


def test_from_numpy_object_bool_column_is_boolean():
    """An object array of {bool, None} must infer BOOLEAN (like
    from_pydict) so histogram keys render as the reference's
    'true'/'false', not Python's str(True) (found by a verify drive,
    round 4)."""
    import numpy as np

    from deequ_tpu.data.table import ColumnType, Table

    rng = np.random.default_rng(3)
    flag = np.where(rng.random(200) > 0.2, rng.random(200) < 0.5, None)
    t = Table.from_numpy({"flag": flag})
    col = t.column("flag")
    assert col.ctype == ColumnType.BOOLEAN
    assert col.valid.sum() == sum(v is not None for v in flag)
    from deequ_tpu.profiles.column_profiler import ColumnProfiler

    hist = ColumnProfiler.profile(t).profiles["flag"].histogram
    assert set(hist.values) <= {"true", "false", "NullValue"}

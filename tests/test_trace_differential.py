"""Trace-differential suite (ISSUE 4 tentpole correctness gate).

The static cost analyzer's predicted dispatch signature — counters,
execution-span histogram, deduplicated family-group set — must equal the
one extracted from a real run's `RunTrace`, as one dict equality:

    plan_cost.dispatch_signature() == observe.dispatch_signature(trace)

Every scenario pins the data-dependent knobs the model states as
assumptions: placement via DEEQU_TPU_PLACEMENT, the counts-family
shortcut off via DEEQU_TPU_NO_COUNTS_FASTPATH, tables small enough to
stay on the single engine, group cardinalities below the device
frequency-aggregation threshold.
"""

from __future__ import annotations

import numpy as np
import pytest

from deequ_tpu import observe
from deequ_tpu.analyzers import (
    ApproxCountDistinct,
    ApproxQuantile,
    Completeness,
    Distinctness,
    Histogram,
    Maximum,
    Mean,
    Minimum,
    StandardDeviation,
    Sum,
    Uniqueness,
)
from deequ_tpu.data.table import Table
from deequ_tpu.lint import SchemaInfo, analyze_plan
from deequ_tpu.observe import dispatch_signature
from deequ_tpu.ops.fused import FusedScanPass
from deequ_tpu.runners import AnalysisRunner


@pytest.fixture(autouse=True)
def _pinned_execution(monkeypatch):
    """Pin every knob the cost model states as an assumption."""
    monkeypatch.setenv("DEEQU_TPU_NO_COUNTS_FASTPATH", "1")
    yield


def _table(n=4096, seed=0):
    rng = np.random.default_rng(seed)
    return Table.from_numpy(
        {
            "price": rng.random(n) * 100.0,
            "cost": rng.standard_normal(n),
            "qty": rng.integers(0, 50, n),
            "cat": rng.integers(0, 8, n),
        }
    )


def _run(table, analyzers):
    ctx = (
        AnalysisRunner.on_data(table)
        .add_analyzers(analyzers)
        .with_tracing(True)
        .run()
    )
    assert ctx.run_trace is not None
    assert ctx.plan_cost is not None, "runner did not attach a PlanCost"
    return ctx


class TestRunnerDifferential:
    def test_device_scan_matches_trace(self, monkeypatch):
        monkeypatch.setenv("DEEQU_TPU_PLACEMENT", "device")
        ctx = _run(
            _table(),
            [
                Mean("price"),
                StandardDeviation("price"),
                Minimum("cost"),
                Maximum("cost"),
                Completeness("qty"),
                Sum("qty"),
            ],
        )
        predicted = ctx.plan_cost.dispatch_signature()
        observed = dispatch_signature(ctx.run_trace)
        assert predicted == observed
        # the scenario actually dispatched: this is not a trivial match
        assert observed["counters"]["device_passes"] == 1
        assert observed["spans"]["dispatch"] >= 1

    def test_host_all_family_groups_match_trace(self, monkeypatch):
        monkeypatch.setenv("DEEQU_TPU_PLACEMENT", "host")
        ctx = _run(
            _table(),
            [
                ApproxQuantile("price", 0.5),
                ApproxQuantile("cost", 0.5),
                ApproxCountDistinct("price"),
                ApproxQuantile("qty", 0.9, where="qty > 10"),
                Mean("price"),
            ],
        )
        predicted = ctx.plan_cost.dispatch_signature()
        observed = dispatch_signature(ctx.run_trace)
        assert predicted == observed
        # the family-group set is non-trivial: a multi-column batched
        # traversal AND a where-filtered solo group
        groups = observed["family_groups"]
        assert groups, "no family kernels dispatched"
        assert any(batched for (_, _, _, _, batched) in groups)
        assert any(w != "where:<all>" for (w, _, _, _, _) in groups)

    def test_grouping_sets_match_trace(self, monkeypatch):
        monkeypatch.setenv("DEEQU_TPU_PLACEMENT", "device")
        ctx = _run(
            _table(),
            [
                Uniqueness(["cat"]),
                Distinctness(["cat"]),
                Uniqueness(["cat", "qty"]),
            ],
        )
        predicted = ctx.plan_cost.dispatch_signature()
        observed = dispatch_signature(ctx.run_trace)
        assert predicted == observed
        # two distinct grouping column sets -> two frequency passes
        assert observed["spans"]["grouping"] == 2
        assert observed["counters"]["group_passes"] == 2

    def test_mixed_plan_matches_trace(self, monkeypatch):
        monkeypatch.setenv("DEEQU_TPU_PLACEMENT", "device")
        ctx = _run(
            _table(),
            [
                Mean("price"),
                StandardDeviation("price"),
                Histogram("cat"),
                Uniqueness(["cat"]),
                Distinctness(["qty"]),
            ],
        )
        predicted = ctx.plan_cost.dispatch_signature()
        observed = dispatch_signature(ctx.run_trace)
        assert predicted == observed
        # scan + aux (Histogram) + two grouping sets all present
        assert observed["counters"]["group_passes"] == 3
        assert observed["spans"]["fused_scan"] == 1


class TestMultiBatchDifferential:
    def test_batched_scan_spans_and_exact_wire_bytes(self, monkeypatch):
        """5 batches of 1024 rows through the fused pass directly: the
        span histogram matches AND the per-dispatch wire bytes equal the
        model's `pack_batch_inputs` replay, byte for byte."""
        monkeypatch.setenv("DEEQU_TPU_PLACEMENT", "device")
        n, batch = 5120, 1024
        table = _table(n)
        analyzers = [
            Mean("price"),
            StandardDeviation("price"),
            Minimum("cost"),
            Completeness("qty"),
        ]
        cost = analyze_plan(
            analyzers,
            SchemaInfo.from_table(table),
            num_rows=n,
            batch_size=batch,
            placement="device",
        )
        scan = cost.scan_pass
        assert scan.n_batches == 5
        assert scan.wire_bytes_per_batch is not None

        with observe.traced_run("scan", enable=True) as handle:
            results = FusedScanPass(analyzers, batch_size=batch).run(table)
        assert all(r.error is None for r in results)
        trace = handle.trace
        assert trace is not None

        assert cost.dispatch_signature() == dispatch_signature(trace)
        dispatches = [s for s in trace.spans() if s.name == "dispatch"]
        assert len(dispatches) == 5
        for sp in dispatches:
            assert sp.attrs.get("wire_bytes") == scan.wire_bytes_per_batch

    def test_prednn_mask_elision_is_predicted(self, monkeypatch):
        """A predicate over a non-nullable column ships NO prednn mask:
        the typechecker proves it all-true and the wire replay must
        account for the elision to stay byte-exact."""
        monkeypatch.setenv("DEEQU_TPU_PLACEMENT", "device")
        n, batch = 2048, 1024
        table = _table(n)
        analyzers = [Mean("price", where="qty > 25"), Minimum("price")]
        cost = analyze_plan(
            analyzers,
            SchemaInfo.from_table(table),
            num_rows=n,
            batch_size=batch,
            placement="device",
        )
        scan = cost.scan_pass
        assert scan.wire_bytes_per_batch is not None

        with observe.traced_run("scan", enable=True) as handle:
            results = FusedScanPass(analyzers, batch_size=batch).run(table)
        assert all(r.error is None for r in results)
        trace = handle.trace

        assert cost.dispatch_signature() == dispatch_signature(trace)
        for sp in trace.spans():
            if sp.name == "dispatch":
                assert sp.attrs.get("wire_bytes") == scan.wire_bytes_per_batch

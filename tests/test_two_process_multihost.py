"""Two-process multihost smoke test: spawns 2 real JAX processes over
loopback and runs run_multihost_analysis end-to-end, exercising the real
allgather_bytes/process_allgather path (parallel/multihost.py) that
single-process tests only hit in its identity branch.

Skips (not fails) when the multi-process runtime can't start in this
environment; a metric mismatch between hosts or vs the whole-table run
is a hard failure."""

from __future__ import annotations

import textwrap

import numpy as np
import pytest

from deequ_tpu.parallel.procspawn import WorkerFailure, run_worker_processes

WORKER = textwrap.dedent(
    """
    import os
    import sys

    # a tiny group cap so the Uniqueness state SPILLS on each host and
    # the spilled-frequencies envelope crosses the real allgather
    os.environ["DEEQU_TPU_MAX_GROUPS_IN_MEMORY"] = "200"

    import jax

    jax.config.update("jax_platforms", "cpu")
    rank, port = int(sys.argv[1]), sys.argv[2]
    jax.distributed.initialize(
        coordinator_address=f"127.0.0.1:{port}",
        num_processes=2,
        process_id=rank,
        initialization_timeout=60,
    )

    import json

    import numpy as np

    from deequ_tpu.analyzers import (
        ApproxCountDistinct,
        Completeness,
        CountDistinct,
        Maximum,
        Mean,
        Minimum,
        Size,
        StandardDeviation,
        Sum,
        Uniqueness,
    )
    from deequ_tpu.data.source import ParquetSource
    from deequ_tpu.parallel import multihost

    rng = np.random.default_rng(100 + rank)
    x = rng.normal(3.0, 2.0, 50_000)
    x[::7] = np.nan
    arrays = {"x": x, "g": rng.integers(0, 1000, 50_000)}
    # stream the partition from Parquet so the grouping fold actually
    # exceeds the cap batch by batch (in-memory single-batch tables
    # compute frequencies in one shot without the accumulator)
    import pyarrow as pa
    import pyarrow.parquet as pq

    path = sys.argv[3] + f"/part{rank}.parquet"
    pq.write_table(
        pa.table({"x": pa.array(arrays["x"], mask=np.isnan(arrays["x"])),
                  "g": pa.array(arrays["g"])}),
        path,
        row_group_size=5_000,
    )
    source = ParquetSource(path, batch_rows=5_000)
    analyzers = [
        Size(),
        Completeness("x"),
        Mean("x"),
        Sum("x"),
        Minimum("x"),
        Maximum("x"),
        StandardDeviation("x"),
        ApproxCountDistinct("g"),
        Uniqueness(("g",)),
        CountDistinct(("g",)),
    ]
    ctx = multihost.run_multihost_analysis(source, analyzers)
    out = {repr(a): ctx.metric_map[a].value.get() for a in analyzers}
    print("RESULT:" + json.dumps(out), flush=True)
    """
)


def test_two_process_multihost_analysis():
    # the shared harness (deequ_tpu/parallel/procspawn.py) owns the
    # port/Popen/RESULT scaffolding; an environment where the loopback
    # runtime can't start surfaces as WorkerFailure -> skip (not fail)
    try:
        results = run_worker_processes(WORKER, 2, timeout=150)
    except WorkerFailure as e:
        if not e.runtime_unavailable:
            raise  # broken RESULT protocol is a real regression
        pytest.skip(
            f"two-process JAX runtime unavailable in this environment: {e}"
        )

    # both hosts must report identical global metrics
    assert results[0].keys() == results[1].keys()
    for key in results[0]:
        assert results[0][key] == pytest.approx(results[1][key], rel=1e-12), key

    # ... equal to the whole-table (both partitions concatenated) run,
    # including the grouping metrics whose per-host states SPILLED
    from deequ_tpu.analyzers import (
        ApproxCountDistinct,
        Completeness,
        CountDistinct,
        Maximum,
        Mean,
        Minimum,
        Size,
        StandardDeviation,
        Sum,
        Uniqueness,
    )
    from deequ_tpu.data.table import Table
    from deequ_tpu.runners.analysis_runner import AnalysisRunner

    parts = []
    for rank in (0, 1):
        rng = np.random.default_rng(100 + rank)
        x = rng.normal(3.0, 2.0, 50_000)
        x[::7] = np.nan
        parts.append({"x": x, "g": rng.integers(0, 1000, 50_000)})
    whole = Table.from_numpy(
        {k: np.concatenate([p[k] for p in parts]) for k in ("x", "g")}
    )
    analyzers = [
        Size(),
        Completeness("x"),
        Mean("x"),
        Sum("x"),
        Minimum("x"),
        Maximum("x"),
        StandardDeviation("x"),
        ApproxCountDistinct("g"),
        Uniqueness(("g",)),
        CountDistinct(("g",)),
    ]
    ctx = AnalysisRunner.do_analysis_run(whole, analyzers)
    for analyzer in analyzers:
        want = ctx.metric_map[analyzer].value.get()
        assert results[0][repr(analyzer)] == pytest.approx(want, rel=1e-9), analyzer

"""Exact output shapes of VerificationResult exporters — the mirror of
the reference's VerificationResultTest.scala (219 LoC): same fixture
(getDfFull), same analyzers, same checks, byte-level row expectations
including the load-bearing 'Mutlicolumn' typo."""

from __future__ import annotations

import json

import pytest

from deequ_tpu import Check, CheckLevel, CheckStatus, VerificationSuite
from deequ_tpu.analyzers import Completeness, Distinctness, Size, Uniqueness
from tests.fixtures import get_df_full


@pytest.fixture(scope="module")
def results():
    """reference: VerificationResultTest.scala:173-196 (evaluate)."""
    checks = [
        Check(CheckLevel.ERROR, "group-1").is_complete("att1"),
        Check(CheckLevel.ERROR, "group-2-E")
        .has_size(lambda n: n > 5, hint="Should be greater than 5!")
        .is_complete("att1"),
        Check(CheckLevel.WARNING, "group-2-W").has_distinctness(
            ["item"], lambda v: v < 0.8, hint="Should be smaller than 0.8!"
        ),
    ]
    suite = VerificationSuite.on_data(get_df_full())
    for check in checks:
        suite = suite.add_check(check)
    return (
        suite.add_required_analyzer(Size())
        .add_required_analyzer(Distinctness(["item"]))
        .add_required_analyzer(Uniqueness(["att1", "att2"]))
        .run()
    )


class TestSuccessMetricsShapes:
    """reference: VerificationResultTest.scala:38-110."""

    def test_rows_exact(self, results):
        rows = results.success_metrics_as_rows()
        as_tuples = {
            (r["entity"], r["instance"], r["name"], r["value"]) for r in rows
        }
        assert ("Dataset", "*", "Size", 4.0) in as_tuples
        assert ("Column", "item", "Distinctness", 1.0) in as_tuples
        assert ("Column", "att1", "Completeness", 1.0) in as_tuples
        # the reference serializes Entity.Multicolumn with its historical
        # typo — byte-compatible output keeps it
        assert ("Mutlicolumn", "att1,att2", "Uniqueness", 0.25) in as_tuples

    def test_rows_filtered_to_requested_analyzers(self, results):
        rows = results.success_metrics_as_rows(
            for_analyzers=[Completeness("att1"), Uniqueness(["att1", "att2"])]
        )
        as_tuples = {
            (r["entity"], r["instance"], r["name"], r["value"]) for r in rows
        }
        assert as_tuples == {
            ("Column", "att1", "Completeness", 1.0),
            ("Mutlicolumn", "att1,att2", "Uniqueness", 0.25),
        }

    def test_json_format(self, results):
        payload = json.loads(results.success_metrics_as_json())
        assert all(
            set(entry.keys()) == {"entity", "instance", "name", "value"}
            for entry in payload
        )
        size_entry = next(e for e in payload if e["name"] == "Size")
        assert size_entry == {
            "entity": "Dataset",
            "instance": "*",
            "name": "Size",
            "value": 4.0,
        }

    def test_table_export_columns(self, results):
        table = results.success_metrics_as_table()
        assert table.column_names == ["entity", "instance", "name", "value"]
        assert table.num_rows >= 4


class TestCheckResultsShapes:
    """reference: VerificationResultTest.scala:115-171."""

    def test_rows_exact(self, results):
        rows = results.check_results_as_rows()
        as_tuples = [
            (
                r["check"],
                r["check_level"],
                r["check_status"],
                r["constraint"],
                r["constraint_status"],
                r["constraint_message"],
            )
            for r in rows
        ]
        assert (
            "group-1",
            "Error",
            "Success",
            "CompletenessConstraint(Completeness(att1,None))",
            "Success",
            "",
        ) in as_tuples
        assert (
            "group-2-E",
            "Error",
            "Error",
            "SizeConstraint(Size(None))",
            "Failure",
            "Value: 4 does not meet the constraint requirement! "
            "Should be greater than 5!",
        ) in as_tuples
        assert (
            "group-2-E",
            "Error",
            "Error",
            "CompletenessConstraint(Completeness(att1,None))",
            "Success",
            "",
        ) in as_tuples
        assert (
            "group-2-W",
            "Warning",
            "Warning",
            "DistinctnessConstraint(Distinctness(List(item)))",
            "Failure",
            "Value: 1.0 does not meet the constraint requirement! "
            "Should be smaller than 0.8!",
        ) in as_tuples

    def test_constraint_order_within_check_preserved(self, results):
        rows = [
            r for r in results.check_results_as_rows() if r["check"] == "group-2-E"
        ]
        assert [r["constraint"] for r in rows] == [
            "SizeConstraint(Size(None))",
            "CompletenessConstraint(Completeness(att1,None))",
        ]

    def test_json_round_trip_equals_rows(self, results):
        assert json.loads(results.check_results_as_json()) == \
            results.check_results_as_rows()

    def test_filter_to_single_check(self, results):
        check = next(iter(results.check_results))
        rows = results.check_results_as_rows(for_checks=[check])
        assert {r["check"] for r in rows} == {check.description}

    def test_table_export_columns(self, results):
        table = results.check_results_as_table()
        assert table.column_names == [
            "check",
            "check_level",
            "check_status",
            "constraint",
            "constraint_status",
            "constraint_message",
        ]

    def test_overall_status(self, results):
        assert results.status == CheckStatus.ERROR

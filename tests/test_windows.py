"""Windowed state algebra (deequ_tpu/windows/): timeline derivation
from dataset layouts, the aligned power-of-two cover, DQSG segment
envelope serde + fail-closed validation, SegmentStore degrade paths
(corruption, signature mismatch, injected `state.segment` chaos
faults), content-keyed span invalidation exactness, the WindowQuery
end-to-end contract (zero rows warm, bit-identical to a full rescan,
O(log n) invalidation on a late partition), DQ323 diagnostics, the
EXPLAIN/admission surfaces, and `DQService.submit_window`.
"""

from __future__ import annotations

import datetime
import glob
import math
import os
import struct
import warnings

import numpy as np
import pytest

from deequ_tpu.analyzers import (
    ApproxCountDistinct,
    ApproxQuantile,
    Completeness,
    CountDistinct,
    Maximum,
    Mean,
    Minimum,
    Size,
    StandardDeviation,
)
from deequ_tpu.data.table import ColumnType, Table
from deequ_tpu.repository.states import (
    FileSystemStateRepository,
    InMemoryStateRepository,
    StateDecodeError,
    encode_states,
)
from deequ_tpu.runners.analysis_runner import AnalysisRunner
from deequ_tpu.testing import faults
from deequ_tpu.windows import (
    SEGMENT_FORMAT_VERSION,
    SEGMENT_MAGIC,
    LastN,
    SegmentStore,
    Sliding,
    Timeline,
    Tumbling,
    WindowQuery,
    aligned_cover,
    decode_segment,
    default_bucket_for,
    encode_segment,
    span_fingerprint,
)
from deequ_tpu.windows.segments import segment_key

DAY0 = datetime.date(2026, 1, 1)


def _bits(x: float) -> bytes:
    return struct.pack(">d", float(x))


class _P:
    """A minimal Partition stand-in (anything with .name)."""

    def __init__(self, name: str) -> None:
        self.name = name


def _daily_table(rng: np.random.Generator, n: int = 400) -> Table:
    x = rng.normal(40.0, 10.0, n)
    x[rng.random(n) < 0.05] = np.nan
    y = x * 0.5 + rng.normal(0, 1.0, n)
    g = rng.integers(0, 500, n)
    return Table.from_pydict(
        {"x": list(x), "y": list(y), "g": [int(v) for v in g]},
        types={
            "x": ColumnType.DOUBLE,
            "y": ColumnType.DOUBLE,
            "g": ColumnType.LONG,
        },
    )


def _write_daily_dataset(dir_path, n_days: int, seed: int = 0) -> list:
    """`n_days` date-named parquet partitions; partition i is a pure
    function of (seed, i)."""
    os.makedirs(str(dir_path), exist_ok=True)
    paths = []
    for i in range(n_days):
        day = DAY0 + datetime.timedelta(days=i)
        path = os.path.join(str(dir_path), f"part-{day.isoformat()}.parquet")
        rng = np.random.default_rng(seed * 1_000 + i)
        _daily_table(rng).to_parquet(path, row_group_size=128)
        paths.append(path)
    return paths


_ANALYZERS = [
    Size(),
    Completeness("x"),
    Mean("x"),
    StandardDeviation("x"),
    Minimum("x"),
    Maximum("y"),
    ApproxCountDistinct("g"),
    ApproxQuantile("x", 0.5),
]


def _snapshot(context) -> dict:
    snap = {}
    for analyzer, metric in context.metric_map.items():
        v = (
            metric.value.get()
            if metric.value.is_success
            else type(metric.value.exception).__name__
        )
        if isinstance(v, float):
            v = _bits(v)
        snap[repr(analyzer)] = v
    return snap


# ---------------------------------------------------------------------------
# timeline derivation
# ---------------------------------------------------------------------------


class TestTimeline:
    def test_iso_date_layout_maps_to_epoch_days(self):
        names = [
            f"part-{(DAY0 + datetime.timedelta(days=d)).isoformat()}.parquet"
            for d in (0, 1, 5)
        ]
        tl = Timeline.derive([_P(n) for n in names])
        assert tl.axis == "date"
        assert tl.buckets == (
            DAY0.toordinal(),
            DAY0.toordinal() + 1,
            DAY0.toordinal() + 5,
        )

    def test_compact_yyyymmdd_layout(self):
        tl = Timeline.derive([_P("20260101.pq"), _P("20260103.pq")])
        assert tl.axis == "date"
        assert tl.buckets[1] - tl.buckets[0] == 2

    def test_compact_form_needs_digit_boundaries(self):
        # a 9-digit run is not a date; the lookaround guards reject it
        assert default_bucket_for("id-202601015.pq") is None

    def test_invalid_calendar_date_is_not_a_bucket(self):
        assert default_bucket_for("part-2026-13-40.parquet") is None

    def test_undated_layout_degrades_to_positional(self):
        tl = Timeline.derive([_P("a.parquet"), _P("b.parquet")])
        assert tl.axis == "index"
        assert tl.buckets == (0, 1)

    def test_one_undated_name_degrades_the_whole_layout(self):
        tl = Timeline.derive([_P("part-2026-01-01.pq"), _P("z.pq")])
        assert tl.axis == "index"

    def test_explicit_extractor_wins(self):
        tl = Timeline.derive(
            [_P("a"), _P("b")], extractor=lambda name: ord(name[0])
        )
        assert tl.buckets == (ord("a"), ord("b"))

    def test_extractor_must_bucket_every_partition(self):
        with pytest.raises(ValueError, match="extractor returned None"):
            Timeline.derive(
                [_P("a"), _P("b")],
                extractor=lambda name: None if name == "b" else 0,
            )

    def test_buckets_must_be_nondecreasing_in_name_order(self):
        # name order is the engine's merge order; buckets that decrease
        # along it would break window contiguity
        with pytest.raises(ValueError, match="non-decreasing"):
            Timeline(("a", "b"), (5, 3))

    def test_frame_and_indices_in(self):
        tl = Timeline(("a", "b", "c", "d"), (10, 11, 11, 14))
        assert tl.indices_in(11, 14) == (1, 2)
        frame = tl.frame(10, 12)
        assert frame.indices == (0, 1, 2)
        assert (frame.lo, frame.hi) == (10, 12)

    def test_shifted_frame_moves_earlier(self):
        tl = Timeline(("a", "b", "c"), (10, 11, 12))
        frame = tl.frame(11, 13)
        prior = frame.shifted(2, tl)
        assert (prior.lo, prior.hi) == (9, 11)
        assert prior.indices == (0,)


# ---------------------------------------------------------------------------
# the aligned power-of-two cover
# ---------------------------------------------------------------------------


class TestAlignedCover:
    def test_known_decomposition(self):
        assert aligned_cover(3, 20) == [(0, 3), (2, 4), (3, 8), (2, 16)]

    def test_empty_and_unit_ranges(self):
        assert aligned_cover(5, 5) == []
        assert aligned_cover(7, 8) == [(0, 7)]

    def test_negative_lo_rejected(self):
        with pytest.raises(ValueError):
            aligned_cover(-1, 4)

    def test_cover_properties_fuzzed(self):
        rng = np.random.default_rng(7)
        for _ in range(200):
            lo = int(rng.integers(0, 2000))
            hi = lo + int(rng.integers(1, 2000))
            spans = aligned_cover(lo, hi)
            cur = lo
            for level, start in spans:
                size = 1 << level
                assert start == cur  # contiguous, ascending
                assert start % size == 0 or start == 0  # aligned
                cur = start + size
            assert cur == hi  # exact cover
            # O(log n) spans: the segment-tree bound
            assert len(spans) <= 2 * max(1, (hi - lo).bit_length())

    def test_same_range_same_spans(self):
        assert aligned_cover(37, 1000) == aligned_cover(37, 1000)


# ---------------------------------------------------------------------------
# DQSG envelope serde
# ---------------------------------------------------------------------------


def _entries():
    blob_a = encode_states([(Size(), None)])
    blob_b = encode_states([(Size(), None)])
    return [("part-a", 10, blob_a), ("part-b", 11, blob_b)]


class TestSegmentSerde:
    def test_round_trip(self):
        entries = _entries()
        blob = encode_segment(3, 8, "sig-1", entries)
        seg = decode_segment(blob)
        assert (seg.level, seg.start, seg.signature) == (3, 8, "sig-1")
        assert seg.entries == entries
        assert seg.span == (8, 16)

    def test_corruption_fails_closed(self):
        blob = bytearray(encode_segment(1, 2, "sig", _entries()))
        blob[len(blob) // 2] ^= 0xFF
        with pytest.raises(StateDecodeError, match="digest"):
            decode_segment(bytes(blob))

    def test_truncation_fails_closed(self):
        blob = encode_segment(1, 2, "sig", _entries())
        for cut in (0, 3, len(blob) // 2, len(blob) - 1):
            with pytest.raises(StateDecodeError):
                decode_segment(blob[:cut])

    def test_version_bump_fails_closed(self):
        blob = encode_segment(1, 2, "sig", _entries())
        body = bytearray(blob[:-32])
        struct.pack_into(">I", body, len(SEGMENT_MAGIC), SEGMENT_FORMAT_VERSION + 1)
        import hashlib

        patched = bytes(body) + hashlib.sha256(bytes(body)).digest()
        with pytest.raises(StateDecodeError, match="version"):
            decode_segment(patched)

    def test_trailing_bytes_fail_closed(self):
        import hashlib

        body = encode_segment(1, 2, "sig", _entries())[:-32] + b"\x00"
        patched = body + hashlib.sha256(body).digest()
        with pytest.raises(StateDecodeError, match="trailing"):
            decode_segment(patched)


class TestSpanFingerprint:
    def test_stable_for_identical_members(self):
        members = [(10, "aa"), (11, "bb")]
        assert span_fingerprint(2, 8, members) == span_fingerprint(
            2, 8, list(members)
        )

    def test_any_change_changes_the_key(self):
        base = span_fingerprint(2, 8, [(10, "aa"), (11, "bb")])
        assert span_fingerprint(2, 8, [(10, "aa"), (11, "XX")]) != base
        assert span_fingerprint(2, 8, [(10, "aa"), (12, "bb")]) != base
        assert span_fingerprint(3, 8, [(10, "aa"), (11, "bb")]) != base
        assert span_fingerprint(2, 12, [(10, "aa"), (11, "bb")]) != base
        assert span_fingerprint(2, 8, [(10, "aa")]) != base

    def test_segment_keys_are_disjoint_from_partition_fingerprints(self):
        # partition fingerprints are bare hex; the seg- prefix keeps the
        # two families from colliding in the same repository slot
        assert segment_key(3, "ab" * 16).startswith("seg-L03-")


# ---------------------------------------------------------------------------
# SegmentStore: persistence + degrade paths
# ---------------------------------------------------------------------------


class TestSegmentStore:
    def _store(self):
        return SegmentStore(InMemoryStateRepository(), "ds", "sig-1")

    def test_save_has_load_round_trip(self):
        store = self._store()
        entries = _entries()
        fp = span_fingerprint(1, 2, [(10, "aa"), (11, "bb")])
        assert not store.has(1, fp)
        assert store.save(1, 2, fp, entries)
        assert store.has(1, fp)
        seg = store.load(1, fp)
        assert seg is not None and seg.entries == entries

    def test_missing_entry_is_a_silent_miss(self):
        with warnings.catch_warnings():
            warnings.simplefilter("error")
            assert self._store().load(0, "0" * 32) is None

    def test_corrupt_entry_warns_dq323_and_misses(self):
        store = self._store()
        fp = "f" * 32
        store.repository.put_blob(
            "ds", "sig-1", segment_key(0, fp), b"DQSG garbage"
        )
        with pytest.warns(RuntimeWarning, match="DQ323"):
            assert store.load(0, fp) is None

    def test_signature_mismatch_warns_dq323_and_misses(self):
        store = self._store()
        fp = "e" * 32
        blob = encode_segment(0, 5, "OTHER-sig", _entries())
        store.repository.put_blob("ds", "sig-1", segment_key(0, fp), blob)
        with pytest.warns(RuntimeWarning, match="signature"):
            assert store.load(0, fp) is None

    def test_injected_read_fault_degrades_with_warning(self):
        store = self._store()
        fp = span_fingerprint(0, 5, [(5, "cc")])
        assert store.save(0, 5, fp, _entries())
        with faults.install("seed=1,state.segment:1.0:1"):
            with pytest.warns(RuntimeWarning, match="DQ323"):
                assert store.load(0, fp) is None
        # fault budget spent: the entry itself is intact
        assert store.load(0, fp) is not None

    def test_injected_write_fault_is_best_effort(self):
        store = self._store()
        fp = "d" * 32
        with faults.install("seed=1,state.segment:1.0:1"):
            assert store.save(0, 5, fp, _entries()) is False
        assert not store.has(0, fp)


# ---------------------------------------------------------------------------
# window specs
# ---------------------------------------------------------------------------


class TestWindowSpecs:
    TL = Timeline(
        ("a", "b", "c", "d", "e"), (100, 101, 102, 104, 106)
    )

    def test_tumbling_series_is_aligned_and_non_overlapping(self):
        frames = Tumbling(4).series(self.TL)
        assert [(f.lo, f.hi) for f in frames] == [(100, 104), (104, 108)]
        assert frames[0].indices == (0, 1, 2)
        assert frames[1].indices == (3, 4)

    def test_tumbling_resolve_is_the_latest_window(self):
        frame = Tumbling(4).resolve(self.TL)
        assert (frame.lo, frame.hi) == (104, 108)

    def test_sliding_resolve_ends_at_the_newest_bucket(self):
        frame = Sliding(3).resolve(self.TL)
        assert (frame.lo, frame.hi) == (104, 107)
        assert frame.indices == (3, 4)

    def test_sliding_series_steps(self):
        frames = Sliding(2, step=2).series(self.TL)
        assert all(f.hi - f.lo == 2 for f in frames)
        assert frames[-1].hi == 107

    def test_last_n_days_is_bucket_arithmetic(self):
        frame = LastN(3, unit="days").resolve(self.TL)
        assert frame.indices == (3, 4)  # buckets 104 and 106 in [104, 107)
        assert LastN(1, unit="days").resolve(self.TL).indices == (4,)

    def test_last_n_partitions_is_positional(self):
        frame = LastN(3, unit="partitions").resolve(self.TL)
        assert frame.indices == (2, 3, 4)

    def test_invalid_specs_rejected(self):
        with pytest.raises(ValueError):
            Tumbling(0)
        with pytest.raises(ValueError):
            Sliding(2, step=0)
        with pytest.raises(ValueError):
            LastN(2, unit="weeks")

    def test_describe_round_trips_through_repr(self):
        assert repr(Sliding(7)) == "sliding(7, step=1)"
        assert repr(LastN(7)) == "last(7 days)"


# ---------------------------------------------------------------------------
# WindowQuery end to end
# ---------------------------------------------------------------------------


@pytest.fixture()
def daily(tmp_path, monkeypatch):
    monkeypatch.setenv("DEEQU_TPU_PLACEMENT", "device")
    monkeypatch.delenv("DEEQU_TPU_STATE_CACHE", raising=False)
    _write_daily_dataset(tmp_path / "ds", 10)
    repo = FileSystemStateRepository(str(tmp_path / "cache"))

    def query():
        source = Table.scan_parquet_dataset(str(tmp_path / "ds"))
        return WindowQuery(
            source, _ANALYZERS, repository=repo, dataset="t"
        ), source

    return tmp_path, repo, query


class TestWindowQueryEndToEnd:
    def test_rejects_grouping_and_non_scan_shareable(self, daily):
        _, repo, query = daily
        q, source = query()
        with pytest.raises(ValueError, match="scan-shareable"):
            WindowQuery(
                source, [CountDistinct(["g"])], repository=repo, dataset="t"
            )
        with pytest.raises(ValueError, match="at least one analyzer"):
            WindowQuery(source, [], repository=repo, dataset="t")
        assert len(q.analyzers) == len(_ANALYZERS)

    def test_cold_plan_reports_dq323_and_rescans(self, daily):
        _, _, query = daily
        q, _ = query()
        plan = q.plan(Sliding(7))
        assert plan.segment_hits == 0
        assert len(plan.partitions_rescanned) == 7
        assert plan.predicted_scan_bytes > 0
        [diag] = plan.diagnostics
        assert diag.code == "DQ323"
        # the caret line underlines the spec text
        rendered = diag.render()
        assert "sliding(7" in rendered and "^" in rendered

    def test_cold_then_warm_bit_identical_with_zero_rows(self, daily):
        _, _, query = daily
        q, source = query()
        cold = q.run(Sliding(7))
        assert [d.code for d in cold.validation_warnings] == ["DQ323"]

        q2, source = query()
        warm = q2.run(Sliding(7), tracing=True)
        plan = warm.window_plan
        assert plan.segment_hits == plan.segments_merged > 0
        assert plan.partitions_rescanned == ()
        assert warm.validation_warnings == []
        counters = warm.run_trace.counters
        assert counters.get("partitions_scanned", 0) == 0
        assert counters["window.segment_hits"] == counters["window.spans"]
        assert counters["window.partitions"] == 7

        parts = source.partitions()
        frame = Sliding(7).resolve(q2.timeline())
        rescan = AnalysisRunner.do_analysis_run(
            source.subset([parts[i].path for i in frame.indices]), _ANALYZERS
        )
        assert _snapshot(warm) == _snapshot(cold) == _snapshot(rescan)

    def test_late_partition_invalidates_o_log_n_spans(self, daily):
        tmp_path, _, query = daily
        q, _ = query()
        q.run(Sliding(7))  # publish covers for days 0..9

        # day 10 arrives late
        day = DAY0 + datetime.timedelta(days=10)
        path = tmp_path / "ds" / f"part-{day.isoformat()}.parquet"
        _daily_table(np.random.default_rng(99)).to_parquet(
            str(path), row_group_size=128
        )

        q2, _ = query()
        plan = q2.plan(Sliding(7))
        n = len(plan.frame.indices)
        # only the spans covering the new day miss; the rest still hit
        assert 1 <= plan.segment_misses <= max(1, 2 * n.bit_length())
        assert plan.partitions_rescanned == (path.name,)
        ctx = q2.run(Sliding(7), tracing=True)
        assert ctx.run_trace.counters.get("partitions_scanned", 0) == 1

    def test_restated_partition_self_invalidates(self, daily):
        tmp_path, _, query = daily
        q, _ = query()
        q.run(Sliding(7))
        day = DAY0 + datetime.timedelta(days=8)
        path = tmp_path / "ds" / f"part-{day.isoformat()}.parquet"
        _daily_table(np.random.default_rng(1234), n=300).to_parquet(
            str(path), row_group_size=128
        )
        q2, source = query()
        plan = q2.plan(Sliding(7))
        assert plan.partitions_rescanned == (path.name,)
        ctx = q2.run(Sliding(7))
        parts = source.partitions()
        frame = Sliding(7).resolve(q2.timeline())
        rescan = AnalysisRunner.do_analysis_run(
            source.subset([parts[i].path for i in frame.indices]), _ANALYZERS
        )
        assert _snapshot(ctx) == _snapshot(rescan)

    def test_corrupt_segment_degrades_and_rebuilds(self, daily):
        tmp_path, _, query = daily
        q, _ = query()
        baseline = _snapshot(q.run(Sliding(7)))
        seg_files = glob.glob(
            str(tmp_path / "cache" / "**" / "*seg-L*"), recursive=True
        )
        assert seg_files
        with open(seg_files[0], "r+b") as fh:
            fh.seek(10)
            fh.write(b"\xde\xad\xbe\xef")
        q2, _ = query()
        with pytest.warns(RuntimeWarning, match="DQ323"):
            again = q2.run(Sliding(7))
        assert _snapshot(again) == baseline
        # the rewrite healed the store: clean warm pass now
        q3, _ = query()
        with warnings.catch_warnings():
            warnings.simplefilter("error")
            healed = q3.run(Sliding(7))
        assert _snapshot(healed) == baseline

    def test_states_returns_a_signed_bag(self, daily):
        _, _, query = daily
        q, _ = query()
        bag = q.states(LastN(5, unit="partitions"))
        assert len(bag) == len(_ANALYZERS)
        assert bag.signature == q.signature()
        assert bag.label
        mean_state = bag.get(Mean("x"))
        assert mean_state is not None
        assert math.isfinite(mean_state.metric_value())

    def test_admission_cost_carries_window_fields(self, daily):
        _, _, query = daily
        q, _ = query()
        q.run(Sliding(7))  # warm the covers
        q2, _ = query()
        cost = q2.admission_cost(Sliding(7))
        assert cost.window_spec.startswith("sliding(7")
        assert cost.window_segments_merged > 0
        assert cost.window_partitions_rescanned == 0
        assert cost.saved_window_bytes > 0
        assert cost.predicted_scan_bytes == 0


# ---------------------------------------------------------------------------
# EXPLAIN + drift pins over a window cost
# ---------------------------------------------------------------------------


class TestWindowExplainAndPins:
    def test_explain_renders_the_windows_line(self, daily):
        from deequ_tpu.lint.explain import render_explain

        _, _, query = daily
        q, _ = query()
        q.run(Sliding(7))
        q2, _ = query()
        cost = q2.admission_cost(Sliding(7))
        text = render_explain(cost, diagnostics=[])
        assert "windows:" in text
        assert "sliding(7" in text
        assert "segment merges" in text

    def test_cost_drift_pins_window_counters(self, daily):
        from deequ_tpu.lint.cost import cost_drift

        _, _, query = daily
        q, _ = query()
        q.run(Sliding(7))
        q2, _ = query()
        cost = q2.admission_cost(Sliding(7))
        ctx = q2.run(Sliding(7), tracing=True)
        drift = cost_drift(cost, ctx.run_trace)
        assert drift["drift.window_segments_merged"] == 0.0
        assert drift["drift.window_partitions_rescanned"] == 0.0


# ---------------------------------------------------------------------------
# service integration: submit_window
# ---------------------------------------------------------------------------


class TestServiceSubmitWindow:
    def test_submit_window_happy_path(self, daily):
        from deequ_tpu.service.service import DQService

        tmp_path, repo, _ = daily
        source = Table.scan_parquet_dataset(str(tmp_path / "ds"))
        with DQService(workers=1, state_repository=repo) as svc:
            handle = svc.submit_window(
                "tenant-a",
                "t",
                source,
                window=Sliding(7),
                analyzers=_ANALYZERS,
            )
            assert handle.wait(120)
            assert handle.status == "done", (handle.reason, handle.error)
            plan = handle.result.window_plan
            assert plan.segments_merged > 0
        # second submission is warm: interactive tier, zero rescans
        with DQService(workers=1, state_repository=repo) as svc:
            handle = svc.submit_window(
                "tenant-a",
                "t",
                source,
                window=Sliding(7),
                analyzers=_ANALYZERS,
            )
            assert handle.wait(120)
            assert handle.status == "done", (handle.reason, handle.error)
            assert handle.result.window_plan.partitions_rescanned == ()

    def test_submit_window_requires_a_repository(self, daily):
        from deequ_tpu.service.codes import DQ_REJECTED
        from deequ_tpu.service.service import DQService

        tmp_path, _, _ = daily
        source = Table.scan_parquet_dataset(str(tmp_path / "ds"))
        with DQService(workers=1) as svc:
            handle = svc.submit_window(
                "tenant-a",
                "t",
                source,
                window=Sliding(7),
                analyzers=_ANALYZERS,
            )
            assert handle.status == "rejected"
            assert handle.code == DQ_REJECTED


# ---------------------------------------------------------------------------
# telemetry: the window series the sentinel watches
# ---------------------------------------------------------------------------


class TestWindowTelemetry:
    def test_segment_hit_ratio_derived_from_trace(self, daily):
        from deequ_tpu.observe.telemetry import engine_metric_record

        _, _, query = daily
        q, _ = query()
        q.run(Sliding(7))
        q2, _ = query()
        ctx = q2.run(Sliding(7), tracing=True)
        rec = engine_metric_record(ctx.run_trace, None)
        assert rec["engine.window.segment_hit_ratio"] == 1.0

    def test_record_window_run_flattens_drift(self, daily):
        from deequ_tpu.checks import CheckLevel, DriftCheck
        from deequ_tpu.repository import InMemoryMetricsRepository
        from deequ_tpu.repository.engine import (
            engine_series,
            record_window_run,
        )

        _, _, query = daily
        q, _ = query()
        ctx = q.run(Sliding(7), tracing=True)
        timeline = q.timeline()
        current = Sliding(5).resolve(timeline)
        baseline = current.shifted(5, timeline)
        check = DriftCheck(CheckLevel.ERROR, "wow").has_no_mean_drift(
            "x", max_relative_delta=0.5
        )
        result = check.evaluate(
            current=q.states(current), baseline=q.states(baseline)
        )
        repo = InMemoryMetricsRepository()
        record_window_run(
            repo,
            ctx.run_trace,
            drift_result=result,
            suite="windows",
            dataset="t",
        )
        [pt] = engine_series(repo, "engine.drift.failed_constraints")
        assert pt.metric_value == 0.0
        [pt] = engine_series(repo, "engine.drift.value_max")
        assert 0.0 <= pt.metric_value < 0.5
        [pt] = engine_series(repo, "engine.window.segment_hit_ratio")
        assert 0.0 <= pt.metric_value <= 1.0

"""Decode-to-wire fusion (ISSUE 9): Arrow buffers straight to packed
device wire, skipping the Column intermediate.

Four layers are pinned here:
  - the wire kernels: MSB bitpacking at non-multiple-of-8 row offsets
    against the np.packbits reference, the one-pass NaN fold, f32
    shift parity with `pack_batch_inputs`, and the narrowed-int
    overflow -> None fallback contract;
  - the decoder: `decode_wire_column` bit-identity of wire rows and
    the WireStubColumn's lazy `.values`/`.valid` accessors against the
    ordinary decode, across sliced odd-offset and multi-chunk inputs;
  - the planner: `classify_wire_columns` eligibility and per-column
    fall-off reasons (with the offending consumer key), static
    narrow-int pinning from type bounds and file statistics;
  - observability: the EXPLAIN `wire:` line, DQ313, the zero-drift
    pin on wire_fused_cols, the `engine.wire_fused_ratio` telemetry
    derivation, and the sentinel's watch list.

The end-to-end fusion-on/off differential fuzz lives in
tests/test_suite_differential_fuzz.py.
"""

from __future__ import annotations

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from deequ_tpu.data.source import ParquetSource
from deequ_tpu.ops import native, runtime

pytestmark = pytest.mark.skipif(
    not native.available(), reason="no C compiler for the native kernels"
)


def _validity_addr(arr):
    bufs = arr.buffers()
    if arr.null_count == 0 or bufs[0] is None:
        return None
    return bufs[0].address


def _expand(bits, n):
    return np.unpackbits(bits, count=n).astype(np.bool_)


class TestWireValidBits:
    @pytest.mark.parametrize("out_off", [0, 1, 3, 7, 9, 13])
    def test_packs_msb_first_at_odd_offsets(self, out_off):
        # rows continue mid-byte in the shared bitmask exactly where the
        # previous chunk stopped — the np.packbits reference is what
        # pack_batch_inputs would have produced for the same mask
        vals = [None if i % 3 == 0 else float(i) for i in range(21)]
        arr = pa.array(vals, type=pa.float64())
        out = np.zeros(8, dtype=np.uint8)
        invalid = native.wire_valid_bits(
            _validity_addr(arr), arr.offset, len(arr), out, out_off
        )
        mask = np.zeros(64, dtype=np.uint8)
        mask[out_off : out_off + 21] = [v is not None for v in vals]
        assert np.array_equal(out, np.packbits(mask)), out_off
        assert invalid == sum(v is None for v in vals)

    def test_sliced_odd_offset_input(self):
        base = pa.array(
            [None if i % 5 == 0 else float(i) for i in range(40)],
            type=pa.float64(),
        )
        arr = base.slice(3, 29)  # bit_offset 3 into the validity bitmap
        out = np.zeros(8, dtype=np.uint8)
        invalid = native.wire_valid_bits(
            _validity_addr(arr), arr.offset, len(arr), out, 0
        )
        ref = np.zeros(64, dtype=np.uint8)
        ref[:29] = [(i + 3) % 5 != 0 for i in range(29)]
        assert np.array_equal(out, np.packbits(ref))
        assert invalid == int(29 - ref.sum())

    def test_null_free_chunk_sets_every_bit(self):
        arr = pa.array([1.0, 2.0, 3.0], type=pa.float64())
        out = np.zeros(2, dtype=np.uint8)
        invalid = native.wire_valid_bits(None, 0, 3, out, 5)
        ref = np.zeros(16, dtype=np.uint8)
        ref[5:8] = 1
        assert np.array_equal(out, np.packbits(ref))
        assert invalid == 0


class TestWirePrimitive:
    def test_f64_nan_folds_into_bits_and_zero(self):
        vals = [1.5, None, float("nan"), -4.0, 0.25]
        arr = pa.array(vals, type=pa.float64())
        out_vals = np.zeros(8, dtype=np.float64)
        out_bits = np.zeros(1, dtype=np.uint8)
        invalid = native.wire_primitive(
            "double",
            arr.buffers()[1].address,
            _validity_addr(arr),
            arr.offset,
            len(arr),
            0.0,
            out_vals,
            out_bits,
            0,
        )
        assert invalid == 2  # the null AND the NaN
        assert np.array_equal(out_vals[:5], [1.5, 0.0, 0.0, -4.0, 0.25])
        assert np.array_equal(
            _expand(out_bits, 5), [True, False, False, True, True]
        )

    def test_f32_shift_parity_with_pack(self):
        # the wire kernel computes (float)((double)v - shift); the pack
        # path subtracts the shift in f64 then astypes — bit-identical
        rng = np.random.default_rng(5)
        raw = rng.normal(1.0e6, 3.0, 64)
        raw[7] = np.nan
        arr = pa.array(raw, type=pa.float64())
        shift = float(raw[0])
        out_vals = np.zeros(64, dtype=np.float32)
        out_bits = np.zeros(8, dtype=np.uint8)
        rc = native.wire_primitive(
            "double",
            arr.buffers()[1].address,
            _validity_addr(arr),
            arr.offset,
            len(arr),
            shift,
            out_vals,
            out_bits,
            0,
        )
        assert rc == 1
        folded = np.where(np.isnan(raw), 0.0, raw)
        ref = (folded - shift).astype(np.float32)
        assert out_vals.tobytes() == ref.tobytes()

    @pytest.mark.parametrize(
        "out_dtype,fits",
        [("int8", 127), ("int16", 32767), ("int32", 2**31 - 1)],
    )
    def test_narrowed_int_exact_and_overflow_none(self, out_dtype, fits):
        ok = pa.array([0, 1, -(fits // 2), fits, None], type=pa.int64())
        out_vals = np.zeros(8, dtype=np.dtype(out_dtype))
        out_bits = np.zeros(1, dtype=np.uint8)
        rc = native.wire_primitive(
            "int64",
            ok.buffers()[1].address,
            _validity_addr(ok),
            ok.offset,
            len(ok),
            0.0,
            out_vals,
            out_bits,
            0,
        )
        assert rc == 1
        assert np.array_equal(out_vals[:5], [0, 1, -(fits // 2), fits, 0])

        # one row past the pinned width: the kernel refuses the whole
        # chunk (rc < 0 -> wrapper None) and the caller falls back
        over = pa.array([0, fits + 1], type=pa.int64())
        rc = native.wire_primitive(
            "int64",
            over.buffers()[1].address,
            None,
            0,
            len(over),
            0.0,
            np.zeros(8, dtype=np.dtype(out_dtype)),
            None,
            0,
        )
        assert rc is None

    def test_int_to_f64_value_row(self):
        arr = pa.array([5, None, -9], type=pa.int32())
        out_vals = np.zeros(8, dtype=np.float64)
        rc = native.wire_primitive(
            "int32",
            arr.buffers()[1].address,
            _validity_addr(arr),
            arr.offset,
            len(arr),
            0.0,
            out_vals,
            None,
            0,
        )
        assert rc == 1
        assert np.array_equal(out_vals[:3], [5.0, 0.0, -9.0])

    def test_unsupported_pair_returns_none(self):
        assert not native.wire_supported("uint64", "float64")
        assert native.wire_supported("double", "float32")
        assert native.wire_supported("int64", "int8")


def _wire_plan(specs, batch_size=256):
    return runtime.WireFusionPlan(specs, batch_size)


def _spec(**kw):
    base = dict(
        column="x",
        token="double",
        want_value=True,
        want_valid=True,
        value_kind="val",
        value_dtype="float64",
        needs_shift=False,
        desc="f64",
    )
    base.update(kw)
    return runtime.ColumnWireSpec(**base)


class TestDecodeWireColumn:
    def test_multi_chunk_odd_lengths_cross_byte_boundaries(self):
        from deequ_tpu.data.arrow_decode import decode_wire_column

        rng = np.random.default_rng(9)
        parts = []
        for m in (13, 7, 11):  # chunk ends off every byte boundary
            vals = rng.normal(0, 1, m)
            vals[0] = np.nan
            parts.append(
                pa.array(
                    [None if i % 4 == 2 else v for i, v in enumerate(vals)],
                    type=pa.float64(),
                )
            )
        chunks = [parts[0], parts[1].slice(1, 5), parts[2]]
        t = pa.table({"x": pa.chunked_array(chunks)})
        spec = _spec()
        wire = _wire_plan({"x": spec})
        out = decode_wire_column("x", chunks, t, spec, wire)
        assert out is not None
        stub, rows = out
        n = sum(len(c) for c in chunks)

        # reference: null/NaN fold over the very same chunks
        raw = np.concatenate(
            [
                np.asarray(c.to_numpy(zero_copy_only=False), dtype=np.float64)
                for c in chunks
            ]
        )
        present = np.concatenate([np.asarray(c.is_valid()) for c in chunks])
        ref_valid = present & ~np.isnan(np.where(present, raw, 0.0))
        ref_vals = np.where(ref_valid, raw, 0.0)
        num = rows["num:x"]
        assert np.array_equal(num.arr[:n], ref_vals)
        bits = rows["valid:x"]
        assert np.array_equal(_expand(bits.arr, n), ref_valid)
        # pad tail stays zero (the OFF path's zeroed group buffer)
        tail = _expand(bits.arr, len(bits.arr) * 8)[n:]
        assert not tail.any()

        # the stub's lazy accessors rebuild bit-identical host data
        assert len(stub) == n
        assert np.array_equal(np.asarray(stub.valid), ref_valid)
        assert np.array_equal(
            np.asarray(stub.values), np.where(ref_valid, ref_vals, 0.0)
        )

    def test_shift_unavailable_falls_back_this_batch(self):
        from deequ_tpu.data.arrow_decode import decode_wire_column

        arr = pa.array([1.0, 2.0], type=pa.float64())
        t = pa.table({"x": arr})
        spec = _spec(value_dtype="float32", needs_shift=True, desc="f32+shift")
        wire = _wire_plan({"x": spec})
        assert decode_wire_column("x", [arr], t, spec, wire) is None

        wire.publish_shifts({"num:x": 1.0})
        out = decode_wire_column("x", [arr], t, spec, wire)
        assert out is not None
        _, rows = out
        assert rows["num:x"].shift == 1.0
        assert np.array_equal(rows["num:x"].arr[:2], [0.0, 1.0])

        wire2 = _wire_plan({"x": spec})
        wire2.abandon_shifts()
        assert decode_wire_column("x", [arr], t, spec, wire2) is None

    def test_narrow_overflow_falls_back_this_batch(self):
        from deequ_tpu.data.arrow_decode import decode_wire_column

        arr = pa.array([1, 2, 300], type=pa.int64())
        t = pa.table({"i": arr})
        spec = _spec(
            column="i", token="int64", value_kind="ival", value_dtype="int8",
            desc="i8",
        )
        wire = _wire_plan({"i": spec})
        assert decode_wire_column("i", [arr], t, spec, wire) is None

    def test_valid_only_bool_column(self):
        from deequ_tpu.data.arrow_decode import decode_wire_column

        arr = pa.array([True, None, False, True, None])
        t = pa.table({"b": arr})
        spec = _spec(
            column="b", token="bool", want_value=False, value_kind="",
            value_dtype="", desc="bits",
        )
        wire = _wire_plan({"b": spec})
        out = decode_wire_column("b", [arr], t, spec, wire)
        assert out is not None
        _, rows = out
        assert set(rows) == {"valid:b"}
        assert np.array_equal(
            _expand(rows["valid:b"].arr, 5), [True, False, True, True, False]
        )
        assert not rows["valid:b"].all_valid


class TestClassifier:
    def _specs(self, keys):
        from deequ_tpu.analyzers.base import InputSpec

        out = {}
        for key in keys:
            col = key.split(":", 1)[1]
            out[key] = InputSpec(key=key, build=None, columns=(col,))
        return out

    def test_packed_only_columns_fuse(self):
        from deequ_tpu.ops.fused import classify_wire_columns

        specs = self._specs(["num:x", "valid:x", "valid:b"])
        wire, falloffs = classify_wire_columns(
            {"x": "double", "b": "bool"},
            specs,
            {"num:x", "valid:x", "valid:b"},
            "float64",
        )
        assert set(wire) == {"x", "b"}
        assert wire["x"].value_kind == "val"
        assert wire["x"].value_dtype == "float64"
        assert not wire["x"].needs_shift
        assert not wire["b"].want_value
        assert falloffs == []

    def test_f32_wire_needs_shift(self):
        from deequ_tpu.ops.fused import classify_wire_columns

        specs = self._specs(["num:x"])
        wire, _ = classify_wire_columns(
            {"x": "double"}, specs, {"num:x"}, "float32"
        )
        assert wire["x"].needs_shift
        assert wire["x"].value_dtype == "float32"

    def test_off_wire_consumer_names_offending_key(self):
        from deequ_tpu.ops.fused import classify_wire_columns

        specs = self._specs(["num:x", "valid:x"])
        wire, falloffs = classify_wire_columns(
            {"x": "double"}, specs, {"valid:x"}, "float64"
        )
        assert wire == {}
        (col, reason, key) = falloffs[0]
        assert col == "x" and key == "num:x" and "off-wire" in reason

    def test_non_pack_consumer_names_offending_key(self):
        from deequ_tpu.ops.fused import classify_wire_columns

        specs = self._specs(["num:x", "raw:x"])
        _, falloffs = classify_wire_columns(
            {"x": "double"}, specs, {"num:x", "raw:x"}, "float64"
        )
        (col, reason, key) = falloffs[0]
        assert col == "x" and key == "raw:x"

    def test_uint64_and_bool_values_fall_off(self):
        from deequ_tpu.ops.fused import classify_wire_columns

        specs = self._specs(["num:u", "num:b", "valid:b"])
        wire, falloffs = classify_wire_columns(
            {"u": "uint64", "b": "bool"},
            specs,
            {"num:u", "num:b", "valid:b"},
            "float64",
        )
        assert wire == {}
        reasons = {c: r for c, r, _ in falloffs}
        assert "uint64" in reasons["u"]
        assert "astype" in reasons["b"]

    def test_int_pinning_from_bounds_and_type(self):
        from deequ_tpu.ops.fused import (
            _pin_int_wire_width,
            classify_wire_columns,
        )

        assert _pin_int_wire_width("int64", None) is None  # full range
        assert _pin_int_wire_width("int64", (0, 100)) == "int8"
        assert _pin_int_wire_width("int64", (-200, 300)) == "int16"
        assert _pin_int_wire_width("int64", (5, 10)) == "int8"  # widens to 0
        assert _pin_int_wire_width("int16", None) == "int16"  # type bounds
        assert _pin_int_wire_width("uint32", None) is None

        specs = self._specs(["num:i"])
        wire, _ = classify_wire_columns(
            {"i": "int64"}, specs, {"num:i"}, "float64",
            int_bounds={"i": (0, 90)},
        )
        assert wire["i"].value_kind == "ival"
        assert wire["i"].value_dtype == "int8"
        wire, _ = classify_wire_columns(
            {"i": "int64"}, specs, {"num:i"}, "float64"
        )
        assert wire["i"].value_kind == "val"
        assert wire["i"].value_dtype == "float64"


def _write_numeric_parquet(tmp_path, n=6000, row_group=700):
    rng = np.random.default_rng(21)
    x = rng.normal(50.0, 4.0, n)
    x[::61] = np.nan
    t = pa.table(
        {
            "x": pa.array(x, type=pa.float64()),
            "i": pa.array(rng.integers(-100, 120, n), type=pa.int64()),
            "b": pa.array(rng.random(n) > 0.4),
            "s": pa.array(["k%d" % (k % 30) for k in range(n)]),
        }
    )
    path = str(tmp_path / "wire.parquet")
    pq.write_table(t, path, row_group_size=row_group)
    return path


def _analyzers():
    from deequ_tpu.analyzers import Completeness, Mean, StandardDeviation

    return [
        Mean("x"),
        StandardDeviation("x"),
        Completeness("x"),
        Mean("i"),
        Completeness("b"),
        Completeness("s"),
    ]


class TestEndToEnd:
    def test_fusion_engages_and_shift_handshake_converges(
        self, tmp_path, monkeypatch
    ):
        from deequ_tpu import observe
        from deequ_tpu.runners import AnalysisRunner

        monkeypatch.setenv("DEEQU_TPU_PLACEMENT", "device")
        monkeypatch.setenv("DEEQU_TPU_DECODE_WORKERS", "1")
        # pin the ARROW decode route: this test watches the per-batch
        # arrow_decode wire_fuse counts and the sticky-shift handshake,
        # which the native parquet reader (ISSUE 11) replaces with
        # assemble_wire_column — engagement there is pinned by the
        # wire fuzz differential's cols_wire_fused check instead
        monkeypatch.setenv("DEEQU_TPU_NATIVE_READER", "0")
        path = _write_numeric_parquet(tmp_path)
        with observe.tracing() as tracer:
            AnalysisRunner().on_data(
                ParquetSource(path, batch_rows=1400)
            ).add_analyzers(_analyzers()).run()

        def spans(root):
            stack = [root]
            while stack:
                sp = stack.pop()
                yield sp
                stack.extend(sp.children)

        decodes = [
            sp
            for root in tracer.roots
            for sp in spans(root)
            if sp.name == "arrow_decode" and "wire_fuse" in sp.attrs
        ]
        assert decodes, "no arrow_decode span carried the wire_fuse attr"
        fused_counts = [sp.attrs["wire_fuse"] for sp in decodes]
        # every batch fuses at least the valid-only bool column; once
        # the pack publishes the sticky shifts (f32 wire) or from batch
        # 0 outright (f64 wire), all three numeric columns fuse
        assert max(fused_counts) == 3, fused_counts
        assert min(fused_counts) >= 1, fused_counts
        assert tracer.counters["wire_fused_cols"] == 3
        assert tracer.counters["wire_cols_total"] == 4

    def test_kill_switch_disables_fusion(self, tmp_path, monkeypatch):
        from deequ_tpu import observe
        from deequ_tpu.runners import AnalysisRunner

        monkeypatch.setenv("DEEQU_TPU_PLACEMENT", "device")
        monkeypatch.setenv("DEEQU_TPU_WIRE_FUSED", "0")
        path = _write_numeric_parquet(tmp_path)
        with observe.tracing() as tracer:
            AnalysisRunner().on_data(
                ParquetSource(path, batch_rows=1400)
            ).add_analyzers(_analyzers()).run()
        assert tracer.counters.get("wire_fused_cols", 0) == 0
        assert tracer.counters["wire_cols_total"] == 4

    def test_explain_pins_to_trace_with_zero_drift(self, tmp_path, monkeypatch):
        from deequ_tpu.lint.cost import cost_drift
        from deequ_tpu.lint.explain import explain_plan
        from deequ_tpu.observe.runtrace import traced_run
        from deequ_tpu.runners import AnalysisRunner

        monkeypatch.setenv("DEEQU_TPU_PLACEMENT", "device")
        path = _write_numeric_parquet(tmp_path)
        analyzers = _analyzers()
        res = explain_plan(ParquetSource(path, batch_rows=1400), analyzers)
        scan = res.cost.scan_pass
        assert scan.wire_fused_cols == 3
        assert scan.saved_pack_bytes and scan.saved_pack_bytes > 0
        rendered = res.render()
        assert "wire: 3/4 column(s) fused at decode" in rendered

        with traced_run("t", enable=True) as handle:
            AnalysisRunner().on_data(
                ParquetSource(path, batch_rows=1400)
            ).add_analyzers(analyzers).run()
        drift = cost_drift(res.cost, handle.trace)
        assert drift["drift.wire_fused_cols"] == 0.0

    def test_dq313_carets_offending_consumer_key(self, tmp_path, monkeypatch):
        from deequ_tpu.analyzers import ApproxQuantile, Mean
        from deequ_tpu.lint.explain import explain_plan

        monkeypatch.setenv("DEEQU_TPU_PLACEMENT", "device")
        path = _write_numeric_parquet(tmp_path)
        res = explain_plan(
            ParquetSource(path, batch_rows=1400),
            [Mean("x"), ApproxQuantile("x", 0.5), Mean("i")],
        )
        d313 = [d for d in res.diagnostics if d.code == "DQ313"]
        assert d313, "assisted re-read produced no DQ313"
        assert any(d.source == "num:x" and d.span == (0, 5) for d in d313)

    def test_telemetry_ratio_and_sentinel_watch(self, tmp_path, monkeypatch):
        from deequ_tpu.observe.runtrace import traced_run
        from deequ_tpu.observe.telemetry import engine_metric_record
        from deequ_tpu.runners import AnalysisRunner

        monkeypatch.setenv("DEEQU_TPU_PLACEMENT", "device")
        path = _write_numeric_parquet(tmp_path)
        with traced_run("t", enable=True) as handle:
            AnalysisRunner().on_data(
                ParquetSource(path, batch_rows=1400)
            ).add_analyzers(_analyzers()).run()
        rec = engine_metric_record(handle.trace)
        assert rec["engine.wire_fused_ratio"] == 0.75

        import importlib.util
        import os

        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        spec = importlib.util.spec_from_file_location(
            "sentinel", os.path.join(repo, "tools", "sentinel.py")
        )
        sentinel = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(sentinel)
        watched = dict(sentinel.WATCHED_SERIES)
        assert watched.get("engine.wire_fused_ratio") == "down"

"""bench-mesh: sharded streaming scan scaling curve (ISSUE 15).

Measures the cold pass over a partitioned dataset at 1, 2, and 4
processes, each process a REAL interpreter running
`parallel.run_sharded_analysis` over its rendezvous-assigned partition
range, exchanging DQST state envelopes through a file allgather (the
loopback stand-in for `process_allgather` — same byte streams, same
merge path).

The scan is made IO-latency-bound with the object-store stall model
(`DEEQU_TPU_SOURCE_STALL_MS`, the same knob bench-reader uses): every
row-group read pays a fixed remote-GET wait on the decoding thread.
That is the regime the sharded scan exists for — the 1B-row cold pass
is object-store-bound, not CPU-bound — and it is the only regime a
single-core CI box can measure honestly: N processes genuinely overlap
N stalls, so the curve reflects the real deployment shape instead of
timeslicing one CPU. Methodology: BENCH.md round 15.

Aborts unless (a) every process at every mesh size reports metrics
bit-identical to the solo pass, (b) 4 processes reach >= 3x the
1-process wall, and (c) per-process throughput at 4 stays within 15%
of solo. Refreshes BENCH_MESH.json.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import textwrap
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from deequ_tpu.parallel.procspawn import WorkerFailure, run_worker_processes

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

ROWS = int(os.environ.get("BENCH_MESH_ROWS", "128000"))
N_PARTS = int(os.environ.get("BENCH_MESH_PARTS", "64"))
STALL_MS = int(os.environ.get("BENCH_MESH_STALL_MS", "150"))
# two row groups per partition: rows/partition/2 when unset
ROW_GROUP = int(os.environ.get("BENCH_MESH_ROW_GROUP", "0")) or (
    ROWS // N_PARTS // 2
)
# filename salt pinned so the deterministic rendezvous split of the
# seeded dataset is balanced at every mesh size in the curve
# (32/32 at N=2, 15/17/15/17 at N=4) — the fingerprint hashes the
# name, so this is part of the dataset definition, not a runtime knob
NAME_SALT = "0063"
MESHES = (1, 2, 4)

WORKER = textwrap.dedent(
    """
    import json, os, sys, time

    os.environ["JAX_PLATFORMS"] = "cpu"
    rank, _port, tmpdir = int(sys.argv[1]), sys.argv[2], sys.argv[3]
    data_dir, n_shards, stall_ms = sys.argv[4], int(sys.argv[5]), sys.argv[6]
    os.environ["DEEQU_TPU_SHARD"] = str(rank)
    # one decode lane per process: the deployment shape this bench
    # models is one process per core, scaled ACROSS processes — extra
    # in-process decode workers would let a single process hide stalls
    # behind concurrency the 1-core-per-process budget doesn't have
    os.environ["DEEQU_TPU_DECODE_WORKERS"] = "1"

    from deequ_tpu.analyzers.scan import (
        Completeness, Maximum, Mean, Minimum, StandardDeviation, Sum,
    )
    from deequ_tpu.data.source import PartitionedParquetSource
    from deequ_tpu.parallel import run_sharded_analysis

    _round = [0]
    _gather_entry = [0.0]

    def gather(payload):
        _gather_entry[0] = time.monotonic()
        r = _round[0]
        _round[0] += 1
        gdir = os.path.join(tmpdir, f"gather-{r}")
        os.makedirs(gdir, exist_ok=True)
        tmp = os.path.join(gdir, f"{rank}.tmp")
        with open(tmp, "wb") as f:
            f.write(payload)
        os.replace(tmp, os.path.join(gdir, f"{rank}.bin"))
        out = []
        for i in range(n_shards):
            p = os.path.join(gdir, f"{i}.bin")
            deadline = time.time() + 300
            while not os.path.exists(p):
                if time.time() > deadline:
                    raise TimeoutError(f"peer {i} missing in round {r}")
                time.sleep(0.01)
            with open(p, "rb") as f:
                out.append(f.read())
        return out

    src = PartitionedParquetSource(
        sorted(
            os.path.join(data_dir, f)
            for f in os.listdir(data_dir)
            if f.endswith(".parquet")
        )
    )
    analyzers = [
        Mean("x0"), Sum("x0"), Minimum("x0"), Maximum("x0"),
        StandardDeviation("x1"), Completeness("x1"),
        Mean("x2"), Sum("x3"),
    ]
    # Warmup pass: same shard assignment, same jit compilations, stall
    # knob off.  Interpreter spawn + jax tracing otherwise land inside
    # one worker's timed window and, on a shared box, inside everyone's
    # gather wait.  The cold pass being modelled is IO-cold, not
    # process-cold.
    warm = run_sharded_analysis(
        src, analyzers, shard=rank, num_shards=n_shards, gather=gather
    )

    # Start barrier: nobody starts the clock until every rank is warm.
    open(os.path.join(tmpdir, f"warm-{rank}"), "w").close()
    deadline = time.time() + 300
    while any(
        not os.path.exists(os.path.join(tmpdir, f"warm-{i}"))
        for i in range(n_shards)
    ):
        if time.time() > deadline:
            raise TimeoutError("peers never finished warmup")
        time.sleep(0.01)

    os.environ["DEEQU_TPU_SOURCE_STALL_MS"] = stall_ms
    t0 = time.monotonic()
    ctx = run_sharded_analysis(
        src, analyzers, shard=rank, num_shards=n_shards, gather=gather
    )
    wall = time.monotonic() - t0
    # scan phase only: t0 -> this shard ENTERING the allgather.  After
    # that it is waiting on the straggler shard, which is barrier time,
    # not this process being slow — per-process throughput is judged on
    # the scan.
    scan_wall = _gather_entry[0] - t0
    metrics = {repr(a): ctx.metric_map[a].value.get() for a in analyzers}
    for a in analyzers:
        assert warm.metric_map[a].value.get() == metrics[repr(a)]

    # this shard's own scan volume, so the driver can judge per-process
    # throughput honestly under rendezvous skew (a bigger shard takes
    # longer BECAUSE it scans more rows, not because it is slower)
    import pyarrow.parquet as pq
    from deequ_tpu.parallel import plan_shards

    mine = plan_shards(src.partitions(), n_shards).assignment(rank)
    rows_local = sum(
        pq.ParquetFile(p).metadata.num_rows for p in mine.paths
    )
    out = {
        "wall_s": wall,
        "scan_wall_s": scan_wall,
        "rows_local": rows_local,
        "metrics": metrics,
    }
    print("RESULT:" + json.dumps(out), flush=True)
    """
)


def write_dataset(root: str) -> None:
    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq

    rng = np.random.default_rng(15)
    per = ROWS // N_PARTS
    for i in range(N_PARTS):
        cols = {}
        for c in range(4):
            x = rng.normal(c + 1.0, 2.0, per)
            x[:: 11 + c] = np.nan
            cols[f"x{c}"] = pa.array(x, mask=np.isnan(x))
        pq.write_table(
            pa.table(cols),
            os.path.join(root, f"part-{NAME_SALT}-{i:03d}.parquet"),
            row_group_size=ROW_GROUP,
        )


def main() -> int:
    out_path = os.path.join(REPO_ROOT, "BENCH_MESH.json")
    with tempfile.TemporaryDirectory() as data_dir:
        print(
            f"bench-mesh: {ROWS} rows x 4 cols in {N_PARTS} partitions, "
            f"{STALL_MS}ms object-store stall per row-group read",
            flush=True,
        )
        write_dataset(data_dir)

        runs = []
        baseline_metrics = None
        for n in MESHES:
            t0 = time.monotonic()
            try:
                results = run_worker_processes(
                    WORKER,
                    n,
                    extra_args=[data_dir, str(n), str(STALL_MS)],
                    timeout=900.0,
                )
            except WorkerFailure as e:
                print(f"bench-mesh: {n}-process run failed: {e}")
                return 1
            spawn_wall = time.monotonic() - t0
            # the scan wall is what scales; interpreter/jax startup is
            # spawn overhead, reported separately
            wall = max(r["wall_s"] for r in results)
            for r in results:
                if baseline_metrics is None:
                    baseline_metrics = r["metrics"]
                if r["metrics"] != baseline_metrics:
                    print(
                        f"bench-mesh: BIT-IDENTITY VIOLATION at {n} "
                        "processes — aborting, no artifact written"
                    )
                    return 1
            # per-process throughput over the rows THAT process scanned,
            # during its scan phase: rendezvous skew makes shards
            # unequal, so rows/N would misread a big shard's longer wall
            # as a slowdown, and a small shard's gather wait for the
            # straggler is barrier time, not scan time
            per_proc = min(
                r["rows_local"] / r["scan_wall_s"] for r in results
            )
            runs.append(
                {
                    "processes": n,
                    "wall_s": round(wall, 3),
                    "spawn_wall_s": round(spawn_wall, 3),
                    "rows_per_s": round(ROWS / wall, 1),
                    "per_process_rows_per_s": round(per_proc, 1),
                    "shard_rows": [r["rows_local"] for r in results],
                }
            )
            print(
                f"bench-mesh: {n} process(es): scan {wall:.2f}s "
                f"({ROWS / wall:,.0f} rows/s)",
                flush=True,
            )

    solo = runs[0]["wall_s"]
    for r in runs:
        r["speedup"] = round(solo / r["wall_s"], 2)
        r["per_process_efficiency"] = round(
            r["per_process_rows_per_s"] / runs[0]["per_process_rows_per_s"], 3
        )

    speedup4 = [r for r in runs if r["processes"] == 4][0]["speedup"]
    eff4 = [r for r in runs if r["processes"] == 4][0]["per_process_efficiency"]
    ok = speedup4 >= 3.0 and eff4 >= 0.85
    doc = {
        "bench": "mesh",
        "round": 15,
        "config": {
            "rows": ROWS,
            "columns": 4,
            "partitions": N_PARTS,
            "row_group_size": ROW_GROUP,
            "source_stall_ms": STALL_MS,
            "model": (
                "IO-latency-bound cold pass (object-store stall model), "
                "one decode lane per process; states-only allgather via "
                "file exchange between real interpreters; warm-process "
                "timing (jit compile excluded, start barrier)"
            ),
        },
        "runs": runs,
        "bit_identical_across_meshes": True,
        "speedup_at_4": speedup4,
        "per_process_efficiency_at_4": eff4,
        "pass": ok,
    }
    with open(out_path, "w") as f:
        json.dump(doc, f, indent=2)
        f.write("\n")
    print(f"bench-mesh: wrote {out_path}")
    print(
        f"bench-mesh: speedup at 4 processes = {speedup4}x "
        f"(target >= 3.0), per-process efficiency {eff4:.0%} "
        f"(target >= 85%)"
    )
    if not ok:
        print("bench-mesh: SCALING TARGET MISSED")
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())

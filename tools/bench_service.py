#!/usr/bin/env python
"""Service scheduling benchmark: interactive latency under heavy load.

The fleet claim under test (ISSUE 14): with ONE worker fully occupied
by a heavy partitioned profile, interactive checks submitted against
the same service must see p99 latency within 2x of their solo p99 —
because every interactive arrival preempts the heavy run at its next
partition boundary (DQ405), runs immediately, and the heavy run
resumes from its committed partition states instead of restarting.

Two phases over the same interactive workload:

  solo        — K interactive submissions on an idle service;
  concurrent  — the same K submissions while a heavy profile scans a
                BENCH_SERVICE_ROWS-row partitioned dataset on the same
                single worker.

The heavy run must COMPLETE (from committed states — its preemption
count and final cached-partition split are recorded), and the ratio
concurrent_p99 / solo_p99 must be <= 2.0 for the bench to pass.

Writes BENCH_SERVICE.json to the repo root and prints it to stdout.
"""

from __future__ import annotations

import json
import os
import shutil
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")

N_PARTITIONS = 128
INTERACTIVE_RUNS = 20
# a realistic interactive check reads ~500k rows from parquet (file
# open included — that's what a user-facing check does); an in-memory
# toy probe would make ANY partition-boundary wait look like a
# violation
INTERACTIVE_ROWS = 524288
RATIO_BUDGET = 2.0


def build_partition(rows: int, seed: int):
    import numpy as np

    from deequ_tpu.data.table import Table

    rng = np.random.default_rng(seed)
    x = rng.normal(10.0, 3.0, rows)
    y = rng.uniform(0.0, 100.0, rows)
    g = rng.integers(0, 50, rows).astype(np.float64)
    return Table.from_pydict({"x": x, "y": y, "g": g})


def heavy_check():
    from deequ_tpu import Check, CheckLevel

    return (
        Check(CheckLevel.ERROR, "heavy-profile")
        .has_size(lambda s: s > 0)
        .is_complete("x")
        .has_mean("x", lambda m: 5.0 < m < 15.0)
        .has_standard_deviation("x", lambda s: s > 0)
        .is_complete("y")
        .has_mean("y", lambda m: m > 0)
    )


def interactive_check():
    from deequ_tpu import Check, CheckLevel

    return (
        Check(CheckLevel.ERROR, "interactive")
        .has_size(lambda s: s > 0)
        .is_complete("x")
        .has_mean("x", lambda m: 5.0 < m < 15.0)
    )


def percentile(sorted_vals, q):
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, int(round(q * (len(sorted_vals) - 1))))
    return sorted_vals[idx]


def run_interactive_round(svc, table, tag):
    # two untimed warmups so kernel compilation doesn't masquerade as
    # scheduling latency in either phase
    for i in range(2):
        h = svc.submit(
            "interactive-tenant", f"{tag}-warm-{i}", table,
            checks=[interactive_check()],
        )
        if not h.wait(timeout=300) or h.status != "done":
            raise SystemExit(f"bench_service: warmup {tag}-{i} failed")
    latencies = []
    for i in range(INTERACTIVE_RUNS):
        t0 = time.monotonic()
        h = svc.submit(
            "interactive-tenant", f"{tag}-{i}", table,
            checks=[interactive_check()],
        )
        if not h.wait(timeout=300):
            raise SystemExit(f"bench_service: interactive run {tag}-{i} hung")
        if h.status != "done":
            raise SystemExit(
                f"bench_service: interactive run {tag}-{i} "
                f"ended {h.status}: {h.reason}"
            )
        latencies.append(time.monotonic() - t0)
    return sorted(latencies)


def main() -> int:
    from deequ_tpu.data.table import Table
    from deequ_tpu.lint.explain import explain_plan
    from deequ_tpu.repository.states import FileSystemStateRepository
    from deequ_tpu.service import DQService

    total_rows = int(os.environ.get("BENCH_SERVICE_ROWS", "2000000"))
    rows_per_part = max(1, total_rows // N_PARTITIONS)

    work = tempfile.mkdtemp(prefix="bench_service_")
    try:
        data_dir = os.path.join(work, "dataset")
        os.makedirs(data_dir)
        for i in range(N_PARTITIONS):
            build_partition(rows_per_part, seed=100 + i).to_parquet(
                os.path.join(data_dir, f"part-{i:03d}.parquet"),
                row_group_size=max(4096, rows_per_part // 4),
            )

        def heavy_data():
            return Table.scan_parquet_dataset(data_dir)

        # classify the bench dataset as heavy regardless of machine-
        # sized defaults: pin both tier boundaries around its predicted
        # scan (the operator override the tier doc describes). The
        # interactive probes predict ~3 orders of magnitude less and
        # stay interactive under the lowered boundary.
        predicted = explain_plan(
            heavy_data(), checks=[heavy_check()]
        ).cost.predicted_scan_bytes
        os.environ["DEEQU_TPU_TIER_INTERACTIVE_BYTES"] = str(
            max(1.0, predicted * 0.25)
        )
        os.environ["DEEQU_TPU_TIER_HEAVY_BYTES"] = str(max(1.0, predicted * 0.5))

        inter_path = os.path.join(work, "interactive.parquet")
        build_partition(INTERACTIVE_ROWS, seed=1).to_parquet(
            inter_path, row_group_size=INTERACTIVE_ROWS // 4
        )

        def inter_table():
            return Table.scan_parquet(inter_path)

        # -- phase 1: solo ---------------------------------------------------
        with DQService(workers=1) as svc:
            solo = run_interactive_round(svc, inter_table, "solo")

        # -- phase 2: concurrent with a heavy profile ------------------------
        repo = FileSystemStateRepository(os.path.join(work, "states"))
        with DQService(workers=1, state_repository=repo) as svc:
            heavy = svc.submit(
                "batch-tenant", "big", heavy_data, checks=[heavy_check()]
            )
            if heavy.tier != "heavy":
                raise SystemExit(
                    f"bench_service: dataset classified {heavy.tier}, "
                    "expected heavy"
                )
            deadline = time.monotonic() + 120
            while heavy.status != "running" and time.monotonic() < deadline:
                time.sleep(0.005)

            concurrent = run_interactive_round(svc, inter_table, "conc")

            if not heavy.wait(timeout=1800):
                raise SystemExit("bench_service: heavy profile never finished")
            if heavy.status != "done":
                raise SystemExit(
                    f"bench_service: heavy profile ended "
                    f"{heavy.status}: {heavy.reason}"
                )
            preemptions = heavy.preemptions
            attempts = heavy.attempts

        solo_p99 = percentile(solo, 0.99)
        conc_p99 = percentile(concurrent, 0.99)
        ratio = conc_p99 / solo_p99 if solo_p99 > 0 else float("inf")

        record = {
            "bench": "service",
            "rows": rows_per_part * N_PARTITIONS,
            "partitions": N_PARTITIONS,
            "interactive_runs": INTERACTIVE_RUNS,
            "interactive_rows": INTERACTIVE_ROWS,
            "solo_p50_s": round(percentile(solo, 0.5), 4),
            "solo_p99_s": round(solo_p99, 4),
            "concurrent_p50_s": round(percentile(concurrent, 0.5), 4),
            "concurrent_p99_s": round(conc_p99, 4),
            "p99_ratio": round(ratio, 3),
            "ratio_budget": RATIO_BUDGET,
            "heavy_completed": True,
            "heavy_preemptions": preemptions,
            "heavy_attempts": attempts,
            "predicted_heavy_scan_bytes": round(predicted, 0),
        }
        out_path = os.path.join(REPO, "BENCH_SERVICE.json")
        with open(out_path, "w", encoding="utf-8") as fh:
            json.dump(record, fh, indent=2, sort_keys=True)
            fh.write("\n")
        print(json.dumps(record, indent=2, sort_keys=True))

        if ratio > RATIO_BUDGET:
            print(
                f"bench_service: FAILED — concurrent p99 {conc_p99:.3f}s is "
                f"{ratio:.2f}x solo p99 {solo_p99:.3f}s (budget "
                f"{RATIO_BUDGET}x)",
                file=sys.stderr,
            )
            return 1
        return 0
    finally:
        shutil.rmtree(work, ignore_errors=True)


if __name__ == "__main__":
    raise SystemExit(main())

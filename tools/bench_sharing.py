#!/usr/bin/env python
"""Fleet-wide scan-sharing benchmark: K co-tenant suites, ONE scan.

The fleet claim under test (ISSUE 17): when K tenants submit suites
over the same table, the service proves "suite ⊆ union scan" for every
member and runs ONE superset scan, fanning the folded states back out
over the analyzer state semigroup. The group must finish in <= 1.5x a
single (widest) solo scan's wall time — not the ~Kx an independent
run-per-tenant schedule costs — and every participant's result must be
BIT-identical to its solo run, with its CONTAINED proof pinned against
the executed plan at zero drift.

Three phases over the same K tenant suites:

  solo        — each suite runs alone (the correctness baseline AND
                the single-scan wall-time yardstick);
  independent — the same K suites on a sharing-disabled single-worker
                service (what the fleet pays without the prover);
  shared      — the same K suites grouped onto one proven union scan.

The bench ABORTS (exit 1, no JSON) on any metric/status mismatch
between a shared result and its solo baseline, on any participant
missing a CONTAINED proof, and on any nonzero proof-drift counter.

Writes BENCH_SHARING.json to the repo root and prints it to stdout.
"""

from __future__ import annotations

import json
import os
import shutil
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")

N_PARTITIONS = 32
N_TENANTS = 4
RATIO_BUDGET = 1.5


def build_partition(rows: int, seed: int):
    import numpy as np

    from deequ_tpu.data.table import Table

    rng = np.random.default_rng(seed)
    x = rng.normal(10.0, 3.0, rows)
    y = rng.uniform(0.0, 100.0, rows)
    g = rng.integers(0, 50, rows).astype(np.float64)
    return Table.from_pydict({"x": x, "y": y, "g": g})


def tenant_checks():
    """K overlapping-but-distinct suites over the same three columns —
    the union scan is as wide as the widest member, so sharing buys
    ~K scans' worth of reading for one."""
    from deequ_tpu import Check, CheckLevel

    return {
        "tenant-a": Check(CheckLevel.ERROR, "a")
        .has_size(lambda n: n > 0)
        .is_complete("x")
        .has_mean("x", lambda m: 5.0 < m < 15.0)
        .has_standard_deviation("x", lambda s: s > 0),
        "tenant-b": Check(CheckLevel.ERROR, "b")
        .is_complete("y")
        .has_mean("y", lambda m: m > 0)
        .has_mean("x", lambda m: m > 0),
        "tenant-c": Check(CheckLevel.ERROR, "c")
        .has_size(lambda n: n > 0)
        .is_complete("g")
        .has_mean("g", lambda m: m >= 0)
        .has_standard_deviation("g", lambda s: s > 0),
        "tenant-d": Check(CheckLevel.ERROR, "d")
        .is_complete("x")
        .is_complete("y")
        .has_mean("y", lambda m: m > 0),
    }


def snapshot(result):
    """Comparable projection of a VerificationResult: overall status,
    per-constraint statuses, and metric values keyed by analyzer."""
    checks = []
    for check, cres in result.check_results.items():
        checks.append(
            (
                check.description,
                cres.status.name,
                tuple(
                    (str(cr.constraint), cr.status.name)
                    for cr in cres.constraint_results
                ),
            )
        )
    metrics = {}
    for analyzer, metric in result.metrics.items():
        v = metric.value
        metrics[repr(analyzer)] = (
            ("FAIL", type(v.exception).__name__) if v.is_failure else ("OK", v.get())
        )
    return result.status.name, tuple(sorted(checks)), metrics


def submit_round(svc, open_table, checks, blocker_table):
    """Submit all K suites behind a short blocker (so the single worker
    sees them queued together) and return (handles, group_wall_s)
    measured from the moment the worker frees up."""
    import time as _t

    from deequ_tpu import Check, CheckLevel

    gate = Check(CheckLevel.ERROR, "gate").has_size(
        lambda n: (_t.sleep(0.5) or n >= 0)
    )
    blocker = svc.submit("gate-tenant", "gate", blocker_table, checks=[gate])
    _t.sleep(0.2)
    handles = {
        tenant: svc.submit(tenant, "bench-ds", open_table, checks=[check])
        for tenant, check in checks.items()
    }
    if not blocker.wait(timeout=300) or blocker.status != "done":
        raise SystemExit("bench_sharing: blocker submission failed")
    t0 = time.monotonic()
    for tenant, handle in handles.items():
        if not handle.wait(timeout=900):
            raise SystemExit(f"bench_sharing: {tenant} hung")
        if handle.status != "done":
            raise SystemExit(
                f"bench_sharing: {tenant} ended {handle.status}: {handle.reason}"
            )
    return handles, time.monotonic() - t0


def main() -> int:
    from deequ_tpu import VerificationSuite
    from deequ_tpu.data.table import Table
    from deequ_tpu.service import DQService

    total_rows = int(os.environ.get("BENCH_SHARING_ROWS", "8000000"))
    rows_per_part = max(1, total_rows // N_PARTITIONS)
    checks = tenant_checks()
    assert len(checks) == N_TENANTS

    work = tempfile.mkdtemp(prefix="bench_sharing_")
    try:
        data_dir = os.path.join(work, "dataset")
        os.makedirs(data_dir)
        for i in range(N_PARTITIONS):
            build_partition(rows_per_part, seed=200 + i).to_parquet(
                os.path.join(data_dir, f"part-{i:03d}.parquet"),
                row_group_size=max(4096, rows_per_part // 4),
            )

        def open_table():
            return Table.scan_parquet_dataset(data_dir)

        blocker_table = Table.from_pydict({"k": [1.0, 2.0]})

        # -- phase 1: solo baselines (untimed warmup, then timed) ------------
        warm = (
            VerificationSuite()
            .on_data(open_table())
            .add_check(next(iter(checks.values())))
            .with_engine("single")
            .run()
        )
        del warm
        solo_snapshots = {}
        solo_wall = {}
        for tenant, check in checks.items():
            t0 = time.monotonic()
            result = (
                VerificationSuite()
                .on_data(open_table())
                .add_check(check)
                .with_engine("single")
                .run()
            )
            solo_wall[tenant] = time.monotonic() - t0
            solo_snapshots[tenant] = snapshot(result)
        single_scan_s = max(solo_wall.values())

        # -- phase 2: independent (sharing off) ------------------------------
        os.environ["DEEQU_TPU_SCAN_SHARING"] = "0"
        try:
            with DQService(workers=1) as svc:
                ind_handles, independent_s = submit_round(
                    svc, open_table, checks, blocker_table
                )
                for tenant, handle in ind_handles.items():
                    if handle.sharing is not None:
                        raise SystemExit(
                            "bench_sharing: sharing ran with the kill switch on"
                        )
                    if snapshot(handle.result) != solo_snapshots[tenant]:
                        raise SystemExit(
                            f"bench_sharing: ABORT — independent run of {tenant} "
                            "diverged from its solo baseline"
                        )
        finally:
            del os.environ["DEEQU_TPU_SCAN_SHARING"]

        # -- phase 3: shared (one proven union scan) -------------------------
        with DQService(workers=1) as svc:
            handles, shared_s = submit_round(svc, open_table, checks, blocker_table)
            shared_scans = svc.telemetry.value("shared_scans")
            participants = []
            for tenant, handle in handles.items():
                if snapshot(handle.result) != solo_snapshots[tenant]:
                    raise SystemExit(
                        f"bench_sharing: ABORT — shared result for {tenant} is "
                        "not bit-identical to its solo baseline"
                    )
                info = handle.sharing
                if not info or not info.get("shared"):
                    raise SystemExit(
                        f"bench_sharing: ABORT — {tenant} did not join the "
                        f"share group ({(info or {}).get('reason', 'no group')})"
                    )
                if info["proof"]["verdict"] != "CONTAINED":
                    raise SystemExit(
                        f"bench_sharing: ABORT — {tenant} proof verdict "
                        f"{info['proof']['verdict']}, expected CONTAINED"
                    )
                drift = info["drift"]
                if any(v != 0 for v in drift.values()):
                    raise SystemExit(
                        f"bench_sharing: ABORT — {tenant} proof drifted from "
                        f"the executed plan: {drift}"
                    )
                participants.append(tenant)
            if len(participants) != N_TENANTS or shared_scans < 1:
                raise SystemExit(
                    f"bench_sharing: group never formed "
                    f"({len(participants)}/{N_TENANTS} shared, "
                    f"{shared_scans} shared scans)"
                )
            charges = {t: round(svc.ledger.bytes_total(t)) for t in participants}

        ratio = shared_s / single_scan_s if single_scan_s > 0 else float("inf")
        speedup = independent_s / shared_s if shared_s > 0 else float("inf")

        record = {
            "bench": "sharing",
            "rows": rows_per_part * N_PARTITIONS,
            "partitions": N_PARTITIONS,
            "tenants": N_TENANTS,
            "solo_wall_s": {t: round(s, 4) for t, s in solo_wall.items()},
            "single_scan_s": round(single_scan_s, 4),
            "independent_s": round(independent_s, 4),
            "shared_s": round(shared_s, 4),
            "shared_vs_single_ratio": round(ratio, 3),
            "ratio_budget": RATIO_BUDGET,
            "speedup_vs_independent": round(speedup, 2),
            "shared_scans": shared_scans,
            "proof_verdicts": {t: "CONTAINED" for t in participants},
            "proof_drift_total": 0,
            "bit_identical_to_solo": True,
            "prorata_charges_bytes": charges,
        }
        out_path = os.path.join(REPO, "BENCH_SHARING.json")
        with open(out_path, "w", encoding="utf-8") as fh:
            json.dump(record, fh, indent=2, sort_keys=True)
            fh.write("\n")
        print(json.dumps(record, indent=2, sort_keys=True))

        if ratio > RATIO_BUDGET:
            print(
                f"bench_sharing: FAILED — {N_TENANTS} co-tenant suites took "
                f"{shared_s:.3f}s, {ratio:.2f}x the single-scan wall "
                f"{single_scan_s:.3f}s (budget {RATIO_BUDGET}x)",
                file=sys.stderr,
            )
            return 1
        return 0
    finally:
        shutil.rmtree(work, ignore_errors=True)


if __name__ == "__main__":
    raise SystemExit(main())

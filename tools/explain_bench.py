#!/usr/bin/env python
"""Smoke EXPLAIN over the benchmark plans — the `make analyze` leg that
proves the static cost analyzer runs end-to-end.

Builds the bench schema WITHOUT building bench data (a zero-row slice of
the same column layout), EXPLAINs the scan-bench analyzer plan at the
bench's default row count, and exits non-zero if the analyzer fails or
predicts an empty plan. Runs in a couple of seconds; scans nothing.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def main() -> int:
    import bench
    from deequ_tpu.lint import SchemaInfo, explain_plan

    # zero rows: same dtype/nullability layout the bench scans, no data
    table = bench.build_table(0)
    schema = SchemaInfo.from_table(table)
    analyzers = bench.scan_analyzers()

    result = explain_plan(
        schema, analyzers=analyzers, num_rows=10_000_000, placement="device"
    )
    print(result.render())

    cost = result.cost
    scan = cost.scan_pass
    if scan is None or not cost.analyzers:
        print("explain_bench: FAILED — no scan pass predicted", file=sys.stderr)
        return 1
    if cost.precondition_failures:
        print(
            "explain_bench: FAILED — bench plan has precondition failures",
            file=sys.stderr,
        )
        return 1
    errors = [d for d in result.diagnostics if d.severity.value == "error"]
    if errors:
        print(
            f"explain_bench: FAILED — {len(errors)} error diagnostic(s)",
            file=sys.stderr,
        )
        return 1
    print(
        f"explain_bench: OK — {len(cost.analyzers)} analyzers, "
        f"{len(cost.passes)} pass(es), {scan.n_batches} batch(es), "
        f"{len(result.diagnostics)} diagnostic(s)"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())

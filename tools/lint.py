#!/usr/bin/env python3
"""Repo lint: ruff (when installed) plus pure-AST checks that need no
third-party tooling (ISSUE 2, satellite).

Checks:
  HOTLOOP  — no `jax.device_get(...)` / `.block_until_ready()` calls
             inside for/while loops in deequ_tpu/ops/fused.py: a host
             sync per iteration destroys the double-buffered pipeline
             (each one is a full device drain).
  TIMING   — no direct `time.perf_counter()` / `time.monotonic()` (or
             their `_ns` variants) in deequ_tpu/runners/ and
             deequ_tpu/ops/: engine timing must flow through
             deequ_tpu.observe (span()/timed_call()) so traces stay the
             single source of runtime truth and the disabled path keeps
             its measured near-zero overhead.
  F401*    — unused imports (fallback when ruff is unavailable).
  E722*    — bare `except:` (fallback when ruff is unavailable).

Exit code 0 = clean, 1 = findings. Run via `make lint` or directly:
    python tools/lint.py
"""

from __future__ import annotations

import ast
import os
import shutil
import subprocess
import sys
from typing import Iterator, List

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
HOT_LOOP_FILES = [os.path.join("deequ_tpu", "ops", "fused.py")]
HOT_LOOP_FORBIDDEN = {"device_get", "block_until_ready"}
# Engine dirs where ad-hoc clock reads are banned (observe/ owns timing).
TIMING_DIRS = (
    os.path.join("deequ_tpu", "runners"),
    os.path.join("deequ_tpu", "ops"),
)
TIMING_FORBIDDEN = {
    "perf_counter",
    "perf_counter_ns",
    "monotonic",
    "monotonic_ns",
}


def _python_files() -> Iterator[str]:
    for top in ("deequ_tpu", "tests", "tools"):
        root = os.path.join(REPO, top)
        for dirpath, dirnames, filenames in os.walk(root):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            for name in sorted(filenames):
                if name.endswith(".py"):
                    yield os.path.join(dirpath, name)


def _rel(path: str) -> str:
    return os.path.relpath(path, REPO)


# -- HOTLOOP: host syncs inside scan-loop bodies ----------------------------


def check_hot_loops(path: str) -> List[str]:
    with open(path, encoding="utf-8") as f:
        tree = ast.parse(f.read(), filename=path)
    findings: List[str] = []

    class Visitor(ast.NodeVisitor):
        def __init__(self) -> None:
            self.loop_depth = 0

        def _loop(self, node: ast.AST) -> None:
            self.loop_depth += 1
            self.generic_visit(node)
            self.loop_depth -= 1

        visit_For = visit_While = visit_AsyncFor = _loop

        def visit_Call(self, node: ast.Call) -> None:
            if self.loop_depth > 0 and isinstance(node.func, ast.Attribute):
                if node.func.attr in HOT_LOOP_FORBIDDEN:
                    findings.append(
                        f"{_rel(path)}:{node.lineno}: HOTLOOP "
                        f"`.{node.func.attr}` inside a loop body — each call "
                        f"is a device drain; hoist it out of the loop"
                    )
            self.generic_visit(node)

    Visitor().visit(tree)
    return findings


# -- TIMING: ad-hoc clock reads in engine code -------------------------------


def check_timing_calls(path: str) -> List[str]:
    """Flag `time.perf_counter()`/`time.monotonic()` (and `_ns`) calls —
    direct or via `from time import ...` — in engine dirs. Timing there
    belongs to deequ_tpu.observe: `span(...)` for traced regions,
    `timed_call(...)` for one-off measurements."""
    with open(path, encoding="utf-8") as f:
        tree = ast.parse(f.read(), filename=path)
    # names bound by `from time import perf_counter [as x]`
    local_clocks = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module == "time":
            for alias in node.names:
                if alias.name in TIMING_FORBIDDEN:
                    local_clocks.add(alias.asname or alias.name)
    findings: List[str] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        hit = None
        if (
            isinstance(func, ast.Attribute)
            and func.attr in TIMING_FORBIDDEN
            and isinstance(func.value, ast.Name)
            and func.value.id == "time"
        ):
            hit = f"time.{func.attr}"
        elif isinstance(func, ast.Name) and func.id in local_clocks:
            hit = func.id
        if hit is not None:
            findings.append(
                f"{_rel(path)}:{node.lineno}: TIMING `{hit}()` in engine "
                f"code — use deequ_tpu.observe (span()/timed_call()) so "
                f"the measurement lands in the trace"
            )
    return findings


# -- F401 fallback: unused imports ------------------------------------------


def _used_names(tree: ast.AST) -> set:
    used = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Name):
            used.add(node.id)
        elif isinstance(node, ast.Constant) and isinstance(node.value, str):
            # forward-ref annotations ("Table"), dotted refs, __all__ entries
            for part in node.value.replace(".", " ").replace("[", " ").replace(
                "]", " "
            ).split():
                if part.isidentifier():
                    used.add(part)
    return used


def check_unused_imports(path: str) -> List[str]:
    if os.path.basename(path) == "__init__.py":
        return []  # re-export surface: unused-looking imports are the point
    with open(path, encoding="utf-8") as f:
        tree = ast.parse(f.read(), filename=path)
    used = _used_names(tree)
    findings: List[str] = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                bound = alias.asname or alias.name.split(".")[0]
                if bound not in used:
                    findings.append(
                        f"{_rel(path)}:{node.lineno}: F401 "
                        f"`{alias.name}` imported but unused"
                    )
        elif isinstance(node, ast.ImportFrom):
            if node.module == "__future__":
                continue
            for alias in node.names:
                if alias.name == "*":
                    continue
                bound = alias.asname or alias.name
                if bound not in used:
                    findings.append(
                        f"{_rel(path)}:{node.lineno}: F401 "
                        f"`{alias.name}` imported but unused"
                    )
    return findings


# -- E722 fallback: bare except ---------------------------------------------


def check_bare_except(path: str) -> List[str]:
    with open(path, encoding="utf-8") as f:
        tree = ast.parse(f.read(), filename=path)
    return [
        f"{_rel(path)}:{node.lineno}: E722 bare `except:` — name the exception"
        for node in ast.walk(tree)
        if isinstance(node, ast.ExceptHandler) and node.type is None
    ]


def run_ruff() -> List[str]:
    """Delegate F401/F821/E722 to ruff when it exists (config lives in
    pyproject.toml); None-equivalent empty result plus a sentinel when
    it doesn't."""
    exe = shutil.which("ruff")
    if exe is None:
        return []
    proc = subprocess.run(
        [exe, "check", "deequ_tpu", "tests", "tools"],
        capture_output=True,
        text=True,
        cwd=REPO,
        timeout=300,
    )
    if proc.returncode == 0:
        return []
    return [line for line in proc.stdout.splitlines() if line.strip()]


def main() -> int:
    findings: List[str] = []

    for rel in HOT_LOOP_FILES:
        path = os.path.join(REPO, rel)
        if os.path.exists(path):
            findings.extend(check_hot_loops(path))

    for path in _python_files():
        rel = _rel(path)
        if any(
            rel == d or rel.startswith(d + os.sep) for d in TIMING_DIRS
        ):
            findings.extend(check_timing_calls(path))

    if shutil.which("ruff") is not None:
        findings.extend(run_ruff())
    else:
        for path in _python_files():
            findings.extend(check_unused_imports(path))
            findings.extend(check_bare_except(path))

    for line in findings:
        print(line)
    if findings:
        print(f"\n{len(findings)} finding(s)")
        return 1
    print("lint clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python3
"""Repo lint: ruff (when installed) plus pure-AST checks that need no
third-party tooling (ISSUE 2, satellite).

Checks:
  HOTLOOP  — no `jax.device_get(...)` / `.block_until_ready()` calls
             inside for/while loops in deequ_tpu/ops/fused.py: a host
             sync per iteration destroys the double-buffered pipeline
             (each one is a full device drain).
  TIMING   — no direct `time.perf_counter()` / `time.monotonic()` (or
             their `_ns` variants) in deequ_tpu/runners/ and
             deequ_tpu/ops/: engine timing must flow through
             deequ_tpu.observe (span()/timed_call()) so traces stay the
             single source of runtime truth and the disabled path keeps
             its measured near-zero overhead.
  PIPELINE — no `jax.device_get(...)` / `.block_until_ready()` anywhere
             in the stream-pipeline stage-worker files
             (deequ_tpu/ops/pipeline.py, deequ_tpu/data/source.py): a
             host sync on a stage thread serializes the very overlap
             the pipeline exists to create — device syncs belong to
             the fold stage (`PipelinedAggFold`) only.
  GLOBALMUT — module-global dicts/lists in deequ_tpu/ops/, runners/,
             and parallel/ must not be mutated inside functions without
             a lock: engine code runs on worker threads (the family
             pool, user threads) and an unguarded shared cache is the
             exact bug class the ExecutionStats fix in PR 3 removed.
             Guard the mutation with `with <...lock...>:` or allowlist
             the ASSIGNMENT line with a `# global-ok: <reason>` comment.
  OBSPRINT — no `print(...)` calls in deequ_tpu/observe/: heartbeat and
             trace output must flow through a sink, callback, or
             explicit stream write (`sys.stderr.write`) — stdout
             belongs to results (bench.py's one-JSON-line contract) and
             a stray print corrupts any caller parsing it.
  PUSHDOWN — deequ_tpu/lint/pushdown.py must stay a pure interpreter:
             no pyarrow/pandas import (not even lazily inside a
             function) and no `open(...)` call. Statistics reach it as
             plain RowGroupStats records; ParquetSource.row_group_stats
             is the single reader. Purity keeps every verdict unit-
             testable without files and the lint layer importable
             without pyarrow.
  SUBSUME  — deequ_tpu/lint/subsume.py (the plan-subsumption prover)
             must stay import-pure like PUSHDOWN: no jax/numpy/
             pyarrow/pandas import, no deequ_tpu.service/ops/runners/
             repository/parallel/verification import (not even lazily),
             and no `open(...)` call. The prover's verdicts gate which
             tenants share one fleet-wide scan — they must be provable
             from the plans alone, unit-testable without an
             accelerator, and importable by tools that never touch the
             runtime.
  DECODE   — the fast-path decode modules (data/arrow_decode.py,
             ops/native/) must stay buffer-level: no `.to_numpy(...)`
             and no `frombuffer(...)` copy idioms outside designated
             fallback functions (names ending `_fallback`). The fast
             path's whole point is ONE native pass from arrow buffers
             to Column backing; a host-copy idiom silently reintroduces
             the intermediate materialization it exists to remove.
  READER   — the native parquet reader dispatch
             (deequ_tpu/data/native_reader.py) must not import pyarrow
             outside designated fallback functions (names ending
             `_fallback`): the module exists to own the bytes end to
             end — pread → page decode → arrow-layout buffers → the
             decode/wire kernels — and a pyarrow import on the native
             path means the arrow materialization it replaces crept
             back in. Per-column fallbacks live in source.py, which
             already holds the pyarrow reader.
  SERDE    — no `pickle` (import or call) in the state serde paths
             (deequ_tpu/repository/states.py,
             deequ_tpu/repository/audit.py,
             deequ_tpu/analyzers/state_provider.py): persisted analyzer
             states are exact-width binary formats that round-trip
             bit-exactly and decode safely; pickle is neither (arbitrary
             code execution on load, no cross-version byte stability),
             so one import silently voids both the bit-identity and the
             corrupt-falls-back-to-rescan contracts.
  FORENSICS — telemetry surfaces (deequ_tpu/observe/telemetry.py,
             deequ_tpu/observe/heartbeat.py,
             deequ_tpu/repository/engine.py) must not import
             deequ_tpu.observe.forensics or touch its row-sample types
             (ViolationSample, ConstraintForensics, ForensicsReport,
             render_forensics): sampled row VALUES are data, and the
             `engine.*` series, OpenMetrics text, and heartbeat
             snapshots are operational metadata that leaves the trust
             boundary (dashboards, scrapes, log shippers). Row evidence
             belongs to the audit trail an operator explicitly loads.
  FAULTS   — fault containment in the stage-worker, readahead, and
             DQ-service files (deequ_tpu/ops/pipeline.py,
             deequ_tpu/data/source.py, deequ_tpu/data/native_reader.py,
             deequ_tpu/service/service.py, deequ_tpu/service/admission.py,
             deequ_tpu/service/breaker.py): no bare `except:` and no
             silently-swallowed exceptions (a handler whose body is
             only `pass`) — every contained fault must count itself
             (runtime.record_fault / record_retry) or land in a degrade
             path. Designated fallbacks stay exempt: any enclosing
             function whose name ends `_fallback`, or an except line
             annotated `# fault-ok: <reason>`. Additionally, every
             `faults.fault_point("<name>")` literal anywhere in
             deequ_tpu/ must name a point registered in
             deequ_tpu/testing/faults.py FAULT_KINDS — an unregistered
             point can never be exercised by the chaos harness, so the
             code behind it is untestable dead weight.
  F401*    — unused imports (fallback when ruff is unavailable).
  E722*    — bare `except:` (fallback when ruff is unavailable).

Exit code 0 = clean, 1 = findings. Run via `make lint` or directly:
    python tools/lint.py
"""

from __future__ import annotations

import ast
import glob
import os
import shutil
import subprocess
import sys
from typing import Iterator, List, Optional

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
HOT_LOOP_FILES = [os.path.join("deequ_tpu", "ops", "fused.py")]
HOT_LOOP_FORBIDDEN = {"device_get", "block_until_ready"}
# Stage-worker files where a host sync is banned OUTRIGHT (not just in
# loops): their code runs on pipeline stage threads, where one sync
# serializes the decode/prep/compute overlap.
PIPELINE_FILES = [
    os.path.join("deequ_tpu", "ops", "pipeline.py"),
    os.path.join("deequ_tpu", "data", "source.py"),
]
PIPELINE_FORBIDDEN = {"device_get", "block_until_ready"}
# Engine dirs where ad-hoc clock reads are banned (observe/ owns timing).
TIMING_DIRS = (
    os.path.join("deequ_tpu", "runners"),
    os.path.join("deequ_tpu", "ops"),
)
TIMING_FORBIDDEN = {
    "perf_counter",
    "perf_counter_ns",
    "monotonic",
    "monotonic_ns",
}
# Dirs where module-global mutable state must be lock-guarded (engine
# code here runs on worker threads: family pool, user threads, mesh).
GLOBALMUT_DIRS = (
    os.path.join("deequ_tpu", "ops"),
    os.path.join("deequ_tpu", "runners"),
    os.path.join("deequ_tpu", "parallel"),
)
# Dirs where `print(` is banned outright: observability output must go
# through a sink/callback/stream-write, never stdout.
OBSPRINT_DIRS = (os.path.join("deequ_tpu", "observe"),)
# Pure-interpreter files: no pyarrow/pandas imports, no open() calls.
PUSHDOWN_FILES = [os.path.join("deequ_tpu", "lint", "pushdown.py")]
PUSHDOWN_FORBIDDEN_MODULES = {"pyarrow", "pandas"}

SUBSUME_FILES = [os.path.join("deequ_tpu", "lint", "subsume.py")]
SUBSUME_FORBIDDEN_MODULES = {"jax", "jaxlib", "numpy", "pyarrow", "pandas"}
SUBSUME_FORBIDDEN_PREFIXES = (
    "deequ_tpu.service",
    "deequ_tpu.ops",
    "deequ_tpu.runners",
    "deequ_tpu.repository",
    "deequ_tpu.parallel",
    "deequ_tpu.verification",
)
# Windowed state algebra + drift math: host-side planning and
# host-side numpy statistics only. No jax/pyarrow/pandas (a window
# query must resolve with zero data rows read and no kernel dispatch),
# and no deequ_tpu.ops imports (sketch behavior is reached through the
# state objects' own methods); numpy IS allowed — the drift statistics
# are host arithmetic. `open(...)` is banned: all persistence goes
# through the StateRepository surface.
WINDOWS_DIR = os.path.join("deequ_tpu", "windows")
WINDOWS_EXTRA_FILES = [os.path.join("deequ_tpu", "analyzers", "drift.py")]
WINDOWS_FORBIDDEN_MODULES = {"jax", "jaxlib", "pyarrow", "pandas"}
WINDOWS_FORBIDDEN_PREFIXES = ("deequ_tpu.ops",)
# Fast-path decode modules: buffer-level only, no host-copy idioms
# outside designated fallback functions (names ending `_fallback`).
DECODE_FILES = [
    os.path.join("deequ_tpu", "data", "arrow_decode.py"),
    os.path.join("deequ_tpu", "ops", "native", "__init__.py"),
]
# Native-reader dispatch: pyarrow must not appear outside designated
# `*_fallback` functions — the module owns the bytes end to end.
READER_FILES = [
    os.path.join("deequ_tpu", "data", "native_reader.py"),
    os.path.join("deequ_tpu", "data", "encfold.py"),
]
READER_FORBIDDEN_MODULES = {"pyarrow"}
# State serde paths: pickle is banned in any form (import, from-import,
# attribute call) — persisted states are versioned exact-width binary.
SERDE_FILES = [
    os.path.join("deequ_tpu", "repository", "states.py"),
    os.path.join("deequ_tpu", "repository", "audit.py"),
    os.path.join("deequ_tpu", "analyzers", "state_provider.py"),
]
# Stage-worker, readahead, and DQ-service files where swallowed
# exceptions are banned: a fault contained here must be counted or
# degrade loudly. The service files carry multi-tenant blast radius —
# a silently-eaten worker fault would fail other tenants' runs with no
# forensics at all.
FAULTS_FILES = [
    os.path.join("deequ_tpu", "ops", "pipeline.py"),
    os.path.join("deequ_tpu", "data", "source.py"),
    os.path.join("deequ_tpu", "data", "native_reader.py"),
    os.path.join("deequ_tpu", "data", "encfold.py"),
    os.path.join("deequ_tpu", "service", "service.py"),
    os.path.join("deequ_tpu", "service", "admission.py"),
    os.path.join("deequ_tpu", "service", "breaker.py"),
    os.path.join("deequ_tpu", "parallel", "shard.py"),
    os.path.join("deequ_tpu", "parallel", "multihost.py"),
]
# The chaos harness's registry: every fault_point("<name>") literal in
# deequ_tpu/ must be a key of FAULT_KINDS in this module.
FAULTS_REGISTRY = os.path.join("deequ_tpu", "testing", "faults.py")
# Telemetry surfaces where forensics row samples are banned: these
# records leave the trust boundary (scrapes, dashboards, log shippers),
# and sampled row values must never ride along.
FORENSICS_FILES = [
    os.path.join("deequ_tpu", "observe", "telemetry.py"),
    os.path.join("deequ_tpu", "observe", "heartbeat.py"),
    os.path.join("deequ_tpu", "repository", "engine.py"),
]
FORENSICS_FORBIDDEN_MODULE = "deequ_tpu.observe.forensics"
FORENSICS_FORBIDDEN_NAMES = {
    "ViolationSample",
    "ConstraintForensics",
    "ForensicsReport",
    "render_forensics",
}
DECODE_FORBIDDEN_ATTRS = {"to_numpy", "frombuffer"}
# Host pack idioms banned inside the decode-to-wire fused path (any
# function or class whose name contains `wire`): the wire kernels emit
# packed bits and shifted/narrowed values directly, so a packbits/astype
# there means the serial numpy pack crept back in. Designated
# `*_fallback` functions stay exempt — they ARE the host re-read.
DECODE_WIRE_FORBIDDEN_ATTRS = {"packbits", "astype"}
GLOBALMUT_MUTATORS = {
    "append",
    "extend",
    "insert",
    "add",
    "update",
    "setdefault",
    "pop",
    "popitem",
    "clear",
    "remove",
    "discard",
}


def _python_files() -> Iterator[str]:
    for top in ("deequ_tpu", "tests", "tools"):
        root = os.path.join(REPO, top)
        for dirpath, dirnames, filenames in os.walk(root):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            for name in sorted(filenames):
                if name.endswith(".py"):
                    yield os.path.join(dirpath, name)


def _rel(path: str) -> str:
    return os.path.relpath(path, REPO)


# -- HOTLOOP: host syncs inside scan-loop bodies ----------------------------


def check_hot_loops(path: str) -> List[str]:
    with open(path, encoding="utf-8") as f:
        tree = ast.parse(f.read(), filename=path)
    findings: List[str] = []

    class Visitor(ast.NodeVisitor):
        def __init__(self) -> None:
            self.loop_depth = 0

        def _loop(self, node: ast.AST) -> None:
            self.loop_depth += 1
            self.generic_visit(node)
            self.loop_depth -= 1

        visit_For = visit_While = visit_AsyncFor = _loop

        def visit_Call(self, node: ast.Call) -> None:
            if self.loop_depth > 0 and isinstance(node.func, ast.Attribute):
                if node.func.attr in HOT_LOOP_FORBIDDEN:
                    findings.append(
                        f"{_rel(path)}:{node.lineno}: HOTLOOP "
                        f"`.{node.func.attr}` inside a loop body — each call "
                        f"is a device drain; hoist it out of the loop"
                    )
            self.generic_visit(node)

    Visitor().visit(tree)
    return findings


# -- PIPELINE: host syncs in stage-worker files ------------------------------


def check_pipeline_syncs(path: str) -> List[str]:
    """Flag `jax.device_get(...)` / `.block_until_ready()` calls anywhere
    in a stage-worker file: stage threads must stay async — the fold
    stage (`PipelinedAggFold` in ops/fused.py) owns every device sync."""
    with open(path, encoding="utf-8") as f:
        tree = ast.parse(f.read(), filename=path)
    findings: List[str] = []
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in PIPELINE_FORBIDDEN
        ):
            findings.append(
                f"{_rel(path)}:{node.lineno}: PIPELINE "
                f"`.{node.func.attr}` in a stage-worker file — a host "
                f"sync on a stage thread serializes the pipeline; move "
                f"the sync to the fold stage (PipelinedAggFold)"
            )
    return findings


# -- TIMING: ad-hoc clock reads in engine code -------------------------------


def check_timing_calls(path: str) -> List[str]:
    """Flag `time.perf_counter()`/`time.monotonic()` (and `_ns`) calls —
    direct or via `from time import ...` — in engine dirs. Timing there
    belongs to deequ_tpu.observe: `span(...)` for traced regions,
    `timed_call(...)` for one-off measurements."""
    with open(path, encoding="utf-8") as f:
        tree = ast.parse(f.read(), filename=path)
    # names bound by `from time import perf_counter [as x]`
    local_clocks = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module == "time":
            for alias in node.names:
                if alias.name in TIMING_FORBIDDEN:
                    local_clocks.add(alias.asname or alias.name)
    findings: List[str] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        hit = None
        if (
            isinstance(func, ast.Attribute)
            and func.attr in TIMING_FORBIDDEN
            and isinstance(func.value, ast.Name)
            and func.value.id == "time"
        ):
            hit = f"time.{func.attr}"
        elif isinstance(func, ast.Name) and func.id in local_clocks:
            hit = func.id
        if hit is not None:
            findings.append(
                f"{_rel(path)}:{node.lineno}: TIMING `{hit}()` in engine "
                f"code — use deequ_tpu.observe (span()/timed_call()) so "
                f"the measurement lands in the trace"
            )
    return findings


# -- OBSPRINT: print() in observability code ---------------------------------


def check_observe_prints(path: str) -> List[str]:
    """Flag any `print(...)` call in deequ_tpu/observe/: heartbeat and
    trace announcements must use a registered sink/callback or an
    explicit `sys.stderr.write` — stdout is reserved for results."""
    with open(path, encoding="utf-8") as f:
        tree = ast.parse(f.read(), filename=path)
    return [
        f"{_rel(path)}:{node.lineno}: OBSPRINT `print(...)` in "
        f"observability code — emit through a sink/callback or "
        f"`sys.stderr.write`; stdout belongs to results"
        for node in ast.walk(tree)
        if isinstance(node, ast.Call)
        and isinstance(node.func, ast.Name)
        and node.func.id == "print"
    ]


# -- PUSHDOWN: purity of the stats interpreter -------------------------------


def check_pushdown_purity(path: str) -> List[str]:
    """Flag pyarrow/pandas imports (top-level or inside any function)
    and `open(...)` calls in the pushdown interpreter: statistics must
    arrive as plain RowGroupStats records through
    ParquetSource.row_group_stats — never read here."""
    with open(path, encoding="utf-8") as f:
        tree = ast.parse(f.read(), filename=path)
    findings: List[str] = []
    for node in ast.walk(tree):
        modules: List[str] = []
        if isinstance(node, ast.Import):
            modules = [alias.name for alias in node.names]
        elif isinstance(node, ast.ImportFrom) and node.module:
            modules = [node.module]
        for mod in modules:
            if mod.split(".")[0] in PUSHDOWN_FORBIDDEN_MODULES:
                findings.append(
                    f"{_rel(path)}:{node.lineno}: PUSHDOWN `{mod}` import "
                    f"in the stats interpreter — statistics arrive as "
                    f"RowGroupStats records; the only reader is "
                    f"ParquetSource.row_group_stats"
                )
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Name)
            and node.func.id == "open"
        ):
            findings.append(
                f"{_rel(path)}:{node.lineno}: PUSHDOWN `open(...)` in the "
                f"stats interpreter — it must never touch files; pass "
                f"RowGroupStats in"
            )
    return findings


# -- SUBSUME: purity of the plan-subsumption prover ---------------------------


def check_subsume_purity(path: str) -> List[str]:
    """Flag accelerator/runtime imports (top-level or inside any
    function) and `open(...)` calls in the subsumption prover: its
    verdicts gate fleet-wide scan sharing and must be provable from
    the plans alone — no jax, no table IO, no service machinery."""
    with open(path, encoding="utf-8") as f:
        tree = ast.parse(f.read(), filename=path)
    findings: List[str] = []
    for node in ast.walk(tree):
        modules: List[str] = []
        if isinstance(node, ast.Import):
            modules = [alias.name for alias in node.names]
        elif isinstance(node, ast.ImportFrom):
            if node.level:
                # relative import: resolve against the prover's package
                # (deequ_tpu.lint for level 1, deequ_tpu for level 2)
                base = "deequ_tpu.lint" if node.level == 1 else "deequ_tpu"
                modules = [f"{base}.{node.module}" if node.module else base]
            elif node.module:
                modules = [node.module]
        for mod in modules:
            bad = mod.split(".")[0] in SUBSUME_FORBIDDEN_MODULES or any(
                mod == p or mod.startswith(p + ".")
                for p in SUBSUME_FORBIDDEN_PREFIXES
            )
            if bad:
                findings.append(
                    f"{_rel(path)}:{node.lineno}: SUBSUME `{mod}` import "
                    f"in the subsumption prover — containment verdicts "
                    f"must be provable from the plans alone (expression "
                    f"AST + lint lattice only)"
                )
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Name)
            and node.func.id == "open"
        ):
            findings.append(
                f"{_rel(path)}:{node.lineno}: SUBSUME `open(...)` in the "
                f"subsumption prover — it must never touch files; plans "
                f"and schemas arrive as arguments"
            )
    return findings


# -- WINDOWS: purity of the windowed state algebra + drift math ---------------


def check_windows_purity(path: str) -> List[str]:
    """Flag accelerator/table-IO imports and `open(...)` calls in the
    windows/ package and the drift statistics: a window query answers
    from persisted states alone (zero rows read, no kernel dispatch),
    and the drift math is host-side numpy — jax, pyarrow, pandas, and
    `deequ_tpu.ops` must never appear on that path."""
    with open(path, encoding="utf-8") as f:
        tree = ast.parse(f.read(), filename=path)
    findings: List[str] = []
    # relative-import base from the file's own package
    pkg = os.path.dirname(_rel(path)).replace(os.sep, ".")
    for node in ast.walk(tree):
        modules: List[str] = []
        if isinstance(node, ast.Import):
            modules = [alias.name for alias in node.names]
        elif isinstance(node, ast.ImportFrom):
            if node.level:
                parts = pkg.split(".")
                base = ".".join(parts[: len(parts) - node.level + 1])
                modules = [f"{base}.{node.module}" if node.module else base]
            elif node.module:
                modules = [node.module]
        for mod in modules:
            bad = mod.split(".")[0] in WINDOWS_FORBIDDEN_MODULES or any(
                mod == p or mod.startswith(p + ".")
                for p in WINDOWS_FORBIDDEN_PREFIXES
            )
            if bad:
                findings.append(
                    f"{_rel(path)}:{node.lineno}: WINDOWS `{mod}` import "
                    f"on the windowed-query/drift path — windows resolve "
                    f"from persisted states with zero rows read, and "
                    f"drift math is host-side numpy (no jax/pyarrow/"
                    f"pandas, no deequ_tpu.ops)"
                )
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Name)
            and node.func.id == "open"
        ):
            findings.append(
                f"{_rel(path)}:{node.lineno}: WINDOWS `open(...)` on the "
                f"windowed-query/drift path — all persistence goes "
                f"through the StateRepository surface"
            )
    return findings


# -- READER: no pyarrow on the native-reader path ------------------------------


def check_reader_purity(path: str) -> List[str]:
    """Flag pyarrow imports in the native-reader dispatch outside
    designated fallback functions (any enclosing function whose name
    ends `_fallback`). The module's contract is page bytes straight to
    arrow-layout buffers through the native kernels; a pyarrow import on
    that path reintroduces the materialization the reader removes."""
    with open(path, encoding="utf-8") as f:
        tree = ast.parse(f.read(), filename=path)
    findings: List[str] = []

    def walk(node: ast.AST, in_fallback: bool) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            in_fallback = in_fallback or node.name.endswith("_fallback")
        if not in_fallback:
            modules: List[str] = []
            if isinstance(node, ast.Import):
                modules = [alias.name for alias in node.names]
            elif isinstance(node, ast.ImportFrom) and node.module:
                modules = [node.module]
            for mod in modules:
                if mod.split(".")[0] in READER_FORBIDDEN_MODULES:
                    findings.append(
                        f"{_rel(path)}:{node.lineno}: READER `{mod}` import "
                        f"on the native reader path — the reader owns the "
                        f"bytes end to end; arrow fallbacks live in "
                        f"source.py or a designated `*_fallback` function"
                    )
        for child in ast.iter_child_nodes(node):
            walk(child, in_fallback)

    walk(tree, False)
    return findings


# -- SERDE: no pickle in the state serde paths --------------------------------


def check_serde_pickle(path: str) -> List[str]:
    """Flag any appearance of pickle in the state serde paths: plain or
    from-imports (top-level or inside any function, including the
    `cPickle`/`dill`/`cloudpickle` spellings) and `pickle.loads/dumps`
    attribute calls. Persisted analyzer states must stay exact-width
    versioned binary — pickle would execute arbitrary bytecode on load
    and break byte stability across versions."""
    serde_banned = {"pickle", "cPickle", "_pickle", "dill", "cloudpickle"}
    with open(path, encoding="utf-8") as f:
        tree = ast.parse(f.read(), filename=path)
    findings: List[str] = []
    for node in ast.walk(tree):
        modules: List[str] = []
        if isinstance(node, ast.Import):
            modules = [alias.name for alias in node.names]
        elif isinstance(node, ast.ImportFrom) and node.module:
            modules = [node.module]
        for mod in modules:
            if mod.split(".")[0] in serde_banned:
                findings.append(
                    f"{_rel(path)}:{node.lineno}: SERDE `{mod}` import in "
                    f"a state serde path — persisted states are versioned "
                    f"exact-width binary; pickle voids the bit-identity "
                    f"and safe-decode contracts"
                )
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and isinstance(node.func.value, ast.Name)
            and node.func.value.id in serde_banned
        ):
            findings.append(
                f"{_rel(path)}:{node.lineno}: SERDE "
                f"`{node.func.value.id}.{node.func.attr}(...)` call in a "
                f"state serde path — use the versioned binary envelope"
            )
    return findings


# -- FORENSICS: no row samples on telemetry surfaces --------------------------


def check_forensics_leak(path: str) -> List[str]:
    """Flag imports of deequ_tpu.observe.forensics and any use of its
    row-sample identifiers in telemetry-surface files. Telemetry records
    (`engine.*` series, OpenMetrics text, heartbeat snapshots) are
    operational metadata that leaves the trust boundary; sampled row
    VALUES stay in the audit trail an operator explicitly loads."""
    with open(path, encoding="utf-8") as f:
        tree = ast.parse(f.read(), filename=path)
    findings: List[str] = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name == FORENSICS_FORBIDDEN_MODULE or (
                    alias.name.startswith(FORENSICS_FORBIDDEN_MODULE + ".")
                ):
                    findings.append(
                        f"{_rel(path)}:{node.lineno}: FORENSICS "
                        f"`{alias.name}` import on a telemetry surface — "
                        f"sampled row values must never reach engine.* "
                        f"records, OpenMetrics text, or heartbeat output"
                    )
        elif isinstance(node, ast.ImportFrom) and node.module:
            if node.module == FORENSICS_FORBIDDEN_MODULE or node.module.startswith(
                FORENSICS_FORBIDDEN_MODULE + "."
            ):
                findings.append(
                    f"{_rel(path)}:{node.lineno}: FORENSICS import from "
                    f"`{node.module}` on a telemetry surface — sampled row "
                    f"values must never reach engine.* records, OpenMetrics "
                    f"text, or heartbeat output"
                )
        else:
            name = None
            if isinstance(node, ast.Name):
                name = node.id
            elif isinstance(node, ast.Attribute):
                name = node.attr
            if name in FORENSICS_FORBIDDEN_NAMES:
                findings.append(
                    f"{_rel(path)}:{node.lineno}: FORENSICS `{name}` on a "
                    f"telemetry surface — row-sample types are banned here; "
                    f"row evidence belongs to the audit trail only"
                )
    return findings


# -- DECODE: no host-copy idioms in fast-path decode modules -----------------


def check_decode_copies(path: str) -> List[str]:
    """Flag `.to_numpy(...)` / `.frombuffer(...)` calls in the fast-path
    decode modules outside designated fallback functions (any enclosing
    function whose name ends `_fallback`). The fast path exists to
    replace exactly these per-column host copies with one native pass
    over the arrow buffers; host materialization belongs in the
    designated fallbacks (e.g. table.py's _column_from_arrow_fallback).

    Inside the decode-to-wire fused path (functions/classes named
    `*wire*`) the rule additionally bans the `.packbits(...)` /
    `.astype(...)` pack idioms: the wire kernels already emit packed
    bits and shifted/narrowed values, so those calls mean the serial
    numpy pack crept back in. `*_fallback` functions stay exempt."""
    with open(path, encoding="utf-8") as f:
        tree = ast.parse(f.read(), filename=path)
    findings: List[str] = []

    def walk(node: ast.AST, in_fallback: bool, in_wire: bool) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            in_fallback = in_fallback or node.name.endswith("_fallback")
            in_wire = in_wire or "wire" in node.name.lower()
        elif isinstance(node, ast.ClassDef):
            in_wire = in_wire or "wire" in node.name.lower()
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and not in_fallback
        ):
            if node.func.attr in DECODE_FORBIDDEN_ATTRS:
                findings.append(
                    f"{_rel(path)}:{node.lineno}: DECODE "
                    f"`.{node.func.attr}(...)` in a fast-path decode module "
                    f"— this is the host copy the fast path removes; decode "
                    f"via the native kernels, or move the copy into a "
                    f"designated `*_fallback` function"
                )
            elif in_wire and node.func.attr in DECODE_WIRE_FORBIDDEN_ATTRS:
                findings.append(
                    f"{_rel(path)}:{node.lineno}: DECODE "
                    f"`.{node.func.attr}(...)` in the decode-to-wire fused "
                    f"path — the wire kernels already pack bits and "
                    f"narrow/shift values; re-packing on the host defeats "
                    f"the fusion. Move the copy into a designated "
                    f"`*_fallback` function"
                )
        for child in ast.iter_child_nodes(node):
            walk(child, in_fallback, in_wire)

    walk(tree, False, False)
    return findings


# -- GLOBALMUT: unguarded module-global mutable state ------------------------


def _is_mutable_literal(node: ast.AST) -> bool:
    if isinstance(node, (ast.Dict, ast.List, ast.DictComp, ast.ListComp)):
        return True
    return (
        isinstance(node, ast.Call)
        and isinstance(node.func, ast.Name)
        and node.func.id in ("dict", "list")
        and not node.args
        and not node.keywords
    )


def _lockish(expr: ast.AST) -> bool:
    """Does a `with` context expression look like a lock acquisition?
    Heuristic: any name/attribute in it contains 'lock' (e.g.
    `_FUSED_CACHE_LOCK`, `self._lock`, `lock.acquire_timeout(...)`)."""
    for node in ast.walk(expr):
        name = None
        if isinstance(node, ast.Name):
            name = node.id
        elif isinstance(node, ast.Attribute):
            name = node.attr
        if name is not None and "lock" in name.lower():
            return True
    return False


def _bound_names(fn: ast.AST) -> set:
    """Names bound in this function's own scope (params + assignment/
    loop/with/except targets), nested scopes excluded."""
    bound = set()
    args = fn.args
    for a in (
        list(args.posonlyargs) + list(args.args) + list(args.kwonlyargs)
    ):
        bound.add(a.arg)
    if args.vararg:
        bound.add(args.vararg.arg)
    if args.kwarg:
        bound.add(args.kwarg.arg)

    def visit(node: ast.AST) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(
                child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
            ):
                bound.add(child.name)  # a nested def/class binds its name
                continue
            if isinstance(child, ast.Lambda):
                continue
            if isinstance(child, ast.Name) and isinstance(
                child.ctx, (ast.Store, ast.Del)
            ):
                bound.add(child.id)
            visit(child)

    visit(fn)
    return bound


def check_global_mutation(path: str) -> List[str]:
    """Flag mutations of module-level dicts/lists inside functions that
    are neither under a lock `with` nor allowlisted (`# global-ok:` on
    the module-level assignment line)."""
    with open(path, encoding="utf-8") as f:
        source = f.read()
    tree = ast.parse(source, filename=path)
    lines = source.splitlines()

    mutable_globals: set = set()
    for node in tree.body:
        if isinstance(node, ast.Assign):
            targets, value = node.targets, node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets, value = [node.target], node.value
        else:
            continue
        if not _is_mutable_literal(value):
            continue
        line = lines[node.lineno - 1] if node.lineno <= len(lines) else ""
        if "# global-ok" in line:
            continue
        for target in targets:
            if isinstance(target, ast.Name) and target.id != "__all__":
                mutable_globals.add(target.id)
    if not mutable_globals:
        return []

    findings: List[str] = []

    def _hit(name: str, lineno: int, what: str) -> None:
        findings.append(
            f"{_rel(path)}:{lineno}: GLOBALMUT {what} mutates module "
            f"global `{name}` without a lock — wrap in `with <lock>:` "
            f"or allowlist the assignment with `# global-ok: <reason>`"
        )

    def _global_subscript(expr: ast.AST, local: set):
        if (
            isinstance(expr, ast.Subscript)
            and isinstance(expr.value, ast.Name)
            and expr.value.id in mutable_globals
            and expr.value.id not in local
        ):
            return expr.value.id
        return None

    def scan_node(node: ast.AST, local: set, lock_depth: int) -> None:
        if lock_depth == 0:
            if isinstance(node, ast.Call) and isinstance(
                node.func, ast.Attribute
            ):
                func = node.func
                if (
                    isinstance(func.value, ast.Name)
                    and func.value.id in mutable_globals
                    and func.value.id not in local
                    and func.attr in GLOBALMUT_MUTATORS
                ):
                    _hit(func.value.id, node.lineno, f"`.{func.attr}()`")
            elif isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = (
                    node.targets
                    if isinstance(node, ast.Assign)
                    else [node.target]
                )
                for target in targets:
                    name = _global_subscript(target, local)
                    if name is not None:
                        _hit(name, node.lineno, "subscript assignment")
            elif isinstance(node, ast.Delete):
                for target in node.targets:
                    name = _global_subscript(target, local)
                    if name is not None:
                        _hit(name, node.lineno, "`del` on subscript")
        if isinstance(node, (ast.With, ast.AsyncWith)) and any(
            _lockish(item.context_expr) for item in node.items
        ):
            lock_depth += 1
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                scan_function(child, local, lock_depth)
            elif isinstance(child, ast.Lambda):
                continue  # expression-only: cannot contain mutations above
            else:
                scan_node(child, local, lock_depth)

    def scan_function(fn: ast.AST, outer_local: set, lock_depth: int) -> None:
        declared_global = {
            name
            for stmt in ast.walk(fn)
            if isinstance(stmt, ast.Global)
            for name in stmt.names
        }
        local = (outer_local | _bound_names(fn)) - declared_global
        for stmt in fn.body:
            scan_node(stmt, local, lock_depth)

    def scan_class(cls: ast.AST) -> None:
        for sub in cls.body:
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                scan_function(sub, set(), 0)
            elif isinstance(sub, ast.ClassDef):
                scan_class(sub)

    for stmt in tree.body:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            scan_function(stmt, set(), 0)
        elif isinstance(stmt, ast.ClassDef):
            scan_class(stmt)
    return findings


# -- F401 fallback: unused imports ------------------------------------------


def _used_names(tree: ast.AST) -> set:
    used = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Name):
            used.add(node.id)
        elif isinstance(node, ast.Constant) and isinstance(node.value, str):
            # forward-ref annotations ("Table"), dotted refs, __all__ entries
            for part in node.value.replace(".", " ").replace("[", " ").replace(
                "]", " "
            ).split():
                if part.isidentifier():
                    used.add(part)
    return used


def check_unused_imports(path: str) -> List[str]:
    if os.path.basename(path) == "__init__.py":
        return []  # re-export surface: unused-looking imports are the point
    with open(path, encoding="utf-8") as f:
        tree = ast.parse(f.read(), filename=path)
    used = _used_names(tree)
    findings: List[str] = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                bound = alias.asname or alias.name.split(".")[0]
                if bound not in used:
                    findings.append(
                        f"{_rel(path)}:{node.lineno}: F401 "
                        f"`{alias.name}` imported but unused"
                    )
        elif isinstance(node, ast.ImportFrom):
            if node.module == "__future__":
                continue
            for alias in node.names:
                if alias.name == "*":
                    continue
                bound = alias.asname or alias.name
                if bound not in used:
                    findings.append(
                        f"{_rel(path)}:{node.lineno}: F401 "
                        f"`{alias.name}` imported but unused"
                    )
    return findings


# -- FAULTS: no swallowed exceptions on the fault-containment paths ----------


def check_fault_containment(path: str) -> List[str]:
    """Flag bare `except:` and silently-swallowed exceptions (handlers
    whose body is solely `pass`) in the stage-worker and readahead
    files. A fault contained on these paths must either count itself
    (runtime.record_fault / record_retry) or degrade into a designated
    fallback — a handler that does neither hides the exact class of
    failure the chaos harness exists to exercise. Exempt: any enclosing
    function whose name ends `_fallback`, and except lines annotated
    `# fault-ok: <reason>`."""
    with open(path, encoding="utf-8") as f:
        src = f.read()
    tree = ast.parse(src, filename=path)
    lines = src.splitlines()
    findings: List[str] = []

    def walk(node: ast.AST, in_fallback: bool) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            in_fallback = in_fallback or node.name.endswith("_fallback")
        if isinstance(node, ast.ExceptHandler) and not in_fallback:
            if node.type is None:
                findings.append(
                    f"{_rel(path)}:{node.lineno}: FAULTS bare `except:` on "
                    f"a fault-containment path — name the exception so "
                    f"injected faults stay distinguishable from "
                    f"KeyboardInterrupt/SystemExit"
                )
            elif all(isinstance(stmt, ast.Pass) for stmt in node.body) and (
                "# fault-ok:" not in lines[node.lineno - 1]
            ):
                findings.append(
                    f"{_rel(path)}:{node.lineno}: FAULTS silently swallowed "
                    f"exception — count it (runtime.record_fault / "
                    f"record_retry), degrade via a `*_fallback` function, "
                    f"or annotate the except line `# fault-ok: <reason>`"
                )
        for child in ast.iter_child_nodes(node):
            walk(child, in_fallback)

    walk(tree, False)
    return findings


def _registered_fault_points() -> Optional[set]:
    """FAULT_KINDS keys from the chaos harness, by AST — None when the
    registry module or the dict is missing (reported as a finding)."""
    path = os.path.join(REPO, FAULTS_REGISTRY)
    if not os.path.exists(path):
        return None
    with open(path, encoding="utf-8") as f:
        tree = ast.parse(f.read(), filename=path)
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AnnAssign):
            targets = [node.target]
        else:
            continue
        for target in targets:
            if (
                isinstance(target, ast.Name)
                and target.id == "FAULT_KINDS"
                and isinstance(node.value, ast.Dict)
            ):
                return {
                    key.value
                    for key in node.value.keys
                    if isinstance(key, ast.Constant)
                    and isinstance(key.value, str)
                }
    return None


def check_fault_registration(path: str, registered: set) -> List[str]:
    """Flag `fault_point("<name>")` call literals naming a point absent
    from the harness's FAULT_KINDS registry. An unregistered point can
    never fire under any DEEQU_TPU_FAULTS spec, so the containment code
    behind it is unexercisable by `make chaos` — register the point or
    delete the probe."""
    with open(path, encoding="utf-8") as f:
        tree = ast.parse(f.read(), filename=path)
    findings: List[str] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        name = func.attr if isinstance(func, ast.Attribute) else (
            func.id if isinstance(func, ast.Name) else None
        )
        if name != "fault_point" or not node.args:
            continue
        arg = node.args[0]
        if not (isinstance(arg, ast.Constant) and isinstance(arg.value, str)):
            continue
        if arg.value not in registered:
            findings.append(
                f"{_rel(path)}:{node.lineno}: FAULTS fault point "
                f"`{arg.value}` is not registered in "
                f"{FAULTS_REGISTRY} FAULT_KINDS — the chaos harness "
                f"can never exercise it"
            )
    return findings


# -- E722 fallback: bare except ---------------------------------------------


def check_bare_except(path: str) -> List[str]:
    with open(path, encoding="utf-8") as f:
        tree = ast.parse(f.read(), filename=path)
    return [
        f"{_rel(path)}:{node.lineno}: E722 bare `except:` — name the exception"
        for node in ast.walk(tree)
        if isinstance(node, ast.ExceptHandler) and node.type is None
    ]


def run_ruff() -> List[str]:
    """Delegate F401/F821/E722 to ruff when it exists (config lives in
    pyproject.toml); None-equivalent empty result plus a sentinel when
    it doesn't."""
    exe = shutil.which("ruff")
    if exe is None:
        return []
    proc = subprocess.run(
        [exe, "check", "deequ_tpu", "tests", "tools"],
        capture_output=True,
        text=True,
        cwd=REPO,
        timeout=300,
    )
    if proc.returncode == 0:
        return []
    return [line for line in proc.stdout.splitlines() if line.strip()]


def main() -> int:
    findings: List[str] = []

    for rel in HOT_LOOP_FILES:
        path = os.path.join(REPO, rel)
        if os.path.exists(path):
            findings.extend(check_hot_loops(path))

    for rel in PIPELINE_FILES:
        path = os.path.join(REPO, rel)
        if os.path.exists(path):
            findings.extend(check_pipeline_syncs(path))

    for rel in PUSHDOWN_FILES:
        path = os.path.join(REPO, rel)
        if os.path.exists(path):
            findings.extend(check_pushdown_purity(path))

    for rel in SUBSUME_FILES:
        path = os.path.join(REPO, rel)
        if os.path.exists(path):
            findings.extend(check_subsume_purity(path))

    windows_dir = os.path.join(REPO, WINDOWS_DIR)
    windows_paths = (
        sorted(glob.glob(os.path.join(windows_dir, "*.py")))
        if os.path.isdir(windows_dir)
        else []
    ) + [
        os.path.join(REPO, rel)
        for rel in WINDOWS_EXTRA_FILES
        if os.path.exists(os.path.join(REPO, rel))
    ]
    for path in windows_paths:
        findings.extend(check_windows_purity(path))

    for rel in DECODE_FILES:
        path = os.path.join(REPO, rel)
        if os.path.exists(path):
            findings.extend(check_decode_copies(path))

    for rel in READER_FILES:
        path = os.path.join(REPO, rel)
        if os.path.exists(path):
            findings.extend(check_reader_purity(path))

    for rel in SERDE_FILES:
        path = os.path.join(REPO, rel)
        if os.path.exists(path):
            findings.extend(check_serde_pickle(path))

    for rel in FORENSICS_FILES:
        path = os.path.join(REPO, rel)
        if os.path.exists(path):
            findings.extend(check_forensics_leak(path))

    for rel in FAULTS_FILES:
        path = os.path.join(REPO, rel)
        if os.path.exists(path):
            findings.extend(check_fault_containment(path))

    registered = _registered_fault_points()
    if registered is None:
        findings.append(
            f"{FAULTS_REGISTRY}: FAULTS chaos-harness registry "
            f"(FAULT_KINDS dict) not found — fault points cannot be "
            f"validated"
        )

    for path in _python_files():
        rel = _rel(path)
        if any(
            rel == d or rel.startswith(d + os.sep) for d in TIMING_DIRS
        ):
            findings.extend(check_timing_calls(path))
        if any(
            rel == d or rel.startswith(d + os.sep) for d in GLOBALMUT_DIRS
        ):
            findings.extend(check_global_mutation(path))
        if any(
            rel == d or rel.startswith(d + os.sep) for d in OBSPRINT_DIRS
        ):
            findings.extend(check_observe_prints(path))
        if registered is not None and rel.startswith(
            "deequ_tpu" + os.sep
        ):
            findings.extend(check_fault_registration(path, registered))

    if shutil.which("ruff") is not None:
        findings.extend(run_ruff())
    else:
        for path in _python_files():
            findings.extend(check_unused_imports(path))
            findings.extend(check_bare_except(path))

    for line in findings:
        print(line)
    if findings:
        print(f"\n{len(findings)} finding(s)")
        return 1
    print("lint clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())

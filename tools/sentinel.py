#!/usr/bin/env python3
"""Regression sentinel: anomaly detection over engine telemetry series.

Loads engine metric time series from two sources and runs the repo's own
anomaly strategies over them, exiting nonzero with a human-readable
verdict when throughput or phase shares regress:

  * a metrics repository JSON file (default `ENGINE_METRICS.json` at the
    repo root — what bench.py appends to; see BENCH.md), filtered to
    `telemetry=engine` result keys via `deequ_tpu.repository.engine`;
  * the committed `BENCH_r0*.json` history (headline rows/s per round).

Detection per series (union of what each strategy flags):

  * `RateOfChangeStrategy` over log-values — scale-free relative step
    detection; a drop of more than `--max-drop` (default 20%) between
    consecutive points flags (for up-is-bad series: a rise of more than
    the same fraction);
  * `OnlineNormalStrategy` one-sided at 3 sigma — drift detection
    against the running mean (lower side for throughput, upper side for
    phase shares);
  * `HoltWinters` (daily/weekly) on series long enough for two full
    cycles plus a test window — catches seasonal-shape breaks.

Usage: `make sentinel`, or
  python tools/sentinel.py [--repo PATH] [--bench GLOB] [--max-drop F]

Exit status: 0 = ok (or not enough history), 1 = regression flagged.
"""

from __future__ import annotations

import argparse
import glob
import json
import math
import os
import sys
from typing import Any, Dict, List, Optional, Sequence, Tuple

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: engine series watched from the metrics repository, with regression
#: direction ("down" = drops are bad, "up" = rises are bad)
WATCHED_SERIES: Sequence[Tuple[str, str]] = (
    ("engine.rows_per_s", "down"),
    ("engine.peak_rss_mb", "up"),
    # pushdown effectiveness: the fraction of parquet row groups skipped
    # statically; a drop means predicates stopped proving groups
    # all-false (stats regressed, interpreter weakened, plan changed)
    ("engine.rg_skipped_ratio", "down"),
    # decode fast-path effectiveness: the fraction of scanned columns on
    # the buffer-level native decode; a drop means columns fell back to
    # the host chain (classifier narrowed, native build broken, schema
    # drifted toward ineligible types)
    ("engine.decode_fastpath_ratio", "down"),
    # per-scan decode worker count; a drop means the pool stopped
    # scaling (env override lost, cpu_count misdetected)
    ("engine.decode_workers", "down"),
    # decode-to-wire effectiveness: the fraction of scanned columns fused
    # straight to wire buffers at decode; a drop means columns fell back
    # to the Column path (consumer set widened, sticky spec lost, wire
    # kernels unavailable)
    ("engine.wire_fused_ratio", "down"),
    # native parquet reader effectiveness: the fraction of fast-path
    # column-chunks decoded by the page-to-wire reader; a drop means
    # chunks fell back to arrow (codec library vanished, writer switched
    # to an unsupported page encoding, chunk layout metadata lost)
    ("engine.reader_native_ratio", "down"),
    # encoded-fold compression: logical values folded per (run, code)
    # entry; a drop toward 1.0 means the data stopped run-compressing
    # (cardinality rising, writer stopped dictionary-coding) and the
    # run-fold kernels stopped paying
    ("engine.encfold.run_ratio", "down"),
    # encoded-fold containment: chunks that failed closed to the
    # row-width path out of planned run-fold chunks; a rise means pages
    # stopped being all-dictionary at decode (writer fallback pages,
    # corrupt runs, dict-size overflow past the cap)
    ("engine.encfold.fallback_ratio", "up"),
    # state-cache effectiveness: the fraction of dataset partitions whose
    # analyzer states loaded from the persistent partition-state cache
    # instead of rescanning; a drop means incremental runs stopped
    # hitting (fingerprints churning, plan signature drifting, envelope
    # decode failures falling back to rescan)
    ("engine.state_cache_hit_ratio", "down"),
    # compiled-plan cache effectiveness: the fraction of fused-fn
    # lookups whose plan shape was already jitted (the fuse cost paid
    # once per shape fleet-wide); a drop means plan shapes stopped
    # deduplicating (shape key churning, cache evicting under max-size,
    # tenants diverging in analyzer spelling)
    ("engine.plan_cache_hit_ratio", "down"),
    # transient-fault recovery: the fraction of retried IO operations
    # that recovered within the retry budget; a drop means transient
    # faults stopped being absorbed (budget misconfigured, backoff too
    # short for the store's stall profile, faults turned persistent)
    ("engine.retry.recovery_ratio", "down"),
    # fault containment cost: the fraction of observed faults that cost
    # a unit its native decode (degraded to the pyarrow fallback); a
    # rise means faults are escaping the retry layer and landing on the
    # slow path
    ("engine.fault.fallback_ratio", "up"),
    # DQ service overload shedding: the fraction of submissions shed at
    # admission (DQ412); growth means the pool is saturated — queues
    # too small, workers too few, or a tenant flooding past its quota
    ("engine.service.shed_ratio", "up"),
    # DQ service circuit breakers currently open: a rise means more
    # (tenant, dataset) pairs are repeatedly failing their runs and
    # being fenced off from the pool (corrupt upstream tables)
    ("engine.service.breaker_open", "up"),
    # sharded-scan per-shard fold throughput: a drop means shards
    # stopped scaling (straggler host, shrunken readahead, partition
    # skew starving the mesh)
    ("engine.shard.rows_per_s", "down"),
    # sharded-scan balance: the largest shard's partition count over
    # the even split; a rise means the rendezvous assignment degenerated
    # (partition count too low for the mesh, exclusions piling up)
    ("engine.shard.skew_ratio", "up"),
    # sharded-scan merge traffic: gathered state-envelope bytes crossing
    # the process boundary; growth means states bloated (HLL/histogram
    # payloads growing, partition counts exploding) — rows never cross,
    # so this must stay KB-scale
    ("engine.shard.merge_bytes", "up"),
    # windowed-query segment effectiveness: the fraction of a window's
    # cover spans answered by a precomputed DQSG segment envelope; a
    # collapse means segment publication broke (warm=False everywhere,
    # put_blob failing silently) or partition churn outruns the covers
    ("engine.window.segment_hit_ratio", "down"),
    # windowed-query rescan pressure: member partitions with no usable
    # cached state; a rise means the per-partition state commit path
    # regressed (serde failures, signature churn) and window queries are
    # quietly turning back into scans
    ("engine.window.partitions_rescanned", "up"),
    # dataset drift: the worst two-sample drift measure a DriftCheck
    # observed (KS distance, cardinality ratio, completeness/moment
    # deltas); a rise means the watched dataset's distribution is moving
    # against its baseline window
    ("engine.drift.value_max", "up"),
    # drift constraint failures per evaluation; any sustained rise means
    # a dataset is actively violating its drift contract (or the
    # baseline wiring broke — DQ324 failures count here too)
    ("engine.drift.failed_constraints", "up"),
)

#: phases whose share of wall time is watched (rises are bad: a phase
#: eating a larger fraction of the run means a new bottleneck)
WATCHED_PHASE_SHARES: Sequence[str] = ("dispatch", "transfer", "merge", "host")

#: minimum points before a series is judged at all
MIN_POINTS = 4

#: HoltWinters needs two full weekly cycles of training plus a test window
HW_MIN_POINTS = 15


def _ensure_repo_on_path() -> None:
    if REPO_ROOT not in sys.path:
        sys.path.insert(0, REPO_ROOT)


def detect_regressions(
    points: Sequence[Any],
    *,
    direction: str = "down",
    max_drop: float = 0.2,
) -> List[Dict[str, Any]]:
    """Run the strategy union over one series of anomaly DataPoints.

    Returns one finding dict per flagged point: {time, value, detail,
    strategies}. Points whose metric_value is None are dropped first.
    """
    _ensure_repo_on_path()
    from deequ_tpu.anomaly import (
        HoltWinters,
        MetricInterval,
        OnlineNormalStrategy,
        RateOfChangeStrategy,
        SeriesSeasonality,
    )

    series = [p for p in points if p.metric_value is not None]
    series.sort(key=lambda p: p.time)
    values = [float(p.metric_value) for p in series]
    times = [p.time for p in series]
    n = len(values)
    if n < MIN_POINTS:
        return []

    flagged: Dict[int, Dict[str, Any]] = {}

    def _flag(index: int, strategy: str, detail: str) -> None:
        if not (0 <= index < n):
            return
        entry = flagged.setdefault(
            index,
            {
                "time": times[index],
                "value": values[index],
                "strategies": [],
                "detail": detail,
            },
        )
        if strategy not in entry["strategies"]:
            entry["strategies"].append(strategy)

    # 1) relative step detection on log-values (scale-free): a drop
    # below (1 - max_drop)x, or a rise above 1/(1 - max_drop)x for
    # up-is-bad series, between consecutive points
    if all(v > 0.0 for v in values):
        logs = [math.log(v) for v in values]
        bound = math.log(1.0 - max_drop)
        if direction == "down":
            roc = RateOfChangeStrategy(max_rate_decrease=bound)
        else:
            roc = RateOfChangeStrategy(max_rate_increase=-bound)
        for idx, anomaly in roc.detect(logs, (1, n)):
            prev = values[idx - 1]
            change = (values[idx] / prev - 1.0) * 100.0 if prev else float("nan")
            _flag(
                idx,
                "RateOfChange",
                f"{change:+.1f}% vs previous point {prev:.6g}",
            )

    # 2) one-sided drift vs the running mean (3 sigma)
    if direction == "down":
        online = OnlineNormalStrategy(
            lower_deviation_factor=3.0, upper_deviation_factor=None
        )
    else:
        online = OnlineNormalStrategy(
            lower_deviation_factor=None, upper_deviation_factor=3.0
        )
    for idx, anomaly in online.detect(values, (0, n)):
        _flag(idx, "OnlineNormal", anomaly.detail or ">3 sigma vs running mean")

    # 3) seasonal forecast residuals, only with enough history for two
    # full (weekly) cycles of training plus a test window
    if n >= HW_MIN_POINTS:
        hw = HoltWinters(MetricInterval.DAILY, SeriesSeasonality.WEEKLY)
        try:
            for idx, anomaly in hw.detect(values, (14, n)):
                _flag(idx, "HoltWinters", anomaly.detail or "forecast residual")
        except (ValueError, ImportError):
            pass  # degenerate series / missing scipy: skip the seasonal pass

    return [flagged[idx] for idx in sorted(flagged)]


def _repo_series(
    repo_path: str,
) -> List[Tuple[str, str, List[Any]]]:
    """(series_name, direction, points) triples from a repository file."""
    _ensure_repo_on_path()
    from deequ_tpu.anomaly import DataPoint
    from deequ_tpu.repository import engine
    from deequ_tpu.repository.fs import FileSystemMetricsRepository

    if not os.path.exists(repo_path):
        return []
    repository = FileSystemMetricsRepository(repo_path)
    available = set(engine.engine_metric_names(repository))
    out: List[Tuple[str, str, List[Any]]] = []
    for name, direction in WATCHED_SERIES:
        if name in available:
            out.append((name, direction, engine.engine_series(repository, name)))

    # phase shares: join phase seconds against wall seconds by timestamp
    wall = {p.time: p.metric_value for p in engine.engine_series(repository, "engine.wall_s")}
    for phase in WATCHED_PHASE_SHARES:
        name = f"engine.phase.{phase}_s"
        if name not in available:
            continue
        shares = [
            DataPoint(p.time, float(p.metric_value) / float(wall[p.time]))
            for p in engine.engine_series(repository, name)
            if p.metric_value is not None and wall.get(p.time)
        ]
        if shares:
            out.append((f"engine.phase_share.{phase}", "up", shares))
    return out


def _bench_series(pattern: str) -> List[Any]:
    """Headline throughput series from committed BENCH_r0*.json rounds."""
    _ensure_repo_on_path()
    from deequ_tpu.anomaly import DataPoint

    points = []
    for path in sorted(glob.glob(pattern)):
        try:
            with open(path, encoding="utf-8") as fh:
                data = json.load(fh)
        except (OSError, ValueError):
            continue
        parsed = data.get("parsed") or {}
        value = parsed.get("value")
        round_n = data.get("n")
        if value is None or round_n is None:
            continue  # early rounds have "parsed": null
        points.append(DataPoint(int(round_n), float(value)))
    points.sort(key=lambda p: p.time)
    return points


def run_sentinel(
    repo_path: str,
    bench_pattern: str,
    *,
    max_drop: float = 0.2,
    out=sys.stdout,
) -> int:
    """Check every watched series; print the verdict; return exit status."""
    findings_total = 0
    checked = 0

    def _report(source: str, name: str, points: Sequence[Any], direction: str) -> None:
        nonlocal findings_total, checked
        live = [p for p in points if p.metric_value is not None]
        if len(live) < MIN_POINTS:
            out.write(
                f"sentinel: {name} — {len(live)} points from {source} "
                f"(need {MIN_POINTS}) — skipped\n"
            )
            return
        checked += 1
        findings = detect_regressions(live, direction=direction, max_drop=max_drop)
        if not findings:
            out.write(f"sentinel: {name} — {len(live)} points from {source} — ok\n")
            return
        findings_total += len(findings)
        out.write(f"sentinel: {name} — {len(live)} points from {source}:\n")
        for f in findings:
            out.write(
                f"  REGRESSION at t={f['time']}: value {f['value']:.6g} "
                f"({f['detail']}) [{', '.join(f['strategies'])}]\n"
            )

    for name, direction, points in _repo_series(repo_path):
        _report(os.path.basename(repo_path), name, points, direction)
    bench_points = _bench_series(bench_pattern)
    if bench_points:
        _report(
            os.path.basename(bench_pattern), "bench.rows_per_s", bench_points, "down"
        )

    if findings_total:
        out.write(
            f"verdict: REGRESSION — {findings_total} flagged point(s) "
            f"across {checked} series\n"
        )
        return 1
    if not checked:
        out.write("verdict: ok — not enough engine history to judge yet\n")
        return 0
    out.write(f"verdict: ok — no regressions across {checked} series\n")
    return 0


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument(
        "--repo",
        default=os.path.join(REPO_ROOT, "ENGINE_METRICS.json"),
        help="metrics repository JSON file with engine telemetry series",
    )
    parser.add_argument(
        "--bench",
        default=os.path.join(REPO_ROOT, "BENCH_r0*.json"),
        help="glob of committed bench round files",
    )
    parser.add_argument(
        "--max-drop",
        type=float,
        default=0.2,
        help="relative throughput drop between points that flags (default 0.2)",
    )
    args = parser.parse_args(argv)
    return run_sentinel(args.repo, args.bench, max_drop=args.max_drop)


if __name__ == "__main__":
    sys.exit(main())
